"""Benchmark driver — BASELINE.json north-star config:
CGLS on a BlockDiag(MatrixMult) with N=4096, the analog of the
reference's ``examples/plot_cgls.py`` hot loop
(``pylops_mpi/optimization/cls_basic.py:370-404``).

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``

Crash-proof by construction: the measurement runs in a *child* process
supervised by this parent. If the TPU backend hangs or errors at init
(round 1 failure mode: "Unable to initialize backend 'axon'"), the
child is killed at a timeout and re-run with ``JAX_PLATFORMS=cpu`` on
an 8-virtual-device mesh, with ``"degraded": true`` recorded. The
parent never exits non-zero and always prints exactly one JSON line.

Extra keys beyond the required four:

- ``mfu``: model FLOP utilisation of the solve's GEMMs vs the chip's
  dense peak FOR THE PRECISION USED — bf16 systolic peak for bf16
  storage, bf16/6 for f32 under the ``highest`` matmul-precision pin
  (3 products × 2 operand splits); 3 significant digits, null on CPU.
  Per-mode values live in ``f32.mfu`` / ``bf16.mfu``.
- ``f32``: the classic two-sweep f32-storage CGLS measured alongside
  the default mode, so BASELINE comparisons stay apples-to-apples when
  the default TPU mode uses bf16 block storage (advisor round-1 note).
- ``components``: the per-config results of
  ``benchmarks/bench_components.py`` (all 5 BASELINE.md driver
  configs), each individually try/except-guarded.
- ``roofline`` (and per-mode ``f32.roofline`` / ``bf16.roofline``):
  predicted-vs-measured placement from the diagnostics cost model
  (``pylops_mpi_tpu/diagnostics/costmodel.py``) — per-iteration
  FLOPs/HBM bytes against the per-chip peaks, with the binding
  resource named (``bound``). On the CPU sim the peak is an assumed
  stream bandwidth, labeled ``peak_source=assumed_cpu_stream``.
- ``platform`` / ``degraded`` / ``tpu_error``: provenance.

Stage budgets (selfcheck/component subprocess timeouts) come from the
central table in ``pylops_mpi_tpu/diagnostics/profiler.py`` (env
overrides unchanged); with ``PYLOPS_MPI_TPU_TRACE`` on, the child also
writes a Chrome-trace JSONL (``bench_trace.jsonl``) next to
``bench_detail.json``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_CHILD_FLAG = "--child"


def _profiler_mod():
    """The diagnostics profiler module (central stage-budget table +
    deadline runner), loaded BY FILE PATH so the jax-free parent/
    supervisor processes never import the package (and jax). The
    module is standalone-loadable by design (stdlib-only imports).
    Returns None when unavailable — callers fall back to their
    historical literals."""
    try:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "pylops_mpi_tpu", "diagnostics", "profiler.py")
        spec = importlib.util.spec_from_file_location(
            "_pmt_diag_profiler", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def _stage_budget(stage: str, default: int, rehearse: bool = False) -> int:
    """Wall budget for a harvest/bench stage from the ONE central
    table (pylops_mpi_tpu/diagnostics/profiler.py), env overrides
    included; ``default`` only covers a missing/broken table."""
    mod = _profiler_mod()
    if mod is None:
        return default
    try:
        return mod.stage_budget(stage, rehearse=rehearse)
    except Exception:
        return default

def _plan_provenance(op_family: str = "blockdiag") -> str:
    """``plan=`` column for bench rows: where the headline operator's
    schedule came from — ``tuned`` (measured plan replayed),
    ``costmodel`` (analytic seed under PYLOPS_MPI_TPU_TUNE=on), or
    ``default`` (tuner off — today's hand-set seams)."""
    try:
        from pylops_mpi_tpu.tuning.plan import applied_provenance
        return applied_provenance(op_family, default="default")
    except Exception:
        return "default"


def _tune_race_row():
    """Tuner-vs-default race (round 10 acceptance): on small SUMMA
    shapes, time every candidate with the tuner's own trial machinery
    and compare (a) the measured winner against (b) the default
    configuration and (c) the pure cost-model pick. CPU-sim sized so
    the compact line carries it every round; the acceptance bar is
    worst ``tuned_vs_default`` ≤ 1.05 and at least one shape with a
    measured win over the cost-model pick."""
    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu.tuning import (space as tspace,
                                           search as tsearch,
                                           plan as tplan)
        from pylops_mpi_tpu.tuning.__main__ import _summa_case
        from pylops_mpi_tpu.parallel.mesh import (default_mesh,
                                                  best_grid_2d)
        mesh = default_mesh()
        n_dev = int(mesh.devices.size)
        platform = _jax.default_backend()
        sp = tspace.space_for("matrixmult")
        grid = best_grid_2d(n_dev)
        rows = []
        for (N, K, M) in ((48, 64, 8), (64, 48, 32)):
            ctx = {"op": "matrixmult", "shape": (N, K, M),
                   "dtype": _np.float32, "n_dev": n_dev,
                   "axes": tuple(mesh.axis_names), "platform": platform,
                   "chip": tplan._chip_kind()[1],
                   "extra": {"grid": grid}}
            factory = _summa_case(N, K, M, mesh)
            winner, trials = tsearch.measure_candidates(
                sp, ctx, factory, repeats=3,
                budget_s=_stage_budget("tune", 240, rehearse=True))
            meas = {tuple(sorted(t["params"].items())): t["best_s"]
                    for t in trials if t.get("ok")}

            def t_of(p):
                return meas.get(tuple(sorted(p.items()))) if p else None

            dflt = tspace.default_params(sp, ctx)
            seed = tspace.rank(sp, ctx)[0]
            t_d, t_s, t_w = t_of(dflt), t_of(seed), t_of(winner)
            rows.append({
                "shape": [N, K, M], "winner": winner,
                "default": dflt, "costmodel_pick": seed,
                "tuned_vs_default": (_sig3(t_w / t_d)
                                     if t_w and t_d else None),
                "tuned_vs_costmodel": (_sig3(t_w / t_s)
                                       if t_w and t_s else None),
                "n_measured": len(meas)})
        r_def = [r["tuned_vs_default"] for r in rows
                 if r.get("tuned_vs_default")]
        r_cm = [r["tuned_vs_costmodel"] for r in rows
                if r.get("tuned_vs_costmodel")]
        return {"shapes": rows,
                "worst_tuned_vs_default": max(r_def) if r_def else None,
                "best_tuned_vs_costmodel": min(r_cm) if r_cm else None}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}


def _batched_race_row(niter=20):
    """Batched-throughput race (the batching-PR acceptance bar): one
    Block-CGLS solve with K RHS columns vs K sequential single-RHS
    fused solves of the SAME systems, on the flagship block-diagonal
    family. ``tol=0`` pins both sides to exactly ``niter`` iterations
    so the race measures schedule amortization, not convergence luck.
    Stamps ``solves_per_sec@K`` (the serving-throughput headline) and
    ``batch_plan`` (plan provenance of the operator the block solve
    ran through). K comes from PYLOPS_MPI_TPU_BATCH when set, else
    16."""
    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu import DistributedArray, MPIBlockDiag
        from pylops_mpi_tpu.ops.local import MatrixMult
        from pylops_mpi_tpu.solvers import block_cgls, cgls
        from pylops_mpi_tpu.tuning.plan import applied_provenance
        from pylops_mpi_tpu.utils.deps import batch_default
        K = batch_default()
        if K <= 1:
            K = 16
        nblk, nblock = 8, 48
        blocks, _, _ = make_problem(nblk, nblock, seed=3)
        Op = MPIBlockDiag([MatrixMult(b, dtype=np.float32)
                           for b in blocks])
        N = nblk * nblock
        rng = _np.random.default_rng(7)
        Y = rng.standard_normal((N, K)).astype(_np.float32)
        yb = DistributedArray(global_shape=(N, K), dtype=_np.float32)
        yb[:] = Y
        ys = []
        for j in range(K):
            yj = DistributedArray(global_shape=N, dtype=_np.float32)
            yj[:] = Y[:, j]
            ys.append(yj)

        def run_block():
            out = block_cgls(Op, yb, niter=niter, tol=0.0)
            _jax.block_until_ready(out[0]._arr)
            return out

        def run_seq():
            outs = [cgls(Op, yj, niter=niter, tol=0.0) for yj in ys]
            _jax.block_until_ready(outs[-1][0]._arr)
            return outs

        run_block()   # compile both programs outside the timed region
        run_seq()
        t0 = time.perf_counter(); bout = run_block()
        t_blk = time.perf_counter() - t0
        t0 = time.perf_counter(); souts = run_seq()
        t_seq = time.perf_counter() - t0
        # the race only counts if both sides solved the same systems
        err = max(float(_np.max(_np.abs(
            _np.asarray(bout[0].array)[:, j]
            - _np.asarray(souts[j][0].array)))) for j in range(K))
        return {"K": K, "niter": niter,
                "shape": [N, N], "nblk": nblk,
                f"solves_per_sec@{K}": _sig3(K / t_blk),
                "sequential_solves_per_sec": _sig3(K / t_seq),
                "speedup_vs_sequential": _sig3(t_seq / t_blk),
                "block_vs_sequential_max_abs_diff": _sig3(err),
                "batch_plan": applied_provenance("blockdiag",
                                                 default="default")}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}


def _serving_race_row(niter=20, n_requests=32):
    """Serving race (the serving-PR acceptance bar): 32 single-RHS
    requests through the continuous-batching daemon — packed into
    K=16 block solves against prewarmed executables — vs the same 32
    solved sequentially through the fused single-RHS path, on the
    flagship block-diagonal family. ``tol=0`` pins every solve to
    exactly ``niter`` iterations AND makes the padded block answers
    bit-identical to the sequential oracles (the race asserts it).
    Stamps ``solves_per_sec`` (wall basis, submit-to-last-result),
    ``speedup_vs_sequential``, and the daemon's p50/p99
    time-in-queue."""
    try:
        import numpy as _np
        from pylops_mpi_tpu import DistributedArray, MPIBlockDiag
        from pylops_mpi_tpu.ops.local import MatrixMult
        from pylops_mpi_tpu.solvers import cgls
        from pylops_mpi_tpu.serving import (FamilySpec, SolveDaemon,
                                            WarmPool)
        nblk, nblock = 8, 48
        blocks, _, _ = make_problem(nblk, nblock, seed=3)
        Op = MPIBlockDiag([MatrixMult(b, dtype=np.float32)
                           for b in blocks])
        N = nblk * nblock
        rng = _np.random.default_rng(7)
        Y = rng.standard_normal((N, n_requests)).astype(_np.float32)
        ys = []
        for j in range(n_requests):
            yj = DistributedArray(global_shape=N, dtype=_np.float32)
            yj[:] = Y[:, j]
            ys.append(yj)

        def run_seq():
            return [_np.asarray(
                cgls(Op, yj, niter=niter, tol=0.0)[0].array)
                for yj in ys]

        run_seq()     # compile the single-RHS program outside timing
        t0 = time.perf_counter()
        oracles = run_seq()
        t_seq = time.perf_counter() - t0

        pool = WarmPool(buckets=(16,))
        pool.register(FamilySpec(name="flagship", operator=Op,
                                 solver="cgls", niter=niter, tol=0.0))
        pool.prewarm(widths=[16])   # compile before the timed region
        daemon = SolveDaemon(pool, window_s=0.05).start()
        try:
            t0 = time.perf_counter()
            tickets = [daemon.submit("flagship", Y[:, j])
                       for j in range(n_requests)]
            results = [t.wait(timeout=120.0) for t in tickets]
            t_pack = time.perf_counter() - t0
            st = daemon.stats()
        finally:
            daemon.drain(timeout=10.0)
        # the race only counts if the daemon solved the same systems
        err = max(float(_np.max(_np.abs(results[j]["x"] - oracles[j])))
                  for j in range(n_requests))
        return {"K": 16, "requests": n_requests, "niter": niter,
                "shape": [N, N], "nblk": nblk,
                "solves_per_sec": _sig3(n_requests / t_pack),
                "sequential_solves_per_sec": _sig3(n_requests / t_seq),
                "speedup_vs_sequential": _sig3(t_seq / t_pack),
                "wait_p50_s": _sig3(st["wait_p50_s"]),
                "wait_p99_s": _sig3(st["wait_p99_s"]),
                "fill_mean": _sig3(st["fill_mean"]),
                "batches": st["batches"],
                "daemon_vs_sequential_max_abs_diff": _sig3(err)}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}


def _aot_provenance():
    """``aot=`` column for bench rows: how the bench process itself
    ran — ``off`` (the default, bit-identical pre-AOT build), ``on``/
    ``auto`` memory-only, or ``on+bank``/``auto+bank`` when an
    executable bank directory is armed."""
    try:
        from pylops_mpi_tpu import aot
        mode = aot.aot_mode()
        if aot.aot_enabled() and aot.bank_dir():
            return mode + "+bank"
        return mode
    except Exception:
        return "off"


# the cold-start child: one clean interpreter, one WarmPool prewarm,
# one packed solve banked to disk for the parent's bit-identity check.
# Mode/output dir arrive as argv; AOT knobs arrive via the environment
# the parent composes per arm. Last stdout line is one JSON dict (the
# _run_json_cmd salvage convention).
_COLD_START_CHILD = r"""
import json, os, sys, time
import numpy as np
mode, outdir = sys.argv[1], sys.argv[2]
from pylops_mpi_tpu import MPIBlockDiag, aot
from pylops_mpi_tpu.ops.local import MatrixMult
from pylops_mpi_tpu.serving import FamilySpec, WarmPool
nblk, nblock, niter = 8, 48, 10
widths = (2, 4, 8)
rng = np.random.default_rng(5)
blocks = []
for _ in range(nblk):
    a = rng.standard_normal((nblock, nblock)).astype(np.float32)
    blocks.append((a @ a.T / nblock
                   + 2.0 * np.eye(nblock, dtype=np.float32))
                  .astype(np.float32))
Op = MPIBlockDiag([MatrixMult(b, dtype=np.float32) for b in blocks])
pool = WarmPool(buckets=widths)
pool.register(FamilySpec(name="cold", operator=Op, solver="cgls",
                         niter=niter, tol=0.0))
t0 = time.perf_counter()
pool.prewarm(widths=list(widths))
prewarm_s = time.perf_counter() - t0
Y = rng.standard_normal((nblk * nblock, widths[-1])).astype(np.float32)
out = pool.solve("cold", Y)
np.save(os.path.join(outdir, "x_%s.npy" % mode), np.asarray(out.x))
print(json.dumps({"mode": mode, "prewarm_s": prewarm_s,
                  "compiles": aot.compile_count()}))
"""


def _cold_start_row():
    """Cold-start race (AOT PR acceptance): daemon prewarm wall with a
    COLD executable bank (compile + serialize) vs the SAME bank warm
    (deserialize only), each arm a clean subprocess so jit caches and
    import state never leak between them. A third ``AOT=off`` arm is
    the bit-identity oracle: all three solve the same packed
    block-CGLS system and the row asserts max-abs-diff 0.0 against it.
    Acceptance bar: banked prewarm ≥ 3× faster than cold; the banked
    arm must also replay with ZERO fresh compiles
    (``aot.compile_count()``)."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="bench_cold_start_")
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        bank = os.path.join(tmp, "bank")
        budget = _stage_budget("cold_start", 240)

        def _arm(mode):
            env = dict(os.environ)
            env["PYTHONPATH"] = (here + os.pathsep
                                 + env.get("PYTHONPATH", ""))
            # a warm persistent compile cache (CI sets one for every
            # pytest leg) must not contaminate the cold arm — every
            # arm runs without it so the race measures the bank alone
            env.pop("PYLOPS_MPI_TPU_COMPILE_CACHE", None)
            if mode == "off":
                env["PYLOPS_MPI_TPU_AOT"] = "off"
                env.pop("PYLOPS_MPI_TPU_AOT_CACHE", None)
            else:
                env["PYLOPS_MPI_TPU_AOT"] = "on"
                env["PYLOPS_MPI_TPU_AOT_CACHE"] = bank
            return _run_json_cmd(
                [sys.executable, "-c", _COLD_START_CHILD, mode, tmp],
                env, budget, cwd=here)

        arms = {}
        for mode in ("cold", "banked", "off"):
            got, err = _arm(mode)
            if err or not isinstance(got, dict):
                return {"error": f"{mode} arm: {err}"[:300]}
            arms[mode] = got
        import numpy as _np
        xs = {m: _np.load(os.path.join(tmp, f"x_{m}.npy"))
              for m in arms}
        diff = max(float(_np.max(_np.abs(xs[m] - xs["off"])))
                   for m in ("cold", "banked"))
        t_cold = arms["cold"].get("prewarm_s")
        t_bank = arms["banked"].get("prewarm_s")
        speedup = (t_cold / t_bank if t_cold and t_bank else None)
        return {"K_buckets": [2, 4, 8], "niter": 10,
                "nblk": 8, "nblock": 48,
                "cold_prewarm_s": _sig3(t_cold),
                "banked_prewarm_s": _sig3(t_bank),
                "speedup": _sig3(speedup),
                "bar": 3.0,
                "meets_bar": bool(speedup is not None
                                  and speedup >= 3.0),
                "cold_compiles": arms["cold"].get("compiles"),
                "banked_compiles": arms["banked"].get("compiles"),
                "zero_compile_replay":
                    arms["banked"].get("compiles") == 0,
                "max_abs_diff_vs_off": _sig3(diff)}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _hier_race_row():
    """Hierarchical-vs-flat race (round 11 acceptance): declare the 8
    virtual devices a 2x4 hybrid fabric and run one pencil transpose
    and one (1, 8)-grid ring SUMMA both ways. On the CPU sim both
    "fabrics" are the same silicon, so wall-clock is context only —
    the acceptance number is DCN bytes per apply (flat/hier ≥ 3),
    traced from the per-fabric collective counters and cross-checked
    against the cost model; the timing evidence lands via the
    ``tpu_hier`` cache merge on hardware harvests."""
    saved = {k: os.environ.get(k) for k in
             ("PYLOPS_MPI_TPU_FABRIC", "PYLOPS_MPI_TPU_METRICS",
              "PYLOPS_MPI_TPU_HIERARCHICAL")}
    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu import (DistributedArray, MPIFFTND,
                                    MPIMatrixMult)
        from pylops_mpi_tpu.parallel.mesh import make_mesh_hybrid
        from pylops_mpi_tpu.diagnostics import costmodel, metrics
        if len(_jax.devices()) != 8:
            return {"skipped": "needs 8 devices"}
        os.environ["PYLOPS_MPI_TPU_FABRIC"] = "2x4"
        os.environ["PYLOPS_MPI_TPU_METRICS"] = "on"
        os.environ.pop("PYLOPS_MPI_TPU_HIERARCHICAL", None)
        mesh_h = make_mesh_hybrid(dcn_size=2)
        rng = _np.random.default_rng(11)

        def _dcn(name):
            snap = metrics.snapshot()
            cnt = snap.get("counters", snap)
            return cnt.get(f"collective.{name}.bytes_dcn", 0)

        # --- pencil transpose: traced hier bytes vs the flat model
        dims = (16, 8, 4)
        x = (rng.standard_normal(dims)
             + 1j * rng.standard_normal(dims)).ravel()
        xd = DistributedArray.to_dist(x, mesh=mesh_h)
        itemsize = int(_np.dtype(xd._arr.dtype).itemsize)
        flat_cost = costmodel.pencil_transpose_cost(
            dims, 8, itemsize=itemsize, n_transposes=1,
            fabric_shape=(2, 4), hierarchical=False)
        metrics.clear_metrics()
        Oph = MPIFFTND(dims, axes=(0, 1), mesh=mesh_h, hierarchical="on")
        _jax.block_until_ready(Oph.matvec(xd)._arr)
        hier_dcn = _dcn("hier_pencil_transpose") / 2  # 2 per forward
        pencil_ratio = (_sig3(flat_cost.dcn_bytes / hier_dcn)
                        if hier_dcn else None)
        # wall-clock context: one jitted forward each way
        Opf = MPIFFTND(dims, axes=(0, 1), mesh=mesh_h,
                       hierarchical="off")
        fh = _jax.jit(lambda v: Oph.matvec(v)._arr)
        ff = _jax.jit(lambda v: Opf.matvec(v)._arr)
        for f in (fh, ff):
            _jax.block_until_ready(f(xd))
        t0 = time.perf_counter()
        _jax.block_until_ready(fh(xd))
        t_h = time.perf_counter() - t0
        t0 = time.perf_counter()
        _jax.block_until_ready(ff(xd))
        t_f = time.perf_counter() - t0

        # --- SUMMA ring on the slice-spanning (1, 8) axis: traced
        # flat vs traced hier, both through collective.ring_pass
        A = rng.standard_normal((24, 16))
        X = rng.standard_normal((16, 8))
        summa_dcn = {}
        for tag, hier in (("flat", "off"), ("hier", "on")):
            metrics.clear_metrics()
            Op = MPIMatrixMult(A, 8, kind="summa", dtype=_np.float64,
                               mesh=mesh_h, grid=(1, 8),
                               schedule="gather", overlap="on",
                               hierarchical=hier)
            _ = Op.matvec(DistributedArray.to_dist(X.ravel(),
                                                   mesh=mesh_h))
            summa_dcn[tag] = _dcn("ring_pass")
        summa_ratio = (_sig3(summa_dcn["flat"] / summa_dcn["hier"])
                       if summa_dcn.get("hier") else None)
        ratios = [r for r in (pencil_ratio, summa_ratio) if r]
        return {
            "fabric": "2x4",
            "pencil": {"dims": list(dims), "itemsize": itemsize,
                       "model_flat_dcn_bytes": int(flat_cost.dcn_bytes),
                       "traced_hier_dcn_bytes": int(hier_dcn),
                       "dcn_reduction": pencil_ratio,
                       "time_hier_vs_flat": (_sig3(t_h / t_f)
                                             if t_f else None)},
            "summa": {"shape": [24, 16, 8], "grid": [1, 8],
                      "flat_ring_dcn_bytes": int(summa_dcn["flat"]),
                      "hier_ring_dcn_bytes": int(summa_dcn["hier"]),
                      "dcn_reduction": summa_ratio},
            "worst_dcn_reduction": min(ratios) if ratios else None}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            from pylops_mpi_tpu.diagnostics import metrics as _m
            _m.clear_metrics()
        except Exception:
            pass


def _spill_provenance() -> str:
    """``spill=`` column for bench rows: the host-staging mode the
    round ran under — ``auto`` (refusals drain through host RAM),
    ``on`` (every concrete move host-staged), or ``off`` (round-13
    refusals). From ``PYLOPS_MPI_TPU_SPILL`` via utils/deps.py."""
    try:
        from pylops_mpi_tpu.utils.deps import spill_mode
        return spill_mode()
    except Exception:
        return "auto"


def _spill_race_row():
    """Host-RAM spill race (round 14 acceptance): an oversized
    destination the device planner refuses drains through the
    host-staging tier instead. The row checks (a) bit-identity of the
    spilled result against the unbounded oracle, (b) the
    double-buffer's overlap on the staged D2H drain (``to_host`` with
    overlap on vs off, best-of-reps — wall-clock is context only on
    the CPU sim, where the "device", the copy engine, and the host are
    the same silicon; the >= 1.3x bar is a hardware number that lands
    via the cache merge, the round-8 overlap-race rule), and (c) the
    traced ``bytes_d2h``/``bytes_h2d`` counters against the plan
    totals with ``cost_model() <= budget``. CPU-sim sized so the
    compact line carries it every round;
    ``BENCH_SPILL_PYLOPS_MPI_TPU=1`` forces it on hardware too."""
    saved = {k: os.environ.get(k) for k in
             ("PYLOPS_MPI_TPU_SPILL", "PYLOPS_MPI_TPU_RESHARD_BUDGET",
              "PYLOPS_MPI_TPU_METRICS")}
    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu import DistributedArray
        from pylops_mpi_tpu.parallel import reshard as _rs
        from pylops_mpi_tpu.parallel import spill as _sp
        from pylops_mpi_tpu.parallel.partition import Partition as _P
        from pylops_mpi_tpu.parallel.mesh import default_mesh
        from pylops_mpi_tpu.diagnostics import metrics
        for k in saved:
            os.environ.pop(k, None)
        os.environ["PYLOPS_MPI_TPU_METRICS"] = "on"
        mesh = default_mesh()
        n_dev = int(mesh.devices.size)
        rng = _np.random.default_rng(14)
        rows, cols = 32 * max(n_dev, 1), 8192   # 16 MB f64 / 8 MB f32
        M = rng.standard_normal((rows, cols))
        x = DistributedArray.to_dist(M, mesh=mesh)
        # the bench child runs without x64, so size the budget from the
        # dtype the array actually landed with — one row of scratch
        itemsize = _np.dtype(x.dtype).itemsize
        row_bytes = cols * itemsize

        # (a) oversized gather: one row of budget is below the device
        # floor (an all_gather needs two live rows), so ``off``
        # refuses; ``auto`` converts the refusal into a host-staged
        # schedule, bit-identical to the unbounded oracle
        budget = row_bytes
        refused = False
        try:
            _rs.reshard(x, partition=_P.BROADCAST, budget=budget,
                        spill="off")
        except _rs.ReshardError:
            refused = True
        oracle = _np.asarray(_rs.reshard(
            x, partition=_P.BROADCAST, budget=None,
            spill="off").asarray())
        metrics.clear_metrics()
        spilled = _rs.reshard(x, partition=_P.BROADCAST, budget=budget)
        host_dst = isinstance(spilled, _sp.HostArray)
        got = (spilled.value if host_dst
               else _np.asarray(spilled.asarray()))
        bit_identical = bool(_np.array_equal(got, oracle))

        # (c) counters vs the plan: a device source draining to a host
        # destination is pure D2H — every byte lands in bytes_d2h and
        # nothing goes back up
        plan = _rs.plan_reshard(
            (rows, cols), itemsize, _rs.Layout.scatter(x._axis_sizes),
            _rs.Layout.replicated(n_dev), budget=budget, spill="auto")
        cnt = metrics.snapshot().get("counters", {})
        d2h = int(cnt.get("collective.reshard.bytes_d2h", 0))
        h2d = int(cnt.get("collective.reshard.bytes_h2d", 0))
        total = rows * cols * itemsize
        bytes_ok = (d2h == plan.nbytes_d2h == total
                    and h2d == plan.nbytes_h2d == 0)

        # (b) the double-buffer: chunk k+1's carve is dispatched before
        # chunk k's blocking host copy, so device work rides under the
        # D2H drain; off serializes with a block per chunk
        def _drain(ov):
            _jax.block_until_ready(x._arr)
            t0 = time.perf_counter()
            _sp.to_host(x, chunks=16, overlap=ov)
            return time.perf_counter() - t0
        for ov in ("on", "off"):    # warm both paths
            _drain(ov)
        t_on = min(_drain("on") for _ in range(5))
        t_off = min(_drain("off") for _ in range(5))
        return {
            "shape": [rows, cols], "budget_bytes": int(budget),
            "chunks": len(plan.steps),
            "off_refuses": refused, "host_dst": host_dst,
            "bit_identical_vs_oracle": bit_identical,
            "bytes_accounting_ok": bytes_ok,
            "d2h_bytes": d2h, "h2d_bytes": h2d,
            "cost_model_bytes": int(plan.cost_model()),
            "cost_model_under_budget": plan.cost_model() <= budget,
            "overlap_on_s": _sig3(t_on), "overlap_off_s": _sig3(t_off),
            "overlap_speedup": _sig3(t_off / t_on) if t_on else None,
            "overlap_note": ("cpu-sim context only: device, copy "
                             "engine and host share the silicon; the "
                             "PCIe overlap win is a hardware number")}
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            from pylops_mpi_tpu.diagnostics import metrics as _m
            _m.clear_metrics()
        except Exception:
            pass


def _precond_race_row():
    """Preconditioned-solver race (preconditioner-PR acceptance bar):
    an ill-conditioned 2-D Laplacian-regularized CGLS solve, run
    unpreconditioned, with the block-Jacobi preconditioner, and with
    the 2-level V-cycle. Stamps iterations-to-tol for each arm plus
    the headline ratios — the acceptance gate is block-Jacobi
    ``iters_ratio <= 0.5`` with a wall-clock win. Error-isolated: a
    preconditioner failure reports itself, never costs the headline."""
    try:
        import numpy as _np
        import jax as _jax
        import jax.numpy as _jnp
        from pylops_mpi_tpu import DistributedArray
        from pylops_mpi_tpu.linearoperator import MPILinearOperator
        from pylops_mpi_tpu.ops.precond import make_precond
        from pylops_mpi_tpu.solvers import cgls

        dims = (24, 24)
        n = dims[0] * dims[1]
        eps = 0.05   # small regularization → large condition number

        def _lap_factory(d):
            """Dirichlet 5-point Laplacian on grid ``d`` (symmetric —
            one-sided boundary stencils would break CG/MG)."""
            class _Lap(MPILinearOperator):
                accepts_block = True
                dims_ = d

                def __init__(self):
                    nn = d[0] * d[1]
                    super().__init__(shape=(nn, nn),
                                     dtype=_np.dtype("float32"))

                def _apply(self, x):
                    arr = x._global() if hasattr(x, "_global") else x
                    g = arr.reshape(d)
                    p = _jnp.pad(g, 1)
                    out = (4.0 * g - p[:-2, 1:-1] - p[2:, 1:-1]
                           - p[1:-1, :-2] - p[1:-1, 2:])
                    flat = (eps * arr.reshape(-1)
                            + out.reshape(-1)).astype(arr.dtype)
                    if hasattr(x, "_global"):
                        return DistributedArray._wrap(
                            x._from_global(flat), x)
                    return flat

                _matvec = _apply
                _rmatvec = _apply
            return _Lap()

        Op = _lap_factory(dims)
        rng = _np.random.default_rng(11)
        xt = rng.standard_normal(n).astype(_np.float32)
        yv = _np.asarray(Op.matvec(
            DistributedArray.to_dist(xt)).asarray())
        y = DistributedArray.to_dist(yv)
        niter = 400
        rtol = 1e-3
        g0 = _np.asarray(Op.rmatvec(
            DistributedArray.to_dist(yv)).asarray())

        # exact diagonal blocks of the normal operator AᴴA (CGLS
        # preconditions the normal system; the mod-m probe would alias
        # the ±row couplings of the squared stencil into the blocks)
        from pylops_mpi_tpu.ops.precond import BlockJacobiPrecond
        Ad = _np.asarray(Op.todense(), dtype=_np.float64)
        Nd = Ad.T @ Ad
        m = dims[1]
        blocks = _np.stack([Nd[i * m:(i + 1) * m, i * m:(i + 1) * m]
                            for i in range(n // m)])
        bj = BlockJacobiPrecond(blocks.astype(_np.float32))
        vc = make_precond(Op, kind="mg", op_factory=_lap_factory,
                          dims=dims, levels=2)

        def _arm(M):
            # the fused stop test is absolute in the M-norm (kold =
            # g·Mg), so each arm's tol comes from its own kold0 — the
            # standard relative-residual PCG criterion, identical
            # reduction factor on every arm
            z0 = (g0 if M is None else _np.asarray(M.matvec(
                DistributedArray.to_dist(g0)).asarray()))
            tol = float(rtol ** 2 * _np.dot(g0, z0))

            def run():
                out = cgls(Op, y, niter=niter, tol=tol, M=M)
                _jax.block_until_ready(out[0]._arr)
                return out
            out = run()                      # compile outside timing
            t0 = time.perf_counter()
            out = run()
            t = time.perf_counter() - t0
            xs = _np.asarray(out[0].asarray())
            err = float(_np.linalg.norm(xs - xt)
                        / _np.linalg.norm(xt))
            return int(out[2]), t, err

        it0, t0s, e0 = _arm(None)
        itb, tbs, eb = _arm(bj)
        itv, tvs, ev = _arm(vc)
        return {
            "problem": {"dims": list(dims), "eps": eps,
                        "niter_cap": niter},
            "unpreconditioned": {"iters": it0, "wall_s": _sig3(t0s),
                                 "rel_err": _sig3(e0),
                                 "solves_per_sec": _sig3(1.0 / t0s)},
            "block_jacobi": {"iters": itb, "wall_s": _sig3(tbs),
                             "rel_err": _sig3(eb),
                             "solves_per_sec": _sig3(1.0 / tbs)},
            "vcycle": {"iters": itv, "wall_s": _sig3(tvs),
                       "rel_err": _sig3(ev),
                       "solves_per_sec": _sig3(1.0 / tvs)},
            "bj_iters_ratio": _sig3(itb / it0) if it0 else None,
            "vc_iters_ratio": _sig3(itv / it0) if it0 else None,
            "bj_wall_speedup": _sig3(t0s / tbs) if tbs else None,
            "vc_wall_speedup": _sig3(t0s / tvs) if tvs else None,
        }
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}


def _sparse_race_row():
    """Sparse-vs-dense matvec race (sparse-tier acceptance bar): at
    ≥90% sparsity the triplet operator's forward+adjoint sweep against
    the dense SUMMA/block operator on the same matrix. Stamps the byte
    ratio the tier-selection cost model reasons from and the measured
    wall ratio. Error-isolated like every race row."""
    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu import DistributedArray
        from pylops_mpi_tpu.ops.matrixmult import MPIMatrixMult
        from pylops_mpi_tpu.ops.sparse import MPISparseMatrixMult

        N = M = 512
        density = 0.05           # 95% sparse — well past the 90% gate
        rng = _np.random.default_rng(13)
        A = (rng.standard_normal((N, M))
             * (rng.random((N, M)) < density)).astype(_np.float32)
        Sp = MPISparseMatrixMult.from_dense(A)
        De = MPIMatrixMult(A, 1, dtype=_np.float32)
        x = DistributedArray.to_dist(
            rng.standard_normal(M).astype(_np.float32))
        y = DistributedArray.to_dist(
            rng.standard_normal(N).astype(_np.float32))

        def _sweep(op):
            def run():
                f = op.matvec(x)
                a = op.rmatvec(y)
                _jax.block_until_ready((f._arr, a._arr))
                return f, a
            run()                            # compile outside timing
            reps = 20
            t0 = time.perf_counter()
            for _ in range(reps):
                f, a = run()
            t = (time.perf_counter() - t0) / reps
            return t, f, a

        t_sp, f_sp, a_sp = _sweep(Sp)
        t_de, f_de, a_de = _sweep(De)
        err = max(
            float(_np.max(_np.abs(_np.asarray(f_sp.asarray())
                                  - _np.asarray(f_de.asarray())))),
            float(_np.max(_np.abs(_np.asarray(a_sp.asarray())
                                  - _np.asarray(a_de.asarray())))))
        it = _np.dtype(_np.float32).itemsize
        bytes_ratio = (Sp.nnz * (it + 8)) / (N * M * it)
        return {
            "shape": [N, M], "density": _sig3(Sp.density),
            "nnz": int(Sp.nnz),
            "sparse_sweep_s": _sig3(t_sp), "dense_sweep_s": _sig3(t_de),
            "sparse_vs_dense_wall": _sig3(t_sp / t_de) if t_de else None,
            "bytes_ratio": _sig3(bytes_ratio),
            "max_abs_diff": _sig3(err),
        }
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}


def _ca_race_row():
    """Communication-avoiding solver race (CA-PR acceptance bar): a
    fused CG solve under an injected per-collective latency floor
    (``PYLOPS_MPI_TPU_REDUCE_STALL`` — a serial dependency chain the
    compiler cannot elide, standing in for the all-reduce α-term the
    single-host CPU sim cannot produce), classic two-reduction engine
    vs the one-reduction pipelined engine on the same trajectory.
    Stamps the body all-reduce counts (pinned via ``utils/hlo.py``
    with the stall OFF — program truth, not timing), iteration parity
    and the wall ratio. Error-isolated like every race row."""
    saved = {k: os.environ.get(k) for k in
             ("PYLOPS_MPI_TPU_CA", "PYLOPS_MPI_TPU_REDUCE_STALL")}

    def _setenv(k, v):
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    try:
        import numpy as _np
        import jax as _jax
        from pylops_mpi_tpu import DistributedArray, MPIBlockDiag
        from pylops_mpi_tpu.ops.local import MatrixMult
        from pylops_mpi_tpu.solvers import cg, clear_fused_cache
        from pylops_mpi_tpu.solvers import ca as _camod
        from pylops_mpi_tpu.solvers.basic import _cg_fused
        from pylops_mpi_tpu.utils import hlo as _hlo

        rng = _np.random.default_rng(17)
        nblk = max(len(_jax.devices()), 2)
        nloc = 48
        mats = []
        for _ in range(nblk):
            m = rng.standard_normal((nloc, nloc)).astype(_np.float32)
            # conditioned to take a few dozen iterations — enough for
            # the per-iteration latency floor to dominate the wall
            mats.append((m @ m.T) * 0.5
                        + 2.0 * _np.eye(nloc, dtype=_np.float32))
        Op = MPIBlockDiag([MatrixMult(m, dtype=_np.float32)
                           for m in mats])
        n = nblk * nloc
        xt = rng.standard_normal(n).astype(_np.float32)
        yv = _np.asarray(Op.matvec(
            DistributedArray.to_dist(xt)).asarray())
        y = DistributedArray.to_dist(yv)
        niter = 80
        # the fused stop test is absolute on kold = r·r; with x0 = 0
        # the standard relative criterion is rel² x ‖y‖²
        tol = float(1e-4 ** 2 * _np.dot(yv.astype(_np.float64), yv))

        def _x0():
            return DistributedArray.to_dist(
                _np.zeros(n, dtype=_np.float32))

        # 1. program truth, stall OFF: all-reduces per while-body
        _setenv("PYLOPS_MPI_TPU_REDUCE_STALL", None)
        _setenv("PYLOPS_MPI_TPU_CA", "off")
        clear_fused_cache()

        def _classic_fn(y_, x_, t_):
            return _cg_fused(Op, y_, x_, t_, niter=niter)

        def _pipe_fn(y_, x_, t_):
            return _camod._pipe_cg_fused(Op, y_, x_, t_, niter=niter)

        red_classic = _hlo.count_reductions(
            _hlo.compiled_hlo(_classic_fn, y, _x0(), 0.0), scope="body")
        red_pipe = _hlo.count_reductions(
            _hlo.compiled_hlo(_pipe_fn, y, _x0(), 0.0), scope="body")

        # 2. the race, stall ON: every reduction pays the latency floor
        stall = os.environ.get("BENCH_CA_STALL_PYLOPS_MPI_TPU", "4096")
        _setenv("PYLOPS_MPI_TPU_REDUCE_STALL", stall)

        def _arm(mode):
            _setenv("PYLOPS_MPI_TPU_CA", mode)
            clear_fused_cache()

            def run():
                out = cg(Op, y, _x0(), niter=niter, tol=tol,
                         fused=True)
                _jax.block_until_ready(out[0]._arr)
                return out

            out = run()              # compile outside timing
            reps = 3
            t0 = time.perf_counter()
            for _ in range(reps):
                out = run()
            t = (time.perf_counter() - t0) / reps
            xs = _np.asarray(out[0].asarray())
            err = float(_np.linalg.norm(xs - xt)
                        / _np.linalg.norm(xt))
            return int(out[1]), t, err

        it0, t0s, e0 = _arm("off")
        itp, tps, ep = _arm("pipelined")
        parity = abs(itp - it0) <= max(2, int(round(0.1 * it0)))
        return {
            "problem": {"nblk": nblk, "nloc": nloc, "niter_cap": niter},
            "host_stall_steps": int(stall),
            "reductions_per_iter": {"classic": red_classic,
                                    "pipelined": red_pipe},
            "classic": {"iters": it0, "wall_s": _sig3(t0s),
                        "rel_err": _sig3(e0),
                        "solves_per_sec": _sig3(1.0 / t0s)},
            "pipelined": {"iters": itp, "wall_s": _sig3(tps),
                          "rel_err": _sig3(ep),
                          "solves_per_sec": _sig3(1.0 / tps)},
            # the sentinel sub-verdict rides this top-level rate
            "solves_per_sec": _sig3(1.0 / tps) if tps else None,
            "iters_parity": parity,
            "wall_speedup": _sig3(t0s / tps) if tps else None,
        }
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}
    finally:
        for k, v in saved.items():
            _setenv(k, v)
        try:
            from pylops_mpi_tpu.solvers import clear_fused_cache
            clear_fused_cache()
        except Exception:
            pass


def _grad_race_row():
    """Gradient race (autodiff-PR acceptance bar): d loss/d y through a
    fused CGLS solve, the implicit fixed-point rule (backward = ONE
    more fused solve, ``pylops_mpi_tpu/autodiff/implicit.py``) vs the
    unrolled scan-tape oracle (what reverse-mode gives everyone else —
    O(niter·n) residency). Both arms compile ``jit(grad(loss))`` once,
    then time 3 post-compile reps; the compiler's own
    ``memory_analysis().temp_size_in_bytes`` stamps each program's
    scratch residency (None when the backend does not report it).
    Agreement between the two gradients is stamped as
    ``max_rel_diff`` — the wall/memory win only counts on matching
    numbers. Error-isolated like every race row."""
    try:
        import numpy as _np
        import jax as _jax
        import jax.numpy as _jnp
        from pylops_mpi_tpu import DistributedArray, MPIBlockDiag
        from pylops_mpi_tpu.ops.local import MatrixMult
        from pylops_mpi_tpu.autodiff import cgls_solve, unrolled_cgls
        from pylops_mpi_tpu.solvers import clear_fused_cache

        rng = _np.random.default_rng(23)
        nblk = max(len(_jax.devices()), 2)
        bm, bn, niter = 48, 32, 60
        mats = [rng.standard_normal((bm, bn)) for _ in range(nblk)]
        Op = MPIBlockDiag([MatrixMult(m, dtype=_np.float64)
                           for m in mats])
        y = DistributedArray.to_dist(
            rng.standard_normal(nblk * bm))
        x0 = DistributedArray.to_dist(_np.zeros(nblk * bn))
        w = _jnp.asarray(rng.standard_normal(nblk * bn))
        damp = 1e-3

        def loss_implicit(y_):
            x = cgls_solve(Op, y_, x0, niter=niter, damp=damp,
                           tol=0.0)
            return _jnp.vdot(w, x._arr.ravel()).real

        def loss_unrolled(y_):
            x = unrolled_cgls(Op, y_, x0, niter=niter, damp=damp)
            return _jnp.vdot(w, x._arr.ravel()).real

        clear_fused_cache()
        out, grads = {}, {}
        for name, fn in (("implicit", loss_implicit),
                         ("unrolled", loss_unrolled)):
            compiled = _jax.jit(_jax.grad(fn)).lower(y).compile()
            g = compiled(y)
            _jax.block_until_ready(g._arr)    # compile/warm outside
            reps = 3
            t0 = time.perf_counter()
            for _ in range(reps):
                g = compiled(y)
                _jax.block_until_ready(g._arr)
            t = (time.perf_counter() - t0) / reps
            temp = None
            try:  # CPU backends may not report a memory analysis
                ma = compiled.memory_analysis()
                v = getattr(ma, "temp_size_in_bytes", None)
                temp = int(v) if v is not None else None
            except Exception:
                temp = None
            grads[name] = _np.asarray(g.asarray())
            out[name] = {"wall_s": _sig3(t),
                         "grads_per_sec": _sig3(1.0 / t),
                         "temp_bytes": temp}
        scale = max(1.0, float(_np.max(_np.abs(grads["unrolled"]))))
        diff = float(_np.max(_np.abs(grads["implicit"]
                                     - grads["unrolled"]))) / scale
        ti = 1.0 / out["implicit"]["grads_per_sec"]
        tu = 1.0 / out["unrolled"]["grads_per_sec"]
        mi = out["implicit"]["temp_bytes"]
        mu = out["unrolled"]["temp_bytes"]
        return {
            "problem": {"nblk": nblk, "bm": bm, "bn": bn,
                        "niter": niter, "dtype": "float64"},
            **out,
            # the sentinel sub-verdict rides this top-level rate
            "grads_per_sec": out["implicit"]["grads_per_sec"],
            "wall_speedup": _sig3(tu / ti) if ti else None,
            "temp_bytes_ratio": (_sig3(mu / mi)
                                 if mi and mu else None),
            "max_rel_diff": _sig3(diff),
            "grads_match": diff <= 1e-5,
        }
    except Exception as e:  # the race must never cost the headline
        return {"error": repr(e)[:300]}
    finally:
        try:
            from pylops_mpi_tpu.solvers import clear_fused_cache
            clear_fused_cache()
        except Exception:
            pass


# dense matmul peak per chip, TFLOP/s (bf16 inputs, f32 accumulation on
# the MXU) — public spec-sheet numbers; most-specific key checked first
_PEAK_TFLOPS = [
    ("v6e", 918.0), ("v6 lite", 918.0), ("v6", 918.0),
    ("v5p", 459.0), ("v5e", 197.0), ("v5 lite", 197.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]

# HBM bandwidth peak per chip, GB/s — public spec-sheet numbers. The
# denominator every `hbm_gbps` claim must be divided by before calling
# anything "at the roofline": round 5 reported 1261 GB/s on a chip
# whose HBM peaks at ~819 GB/s, which is physically impossible for an
# HBM-streaming workload and was actually a VMEM-resident working set
# (docs/design.md round-7 correction).
_PEAK_HBM_GBPS = [
    ("v6e", 1640.0), ("v6 lite", 1640.0), ("v6", 1640.0),
    ("v5p", 2765.0), ("v5e", 819.0), ("v5 lite", 819.0), ("v5", 2765.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]


def _peak_hbm_gbps(device):
    """Per-chip HBM bandwidth peak, GB/s (None off-TPU / unknown-TPU —
    an unknown chip gets NO roofline rather than a wrong one)."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, gb in _PEAK_HBM_GBPS:
        if key in kind:
            return gb
    return None


def _vmem_budget_bytes() -> int:
    """Per-core VMEM assumed for the on-chip-residency check
    (override: PYLOPS_MPI_TPU_VMEM_BYTES). A per-device working set at
    or under this streams from VMEM after the first iteration, so its
    measured GB/s is NOT an HBM number — the round-5 'roofline' artifact
    (4 MB/device blocks at N=1024 'achieving' 1261 GB/s on an 819 GB/s
    chip)."""
    try:
        return int(os.environ.get("PYLOPS_MPI_TPU_VMEM_BYTES",
                                  str(16 << 20)))
    except ValueError:
        return 16 << 20


def _peak_flops_per_chip(device, mode: str = "bf16"):
    """Per-chip dense-matmul peak for ``mode``. The spec-sheet figures
    are bf16-input/f32-accumulate; f32 GEMMs under the package's
    ``jax_default_matmul_precision=highest`` pin run as 6 bf16 MXU
    passes (3 products × 2 operand splits), so the f32 peak is bf16/6 —
    MFU must be reported against the precision actually used, never
    f32 throughput against the bf16 ceiling (round-4 VERDICT weak #3)."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    peak = None
    for key, tf in _PEAK_TFLOPS:
        if key in kind:
            peak = tf * 1e12
            break
    if peak is None and getattr(device, "platform", "") == "tpu":
        peak = 275e12  # conservative unknown-TPU default (v4 figure)
    if peak is not None and mode.startswith("f32"):
        peak /= 6.0
    return peak


def _sig3(x):
    """3 significant digits — NEVER a fixed decimal count: tiny MFUs
    (~3e-5 at GEMV-bound solve sizes) must survive serialization, they
    ARE the diagnostic story (round-4 VERDICT weak #3)."""
    return None if x is None else float(f"{x:.3g}")


def make_problem(nblk, nblock, seed=0):
    """The flagship linear system, shared by the headline measurement
    and the subprocess NumPy baseline so the two can never
    desynchronize: diagonally-dominant blocks (cond ≈ 1 + 2/√N, so the
    solve demonstrates convergence, not just throughput), a known
    model, and its exact data.

    Blocks are quantized to the bf16 grid (exactly representable at
    both storage precisions): the f32 and bf16-storage rows then solve
    the IDENTICAL system, so any rel_err gap between them measures
    recurrence contamination (the dtype-stability property the fused
    solvers pin), not the ~2⁻⁹ representation rounding of random f32
    entries — which would otherwise floor the bf16 row at ~2e-3 no
    matter how clean the solver is. Conditioning and the f32 numbers
    are unaffected (the quantized blocks are the same random
    diagonally-dominant family)."""
    try:
        import ml_dtypes
        _bf16 = ml_dtypes.bfloat16
    except ImportError:  # ships with jax; NumPy-only baseline fallback
        _bf16 = None
    rng = np.random.default_rng(seed)
    blocks_np = []
    for _ in range(nblk):
        b = (rng.standard_normal((nblock, nblock))
             / np.sqrt(nblock)).astype(np.float32)
        np.fill_diagonal(b, b.diagonal() + 4.0)
        if _bf16 is not None:
            b = b.astype(_bf16).astype(np.float32)
        blocks_np.append(b)
    xtrue = rng.standard_normal(nblk * nblock).astype(np.float32)
    y_np = np.concatenate([b @ xtrue[i * nblock:(i + 1) * nblock]
                           for i, b in enumerate(blocks_np)])
    return blocks_np, xtrue, y_np


def numpy_cgls_iters_per_sec_subprocess(nblk, nblock, seed=0, niter=10,
                                        timeout=600, k=5):
    """The NumPy stand-in timed in a CLEAN subprocess: measuring it
    inside the bench child — after XLA has claimed the host's thread
    pools — penalizes BLAS unpredictably (observed round 3: 13.5 vs
    8.4 iters/s run to run for the identical problem). The subprocess
    regenerates the same seeded blocks, so nothing large crosses the
    pipe. Falls back to the in-process number on any failure.

    Returns ``(median_ips, stats-dict)`` over ``k`` repeats — round-3
    VERDICT weak #7: a point estimate hid a noise band wider than the
    signal; the artifact now carries the dispersion so ``vs_baseline``
    is trustworthy (or visibly not)."""
    import subprocess
    code = (
        "import json, sys\n"
        "import numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "import bench\n"
        "blocks, xt, y = bench.make_problem(%d, %d, seed=%d)\n"
        "rs = sorted(bench.numpy_cgls_iters_per_sec(blocks, y, niter=%d)"
        " for _ in range(%d))\n"
        "print(json.dumps({'median': float(np.median(rs)),"
        " 'min': rs[0], 'max': rs[-1]}))\n"
    ) % (os.path.dirname(os.path.abspath(__file__)), nblk, nblock, seed,
         niter, k)
    env = {k_: v for k_, v in os.environ.items()
           if not k_.startswith(("XLA_", "JAX_"))}
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=timeout)
        for line in reversed((p.stdout or "").strip().splitlines()):
            if line.startswith("{"):
                st = json.loads(line)
                med = float(st["median"])
                spread = ((st["max"] - st["min"]) / med * 100.0
                          if med else 0.0)
                return med, {"median": round(med, 2),
                             "min": round(st["min"], 2),
                             "max": round(st["max"], 2),
                             "spread_pct": round(spread, 1), "k": k}
    except Exception:
        pass
    return None, None


def numpy_cgls_iters_per_sec(blocks, y, niter=10):
    """Reference-style CGLS: per-iteration host scalars, NumPy matvecs —
    mirrors pylops_mpi/optimization/cls_basic.py:370-404."""
    def matvec(x):
        return np.concatenate([b @ x[i * b.shape[1]:(i + 1) * b.shape[1]]
                               for i, b in enumerate(blocks)])

    def rmatvec(x):
        return np.concatenate([b.T @ x[i * b.shape[0]:(i + 1) * b.shape[0]]
                               for i, b in enumerate(blocks)])

    x = np.zeros(sum(b.shape[1] for b in blocks), dtype=y.dtype)
    s = y - matvec(x)
    r = rmatvec(s)
    c = r.copy()
    q = matvec(c)
    kold = float(np.abs(r @ r))
    t0 = time.perf_counter()
    for _ in range(niter):
        a = kold / float(q @ q)
        x += a * c
        s -= a * q
        r = rmatvec(s)
        k = float(np.abs(r @ r))
        c = r + (k / kold) * c
        q = matvec(c)
        kold = k
    return niter / (time.perf_counter() - t0)


def _enable_compile_cache():
    """Persistent XLA compilation cache shared by every bench/selfcheck/
    diag process: compiles over the remote TPU tunnel cost tens of
    seconds each, and the harvest protocol re-runs the same programs
    across stages and windows.

    Namespaced by a host fingerprint: XLA's CPU AOT executables bake in
    the compile machine's ISA features, and loading one compiled on a
    different host warns about SIGILL risk (observed: round-3 cache
    entries carried amx/avx512 feature sets this host lacks). A
    per-host subdir makes stale cross-machine entries unreachable."""
    try:
        import hashlib
        import platform as _plat
        import jax
        fp = _plat.machine() + "-" + _plat.processor()
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("flags"):
                        fp += line
                        break
        except OSError:
            pass
        sub = hashlib.sha256(fp.encode()).hexdigest()[:12]
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".jax_cache", sub)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        pass  # cache is an optimization, never a requirement


def child_main():
    """The actual measurement. Runs in a supervised subprocess."""
    import jax
    _enable_compile_cache()
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # JAX_PLATFORMS alone is insufficient: a TPU plugin registered
        # from sitecustomize can override env-level selection, and its
        # backend init can hang when the device tunnel is down
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)

    # tracing on (PYLOPS_MPI_TPU_TRACE=spans|full) with no explicit
    # sink: land the Chrome-trace JSONL next to bench_detail.json so
    # the run always leaves an openable artifact
    if os.environ.get("PYLOPS_MPI_TPU_TRACE", "off") not in ("", "off"):
        os.environ.setdefault("PYLOPS_MPI_TPU_TRACE_FILE",
                              os.path.join(here, "bench_trace.jsonl"))

    def _progress(msg):
        # stderr markers: when the supervising daemon kills this child on
        # timeout, its stderr tail shows the stage reached (round 3: a
        # 2400s full-flagship timeout left zero evidence of where)
        print(f"[bench-child] {msg}", file=sys.stderr, flush=True)

    # On real TPU, validate every Pallas kernel against oracles BEFORE
    # the headline: Mosaic compile/layout failures only surface on
    # hardware, and a dead kernel must downgrade the bench mode (fused
    # normal path / explicit stencil off) instead of corrupting it.
    # The selfcheck runs in its OWN subprocess, spawned BEFORE this
    # process touches the backend: (a) a runtime UNIMPLEMENTED from a
    # missing backend op (e.g. the axon tunnel's FFT custom-call) wedges
    # the process it happens in and the headline must not inherit that;
    # (b) standard libtpu grants exclusive chip access — a subprocess
    # spawned while the parent already holds the device would hang.
    selfcheck = None
    allow_pallas_normal = True
    allow_bf16_storage = True
    tpu_intended = os.environ.get("BENCH_FORCE_CPU") != "1"
    if tpu_intended and os.environ.get("BENCH_SELFCHECK_PYLOPS_MPI_TPU",
                                       "1") != "0":
        try:
            _progress("selfcheck (isolated subprocess, pre-backend)")
            here_b = os.path.join(here, "benchmarks", "tpu_selfcheck.py")
            selfcheck, sc_err = _run_json_cmd(
                [sys.executable, here_b], dict(os.environ),
                timeout=_stage_budget("bench_selfcheck", 600), cwd=here)
            if selfcheck is None:
                raise RuntimeError(sc_err or "selfcheck subprocess died")
            if selfcheck.get("platform") != "tpu":
                # tunnel dropped: the subprocess fell back to CPU
                # interpret mode, which proves nothing about hardware —
                # keep the report but gate nothing on it
                selfcheck = {**selfcheck, "note": "ran off-TPU; kernel "
                             "gating skipped"}
            else:
                ck = selfcheck.get("checks", {})
                if not ck.get("pallas_normal_matvec", {}).get("ok"):
                    allow_pallas_normal = False
                # the bf16 Mosaic lowering can fail independently of f32
                # (different tiling/layout constraints) — a dead bf16
                # kernel must drop the headline to f32, not corrupt it
                if not ck.get("pallas_normal_matvec_bf16", {}).get("ok"):
                    allow_bf16_storage = False
                if not (ck.get("pallas_first_derivative", {}).get("ok")
                        and ck.get("pallas_second_derivative",
                                   {}).get("ok")
                        and ck.get("pallas_stencil_taps", {}).get("ok")):
                    os.environ["PYLOPS_MPI_TPU_EXPLICIT_STENCIL"] = "0"
                    os.environ["BENCH_STENCIL_SELFCHECK_DEAD"] = "1"
        except Exception as e:
            # selfcheck itself crashed: trust NO unvalidated Pallas path
            selfcheck = {"ok": False, "error": repr(e)[:300]}
            allow_pallas_normal = False
            allow_bf16_storage = False
            os.environ["PYLOPS_MPI_TPU_EXPLICIT_STENCIL"] = "0"
            os.environ["BENCH_STENCIL_SELFCHECK_DEAD"] = "1"

    import pylops_mpi_tpu as pmt
    from pylops_mpi_tpu.ops.local import MatrixMult
    from pylops_mpi_tpu.solvers.basic import _cgls_fused, _cgls_fused_normal

    n_dev = len(jax.devices())
    platform = jax.default_backend()
    on_tpu = platform == "tpu"
    mesh = pmt.make_mesh()
    pmt.set_default_mesh(mesh)

    nblk = max(n_dev, 1)
    # size overrides let the probe daemon run a seconds-cheap small
    # flagship (N=1024, 20 iters) the moment a TPU window opens, before
    # committing to the full N=4096 solve
    nblock = int(os.environ.get("BENCH_NBLOCK_PYLOPS_MPI_TPU", "4096"))
    niter = int(os.environ.get("BENCH_NITER_PYLOPS_MPI_TPU", "50"))

    blocks_np, xtrue, y_np = make_problem(nblk, nblock, seed=0)
    dy = pmt.DistributedArray.to_dist(y_np, mesh=mesh)
    x0 = pmt.DistributedArray.to_dist(np.zeros_like(xtrue), mesh=mesh)
    # stage the weights on device ONCE: both measure() modes (f32 and
    # bf16, which casts on device) reuse these — at N=4096 the 512 MB
    # re-upload per mode dominates wall-clock on the remote tunnel
    _progress(f"uploading {nblk}x{nblock}^2 blocks")
    blocks_dev = [jnp.asarray(b) for b in blocks_np]
    jax.block_until_ready(blocks_dev[-1])

    def measure(bf16: bool, fused_normal: bool, reps_override=None):
        """Marginal-cost timing: solves of ``niter`` and ``3*niter``
        iterations, per-iteration time = slope between them. This
        cancels the per-dispatch overhead of the remote-TPU tunnel,
        which fluctuates between ~0.1 ms and tens of ms run to run
        (observed round 2) and would otherwise dominate the number.
        Returns (iters/s, GFLOP/s, GB/s, rel_err, used_normal)."""
        # explicit dtype: the env-level precision policy must not
        # silently flip the f32 row's storage (bench.py measures BOTH
        # modes itself)
        Op = pmt.MPIBlockDiag(
            [MatrixMult(b, dtype=np.float32) for b in blocks_dev],
            compute_dtype=jnp.bfloat16 if bf16 else np.float32)
        use_normal = (fused_normal and allow_pallas_normal
                      and Op.has_fused_normal)
        solver = _cgls_fused_normal if use_normal else _cgls_fused

        def make_fn(nit):
            return jax.jit(lambda y, x, damp, tol: solver(Op, y, x, damp,
                                                          tol, niter=nit))

        reps = reps_override if reps_override is not None else int(
            os.environ.get("BENCH_REPS_PYLOPS_MPI_TPU",
                           "5" if on_tpu else "7"))

        def timed(fn):
            out = fn(dy, x0, 0.0, 0.0)
            jax.block_until_ready(out[0]._arr)
            dts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                out = fn(dy, x0, 0.0, 0.0)
                jax.block_until_ready(out[0]._arr)
                dts.append(time.perf_counter() - t0)
            # min for the estimate (least-noise), full spread recorded
            # so the artifact shows whether the band swamps the signal
            timed.spread_pct = round((max(dts) - min(dts))
                                     / min(dts) * 100.0, 1)
            return min(dts), out

        fn1, fn3 = make_fn(niter), make_fn(3 * niter)
        t1, out = timed(fn1)
        # spread of the niter headline run, captured before timed(fn3)
        # overwrites it — the artifact's spread_pct must describe the
        # measurement it annotates
        measure.last_spread_pct = timed.spread_pct
        t3, _ = timed(fn3)
        per_iter = (t3 - t1) / (2 * niter)
        if per_iter <= 0:
            # tunnel noise swamped the slope: retry the timing (the
            # compiled executables are reused), then fall back to
            # absolute timing rather than reporting a bogus
            # near-infinite rate
            t1, out = timed(fn1)
            measure.last_spread_pct = timed.spread_pct
            t3, _ = timed(fn3)
            per_iter = (t3 - t1) / (2 * niter)
            if per_iter <= 0:
                per_iter = t3 / (3 * niter)
        # 2 GEMMs (matvec+rmatvec) per iteration, 2*N^2 flops each/block
        gflops = (4.0 * nblock * nblock * nblk / per_iter) / 1e9
        # one (fused-normal) or two (classic) sweeps of the blocks/iter
        itemsize = 2 if bf16 else 4
        sweeps = 1 if use_normal else 2
        gbps = (sweeps * nblock * nblock * nblk * itemsize / per_iter) / 1e9
        rel_err = float(np.linalg.norm(out[0].asarray() - xtrue)
                        / np.linalg.norm(xtrue))
        # solver-status stamp (ISSUE 6): the headline runs guards-off
        # (bench times the production fast path), so the status is the
        # host-side resolution — a non-finite solution is a breakdown
        # the resilience layer would have caught in-loop
        iit = int(out[1])
        measure.last_status = (
            "breakdown" if not np.isfinite(rel_err)
            else ("converged" if iit < niter else "maxiter"))
        return 1.0 / per_iter, gflops, gbps, rel_err, use_normal

    # Component configs: on CPU they run in-process before the headline
    # (cheap, no wedge risk, isolated retry as backstop). On TPU each
    # config runs in its OWN subprocess AFTER the headline — one config
    # hitting a missing backend op (UNIMPLEMENTED) wedges whatever
    # process it runs in, and in round 3 that cost the entire
    # full-flagship stage; headline first means the number that matters
    # is banked before any component can misbehave.
    # BENCH_SIMULATE_TPU_ORDERING=1 forces the TPU ordering off-TPU so
    # the harvest-ladder rehearsal can exercise headline-first banking
    # and timeout salvage without hardware (round-3 VERDICT next #3).
    tpu_like = on_tpu or os.environ.get(
        "BENCH_SIMULATE_TPU_ORDERING") == "1"
    components = []
    run_comps = os.environ.get("BENCH_COMPONENTS_PYLOPS_MPI_TPU",
                               "1") != "0"
    if run_comps and not tpu_like:
        try:
            from benchmarks.bench_components import (
                run_components, retry_failed_isolated)
            _progress("components (in-process, cpu)")
            components = run_components(quick=not on_tpu)
            components = retry_failed_isolated(
                components, quick=not on_tpu,
                timeout=_stage_budget("component", 150))
        except Exception as e:  # components must never kill the headline
            components = [{"bench": "components", "error": repr(e)[:300]}]
        # release fused-solver cache entries (compiled executables +
        # pinned operator buffers) before the memory-heaviest solve
        pmt.clear_fused_cache()

    # Headline policy (round-3 VERDICT weak #4): **f32 is primary** —
    # vs_baseline compares against an f32 NumPy solve and the BASELINE
    # target is bit-meaningful CGLS convergence. bf16 block storage
    # (native TPU matrix format, half the HBM traffic) is measured and
    # reported as a labeled secondary ON EVERY BACKEND: the CPU-sim
    # row races bf16-storage against f32 so the round-5 40× two-sweep
    # cliff (bf16_race) can never rot undetected between TPU windows —
    # with the bf16-representable flagship blocks (make_problem) its
    # rel_err must track f32's, and its iters/s must stay ≥~0.8× f32
    # (ISSUE 2 acceptance). Set BENCH_PRIMARY_PYLOPS_MPI_TPU=bf16 to
    # flip the headline, or BENCH_BF16_PYLOPS_MPI_TPU=0 to skip bf16.
    measure_bf16 = (allow_bf16_storage
                    and os.environ.get("BENCH_BF16_PYLOPS_MPI_TPU",
                                       "1") != "0"
                    and os.environ.get("BENCH_F32_PYLOPS_MPI_TPU",
                                       "0") != "1")
    primary_bf16 = (measure_bf16
                    and os.environ.get("BENCH_PRIMARY_PYLOPS_MPI_TPU",
                                       "f32") == "bf16")
    _progress(f"headline f32 (N={nblock}, {niter} iters)")
    f32_ips, f32_gflops, f32_gbps, f32_err, _ = measure(bf16=False,
                                                        fused_normal=False)
    f32_spread = getattr(measure, "last_spread_pct", None)
    f32_status = getattr(measure, "last_status", None)
    f32_mode = "f32 two-sweep"
    f32_race = None
    # On CPU, race the native one-pass normal kernel (XLA-FFI,
    # native/ffi.py): one DRAM sweep of the blocks per iteration vs
    # the two-sweep's two — the schedule that beats the NumPy stand-in
    # (round-4 VERDICT next #2). Works on the virtual multi-device
    # mesh too: ffi.py caps per-shard threads so concurrent shard
    # calls share the socket instead of oversubscribing it.
    if (not on_tpu
            and os.environ.get("BENCH_F32_NORMAL_PYLOPS_MPI_TPU",
                               "1") != "0"):
        _progress("headline f32 fused-normal (native one-pass, race)")
        n_ips, n_gflops, n_gbps, n_err, used_n = measure(
            bf16=False, fused_normal=True)
        if used_n:
            f32_race = {"two_sweep_iters_per_sec": round(f32_ips, 2),
                        "fused_normal_iters_per_sec": round(n_ips, 2)}
            if n_ips > f32_ips:
                f32_ips, f32_gflops, f32_gbps, f32_err = (n_ips, n_gflops,
                                                          n_gbps, n_err)
                f32_spread = getattr(measure, "last_spread_pct", None)
                f32_status = getattr(measure, "last_status", None)
                f32_mode = "f32 fused-normal (native one-pass)"
    bf16_race = None
    bf16_res = None
    if measure_bf16 and on_tpu:
        _progress("headline bf16 fused-normal")
        b_ips, b_gflops, b_gbps, b_err, used_nrm = measure(
            bf16=True, fused_normal=True)
        b_status = getattr(measure, "last_status", None)
        b_mode = ("bf16-storage fused-normal" if used_nrm
                  else "bf16-storage two-sweep")
        if used_nrm:
            # race the two-sweep variant: the one-HBM-sweep Pallas
            # kernel is a theory-backed bet, but the round-3 small
            # flagship measured it SLOWER than XLA's two GEMVs on the
            # tunnel backend — take whichever actually wins, keep both
            _progress("headline bf16 two-sweep (race)")
            ips2, gflops2, gbps2, err2, _ = measure(bf16=True,
                                                    fused_normal=False)
            bf16_race = {"fused_normal_iters_per_sec": round(b_ips, 2),
                         "two_sweep_iters_per_sec": round(ips2, 2)}
            if ips2 > b_ips:
                b_ips, b_gflops, b_gbps, b_err = ips2, gflops2, gbps2, err2
                b_status = getattr(measure, "last_status", None)
                b_mode = "bf16-storage two-sweep (won race)"
    elif measure_bf16:
        # CPU-sim leg: two-sweep only (the Pallas interpret-mode
        # normal kernel is a perf trap off-TPU) and few reps — this
        # row exists to pin "no 40× cliff, f32-tracking rel_err", not
        # to win a throughput contest
        _progress("headline bf16 two-sweep (cpu-sim, race vs f32)")
        b_ips, b_gflops, b_gbps, b_err, _ = measure(
            bf16=True, fused_normal=False, reps_override=3)
        b_status = getattr(measure, "last_status", None)
        b_mode = "bf16-storage two-sweep (cpu-sim)"
        bf16_race = {"two_sweep_iters_per_sec": round(b_ips, 2),
                     "f32_two_sweep_iters_per_sec": round(f32_ips, 2)}
    if measure_bf16:
        bf16_res = {"iters_per_sec": round(b_ips, 2),
                    "gflops": round(b_gflops, 1),
                    "hbm_gbps": round(b_gbps, 1),
                    "rel_err": f"{b_err:.1e}", "mode": b_mode,
                    # resilience stamps (ISSUE 6): solve exit status +
                    # restart count (0 — bench times the single-attempt
                    # fast path, resilient_solve is not in the loop)
                    "status": b_status, "restarts": 0,
                    # the cliff detector: round 5 banked 0.025 here
                    "vs_f32": round(b_ips / f32_ips, 2)
                    if f32_ips else None}
        # mfu vs the bf16 peak is attached below once peaks are known
    if primary_bf16 and bf16_res is not None:
        ips, gflops, gbps, rel_err, mode = (b_ips, b_gflops, b_gbps,
                                            b_err, b_mode)
    else:
        ips, gflops, gbps, rel_err = f32_ips, f32_gflops, f32_gbps, f32_err
        mode = f32_mode

    # NumPy single-process stand-in for the reference CPU engine, timed
    # in a clean subprocess (fair BLAS threading); in-process fallback
    _progress("numpy baseline (subprocess, median-of-k)")
    cpu_ips, cpu_stats = numpy_cgls_iters_per_sec_subprocess(
        nblk, nblock, seed=0, niter=10)
    if cpu_ips is None:
        cpu_ips = numpy_cgls_iters_per_sec(blocks_np, y_np, niter=10)
        cpu_stats = {"note": "in-process fallback, single run"}

    # Degraded-CPU provenance (round-2 VERDICT weak #1): separate the
    # three candidate explanations for trailing the NumPy stand-in —
    # XLA-vs-BLAS GEMV speed, the 8-virtual-device carve of one
    # socket's threads/bandwidth, and collective/loop overhead — so the
    # artifact carries the breakdown instead of a bare 0.9x.
    cpu_breakdown = None
    if (not on_tpu and os.environ.get("BENCH_CPU_BREAKDOWN_PYLOPS_MPI_TPU",
                                      "1") != "0"):
        try:
            import time as _t
            A3 = jnp.asarray(np.stack(blocks_np))
            X2 = jnp.asarray(xtrue.reshape(nblk, nblock))

            def _best(f, reps=5):
                f()
                dt = float("inf")
                for _ in range(reps):
                    t0 = _t.perf_counter()
                    f()
                    dt = min(dt, _t.perf_counter() - t0)
                return dt

            # one fwd+adj sweep in NumPy (the baseline's memory pattern)
            xv = xtrue.copy()
            yv = y_np.copy()

            def np_sweep():
                for i, b in enumerate(blocks_np):
                    yv[i * nblock:(i + 1) * nblock] = \
                        b @ xv[i * nblock:(i + 1) * nblock]
                for i, b in enumerate(blocks_np):
                    xv[i * nblock:(i + 1) * nblock] = \
                        b.T @ yv[i * nblock:(i + 1) * nblock]

            t_np = _best(np_sweep)

            # the same sweep as ONE jitted batched einsum (no mesh)
            @jax.jit
            def _xla_sweep(X):
                q = jnp.einsum("bmn,bn->bm", A3, X)
                return jnp.einsum("bmn,bm->bn", A3, q)

            t_xla = _best(lambda: jax.block_until_ready(_xla_sweep(X2)))

            # the mesh-partitioned operator sweep (headline's inner op)
            Op = pmt.MPIBlockDiag([MatrixMult(b, dtype=np.float32)
                                   for b in blocks_np])
            dx0 = pmt.DistributedArray.to_dist(xtrue, mesh=mesh)
            _mv = jax.jit(lambda v: Op.rmatvec(Op.matvec(v))._arr)
            t_mesh = _best(lambda: jax.block_until_ready(_mv(dx0)))
            cpu_breakdown = {
                "numpy_sweep_ms": round(t_np * 1e3, 1),
                "xla_batched_sweep_ms": round(t_xla * 1e3, 1),
                "mesh_op_sweep_ms": round(t_mesh * 1e3, 1),
                "note": ("sweep = one matvec+rmatvec pass over all "
                         "blocks. xla_batched is the single-program "
                         "form; mesh_op adds the 8-virtual-device "
                         "carve (one socket's threads/bandwidth split "
                         "8 ways) + collective sync — the CI mesh "
                         "simulates placement, it cannot scale "
                         "hardware. See docs/benchmarking.md."),
            }
        except Exception as e:  # breakdown must never kill the headline
            cpu_breakdown = {"error": repr(e)[:300]}

    # tuner-vs-default race (round 10): small shapes, every CPU-sim
    # round (compact line carries the verdict between TPU windows);
    # BENCH_TUNE_RACE_PYLOPS_MPI_TPU=1 forces it on hardware too
    tune_race = None
    race_env = os.environ.get("BENCH_TUNE_RACE_PYLOPS_MPI_TPU", "")
    if race_env != "0" and (not on_tpu or race_env == "1"):
        _progress("tuner-vs-default race (small shapes)")
        tune_race = _tune_race_row()

    # batched-throughput race (batching PR): block-CGLS with K RHS
    # columns vs K sequential fused solves; every CPU-sim round,
    # BENCH_BATCHED_PYLOPS_MPI_TPU=1 forces it on hardware too
    batched = None
    batched_env = os.environ.get("BENCH_BATCHED_PYLOPS_MPI_TPU", "")
    if batched_env != "0" and (not on_tpu or batched_env == "1"):
        _progress("batched-throughput race (block-CGLS vs sequential)")
        batched = _batched_race_row()

    # serving race (serving PR): 32 single-RHS requests through the
    # continuous-batching daemon vs sequential fused solves; every
    # CPU-sim round, BENCH_SERVING_PYLOPS_MPI_TPU=1 forces it on
    # hardware too
    serving_row = None
    serving_env = os.environ.get("BENCH_SERVING_PYLOPS_MPI_TPU", "")
    if serving_env != "0" and (not on_tpu or serving_env == "1"):
        _progress("serving race (packed daemon vs sequential)")
        serving_row = _serving_race_row()

    # hierarchical-vs-flat race (round 11): per-fabric DCN bytes on
    # the simulated 2x4 hybrid, every CPU-sim round;
    # BENCH_HIER_PYLOPS_MPI_TPU=1 forces it on hardware too
    hier_race = None
    hier_env = os.environ.get("BENCH_HIER_PYLOPS_MPI_TPU", "")
    if hier_env != "0" and (not on_tpu or hier_env == "1"):
        _progress("hierarchical-vs-flat race (2x4 hybrid DCN bytes)")
        hier_race = _hier_race_row()

    # host-RAM spill race (round 14): oversized reshard drains through
    # host staging, overlap-on vs overlap-off, every CPU-sim round;
    # BENCH_SPILL_PYLOPS_MPI_TPU=1 forces it on hardware too
    spill_race = None
    spill_env = os.environ.get("BENCH_SPILL_PYLOPS_MPI_TPU", "")
    if spill_env != "0" and (not on_tpu or spill_env == "1"):
        _progress("spill race (host-staged oversized reshard)")
        spill_race = _spill_race_row()

    # preconditioned-solver race (preconditioner PR): ill-conditioned
    # Laplacian-regularized CGLS, bare vs block-Jacobi vs V-cycle;
    # every CPU-sim round, BENCH_PRECOND_PYLOPS_MPI_TPU=1 forces it on
    # hardware too
    precond_race = None
    precond_env = os.environ.get("BENCH_PRECOND_PYLOPS_MPI_TPU", "")
    if precond_env != "0" and (not on_tpu or precond_env == "1"):
        _progress("preconditioner race (bare vs block-Jacobi vs MG)")
        precond_race = _precond_race_row()

    # sparse-vs-dense matvec race (sparse-tier PR): 95%-sparse matrix,
    # triplet operator vs dense block operator; every CPU-sim round,
    # BENCH_SPARSE_PYLOPS_MPI_TPU=1 forces it on hardware too
    sparse_race = None
    sparse_env = os.environ.get("BENCH_SPARSE_PYLOPS_MPI_TPU", "")
    if sparse_env != "0" and (not on_tpu or sparse_env == "1"):
        _progress("sparse-vs-dense matvec race (95% sparsity)")
        sparse_race = _sparse_race_row()

    # communication-avoiding solver race (CA PR): classic vs pipelined
    # CG under an injected per-collective latency floor; every CPU-sim
    # round, BENCH_CA_PYLOPS_MPI_TPU=1 forces it on hardware too
    ca_race = None
    ca_env = os.environ.get("BENCH_CA_PYLOPS_MPI_TPU", "")
    if ca_env != "0" and (not on_tpu or ca_env == "1"):
        _progress("CA race (classic vs pipelined CG, stalled reduce)")
        ca_race = _ca_race_row()

    # gradient race (autodiff PR): implicit fixed-point gradient vs
    # the unrolled scan-tape oracle through a fused CGLS solve; every
    # CPU-sim round, BENCH_GRAD_PYLOPS_MPI_TPU=1 forces it on hardware
    grad_race = None
    grad_env = os.environ.get("BENCH_GRAD_PYLOPS_MPI_TPU", "")
    if grad_env != "0" and (not on_tpu or grad_env == "1"):
        _progress("gradient race (implicit vs unrolled d/dy of CGLS)")
        grad_race = _grad_race_row()

    # cold-start race (AOT PR): daemon prewarm wall with a cold
    # executable bank vs the same bank warm, bit-identity vs AOT=off;
    # every CPU-sim round, BENCH_COLD_START_PYLOPS_MPI_TPU=1 forces
    # it on hardware too
    cold_start = None
    cold_env = os.environ.get("BENCH_COLD_START_PYLOPS_MPI_TPU", "")
    if cold_env != "0" and (not on_tpu or cold_env == "1"):
        _progress("cold-start race (AOT bank: cold vs banked prewarm)")
        cold_start = _cold_start_row()

    peak_bf16 = _peak_flops_per_chip(jax.devices()[0], "bf16")
    peak_f32 = _peak_flops_per_chip(jax.devices()[0], "f32_highest")
    peak_hbm = _peak_hbm_gbps(jax.devices()[0]) if on_tpu else None

    def _roofline_row(row_ips, itemsize, mode_str):
        """Predicted-vs-measured roofline columns for one bench row
        (diagnostics/costmodel.py): the cost model's per-iteration
        FLOPs/HBM bytes against the per-chip peaks. On TPU the peaks
        are spec-sheet; on the CPU sim an assumed stream bandwidth
        (BENCH_CPU_GBPS, default 30 GB/s/socket, carved across the
        virtual devices) keeps the columns present and clearly
        labeled — the point of the row is attribution, not a
        benchmark of the laptop."""
        try:
            from pylops_mpi_tpu.diagnostics import costmodel
        except Exception:
            return None
        try:
            nd = max(n_dev, 1)
            sweeps = 1 if "fused-normal" in mode_str else 2
            try:  # classic CGLS pays 5 small all-reduces per iteration
                from pylops_mpi_tpu.solvers.ca import (
                    classic_reductions_per_iter)
                red_per_iter = classic_reductions_per_iter("cgls")
            except Exception:
                red_per_iter = 0.0
            cost = costmodel.OpCost(
                flops=4.0 * nblock * nblock * nblk / nd,
                hbm_bytes=sweeps * nblock * nblock * nblk * itemsize / nd,
                ici_bytes=0.0, notes=("cgls.per_iteration",),
                reductions_per_iter=red_per_iter)
            if on_tpu:
                peaks = costmodel.device_peaks(
                    jax.devices()[0],
                    mode="bf16" if itemsize == 2 else "f32_highest")
                src = "tpu_spec"
            else:
                try:
                    socket_gbps = float(os.environ.get(
                        "BENCH_CPU_GBPS", "30"))
                except ValueError:
                    socket_gbps = 30.0
                peaks = {"flops": None, "hbm_gbps": socket_gbps / nd,
                         "ici_gbps": None,
                         "allreduce_latency_s":
                             costmodel.allreduce_latency_s("host")}
                src = "assumed_cpu_stream"
            rl = costmodel.roofline(cost, peaks, n_dev=nd,
                                    measured_s=(1.0 / row_ips
                                                if row_ips else None))
            out = {"bound": rl["bound"], "peak_source": src,
                   "flops_per_iter_dev": cost.flops,
                   "hbm_bytes_per_iter_dev": cost.hbm_bytes,
                   "reductions_per_iter": cost.reductions_per_iter}
            # measured-regime re-bucket (round 10): an implied
            # bandwidth above the HBM peak means VMEM residency, never
            # ">100% of HBM" (the round-5 misattribution)
            for k in ("regime", "implied_hbm_gbps", "hbm_pct"):
                if rl.get(k) is not None:
                    out[k] = rl[k]
            if rl["predicted_s"]:
                pred_ips = 1.0 / rl["predicted_s"]
                out["predicted_iters_per_sec"] = round(pred_ips, 2)
                out["measured_iters_per_sec"] = round(row_ips, 2)
                out["measured_vs_predicted"] = _sig3(row_ips / pred_ips)
            return out
        except Exception as e:  # roofline must never kill the headline
            return {"error": repr(e)[:200]}
    f32_mfu = (_sig3(f32_gflops * 1e9 / (peak_f32 * n_dev))
               if peak_f32 else None)
    b_mfu = (_sig3(b_gflops * 1e9 / (peak_bf16 * n_dev))
             if (peak_bf16 and bf16_res is not None) else None)
    mfu = b_mfu if (primary_bf16 and bf16_res is not None) else f32_mfu
    if bf16_res is not None and b_mfu is not None:
        bf16_res["mfu"] = b_mfu  # vs the bf16 MXU peak

    def _hbm_fields(gbps, itemsize):
        """Roofline-honest HBM annotation for one TPU row: either
        ``hbm_pct`` (measured aggregate GB/s over the aggregate chip
        peak) or the on-chip-resident flag when the per-device working
        set fits VMEM — in which case the number is a cache-bandwidth
        curiosity, not an HBM measurement. CPU rows carry neither (no
        meaningful peak)."""
        if not on_tpu:
            return {}
        ws_dev = nblk * nblock * nblock * itemsize / max(n_dev, 1)
        if ws_dev <= _vmem_budget_bytes():
            return {"on_chip_resident":
                    "on-chip-resident — not an HBM measurement"}
        if peak_hbm:
            return {"hbm_pct": round(100.0 * gbps / (peak_hbm * n_dev),
                                     1)}
        return {"hbm_pct": None}  # unknown chip: no roofline claimed
    plan_prov = _plan_provenance("blockdiag")
    if bf16_res is not None:
        bf16_res["plan"] = plan_prov
        bf16_res.update(_hbm_fields(b_gbps, 2))
        rr = _roofline_row(b_ips, 2, b_mode)
        if rr:
            bf16_res["roofline"] = rr
    f32_roofline = _roofline_row(f32_ips, 4, f32_mode)
    head_roofline = (bf16_res.get("roofline")
                     if (primary_bf16 and bf16_res is not None)
                     else f32_roofline)

    result = {
        "metric": f"CGLS iters/sec (BlockDiag MatrixMult, {nblk}x{nblock}^2,"
                  f" {n_dev} dev {platform}, {mode}, fused while_loop,"
                  f" marginal per-iter timing; GEMM GFLOP/s={gflops:.0f};"
                  f" rel_err={rel_err:.1e})",
        "value": round(ips, 2),
        "unit": "iters/s",
        "vs_baseline": round(ips / cpu_ips, 2),
        "plan": plan_prov,  # tuned | costmodel | default (round 10)
        "spill": _spill_provenance(),  # auto | on | off (round 14)
        "aot": _aot_provenance(),  # off | on | on+bank (round 18)
        # resilience stamps (ISSUE 6): headline solve exit status +
        # restart count (0 = single attempt, no resilient driver)
        "status": (b_status if (primary_bf16 and bf16_res is not None)
                   else f32_status),
        "restarts": 0,
        "mfu": mfu,
        "hbm_gbps": round(gbps, 1),  # the roofline that matters: GEMV
                                     # solves are HBM-bandwidth-bound
        **_hbm_fields(gbps, 2 if (primary_bf16 and bf16_res is not None)
                      else 4),
        "platform": platform,
        "n_devices": n_dev,
        "gflops": round(gflops, 1),
        **({"roofline": head_roofline} if head_roofline else {}),
        "f32": {"iters_per_sec": round(f32_ips, 2),
                "plan": plan_prov,
                "status": f32_status, "restarts": 0,
                "gflops": round(f32_gflops, 1),
                "hbm_gbps": round(f32_gbps, 1),
                **_hbm_fields(f32_gbps, 4),
                "vs_baseline": round(f32_ips / cpu_ips, 2),
                "rel_err": f"{f32_err:.1e}",
                "mfu": f32_mfu,  # vs the f32-`highest` peak (bf16/6)
                "mode": f32_mode,
                **({"roofline": f32_roofline} if f32_roofline else {}),
                **({"race": f32_race} if f32_race else {}),
                **({"spread_pct": f32_spread}
                   if f32_spread is not None else {})},
        # provenance for cache-merge re-ranking: the peaks MFU was
        # computed against (None off-TPU)
        **({"peak_tflops": {"bf16": round(peak_bf16 / 1e12, 1),
                            "f32_highest": round(peak_f32 / 1e12, 1)}}
           if peak_bf16 else {}),
        **({"peak_hbm_gbps": {"per_chip": peak_hbm,
                              "aggregate": round(peak_hbm * n_dev, 1)}}
           if peak_hbm else {}),
        "numpy_baseline_iters_per_sec": round(cpu_ips, 2),
        **({"numpy_baseline_stats": cpu_stats} if cpu_stats else {}),
        "nblock": nblock,
        "components": components,
        **({"bf16": bf16_res} if bf16_res else {}),
        **({"bf16_race": bf16_race} if bf16_race else {}),
        **({"tune_race": tune_race} if tune_race else {}),
        **({"batched": batched} if batched else {}),
        **({"serving": serving_row} if serving_row else {}),
        **({"hierarchical_vs_flat": hier_race} if hier_race else {}),
        **({"spill_oversized": spill_race} if spill_race else {}),
        **({"precond": precond_race} if precond_race else {}),
        **({"sparse_vs_dense": sparse_race} if sparse_race else {}),
        **({"ca_vs_classic": ca_race} if ca_race else {}),
        **({"grad_race": grad_race} if grad_race else {}),
        **({"cold_start": cold_start} if cold_start else {}),
        **({"selfcheck": selfcheck} if selfcheck is not None else {}),
        **({"cpu_breakdown": cpu_breakdown} if cpu_breakdown else {}),
    }

    if run_comps and tpu_like:
        # bank the headline NOW: the supervisor salvages the last JSON
        # line on timeout, so a component hang cannot cost the number
        print(json.dumps({**result, "partial": "components pending"}),
              flush=True)
        try:  # components must never cost the already-measured headline
            from benchmarks.bench_components import (_run_one_isolated,
                                                     _BENCHES,
                                                     run_components)
            t_comp = _stage_budget("component", 150)
            isolation_dead = False
            for name, _fn in _BENCHES:
                if not isolation_dead:
                    _progress(f"component {name} (isolated)")
                    r = _run_one_isolated(name, False, t_comp)
                    err = str(r.get("error", ""))
                    # an exclusive-access runtime rejects the second
                    # process outright (fast rc!=0, not a timeout):
                    # fall back to in-process for the rest — wedge risk
                    # is acceptable now that the headline is banked
                    if err and "timeout" not in err:
                        isolation_dead = True
                    else:
                        components.append(r)
                        continue
                _progress(f"component {name} (in-process fallback)")
                components.extend(run_components(quick=False, only=name))
        except Exception as e:
            components.append({"bench": "components",
                               "error": repr(e)[:300]})
        result["components"] = components

    print(json.dumps(result))


def _run_json_cmd(cmd, env, timeout, cwd=None):
    """Run ``cmd``, parse the last JSON line of its stdout. Returns
    ``(parsed-json, error-string)`` — exactly one of the two is None.
    Shared by this driver and the probe daemon
    (benchmarks/tpu_probe_loop.py) so the subtle timeout/parse handling
    has a single implementation."""
    try:
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=timeout, cwd=cwd)
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr.decode("utf-8", "replace")[-1500:]
                if isinstance(e.stderr, bytes) else str(e.stderr)[-1500:])
        # salvage: the bench child prints a headline-only JSON line
        # BEFORE the component sweep — a timeout mid-components must
        # not discard an already-measured headline
        out = (e.stdout.decode("utf-8", "replace")
               if isinstance(e.stdout, bytes) else (e.stdout or ""))
        for line in reversed(out.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    got = json.loads(line)
                    got["salvaged_after_timeout"] = timeout
                    return got, None
                except json.JSONDecodeError:
                    continue
        return None, f"timeout after {timeout}s; stderr tail: {tail}"
    except Exception as e:  # spawn failure itself must not crash parent
        return None, f"spawn failed: {e!r}"
    for line in reversed((p.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"rc={p.returncode}; stderr tail: {(p.stderr or '')[-1500:]}"


def _run_child(env, timeout):
    """Run this file with --child; return (parsed-json, error-string)."""
    return _run_json_cmd([sys.executable, os.path.abspath(__file__),
                          _CHILD_FLAG], env, timeout)


def _tpu_probe(timeout: int):
    """Cheap liveness check: init whatever backend is default in a
    disposable child. A dead TPU tunnel hangs/errors here in
    ``timeout`` seconds instead of consuming the full measurement
    budget; the healthy path pays one duplicated backend init (tens of
    seconds, small against the 1800 s budget it protects). Returns
    ``(status, detail)``: status is the backend name ("tpu"/"cpu"/...)
    on success or "dead" with the child's stderr tail, so the real init
    error (lock, dead tunnel, plugin misconfig) stays visible.

    ``PYLOPS_MPI_TPU_TEST_FORCE_PROBE`` (deliberately verbose name — a
    stray export must not defeat the dead-tunnel guard) pins the probed
    backend so tests can exercise callers' control flow without a
    minutes-long hang against a dead tunnel."""
    forced = os.environ.get("PYLOPS_MPI_TPU_TEST_FORCE_PROBE")
    if forced:
        code = (f"import jax; jax.config.update('jax_platforms', "
                f"'{forced}'); print(jax.default_backend())")
    else:
        code = "import jax; print(jax.default_backend())"
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           env=dict(os.environ), capture_output=True,
                           text=True, timeout=timeout)
        if p.returncode == 0:
            return (p.stdout or "").strip().splitlines()[-1], ""
        return "dead", (p.stderr or "")[-600:]
    except subprocess.TimeoutExpired:
        return "dead", f"probe hung (> {timeout}s)"
    except Exception as e:
        return "dead", repr(e)[:300]


# the rev key must change when CODE changes, not when artifacts do:
# keying on HEAD would invalidate banked 40-minute stages every time
# log/cache files, docs, or regenerated benchmark artifacts (e.g.
# benchmarks/rehearsal_r04.json) get committed. benchmarks/ holds both
# code and artifacts, so only its *.py files count. Shared with the
# probe daemon.
_CODE_PATHS = ["pylops_mpi_tpu", "bench.py", "__graft_entry__.py",
               ":(glob)benchmarks/*.py"]


def _current_code_rev() -> str:
    try:
        import hashlib
        root = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for p in ("pylops_mpi_tpu", "bench.py", "__graft_entry__.py"):
            r = subprocess.run(["git", "rev-parse", f"HEAD:{p}"],
                               capture_output=True, text=True, cwd=root,
                               timeout=10)
            h.update((r.stdout.strip() if r.returncode == 0
                      else "none").encode())
        bl = subprocess.run(
            ["git", "ls-tree", "HEAD", "benchmarks/"],
            capture_output=True, text=True, cwd=root, timeout=10).stdout
        for line in sorted(l for l in bl.splitlines()
                           if l.endswith(".py")):
            h.update(line.encode())
        d = subprocess.run(["git", "status", "--porcelain", "--"]
                           + _CODE_PATHS,
                           capture_output=True, text=True, cwd=root,
                           timeout=10).stdout.strip()
        return h.hexdigest()[:16] + ("+dirty" if d else "")
    except Exception:
        return "unknown"


def _probe_log_summary(root=None):
    """Summarize tpu_probe_log.jsonl (written by
    benchmarks/tpu_probe_loop.py all round): attempt counts per status
    + time span, proving how persistently the flaky tunnel was tried
    even when no window ever opened."""
    path = os.path.join(root or os.path.dirname(os.path.abspath(__file__)),
                        "tpu_probe_log.jsonl")
    try:
        statuses, first_ts, last_ts, stages = {}, None, None, []
        with open(path, errors="replace") as f:
            for line in f:
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    continue
                s = e.get("status", "?")
                if s == "stage":
                    stages.append({k: e.get(k) for k in
                                   ("ts", "stage", "ok", "seconds",
                                    "error") if k in e})
                    continue
                if s in ("daemon_start", "daemon_deadline", "complete"):
                    continue
                statuses[s] = statuses.get(s, 0) + 1
                first_ts = first_ts or e.get("ts")
                last_ts = e.get("ts") or last_ts
        if not statuses and not stages:
            return None
        return {"attempts": sum(statuses.values()), "statuses": statuses,
                "first_ts": first_ts, "last_ts": last_ts,
                "stages": stages[-10:]}
    except Exception:  # a corrupt log must never zero out the result
        return None


def _merge_tpu_cache(result, root=None):
    """If the live run degraded to CPU but the probe daemon harvested a
    TPU window earlier in the round, promote the cached TPU flagship to
    the primary result (full > small), keeping the live CPU numbers
    under ``cpu_live``. Always attaches the probe-log summary and any
    cached selfcheck."""
    root = root or os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(root, "tpu_cache.json")) as f:
            cache = json.load(f)
    except Exception:
        cache = {}
    summary = _probe_log_summary(root)

    if result.get("platform") != "tpu":
        for key in ("flagship_full", "flagship_mid", "flagship_small"):
            ent = cache.get(key) or {}
            r = ent.get("result")
            if r and r.get("platform") == "tpu" and not ent.get("error"):
                cpu_live = {k: result.get(k) for k in
                            ("metric", "value", "vs_baseline", "platform",
                             "degraded", "tpu_error", "components",
                             "cpu_breakdown", "flagship_1dev_cpu",
                             "roofline", "f32", "bf16", "plan",
                             "spill", "tune_race", "batched", "serving",
                             "hierarchical_vs_flat", "spill_oversized",
                             "precond", "sparse_vs_dense",
                             "ca_vs_classic", "grad_race",
                             "cold_start", "aot")
                            if k in result}
                result = dict(r)
                result["cached"] = True
                result["cache_stage"] = key
                result["cache_ts"] = ent.get("ts")
                result["cpu_live"] = cpu_live
                # the tuner race is a live CPU-sim measurement: it must
                # ride the compact line EVERY round, banked headline or
                # not (round 10); a legacy banked artifact without a
                # plan= column stays honest via "default"
                if cpu_live.get("tune_race") is not None:
                    result["tune_race"] = cpu_live["tune_race"]
                # same rule for the batched-throughput race: a live
                # CPU-sim number that must not vanish behind a banked
                # TPU headline
                if cpu_live.get("batched") is not None:
                    result["batched"] = cpu_live["batched"]
                # and the serving race: live daemon throughput +
                # time-in-queue that rides every compact line
                if cpu_live.get("serving") is not None:
                    result["serving"] = cpu_live["serving"]
                # and the hierarchical DCN-byte race: a live CPU-sim
                # attribution that must ride every compact line
                if cpu_live.get("hierarchical_vs_flat") is not None:
                    result["hierarchical_vs_flat"] = \
                        cpu_live["hierarchical_vs_flat"]
                # and the host-RAM spill race: live CPU-sim evidence
                # that oversized moves drain bit-identically (round 14)
                if cpu_live.get("spill_oversized") is not None:
                    result["spill_oversized"] = \
                        cpu_live["spill_oversized"]
                # and the preconditioner + sparse-tier races: live
                # CPU-sim iterations-to-tol / byte-ratio evidence that
                # rides every compact line
                if cpu_live.get("precond") is not None:
                    result["precond"] = cpu_live["precond"]
                if cpu_live.get("sparse_vs_dense") is not None:
                    result["sparse_vs_dense"] = \
                        cpu_live["sparse_vs_dense"]
                # and the communication-avoiding race: live CPU-sim
                # wall-speedup + HLO-pinned reduction counts that ride
                # every compact line (round 17)
                if cpu_live.get("ca_vs_classic") is not None:
                    result["ca_vs_classic"] = \
                        cpu_live["ca_vs_classic"]
                # and the gradient race: live CPU-sim implicit-vs-
                # unrolled wall/memory evidence that rides every
                # compact line (autodiff PR)
                if cpu_live.get("grad_race") is not None:
                    result["grad_race"] = cpu_live["grad_race"]
                # and the cold-start race: live CPU-sim prewarm walls
                # (cold vs banked AOT executable bank) that ride every
                # compact line (round 18)
                if cpu_live.get("cold_start") is not None:
                    result["cold_start"] = cpu_live["cold_start"]
                if cpu_live.get("aot") is not None:
                    result["aot"] = cpu_live["aot"]
                result.setdefault("plan", "default")
                # a legacy banked artifact predating the AOT tier ran
                # the pre-round-18 always-jit path
                result.setdefault("aot", "off")
                # a legacy banked artifact predating the spill tier ran
                # under the round-13 refusal semantics
                result.setdefault("spill", "off")
                # every TPU row carries an HBM qualifier; a legacy
                # banked artifact predating the hbm_pct schema gets an
                # explicit marker instead of silently claiming nothing
                if ("hbm_pct" not in result
                        and "on_chip_resident" not in result):
                    result["hbm_note"] = ("legacy artifact: hbm_gbps "
                                          "recorded without a peak "
                                          "(pre-hbm_pct schema)")
                # headline policy (round 4): f32 primary. A cache entry
                # banked under the old bf16-primary policy carries the
                # f32 numbers alongside — re-rank instead of re-running
                f32 = result.get("f32") or {}
                if (f32.get("iters_per_sec") is not None
                        and "f32" not in str(result.get("metric", ""))
                        and "bf16" in str(result.get("metric", ""))):
                    result["bf16"] = {
                        "iters_per_sec": result.get("value"),
                        "rel_err": (result.get("metric", "").split(
                            "rel_err=")[-1].rstrip(")")
                            if "rel_err=" in result.get("metric", "")
                            else None),
                        "mode": "bf16 (was primary when banked)"}
                    old_gflops = result.get("gflops")
                    old_mfu = result.get("mfu")
                    result["value"] = f32["iters_per_sec"]
                    result["vs_baseline"] = f32.get("vs_baseline")
                    result["hbm_gbps"] = f32.get("hbm_gbps")
                    result["gflops"] = f32.get("gflops")
                    # mfu must describe f32's throughput vs the f32
                    # peak, never pair f32 GFLOP/s with bf16's ceiling.
                    # Preference order: the banked per-mode value (new
                    # artifacts), exact recompute from banked peaks,
                    # then rescale of the old top-level number — and an
                    # `is not None` guard throughout: a tiny true MFU
                    # (3e-5 at GEMV sizes) is data, not falsy-missing
                    # (round-4 VERDICT weak #3)
                    peaks = result.get("peak_tflops") or {}
                    if f32.get("mfu") is not None:
                        result["mfu"] = f32["mfu"]
                    elif (peaks.get("f32_highest") and f32.get("gflops")
                          and result.get("n_devices")):
                        result["mfu"] = _sig3(
                            f32["gflops"] / (peaks["f32_highest"] * 1e3
                                             * result["n_devices"]))
                    elif (old_mfu and old_gflops and f32.get("gflops")):
                        # legacy artifact: old_mfu was vs the bf16 peak;
                        # f32-highest peak is bf16/6. A banked 0.0 is
                        # the round-4 rounding casualty, not a
                        # measurement — fall through to null rather
                        # than resurrect it as a fake zero
                        result["mfu"] = _sig3(
                            6.0 * old_mfu * f32["gflops"] / old_gflops)
                    else:
                        result["mfu"] = None
                    # REWRITE the label: the old string names bf16's
                    # mode and rel_err, which no longer describe the
                    # promoted numbers
                    base = result.get("metric", "").split("(")[0].strip()
                    result["metric"] = (
                        f"{base} (cached {key}, f32 two-sweep promoted "
                        f"to primary per round-4 policy"
                        + (f"; rel_err={f32['rel_err']}"
                           if f32.get("rel_err") else "") + ")")
                break
    if "selfcheck" not in result:
        ent = cache.get("selfcheck") or {}
        r = ent.get("result")
        # only a selfcheck that actually ran on TPU counts as hardware
        # kernel validation — a tunnel drop makes the child silently
        # fall back to CPU interpret mode, which proves nothing.
        # A result harvested from OLDER code is still evidence but must
        # not read as a verdict on the current kernels (round-3 weak #5:
        # the wedge-poisoned selfcheck sat in the cache keyed to an old
        # rev) — mark it stale so nothing downstream gates on it.
        if r and r.get("platform") == "tpu":
            result["selfcheck"] = {**r, "cached": True,
                                   "code_rev": ent.get("code_rev")}
            if ent.get("code_rev") != _current_code_rev():
                result["selfcheck"]["stale"] = True
    ent = cache.get("breakdown") or {}
    r = ent.get("result")
    if r and r.get("platform") == "tpu" and "tpu_breakdown" not in result:
        result["tpu_breakdown"] = {**r, "cached": True,
                                   "ts": ent.get("ts")}
    for stage, out_key in (("bisect", "tpu_bisect"),
                           ("fft_planar", "tpu_fft_planar")):
        ent = cache.get(stage) or {}
        r = ent.get("result")
        if not (r and isinstance(r.get("results"), dict)):
            continue
        probes = r["results"]
        plats = {v.get("platform") for v in probes.values()
                 if isinstance(v, dict)} - {None}
        # same hardware-evidence rule as the selfcheck/diag merges: a
        # rehearsal bisect (cpu children) proves nothing about the
        # chip. An EMPTY platform set is NOT the same thing: a probe
        # only tags its platform on success, so a hardware window in
        # which every probe died (round 5: the whole complex-FFT
        # family UNIMPLEMENTED) emits no tags at all — that all-fail
        # outcome IS the round's evidence. Accept it whenever the
        # harvest wasn't a rehearsal (the daemon stamps those).
        if plats == {"tpu"} or (not plats and not ent.get("rehearse")):
            result[out_key] = {
                "ts": ent.get("ts"), "code_rev": ent.get("code_rev"),
                **({"platform": "tpu"} if plats == {"tpu"}
                   else {"all_probes_failed": True}),
                "probes": {k: {"ok": v.get("ok"),
                               **({"error": v.get("error")}
                                  if v.get("error") else {})}
                           for k, v in probes.items()
                           if isinstance(v, dict)}}
    ent = cache.get("overlap") or {}
    r = ent.get("result")
    # overlap-race stage (round 8): hardware evidence only — the CPU
    # rows are banked by the live components sweep anyway, and a
    # rehearsal must never read as an ICI measurement
    if (r and isinstance(r.get("rows"), list)
            and r.get("platform") == "tpu" and "tpu_overlap" not in result):
        result["tpu_overlap"] = {
            "ts": ent.get("ts"), "code_rev": ent.get("code_rev"),
            "rows": [{k: row.get(k) for k in
                      ("bench", "value", "pipelined_vs_bulk", "schedule",
                       "stat_a_pipelined_vs_bulk", "ring_steps",
                       "ici_bytes_per_step", "comm_chunks", "a2a_count",
                       "ici_bytes_per_chunk", "shape", "error")
                      if row.get(k) is not None}
                     for row in r["rows"] if isinstance(row, dict)]}
    ent = cache.get("hier") or {}
    r = ent.get("result")
    # hierarchical-race stage (round 11): hardware evidence only — the
    # CPU-sim DCN-byte attribution rides the live row every round; a
    # TPU harvest adds the wall-clock side the sim cannot measure
    # (both fabrics are the same silicon there)
    if (r and r.get("platform") == "tpu" and "tpu_hier" not in result):
        result["tpu_hier"] = {
            "ts": ent.get("ts"), "code_rev": ent.get("code_rev"),
            **{k: r.get(k) for k in
               ("fabric", "pencil", "summa", "worst_dcn_reduction",
                "error")
               if r.get(k) is not None}}
    ent = cache.get("diag") or {}
    r = ent.get("result")
    # same hardware-evidence rule as the selfcheck merge above: a diag
    # run whose own backend report isn't "tpu" proves nothing
    if r and r.get("steps") and r.get("platform") == "tpu":
        # compact per-step verdicts from the on-hardware piecewise
        # diagnosis (benchmarks/tpu_diag.py)
        result["tpu_diag"] = {
            "ts": ent.get("ts"), "code_rev": ent.get("code_rev"),
            "steps": [{"step": s.get("step"), "ok": s.get("ok"),
                       **({"err": s.get("err")} if s.get("err") else {})}
                      for s in r["steps"] if "step" in s]}
        # the bf16-race attribution rides into the banked artifact IN
        # FULL: normal_matvec_perf_us times one sweep of each
        # (two_sweep|pallas_normal) × (f32|bf16) formulation at the
        # same shape — the recorded cause for a bf16_race anomaly like
        # round 5's 40× two-sweep cliff (previously the diag measured
        # it but the artifact dropped the numbers)
        for s in r["steps"]:
            if (s.get("step") == "normal_matvec_perf_us" and s.get("ok")
                    and s.get("out")):
                result["tpu_diag"]["bf16_attribution"] = {
                    "sweep_us": s["out"],
                    "note": ("per-variant µs for one sweep at the diag "
                             "shape; two_sweep_bf16 ≫ two_sweep_f32 "
                             "attributes a bf16_race cliff to the XLA "
                             "two-sweep lowering, not the Pallas "
                             "kernel")}
                break
    if summary:
        result["probe_log"] = summary
    return result


# --------------------------------------------------- regression sentinel
# ISSUE 10: compare a fresh artifact against the banked BENCH_r*.json
# history so a perf regression fails loudly in CI instead of silently
# shipping a slower flagship row.  History rows mix platforms and
# shapes (r02 is an 8-dev CPU run, r04/r05 are 1-dev TPU), so rows are
# bucketed by (platform, n_devices, nblock) and the fresh value is
# compared against the MEDIAN of its own bucket — robust to one
# anomalous round in the bank.

_SENTINEL_FLAG = "--sentinel"
_SENTINEL_ARTIFACT_FLAG = "--sentinel-artifact"
_SENTINEL_TOL_FLAG = "--sentinel-tol"
# module state so _emit_final can stamp the verdict onto the one
# compact stdout line without threading a parameter through main()
_SENTINEL_STATE = {"enabled": False, "tolerance": None, "verdict": None}


def _sentinel_tolerance(explicit=None):
    """Relative slowdown tolerated before the sentinel trips: the
    ``--sentinel-tol`` flag, else ``BENCH_SENTINEL_TOL``, else 0.15
    (the ISSUE 10 acceptance threshold). Clamped to [0, 1)."""
    v = explicit
    if v is None:
        try:
            v = float(os.environ.get("BENCH_SENTINEL_TOL", "0.15"))
        except ValueError:
            v = 0.15
    return min(0.999, max(0.0, float(v)))


def _load_bench_history(root=None):
    """Parsed rows from the banked ``BENCH_r*.json`` files next to this
    script, round order, skipping rounds whose ``parsed`` is null or
    garbage (r01/r03 in the current bank). Every failure mode is a
    skipped row, never an exception — the sentinel degrades to
    ``no-history`` rather than taking the bench down."""
    import glob
    root = root or os.path.dirname(os.path.abspath(__file__))
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        v = parsed.get("value")
        if not isinstance(v, (int, float)) or v <= 0:
            continue
        parsed = dict(parsed)
        parsed["_source"] = os.path.basename(path)
        rows.append(parsed)
    return rows


def _norm_metric(metric):
    """Metric string reduced to its measurement identity: per-run
    numeric annotations (``rel_err=2.6e-07``, ``GEMM GFLOP/s=631``)
    stripped, punctuation/case collapsed. What remains describes WHAT
    was measured — e.g. r04's 'cached flagship_small ... promoted to
    primary' vs r05's '... marginal per-iter timing' are different
    methodologies at the same (tpu, 1 dev, nblock=1024) topology, 100x
    apart, and must never share a baseline."""
    import re
    m = re.sub(r"[\w ./]+=\s*[-+0-9.eE]+", "", str(metric or ""))
    return re.sub(r"[^a-z0-9]+", " ", m.lower()).strip()


def _sentinel_bucket(row):
    """Comparability key: rows from different platforms/topologies,
    flagship shapes or timing methodologies must never be compared (a
    1-dev TPU round at 150k iters/s would flag every CPU round as a
    99% regression)."""
    return (_norm_metric(row.get("metric")), row.get("platform"),
            row.get("n_devices"), row.get("nblock"))


def _sentinel_check(result, history, tolerance=0.15):
    """Verdict dict for ``result`` against ``history``. ``regressed``
    is True when the fresh value is below ``median(bucket) x
    (1 - tolerance)``; an empty bucket is ``status="no-history"`` and
    never trips (first round on a new topology must pass)."""
    import statistics
    bucket = _sentinel_bucket(result)
    rows = [h for h in history if _sentinel_bucket(h) == bucket]
    verdict = {
        "tolerance": tolerance,
        "bucket": {"metric": bucket[0][:80], "platform": bucket[1],
                   "n_devices": bucket[2], "nblock": bucket[3]},
        "n_history": len(rows),
        "history": [{"source": h.get("_source"), "value": h.get("value")}
                    for h in rows],
    }
    fresh = result.get("value")
    if not rows:
        verdict.update(status="no-history", regressed=False)
        return verdict
    baseline = statistics.median(h["value"] for h in rows)
    verdict["baseline"] = round(baseline, 4)
    if not isinstance(fresh, (int, float)) or fresh <= 0:
        # a dead/valueless fresh run against real history IS a
        # regression — this is exactly the failure CI must catch
        verdict.update(fresh=fresh, status="no-value", regressed=True)
        return verdict
    ratio = fresh / baseline
    regressed = fresh < baseline * (1.0 - tolerance)
    verdict.update(fresh=round(float(fresh), 4), ratio=round(ratio, 4),
                   status="regressed" if regressed else "ok",
                   regressed=regressed)

    # serving-throughput sub-verdict (serving PR): the packed daemon's
    # solves/sec rides the same bucketed-median rule. Rounds banked
    # before the serving row existed carry no number, so the sub-check
    # silently stands down until history accrues — it can only trip
    # against rounds that actually measured the daemon.
    def _srv_rate(row):
        s = row.get("serving") or {}
        v = s.get("solves_per_sec")
        return float(v) if isinstance(v, (int, float)) and v > 0 else None
    fresh_srv = _srv_rate(result)
    hist_srv = [v for v in (_srv_rate(h) for h in rows) if v is not None]
    if fresh_srv is not None and hist_srv:
        base = statistics.median(hist_srv)
        srv_reg = fresh_srv < base * (1.0 - tolerance)
        verdict["serving"] = {"fresh": round(fresh_srv, 4),
                              "baseline": round(base, 4),
                              "ratio": round(fresh_srv / base, 4),
                              "regressed": srv_reg}
        if srv_reg:
            verdict.update(status="regressed", regressed=True)

    # CA-solver sub-verdict (CA PR): the pipelined engine's
    # latency-stalled solves/sec rides the same bucketed-median rule
    # — the wall win the ca_vs_classic row measures must survive, not
    # just exist once. Same stand-down rule as serving: no history
    # with the number, no verdict.
    def _ca_rate(row):
        c = row.get("ca_vs_classic") or {}
        v = c.get("solves_per_sec")
        return float(v) if isinstance(v, (int, float)) and v > 0 else None
    fresh_ca = _ca_rate(result)
    hist_ca = [v for v in (_ca_rate(h) for h in rows) if v is not None]
    if fresh_ca is not None and hist_ca:
        base = statistics.median(hist_ca)
        ca_reg = fresh_ca < base * (1.0 - tolerance)
        verdict["ca"] = {"fresh": round(fresh_ca, 4),
                         "baseline": round(base, 4),
                         "ratio": round(fresh_ca / base, 4),
                         "regressed": ca_reg}
        if ca_reg:
            verdict.update(status="regressed", regressed=True)

    # gradient sub-verdict (autodiff PR): the implicit rule's
    # grads/sec rides the same bucketed-median rule — the one-extra-
    # solve backward pass must stay a throughput win over history,
    # not just beat the unrolled tape once. Same stand-down rule:
    # rounds banked before the row existed carry no number.
    def _grad_rate(row):
        g = row.get("grad_race") or {}
        v = g.get("grads_per_sec")
        return float(v) if isinstance(v, (int, float)) and v > 0 else None
    fresh_gr = _grad_rate(result)
    hist_gr = [v for v in (_grad_rate(h) for h in rows) if v is not None]
    if fresh_gr is not None and hist_gr:
        base = statistics.median(hist_gr)
        gr_reg = fresh_gr < base * (1.0 - tolerance)
        verdict["grad"] = {"fresh": round(fresh_gr, 4),
                           "baseline": round(base, 4),
                           "ratio": round(fresh_gr / base, 4),
                           "regressed": gr_reg}
        if gr_reg:
            verdict.update(status="regressed", regressed=True)

    # cold-start sub-verdict (AOT PR): banked prewarm SECONDS ride the
    # bucketed-median rule INVERTED — lower is better, so this trips
    # when a fresh banked prewarm runs SLOWER than median × (1 + tol).
    # Deserialize wall is millisecond-scale and jittery on a shared CI
    # host, so the tolerance floors at 50% — the verdict exists to
    # catch the bank silently degrading to recompile (a ~20×
    # blow-up), not to police scheduler noise. Same stand-down rule as
    # serving: rounds banked before the row existed carry no number,
    # so no verdict until history accrues.
    def _cold_secs(row):
        c = row.get("cold_start") or {}
        v = c.get("banked_prewarm_s")
        return float(v) if isinstance(v, (int, float)) and v > 0 else None
    fresh_cold = _cold_secs(result)
    hist_cold = [v for v in (_cold_secs(h) for h in rows)
                 if v is not None]
    if fresh_cold is not None and hist_cold:
        base = statistics.median(hist_cold)
        cs_tol = max(tolerance, 0.5)
        cs_reg = fresh_cold > base * (1.0 + cs_tol)
        verdict["cold_start"] = {"fresh": round(fresh_cold, 4),
                                 "baseline": round(base, 4),
                                 "ratio": round(fresh_cold / base, 4),
                                 "tolerance": cs_tol,
                                 "regressed": cs_reg}
        if cs_reg:
            verdict.update(status="regressed", regressed=True)
    return verdict


def _sentinel_artifact_main(path, tolerance):
    """``--sentinel-artifact PATH``: judge an EXISTING artifact (full
    ``bench_detail.json`` or one compact line — both carry value/
    platform/n_devices/nblock at top level) without running the bench.
    Prints the verdict as the last stdout line; exit 1 on regression.
    This is the fast path for tests and for re-judging a banked run."""
    try:
        with open(path) as f:
            result = json.load(f)
    except (OSError, ValueError) as e:
        print(json.dumps({"sentinel": {"status": "unreadable-artifact",
                                       "error": repr(e)[:200],
                                       "regressed": True},
                          "regressed": True}))
        return 1
    verdict = _sentinel_check(result, _load_bench_history(), tolerance)
    print(json.dumps({"sentinel": verdict,
                      "regressed": verdict["regressed"]}))
    return 1 if verdict["regressed"] else 0


def _emit_final(result):
    """Write the FULL artifact to ``bench_detail.json`` and print a
    compact (≤2 KB) summary as the LAST stdout line. Round-3 failure
    being fixed: the driver records only a stdout tail, and the full
    JSON (components + probe log + selfcheck) overflowed it, leaving
    ``BENCH_r03.json`` with ``"parsed": null``."""
    if _SENTINEL_STATE["enabled"]:
        verdict = _sentinel_check(result, _load_bench_history(),
                                  _SENTINEL_STATE["tolerance"])
        _SENTINEL_STATE["verdict"] = verdict
        result["sentinel"] = verdict
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        with open(os.path.join(root, "bench_detail.json"), "w") as f:
            json.dump(result, f, indent=1)
    except Exception:
        pass  # detail file is best-effort; the summary line is not
    print(json.dumps(_compact_line(result)))


def _compact_line(result):
    """The ≤2 KB summary dict for one stdout line (shared by the final
    emit and the pre-1-dev-child partial banking in main())."""
    sc = result.get("selfcheck") or {}
    checks = sc.get("checks") or {}
    comps = [c for c in (result.get("components") or [])
             if isinstance(c, dict)]
    bd = result.get("tpu_breakdown") or {}
    probe = result.get("probe_log") or {}
    compact = {
        "metric": result.get("metric", ""),
        "value": result.get("value"),
        "unit": result.get("unit"),
        "vs_baseline": result.get("vs_baseline"),
        "mfu": result.get("mfu"),
        "hbm_gbps": result.get("hbm_gbps"),
        "gflops": result.get("gflops"),
        "platform": result.get("platform"),
        "n_devices": result.get("n_devices"),
        "nblock": result.get("nblock"),
        "numpy_baseline_iters_per_sec":
            result.get("numpy_baseline_iters_per_sec"),
        # resilience stamps (ISSUE 6) ride every compact line
        "status": result.get("status"),
        "restarts": result.get("restarts"),
        "detail_file": "bench_detail.json",
    }
    for k in ("degraded", "cached", "cache_stage", "partial",
              "salvaged_after_timeout", "hbm_pct", "on_chip_resident",
              "hbm_note"):
        if result.get(k) is not None and result.get(k) is not False:
            compact[k] = result[k]
    if "f32" in result:
        compact["f32"] = {k: result["f32"].get(k) for k in
                          ("iters_per_sec", "vs_baseline", "hbm_gbps",
                           "hbm_pct", "on_chip_resident", "status")
                          if result["f32"].get(k) is not None}
    if result.get("bf16"):
        compact["bf16"] = {k: result["bf16"].get(k) for k in
                           ("iters_per_sec", "rel_err", "mode", "vs_f32",
                            "hbm_pct", "on_chip_resident", "status")
                           if result["bf16"].get(k) is not None}
    if result.get("bf16_race"):
        compact["bf16_race"] = result["bf16_race"]
    if result.get("plan"):
        compact["plan"] = result["plan"]
    if result.get("spill"):
        compact["spill"] = result["spill"]
    if result.get("aot"):
        compact["aot"] = result["aot"]
    sr = result.get("spill_oversized") or {}
    if sr and not sr.get("error"):
        compact["spill_oversized"] = {
            k: sr.get(k) for k in
            ("off_refuses", "bit_identical_vs_oracle",
             "bytes_accounting_ok", "cost_model_under_budget",
             "overlap_speedup")
            if sr.get(k) is not None}
    elif sr.get("error"):
        compact["spill_oversized"] = {"error": sr["error"][:120]}
    bt = result.get("batched") or {}
    if bt and not bt.get("error"):
        compact["batched"] = {
            k: bt.get(k) for k in
            ([f"solves_per_sec@{bt.get('K')}", "speedup_vs_sequential",
              "batch_plan", "K"])
            if bt.get(k) is not None}
    elif bt.get("error"):
        compact["batched"] = {"error": bt["error"][:120]}
    srv = result.get("serving") or {}
    if srv and not srv.get("error"):
        compact["serving"] = {
            k: srv.get(k) for k in
            ("solves_per_sec", "speedup_vs_sequential",
             "wait_p50_s", "wait_p99_s", "K")
            if srv.get(k) is not None}
    elif srv.get("error"):
        compact["serving"] = {"error": srv["error"][:120]}
    tr = result.get("tune_race") or {}
    if tr and not tr.get("error"):
        compact["tune_race"] = {
            k: tr.get(k) for k in
            ("worst_tuned_vs_default", "best_tuned_vs_costmodel")
            if tr.get(k) is not None}
    elif tr.get("error"):
        compact["tune_race"] = {"error": tr["error"][:120]}
    hr = result.get("hierarchical_vs_flat") or {}
    if hr and not hr.get("error"):
        compact["hier"] = {k: v for k, v in (
            ("pencil_dcn_reduction",
             (hr.get("pencil") or {}).get("dcn_reduction")),
            ("summa_dcn_reduction",
             (hr.get("summa") or {}).get("dcn_reduction")),
            ("worst_dcn_reduction", hr.get("worst_dcn_reduction")),
        ) if v is not None}
    elif hr.get("error"):
        compact["hier"] = {"error": hr["error"][:120]}
    pr = result.get("precond") or {}
    if pr and not pr.get("error"):
        compact["precond"] = {k: v for k, v in (
            ("bare_iters", (pr.get("unpreconditioned") or {})
             .get("iters")),
            ("bj_iters", (pr.get("block_jacobi") or {}).get("iters")),
            ("vc_iters", (pr.get("vcycle") or {}).get("iters")),
            ("bj_iters_ratio", pr.get("bj_iters_ratio")),
            ("vc_iters_ratio", pr.get("vc_iters_ratio")),
            ("bj_wall_speedup", pr.get("bj_wall_speedup")),
            ("vc_wall_speedup", pr.get("vc_wall_speedup")),
        ) if v is not None}
    elif pr.get("error"):
        compact["precond"] = {"error": pr["error"][:120]}
    sv = result.get("sparse_vs_dense") or {}
    if sv and not sv.get("error"):
        compact["sparse_vs_dense"] = {
            k: sv.get(k) for k in
            ("density", "sparse_vs_dense_wall", "bytes_ratio",
             "max_abs_diff") if sv.get(k) is not None}
    elif sv.get("error"):
        compact["sparse_vs_dense"] = {"error": sv["error"][:120]}
    car = result.get("ca_vs_classic") or {}
    if car and not car.get("error"):
        compact["ca"] = {k: v for k, v in (
            ("classic_iters", (car.get("classic") or {}).get("iters")),
            ("pipelined_iters",
             (car.get("pipelined") or {}).get("iters")),
            ("reductions", car.get("reductions_per_iter")),
            ("iters_parity", car.get("iters_parity")),
            ("wall_speedup", car.get("wall_speedup")),
        ) if v is not None}
    elif car.get("error"):
        compact["ca"] = {"error": car["error"][:120]}
    gr = result.get("grad_race") or {}
    if gr and not gr.get("error"):
        compact["grad"] = {k: v for k, v in (
            ("wall_speedup", gr.get("wall_speedup")),
            ("temp_bytes_ratio", gr.get("temp_bytes_ratio")),
            ("max_rel_diff", gr.get("max_rel_diff")),
            ("grads_match", gr.get("grads_match")),
            ("grads_per_sec", gr.get("grads_per_sec")),
        ) if v is not None}
    elif gr.get("error"):
        compact["grad"] = {"error": gr["error"][:120]}
    cs = result.get("cold_start") or {}
    if cs and not cs.get("error"):
        compact["cold_start"] = {
            k: cs.get(k) for k in
            ("cold_prewarm_s", "banked_prewarm_s", "speedup",
             "meets_bar", "zero_compile_replay", "max_abs_diff_vs_off")
            if cs.get(k) is not None}
    elif cs.get("error"):
        compact["cold_start"] = {"error": cs["error"][:120]}
    rl = result.get("roofline") or {}
    if rl and not rl.get("error"):
        compact["roofline"] = {
            k: rl.get(k) for k in
            ("bound", "predicted_iters_per_sec", "measured_vs_predicted",
             "peak_source") if rl.get(k) is not None}
    if result.get("flagship_1dev_cpu"):
        f1 = result["flagship_1dev_cpu"]
        compact["flagship_1dev_cpu"] = (
            {"error": f1["error"]} if f1.get("error") else
            {k: f1.get(k) for k in ("value", "vs_baseline",
                                    "numpy_baseline_iters_per_sec")})
    if sc:
        n_ok = sum(1 for v in checks.values()
                   if isinstance(v, dict) and v.get("ok"))
        compact["selfcheck"] = {
            "platform": sc.get("platform"), "ok": n_ok,
            "total": len(checks) or None,
            **({"stale": True} if sc.get("stale") else {}),
            **({"cached": True} if sc.get("cached") else {})}
    if comps:
        failed = [c.get("bench") for c in comps if c.get("error")]
        compact["components"] = {"n": len(comps),
                                 **({"failed": failed} if failed else {})}
    if bd:
        nf = bd.get("niter_fit") or {}
        compact["tpu_breakdown"] = {
            "per_iter_ms": nf.get("per_iter_ms"),
            "fixed_ms": nf.get("fixed_ms"),
            "sweep_ms": bd.get("sweep_ms"),
            "vs_sweep": bd.get("while_loop_marginal_vs_sweep"),
            "reduction_ms": bd.get("reduction_overhead_per_iter_ms"),
            "dispatch_ms": bd.get("dispatch_ms")}
    ov = result.get("tpu_overlap") or {}
    if ov:
        compact["overlap"] = {
            row.get("bench"): row.get("pipelined_vs_bulk")
            for row in ov.get("rows", []) if isinstance(row, dict)}
    th = result.get("tpu_hier") or {}
    if th:
        compact["tpu_hier"] = {
            k: th.get(k) for k in
            ("worst_dcn_reduction",) if th.get(k) is not None}
        ptime = (th.get("pencil") or {}).get("time_hier_vs_flat")
        if ptime is not None:
            compact["tpu_hier"]["pencil_time_hier_vs_flat"] = ptime
    fp = result.get("tpu_fft_planar") or {}
    if fp:
        pr = fp.get("probes") or {}
        compact["fft_planar"] = {
            "ok": sum(1 for v in pr.values()
                      if isinstance(v, dict) and v.get("ok")),
            "total": len(pr) or None,
            **({"all_failed": True} if fp.get("all_probes_failed")
               else {})}
    if probe:
        compact["probe"] = {"attempts": probe.get("attempts"),
                            "statuses": probe.get("statuses"),
                            "last_ts": probe.get("last_ts")}
    sv = result.get("sentinel") or {}
    if sv:
        # the boolean stamp survives shedding; the detail dict is the
        # first victim below
        compact["regressed"] = bool(sv.get("regressed"))
        compact["sentinel"] = {
            k: sv.get(k) for k in
            ("status", "baseline", "fresh", "ratio", "tolerance",
             "n_history") if sv.get(k) is not None}
    # hard ≤2KB guarantee: shed optional detail, most-expendable first
    for victim in ("sentinel", "probe", "roofline", "components", "bf16_race",
                   "bf16", "f32", "flagship_1dev_cpu", "tpu_breakdown",
                   "overlap", "tpu_hier", "fft_planar", "selfcheck"):
        if len(json.dumps(compact)) <= 2000:
            break
        compact.pop(victim, None)
    if len(json.dumps(compact)) > 2000:
        compact["metric"] = compact.get("metric", "")[:120]
    return compact


def main():
    t_tpu = int(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
    t_cpu = int(os.environ.get("BENCH_CPU_TIMEOUT", "1500"))
    t_probe = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))

    result, err1 = None, "accelerator attempt skipped (JAX_PLATFORMS=cpu)"
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        status, detail = _tpu_probe(t_probe)
        if status != "dead":
            # any live backend (tpu, or plain cpu on accelerator-less
            # machines — the pre-probe behavior) gets the first attempt
            result, err1 = _run_child(dict(os.environ), t_tpu)
        else:
            err1 = (f"TPU probe failed within {t_probe}s: "
                    f"{detail or 'backend init hung or errored'}")

    if result is None:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_FORCE_CPU"] = "1"
        env["PYLOPS_MPI_TPU_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
        result, err2 = _run_child(env, t_cpu)
        if result is not None:
            result["degraded"] = True
            result["tpu_error"] = (err1 or "")[:600]
            # Apples-to-apples CPU run (round-2 VERDICT weak #1, round-4
            # next #2): the SAME N=4096 problem on ONE XLA device with
            # the full host thread pool — no 8-virtual-device carve —
            # fused while_loop vs the clean-subprocess NumPy CGLS the
            # child itself re-times. This is the one configuration
            # where framework and stand-in see identical hardware, so
            # its vs_baseline is the fair CPU comparison. It must run
            # BEFORE cache promotion: round 4 returned early on a
            # banked TPU entry and the row was silently absent from
            # the artifact.
            # bank what we already have BEFORE the extra child: the
            # driver takes the LAST stdout JSON line, so if an outer
            # wall budget kills this parent mid-1-dev-run, the full
            # degraded artifact (merged with any TPU cache) still
            # stands instead of parsed-null
            try:
                early = _merge_tpu_cache(dict(result))
                early["partial"] = "flagship_1dev_cpu pending"
                # the partial flag rides INSIDE the compact builder so
                # its ≤2KB shedding accounts for it
                print(json.dumps(_compact_line(early)), flush=True)
            except Exception:
                pass
            env1 = dict(os.environ)
            env1["JAX_PLATFORMS"] = "cpu"
            env1["BENCH_FORCE_CPU"] = "1"
            env1["PYLOPS_MPI_TPU_PLATFORM"] = "cpu"
            env1["XLA_FLAGS"] = " ".join(
                f for f in env1.get("XLA_FLAGS", "").split()
                if "force_host_platform_device_count" not in f)
            env1["BENCH_COMPONENTS_PYLOPS_MPI_TPU"] = "0"
            env1["BENCH_CPU_BREAKDOWN_PYLOPS_MPI_TPU"] = "0"
            env1["BENCH_SELFCHECK_PYLOPS_MPI_TPU"] = "0"
            # headline-only and few reps: this row must stay cheap —
            # it now runs on EVERY degraded bench (incl. when a banked
            # TPU entry will supersede the CPU numbers), and the
            # driver's wall budget also has to fit the main CPU child
            env1.setdefault("BENCH_REPS_PYLOPS_MPI_TPU", "3")
            r1, e1 = _run_child(env1, min(t_cpu, int(os.environ.get(
                "BENCH_1DEV_TIMEOUT", "480"))))
            if r1 is not None:
                result["flagship_1dev_cpu"] = {
                    k: r1.get(k) for k in
                    ("metric", "value", "unit", "vs_baseline", "gflops",
                     "hbm_gbps", "numpy_baseline_iters_per_sec",
                     "n_devices", "nblock")}
            else:
                result["flagship_1dev_cpu"] = {"error": (e1 or "")[:300]}
            # merge ONCE for this path; on cache promotion the 1-dev
            # row also stays at top level (cpu_live carries it too)
            merged = _merge_tpu_cache(dict(result))
            if merged.get("cached"):
                merged["flagship_1dev_cpu"] = result["flagship_1dev_cpu"]
            _emit_final(merged)
            return
        else:
            result = {
                "metric": "CGLS iters/sec (bench failed on all backends)",
                "value": 0.0, "unit": "iters/s", "vs_baseline": 0.0,
                "degraded": True,
                "tpu_error": (err1 or "")[:600],
                "cpu_error": (err2 or "")[:600],
            }
    result = _merge_tpu_cache(result)
    _emit_final(result)


def _argval(argv, flag):
    """Value following ``flag`` in ``argv`` (None when absent/last)."""
    try:
        i = argv.index(flag)
        return argv[i + 1]
    except (ValueError, IndexError):
        return None


if __name__ == "__main__":
    if _CHILD_FLAG in sys.argv:
        child_main()  # child may crash; the parent handles it
    elif _SENTINEL_ARTIFACT_FLAG in sys.argv:
        _tol = _argval(sys.argv, _SENTINEL_TOL_FLAG)
        sys.exit(_sentinel_artifact_main(
            _argval(sys.argv, _SENTINEL_ARTIFACT_FLAG) or "",
            _sentinel_tolerance(float(_tol) if _tol else None)))
    else:
        if _SENTINEL_FLAG in sys.argv:
            _tol = _argval(sys.argv, _SENTINEL_TOL_FLAG)
            _SENTINEL_STATE["enabled"] = True
            _SENTINEL_STATE["tolerance"] = _sentinel_tolerance(
                float(_tol) if _tol else None)
        try:
            main()
        except Exception as e:  # absolute last resort: still emit a line
            print(json.dumps({
                "metric": "CGLS iters/sec (bench driver crashed)",
                "value": 0.0, "unit": "iters/s", "vs_baseline": 0.0,
                "degraded": True, "error": repr(e)[:800]}))
        v = _SENTINEL_STATE["verdict"]
        sys.exit(1 if (v and v.get("regressed")) else 0)

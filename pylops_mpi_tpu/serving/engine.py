"""Warm-executable pool: compiled block programs, ready before traffic.

The serving daemon's latency floor is compile time — a cold
(operator family, K) pair pays seconds of XLA compilation on the
request that first needs it. The :class:`WarmPool` removes that cliff:

- **Families** — a :class:`FamilySpec` names one operator instance plus
  its solver configuration (``cg``/``cgls``, ``niter``, ``tol``,
  ``damp``). The SAME instance is used for every solve and every
  prewarm, so the fused-executable cache in ``solvers/basic.py``
  (keyed on ``id(Op)``) hits by construction.
- **K buckets** — incoming fills are rounded up to the next width in
  ``PYLOPS_MPI_TPU_SERVE_K_BUCKETS`` (default ``1,2,4,8,16``) and the
  short side padded with zero columns. Padding is EXACT: block-Krylov
  recurrences are column-independent (every scalar is a per-column
  ``col_dot``), a zero column's residual is zero so it freezes at
  iteration 0, and the padded program is the same compiled executable
  the full bucket uses — so K distinct fills share one program instead
  of K programs.
- **Prewarm** — at startup the pool consults the tuning plan cache
  (:func:`pylops_mpi_tpu.tuning.plan.cached_batch_widths`) for the
  block widths real traffic measured plans at, and compiles those
  (falling back to every configured bucket when there is no history) by
  running a zero-RHS solve per (family, K): zero data means zero
  initial residual, the fused ``while_loop`` condition is false at
  entry, and the call compiles the program without executing a single
  iteration.

Per-column robustness (one tenant must not hurt its batch-mates) is
inherited from the block solvers: each column freezes on its OWN
convergence test, and with ``PYLOPS_MPI_TPU_GUARDS=on`` a breakdown
column is frozen with a per-column verdict while the rest run to their
own finish. Serve deployments should run with guards on — without
them a non-finite column collapses the shared loop condition for the
whole batch (see ``docs/serving.md#poisoned-columns``).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..distributedarray import DistributedArray
from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace

__all__ = ["k_buckets", "bucket_for", "FamilySpec", "BlockOutcome",
           "WarmPool"]

_DEFAULT_BUCKETS = (1, 2, 4, 8, 16)

# (family signature, bucket) pairs whose program went through a
# compile in THIS process — shared across WarmPool instances so a
# daemon restart (fresh pool, fresh operator instance, identical
# program) does not silently recompile at prewarm (the id(Op)-keyed
# fused cache cannot see the equivalence; the signature can).
_WARMED_SIGS: set = set()


def clear_warmed_signatures() -> None:
    """Drop the process-wide prewarm ledger (test isolation)."""
    _WARMED_SIGS.clear()


def k_buckets() -> Tuple[int, ...]:
    """``PYLOPS_MPI_TPU_SERVE_K_BUCKETS`` parsed to a sorted tuple of
    distinct positive widths (default ``(1, 2, 4, 8, 16)``; malformed
    tokens are dropped, an empty survivor set falls back to the
    default — a typo must not leave the pool bucketless)."""
    raw = os.environ.get("PYLOPS_MPI_TPU_SERVE_K_BUCKETS", "")
    vals = set()
    for tok in raw.split(","):
        tok = tok.strip()
        if tok.isdigit() and int(tok) >= 1:
            vals.add(int(tok))
    return tuple(sorted(vals)) if vals else _DEFAULT_BUCKETS


def bucket_for(count: int, buckets: Optional[Sequence[int]] = None) -> int:
    """Smallest configured bucket that fits ``count`` columns (the
    largest bucket when ``count`` overflows them all — the caller is
    expected to chunk at the max bucket, which the dispatcher does by
    construction)."""
    bs = tuple(buckets) if buckets else k_buckets()
    for b in bs:
        if b >= count:
            return b
    return bs[-1]


@dataclass(frozen=True)
class FamilySpec:
    """One servable operator family: the operator INSTANCE (reused for
    every solve so the fused cache hits), the engine and its fixed
    solve parameters. ``tol=0.0`` is the bit-for-bit setting: it pins
    every column to the full ``niter`` schedule, so a packed solve
    equals its single-RHS oracle exactly."""
    name: str
    operator: object
    solver: str = "cgls"          # "cg" | "cgls"
    niter: int = 10
    tol: float = 0.0
    damp: float = 0.0
    dtype: object = np.float32
    # optional preconditioner (ops/precond.py) threaded into the block
    # solvers; part of the family identity — the fused cache keys on
    # id(M), so every bucket of the family reuses one compiled PCG/
    # PCGLS program, and M=None families lower bit-identically to the
    # pre-preconditioner engine
    M: object = None
    # opt-in marker for families served with PYLOPS_MPI_TPU_AUTODIFF=on
    # whose callers differentiate through the solve (autodiff/implicit).
    # Folded into signature() ONLY when True so the default False keeps
    # every existing family signature — and therefore every prewarm/AOT
    # bank key — byte-identical to the pre-autodiff engine.
    differentiable: bool = False

    def __post_init__(self):
        if self.solver not in ("cg", "cgls"):
            raise ValueError(
                f"solver={self.solver!r}: expected 'cg' or 'cgls'")

    @property
    def nrows(self) -> int:
        return int(self.operator.shape[0])

    def signature(self) -> Tuple:
        """Structural identity of the family's compiled program:
        solver configuration plus the operator's AOT fingerprint
        (class, shape, dtype, leaf avals — ``aot.op_signature``).
        Two specs with equal signatures lower to the SAME program even
        when their operator INSTANCES differ (a daemon restart builds
        a fresh operator), which is what lets prewarm skip recompiles
        it used to pay silently. Preconditioned families fold in
        ``id(M)`` — M is closure-captured, so only the same instance
        reuses a program. ``differentiable`` is folded in only when
        True (key neutrality for the default)."""
        from ..aot import op_signature
        sig = (self.solver, int(self.niter), float(self.tol),
               float(self.damp), str(np.dtype(self.dtype)),
               op_signature(self.operator),
               None if self.M is None else ("M", id(self.M)))
        if self.differentiable:
            sig = sig + ("differentiable",)
        return sig


@dataclass
class BlockOutcome:
    """One packed solve, already sliced back to the real fill: ``x``
    is ``(M, k)`` (padding columns dropped), ``statuses`` one name per
    real column (``converged``/``maxiter``/``breakdown``)."""
    x: np.ndarray
    iiter: int
    statuses: Tuple[str, ...]
    k: int                        # real fill
    bucket: int                   # compiled width actually run
    wall_s: float


def _column_statuses(kold: np.ndarray, tol: float) -> Tuple[str, ...]:
    """Per-column verdict from the final per-column residual scalars:
    non-finite → breakdown, at/under tolerance → converged, else
    maxiter. (With guards on the solver additionally froze breakdown
    columns in-loop; this classification agrees with the recorded
    verdicts for the finite/non-finite split.)"""
    kold = np.atleast_1d(np.asarray(kold))
    out = []
    for v in kold:
        if not np.isfinite(v):
            out.append("breakdown")
        elif v < tol:
            out.append("converged")
        else:
            out.append("maxiter")
    return tuple(out)


class WarmPool:
    """Registry of servable families + the packed-solve entry point.

    Thread-safe for one solve at a time (an internal lock — the
    dispatcher is single-threaded, but drain paths and tests may race
    it). ``warmed`` records every (family, bucket) pair that has been
    through a compile, whether by :meth:`prewarm` or by live traffic.
    """

    def __init__(self, buckets: Optional[Sequence[int]] = None):
        self._families: Dict[str, FamilySpec] = {}
        self._buckets = tuple(sorted(set(buckets))) if buckets \
            else k_buckets()
        self._lock = threading.Lock()
        self.warmed: set = set()

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    @property
    def k_max(self) -> int:
        return self._buckets[-1]

    def register(self, spec: FamilySpec) -> FamilySpec:
        if spec.name in self._families:
            raise ValueError(f"family {spec.name!r} already registered")
        self._families[spec.name] = spec
        return spec

    def family(self, name: str) -> FamilySpec:
        try:
            return self._families[name]
        except KeyError:
            raise KeyError(
                f"unknown operator family {name!r}; registered: "
                f"{sorted(self._families)}") from None

    def families(self) -> Tuple[str, ...]:
        return tuple(sorted(self._families))

    # ------------------------------------------------------------ solve
    def solve(self, name: str, Y: np.ndarray) -> BlockOutcome:
        """Solve ``Y``'s ``k`` columns as one padded block program of
        the next-larger bucket width. ``Y`` is ``(N, k)`` (a 1-D ``y``
        is treated as ``k=1``)."""
        from ..solvers.block import block_cg, block_cgls
        spec = self.family(name)
        Y = np.asarray(Y, dtype=np.dtype(spec.dtype))
        if Y.ndim == 1:
            Y = Y[:, None]
        N, k = Y.shape
        if N != spec.nrows:
            raise ValueError(
                f"family {name!r} expects data length {spec.nrows}, "
                f"got {N}")
        bucket = bucket_for(k, self._buckets)
        if k > bucket:
            raise ValueError(
                f"fill {k} exceeds the largest bucket {bucket}; "
                "dispatch at most k_max columns per batch")
        if bucket > k:
            Y = np.concatenate(
                [Y, np.zeros((N, bucket - k), dtype=Y.dtype)], axis=1)
        yb = DistributedArray(global_shape=(N, bucket),
                              dtype=np.dtype(spec.dtype))
        yb[:] = Y
        t0 = time.perf_counter()
        with self._lock, _trace.span("serve.pool_solve", cat="serving",
                                     family=name, fill=k, bucket=bucket,
                                     solver=spec.solver):
            if spec.solver == "cg":
                xb, iiter, cost = block_cg(
                    spec.operator, yb, niter=spec.niter, tol=spec.tol,
                    M=spec.M)
                kold = np.asarray(cost)[-1] ** 2
            else:
                xb, _istop, iiter, kold, _r2, _cost = block_cgls(
                    spec.operator, yb, niter=spec.niter,
                    damp=spec.damp, tol=spec.tol, M=spec.M)
        wall = time.perf_counter() - t0
        self.warmed.add((name, bucket))
        _WARMED_SIGS.add((spec.signature(), bucket))
        _metrics.inc("serve.pool.solves")
        _metrics.observe("serve.batch.fill", k / bucket)
        x = np.asarray(xb.array)[:, :k]
        statuses = _column_statuses(kold, spec.tol)[:k]
        return BlockOutcome(x=x, iiter=int(iiter), statuses=statuses,
                            k=k, bucket=bucket, wall_s=wall)

    # ---------------------------------------------------------- prewarm
    def prewarm(self, names: Optional[Sequence[str]] = None,
                widths: Optional[Sequence[int]] = None) -> Dict:
        """Compile (family, bucket) programs before traffic arrives.

        Bucket choice per family, in order: the explicit ``widths``
        argument; else the plan cache's banked block widths for the
        operator's family name (``tuning.plan.cached_batch_widths`` —
        a width that earned a measured plan is a width traffic used),
        rounded up to configured buckets; else EVERY configured bucket
        (no history → assume any fill can arrive). Each compile is a
        zero-RHS solve: the loop condition is false at entry, so the
        cost is exactly one compilation, zero iterations. Returns
        ``{family: [buckets compiled]}``.

        Prewarm is keyed on the family SIGNATURE (shape/dtype/solver
        config — :meth:`FamilySpec.signature`), not the operator
        instance id: with the AOT tier armed
        (``PYLOPS_MPI_TPU_AOT``), a (signature, bucket) pair that
        already went through a compile in this process is skipped
        outright — a restarted daemon registering a FRESH operator
        instance for an identical program stops paying a silent
        recompile per bucket. (Without the AOT tier the executables
        live only in the id-keyed fused cache, so an instance change
        genuinely requires the recompile and the zero-RHS solve runs
        as before.) With a banked AOT cache on disk, the zero-RHS
        solves themselves load serialized executables in milliseconds
        instead of compiling — the cold-start path the bench
        ``cold_start`` row measures."""
        from ..tuning.plan import cached_batch_widths
        from ..aot import aot_enabled
        report: Dict[str, list] = {}
        for name in (names if names is not None else self.families()):
            spec = self.family(name)
            if widths is not None:
                want = [bucket_for(w, self._buckets) for w in widths]
            else:
                hist = cached_batch_widths(type(spec.operator).__name__)
                want = [bucket_for(w, self._buckets)
                        for w in hist if w <= self.k_max]
                if not want:
                    want = list(self._buckets)
            sig = spec.signature() if aot_enabled() else None
            done = []
            for b in sorted(set(want)):
                if sig is not None and (sig, b) in _WARMED_SIGS:
                    # identical program already compiled (or banked)
                    # in this process — the signature-keyed AOT tier
                    # serves it to the new instance without a compile
                    self.warmed.add((name, b))
                    done.append(b)
                    _metrics.inc("serve.pool.prewarm_skipped")
                    _trace.event("serve.prewarm_skip", cat="serving",
                                 family=name, bucket=b)
                    continue
                with _trace.span("serve.prewarm", cat="serving",
                                 family=name, bucket=b):
                    self.solve(name, np.zeros((spec.nrows, b),
                                              dtype=np.dtype(spec.dtype)))
                done.append(b)
                _metrics.inc("serve.pool.prewarmed")
            report[name] = done
        return report

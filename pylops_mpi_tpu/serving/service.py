"""Serve-forever deployment: daemon facade, worker loop, supervisor.

Three layers, innermost first:

- :class:`SolveDaemon` — one process's always-on solve service: an
  :class:`~.queue.AdmissionQueue` + :class:`~.queue.Dispatcher` over a
  :class:`~.engine.WarmPool`. ``submit()`` returns a
  :class:`~.queue.Ticket`; ``stats()`` is the backpressure report;
  ``drain()`` stops admission, finishes in-flight batches, and joins
  the dispatcher within ``PYLOPS_MPI_TPU_SERVE_DRAIN_TIMEOUT``.
- :func:`worker_main` — the supervised replica: heartbeats
  (:func:`~pylops_mpi_tpu.resilience.elastic.maybe_start_heartbeat`,
  beats carry the live metrics registry), SIGTERM routed to a graceful
  drain, and a claim→solve→bank loop against the durable
  :mod:`~.spool`. Replicas are INDEPENDENT — each owns its local
  devices and compiled pool; scaling out is adding claimants on the
  shared spool, with rename atomicity as the only coordination.
- :func:`serve_job` — grows the PR 7 supervisor from run-one-job into
  serve-forever: ``launch_job`` with an ``on_relaunch`` hook that
  sweeps the dead attempt's claimed-but-unfinished requests back to
  pending (bounded by the retry budget) BEFORE the relaunch, so a
  crashed worker's in-flight batch is lost to nobody. Worker crash →
  classify → kill attempt → recover claims → relaunch on surviving
  slots, exactly the chaos-leg lifecycle, now with zero dropped
  requests.

Stopping a deployment is a drain, not a kill: SIGTERM (or the spool's
DRAIN marker) stops admission/claiming; workers finish what they hold
and exit 0; the supervisor sees clean exits and reports ``ok=True``.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Sequence

import numpy as np

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from .engine import WarmPool
from .queue import AdmissionQueue, Dispatcher, Ticket
from . import spool as _spool

__all__ = ["drain_timeout_s", "SolveDaemon", "worker_main", "serve_job"]


def drain_timeout_s() -> float:
    """``PYLOPS_MPI_TPU_SERVE_DRAIN_TIMEOUT`` graceful-drain bound in
    seconds (default 30.0, floored at 0)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_SERVE_DRAIN_TIMEOUT",
                                 "30"))
    except ValueError:
        v = 30.0
    return max(0.0, v)


class SolveDaemon:
    """One process's always-on solve service (see module docstring).

    ``prewarm=True`` compiles the pool's (family, bucket) programs
    before :meth:`start` returns, so the first request never pays
    compile latency."""

    def __init__(self, pool: WarmPool, *,
                 window_s: Optional[float] = None,
                 queue_bound: Optional[int] = None,
                 rehearse: bool = False):
        self.pool = pool
        self.queue = AdmissionQueue(bound=queue_bound)
        self.dispatcher = Dispatcher(pool, self.queue,
                                     window_s=window_s,
                                     rehearse=rehearse)
        self._started = False

    def start(self, prewarm: bool = False) -> "SolveDaemon":
        if prewarm:
            self.pool.prewarm()
        if not self._started:
            self.dispatcher.start()
            self._started = True
            _trace.event("serve.daemon_start", cat="serving",
                         families=list(self.pool.families()),
                         buckets=list(self.pool.buckets))
        return self

    def submit(self, family: str, y: np.ndarray,
               deadline_ts: Optional[float] = None,
               request_id: Optional[str] = None) -> Ticket:
        """Admit one single-RHS request (raises
        :class:`~.queue.QueueFull` past the bound — backpressure)."""
        if not self._started:
            raise RuntimeError("SolveDaemon.start() before submit()")
        return self.queue.submit(family, y, deadline_ts=deadline_ts,
                                 request_id=request_id)

    def stats(self) -> Dict:
        return self.dispatcher.stats()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful stop: refuse new admissions, wait for the queue to
        empty and in-flight batches to resolve (bounded by ``timeout``,
        default the drain knob), then join the dispatcher. True when
        fully drained in time."""
        timeout = drain_timeout_s() if timeout is None else timeout
        self.queue.start_drain()
        end = time.monotonic() + timeout
        drained = self.queue.drain_empty(timeout=timeout)
        while drained and not self.dispatcher.idle():
            if time.monotonic() >= end:
                drained = False
                break
            time.sleep(0.01)
        self.dispatcher.stop()
        self._started = False
        _trace.event("serve.daemon_drain", cat="serving",
                     drained=drained, **self.stats())
        return drained


def worker_main(spool_dir: str, pool: WarmPool, *,
                poll_s: float = 0.02,
                window_s: Optional[float] = None,
                prewarm: bool = True,
                idle_exit_s: Optional[float] = None) -> int:
    """Supervised serve-forever replica over a durable spool.

    Claims up to ``k_max`` pending requests per round, runs them
    through this process's :class:`SolveDaemon` (so admission-window /
    deadline semantics apply), banks each result, and releases the
    claims. Exits 0 when a drain is requested — SIGTERM
    (:func:`~pylops_mpi_tpu.resilience.elastic.install_sigterm_drain`)
    or the spool's DRAIN marker — and everything pending is done.
    ``idle_exit_s`` (tests) also exits after that long with no work
    and no drain. Returns the number of requests this worker solved.
    """
    from ..resilience import elastic
    _spool.init_spool(spool_dir)
    elastic.maybe_start_heartbeat()
    elastic.install_sigterm_drain()
    daemon = SolveDaemon(pool, window_s=window_s).start(prewarm=prewarm)
    solved = 0
    idle_since = time.monotonic()
    _metrics.set_gauge("serve.worker.up", 1)
    while True:
        draining = (elastic.drain_requested()
                    or _spool.drain_requested(spool_dir))
        claims = _spool.claim(spool_dir, daemon.pool.k_max)
        if not claims:
            if draining:
                break
            if idle_exit_s is not None and \
                    time.monotonic() - idle_since > idle_exit_s:
                break
            time.sleep(poll_s)
            continue
        idle_since = time.monotonic()
        tickets = [(c, daemon.submit(c.family, c.y,
                                     deadline_ts=c.deadline_ts,
                                     request_id=c.request_id))
                   for c in claims]
        for c, t in tickets:
            try:
                res = t.wait(timeout=drain_timeout_s() + 60.0)
            except Exception as e:  # solver/deadline failure, not a crash
                _spool.fail(spool_dir, c, repr(e))
                continue
            _spool.complete(spool_dir, c, res["x"],
                            iiter=res["iiter"], status=res["status"])
            solved += 1
            _metrics.inc("serve.worker.solved")
    daemon.drain()
    _metrics.set_gauge("serve.worker.up", 0)
    _trace.event("serve.worker_exit", cat="serving", solved=solved)
    return solved


def serve_job(argv: Sequence[str], num_workers: int, spool_dir: str, *,
              max_relaunches: int = 2, **launch_kwargs):
    """Run a serve-forever worker fleet under the supervisor.

    ``argv`` is the worker command line (same placeholder contract as
    :func:`~pylops_mpi_tpu.resilience.supervisor.launch_job`); the
    worker is expected to call :func:`worker_main` on ``spool_dir``.
    The supervisor's ``on_relaunch`` hook sweeps the dead attempt's
    claimed requests back to pending before each relaunch, and a final
    sweep runs after the job ends (a terminal failure must still
    surface its orphans). Restart-rate lands on the
    ``supervisor.relaunches`` counter; the per-worker serving stats
    arrive in ``JobResult.metrics`` / ``job_report.json`` via the
    heartbeat-embedded registry as usual."""
    from ..resilience.supervisor import launch_job
    _spool.init_spool(spool_dir)

    def _recover(next_attempt: int, failure) -> None:
        requeued, quarantined = _spool.recover_claimed(spool_dir)
        _trace.event("serve.relaunch_recover", cat="serving",
                     attempt=next_attempt, requeued=requeued,
                     quarantined=quarantined,
                     failure_kind=getattr(failure, "kind", None))

    result = launch_job(argv, num_workers,
                        max_relaunches=max_relaunches,
                        on_relaunch=_recover, **launch_kwargs)
    _spool.recover_claimed(spool_dir)
    return result

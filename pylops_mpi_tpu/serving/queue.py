"""Admission queue + continuous batcher for single-RHS requests.

The economics this layer exists for (PR 8): block-CGLS at K=16 does
~12× the solves/sec of 16 sequential solves — but only when callers
arrive pre-batched, and interactive inverse-problem traffic arrives
one RHS at a time. The :class:`AdmissionQueue` holds arriving
requests; the :class:`Dispatcher` drains them into packed (N, K)
block solves against the :class:`~.engine.WarmPool`.

Batch formation — a batch of one family dispatches when the FIRST of
these holds:

1. **Full** — ``k_max`` (largest configured bucket) same-family
   requests are waiting.
2. **Window expired** — the oldest waiting request has been held for
   ``PYLOPS_MPI_TPU_SERVE_WINDOW_MS`` (default 10 ms): latency paid to
   let a fuller batch form, bounded.
3. **Deadline near** — a waiting request's ``deadline_ts`` is within
   the dispatcher's solve-time estimate: the batch dispatches
   UNDERSIZED rather than blow the deadline (counted as
   ``serve.deadline_forced``).

Every dispatched batch runs under a
:class:`~pylops_mpi_tpu.diagnostics.profiler.DeadlineRunner` against
the central ``STAGE_BUDGETS["serve_batch"]`` row and the batch's
earliest request deadline: a batch whose window has already passed is
SKIPPED (tickets fail fast with the runner's reason) instead of
burning solver time on an answer nobody is waiting for.

Backpressure: :meth:`AdmissionQueue.submit` rejects with
:class:`QueueFull` once depth crosses ``PYLOPS_MPI_TPU_SERVE_QUEUE``
(default 1024) — the admission-reject signal autoscalers key on,
mirrored to the ``serve.rejects`` counter and the ``serve.queue.depth``
gauge.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from ..diagnostics.profiler import DeadlineRunner, stage_budget
from .engine import WarmPool, bucket_for

__all__ = ["queue_bound", "batch_window_s", "QueueFull", "Ticket",
           "SolveRequest", "AdmissionQueue", "pack", "Dispatcher"]


def queue_bound() -> int:
    """``PYLOPS_MPI_TPU_SERVE_QUEUE`` admission-queue depth bound
    (default 1024, floored at 1)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_SERVE_QUEUE", "1024"))
    except ValueError:
        v = 1024
    return max(1, v)


def batch_window_s() -> float:
    """``PYLOPS_MPI_TPU_SERVE_WINDOW_MS`` batch-formation window in
    SECONDS (default 0.010; floored at 0 — zero means dispatch
    whatever is waiting, the lowest-latency setting)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_SERVE_WINDOW_MS", "10"))
    except ValueError:
        v = 10.0
    return max(0.0, v) / 1000.0


class QueueFull(RuntimeError):
    """Admission rejected: queue at its bound (or draining). The
    caller's backpressure signal — retry with backoff, shed load, or
    scale out."""


class Ticket:
    """The caller's handle for one submitted request: block on
    :meth:`wait` for the :class:`RequestResult`, or poll
    :meth:`done`."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._event = threading.Event()
        self._result: Optional[Dict] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, result: Dict) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> Dict:
        """Block until resolved; returns ``{"x", "iiter", "status",
        "wait_s", "batch_k", "bucket"}`` or raises the batch's error
        (or TimeoutError)."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not resolved in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result


class SolveRequest:
    """One queued single-RHS request (internal; callers hold the
    :class:`Ticket`)."""

    __slots__ = ("request_id", "family", "y", "deadline_ts", "t_mono",
                 "ticket")

    def __init__(self, request_id: str, family: str, y: np.ndarray,
                 deadline_ts: Optional[float]):
        self.request_id = request_id
        self.family = family
        self.y = y
        self.deadline_ts = deadline_ts    # wall clock (time.time)
        self.t_mono = time.monotonic()    # queue-wait reference
        self.ticket = Ticket(request_id)


def pack(requests: List[SolveRequest],
         buckets: Optional[Tuple[int, ...]] = None
         ) -> Tuple[np.ndarray, int]:
    """Stack a same-family batch into an ``(N, k)`` RHS matrix and pick
    its bucket: the smallest configured width holding all ``k``
    columns (the engine pads the difference with zero columns, which
    the per-column freeze makes exact)."""
    if not requests:
        raise ValueError("cannot pack an empty batch")
    fams = {r.family for r in requests}
    if len(fams) > 1:
        raise ValueError(f"one family per batch, got {sorted(fams)}")
    Y = np.stack([np.asarray(r.y).ravel() for r in requests], axis=1)
    return Y, bucket_for(Y.shape[1], buckets)


class AdmissionQueue:
    """Bounded FIFO of :class:`SolveRequest`\\ s with condition-variable
    handoff to the dispatcher."""

    def __init__(self, bound: Optional[int] = None):
        self.bound = queue_bound() if bound is None else max(1, int(bound))
        self._dq: deque = deque()
        self._cond = threading.Condition()
        self._draining = False
        self._ids = itertools.count()
        self.submitted = 0
        self.rejected = 0

    def depth(self) -> int:
        with self._cond:
            return len(self._dq)

    def submit(self, family: str, y: np.ndarray,
               deadline_ts: Optional[float] = None,
               request_id: Optional[str] = None) -> Ticket:
        """Admit one request or raise :class:`QueueFull` (bound hit, or
        queue draining). Returns the caller's :class:`Ticket`."""
        with self._cond:
            if self._draining:
                self.rejected += 1
                _metrics.inc("serve.rejects")
                raise QueueFull("queue is draining; not admitting")
            if len(self._dq) >= self.bound:
                self.rejected += 1
                _metrics.inc("serve.rejects")
                raise QueueFull(
                    f"admission queue at bound {self.bound} "
                    "(PYLOPS_MPI_TPU_SERVE_QUEUE); shed or retry")
            rid = request_id if request_id is not None \
                else f"r{next(self._ids)}"
            req = SolveRequest(rid, family, y, deadline_ts)
            self._dq.append(req)
            self.submitted += 1
            _metrics.inc("serve.requests")
            _metrics.set_gauge("serve.queue.depth", len(self._dq))
            self._cond.notify_all()
            return req.ticket

    def start_drain(self) -> None:
        """Stop admitting; already-queued requests still dispatch."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def collect(self, k_max: int, window_s: float,
                margin_s: float = 0.0, poll_s: float = 0.05
                ) -> Tuple[List[SolveRequest], bool]:
        """Dispatcher side: block until a batch should go, then pop it.

        Returns ``(batch, forced)`` — ``batch`` empty when the poll
        tick elapsed with nothing to do; ``forced`` True when a near
        deadline pushed out an undersized batch. The batch is the
        oldest waiting request's family, FIFO order, at most ``k_max``
        columns; other families stay queued for the next round."""
        with self._cond:
            if not self._dq:
                self._cond.wait(timeout=poll_s)
                if not self._dq:
                    return [], False
            forced = False
            while True:
                first = self._dq[0]
                fam = first.family
                count = sum(1 for r in self._dq if r.family == fam)
                if count >= k_max:
                    break
                age = time.monotonic() - first.t_mono
                if age >= window_s:
                    break
                now = time.time()
                ddls = [r.deadline_ts for r in self._dq
                        if r.family == fam and r.deadline_ts is not None]
                if ddls and min(ddls) - now <= margin_s:
                    forced = True
                    break
                # wake at whichever edge comes first: poll tick, window
                # expiry, or the margin point of the nearest deadline —
                # a fixed poll could overshoot a near deadline past zero
                wait_t = min(poll_s, window_s - age)
                if ddls:
                    wait_t = min(wait_t, min(ddls) - now - margin_s)
                self._cond.wait(timeout=max(0.001, wait_t))
                if not self._dq:
                    return [], False
            taken: List[SolveRequest] = []
            rest: deque = deque()
            for r in self._dq:
                if r.family == fam and len(taken) < k_max:
                    taken.append(r)
                else:
                    rest.append(r)
            self._dq = rest
            _metrics.set_gauge("serve.queue.depth", len(self._dq))
            return taken, forced

    def drain_empty(self, timeout: Optional[float] = None) -> bool:
        """Wait until the queue is empty (dispatched, not necessarily
        resolved). True when empty within ``timeout``."""
        end = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._dq:
                rem = None if end is None else end - time.monotonic()
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(timeout=0.05 if rem is None
                                else min(0.05, rem))
        return True


class Dispatcher(threading.Thread):
    """The continuous-batching loop: collect → pack → padded block
    solve → resolve tickets, forever (daemon thread).

    Keeps its OWN bounded stats (wait-time samples, counters)
    independent of the metrics gate so :meth:`stats` — the
    backpressure/autoscaling report — works in any configuration; the
    same numbers are mirrored into the metrics registry (and thus
    heartbeats / job_report.json) when ``PYLOPS_MPI_TPU_METRICS=on``.
    """

    def __init__(self, pool: WarmPool, queue: AdmissionQueue, *,
                 window_s: Optional[float] = None,
                 rehearse: bool = False,
                 on_batch: Optional[Callable[[Dict], None]] = None):
        super().__init__(name="pylops-serve-dispatch", daemon=True)
        self.pool = pool
        self.queue = queue
        self.window_s = batch_window_s() if window_s is None \
            else max(0.0, float(window_s))
        self.rehearse = bool(rehearse)
        self.on_batch = on_batch
        self._halt = threading.Event()
        self._inflight = threading.Event()
        self._ewma_wall = 0.0     # solve-time estimate for margins
        self.batches = 0
        self.solves = 0
        self.forced = 0
        self.failed = 0
        self.wait_samples: deque = deque(maxlen=4096)
        self.fill_samples: deque = deque(maxlen=4096)
        self._t_solving = 0.0
        self._t_started = time.monotonic()

    def _margin_s(self) -> float:
        # dispatch early enough that the estimated solve still lands
        # inside the deadline; 1.5× EWMA + 10 ms floor absorbs jitter
        return 1.5 * self._ewma_wall + 0.010

    def run(self) -> None:
        while not self._halt.is_set():
            batch, forced = self.queue.collect(
                self.pool.k_max, self.window_s,
                margin_s=self._margin_s())
            if not batch:
                continue
            self._inflight.set()
            try:
                self._dispatch(batch, forced)
            finally:
                self._inflight.clear()

    def _dispatch(self, batch: List[SolveRequest], forced: bool) -> None:
        Y, bucket = pack(batch, self.pool.buckets)
        k = len(batch)
        deadlines = [r.deadline_ts for r in batch
                     if r.deadline_ts is not None]
        runner = DeadlineRunner(
            deadline_ts=min(deadlines) if deadlines else None,
            min_stage_s=0)
        budget = stage_budget("serve_batch", rehearse=self.rehearse)
        fam = batch[0].family

        def _solve(_eff_timeout):
            return self.pool.solve(fam, Y), None

        rec = runner.run("serve_batch", _solve, budget)
        now_mono = time.monotonic()
        waits = [now_mono - r.t_mono for r in batch]
        self.batches += 1
        self.solves += k
        self.wait_samples.extend(waits)
        self.fill_samples.append(k / bucket)
        if forced:
            self.forced += 1
            _metrics.inc("serve.deadline_forced")
        _metrics.inc("serve.batches")
        _metrics.inc("serve.solves", k)
        for w in waits:
            _metrics.observe("serve.queue.wait_s", w)
        outcome = rec.result
        if rec.get("skipped") or outcome is None:
            self.failed += k
            _metrics.inc("serve.deadline_missed" if rec.get("skipped")
                         else "serve.batch_errors")
            reason = rec.get("reason") or rec.get("error") \
                or "batch solve failed"
            for r in batch:
                r.ticket._fail(RuntimeError(
                    f"request {r.request_id}: {reason}"))
            return
        self._t_solving += outcome.wall_s
        self._ewma_wall = outcome.wall_s if self._ewma_wall == 0 \
            else 0.7 * self._ewma_wall + 0.3 * outcome.wall_s
        rate = k / outcome.wall_s if outcome.wall_s > 0 else 0.0
        _metrics.set_gauge("serve.solves_per_sec", rate)
        for j, r in enumerate(batch):
            r.ticket._resolve({
                "x": outcome.x[:, j],
                "iiter": outcome.iiter,
                "status": outcome.statuses[j],
                "wait_s": waits[j],
                "batch_k": k,
                "bucket": bucket,
            })
        _trace.event("serve.batch", cat="serving", family=fam, fill=k,
                     bucket=bucket, forced=forced,
                     wall_s=round(outcome.wall_s, 4))
        if self.on_batch is not None:
            try:
                self.on_batch({"family": fam, "fill": k,
                               "bucket": bucket, "forced": forced,
                               "wall_s": outcome.wall_s})
            except Exception:
                pass

    # ------------------------------------------------------------ stats
    def _quantile(self, samples: List[float], q: float) -> float:
        if not samples:
            return 0.0
        s = sorted(samples)
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def stats(self) -> Dict:
        """The backpressure/autoscaling report: queue depth, admission
        counters, batch fill, solves/sec (solve-wall basis), and
        p50/p99 time-in-queue over the recent window."""
        waits = list(self.wait_samples)
        fills = list(self.fill_samples)
        return {
            "queue_depth": self.queue.depth(),
            "queue_bound": self.queue.bound,
            "submitted": self.queue.submitted,
            "rejected": self.queue.rejected,
            "batches": self.batches,
            "solves": self.solves,
            "forced": self.forced,
            "failed": self.failed,
            "fill_mean": (sum(fills) / len(fills)) if fills else 0.0,
            "solves_per_sec": (self.solves / self._t_solving
                               if self._t_solving > 0 else 0.0),
            "wait_p50_s": self._quantile(waits, 0.50),
            "wait_p99_s": self._quantile(waits, 0.99),
        }

    def idle(self) -> bool:
        return not self._inflight.is_set() and self.queue.depth() == 0

    def stop(self, timeout: float = 5.0) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=timeout)

"""Always-on solve service (ISSUE 12): continuous batching over the
block engine.

The serving subsystem turns the library's one-shot solvers into a
long-lived daemon for single-RHS traffic:

- :mod:`.engine` — :class:`WarmPool`: compiled block-CG/CGLS programs
  per (operator family, K bucket), pre-warmed from the tuning plan
  cache so first-request latency is compile-free; ragged fills are
  zero-padded to the bucket (exact, by per-column freeze).
- :mod:`.queue` — :class:`AdmissionQueue` (bounded, rejecting —
  backpressure) + :class:`Dispatcher` (continuous batcher: full
  bucket / window expiry / deadline-forced undersized dispatch, every
  batch under a ``DeadlineRunner``).
- :mod:`.spool` — durable filesystem queue for supervised workers
  (atomic claim/complete/recover; crash-safe at any instant).
- :mod:`.service` — :class:`SolveDaemon` (in-process facade),
  :func:`worker_main` (supervised replica with SIGTERM drain), and
  :func:`serve_job` (serve-forever under the PR 7 supervisor with
  crashed-attempt request recovery).

See ``docs/serving.md`` for architecture, knobs, and deadline /
backpressure semantics.
"""

from . import engine, queue, service, spool
from .engine import FamilySpec, WarmPool, BlockOutcome, k_buckets, \
    bucket_for
from .queue import (AdmissionQueue, Dispatcher, QueueFull, Ticket,
                    pack, queue_bound, batch_window_s)
from .service import SolveDaemon, worker_main, serve_job, \
    drain_timeout_s

__all__ = ["engine", "queue", "service", "spool",
           "FamilySpec", "WarmPool", "BlockOutcome", "k_buckets",
           "bucket_for",
           "AdmissionQueue", "Dispatcher", "QueueFull", "Ticket",
           "pack", "queue_bound", "batch_window_s",
           "SolveDaemon", "worker_main", "serve_job",
           "drain_timeout_s"]

"""Durable request spool: crash-safe handoff to supervised workers.

The in-process :class:`~.queue.AdmissionQueue` dies with its process;
a serve-forever deployment needs the in-flight requests of a crashed
worker BACK. The spool is a filesystem queue with the repo's standard
atomicity idioms (temp + ``os.replace`` writes, ``os.rename`` moves),
so every transition is crash-safe at any instant:

::

    pending/<id>.a<attempt>.npz   enqueued, unowned
    claimed/<id>.a<attempt>.npz   owned by one worker (atomic rename:
                                  exactly one winner per file)
    results/<id>.npz              solved (idempotent overwrite — a
                                  re-solved request writes identical
                                  bytes, so recovery double-solves are
                                  harmless, never wrong)
    failed/<id>.a<attempt>.npz    retry budget exhausted
    DRAIN                         marker: workers finish what is
                                  pending and exit 0

Recovery (:func:`recover_claimed`) moves a dead attempt's claimed
files back to ``pending`` with the attempt counter bumped, bounded by
the PR 6 retry budget (``PYLOPS_MPI_TPU_RETRIES``): a request that
kills its worker ``retries+1`` times is quarantined in ``failed/``
instead of crash-looping the fleet. The supervisor's ``on_relaunch``
hook calls this between attempts (see ``serving/service.py``).

No locks, no daemons, no network: multiple workers on one spool
coordinate purely through rename atomicity, the same way the tuning
cache and heartbeat files already do.
"""

from __future__ import annotations

import json
import os
import uuid
from collections import namedtuple
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace

__all__ = ["init_spool", "enqueue", "claim", "complete", "fail",
           "recover_claimed", "read_result", "result_ids",
           "pending_count", "claimed_count", "request_drain",
           "drain_requested", "Claim"]

_DIRS = ("pending", "claimed", "results", "failed")

Claim = namedtuple("Claim", ["request_id", "attempt", "family", "y",
                             "deadline_ts", "path"])
Claim.__doc__ = ("One claimed request: identity, 0-based re-enqueue "
                 "counter, payload, and the claimed-file path this "
                 "worker owns.")


def init_spool(root: str) -> str:
    root = os.path.abspath(root)
    for d in _DIRS:
        os.makedirs(os.path.join(root, d), exist_ok=True)
    return root


def _parse_name(fname: str) -> Optional[Tuple[str, int]]:
    """``<id>.a<attempt>.npz`` → ``(id, attempt)``; None for foreign
    files (editor droppings etc. must not crash the claim loop)."""
    if not fname.endswith(".npz"):
        return None
    stem = fname[:-4]
    rid, sep, att = stem.rpartition(".a")
    if not sep or not rid or not att.isdigit():
        return None
    return rid, int(att)


def enqueue(root: str, family: str, y: np.ndarray, *,
            request_id: Optional[str] = None,
            deadline_ts: Optional[float] = None) -> str:
    """Append one single-RHS request; returns its id. Atomic: the file
    appears in ``pending/`` complete or not at all."""
    root = init_spool(root)
    rid = request_id or uuid.uuid4().hex[:16]
    meta = {"family": str(family),
            "deadline_ts": deadline_ts}
    tmp = os.path.join(root, f".enq_{os.getpid()}_{rid}.npz")
    dst = os.path.join(root, "pending", f"{rid}.a0.npz")
    with open(tmp, "wb") as f:
        np.savez(f, y=np.asarray(y), meta=json.dumps(meta))
    os.replace(tmp, dst)
    _metrics.inc("serve.spool.enqueued")
    return rid


def _load(path: str, rid: str, attempt: int) -> Optional[Claim]:
    try:
        with np.load(path, allow_pickle=False) as z:
            y = np.asarray(z["y"])
            meta = json.loads(str(z["meta"]))
    except (OSError, ValueError, KeyError):
        return None  # torn/foreign file: skip, never crash the worker
    return Claim(request_id=rid, attempt=attempt,
                 family=meta.get("family", ""), y=y,
                 deadline_ts=meta.get("deadline_ts"), path=path)


def claim(root: str, limit: int) -> List[Claim]:
    """Atomically take up to ``limit`` pending requests (oldest
    first). Concurrent workers race on ``os.rename``; exactly one
    wins each file, losers skip on ``FileNotFoundError``."""
    root = os.path.abspath(root)
    pend = os.path.join(root, "pending")
    try:
        names = os.listdir(pend)
    except OSError:
        return []
    entries = []
    for n in names:
        parsed = _parse_name(n)
        if parsed is None:
            continue
        p = os.path.join(pend, n)
        try:
            entries.append((os.path.getmtime(p), n, parsed))
        except OSError:
            continue  # another worker just claimed it
    entries.sort()
    out: List[Claim] = []
    for _, n, (rid, att) in entries:
        if len(out) >= limit:
            break
        src = os.path.join(pend, n)
        dst = os.path.join(root, "claimed", n)
        try:
            os.rename(src, dst)
        except OSError:
            continue  # lost the race
        c = _load(dst, rid, att)
        if c is not None:
            out.append(c)
            _metrics.inc("serve.spool.claimed")
    return out


def complete(root: str, c: Claim, x: np.ndarray, *,
             iiter: int = 0, status: str = "converged") -> str:
    """Bank the result and release the claim. Result writes are
    idempotent overwrites keyed by request id only — a recovered
    request re-solved after a crash-after-complete rewrites identical
    bytes (deterministic solves), so recovery never corrupts."""
    root = os.path.abspath(root)
    dst = os.path.join(root, "results", f"{c.request_id}.npz")
    tmp = dst + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, x=np.asarray(x), iiter=np.int64(iiter),
                 status=str(status))
    os.replace(tmp, dst)
    try:
        os.remove(c.path)
    except OSError:
        pass  # already recovered elsewhere; the result stands
    _metrics.inc("serve.spool.completed")
    return dst


def fail(root: str, c: Claim, error: str) -> None:
    """Quarantine a request this worker cannot solve (solver error,
    not a crash): move the claim to ``failed/`` with the error text
    alongside."""
    root = os.path.abspath(root)
    dst = os.path.join(root, "failed", os.path.basename(c.path))
    try:
        os.rename(c.path, dst)
        with open(dst + ".err", "w") as f:
            f.write(str(error)[:2000])
    except OSError:
        pass
    _metrics.inc("serve.spool.failed")


def recover_claimed(root: str,
                    max_attempts: Optional[int] = None
                    ) -> Tuple[int, int]:
    """Re-enqueue every claimed-but-unfinished request (the dead
    attempt's in-flight work), attempt counter bumped; requests past
    the retry budget (default ``PYLOPS_MPI_TPU_RETRIES`` + 1 total
    attempts) go to ``failed/`` instead. A request whose result
    ALREADY exists (crash between result write and claim release) is
    simply released — re-solving is harmless but pointless. Returns
    ``(requeued, quarantined)``. Idempotent: a second sweep finds an
    empty ``claimed/`` and does nothing."""
    if max_attempts is None:
        from ..resilience.retry import default_retries
        max_attempts = default_retries() + 1
    root = os.path.abspath(root)
    cl = os.path.join(root, "claimed")
    try:
        names = os.listdir(cl)
    except OSError:
        return 0, 0
    requeued = quarantined = 0
    for n in sorted(names):
        parsed = _parse_name(n)
        if parsed is None:
            continue
        rid, att = parsed
        src = os.path.join(cl, n)
        if os.path.exists(os.path.join(root, "results", f"{rid}.npz")):
            try:
                os.remove(src)
            except OSError:
                pass
            continue
        if att + 1 >= max_attempts:
            try:
                os.rename(src, os.path.join(root, "failed", n))
                with open(os.path.join(root, "failed", n + ".err"),
                          "w") as f:
                    f.write(f"retry budget exhausted after "
                            f"{att + 1} attempts")
            except OSError:
                continue
            quarantined += 1
            _metrics.inc("serve.spool.quarantined")
            continue
        dst = os.path.join(root, "pending", f"{rid}.a{att + 1}.npz")
        try:
            os.rename(src, dst)
        except OSError:
            continue
        requeued += 1
        _metrics.inc("serve.requeues")
    if requeued or quarantined:
        _trace.event("serve.spool_recover", cat="serving",
                     requeued=requeued, quarantined=quarantined)
    return requeued, quarantined


def read_result(root: str, request_id: str) -> Optional[Dict]:
    path = os.path.join(os.path.abspath(root), "results",
                        f"{request_id}.npz")
    try:
        with np.load(path, allow_pickle=False) as z:
            return {"x": np.asarray(z["x"]),
                    "iiter": int(z["iiter"]),
                    "status": str(z["status"])}
    except (OSError, ValueError, KeyError):
        return None


def result_ids(root: str) -> List[str]:
    try:
        names = os.listdir(os.path.join(os.path.abspath(root),
                                        "results"))
    except OSError:
        return []
    return sorted(n[:-4] for n in names if n.endswith(".npz"))


def pending_count(root: str) -> int:
    try:
        return len([n for n in os.listdir(
            os.path.join(os.path.abspath(root), "pending"))
            if n.endswith(".npz")])
    except OSError:
        return 0


def claimed_count(root: str) -> int:
    try:
        return len([n for n in os.listdir(
            os.path.join(os.path.abspath(root), "claimed"))
            if n.endswith(".npz")])
    except OSError:
        return 0


def request_drain(root: str) -> None:
    """Drop the DRAIN marker: workers stop claiming once pending is
    empty and exit 0 — the deployment-wide graceful stop."""
    path = os.path.join(init_spool(root), "DRAIN")
    with open(path, "w") as f:
        f.write("drain\n")


def drain_requested(root: str) -> bool:
    return os.path.exists(os.path.join(os.path.abspath(root), "DRAIN"))

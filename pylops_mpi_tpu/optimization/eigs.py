"""Module-path parity with ``pylops_mpi.optimization.eigs``."""
from ..solvers.eigs import power_iteration  # noqa: F401

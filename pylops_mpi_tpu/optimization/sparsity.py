"""Module-path parity with ``pylops_mpi.optimization.sparsity``."""
from ..solvers.sparsity import ISTA, FISTA, ista, fista  # noqa: F401

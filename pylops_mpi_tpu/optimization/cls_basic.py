"""Module-path parity with ``pylops_mpi.optimization.cls_basic``."""
from ..solvers.basic import CG, CGLS  # noqa: F401

"""Module-path parity with ``pylops_mpi.optimization.basic`` (and the
class API of ``cls_basic``)."""
from ..solvers.basic import CG, CGLS, cg, cgls  # noqa: F401

"""Module-path parity with ``pylops_mpi.optimization.cls_sparsity``."""
from ..solvers.sparsity import ISTA, FISTA  # noqa: F401

"""Namespace parity with ``pylops_mpi.optimization``."""
from ..solvers.basic import CG, CGLS, cg, cgls
from ..solvers.sparsity import ISTA, FISTA, ista, fista
from ..solvers.eigs import power_iteration
from ..solvers import basic, sparsity, eigs

"""Schema-versioned atomic on-disk bank for serialized executables.

Layout (``PYLOPS_MPI_TPU_AOT_CACHE`` names the directory):

- ``index.json`` — ``{"schema": N, "entries": {entry_id: {"key":
  <repr of the bank key>, "signature": <compile_signature dict>,
  "avals": <args fingerprint>, "payload": "exe_<id>.bin",
  "compile_s": wall, "nbytes": payload size, "created_s": epoch}}}``.
  Written read-merge-atomic (temp file + ``os.replace``) under an
  ``fcntl.flock`` sidecar — the plan-cache discipline
  (``tuning/cache.py``), so two processes banking concurrently merge
  instead of clobbering.
- ``exe_<id>.bin`` — one pickled container per entry:
  ``{"payload": <PJRT serialized executable bytes>, "out_tree":
  <pickled output treedef>}``. Written first, indexed second, so a
  crash between the two leaves an orphaned blob, never a dangling
  index row.

Every failure mode — unreadable index, schema mismatch, missing or
truncated payload, signature/aval mismatch — is a CLASSIFIED miss: a
``aot.cache_error`` trace event (plus ``aot.cache.miss``) and a fresh
compile. The bank can never take the workload down and can never
serve a stale program (the loaded executable additionally re-validates
operand avals at call time).

Multi-host contract: only rank 0 (``PYLOPS_MPI_TPU_PROCESS_ID`` unset
or ``0``) writes the bank; other ranks read it. Every rank lowers the
same SPMD program, so one writer suffices and NFS-backed cache dirs
see no cross-rank write races (docs/aot.md#multi-host).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading
from typing import Any, Dict, Optional, Tuple

from ..diagnostics import trace as _trace

__all__ = ["SCHEMA_VERSION", "aot_mode", "aot_enabled", "bank_dir",
           "rank_writes", "entry_id", "load_index", "lookup",
           "store_entry", "clear_memory"]

SCHEMA_VERSION = 1
_AOT_MODES = ("auto", "on", "off")

_LOCK = threading.Lock()
# process-local tier: bank_key -> loaded AotExecutable. Always
# consulted first; the ONLY tier under AOT=on with no cache dir
# (memory-only — nothing is written to disk behind the user's back,
# mirroring the TUNE/TUNE_CACHE split).
_MEM: Dict[Tuple, Any] = {}
_warned_corrupt = False
_warned_mode = False


def aot_mode() -> str:
    """``PYLOPS_MPI_TPU_AOT`` resolved to ``auto``/``on``/``off``
    (default ``off`` — the seam must be bit-identical to the pre-AOT
    build unless asked for; unknown values warn once and fall back,
    the watchdog-knob rule)."""
    global _warned_mode
    m = os.environ.get("PYLOPS_MPI_TPU_AOT", "off").strip().lower()
    if m in ("", "none", "default", "0"):
        m = "off"
    if m == "1":
        m = "on"
    if m not in _AOT_MODES:
        if not _warned_mode:
            import warnings
            warnings.warn(f"PYLOPS_MPI_TPU_AOT={m!r} is not one of "
                          f"{_AOT_MODES}; using 'off'", stacklevel=2)
            _warned_mode = True
        m = "off"
    return m


def aot_enabled() -> bool:
    """``on`` → armed (memory-only without a cache dir); ``off`` →
    disarmed; ``auto`` → armed only when ``PYLOPS_MPI_TPU_AOT_CACHE``
    names a bank directory."""
    m = aot_mode()
    if m == "on":
        return True
    if m == "off":
        return False
    return bank_dir() is not None


def bank_dir(path: Optional[str] = None) -> Optional[str]:
    """Resolved bank directory: the explicit argument, else
    ``PYLOPS_MPI_TPU_AOT_CACHE``, else ``None`` (memory-only)."""
    if path:
        return path
    return os.environ.get("PYLOPS_MPI_TPU_AOT_CACHE") or None


def rank_writes() -> bool:
    """Whether THIS process may write the bank: rank 0 of the elastic
    contract, or any single-process run. Non-zero ranks lower the same
    SPMD program — they read the bank rank 0 populates."""
    rid = os.environ.get("PYLOPS_MPI_TPU_PROCESS_ID", "0") or "0"
    try:
        return int(rid) == 0
    except ValueError:
        return True


def entry_id(key: Tuple) -> str:
    """Stable filename-safe id for a bank key (sha256 of its repr —
    the key is built from plain values whose repr is deterministic:
    strings, ints, bools, dtypes, nested tuples)."""
    return hashlib.sha256(repr(key).encode()).hexdigest()[:32]


def _cache_error(where: str, why: str) -> None:
    """One structured ``aot.cache_error`` event + one-time warning per
    corrupt/mismatched bank; the caller proceeds with a fresh compile
    — never an exception, never a stale program."""
    global _warned_corrupt
    _trace.event("aot.cache_error", cat="aot", path=where, why=why)
    if not _warned_corrupt:
        import warnings
        warnings.warn(
            f"pylops_mpi_tpu AOT bank {where!r} unusable ({why}); "
            "falling back to fresh compiles", stacklevel=3)
        _warned_corrupt = True


def load_index(dirpath: Optional[str] = None) -> Dict[str, dict]:
    """Entry table from ``index.json`` (``{}`` when unset/missing/
    corrupt/version-mismatched — every failure mode is a logged
    miss)."""
    dirpath = bank_dir(dirpath)
    if not dirpath:
        return {}
    path = os.path.join(dirpath, "index.json")
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        _cache_error(path, f"unreadable: {e!r}")
        return {}
    if not isinstance(doc, dict):
        _cache_error(path, "not a JSON object")
        return {}
    if doc.get("schema") != SCHEMA_VERSION:
        _cache_error(path, f"schema {doc.get('schema')!r} != "
                           f"{SCHEMA_VERSION}")
        return {}
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        _cache_error(path, "missing 'entries' table")
        return {}
    return {str(k): v for k, v in entries.items()
            if isinstance(v, dict)}


def _signature_mismatch(banked: dict, live: dict) -> Optional[str]:
    """First field on which the banked signature disagrees with the
    live environment, or ``None`` when the entry is replayable here."""
    if not isinstance(banked, dict):
        return "signature missing"
    for field, want in live.items():
        got = banked.get(field)
        if got != want:
            return f"{field}: banked {got!r} != live {want!r}"
    return None


def lookup(key: Tuple, signature: dict, avals: Tuple,
           dirpath: Optional[str] = None
           ) -> Optional[Tuple[bytes, bytes, dict]]:
    """Raw banked bytes for ``key`` — ``(payload, out_tree_bytes,
    entry_meta)`` — or ``None`` (classified miss). The caller
    deserializes; this layer only guarantees the entry was banked for
    THIS key in an environment matching ``signature``/``avals``."""
    eid = entry_id(key)
    entry = load_index(dirpath).get(eid)
    if entry is None:
        return None
    why = _signature_mismatch(entry.get("signature"), signature)
    if why is None and entry.get("avals") != _avals_json(avals):
        why = "operand avals changed"
    if why is not None:
        _cache_error(os.path.join(bank_dir(dirpath) or "", "index.json"),
                     f"entry {eid}: {why}")
        return None
    blob_path = os.path.join(bank_dir(dirpath) or "",
                             str(entry.get("payload", "")))
    try:
        with open(blob_path, "rb") as f:
            container = pickle.loads(f.read())
        payload = container["payload"]
        out_tree = container["out_tree"]
        if not isinstance(payload, bytes) or not isinstance(out_tree,
                                                            bytes):
            raise ValueError("container fields are not bytes")
    except Exception as e:  # missing/truncated/garbage blob
        _cache_error(blob_path, f"payload unusable: {e!r}")
        return None
    return payload, out_tree, entry


def _avals_json(avals: Tuple) -> list:
    """The aval fingerprint as the JSON shape it round-trips to
    (tuples become lists), so stored-vs-live comparison is exact."""
    return json.loads(json.dumps(avals))


class _file_lock:
    """Best-effort cross-process mutex around the read-merge-write
    cycle — two concurrent writers (e.g. a prewarm pass racing a live
    solve in another process) would each read, merge only their own
    entry and atomically replace, silently dropping the other's
    executable. ``fcntl.flock`` on a ``.lock`` sidecar serializes the
    cycle; without ``fcntl`` it degrades to a no-op (the write stays
    atomic and valid, a concurrent entry may be lost — never the
    file)."""

    def __init__(self, path: str):
        self._path = path + ".lock"
        self._fh = None

    def __enter__(self):
        try:
            import fcntl
            self._fh = open(self._path, "a")
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        except Exception:
            if self._fh is not None:
                self._fh.close()
            self._fh = None
        return self

    def __exit__(self, *exc):
        if self._fh is not None:
            try:
                import fcntl
                fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
            except Exception:
                pass
            self._fh.close()
        return False


def store_entry(key: Tuple, signature: dict, avals: Tuple,
                payload: bytes, out_tree: bytes, compile_s: float,
                dirpath: Optional[str] = None) -> None:
    """Bank a serialized executable: blob first, index row second
    (read-merge-atomic-write under the cross-process lock). No-op
    without a bank dir or on a non-writing rank; a failed write is a
    trace event, never an exception — the in-process executable is
    already usable."""
    dirpath = bank_dir(dirpath)
    if not dirpath or not rank_writes():
        return
    eid = entry_id(key)
    try:
        os.makedirs(dirpath, exist_ok=True)
        blob_name = f"exe_{eid}.bin"
        blob = pickle.dumps({"payload": payload, "out_tree": out_tree})
        fd, tmp = tempfile.mkstemp(prefix=f".aot_{os.getpid()}_",
                                   dir=dirpath)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(dirpath, blob_name))
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        index_path = os.path.join(dirpath, "index.json")
        with _file_lock(index_path):
            entries = load_index(dirpath)
            entries[eid] = {
                "key": repr(key),
                "signature": json.loads(json.dumps(signature)),
                "avals": _avals_json(avals),
                "payload": blob_name,
                "compile_s": round(float(compile_s), 4),
                "nbytes": len(blob),
                "created_s": _now(),
            }
            doc = {"schema": SCHEMA_VERSION, "entries": entries}
            fd, tmp = tempfile.mkstemp(
                prefix=f".aot_index_{os.getpid()}_", dir=dirpath)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                os.replace(tmp, index_path)
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
    except Exception as e:  # persistence must never break the workload
        _trace.event("aot.cache_error", cat="aot", path=dirpath,
                     why=f"write failed: {e!r}")


def _now() -> float:
    import time
    return round(time.time(), 3)


def mem_get(key: Tuple):
    """Process-local executable for ``key`` (no metrics — the caller
    classifies the hit tier)."""
    with _LOCK:
        return _MEM.get(key)


def mem_put(key: Tuple, exe) -> None:
    with _LOCK:
        _MEM[key] = exe


def clear_memory() -> None:
    """Drop the process-local executable tier (test isolation
    helper); also re-arms the one-time corruption warning."""
    global _warned_corrupt, _warned_mode
    with _LOCK:
        _MEM.clear()
    _warned_corrupt = False
    _warned_mode = False

"""JAX persistent compilation cache wiring — the fallback layer.

The executable bank (:mod:`~pylops_mpi_tpu.aot.store`) serializes
only the programs whose operators enter as jit arguments; everything
else — closure-captured operators, preconditioned solves, ISTA/FISTA,
one-off jits across the package — still pays XLA compile on first
trace. ``PYLOPS_MPI_TPU_COMPILE_CACHE=<dir>`` points JAX's own
persistent compilation cache at a shared directory so those compiles
are paid once per (program, jax version, backend) ACROSS processes:
CI legs share a per-job dir, the tier-1 command keeps one under
``/tmp``, and a supervisor relaunch re-traces but does not re-optimize.

Multi-host contract: rank 0 writes, other ranks read — every rank
lowers the same SPMD program, so one writer suffices and NFS cache
dirs see no cross-rank write races. Non-zero ranks get the read-only
behavior by an effectively-infinite ``min_compile_time`` floor (JAX
has no explicit read-only switch; a cache write only happens for
compiles slower than the floor).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..diagnostics import trace as _trace
from .store import rank_writes

__all__ = ["compile_cache_dir", "maybe_enable_compile_cache"]

_LOCK = threading.Lock()
_enabled_dir: Optional[str] = None


def compile_cache_dir() -> Optional[str]:
    """``PYLOPS_MPI_TPU_COMPILE_CACHE`` (a directory), or ``None``."""
    return os.environ.get("PYLOPS_MPI_TPU_COMPILE_CACHE") or None


def maybe_enable_compile_cache(path: Optional[str] = None
                               ) -> Optional[str]:
    """Point ``jax_compilation_cache_dir`` at the configured directory
    (idempotent; process-wide). Called at package import so every
    entry point — tests, bench, workers, the serving daemon — shares
    the job's cache without per-call wiring. Returns the enabled dir
    or ``None`` (unset env, or jax too old to have the knobs — a
    config failure is traced and swallowed, never fatal)."""
    global _enabled_dir
    path = path or compile_cache_dir()
    if not path:
        return None
    with _LOCK:
        if _enabled_dir == path:
            return path
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            if rank_writes():
                # bank every compile, however fast: CPU-sim programs
                # compile in ms and the defaults would skip them all
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
                try:
                    jax.config.update(
                        "jax_persistent_cache_min_entry_size_bytes", 0)
                except Exception:
                    pass  # knob landed after the min-time one
            else:
                # read-only rank: reads always hit; a write would need
                # a compile slower than this floor
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    1e9)
            _enabled_dir = path
            _trace.event("aot.compile_cache", cat="aot", path=path,
                         writer=rank_writes())
            return path
        except Exception as e:
            _trace.event("aot.cache_error", cat="aot", path=path,
                         why=f"compile cache enable failed: {e!r}")
            return None

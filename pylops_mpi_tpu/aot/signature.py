"""Compile-relevant signatures for the AOT executable bank.

A serialized executable is only valid in an environment that would
have produced the same lowered program: same jax version, same
backend/chip kind, same device count, same precision pins, same
compile-relevant knob states. The bank stores
:func:`compile_signature` next to every entry and the loader compares
field-by-field — ANY mismatch is a classified miss that falls back to
fresh compile (never a crash, never a stale program). The operator
itself enters the key through :func:`op_signature`, a structural
fingerprint that survives process restarts (``id(Op)`` — the in-memory
fused-cache key — does not).
"""

import os
from typing import Any, Dict, Tuple

# Env knobs whose value changes the TRACED fused program (directly or
# through the builders _get_fused wraps). Guards/telemetry/stall/
# donation state already ride the fused-cache key itself; these are
# the ambient ones a key built in another process could silently
# disagree on.
_COMPILE_KNOBS = (
    "PYLOPS_MPI_TPU_X64",
    "PYLOPS_MPI_TPU_MATMUL_PRECISION",
    "PYLOPS_MPI_TPU_EXPLICIT_STENCIL",
    "PYLOPS_MPI_TPU_OVERLAP",
    "PYLOPS_MPI_TPU_COMM_CHUNKS",
    "PYLOPS_MPI_TPU_HIERARCHICAL",
    "PYLOPS_MPI_TPU_FABRIC",
    "PYLOPS_MPI_TPU_CA",
    "PYLOPS_MPI_TPU_CA_S",
)


def compile_signature() -> Dict[str, Any]:
    """The environment fingerprint stored with (and checked against)
    every banked executable. Keys are plain JSON scalars so the
    signature round-trips through the index file unchanged."""
    import jax
    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "n_devices": jax.device_count(),
        "n_processes": int(os.environ.get(
            "PYLOPS_MPI_TPU_NUM_PROCESSES", "1") or "1"),
        "x64": bool(jax.config.jax_enable_x64),
        "topology": _topology_key(),
        "knobs": {k: os.environ.get(k, "") for k in _COMPILE_KNOBS},
    }


def _topology_key() -> str:
    """The fabric topology key when the mesh module can produce one
    (hybrid dcn x ici classification), else the flat device count."""
    try:
        import jax
        from ..parallel.topology import topology_key
        from ..parallel.mesh import default_mesh
        return str(topology_key(default_mesh()))
    except Exception:
        try:
            import jax
            return f"flat{jax.device_count()}"
        except Exception:
            return "unknown"


def op_signature(Op) -> Tuple:
    """Structural fingerprint of a jit-argument operator: class name,
    logical shape/dtype, and the avals of its registered device-buffer
    leaves. Two operator INSTANCES with the same signature lower to
    the same program (their buffers are runtime arguments, not baked
    constants), which is exactly what lets a fresh process reuse an
    executable banked by a dead one. Operators may override with an
    ``aot_signature()`` method when structure alone under-determines
    the trace."""
    hook = getattr(Op, "aot_signature", None)
    if callable(hook):
        return ("custom", type(Op).__name__, tuple(hook()))
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(Op)
    avals = tuple(
        (tuple(getattr(leaf, "shape", ())), str(getattr(leaf, "dtype",
                                                        type(leaf))))
        for leaf in leaves)
    return (type(Op).__name__, tuple(Op.shape), str(Op.dtype), avals)


def args_avals(args) -> Tuple:
    """Shape/dtype fingerprint of the flat runtime operands — banked
    next to the signature so a key collision across differently-shaped
    problems is caught BEFORE deserialization (the executable's own
    aval check at call time is the second fence)."""
    import jax
    flat, _ = jax.tree_util.tree_flatten((tuple(args), {}))
    return tuple(
        (tuple(getattr(leaf, "shape", ())), str(getattr(leaf, "dtype",
                                                        type(leaf))))
        for leaf in flat)

"""Serialize, load, and replay compiled fused-solver executables.

The replay path is FLAT CALL, not ``Compiled.__call__``: a jitted
program whose operator enters as a pytree argument stores a shallow
copy of that operator inside its input treedef, and treedef equality
on operator aux data is identity-based — so ``Compiled.__call__``
rejects even the in-process round trip. Instead we flatten the live
operands ourselves, invoke the loaded ``MeshExecutable`` directly,
and unflatten through the banked OUTPUT treedef (whose aux data —
meshes, shardings — serializes fine through the PJRT pickler's device
hooks). The executable re-validates operand avals on every call, so a
stale banked program can raise but never silently compute the wrong
thing; any such raise falls back to a fresh compile.

``compile_count()`` counts fresh XLA compiles performed by this seam —
the CI ``test-aot`` leg pins it to ZERO on a replay run against a
seeded bank.
"""

from __future__ import annotations

import io
import pickle
import threading
import time
from typing import Any, Optional, Tuple

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from . import signature as _sig
from . import store as _store

__all__ = ["AotExecutable", "compile_count", "reset_compile_count",
           "serialize_compiled", "load_serialized", "maybe_aot_fused"]

_COUNT_LOCK = threading.Lock()
_COMPILE_COUNT = 0


def compile_count() -> int:
    """Fresh XLA compiles performed by the AOT seam in this process
    (bank loads do NOT count — that is the point)."""
    return _COMPILE_COUNT


def reset_compile_count() -> None:
    global _COMPILE_COUNT
    with _COUNT_LOCK:
        _COMPILE_COUNT = 0


def _bump_compiles() -> None:
    global _COMPILE_COUNT
    with _COUNT_LOCK:
        _COMPILE_COUNT += 1
    _metrics.inc("aot.compiles")


class AotExecutable:
    """A loaded executable plus the banked output treedef. ``banked``
    records provenance (``True`` = deserialized from the bank, eligible
    for the stale-program fallback; ``False`` = freshly compiled in
    this process)."""

    __slots__ = ("exe", "out_tree", "banked")

    def __init__(self, exe, out_tree, banked: bool):
        self.exe = exe
        self.out_tree = out_tree
        self.banked = banked

    def call(self, args: Tuple):
        """Flat-call ``args`` (the FULL jit operand tuple, operator
        included) and unflatten through the banked output treedef."""
        import jax
        flat, _ = jax.tree_util.tree_flatten((tuple(args), {}))
        out_flat = self.exe.call(*flat)
        return jax.tree_util.tree_unflatten(self.out_tree, out_flat)


def serialize_compiled(compiled) -> Tuple[bytes, bytes]:
    """``(payload, out_tree_bytes)`` for a ``jax.stages.Compiled``.
    The payload is PJRT executable serialization
    (``jax.experimental.serialize_executable``); the output treedef is
    pickled through the same device-aware pickler (its aux data holds
    meshes/shardings, which plain pickle rejects)."""
    from jax.experimental import serialize_executable as se
    payload, _in_tree, out_tree = se.serialize(compiled)
    buf = io.BytesIO()
    se._JaxPjrtPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(
        out_tree)
    return payload, buf.getvalue()


def load_serialized(payload: bytes, out_tree_bytes: bytes
                    ) -> AotExecutable:
    """Deserialize a banked payload into a live ``MeshExecutable`` on
    this process's backend. Raises on any mismatch — the caller
    classifies the failure and falls back to fresh compile."""
    import jax
    from jax.experimental import serialize_executable as se
    backend = jax.devices()[0].client
    unloaded, _args_info, _kwargs = se._JaxPjrtUnpickler(
        io.BytesIO(payload), backend).load()
    exe = unloaded.load()
    out_tree = se._JaxPjrtUnpickler(io.BytesIO(out_tree_bytes),
                                    backend).load()
    return AotExecutable(exe, out_tree, banked=True)


class _AotFused:
    """The callable ``_get_fused`` returns on the AOT path for a
    jit-argument operator: resolves its executable lazily on first
    call (memory tier → disk bank → fresh compile), then flat-calls
    it. Matches the off-path calling convention exactly — invoked with
    the runtime operands only, the operator bound at construction."""

    def __init__(self, jfn, op, bank_key: Tuple):
        self._jfn = jfn
        self._op = op
        self._bank_key = bank_key
        self._exe: Optional[AotExecutable] = None

    def __call__(self, *operands):
        args = (self._op,) + operands
        if self._exe is None:
            self._exe = _resolve(self._jfn, self._bank_key, args)
        try:
            return self._exe.call(args)
        except Exception as e:
            if not self._exe.banked:
                raise
            # a banked program this environment cannot actually run
            # (the executable's own aval fence) — never serve it;
            # recompile fresh and retry once. The failed call
            # validated avals before executing, so no operand buffer
            # was consumed.
            _trace.event("aot.cache_error", cat="aot",
                         path=str(_store.bank_dir() or "<memory>"),
                         why=f"banked executable rejected at call "
                             f"time: {e!r}")
            self._exe = _fresh_compile(self._jfn, self._bank_key, args)
            return self._exe.call(args)


def _resolve(jfn, bank_key: Tuple, args: Tuple) -> AotExecutable:
    """Memory tier → disk bank → fresh compile, with classified
    hit/miss metrics at each step."""
    mem = _store.mem_get(bank_key)
    if mem is not None:
        _metrics.inc("aot.cache.hit")
        _trace.event("aot.hit", cat="aot", tier="memory")
        return mem
    sig = _sig.compile_signature()
    avals = _sig.args_avals(args)
    banked = _store.lookup(bank_key, sig, avals)
    if banked is not None:
        payload, out_tree_bytes, entry = banked
        t0 = time.perf_counter()
        try:
            exe = load_serialized(payload, out_tree_bytes)
        except Exception as e:  # undeserializable blob: classified miss
            _store._cache_error(str(_store.bank_dir() or "<memory>"),
                                f"deserialize failed: {e!r}")
        else:
            load_s = time.perf_counter() - t0
            _metrics.inc("aot.cache.hit")
            _metrics.observe("aot.load_s", load_s)
            _trace.event("aot.hit", cat="aot", tier="disk",
                         load_s=round(load_s, 4),
                         compile_s_saved=entry.get("compile_s"))
            _store.mem_put(bank_key, exe)
            return exe
    _metrics.inc("aot.cache.miss")
    return _fresh_compile(jfn, bank_key, args)


def _fresh_compile(jfn, bank_key: Tuple, args: Tuple) -> AotExecutable:
    """Lower+compile the fused program explicitly (so the executable
    object is ours to serialize), bank it best-effort, and return it
    for flat-call replay."""
    t0 = time.perf_counter()
    compiled = jfn.lower(*args).compile()
    compile_s = time.perf_counter() - t0
    _bump_compiles()
    _metrics.observe("aot.compile_s", compile_s)
    _trace.event("aot.compile", cat="aot",
                 compile_s=round(compile_s, 4))
    out_tree = None
    try:
        payload, out_tree_bytes = serialize_compiled(compiled)
        # Store-time round-trip fence: an executable XLA itself served
        # from its persistent compilation cache can serialize into a
        # payload that does NOT deserialize ("Symbols not found" on the
        # CPU backend). Verify before banking so no later process has
        # to fail the deserialize first and fall back every cold start.
        out_tree = load_serialized(payload, out_tree_bytes).out_tree
        _store.store_entry(bank_key, _sig.compile_signature(),
                           _sig.args_avals(args), payload,
                           out_tree_bytes, compile_s)
    except Exception as e:  # serialization is best-effort
        _trace.event("aot.cache_error", cat="aot",
                     path=str(_store.bank_dir() or "<memory>"),
                     why=f"serialize/round-trip failed; not banked: "
                         f"{e!r}")
    if out_tree is None:
        # fall back to flattening a throwaway jaxpr-free structure:
        # the Compiled wrapper knows its own output treedef
        out_tree = compiled.out_tree
    exe = AotExecutable(compiled._executable, out_tree, banked=False)
    _store.mem_put(bank_key, exe)
    return exe


def maybe_aot_fused(jfn, op, key: Tuple) -> Optional[Any]:
    """The seam ``solvers/basic.py:_get_fused`` calls on the
    jit-argument branch. Returns an ``_AotFused`` callable when the
    AOT tier is armed, else ``None`` (the off path — bit-identical to
    the pre-AOT build). ``key`` is the fused-cache key whose first
    element is ``id(op)``; the bank key replaces it with the
    structural :func:`~pylops_mpi_tpu.aot.signature.op_signature` so a
    fresh process (new instance, same program) can hit."""
    if not _store.aot_enabled():
        return None
    bank_key = (_sig.op_signature(op),) + tuple(key[1:])
    return _AotFused(jfn, op, bank_key)

"""Ahead-of-time compile tier: persistent executables for the fused
solver programs.

Every runtime tier so far still pays full XLA compile cost at process
start — the serving WarmPool compiles each (family, K-bucket) at
daemon boot, the tuner recompiles every candidate per trial, and a
supervisor relaunch recompiles the whole solver on the recovery
critical path. This package makes the compiled executable itself a
persistent, cacheable artifact the same way ``tuning/cache.py`` made
schedules one:

- :mod:`~pylops_mpi_tpu.aot.executable` — lower the fused program
  once, serialize the compiled executable via
  ``jax.experimental.serialize_executable`` (PJRT executable
  serialization), and replay it through the flat-call path on the
  next process start;
- :mod:`~pylops_mpi_tpu.aot.store` — a schema-versioned atomic
  on-disk bank keyed like the plan cache plus the compile-relevant
  signature (jax version, backend/chip kind, mesh size, topology key,
  dtype/precision, guard/CA/telemetry knob states). Corrupt,
  truncated, or signature-mismatched entries fall back to fresh
  compile with a traced ``aot.cache_error`` event;
- :mod:`~pylops_mpi_tpu.aot.compile_cache` — JAX's persistent
  compilation cache (``PYLOPS_MPI_TPU_COMPILE_CACHE``) as the
  fallback layer for programs we don't explicitly serialize
  (closure-captured operators, preconditioned solves, ISTA/FISTA).

``PYLOPS_MPI_TPU_AOT=off`` (the default) is bit-identical to the
pre-AOT build: the seam in ``solvers/basic.py:_get_fused`` contributes
nothing to the traced program or its cache keys (pinned by
tests/test_aot.py). See docs/aot.md.
"""

from .store import (SCHEMA_VERSION, aot_mode, aot_enabled, bank_dir,
                    clear_memory, load_index, store_entry, lookup,
                    rank_writes)
from .signature import compile_signature, op_signature
from .executable import (AotExecutable, compile_count,
                         reset_compile_count, serialize_compiled,
                         load_serialized, maybe_aot_fused)
from .compile_cache import (maybe_enable_compile_cache,
                            compile_cache_dir)

__all__ = [
    "SCHEMA_VERSION", "aot_mode", "aot_enabled", "bank_dir",
    "clear_memory", "load_index", "store_entry", "lookup",
    "rank_writes", "compile_signature", "op_signature",
    "AotExecutable", "compile_count", "reset_compile_count",
    "serialize_compiled", "load_serialized", "maybe_aot_fused",
    "maybe_enable_compile_cache", "compile_cache_dir",
]

"""Namespace parity with ``pylops_mpi.signalprocessing``."""
from ..ops.fft import MPIFFTND, MPIFFT2D
from ..ops.fredholm import MPIFredholm1
from ..ops.nonstatconv import MPINonStationaryConvolve1D

"""DistributedArray: a mesh-sharded ndarray with the reference's semantics.

TPU-native rebuild of ``pylops_mpi/DistributedArray.py`` (ref lines
26-960). The reference is SPMD: every MPI rank owns one shard and all
wire traffic is explicit (allreduce for ``dot``/``norm``, p2p for ghost
cells, pairwise sendrecv for ``redistribute``). Here a single controller
holds one :class:`jax.Array` laid out over a :class:`jax.sharding.Mesh`
with a :class:`NamedSharding`; elementwise arithmetic, reductions and
reshards are plain ``jnp`` ops whose collectives XLA's partitioner emits
over ICI.

**Physical layout.** XLA requires equal per-device shards, so the
partition axis is always laid out as ``P`` blocks of ``s_phys`` rows:
``s_phys = max(local sizes)``, zero-padded per shard when the logical
split is uneven (exactly the pad-to-max strategy the reference's NCCL
path uses for ragged allgathers, ``utils/_nccl.py:363-403``). In the
common even case the physical and logical arrays coincide and no padding
or masking exists anywhere on the hot path. Reductions apply static
valid-masks derived from ``local_shapes`` metadata.

Semantics preserved from the reference:

- the :class:`Partition` placement model and balanced remainder split
  (ref ``DistributedArray.py:26-71``), including user-specified ragged
  ``local_shapes``;
- ``to_dist`` / ``asarray`` scatter/gather (ref ``408-461``, ``371-406``);
- arithmetic / ``dot`` / ``norm`` for all orders incl. 0 and ±inf
  (ref ``588-808``);
- ``mask`` sub-communicator groups: reductions per rank-group
  (ref ``74-100``) — realised as static segment reductions over the
  shard blocks rather than ``Comm.Split``;
- shard-major ``ravel`` (ref ``847-875``), ``add_ghost_cells``
  (ref ``877-954``) and ``redistribute`` (ref ``463-522``).

Deliberate semantic departures (documented, not bugs):

- ``BROADCAST`` vs ``UNSAFE_BROADCAST`` coincide: a replicated JAX array
  cannot drift between devices, so rank-0 write-resync
  (ref ``207-220``) has no analog.
- reductions return results in the array's real dtype (f64 only under
  ``jax_enable_x64``) instead of always-f64.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .parallel.mesh import default_mesh, axis_sharding, replicated_sharding
from .parallel.partition import (Partition, local_split, pad_index_map,
                                 unpad_index_map)

__all__ = ["DistributedArray", "Partition", "local_split"]


NDArrayLike = Union[np.ndarray, jax.Array]


def _sorted_colors(mask: Sequence[int]) -> List[Any]:
    seen = []
    for c in mask:
        if c not in seen:
            seen.append(c)
    return sorted(seen)


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class DistributedArray:
    """Mesh-sharded array (ref ``pylops_mpi/DistributedArray.py:74-960``).

    Parameters
    ----------
    global_shape : tuple or int
        Logical global shape.
    mesh : jax.sharding.Mesh, optional
        1-D device mesh (defaults to the process-wide mesh over all
        devices). Plays the role of ``base_comm``.
    partition : Partition
        Placement policy (SCATTER / BROADCAST / UNSAFE_BROADCAST).
    axis : int
        Sharded dimension for SCATTER.
    local_shapes : list of tuples, optional
        Logical per-shard shapes (defaults to the balanced split,
        ref ``DistributedArray.py:42-71``). May be ragged along ``axis``.
    mask : list of int, optional
        Group color per shard; ``dot``/``norm`` reduce within groups
        (ref ``DistributedArray.py:74-100``).
    dtype : dtype, optional
    """

    def __init__(self, global_shape, mesh: Optional[Mesh] = None,
                 partition: Partition = Partition.SCATTER, axis: int = 0,
                 local_shapes: Optional[Sequence[Tuple[int, ...]]] = None,
                 mask: Optional[Sequence[int]] = None,
                 dtype=None):
        if isinstance(global_shape, (int, np.integer)):
            global_shape = (int(global_shape),)
        global_shape = tuple(int(s) for s in global_shape)
        if partition not in Partition:
            raise ValueError(f"Should be one of {[p for p in Partition]}")
        if axis < 0:
            axis += len(global_shape)
        if partition == Partition.SCATTER and not (0 <= axis < len(global_shape)):
            raise IndexError(f"axis {axis} out of range for shape {global_shape}")
        self._mesh = mesh if mesh is not None else default_mesh()
        self._n_shards = int(self._mesh.devices.size)
        self._partition = partition
        self._axis = int(axis)
        self._global_shape = global_shape
        if local_shapes is None:
            local_shapes = local_split(global_shape, self._n_shards, partition, axis)
        else:
            local_shapes = tuple(tuple(int(v) for v in np.atleast_1d(s)) for s in local_shapes)
            if len(local_shapes) != self._n_shards:
                raise ValueError(f"need {self._n_shards} local shapes, got {len(local_shapes)}")
            if partition == Partition.SCATTER:
                tot = sum(s[axis] for s in local_shapes)
                if tot != global_shape[axis]:
                    raise ValueError(
                        f"local shapes sum to {tot} != global dim {global_shape[axis]}")
        self._local_shapes = local_shapes
        if mask is not None:
            mask = tuple(mask)
            if len(mask) != self._n_shards:
                raise ValueError(f"mask must have {self._n_shards} entries")
        self._mask = mask
        dtype = jnp.zeros(0, dtype=dtype).dtype if dtype is not None else jnp.zeros(0).dtype
        self._arr = lax.with_sharding_constraint(
            jnp.zeros(self._phys_shape(), dtype=dtype), self._sharding())

    # -------------------------------------------------------------- layout
    @property
    def _axis_sizes(self) -> Tuple[int, ...]:
        """Logical per-shard size along the partition axis."""
        return tuple(s[self._axis] for s in self._local_shapes)

    @property
    def _s_phys(self) -> int:
        return max(self._axis_sizes) if self._axis_sizes else 0

    @property
    def _even(self) -> bool:
        """True when the logical split is the uniform one (physical ==
        logical, no padding anywhere)."""
        sizes = self._axis_sizes
        return self._partition != Partition.SCATTER or len(set(sizes)) == 1

    def _phys_shape(self) -> Tuple[int, ...]:
        if self._partition != Partition.SCATTER:
            return self._global_shape
        shp = list(self._global_shape)
        shp[self._axis] = self._n_shards * self._s_phys
        return tuple(shp)

    def _sharding(self) -> NamedSharding:
        if self._partition == Partition.SCATTER:
            return axis_sharding(self._mesh, len(self._global_shape), self._axis)
        return replicated_sharding(self._mesh)

    def _place(self, arr: jax.Array) -> jax.Array:
        """Pin physical placement (constraint under trace, device_put when
        concrete)."""
        sh = self._sharding()
        if _is_tracer(arr):
            return lax.with_sharding_constraint(arr, sh)
        return jax.device_put(arr, sh)

    def _from_global(self, garr: jax.Array) -> jax.Array:
        """Logical global → physical (pad each shard to ``s_phys``): one
        static-index ``take`` + zero mask; the traced program is
        P-independent (round-1 VERDICT weak #6 replaced a per-shard
        slice/pad/concat loop here)."""
        if self._even:
            return garr
        src, valid = pad_index_map(self._axis_sizes, self._s_phys)
        out = jnp.take(garr, jnp.asarray(src), axis=self._axis)
        mshape = [1] * self.ndim
        mshape[self._axis] = len(valid)
        return jnp.where(jnp.asarray(valid).reshape(mshape), out,
                         jnp.zeros((), dtype=out.dtype))

    def _global(self) -> jax.Array:
        """Physical → logical global (strip padding): one static-index
        ``take``. Jit-safe, P-independent trace."""
        if self._even:
            return self._arr
        idx = unpad_index_map(self._axis_sizes, self._s_phys)
        return jnp.take(self._arr, jnp.asarray(idx), axis=self._axis)

    def _valid_mask_blocks(self) -> Optional[np.ndarray]:
        """(P, s_phys) bool mask of logically-valid rows; None if even."""
        if self._even:
            return None
        sizes = np.asarray(self._axis_sizes)
        return np.arange(self._s_phys)[None, :] < sizes[:, None]

    def _valid_phys_mask(self) -> jax.Array:
        """Bool mask over the physical array marking logically-valid
        entries (broadcast along non-partition dims)."""
        vm = self._valid_mask_blocks()
        shape = [1] * self.ndim
        shape[self._axis] = self._n_shards * self._s_phys
        return jnp.asarray(vm.reshape(-1)).reshape(shape)

    @classmethod
    def _wrap(cls, arr: jax.Array, like: "DistributedArray", *,
              partition=None, axis=None, local_shapes=None, mask=None,
              global_shape=None, keep_mask: bool = True) -> "DistributedArray":
        """Internal jit-safe constructor from a *physical* array."""
        out = cls.__new__(cls)
        out._mesh = like._mesh
        out._n_shards = like._n_shards
        out._partition = partition if partition is not None else like._partition
        out._axis = axis if axis is not None else like._axis
        out._global_shape = tuple(global_shape) if global_shape is not None else like._global_shape
        out._local_shapes = tuple(tuple(s) for s in local_shapes) if local_shapes is not None \
            else like._local_shapes
        out._mask = mask if mask is not None else (like._mask if keep_mask else None)
        out._arr = arr
        return out

    # ---------------------------------------------------------- properties
    @property
    def global_shape(self) -> Tuple[int, ...]:
        return self._global_shape

    @property
    def local_shapes(self) -> Tuple[Tuple[int, ...], ...]:
        return self._local_shapes

    @property
    def local_shape(self) -> Tuple[int, ...]:
        # shard-0 logical shape (the reference reports the calling rank's)
        return self._local_shapes[0]

    @property
    def partition(self) -> Partition:
        return self._partition

    @property
    def axis(self) -> int:
        return self._axis

    @property
    def mask(self):
        return self._mask

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def n_shards(self) -> int:
        return self._n_shards

    @property
    def dtype(self):
        return self._arr.dtype

    @property
    def ndim(self) -> int:
        return len(self._global_shape)

    @property
    def size(self) -> int:
        return int(np.prod(self._global_shape))

    @property
    def array(self) -> jax.Array:
        """The logical global (sharded) jax.Array."""
        return self._global()

    @property
    def engine(self) -> str:
        return "jax"

    # ------------------------------------------------------ create/gather
    @classmethod
    def to_dist(cls, x: NDArrayLike, mesh: Optional[Mesh] = None,
                partition: Partition = Partition.SCATTER, axis: int = 0,
                local_shapes=None, mask=None) -> "DistributedArray":
        """Scatter a global array over the mesh
        (ref ``DistributedArray.py:408-461``; there every rank holds the
        full ``x`` and slices its shard — here the controller places it
        once with ``jax.device_put``)."""
        host_src = isinstance(x, np.ndarray)
        if not host_src:
            x = jnp.asarray(x)
        dtype = jax.dtypes.canonicalize_dtype(x.dtype)
        out = cls(global_shape=x.shape, mesh=mesh, partition=partition,
                  axis=axis, local_shapes=local_shapes, mask=mask,
                  dtype=dtype)
        if host_src and not out._even:
            # Uneven split from a host array: cast to the canonical
            # dtype first (half the traffic when x64 is off), then pack
            # to the padded physical layout with the native (C++) host
            # runtime in one threaded pass instead of tracing per-shard
            # pad+concat.
            from . import native
            phys = native.pack_padded(np.asarray(x, dtype=dtype), out._axis,
                                      out._axis_sizes, out._s_phys)
            out._arr = out._place(jnp.asarray(phys))
        else:
            out._arr = out._place(out._from_global(jnp.asarray(x)))
        return out

    def asarray(self) -> np.ndarray:
        """Gather the global array to host
        (ref ``DistributedArray.py:371-406``)."""
        if not self._even:
            # Pull the padded physical buffer once and strip padding on
            # host with the native runtime (threaded memcpy) rather than
            # compiling a per-shard slice+concat gather.
            from . import native
            phys = np.asarray(jax.device_get(self._arr))
            return native.unpack_padded(phys, self._axis, self._axis_sizes,
                                        self._s_phys)
        return np.asarray(jax.device_get(self._global()))

    def local_arrays(self) -> List[np.ndarray]:
        """Per-shard views under the logical split — debug/parity helper
        standing in for the reference's per-rank ``local_array``. For
        non-SCATTER partitions this materializes P host copies of the
        full array (warned above 256 MB total) — prefer ``asarray()``
        when one copy is enough."""
        if self._partition != Partition.SCATTER:
            g = self.asarray()
            if g.nbytes * self._n_shards > 256 * 1024 ** 2:
                import warnings
                warnings.warn(
                    f"local_arrays on a {self._partition.name} array "
                    f"copies all {g.nbytes >> 20} MB x {self._n_shards} "
                    "shards to host; use asarray() for one copy",
                    stacklevel=2)
            return [g.copy() for _ in range(self._n_shards)]
        phys = np.asarray(jax.device_get(self._arr))
        sp = self._s_phys
        out = []
        for i, n in enumerate(self._axis_sizes):
            idx = [slice(None)] * self.ndim
            idx[self._axis] = slice(i * sp, i * sp + n)
            out.append(phys[tuple(idx)])
        return out

    # --------------------------------------------------------- get / set
    def __getitem__(self, key):
        return self._global()[key]

    def __setitem__(self, key, value):
        """Functional update on the logical global view. The reference's
        per-rank ``arr[:] = local`` + rank-0 re-broadcast
        (ref ``DistributedArray.py:207-220``) has no analog — there is a
        single consistent value."""
        if key == slice(None, None, None):
            v = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype),
                                 self._global_shape)
            self._arr = self._place(self._from_global(v))
        else:
            g = self._global().at[key].set(value)
            self._arr = self._place(self._from_global(g))

    def fill(self, value) -> None:
        self[:] = value

    # --------------------------------------------------------- arithmetic
    def _check_compat(self, other: "DistributedArray") -> None:
        if self._global_shape != other._global_shape:
            raise ValueError(
                f"Global shape mismatch {self._global_shape} != {other._global_shape}")
        if self._partition != other._partition:
            raise ValueError(
                f"Partition mismatch {self._partition} != {other._partition}")
        if self._mask != other._mask:
            raise ValueError("Mask mismatch")

    def _group_ids_per_shard(self) -> np.ndarray:
        colors = _sorted_colors(self._mask)
        cmap = {c: i for i, c in enumerate(colors)}
        return np.asarray([cmap[c] for c in self._mask])

    def _expand_group_scalars(self, s: jax.Array) -> jax.Array:
        """Broadcast a (ngroups,) vector of per-group scalars across the
        physical partition axis, constant within each shard's group —
        the one-controller analog of each rank using its own group's
        reduction result."""
        per_shard = s[jnp.asarray(self._group_ids_per_shard())]      # (P,)
        per_index = jnp.repeat(per_shard, self._s_phys,
                               total_repeat_length=self._n_shards * self._s_phys)
        shape = [1] * self.ndim
        shape[self._axis] = per_index.shape[0]
        return per_index.reshape(shape)

    def _operand_phys(self, x: "DistributedArray") -> jax.Array:
        """Other-array physical buffer in *this* array's layout. Arrays
        split differently (axis or shard sizes) repack through the
        logical view (the reference instead raises — rebalancing is the
        @reshaped decorator's job there, ref utils/decorators.py:9-86)."""
        self._check_compat(x)
        if x._axis != self._axis or x._axis_sizes != self._axis_sizes:
            return self._from_global(x._global())
        return x._arr

    def _coerce_operand(self, x):
        if isinstance(x, DistributedArray):
            return self._operand_phys(x)
        if isinstance(x, (jax.Array, np.ndarray)) and np.ndim(x) == 1 \
                and self._mask is not None \
                and self._partition == Partition.SCATTER \
                and x.shape[0] == len(_sorted_colors(self._mask)) \
                and x.shape != self._global_shape:
            # per-group scalars from a masked dot/norm
            return self._expand_group_scalars(jnp.asarray(x))
        return x

    def add(self, x):
        return DistributedArray._wrap(self._arr + self._coerce_operand(x), self)

    def iadd(self, x):
        self._arr = self._arr + self._coerce_operand(x)
        return self

    def multiply(self, x):
        return DistributedArray._wrap(self._arr * self._coerce_operand(x), self)

    def __add__(self, x):
        return self.add(x)

    def __radd__(self, x):
        return self.add(x)

    def __iadd__(self, x):
        return self.iadd(x)

    def __sub__(self, x):
        return DistributedArray._wrap(self._arr - self._coerce_operand(x), self)

    def __rsub__(self, x):
        return DistributedArray._wrap(self._coerce_operand(x) - self._arr, self)

    def __isub__(self, x):
        self._arr = self._arr - self._coerce_operand(x)
        return self

    def __mul__(self, x):
        return self.multiply(x)

    def __rmul__(self, x):
        return self.multiply(x)

    def __truediv__(self, x):
        if self._even:
            return DistributedArray._wrap(self._arr / self._coerce_operand(x), self)
        # guard 0/0 only in the pad region (valid zeros must still -> inf/nan)
        num, den = self._arr, self._coerce_operand(x)
        vm = self._valid_phys_mask()
        out = jnp.where(vm, num / jnp.where(vm, den, 1), 0)
        return DistributedArray._wrap(out, self)

    def __neg__(self):
        return DistributedArray._wrap(-self._arr, self)

    # --------------------------------------------------------- reductions
    def _shard_partials(self, z: jax.Array, op: str, fill) -> jax.Array:
        """Reduce a physical array to one partial per shard: reshape the
        partition axis into (P, s_phys) blocks, mask padding, reduce
        everything but the shard axis."""
        zb = jnp.moveaxis(z, self._axis, 0)
        zb = zb.reshape((self._n_shards, self._s_phys) + zb.shape[1:])
        vm = self._valid_mask_blocks()
        if vm is not None:
            mshape = (self._n_shards, self._s_phys) + (1,) * (zb.ndim - 2)
            zb = jnp.where(jnp.asarray(vm).reshape(mshape), zb, fill)
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]
        return red(zb.reshape(self._n_shards, -1), axis=1)

    def _reduce(self, z: jax.Array, op: str, fill=0) -> jax.Array:
        """Full or per-group reduction of a physical elementwise array."""
        grouped = self._mask is not None and self._partition == Partition.SCATTER
        if not grouped and self._even:
            red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]
            return red(z)
        partials = self._shard_partials(z, op, fill)                  # (P,)
        if not grouped:
            red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]
            return red(partials)
        gid = jnp.asarray(self._group_ids_per_shard())
        ngroups = len(_sorted_colors(self._mask))
        f = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
             "min": jax.ops.segment_min}[op]
        return f(partials, gid, num_segments=ngroups)

    def dot(self, y: "DistributedArray", vdot: bool = False) -> jax.Array:
        """Distributed dot product (ref ``DistributedArray.py:655-687``):
        flatten, multiply, reduce — the reference's explicit allreduce
        over the sub-communicator becomes a (possibly segmented) sum the
        partitioner lowers to ``psum``. With a ``mask``, returns the
        vector of per-group scalars (each reference rank sees only its
        own group's value; here all groups are visible at once)."""
        a = jnp.conj(self._arr) if vdot else self._arr
        z = a * self._operand_phys(y)
        # narrow (bf16/f16) vector spaces accumulate at f32 — the
        # precision policy's reduction floor (ops/_precision.py); a
        # no-op cast for f32 and wider
        from .ops._precision import accum_dtype
        z = z.astype(accum_dtype(z.dtype))
        if self._partition != Partition.SCATTER:
            # BROADCAST ignores mask, as the reference's to_dist round-trip
            # in dot does (ref DistributedArray.py:678-682)
            return jnp.sum(z)
        return self._reduce(z, "sum")

    def col_dot(self, y: "DistributedArray", vdot: bool = False) -> jax.Array:
        """Per-column dot product of a block (column-batched) vector:
        for a ``(N, K)`` array sharded on axis 0 this reduces over the
        row axis only and returns the ``(K,)`` vector of column dots —
        the reduction the block-Krylov recurrences need (``dot`` would
        collapse the column axis too). Padding rows of a ragged split
        are masked out; accumulation uses the same precision-policy
        floor as ``dot``."""
        if self.ndim != 2:
            raise ValueError(
                f"col_dot needs a 2-D (rows, columns) array, got "
                f"global_shape={self._global_shape}")
        if self._axis != 0:
            raise ValueError("col_dot needs the row axis sharded (axis=0)")
        if self._mask is not None:
            raise NotImplementedError(
                "col_dot does not support masked (sub-communicator) arrays")
        a = jnp.conj(self._arr) if vdot else self._arr
        z = a * self._operand_phys(y)
        from .ops._precision import accum_dtype
        z = z.astype(accum_dtype(z.dtype))
        if self._partition == Partition.SCATTER and not self._even:
            z = jnp.where(self._valid_phys_mask(), z, 0)
        return jnp.sum(z, axis=0)

    def _vector_norm_flat(self, ord=None) -> jax.Array:
        """Whole-array vector norm, optionally per mask-group
        (ref ``_compute_vector_norm``, ``DistributedArray.py:689-759``)."""
        ord = 2 if ord is None else ord
        if ord in ("fro", "nuc"):
            raise ValueError(f"norm-{ord} not possible for vectors")
        x = self._arr
        # narrow (bf16/f16) spaces reduce at f32 — the precision
        # policy's reduction floor (ops/_precision.py); complex dtypes
        # are never sub-f32
        if not jnp.issubdtype(x.dtype, jnp.complexfloating):
            from .ops._precision import accum_dtype
            acc = accum_dtype(x.dtype)
            if acc != np.dtype(x.dtype):
                x = x.astype(acc)
        if self._partition != Partition.SCATTER:
            x2 = jnp.abs(x)
            if ord == 0:
                return jnp.count_nonzero(x).astype(x2.dtype)
            if ord == np.inf:
                return jnp.max(x2)
            if ord == -np.inf:
                return jnp.min(x2)
            return jnp.sum(x2 ** ord) ** (1.0 / ord)
        if ord == 0:
            return self._reduce((x != 0).astype(jnp.abs(x).dtype), "sum")
        if ord == np.inf:
            return self._reduce(jnp.abs(x), "max", fill=-np.inf)
        if ord == -np.inf:
            return self._reduce(jnp.abs(x), "min", fill=np.inf)
        return self._reduce(jnp.abs(x) ** ord, "sum") ** (1.0 / ord)

    def norm(self, ord=None, axis: Optional[int] = None) -> jax.Array:
        """Distributed ``numpy.linalg.norm``
        (ref ``DistributedArray.py:775-808``). ``axis=None`` flattens;
        ``axis=k`` computes vector norms along ``k`` (the distinction the
        reference draws between the sharded and local axes dissolves —
        XLA partitions either)."""
        if axis is None:
            return self._vector_norm_flat(ord)
        if axis >= self.ndim:
            raise ValueError(f"axis={axis} out of range for ndim={self.ndim}")
        return jnp.linalg.norm(self._global(), ord=ord, axis=axis)

    # ------------------------------------------------------------ algebra
    def conj(self) -> "DistributedArray":
        return DistributedArray._wrap(jnp.conj(self._arr), self)

    def copy(self) -> "DistributedArray":
        return DistributedArray._wrap(self._arr + 0, self)

    def zeros_like(self) -> "DistributedArray":
        return DistributedArray._wrap(jnp.zeros_like(self._arr), self)

    def empty_like(self) -> "DistributedArray":
        return self.zeros_like()

    def ravel(self, order: str = "C") -> "DistributedArray":
        """Shard-major flatten (ref ``DistributedArray.py:847-875``): the
        result is the concatenation of each shard's C-order ravel —
        identical to the global ravel when ``axis == 0``, a shard
        permutation of it otherwise, exactly as in the reference."""
        if order not in ("C", "K", "A"):
            raise NotImplementedError("only C-order ravel is supported")
        new_locals = tuple((int(np.prod(s)),) for s in self._local_shapes)
        if self._partition != Partition.SCATTER:
            arr = self._arr.reshape(-1)
            return DistributedArray._wrap(arr, self, axis=0,
                                          global_shape=(self.size,),
                                          local_shapes=new_locals)
        if self._axis == 0 and self.ndim == 1:
            return DistributedArray._wrap(self._arr, self,
                                          global_shape=(self.size,),
                                          local_shapes=new_locals)
        if self._axis == 0:
            # The physical C-order reshape IS the shard-major flatten,
            # even for ragged splits: each shard's padding rows are the
            # tail rows of its physical block, so they land at the tail
            # of its flat block — exactly the flat pad-to-max layout
            # (s_phys_flat = s_phys * inner). Zero comm, P-independent
            # trace.
            out = DistributedArray._wrap(
                self._arr.reshape(-1), self, axis=0,
                global_shape=(self.size,), local_shapes=new_locals)
            out._arr = out._place(out._arr)
            return out
        # axis != 0: per-shard ravels genuinely interleave; rare path
        # (the reshaped decorator redistributes to axis 0 before
        # ravelling on hot paths, ref utils/decorators.py:79-82)
        shards = []
        sp = self._s_phys
        for i, n in enumerate(self._axis_sizes):
            idx = [slice(None)] * self.ndim
            idx[self._axis] = slice(i * sp, i * sp + n)
            shards.append(self._arr[tuple(idx)].reshape(-1))
        g = jnp.concatenate(shards)
        out = DistributedArray._wrap(g, self, axis=0,
                                     global_shape=(self.size,),
                                     local_shapes=new_locals)
        out._arr = out._place(out._from_global(g))
        return out

    # ----------------------------------------------------- redistribution
    def redistribute(self, axis: int) -> "DistributedArray":
        """Change the sharded axis — the all-to-all pattern of
        ref ``DistributedArray.py:463-522``. Concrete arrays route
        through the bounded-memory resharding planner
        (:mod:`~pylops_mpi_tpu.parallel.reshard` — budget enforcement,
        chunked steps, ici/dcn byte attribution); traced arrays keep
        the original one-shot resharding placement so every existing
        jitted call site's HLO is bit-identical."""
        if self._partition != Partition.SCATTER:
            raise ValueError("redistribute only applies to SCATTER arrays")
        if axis == self._axis:
            return self.copy()
        if not _is_tracer(self._arr):
            from .parallel import reshard as _reshard
            return _reshard.reshard(self, axis=axis)
        out = DistributedArray._wrap(
            None, self, axis=axis,
            local_shapes=local_split(self._global_shape, self._n_shards,
                                     Partition.SCATTER, axis))
        out._arr = out._place(out._from_global(self._global()))
        return out

    def to_partition(self, partition: Partition,
                     axis: Optional[int] = None) -> "DistributedArray":
        """Convert between BROADCAST and SCATTER placements (the idiom at
        ref ``FirstDerivative.py:130-131``). Concrete arrays go through
        the resharding planner (see :meth:`redistribute`); traced
        arrays keep the original placement path."""
        axis = self._axis if axis is None else axis
        if not _is_tracer(self._arr):
            from .parallel import reshard as _reshard
            return _reshard.reshard(self, partition=partition, axis=axis)
        out = DistributedArray._wrap(
            None, self, partition=partition, axis=axis,
            local_shapes=local_split(self._global_shape, self._n_shards,
                                     partition, axis))
        out._arr = out._place(out._from_global(self._global()))
        return out

    def reshard(self, *, mesh=None, partition: Optional[Partition] = None,
                axis: Optional[int] = None, local_shapes=None,
                budget=..., chunks: Optional[int] = None
                ) -> "DistributedArray":
        """Move to any new layout — partition, axis, ragged split,
        and/or a different mesh (shrink/grow) — through the
        bounded-memory planner; peak scratch never exceeds ``budget``
        (default ``PYLOPS_MPI_TPU_RESHARD_BUDGET``). See
        :func:`pylops_mpi_tpu.parallel.reshard.reshard`."""
        from .parallel import reshard as _reshard
        if budget is ...:
            budget = _reshard._UNSET
        return _reshard.reshard(self, mesh=mesh, partition=partition,
                                axis=axis, local_shapes=local_shapes,
                                budget=budget, chunks=chunks)

    def to_host(self, *, budget=..., chunks: Optional[int] = None,
                overlap: Optional[str] = None):
        """Evacuate to host RAM as a
        :class:`~pylops_mpi_tpu.parallel.spill.HostArray` (layout
        metadata preserved), streaming chunk-at-a-time under the
        budget — the explicit spill of the round-14 host-staging tier.
        ``HostArray.to_device()`` is the inverse. See
        :func:`pylops_mpi_tpu.parallel.spill.to_host`."""
        from .parallel import reshard as _reshard
        from .parallel import spill as _spill
        if budget is ...:
            budget = _reshard._UNSET
        return _spill.to_host(self, budget=budget, chunks=chunks,
                              overlap=overlap)

    # -------------------------------------------------------- ghost cells
    def _ghost_widths(self, cells_front, cells_back):
        """Validated (front, back) widths with the reference's error
        text (ref ``DistributedArray.py:891-906``)."""
        front = int(cells_front) if cells_front else 0
        back = int(cells_back) if cells_back else 0
        sizes = self._axis_sizes
        for i in range(1, self._n_shards):
            if front > sizes[i - 1]:
                raise ValueError(
                    f"Local shape {sizes[i - 1]} along axis={self._axis} "
                    f"must be >= ghost width {front}")
        for i in range(self._n_shards - 1):
            if back > sizes[i + 1]:
                raise ValueError(
                    f"Local shape {sizes[i + 1]} along axis={self._axis} "
                    f"must be >= ghost width {back}")
        return front, back

    def ghosted(self, cells_front: Optional[int] = None,
                cells_back: Optional[int] = None) -> "DistributedArray":
        """Every shard extended with its neighbours' boundary rows —
        the reference's ghost-cell idiom for writing custom stencil
        operators (ref ``DistributedArray.py:877-954``, a p2p Send/Recv
        chain there), as ONE shard_map kernel whose only communication
        is the boundary-slab ``ppermute`` pair of
        :func:`~pylops_mpi_tpu.parallel.collectives.cart_halo_extend`
        (round-2 VERDICT weak #3 replaced a global-gather emulation
        here). Shard 0 gets no front ghost and shard P-1 no back ghost,
        so the result's per-shard shapes match the reference's ghosted
        ``local_array`` shapes exactly; the concatenation of shards is
        the returned SCATTER array of global length
        ``n + (P-1)*(front+back)``."""
        front, back = self._ghost_widths(cells_front, cells_back)
        if self._partition != Partition.SCATTER:
            raise ValueError("ghost cells apply to SCATTER arrays")
        P = self._n_shards
        ax = self._axis
        sizes = self._axis_sizes
        out_sizes = [(front if i > 0 else 0) + sizes[i]
                     + (back if i < P - 1 else 0) for i in range(P)]
        if P == 1 or (front == 0 and back == 0):
            return self.copy()
        if len(self._mesh.axis_names) != 1:
            raise ValueError("ghosted requires a 1-D mesh")
        out_locals = []
        for i, s in enumerate(self._local_shapes):
            shp = list(s)
            shp[ax] = out_sizes[i]
            out_locals.append(tuple(shp))
        out_gshape = list(self._global_shape)
        out_gshape[ax] = sum(out_sizes)
        sp = self._s_phys
        L_out = max(out_sizes)
        ragged = not self._even
        axis_name = self._mesh.axis_names[0]
        valid_tab = jnp.asarray(sizes, dtype=jnp.int32)
        out_valid_tab = jnp.asarray(out_sizes, dtype=jnp.int32)
        from .parallel.collectives import halo_slab
        from .jaxcompat import shard_map
        from jax.sharding import PartitionSpec as PSpec

        def _iota(shape):
            return lax.broadcasted_iota(jnp.int32, shape, ax)

        def kernel(b):
            idx = lax.axis_index(axis_name)
            valid = jnp.take(valid_tab, idx)
            zero = jnp.zeros((), b.dtype)
            if ragged:  # scrub pad-tail garbage before it is exchanged
                b = jnp.where(_iota(b.shape) < valid, b, zero)
            slab = halo_slab(b, axis_name, P, ax, front, back, valid,
                             sp, ragged)
            if front:
                # shard 0 has no front ghost: shift its content so valid
                # rows start at physical row 0 (ragged convention)
                padw = [(0, 0)] * slab.ndim
                padw[ax] = (0, front)
                ext = jnp.pad(slab, padw)
                start = [0] * slab.ndim
                start[ax] = jnp.where(idx == 0, front, 0)
                slab = lax.dynamic_slice(
                    ext, [jnp.asarray(s) for s in start], slab.shape)
            out = lax.slice_in_dim(slab, 0, L_out, axis=ax)
            # zero everything past this shard's ghosted length (pad
            # region + halo residue on edge/deficit shards)
            return jnp.where(_iota(out.shape) < jnp.take(out_valid_tab, idx),
                             out, zero)

        spec = [None] * self.ndim
        spec[ax] = axis_name
        arr = shard_map(kernel, mesh=self._mesh, in_specs=PSpec(*spec),
                        out_specs=PSpec(*spec), check_vma=False)(self._arr)
        out = DistributedArray._wrap(arr, self,
                                     global_shape=tuple(out_gshape),
                                     local_shapes=tuple(out_locals))
        return out

    def _ghost_cells_gather(self, cells_front, cells_back) -> List[jax.Array]:
        """Slice-from-global form: the mesh-shape-independent (and
        gather-scaling) fallback, kept for multi-axis meshes and as the
        oracle the ring-exchange kernel is tested against."""
        front, back = self._ghost_widths(cells_front, cells_back)
        sizes = self._axis_sizes
        offs = np.concatenate([[0], np.cumsum(sizes)])
        g = self._global()
        out = []
        for i in range(self._n_shards):
            lo = max(0, int(offs[i]) - (front if i > 0 else 0))
            hi = min(self._global_shape[self._axis],
                     int(offs[i + 1]) + (back if i < self._n_shards - 1 else 0))
            idx = [slice(None)] * self.ndim
            idx[self._axis] = slice(lo, hi)
            out.append(g[tuple(idx)])
        return out

    def add_ghost_cells(self, cells_front: Optional[int] = None,
                        cells_back: Optional[int] = None) -> List[jax.Array]:
        """Per-shard ghosted arrays as a host-side list
        (ref ``DistributedArray.py:877-954`` returns the per-rank
        ``local_array``). The device computation is the single
        ppermute-pair kernel of :meth:`ghosted` (one device_get plus
        host slicing); multi-axis (hybrid dcn×ici) meshes take the
        slice-from-global fallback, which has no mesh-shape
        dependence."""
        if (self._partition == Partition.SCATTER
                and len(self._mesh.axis_names) != 1):
            return self._ghost_cells_gather(cells_front, cells_back)
        return [jnp.asarray(a) for a in
                self.ghosted(cells_front, cells_back).local_arrays()]

    # ------------------------------------------------------------- pytree
    def tree_flatten(self):
        aux = (self._mesh, self._partition, self._axis, self._global_shape,
               self._local_shapes, self._mask)
        return (self._arr,), aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        out = cls.__new__(cls)
        (out._mesh, out._partition, out._axis, out._global_shape,
         out._local_shapes, out._mask) = aux
        out._n_shards = int(out._mesh.devices.size)
        out._arr = children[0]
        return out

    def __repr__(self):
        return (f"<DistributedArray global_shape={self._global_shape}, "
                f"partition={self._partition.name}, axis={self._axis}, "
                f"dtype={self.dtype}, devices={self._n_shards}>")


jax.tree_util.register_pytree_node(
    DistributedArray,
    lambda x: x.tree_flatten(),
    DistributedArray.tree_unflatten,
)

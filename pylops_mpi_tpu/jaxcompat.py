"""Version-tolerant imports for jax APIs that moved between releases.

The package targets current jax — where ``shard_map`` is a top-level
export and its replication check is spelled ``check_vma`` — but must
also import and run on the 0.4.x line, where it lives in
``jax.experimental.shard_map`` and the kwarg is ``check_rep`` (CI and
driver containers pin a different jax generation than the TPU bench
host). Only APIs the package actually consumes belong here; everything
else imports ``jax`` directly.
"""

from __future__ import annotations

import inspect

try:  # current jax: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # the 0.4.x line
    from jax.experimental.shard_map import shard_map as _shard_map

_SM_PARAMS = frozenset(inspect.signature(_shard_map).parameters)

__all__ = ["shard_map"]


def shard_map(f, /, **kwargs):
    """``jax.shard_map`` with the ``check_vma`` spelling accepted on
    every supported jax generation (pre-rename releases call the same
    switch ``check_rep``)."""
    if "check_vma" in kwargs and "check_vma" not in _SM_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)

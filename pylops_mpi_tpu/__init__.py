"""pylops_mpi_tpu — TPU-native distributed linear operators and solvers.

A ground-up rebuild of PyLops-MPI (reference ``pylops_mpi/__init__.py``)
for TPU: one controller drives a :class:`jax.sharding.Mesh`; MPI/NCCL
collectives become XLA ``psum``/``all_gather``/``all_to_all``/``ppermute``
over ICI/DCN; solver loops run on device as ``lax.while_loop``s.
"""

from .utils.deps import apply_environment as _apply_environment

# Honour the env seams (platform override, x64, matmul precision — the
# last pins true-f32 GEMMs on TPU, see utils/deps.py) before anything
# touches a jax backend.
_apply_environment()

from .parallel.partition import Partition, local_split
from .parallel.mesh import (
    make_mesh, make_mesh_2d, make_mesh_hybrid, initialize_multihost,
    default_mesh, set_default_mesh, best_grid_2d,
)
from .distributedarray import DistributedArray
from .stacked import StackedDistributedArray
from .stackedlinearoperator import MPIStackedLinearOperator
from .linearoperator import (
    MPILinearOperator, LinearOperator, aslinearoperator, asmpilinearoperator,
)
from .ops.blockdiag import MPIBlockDiag, MPIStackedBlockDiag
from .ops.stack import MPIVStack, MPIStackedVStack, MPIHStack
from .ops.derivatives import (MPIFirstDerivative, MPISecondDerivative,
                              MPILaplacian, MPIGradient)
from .ops.matrixmult import MPIMatrixMult
from .ops.halo import MPIHalo, halo_block_split
from .ops.nonstatconv import MPINonStationaryConvolve1D
from .ops.fft import MPIFFTND, MPIFFT2D
from .ops.fredholm import MPIFredholm1
from .ops.mdc import MPIMDC
from .ops.precond import (JacobiPrecond, BlockJacobiPrecond,
                          VCyclePrecond, make_precond)
from .ops.sparse import MPISparseMatrixMult, auto_sparse_matmult
from .solvers.basic import CG, CGLS, cg, cgls, clear_fused_cache
from .solvers.sparsity import ISTA, FISTA, ista, fista
from .solvers.segmented import cg_segmented, cgls_segmented
from .solvers.block import (block_cg, block_cgls, block_cg_segmented,
                            batched_solve, batched_cache_info)
from .solvers.eigs import power_iteration
from .parallel.reshard import (Layout, ReshardError, plan_reshard,
                               reshard_budget)
from .parallel.spill import HostArray
from .resilience import resilient_solve
from .utils.dottest import dottest
from .plotting.plotting import plot_distributed_array, plot_local_arrays

from . import diagnostics
from . import resilience
from . import ops
from . import solvers
from . import utils
from . import parallel
from . import basicoperators
from . import signalprocessing
from . import waveeqprocessing
from . import optimization
from . import plotting
from . import models
from . import serving
from . import aot

# process-wide fallback compile tier: point JAX's persistent
# compilation cache at PYLOPS_MPI_TPU_COMPILE_CACHE (no-op unset) so
# every entry point — tests, bench, supervised workers, the serving
# daemon — shares the job's cache without per-call wiring (docs/aot.md)
aot.maybe_enable_compile_cache()

__version__ = "0.1.0"

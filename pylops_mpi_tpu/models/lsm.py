"""Least-squares (Kirchhoff) migration.

Application-layer analog of the reference's ``tutorials/lsm.py``: there
each rank builds a ``pylops.waveeqprocessing.LSM`` (Kirchhoff
demigration for its batch of sources) and the ranks are stacked with
``MPIVStack`` — model BROADCAST, data SCATTER over sources, adjoint
sum-allreduce (ref ``pylops_mpi/basicoperators/VStack.py:135-150``).

Here the Kirchhoff engine is jnp-native and deliberately scatter-free:
the forward "spray" of each image point onto its travel-time sample is
a per-shot-gather one-hot contraction (an MXU matmul), and the adjoint
is a pure gather (``take_along_axis``) — no ``.at[].add`` anywhere (see
the note in ``ops/pallas_kernels.py`` / the FirstDerivative operators on
XLA scatter under GSPMD). Travel times are straight-ray constant-velocity
(the reference's analytical mode); amplitudes use geometrical spreading
``1/sqrt(d_s d_r)``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray, Partition
from ..ops.blockdiag import MPIBlockDiag  # noqa: F401  (re-export convenience)
from ..ops.stack import MPIVStack
from ..ops.local import Conv1D, LocalOperator
from ..solvers.basic import cgls

__all__ = ["TravelTimeSpray", "KirchhoffDemigration", "MPILSM", "lsm"]


def _straight_ray(points: np.ndarray, pix: np.ndarray, vel: float):
    """(npts, npix) travel time + distance for straight rays in a
    constant-velocity medium."""
    d = np.sqrt(((points[:, None, :] - pix[None, :, :]) ** 2).sum(-1))
    return d / vel, d


class TravelTimeSpray(LocalOperator):
    """Spray image-point amplitudes onto travel-time samples of
    source–receiver traces: ``y[p, itrav[p, i]] += amp[p, i] * m[i]``.

    Forward iterates shot gathers with ``lax.map``; each gather is an
    ``(npix, nt)`` one-hot contraction so the hot op is a matmul, not a
    scatter. Adjoint gathers ``y[p, itrav[p, i]]`` with
    ``take_along_axis`` and reduces over traces.
    """

    def __init__(self, itrav: np.ndarray, amp: np.ndarray, nt: int,
                 dtype=np.float32):
        npairs, npix = itrav.shape
        self.nt = int(nt)
        valid = itrav < nt
        self.itrav = jnp.asarray(np.where(valid, itrav, 0), dtype=jnp.int32)
        self.amp = jnp.asarray(np.where(valid, amp, 0.0), dtype=dtype)
        super().__init__(dims=npix, dimsd=(npairs, nt), dtype=dtype)

    def _matvec(self, x):
        nt = self.nt
        tgrid = jnp.arange(nt, dtype=jnp.int32)

        def one_pair(args):
            it, a = args                              # (npix,), (npix,)
            onehot = (it[:, None] == tgrid[None, :]).astype(x.dtype)
            return (x * a) @ onehot                   # (nt,)

        y = lax.map(one_pair, (self.itrav, self.amp))
        return y.ravel()

    def _rmatvec(self, x):
        y = x.reshape(self.dimsd)                     # (npairs, nt)
        picked = jnp.take_along_axis(y, self.itrav, axis=1)  # (npairs, npix)
        return (jnp.conj(self.amp) * picked).sum(axis=0)


def KirchhoffDemigration(z: np.ndarray, x: np.ndarray, t: np.ndarray,
                         sources: np.ndarray, recs: np.ndarray, vel: float,
                         wav: np.ndarray, wavcenter: int,
                         dtype=np.float32) -> LocalOperator:
    """Kirchhoff demigration ``d(s, r, t) = w(t) * Σ_x a(x) m(x)
    δ(t − t_s(x) − t_r(x))`` for one batch of sources
    (constant-velocity straight rays; jnp-native analog of the engine
    inside ``pylops.waveeqprocessing.LSM`` the reference stacks,
    ref ``tutorials/lsm.py``)."""
    zz, xx = np.meshgrid(z, x, indexing="ij")
    pix = np.stack([xx.ravel(), zz.ravel()], axis=1)        # (npix, 2)
    srcs = np.asarray(sources, dtype=float).T               # (ns, 2)
    rcvs = np.asarray(recs, dtype=float).T                  # (nr, 2)
    dt = float(t[1] - t[0])
    nt = len(t)
    ts, ds = _straight_ray(srcs, pix, vel)                  # (ns, npix)
    tr, dr = _straight_ray(rcvs, pix, vel)                  # (nr, npix)
    ttot = ts[:, None, :] + tr[None, :, :]                  # (ns, nr, npix)
    amp = 1.0 / np.sqrt(ds[:, None, :] * dr[None, :, :] + 1e-10)
    itrav = np.rint(ttot / dt).astype(np.int64).reshape(-1, pix.shape[0])
    amp = amp.reshape(-1, pix.shape[0])
    spray = TravelTimeSpray(itrav, amp, nt, dtype=dtype)
    conv = Conv1D(spray.dimsd, wav.astype(dtype), axis=-1, offset=wavcenter,
                  dtype=dtype)
    return conv * spray


def MPILSM(z, x, t, sources, recs, vel, wav, wavcenter,
           mesh=None, dtype=np.float32) -> MPIVStack:
    """Distributed LSM operator: sources split over shards, one
    Kirchhoff demigration block per shard, stacked with ``MPIVStack``
    (model BROADCAST, data SCATTER — ref ``tutorials/lsm.py``)."""
    from ..parallel.mesh import default_mesh
    mesh = mesh if mesh is not None else default_mesh()
    P = int(mesh.devices.size)
    sources = np.asarray(sources, dtype=float)
    ns = sources.shape[1]
    chunks = np.array_split(np.arange(ns), P)
    ops = [KirchhoffDemigration(z, x, t, sources[:, c], recs, vel, wav,
                                wavcenter, dtype=dtype)
           for c in chunks if len(c)]
    return MPIVStack(ops, mesh=mesh)


def lsm(z, x, t, sources, recs, vel, wav, wavcenter, refl: np.ndarray,
        niter: int = 20, mesh=None,
        dtype=np.float32) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Model data from ``refl`` and invert with CGLS. Returns
    ``(minv, d, cost)`` with ``minv``/``refl`` on the ``(nz, nx)`` grid."""
    Op = MPILSM(z, x, t, sources, recs, vel, wav, wavcenter, mesh=mesh,
                dtype=dtype)
    m = DistributedArray.to_dist(refl.ravel().astype(dtype),
                                 partition=Partition.BROADCAST, mesh=mesh)
    d = Op.matvec(m)
    x0 = DistributedArray.to_dist(np.zeros(Op.shape[1], dtype=dtype),
                                  partition=Partition.BROADCAST, mesh=mesh)
    out = cgls(Op, d, x0=x0, niter=niter, tol=0.0)
    minv, cost = out[0], out[5]
    return (np.asarray(minv.asarray()).reshape(len(z), len(x)),
            np.asarray(d.asarray()), np.asarray(cost))

"""Application pipelines (L6) — analogs of the reference's tutorials."""
from .poststack import (PoststackLinearModelling, MPIPoststackLinearModelling,
                        poststack_inversion, ricker)
from .mdd import mdd, kernel_to_frequency
from .lsm import TravelTimeSpray, KirchhoffDemigration, MPILSM, lsm

"""Post-stack seismic inversion pipeline.

Application-layer analog of the reference's ``tutorials/poststack.py``
(BASELINE config #4): distributed post-stack modelling as an
``MPIBlockDiag`` of per-trace-block local operators, inverted with CGLS,
optionally with Laplacian regularization through a stacked system.

Layout: the model/data cube is ``(nx, nt0)`` — spatial (distributed)
axis first, time last — so each shard's block is contiguous in the
global C-order flatten and the BlockDiag model space coincides with the
Laplacian's (the same reason the reference distributes its model over
axis 0, ``tutorials/poststack.py``).

The local modelling operator mirrors pylops' ``PoststackLinearModelling``:
``d = 0.5 · W · D m`` with ``W`` a stationary wavelet convolution along
time and ``D`` the first derivative along time.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..distributedarray import DistributedArray, Partition
from ..stacked import StackedDistributedArray
from ..ops.blockdiag import MPIBlockDiag
from ..ops.stack import MPIStackedVStack
from ..ops.derivatives import MPILaplacian
from ..ops.local import Conv1D, FirstDerivative, LocalOperator
from ..solvers.basic import cgls

__all__ = ["PoststackLinearModelling", "MPIPoststackLinearModelling",
           "poststack_inversion", "ricker"]


def ricker(t, f0: float = 20.0):
    """Ricker wavelet (zero-phase), the standard seismic test wavelet."""
    t = np.asarray(t)
    t = np.concatenate([-t[:0:-1], t])
    w = (1 - 2 * (np.pi * f0 * t) ** 2) * np.exp(-(np.pi * f0 * t) ** 2)
    return w, t


def PoststackLinearModelling(wav: np.ndarray, nt0: int,
                             spatdims: Tuple[int, ...] = (),
                             dtype=np.float64) -> LocalOperator:
    """Local post-stack modelling ``0.5 · W · D`` over a
    ``(*spatdims, nt0)`` block, time on the last axis (jnp analog of
    ``pylops.avo.poststack.PoststackLinearModelling``)."""
    dims = tuple(spatdims) + (nt0,)
    taxis = len(dims) - 1
    D = FirstDerivative(dims, axis=taxis, kind="centered", edge=True,
                        dtype=dtype)
    W = Conv1D(dims, jnp.asarray(wav), axis=taxis, offset=len(wav) // 2,
               dtype=dtype)
    return 0.5 * (W @ D)


def MPIPoststackLinearModelling(wav: np.ndarray, nt0: int, nx: int,
                                mesh=None, dtype=np.float64
                                ) -> MPIBlockDiag:
    """Distribute ``nx`` traces over the mesh, one local modelling block
    per shard (the reference tutorial's MPIBlockDiag layout)."""
    from ..parallel.mesh import default_mesh
    mesh = mesh if mesh is not None else default_mesh()
    nsh = int(mesh.devices.size)
    chunks = [len(c) for c in np.array_split(np.arange(nx), nsh)]
    ops = [PoststackLinearModelling(wav, nt0, (c,), dtype=dtype)
           for c in chunks]
    return MPIBlockDiag(ops, mesh=mesh)


def poststack_inversion(d: np.ndarray, wav: np.ndarray,
                        niter: int = 100, epsR: Optional[float] = None,
                        damp: float = 1e-4, mesh=None, dtype=np.float64):
    """Invert post-stack data ``d (nx, nt0)`` for acoustic impedance.

    ``epsR=None``: plain CGLS. With ``epsR``: Laplacian-regularized
    stacked system ``[Op; εR·∇²] m = [d; 0]`` — the reference tutorial's
    regularized path via MPIStackedVStack + StackedDistributedArray.
    """
    nx, nt0 = d.shape
    Op = MPIPoststackLinearModelling(wav, nt0, nx, mesh=mesh, dtype=dtype)
    dy = DistributedArray.to_dist(d.ravel(), mesh=Op.mesh,
                                  local_shapes=Op.local_shapes_n)
    x0 = DistributedArray(global_shape=Op.shape[1], mesh=Op.mesh,
                          local_shapes=Op.local_shapes_m, dtype=dtype)
    if epsR is None:
        # damping stabilises the near-singular W·D normal equations
        # (cond ~ 1e17): without it CGLS trajectories are rounding-order
        # sensitive
        x, *_ = cgls(Op, dy, x0, niter=niter, damp=damp, tol=1e-10)
    else:
        LapOp = MPILaplacian(dims=(nx, nt0), axes=(0, 1), weights=(1, 1),
                             sampling=(1, 1), mesh=Op.mesh, dtype=dtype)
        StackOp = MPIStackedVStack([Op, epsR * LapOp])
        zero = DistributedArray(global_shape=LapOp.shape[0], mesh=Op.mesh,
                                dtype=dtype)
        dstack = StackedDistributedArray([dy, zero])
        x, *_ = cgls(StackOp, dstack, x0, niter=niter, damp=damp, tol=1e-10)
    return x.asarray().reshape(nx, nt0), Op

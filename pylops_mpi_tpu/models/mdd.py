"""Multi-dimensional deconvolution (MDD) pipeline.

Application-layer analog of the reference's ``tutorials/mdd.py``
(BASELINE config #5): build the frequency-sharded MDC operator from a
time-domain kernel, model data, and invert with CGLS.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..distributedarray import DistributedArray, Partition
from ..ops.mdc import MPIMDC
from ..solvers.basic import cgls

__all__ = ["mdd", "kernel_to_frequency"]


def kernel_to_frequency(Gt: np.ndarray, nfmax: Optional[int] = None
                        ) -> np.ndarray:
    """Time-domain kernel ``(ns, nr, nt)`` → one-sided frequency kernel
    ``(nfmax, ns, nr)`` (the preprocessing step of tutorials/mdd.py)."""
    ns, nr, nt = Gt.shape
    Gf = np.fft.rfft(Gt, nt, axis=-1)
    Gf = np.moveaxis(Gf, -1, 0)          # (nfft, ns, nr)
    if nfmax is not None:
        Gf = Gf[:nfmax]
    return Gf


def mdd(G: np.ndarray, d: np.ndarray, nt: int, nv: int = 1,
        dt: float = 1.0, dr: float = 1.0, twosided: bool = True,
        niter: int = 50, mesh=None) -> Tuple[np.ndarray, object]:
    """Solve ``d = MDC(G) m`` for ``m`` with CGLS.

    Parameters
    ----------
    G : (nfmax, ns, nr) complex frequency kernel
    d : (nt, ns, nv) data
    """
    Op = MPIMDC(G, nt=nt, nv=nv, dt=dt, dr=dr, twosided=twosided, mesh=mesh)
    dy = DistributedArray.to_dist(np.asarray(d, dtype=float).ravel(),
                                  partition=Partition.BROADCAST, mesh=mesh)
    x0 = DistributedArray.to_dist(np.zeros(Op.shape[1]),
                                  partition=Partition.BROADCAST, mesh=mesh)
    x, istop, iiter, r1, r2, cost = cgls(Op, dy, x0, niter=niter, tol=1e-12)
    nr = Op.shape[1] // (nt * nv)
    return x.asarray().reshape(nt, nr, nv), Op

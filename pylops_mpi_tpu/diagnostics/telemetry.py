"""In-loop solver telemetry (per-iteration convergence scalars).

The fused CG/CGLS/ISTA/FISTA solvers run as ONE ``lax.while_loop``
under ``jit`` — the whole point of the design is that no scalar
crosses the host boundary per iteration. That also means convergence
is invisible until the solve returns. This module captures
per-iteration scalars (residual norms, recurrence/step quantities)
from INSIDE the fused loops via ``jax.debug.callback``, recording each
sample both in a host-side history (:func:`history`) and as a Chrome
counter event in the trace buffer (:mod:`.trace`), so one solve's
JSONL artifact carries the convergence trajectory next to the
operator/collective spans.

OFF BY DEFAULT, and provably free when off: :func:`iteration` returns
before touching jax, so a disabled build traces NOTHING into the loop
body — ``utils/hlo.py::assert_no_host_callbacks`` pins that the
compiled fused programs contain zero host callbacks, leaving the
donated/fused hot path untouched (bit-identical HLO).

Gating: ``PYLOPS_MPI_TPU_TELEMETRY`` = ``auto`` (default; on exactly
when ``PYLOPS_MPI_TPU_TRACE=full``) | ``on`` | ``off``. The fused
solver cache keys on :func:`telemetry_signature` (``solvers/basic.py
_get_fused``) so flipping the gate retraces instead of silently
reusing an executable compiled under the other mode.

Caveats: ``jax.debug.callback`` samples arrive asynchronously
(``ordered=False``) — within one solve they are monotone in practice
but callers should sort by ``iiter`` (``history`` does); masked
vectors make the recurrence scalars per-group VECTORS, stored as
lists. The callback costs a device→host sync per iteration — this is
a diagnosis mode, not a production one.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Dict, List, Optional

from . import trace

__all__ = ["telemetry_enabled", "telemetry_signature", "iteration",
           "history", "clear_history"]

_LOCK = threading.Lock()
_HISTORY: Dict[str, List[Dict]] = {}
_warned_mode = False


def _mode() -> str:
    global _warned_mode
    m = os.environ.get("PYLOPS_MPI_TPU_TELEMETRY", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m in ("1", "true"):
        m = "on"
    if m in ("0", "false"):
        m = "off"
    if m not in ("auto", "on", "off"):
        if not _warned_mode:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_TELEMETRY={m!r} is not one of "
                "['auto', 'on', 'off']; using 'auto'", stacklevel=2)
            _warned_mode = True
        m = "auto"
    return m


def telemetry_enabled() -> bool:
    """True when per-iteration capture is active: explicit ``on``, or
    ``auto`` with the trace layer in ``full`` mode."""
    m = _mode()
    if m == "on":
        return True
    if m == "off":
        return False
    return trace.trace_mode() == "full"


def telemetry_signature():
    """Hashable compile-relevant state for the fused-solver cache key:
    a program traced with telemetry on embeds host callbacks, one
    traced with it off must not — the two can never share an
    executable (same pattern as the donation gate)."""
    return ("telemetry", telemetry_enabled())


def _to_host_value(v):
    import numpy as np
    a = np.asarray(v)
    if a.size == 1:
        return float(a.reshape(()))
    return [float(x) for x in a.ravel()]


def _record(solver: str, names, iiter, *vals) -> None:
    """Host-side sink for the debug callback (runs OUTSIDE the traced
    program): appends to the history and emits a Chrome counter."""
    try:
        it = int(_to_host_value(iiter))
        sample = {"iiter": it}
        counters = {}
        for n, v in zip(names, vals):
            hv = _to_host_value(v)
            sample[n] = hv
            if isinstance(hv, float):
                counters[n] = hv
        with _LOCK:
            _HISTORY.setdefault(solver, []).append(sample)
        trace.counter(f"solver.{solver}", {"iiter": it, **counters})
    except Exception:
        pass  # telemetry must never be able to kill a solve


def iteration(solver: str, iiter, **scalars) -> None:
    """Record one solver iteration from INSIDE a fused loop body.

    ``iiter`` and the ``scalars`` values are traced jax scalars (or
    per-mask-group vectors); ``solver`` and the scalar NAMES are
    static. When telemetry is disabled this returns before touching
    jax — nothing enters the traced program (the zero-host-callback
    pin). When enabled it stages ONE ``jax.debug.callback`` per
    iteration."""
    if not telemetry_enabled():
        return
    import jax
    names = tuple(scalars)
    jax.debug.callback(partial(_record, solver, names), iiter,
                       *scalars.values())


def history(solver: Optional[str] = None) -> List[Dict]:
    """Recorded samples (sorted by ``iiter``) for ``solver``, or the
    whole ``{solver: samples}`` dict when ``solver`` is None."""
    with _LOCK:
        if solver is not None:
            return sorted(_HISTORY.get(solver, ()),
                          key=lambda s: s["iiter"])
        return {k: sorted(v, key=lambda s: s["iiter"])
                for k, v in _HISTORY.items()}


def clear_history(solver: Optional[str] = None) -> None:
    with _LOCK:
        if solver is None:
            _HISTORY.clear()
        else:
            _HISTORY.pop(solver, None)

"""Profiler hooks and the deadline-aware harvest-stage runner.

Two jobs, both born from VERDICT round 5 ("a 900 s harvest stage
burned a rare ~20-minute TPU window producing nothing"):

1. :func:`profile_capture` — ``jax.profiler`` trace-capture around a
   region (the XLA/device-level view the host-side span tracer cannot
   give), gated by an env dir so any harvest stage can be captured
   without code changes.

2. :class:`DeadlineRunner` + :data:`STAGE_BUDGETS` — the central
   per-stage wall-budget table for the harvest ladder (previously the
   900 s-class limits were duplicated inline across ``bench.py``,
   ``benchmarks/tpu_probe_loop.py`` and
   ``benchmarks/rehearse_ladder.py``) and a runner that (a) caps each
   stage's timeout at ``min(budget, window remaining)``, (b) records
   whether a killed stage still BANKED a partial artifact (the
   ``_run_json_cmd`` salvage), and (c) SKIPS stages the remaining
   window cannot fit — yielding the window instead of eating it.

STANDALONE-LOADABLE BY DESIGN: module-level imports are stdlib only
and there are no relative imports, so the probe daemon's jax-free
parent process loads this file directly via
``importlib.util.spec_from_file_location`` (see
``benchmarks/tpu_probe_loop.py::_profiler_mod``) without pulling the
package (and jax) into the long-lived supervisor. Trace emission is
lazy and guarded for the same reason.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

__all__ = ["STAGE_BUDGETS", "stage_budget", "DeadlineRunner",
           "StageRecord", "profile_capture", "profile_dir"]


# ------------------------------------------------------------ budget table
# Per-stage wall budgets, seconds. ONE table, two columns:
#   "tpu"      — the live-window budget the probe daemon enforces
#                (previously the PROBE_*_TIMEOUT inline defaults in
#                tpu_probe_loop.py);
#   "rehearse" — the CPU-rehearsal enforcement budget
#                (previously rehearse_ladder.py's BUDGETS dict).
# Env override names are unchanged (PROBE_<STAGE>_TIMEOUT, with the
# historical "flagship_" prefix dropped: PROBE_SMALL_TIMEOUT etc.), so
# existing harvest configs keep working.
STAGE_BUDGETS: Dict[str, Dict[str, Optional[int]]] = {
    "selfcheck":      {"tpu": 900,  "rehearse": 600},
    # the autotuner sweep (python -m pylops_mpi_tpu.tuning): runs
    # EARLY in the ladder so later stages replay measured plans; also
    # the per-search budget tuning.search enforces in-process
    # (PYLOPS_MPI_TPU_TUNE_BUDGET overrides for a single search)
    "tune":           {"tpu": 600,  "rehearse": 240},
    "flagship_small": {"tpu": 900,  "rehearse": 600},
    "fft_planar":     {"tpu": 700,  "rehearse": 600},
    "flagship_full":  {"tpu": 3000, "rehearse": 2400},
    "flagship_mid":   {"tpu": 1200, "rehearse": 1200},
    "overlap":        {"tpu": 600,  "rehearse": 600},
    # hierarchical-vs-flat race (round 11): per-fabric byte + timing
    # rows on the hybrid mesh; cheap, slotted right after overlap
    "hier":           {"tpu": 300,  "rehearse": 300},
    "bisect":         {"tpu": 1200, "rehearse": 900},
    "breakdown":      {"tpu": 900,  "rehearse": 700},
    "diag":           {"tpu": 900,  "rehearse": 700},
    # bench-child internal budgets (bench.py consumes these directly):
    # the pre-headline selfcheck subprocess and the per-component cap
    "bench_selfcheck": {"tpu": 600, "rehearse": 600},
    "component":       {"tpu": 150, "rehearse": 150},
    # elastic-runtime watched phases (resilience/elastic.py
    # watched_call deadlines; PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT
    # overrides globally, PROBE_<STAGE>_TIMEOUT per stage):
    # blocking jax.distributed bring-up, blocking multi-host
    # checkpoint save/load, and the CI chaos leg's whole
    # kill/recover suite
    "multihost_init":  {"tpu": 300, "rehearse": 120},
    "checkpoint_io":   {"tpu": 600, "rehearse": 300},
    "multihost_chaos": {"tpu": 900, "rehearse": 600},
    # serving-daemon stages (serving/queue.py dispatcher wraps every
    # packed batch solve in a DeadlineRunner with this budget; the CI
    # serve-forever smoke uses serve_smoke as its job timeout)
    "serve_batch":     {"tpu": 120, "rehearse": 60},
    "serve_smoke":     {"tpu": 900, "rehearse": 600},
}

_ENV_NAMES = {
    "bench_selfcheck": "BENCH_SELFCHECK_TIMEOUT",
    "component": "BENCH_COMPONENT_TIMEOUT",
}


def _env_name(stage: str) -> str:
    if stage in _ENV_NAMES:
        return _ENV_NAMES[stage]
    return "PROBE_" + stage.replace("flagship_", "").upper() + "_TIMEOUT"


def stage_budget(stage: str, rehearse: bool = False,
                 env: Optional[Dict] = None) -> int:
    """Wall budget (seconds) for one harvest stage: the env override
    (``PROBE_<STAGE>_TIMEOUT`` / ``BENCH_*_TIMEOUT``) when set and
    parseable, else the table column for the flavor. Unknown stages
    raise — a typo'd stage name must not silently get some default."""
    if stage not in STAGE_BUDGETS:
        raise KeyError(f"unknown harvest stage {stage!r}; known: "
                       f"{sorted(STAGE_BUDGETS)}")
    env = os.environ if env is None else env
    raw = env.get(_env_name(stage))
    if raw is not None:
        try:
            return int(raw)
        except ValueError:
            pass  # malformed override: fall through to the table
    return STAGE_BUDGETS[stage]["rehearse" if rehearse else "tpu"]


# --------------------------------------------------------- deadline runner
class StageRecord(dict):
    """One stage outcome (a plain dict for easy JSON banking):
    ``stage``, ``budget_s``, ``effective_timeout_s``, ``seconds``,
    ``ok``, ``skipped``, ``banked_partial``, ``hit_budget``,
    ``error``. ``result`` holds the stage's parsed artifact (may be a
    salvaged partial)."""

    @property
    def result(self):
        return self.get("result")


class DeadlineRunner:
    """Run harvest stages against a hard window deadline.

    ``fn`` passed to :meth:`run` receives the EFFECTIVE timeout
    (seconds) and returns ``(result, err)`` in the
    ``bench._run_json_cmd`` convention — ``result`` may be a salvaged
    partial line when the child was killed at the timeout (detected
    here via its ``salvaged_after_timeout`` stamp). The runner:

    - caps each stage at ``min(budget, remaining window)`` (a stage
      never eats past the deadline);
    - skips a stage outright when the remaining window is under
      ``min_stage_s`` (better to yield the window for the next probe
      than to start a stage that cannot finish);
    - records every outcome (:attr:`records`) — including whether a
      killed stage still banked a partial artifact — and emits a
      structured trace event per stage when the trace layer is
      available and enabled.
    """

    def __init__(self, deadline_ts: Optional[float] = None,
                 min_stage_s: int = 30,
                 log: Optional[Callable[[Dict], None]] = None):
        self.deadline_ts = deadline_ts
        self.min_stage_s = int(min_stage_s)
        self._log = log
        self.records: List[StageRecord] = []

    def remaining(self) -> Optional[float]:
        """Seconds left in the window (None = no deadline)."""
        if self.deadline_ts is None:
            return None
        return self.deadline_ts - time.time()

    def _emit(self, rec: StageRecord) -> None:
        self.records.append(rec)
        payload = {k: v for k, v in rec.items() if k != "result"}
        if self._log is not None:
            try:
                self._log(dict(payload))
            except Exception:
                pass
        try:
            # only if the trace layer is ALREADY imported: this module
            # is file-path-loaded by jax-free supervisors, and emitting
            # here must never pull the package (and jax) into them
            import sys
            tr = sys.modules.get("pylops_mpi_tpu.diagnostics.trace")
            if tr is not None:
                tr.event(f"harvest.{rec['stage']}", cat="harvest",
                         **payload)
        except Exception:
            pass

    def run(self, stage: str, fn: Callable, budget_s: int) -> StageRecord:
        rem = self.remaining()
        if rem is not None and rem < min(budget_s, self.min_stage_s):
            rec = StageRecord(stage=stage, budget_s=budget_s,
                              skipped=True, ok=False,
                              reason="window exhausted "
                                     f"({rem:.0f}s remaining)",
                              result=None)
            self._emit(rec)
            return rec
        eff = int(budget_s) if rem is None \
            else max(1, min(int(budget_s), int(rem)))
        t0 = time.time()
        try:
            result, err = fn(eff)
        except Exception as e:  # a crashing stage must not end the window
            result, err = None, f"stage raised: {e!r}"
        seconds = round(time.time() - t0, 1)
        banked_partial = bool(
            isinstance(result, dict)
            and (result.get("salvaged_after_timeout")
                 or result.get("partial")))
        rec = StageRecord(
            stage=stage, budget_s=int(budget_s),
            effective_timeout_s=eff, seconds=seconds,
            ok=result is not None and not err,
            skipped=False,
            hit_budget=seconds >= eff - 1,
            banked_partial=banked_partial,
            result=result)
        if err:
            rec["error"] = str(err)[:300]
        self._emit(rec)
        return rec

    def report(self) -> Dict:
        """Summary for artifacts: per-stage outcomes (without the
        payloads) + whether the window was yielded with stages
        unrun."""
        return {
            "stages": [{k: v for k, v in r.items() if k != "result"}
                       for r in self.records],
            "skipped": [r["stage"] for r in self.records
                        if r.get("skipped")],
            "banked_partials": [r["stage"] for r in self.records
                                if r.get("banked_partial")],
            "remaining_s": (None if self.deadline_ts is None
                            else round(self.remaining(), 1)),
        }


# ------------------------------------------------------------ jax.profiler
def profile_dir() -> Optional[str]:
    """``PYLOPS_MPI_TPU_PROFILE_DIR`` — when set, the solvers' /
    bench's :func:`profile_capture` regions actually capture; unset
    (default) they are no-ops."""
    return os.environ.get("PYLOPS_MPI_TPU_PROFILE_DIR") or None


class _NoopCapture:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def profile_capture(name: str, logdir: Optional[str] = None):
    """Context manager: capture a ``jax.profiler`` trace of the region
    into ``logdir`` (default: ``$PYLOPS_MPI_TPU_PROFILE_DIR/<name>``;
    no-op when neither is set, or when the profiler cannot start —
    e.g. a second concurrent capture). TensorBoard/XProf-compatible;
    this is the DEVICE-side complement of the host-side span tracer
    (``diagnostics/trace.py``)."""
    base = logdir or profile_dir()
    if not base:
        return _NoopCapture()
    path = os.path.join(base, name) if logdir is None else logdir

    class _Capture:
        def __enter__(self):
            self._on = False
            try:
                import jax.profiler
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
                self._on = True
            except Exception:
                pass  # profiling must never break the workload
            return self

        def __exit__(self, *exc):
            if self._on:
                try:
                    import jax.profiler
                    jax.profiler.stop_trace()
                except Exception:
                    pass
            return False

    return _Capture()


# convenience for scripts that bank runner reports next to artifacts
def dump_report(runner: DeadlineRunner, path: str) -> None:
    with open(path, "w") as f:
        json.dump(runner.report(), f, indent=1)

"""Cross-worker trace aggregation: one clock-aligned fleet trace.

Every supervised worker dumps its own Chrome-trace JSONL
(``PYLOPS_MPI_TPU_TRACE_FILE``, :mod:`.trace`) with timestamps relative
to its OWN process start — useless for the questions that matter at
pod scale ("which rank is the straggler in this all_to_all?"). This
module merges per-rank artifacts into one timeline:

1. **Clock alignment.** Trace timestamps have per-process epochs, so
   the merger needs a shared reference. The collective spans are it:
   every rank enters the same collective in the same deterministic
   program order (``parallel/collectives.py`` stamps a per-op sequence
   number ``seq`` into each span for exactly this), so matching span
   ENTRY times across ranks gives per-rank clock deltas. The per-rank
   offset is the MEDIAN delta over all matched collectives — robust to
   a minority of genuinely-late entries, which are the signal, not the
   clock. (A stall that precedes every collective a rank ever emits is
   indistinguishable from a later process start and is absorbed into
   the offset — that is inherent to trace-only alignment.)
2. **Straggler attribution.** After alignment, each collective matched
   across ≥2 ranks is stamped with ``skew_us`` (spread of aligned
   entry times) and ``straggler_rank`` (the last rank to arrive — the
   one everyone else waited on).
3. **Merged Chrome trace.** Events are re-homed to ``pid=rank`` (with
   ``process_name`` metadata), offset-shifted onto the common clock
   and sorted — one file Perfetto opens showing the whole fleet.
4. **Critical path.** Per solver root span, the max-duration child
   chain (:func:`critical_path`) — where the wall actually went.

Loaders are TOLERANT by design: a killed worker's artifact ends in
unclosed ``ph="B"`` spans and possibly a truncated final line
(:mod:`.trace` post-mortem flush); garbage must degrade to skipped
lines, never an exception — a post-mortem tool that crashes on
post-mortem artifacts is worthless.

CLI: ``python -m pylops_mpi_tpu.diagnostics aggregate <dir-or-files>``
(see :mod:`.__main__`).
"""

from __future__ import annotations

import json
import os
import re
import statistics
from typing import Dict, List, Optional, Sequence, Tuple

from . import trace as _trace

__all__ = ["load_events", "guess_rank", "collective_entries",
           "align_offsets", "merge_traces", "aggregate_files",
           "critical_path", "discover_trace_files"]


def load_events(path: str) -> List[Dict]:
    """Parse one trace artifact (JSONL, a Chrome JSON array, or a
    ``{"traceEvents": [...]}`` object — the CLI's merged-trace output)
    into a list of event dicts. Tolerant: unreadable files yield
    ``[]``; truncated/garbage lines and non-dict entries are skipped;
    events without a ``name`` or a numeric ``ts`` are dropped. Never
    raises on artifact content."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    events: List[Dict] = []
    candidates = None
    stripped = text.lstrip()
    if stripped.startswith("["):  # chrome-array dump
        try:
            doc = json.loads(stripped)
        except ValueError:
            doc = []
        candidates = doc if isinstance(doc, list) else []
    elif stripped.startswith("{"):
        # one whole-file {"traceEvents": [...]} object — but a JSONL's
        # first line starts with "{" too, so only claim it when the
        # WHOLE text parses to that shape; else fall through to JSONL
        try:
            doc = json.loads(stripped)
            if isinstance(doc, dict) \
                    and isinstance(doc.get("traceEvents"), list):
                candidates = doc["traceEvents"]
        except ValueError:
            pass
    if candidates is None:
        candidates = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                candidates.append(json.loads(line))
            except ValueError:
                continue  # truncated final line of a killed worker
    for ev in candidates:
        if not isinstance(ev, dict):
            continue
        if not isinstance(ev.get("name"), str):
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            continue
        events.append(ev)
    return events


_RANK_RE = re.compile(r"(?:rank|worker|proc)[._-]?(\d+)", re.IGNORECASE)


def guess_rank(path: str) -> Optional[int]:
    """Rank inferred from a trace filename (``trace.rank1.jsonl``,
    ``worker0.attempt2.trace.jsonl``, ...), or ``None``."""
    m = None
    for m in _RANK_RE.finditer(os.path.basename(path)):
        pass  # keep the LAST match: "worker0.attempt1" → the worker id
    if m is None:
        return None
    # prefer an explicit "rank"/"worker" over "attempt": re-scan for
    # the first rank/worker-flavored match
    for mm in _RANK_RE.finditer(os.path.basename(path)):
        if mm.group(0).lower().startswith(("rank", "worker", "proc")):
            return int(mm.group(1))
    return int(m.group(1))


def collective_entries(events: Sequence[Dict]) -> Dict[Tuple, float]:
    """``{(name, seq): entry_ts_us}`` for every collective span in one
    rank's events (``cat="collective"``, ``ph`` ``X`` or ``B`` — open
    spans from a post-mortem flush still have a valid entry time).
    Spans without a stamped ``seq`` fall back to their per-name
    occurrence index in buffer order (pre-seq artifacts)."""
    out: Dict[Tuple, float] = {}
    fallback_idx: Dict[str, int] = {}
    for ev in events:
        if not isinstance(ev, dict) \
                or ev.get("cat") != "collective" \
                or ev.get("ph") not in ("X", "B") \
                or not isinstance(ev.get("ts"), (int, float)):
            continue
        name = ev["name"]
        args = ev.get("args")
        seq = args.get("seq") if isinstance(args, dict) else None
        if not isinstance(seq, int):
            seq = fallback_idx.get(name, 0)
            fallback_idx[name] = seq + 1
        key = (name, seq)
        if key not in out:  # first entry wins on duplicates
            out[key] = float(ev["ts"])
    return out


def align_offsets(entries: Dict[int, Dict[Tuple, float]]
                  ) -> Dict[int, float]:
    """Per-rank clock offsets (µs to ADD to a rank's timestamps) that
    put every rank on the reference rank's clock. Reference = lowest
    rank; for each other rank the offset is the median of
    ``ref_entry - rank_entry`` over the collectives both recorded.
    Ranks sharing no collective with the reference get offset 0."""
    if not entries:
        return {}
    ref = min(entries)
    offsets = {ref: 0.0}
    for rank, ents in entries.items():
        if rank == ref:
            continue
        deltas = [entries[ref][k] - ents[k]
                  for k in ents.keys() & entries[ref].keys()]
        offsets[rank] = statistics.median(deltas) if deltas else 0.0
    return offsets


def merge_traces(traces: Dict[int, Sequence[Dict]]) -> Dict:
    """Merge per-rank event lists into one fleet trace. Returns::

        {"events":      clock-aligned merged events, pid=rank,
         "offsets_us":  {rank: applied offset},
         "collectives": [{"name", "seq", "skew_us", "straggler_rank",
                          "entries_us": {rank: aligned entry},
                          "fabric"?: "ici"|"dcn"|"split"}, ...],
         "ranks":       sorted rank list}

    Every collective matched across ≥2 ranks carries ``skew_us`` and
    ``straggler_rank`` — stamped both in the summary list and into the
    merged events' ``args`` so Perfetto shows them on the span."""
    entries = {r: collective_entries(evs) for r, evs in traces.items()}
    offsets = align_offsets(entries)

    # fabric attribution (round 11): the collectives stamp a ``fabric``
    # span tag ("ici"/"dcn" for single-fabric dispatches, "split" for
    # two-level schedules) on classified meshes; lift it onto the
    # matched-collective summary so the fleet view shows which
    # interconnect each straggler analysis rode. First rank's tag wins
    # (the dispatch is SPMD — tags cannot differ across ranks).
    fabrics: Dict[Tuple, str] = {}
    for rank, evs in traces.items():
        fallback_idx: Dict[str, int] = {}
        for ev in evs:
            if not isinstance(ev, dict) or ev.get("cat") != "collective" \
                    or ev.get("ph") not in ("X", "B"):
                continue
            args = ev.get("args") if isinstance(ev.get("args"), dict) \
                else {}
            seq = args.get("seq")
            if not isinstance(seq, int):
                seq = fallback_idx.get(ev["name"], 0)
                fallback_idx[ev["name"]] = seq + 1
            fab = args.get("fabric")
            if isinstance(fab, str):
                fabrics.setdefault((ev["name"], seq), fab)

    # per-collective skew/straggler from ALIGNED entry times
    per_key: Dict[Tuple, Dict[int, float]] = {}
    for rank, ents in entries.items():
        off = offsets.get(rank, 0.0)
        for key, ts in ents.items():
            per_key.setdefault(key, {})[rank] = ts + off
    collectives = []
    stamp: Dict[Tuple, Dict] = {}
    for key in sorted(per_key, key=lambda k: (k[0], k[1])):
        aligned = per_key[key]
        if len(aligned) < 2:
            continue
        lo, hi = min(aligned.values()), max(aligned.values())
        straggler = max(aligned, key=lambda r: aligned[r])
        rec = {"name": key[0], "seq": key[1],
               "skew_us": round(hi - lo, 3),
               "straggler_rank": straggler,
               "entries_us": {str(r): round(t, 3)
                              for r, t in sorted(aligned.items())}}
        if key in fabrics:
            rec["fabric"] = fabrics[key]
        collectives.append(rec)
        stamp[key] = rec

    merged: List[Dict] = []
    for rank in sorted(traces):
        off = offsets.get(rank, 0.0)
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank{rank}"}})
        fallback_idx: Dict[str, int] = {}
        for ev in traces[rank]:
            if not isinstance(ev, dict) or not isinstance(
                    ev.get("ts"), (int, float)):
                continue  # tolerate raw (unloaded) event lists too
            ev = dict(ev)
            args = dict(ev["args"]) if isinstance(ev.get("args"),
                                                  dict) else {}
            args["worker_pid"] = ev.get("pid")
            ev["ts"] = round(float(ev["ts"]) + off, 3)
            ev["pid"] = rank
            if ev.get("cat") == "collective" and ev.get("ph") in ("X",
                                                                  "B"):
                seq = args.get("seq")
                if not isinstance(seq, int):
                    seq = fallback_idx.get(ev["name"], 0)
                    fallback_idx[ev["name"]] = seq + 1
                rec = stamp.get((ev["name"], seq))
                if rec is not None:
                    args["skew_us"] = rec["skew_us"]
                    args["straggler_rank"] = rec["straggler_rank"]
            ev["args"] = args
            merged.append(ev)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return {"events": merged, "offsets_us": {r: round(o, 3)
                                             for r, o in offsets.items()},
            "collectives": collectives, "ranks": sorted(traces)}


def critical_path(events: Sequence[Dict]) -> List[Dict]:
    """Per solver root span (``solver.*``), the max-duration child
    chain: ``[{"solver", "pid", "dur_us", "path": [{"name",
    "dur_us"}, ...]}, ...]`` — the critical-path summary per solve.
    Uses the hardened :func:`~pylops_mpi_tpu.diagnostics.trace.\
span_tree`, so post-mortem artifacts are fine."""
    # span_tree scans per-thread; group per pid first so two ranks'
    # same-tid events don't interleave into one bogus tree
    by_pid: Dict = {}
    for ev in events:
        if isinstance(ev, dict):
            by_pid.setdefault(ev.get("pid"), []).append(ev)
    out = []
    for pid in sorted(by_pid, key=lambda p: (p is None, p)):
        for root in _trace.span_tree(by_pid[pid]):
            if not str(root.get("name", "")).startswith("solver."):
                continue
            path = []
            node = root
            while node.get("children"):
                node = max(node["children"],
                           key=lambda n: n.get("dur") or 0.0)
                path.append({"name": node["name"],
                             "dur_us": node.get("dur")})
            out.append({"solver": root["name"], "pid": pid,
                        "dur_us": root.get("dur"), "path": path})
    return out


def discover_trace_files(paths: Sequence[str]) -> List[str]:
    """Expand directories into their ``*.jsonl``/``*.trace`` files
    (sorted); plain files pass through. Missing paths are skipped —
    the tolerant-loader rule applies to discovery too."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith((".jsonl", ".trace")) \
                        and "trace" in name.lower():
                    out.append(os.path.join(p, name))
        elif os.path.exists(p):
            out.append(p)
    return out


def aggregate_files(paths: Sequence[str],
                    ranks: Optional[Sequence[int]] = None) -> Dict:
    """Load + merge trace artifacts (see :func:`merge_traces`).
    ``ranks`` overrides rank assignment; else filenames are parsed
    (:func:`guess_rank`) with positional fallback. Adds a
    ``critical_path`` summary and per-file provenance."""
    files = discover_trace_files(paths)
    traces: Dict[int, List[Dict]] = {}
    sources: Dict[int, str] = {}
    for i, path in enumerate(files):
        if ranks is not None and i < len(ranks):
            rank = int(ranks[i])
        else:
            g = guess_rank(path)
            rank = g if g is not None and g not in traces else i
        while rank in traces:  # collision → next free positional slot
            rank += 1
        traces[rank] = load_events(path)
        sources[rank] = path
    result = merge_traces(traces)
    result["sources"] = {str(r): sources[r] for r in sorted(sources)}
    result["critical_path"] = critical_path(result["events"])
    return result

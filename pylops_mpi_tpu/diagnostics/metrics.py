"""Process-wide metrics registry — counters, gauges, histograms.

The fleet-observability companion to :mod:`.trace` (ISSUE 10): spans
answer "what happened, when" for ONE process; this module answers "how
much, so far" in a form a supervisor can poll while the worker is
still alive. The registry holds the numbers every prior subsystem
already computes but only logs transiently:

- solver iterations / solves / restarts (``solvers/basic.py``,
  ``solvers/block.py``, ``resilience/driver.py``),
- guard verdicts per status kind (``resilience/status.py``),
- collective calls and byte estimates per op
  (``parallel/collectives.py``),
- tuning plan-cache hits/misses (``tuning/cache.py``),
- bounded-retry counts (``resilience/retry.py``),
- per-stage wall clocks (the :func:`timer` handle around the solver
  entry points).

Gating — ``PYLOPS_MPI_TPU_METRICS``:

- ``off`` (default): every entry point returns after ONE env dict
  lookup; nothing is allocated, no thread is started. The registry is
  pure host-side Python and never touches jax, so compiled programs
  are BIT-IDENTICAL in both modes (pinned in
  ``tests/test_fleet_obs.py`` via ``utils/hlo.py``) — unlike
  ``TRACE=full`` telemetry, metrics-on adds zero in-loop host
  callbacks because every increment happens AFTER the fused loop
  returns to Python.
- ``on``: increments are recorded (one lock + dict op each). Unknown
  values warn once and stay off — same rule as the trace/guard knobs.

Snapshots: :func:`snapshot` returns the registry as one JSON-safe
dict. When ``PYLOPS_MPI_TPU_METRICS_FILE`` is set, a daemon thread
(started lazily at the first recorded metric) writes the snapshot
there every ``PYLOPS_MPI_TPU_METRICS_INTERVAL`` seconds, atomically
(pid-suffixed temp + ``os.replace``, the heartbeat/plan-cache idiom)
with a final write at exit — a killed worker leaves its last-written
numbers behind. Supervised workers additionally embed the snapshot in
every heartbeat (``resilience/elastic.py``), so the supervisor sees
live per-worker PROGRESS, not just liveness, and
:func:`~pylops_mpi_tpu.resilience.supervisor.launch_job` harvests the
final snapshots into ``JobResult.metrics`` / ``job_report.json``.

This module is deliberately stdlib-only and standalone-loadable (like
:mod:`.profiler`): the supervisor process, which never imports jax,
reads and embeds snapshots through it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence

__all__ = ["metrics_mode", "metrics_enabled", "metrics_file",
           "metrics_interval", "inc", "collective_bytes", "set_gauge",
           "observe", "timer", "hist_quantiles",
           "snapshot", "clear_metrics", "write_snapshot",
           "read_snapshot", "SNAPSHOT_SCHEMA"]

SNAPSHOT_SCHEMA = 1

_MODES = ("off", "on")
_warned_mode = False


def metrics_mode() -> str:
    """``PYLOPS_MPI_TPU_METRICS`` resolved to ``off``/``on`` (default
    ``off``; ``1``/``true`` count as ``on``; unknown values warn once
    and stay off — a typo in a CI matrix must not silently flip the
    registry on). Read per call so tests and long-lived sessions can
    flip the env without a cache to reset."""
    global _warned_mode
    m = os.environ.get("PYLOPS_MPI_TPU_METRICS", "off").strip().lower()
    if m in ("", "0", "none", "default", "false"):
        m = "off"
    if m in ("1", "true"):
        m = "on"
    if m not in _MODES:
        if not _warned_mode:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_METRICS={m!r} is not one of {_MODES}; "
                "metrics stay off", stacklevel=2)
            _warned_mode = True
        m = "off"
    return m


def metrics_enabled() -> bool:
    return metrics_mode() == "on"


def metrics_file() -> Optional[str]:
    """``PYLOPS_MPI_TPU_METRICS_FILE`` — the periodic-snapshot path
    (assigned per worker by the supervisor), or ``None``."""
    return os.environ.get("PYLOPS_MPI_TPU_METRICS_FILE") or None


def metrics_interval() -> float:
    """``PYLOPS_MPI_TPU_METRICS_INTERVAL`` snapshot-write interval in
    seconds (default 5.0; floored at 0.05 so a typo cannot busy-spin
    the writer — the heartbeat rule)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_METRICS_INTERVAL",
                                 "5.0"))
    except ValueError:
        v = 5.0
    return max(0.05, v)


_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}
# histogram summaries, not buckets: the consumers (heartbeat payload,
# job_report.json) need "how long / how many, roughly", and a fixed
# 5-number summary keeps every beat O(registry size), never O(samples)
_HISTS: Dict[str, Dict[str, float]] = {}
# a bounded ring of RECENT raw samples per histogram, kept OUT of the
# snapshot (schema unchanged, beats stay O(registry size)): the serving
# layer's backpressure report wants p50/p99 time-in-queue, which a
# 5-number summary cannot give. 512 samples bounds memory while keeping
# tail quantiles meaningful over the recent window.
_HSAMPLES: Dict[str, "deque"] = {}
_HSAMPLES_MAX = 512


def inc(name: str, value: float = 1) -> None:
    """Add ``value`` to counter ``name``. No-op (one env lookup) when
    metrics are off."""
    if metrics_mode() == "off":
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + value
    _maybe_start_writer()


def collective_bytes(name: str, nbytes: float,
                     fabric: Optional[str] = None) -> None:
    """Per-fabric collective byte accounting (round 11 bugfix):
    ``collective.{name}.bytes`` used to lump ICI and DCN traffic into
    one number, which made the hierarchical schedules' whole point —
    moving bytes OFF the slow fabric — invisible in the registry. The
    aggregate counter still carries every byte (dashboards keyed on it
    keep working, and flat meshes — ``fabric=None`` — see no new
    counters at all); when the caller resolves a fabric via
    :mod:`pylops_mpi_tpu.parallel.topology`, the same bytes ALSO land
    in ``collective.{name}.bytes_ici`` / ``.bytes_dcn``. A split
    emission (one call per fabric share of a two-level collective) sums
    back to the legacy counter by construction.

    Round 14: ``fabric="h2d"``/``"d2h"`` account the host-staging
    transfers of the spill tier (``parallel/spill.py``) into
    ``collective.{name}.bytes_h2d`` / ``.bytes_d2h`` ONLY — host↔device
    copies are not inter-device payload, so they never inflate the
    legacy ``.bytes`` counter dashboards key on."""
    if metrics_mode() == "off":
        return
    if fabric in ("h2d", "d2h"):
        inc(f"collective.{name}.bytes_{fabric}", nbytes)
        return
    inc(f"collective.{name}.bytes", nbytes)
    if fabric in ("ici", "dcn"):
        inc(f"collective.{name}.bytes_{fabric}", nbytes)


def set_gauge(name: str, value: float) -> None:
    """Set gauge ``name`` to its latest ``value`` (last-write-wins)."""
    if metrics_mode() == "off":
        return
    with _LOCK:
        _GAUGES[name] = value
    _maybe_start_writer()


def observe(name: str, value: float) -> None:
    """Record one sample into histogram ``name`` (count/sum/min/max/
    last summary)."""
    if metrics_mode() == "off":
        return
    value = float(value)
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            _HISTS[name] = {"count": 1, "sum": value, "min": value,
                            "max": value, "last": value}
        else:
            h["count"] += 1
            h["sum"] += value
            h["min"] = min(h["min"], value)
            h["max"] = max(h["max"], value)
            h["last"] = value
        ring = _HSAMPLES.get(name)
        if ring is None:
            ring = _HSAMPLES[name] = deque(maxlen=_HSAMPLES_MAX)
        ring.append(value)
    _maybe_start_writer()


def hist_quantiles(name: str,
                   qs: Sequence[float] = (0.5, 0.99)
                   ) -> Optional[Dict[str, float]]:
    """Quantiles over histogram ``name``'s recent-sample ring (last
    ``512`` observations): ``{"p50": ..., "p99": ...}`` by default, or
    ``None`` when the histogram has no samples (or metrics are off).
    Nearest-rank on the sorted window — good enough for the serving
    backpressure report, and O(window) only when asked, never per
    observation."""
    with _LOCK:
        ring = _HSAMPLES.get(name)
        samples = sorted(ring) if ring else None
    if not samples:
        return None
    n = len(samples)
    out = {}
    for q in qs:
        q = min(1.0, max(0.0, float(q)))
        idx = min(n - 1, max(0, int(round(q * (n - 1)))))
        out[f"p{q * 100:g}"] = samples[idx]
    return out


class _Timer:
    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name
        self.t0 = 0.0

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        observe(self.name + ".wall_s", time.perf_counter() - self.t0)
        return False


class _NoopTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_TIMER = _NoopTimer()


def timer(name: str):
    """Context manager observing the block's wall time into histogram
    ``<name>.wall_s`` — the per-stage wall metric around the solver
    entry points. Shared no-op when metrics are off."""
    if metrics_mode() == "off":
        return _NOOP_TIMER
    return _Timer(name)


def snapshot() -> Dict:
    """The registry as one JSON-safe dict:
    ``{"schema", "pid", "wall", "counters", "gauges", "histograms"}``.
    Cheap (one lock, shallow copies) — safe to embed in every
    heartbeat."""
    with _LOCK:
        return {"schema": SNAPSHOT_SCHEMA, "pid": os.getpid(),
                "wall": time.time(),
                "counters": dict(_COUNTERS),
                "gauges": dict(_GAUGES),
                "histograms": {k: dict(v) for k, v in _HISTS.items()}}


def clear_metrics() -> None:
    """Drop every recorded value (test-isolation helper). The snapshot
    writer thread, once started, stays running — it will just write
    empty registries."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _HSAMPLES.clear()


# ------------------------------------------------- snapshot persistence
def write_snapshot(path: Optional[str] = None) -> Optional[str]:
    """Write :func:`snapshot` to ``path`` (default:
    :func:`metrics_file`) atomically — pid-suffixed temp +
    ``os.replace``, so a reader can never observe a torn snapshot.
    Returns the path written, or ``None`` when no path is configured.
    A failed write is swallowed: persistence must never take the
    workload down (the heartbeat/plan-cache rule)."""
    path = path or metrics_file()
    if not path:
        return None
    path = os.path.abspath(path)
    tmp = path + f".tmp{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(snapshot(), f)
        os.replace(tmp, path)
        return path
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None


def read_snapshot(path: str) -> Optional[Dict]:
    """Parse a snapshot file: the dict, or ``None`` when missing /
    (transiently) unparseable / not a snapshot — the supervisor-side
    reader, so every failure mode is a quiet miss."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "counters" not in doc:
        return None
    return doc


_WRITER_LOCK = threading.Lock()
_writer_started = False


def _maybe_start_writer() -> None:
    """Start the periodic snapshot-writer daemon thread once, iff a
    snapshot file is configured. Called from every record path with a
    plain-bool fast exit, so steady-state cost is one attribute read."""
    global _writer_started
    if _writer_started or not metrics_file():
        return
    with _WRITER_LOCK:
        if _writer_started:
            return
        _writer_started = True
        import atexit
        atexit.register(write_snapshot)

        def loop():
            while True:
                time.sleep(metrics_interval())
                write_snapshot()

        threading.Thread(target=loop, daemon=True,
                         name="pylops-metrics").start()
    write_snapshot()  # first snapshot immediately, like the first beat

"""Fleet-observability CLI: ``python -m pylops_mpi_tpu.diagnostics``.

Subcommands (jax-free — everything here is host-side file crunching,
so it runs on a login node or in CI without touching an accelerator):

``aggregate <dir-or-files...>``
    Merge per-worker Chrome-trace JSONLs (the
    ``PYLOPS_MPI_TPU_TRACE_FILE`` artifacts of a supervised job) into
    ONE clock-aligned fleet trace with ``pid=rank``, every matched
    collective stamped with ``skew_us`` + ``straggler_rank``, and a
    per-solve critical-path summary (:mod:`.aggregate`). ``--out``
    writes the merged trace (``--fmt chrome`` opens directly in
    Perfetto; ``jsonl`` keeps the line-per-event artifact shape).

``metrics <snapshot-or-logdir...>``
    Pretty-print metrics snapshots (``*.metrics.json`` written by
    :mod:`.metrics`, or a supervisor logdir containing them /
    ``job_report.json``) as one combined per-worker table.

Output contract: progress goes to stderr; the LAST stdout line is one
compact JSON summary (the ``bench._run_json_cmd`` salvage convention
shared with ``python -m pylops_mpi_tpu.tuning``). Exit is nonzero only
on usage errors — tolerant loading is the whole point of a post-mortem
tool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from . import aggregate as _agg
from . import metrics as _metrics


def _eprint(msg: str) -> None:
    print(f"[diagnostics] {msg}", file=sys.stderr, flush=True)


def _cmd_aggregate(args) -> int:
    files = _agg.discover_trace_files(args.paths)
    if not files:
        _eprint(f"no trace files found under {args.paths}")
        print(json.dumps({"ok": False, "error": "no trace files"}))
        return 1
    _eprint(f"aggregating {len(files)} trace file(s)")
    result = _agg.aggregate_files(files, ranks=args.ranks)
    events = result["events"]
    if args.out:
        if args.fmt == "chrome":
            with open(args.out, "w") as f:
                json.dump({"traceEvents": events}, f)
        else:
            with open(args.out, "w") as f:
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
        _eprint(f"merged trace ({len(events)} events, "
                f"{len(result['ranks'])} ranks) -> {args.out}")
    worst = max(result["collectives"], key=lambda c: c["skew_us"],
                default=None)
    summary = {"ok": True, "ranks": result["ranks"],
               "n_events": len(events),
               "n_collectives_matched": len(result["collectives"]),
               "offsets_us": result["offsets_us"],
               "max_skew": worst,
               "critical_path": result["critical_path"],
               "out": args.out}
    if args.summary_out:
        full = dict(summary)
        full["collectives"] = result["collectives"]
        full["sources"] = result["sources"]
        with open(args.summary_out, "w") as f:
            json.dump(full, f, indent=1)
    print(json.dumps(summary))
    return 0


def _find_metric_files(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                if name.endswith(".metrics.json") \
                        or name == "job_report.json":
                    out.append(os.path.join(p, name))
        elif os.path.exists(p):
            out.append(p)
    return out


def _cmd_metrics(args) -> int:
    files = _find_metric_files(args.paths)
    if not files:
        _eprint(f"no metrics files found under {args.paths}")
        print(json.dumps({"ok": False, "error": "no metrics files"}))
        return 1
    docs = {}
    for path in files:
        name = os.path.basename(path)
        if name == "job_report.json":
            try:
                with open(path) as f:
                    docs[name] = json.load(f)
            except (OSError, ValueError):
                _eprint(f"unreadable job report {path}; skipped")
        else:
            snap = _metrics.read_snapshot(path)
            if snap is None:
                _eprint(f"unreadable snapshot {path}; skipped")
            else:
                docs[name] = snap
    for name, doc in docs.items():
        _eprint(f"-- {name}")
        for line in json.dumps(doc, indent=1,
                               sort_keys=True).splitlines():
            _eprint("   " + line)
    print(json.dumps({"ok": bool(docs), "files": sorted(docs)}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pylops_mpi_tpu.diagnostics",
        description="fleet observability: trace aggregation + metrics")
    sub = ap.add_subparsers(dest="cmd", required=True)

    agg = sub.add_parser("aggregate",
                         help="merge per-worker traces, stamp "
                              "skew/straggler per collective")
    agg.add_argument("paths", nargs="+",
                     help="trace JSONL files and/or directories "
                          "(e.g. a supervisor logdir)")
    agg.add_argument("--out", default=None,
                     help="write the merged trace here")
    agg.add_argument("--fmt", choices=("chrome", "jsonl"),
                     default="chrome",
                     help="merged-trace format (default: chrome array, "
                          "opens in Perfetto)")
    agg.add_argument("--summary-out", default=None,
                     help="write the full aggregation summary JSON "
                          "(all matched collectives) here")
    agg.add_argument("--ranks", type=int, nargs="*", default=None,
                     help="explicit rank per input file (default: "
                          "parse filenames, fall back to order)")
    agg.set_defaults(fn=_cmd_aggregate)

    met = sub.add_parser("metrics",
                         help="pretty-print metrics snapshots / a job "
                              "report")
    met.add_argument("paths", nargs="+",
                     help="snapshot files and/or supervisor logdirs")
    met.set_defaults(fn=_cmd_metrics)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

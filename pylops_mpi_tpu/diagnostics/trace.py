"""Structured span tracer — Chrome-trace-event JSONL.

The runtime-observability entry layer (round 9): a lightweight span
tracer with a context-manager API, nested spans, monotonic timestamps
and a thread-safe ring buffer. Every operator ``matvec``/``rmatvec``
(``linearoperator.py``), every hand-scheduled collective
(``parallel/collectives.py``: ``ring_pass``,
``chunked_pencil_transpose``, ``plane_all_to_all``, the halo
exchanges) and every solver call (``solvers/*``) opens a span tagged
with shapes, dtypes, overlap mode and mesh axes. One-shot notes that
previously went to stdout/logging (``resolve_chunks`` fallbacks, SUMMA
schedule selection) land here as instant events, so they ride in the
JSONL artifact instead of scrolling away.

Gating — ``PYLOPS_MPI_TPU_TRACE``:

- ``off`` (default): every entry point returns a shared no-op; the
  only cost is one env lookup per call. Nothing is ever added to a
  traced program, so compiled HLO is BIT-IDENTICAL to untraced runs
  (the exact-equality overlap/precision suites pin this).
- ``spans``: operator / collective / solver spans and structured
  events are recorded.
- ``full``: additionally enables the in-loop solver telemetry
  (:mod:`.telemetry` — per-iteration residual norms via
  ``jax.debug.callback``; the only mode that changes compiled
  programs).

Timestamp semantics: spans record HOST wall-clock (``perf_counter_ns``
relative to process start). A span around code running under a ``jit``
trace measures *trace time*, not device time — such spans are tagged
``"jax_tracing": true``; they still carry the schedule metadata
(shapes, chunk counts, byte estimates), which is their real payload.
Device-side timing belongs to :mod:`.profiler`'s ``jax.profiler``
capture.

Events are Chrome trace-event dicts (``ph`` ``X``/``i``/``C``), one
JSON object per line when dumped (``dump(path)``); set
``PYLOPS_MPI_TPU_TRACE_FILE`` to auto-dump at process exit. Open in
Perfetto via ``dump(path, fmt="chrome")`` (a single JSON array) or
``jq -s . trace.jsonl > trace.json``.

Post-mortem flush: when ``PYLOPS_MPI_TPU_TRACE_FILE`` is set, the
flush is registered for ``atexit`` AND ``SIGTERM`` (a supervised
worker's usual death is a signal, which skips atexit entirely), and it
is installed at the FIRST span *entry*, not just the first completed
event — a worker killed inside its very first span still leaves a
parseable artifact. Spans still open at flush time are emitted as
Chrome ``ph="B"`` (begin-without-end) events, so the post-mortem shows
exactly which phase the process died in. The SIGTERM handler chains
any previously-installed handler, then re-raises the default so the
exit status still says "killed by SIGTERM".
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["trace_mode", "trace_enabled", "span", "op_span", "event",
           "counter", "get_events", "clear_events", "dump", "span_tree",
           "open_span_events"]

_MODES = ("off", "spans", "full")
_warned_mode = False


def trace_mode() -> str:
    """``PYLOPS_MPI_TPU_TRACE`` resolved to ``off``/``spans``/``full``
    (unknown values fall back to ``off`` with a one-time warning — a
    typo in a CI matrix must not silently flip tracing on). Read per
    call (a dict lookup) so tests and long-lived sessions can flip the
    env without a cache to reset."""
    global _warned_mode
    m = os.environ.get("PYLOPS_MPI_TPU_TRACE", "off").strip().lower()
    if m in ("", "0", "none", "default"):
        m = "off"
    if m not in _MODES:
        if not _warned_mode:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_TRACE={m!r} is not one of {_MODES}; "
                "tracing stays off", stacklevel=2)
            _warned_mode = True
        m = "off"
    return m


def trace_enabled() -> bool:
    return trace_mode() != "off"


def _buffer_size() -> int:
    try:
        return max(1024, int(os.environ.get(
            "PYLOPS_MPI_TPU_TRACE_BUFFER", str(1 << 16))))
    except ValueError:
        return 1 << 16


# Ring buffer of completed Chrome events. A deque with maxlen drops the
# OLDEST events on overflow — a long solve can never grow host memory
# unboundedly; raise PYLOPS_MPI_TPU_TRACE_BUFFER to keep more.
_LOCK = threading.Lock()
_BUF: deque = deque(maxlen=_buffer_size())
_EPOCH_NS = time.perf_counter_ns()
_tls = threading.local()  # per-thread open-span stack (nesting depth)
_atexit_registered = False
# Cross-thread registry of OPEN spans (id(span) → span): the flush
# handlers read it to emit ph="B" events for phases cut short by a
# kill. Distinct from _tls.stack, which only the owning thread sees.
_OPEN: Dict[int, "_Span"] = {}


def _now_us() -> float:
    return (time.perf_counter_ns() - _EPOCH_NS) / 1e3


def _jsonable(v):
    """Best-effort JSON-safe value: tuples/lists recurse, numpy/jax
    scalars go through float/int, everything else falls back to
    ``str`` — a span tag must never be able to crash the traced
    workload."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:
        import numpy as np
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, (np.floating, np.number)):
            return float(v)
    except Exception:
        pass
    return str(v)


def _jax_tracing() -> bool:
    """True when called under an active jax trace (jit/shard_map/vmap
    tracing pass) — spans recorded there measure trace time, and are
    tagged so readers never mistake them for device time."""
    try:
        import jax.core
        return not jax.core.trace_state_clean()
    except Exception:
        return False


def _ensure_flush_handlers() -> None:
    """Register the exit-flush (atexit + SIGTERM) once, iff
    ``PYLOPS_MPI_TPU_TRACE_FILE`` is set. Called from both span entry
    and event recording, so a process killed inside its FIRST span
    (nothing completed yet) still flushes. Caller holds ``_LOCK``."""
    global _atexit_registered
    if _atexit_registered or not os.environ.get(
            "PYLOPS_MPI_TPU_TRACE_FILE"):
        return
    import atexit
    atexit.register(_atexit_dump)
    try:  # signal handlers only install from the main thread
        import signal
        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            _atexit_dump()
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)
            else:  # die with the honest "killed by SIGTERM" status
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: atexit still covers us
    _atexit_registered = True


def _record(ev: Dict) -> None:
    with _LOCK:
        _BUF.append(ev)
        _ensure_flush_handlers()


def _atexit_dump() -> None:
    path = os.environ.get("PYLOPS_MPI_TPU_TRACE_FILE")
    if path:
        try:
            dump(path)
        except Exception:
            pass  # a failed flush must never mask the real exit status


class _NoopSpan:
    """Shared do-nothing context manager — the entire cost of tracing
    when ``PYLOPS_MPI_TPU_TRACE=off`` (beyond the mode lookup)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, **tags):
        return self


_NOOP = _NoopSpan()


class _Span:
    """One open span: records a Chrome ``ph="X"`` (complete) event on
    exit, carrying its nesting depth and parent name so span trees can
    be rebuilt from the flat buffer (``span_tree``)."""

    __slots__ = ("name", "args", "t0", "_depth", "_parent", "_tid")

    def __init__(self, name: str, args: Dict):
        self.name = name
        self.args = args
        self.t0 = 0.0
        self._depth = 0
        self._parent = None
        self._tid = 0

    def tag(self, **tags) -> "_Span":
        """Attach tags discovered mid-span (e.g. a resolved chunk
        count) to the event that will be emitted at exit."""
        self.args.update({k: _jsonable(v) for k, v in tags.items()})
        return self

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._depth = len(stack)
        self._parent = stack[-1].name if stack else None
        stack.append(self)
        self.t0 = _now_us()
        self._tid = threading.get_ident()
        with _LOCK:
            _OPEN[id(self)] = self
            _ensure_flush_handlers()  # flush even if we never close
        return self

    def __exit__(self, *exc):
        t1 = _now_us()
        stack = getattr(_tls, "stack", ())
        if stack and stack[-1] is self:
            stack.pop()
        with _LOCK:
            _OPEN.pop(id(self), None)
        args = dict(self.args)
        args["depth"] = self._depth
        if self._parent is not None:
            args["parent"] = self._parent
        _record({"name": self.name, "ph": "X", "ts": round(self.t0, 3),
                 "dur": round(t1 - self.t0, 3), "pid": os.getpid(),
                 "tid": threading.get_ident(), "cat": args.pop(
                     "cat", "span"), "args": args})
        return False


def span(name: str, cat: str = "span", **tags):
    """Open a traced span (context manager). No-op when tracing is
    off. ``tags`` become the Chrome event's ``args``; tags are
    JSON-sanitized so arbitrary shapes/dtypes/meshes are safe to
    pass. Spans nest: each records its depth and parent name."""
    if trace_mode() == "off":
        return _NOOP
    args = {k: _jsonable(v) for k, v in tags.items()}
    if _jax_tracing():
        args["jax_tracing"] = True
    args["cat"] = cat
    return _Span(name, args)


def op_span(op, which: str):
    """Span for one operator apply — the wiring point used by
    ``MPILinearOperator.matvec``/``rmatvec``. Tags: operator class,
    operator shape, dtype, mesh axis names, and (when the operator
    carries them) overlap mode / schedule / grid. Returns the shared
    no-op when tracing is off so the eager hot path pays only the mode
    lookup."""
    if trace_mode() == "off":
        return _NOOP
    tags = {"op": type(op).__name__, "shape": getattr(op, "shape", None),
            "dtype": getattr(op, "dtype", None)}
    mesh = getattr(op, "mesh", None)
    if mesh is not None:
        tags["mesh_axes"] = getattr(mesh, "axis_names", None)
    for extra in ("overlap", "schedule", "grid", "compute_dtype"):
        v = getattr(op, extra, None)
        if v is not None:
            tags[extra] = v
    return span(f"{type(op).__name__}.{which}", cat="operator", **tags)


def event(name: str, cat: str = "event", **tags) -> None:
    """Instant event (Chrome ``ph="i"``): the structured replacement
    for one-shot stdout/log notes — ``resolve_chunks`` fallbacks,
    SUMMA schedule selection — so they land in the JSONL artifact."""
    if trace_mode() == "off":
        return
    args = {k: _jsonable(v) for k, v in tags.items()}
    if _jax_tracing():
        args["jax_tracing"] = True
    _record({"name": name, "ph": "i", "s": "t", "ts": round(_now_us(), 3),
             "pid": os.getpid(), "tid": threading.get_ident(),
             "cat": cat, "args": args})


def counter(name: str, values: Dict[str, float],
            cat: str = "telemetry") -> None:
    """Counter sample (Chrome ``ph="C"``): Perfetto renders these as
    time-series tracks — the shape the per-iteration solver telemetry
    lands in (:mod:`.telemetry`)."""
    if trace_mode() == "off":
        return
    _record({"name": name, "ph": "C", "ts": round(_now_us(), 3),
             "pid": os.getpid(), "tid": threading.get_ident(),
             "cat": cat, "args": {k: _jsonable(v)
                                  for k, v in values.items()}})


def get_events() -> List[Dict]:
    """Snapshot of the ring buffer (oldest first)."""
    with _LOCK:
        return list(_BUF)


def clear_events() -> None:
    """Drop buffered events AND forget open-span registrations (a test
    that leaked a span must not haunt later dumps; a leaked span's own
    ``__exit__`` pops nothing and stays harmless)."""
    with _LOCK:
        _BUF.clear()
        _OPEN.clear()


def open_span_events() -> List[Dict]:
    """Chrome ``ph="B"`` events for every span currently OPEN, across
    all threads — the post-mortem's "died while doing X" lines. Safe
    from signal/atexit context (one lock, no allocation surprises)."""
    with _LOCK:
        spans = list(_OPEN.values())
    out = []
    for s in spans:
        args = dict(s.args)
        args["open"] = True
        args["depth"] = s._depth
        if s._parent is not None:
            args["parent"] = s._parent
        out.append({"name": s.name, "ph": "B", "ts": round(s.t0, 3),
                    "pid": os.getpid(), "tid": s._tid,
                    "cat": args.pop("cat", "span"), "args": args})
    out.sort(key=lambda ev: ev["ts"])
    return out


def dump(path: str, fmt: str = "jsonl") -> int:
    """Write the buffered events to ``path``: ``fmt="jsonl"`` (one
    Chrome event object per line — the artifact format) or
    ``fmt="chrome"`` (a single JSON array Perfetto/chrome://tracing
    open directly). Spans still open at dump time are appended as
    ``ph="B"`` (begin) events so a killed process's in-flight phase
    survives to the artifact. Returns the number of events written."""
    events = get_events() + open_span_events()
    if fmt == "chrome":
        with open(path, "w") as f:
            json.dump(events, f)
    elif fmt == "jsonl":
        with open(path, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
    else:
        raise ValueError(f"fmt={fmt!r}: expected 'jsonl' or 'chrome'")
    return len(events)


def span_tree(events: Optional[List[Dict]] = None) -> List[Dict]:
    """Rebuild the span nesting from a flat event list: returns the
    roots, each ``{"name", "dur", "args", "children": [...]}`` — the
    verification handle for the nesting/ordering tests. Chrome ``X``
    events carry explicit ``depth``; reconstruction scans per-thread in
    END-time order (a parent's event is recorded after its
    children's), pushing each span under the most recent deeper-or-
    equal-depth run.

    Hardened for POST-MORTEM artifacts (ISSUE 10): the input may be a
    killed worker's flush, so non-dict entries, events with missing or
    mistyped fields, and unclosed ``ph="B"`` spans must all degrade
    gracefully instead of raising. ``B`` events (one still-open
    ancestry chain per thread) become nodes with ``dur=None``; spans
    whose parent never closed are adopted under the deepest open span
    shallower than them."""
    if events is None:
        events = get_events()
    roots: List[Dict] = []
    by_tid: Dict = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") not in ("X", "B"):
            continue
        if not isinstance(ev.get("name"), str) \
                or not isinstance(ev.get("ts"), (int, float)):
            continue  # a garbage line must not crash the post-mortem
        by_tid.setdefault(ev.get("tid"), []).append(ev)
    for tid_events in by_tid.values():
        stack: List = []  # (depth, node) of spans awaiting a parent
        open_chain: List = []  # (depth, node) of ph="B" open spans
        for ev in tid_events:  # buffer order == end-time order
            args = ev.get("args") if isinstance(ev.get("args"),
                                                dict) else {}
            depth = args.get("depth", 0)
            if not isinstance(depth, int) or depth < 0:
                depth = 0
            dur = ev.get("dur")
            node = {"name": ev["name"], "ts": ev["ts"],
                    "dur": dur if isinstance(dur, (int, float)) else None,
                    "args": args, "children": []}
            if ev.get("ph") == "B":
                open_chain.append((depth, node))
                continue
            while stack and stack[-1][0] > depth:
                node["children"].append(stack.pop()[1])
            node["children"].reverse()  # recorded youngest-first
            if depth == 0:
                roots.append(node)
            else:
                stack.append((depth, node))
        if open_chain:
            # the open spans of one thread form a single ancestry
            # chain (outermost first after the depth sort); completed
            # spans still awaiting a parent were inside the deepest
            # open span shallower than them
            open_chain.sort(key=lambda p: p[0])
            for i in range(len(open_chain) - 1):
                open_chain[i][1]["children"].append(open_chain[i + 1][1])
            for d, n in stack:
                host = None
                for bd, bn in open_chain:
                    if bd < d:
                        host = bn
                (host["children"].append(n) if host is not None
                 else roots.append(n))
            stack = []
            roots.append(open_chain[0][1])
        # orphans (parent span still open at snapshot time)
        roots.extend(n for _, n in stack)
    roots.sort(key=lambda n: n["ts"])
    return roots

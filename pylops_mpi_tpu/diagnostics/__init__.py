"""Runtime observability subsystem (round 9).

The reference ships no runtime introspection at all; after three perf
rounds this repo had many tuned kernels and zero visibility into where
time, bytes and iterations actually go (VERDICT round 5: a 900 s
harvest stage burned a rare ~20-minute TPU window producing nothing).
Four modules make the folklore first-class:

- :mod:`~pylops_mpi_tpu.diagnostics.trace` — structured span tracer
  (context-manager API, nested spans, thread-safe ring buffer) emitting
  Chrome-trace-event JSONL, gated by ``PYLOPS_MPI_TPU_TRACE``; wired
  through every operator ``matvec``/``rmatvec``, the hand-scheduled
  collectives, and the solver entry points.
- :mod:`~pylops_mpi_tpu.diagnostics.costmodel` — per-op cost registry
  (FLOPs, HBM bytes, ICI bytes per apply) generalizing the comm-volume
  model previously private to ``ops/matrixmult.py``'s auto-select,
  plus the per-chip peak tables and a roofline predictor
  (``bench.py`` stamps predicted-vs-measured on every row).
- :mod:`~pylops_mpi_tpu.diagnostics.telemetry` — per-iteration
  convergence telemetry captured from INSIDE the fused solver
  ``while_loop``\\ s via ``jax.debug.callback``; off by default, with
  an HLO pin (``utils/hlo.py::assert_no_host_callbacks``) proving the
  donated/fused hot path carries zero host callbacks when disabled.
- :mod:`~pylops_mpi_tpu.diagnostics.profiler` — ``jax.profiler``
  trace-capture hooks plus the deadline-aware stage runner and the
  central per-stage wall-budget table consumed by the harvest ladder
  (``bench.py``, ``benchmarks/tpu_probe_loop.py``,
  ``benchmarks/rehearse_ladder.py``).

Fleet observability (ISSUE 10) adds the cross-process half:

- :mod:`~pylops_mpi_tpu.diagnostics.metrics` — process-wide
  counters/gauges/histograms (solver iterations, guard verdicts,
  collective bytes, plan-cache hits, retries, per-stage wall) gated by
  ``PYLOPS_MPI_TPU_METRICS``, with atomic periodic snapshots and the
  snapshot embedded in every supervised heartbeat.
- :mod:`~pylops_mpi_tpu.diagnostics.aggregate` — merges per-worker
  trace JSONLs into ONE clock-aligned Chrome trace (``pid=rank``),
  stamping every matched collective with ``skew_us`` +
  ``straggler_rank`` and computing per-solve critical paths.
- ``python -m pylops_mpi_tpu.diagnostics`` — the jax-free CLI over
  both (:mod:`~pylops_mpi_tpu.diagnostics.__main__`).

See ``docs/observability.md`` for the env knobs and artifact schema.
"""

from . import trace
from . import costmodel
from . import telemetry
from . import profiler
from . import metrics
from . import aggregate

from .trace import (trace_mode, trace_enabled, span, event, counter,
                    get_events, clear_events, dump, span_tree)
from .costmodel import (OpCost, estimate, register_cost, roofline,
                        summa_comm_volume, pencil_transpose_cost,
                        peak_flops, peak_hbm_gbps, peak_ici_gbps,
                        device_peaks)
from .telemetry import (telemetry_enabled, iteration, history,
                        clear_history, telemetry_signature)
from .profiler import (STAGE_BUDGETS, stage_budget, DeadlineRunner,
                       profile_capture)
from .metrics import (metrics_mode, metrics_enabled, inc, set_gauge,
                      observe, timer, snapshot, clear_metrics,
                      write_snapshot, read_snapshot)
from .aggregate import (load_events, merge_traces, aggregate_files,
                        critical_path)

__all__ = [
    "trace", "costmodel", "telemetry", "profiler", "metrics",
    "aggregate",
    "metrics_mode", "metrics_enabled", "inc", "set_gauge", "observe",
    "timer", "snapshot", "clear_metrics", "write_snapshot",
    "read_snapshot",
    "load_events", "merge_traces", "aggregate_files", "critical_path",
    "trace_mode", "trace_enabled", "span", "event", "counter",
    "get_events", "clear_events", "dump", "span_tree",
    "OpCost", "estimate", "register_cost", "roofline",
    "summa_comm_volume", "pencil_transpose_cost", "peak_flops",
    "peak_hbm_gbps", "peak_ici_gbps", "device_peaks",
    "telemetry_enabled", "iteration", "history", "clear_history",
    "telemetry_signature",
    "STAGE_BUDGETS", "stage_budget", "DeadlineRunner", "profile_capture",
]

"""Per-op cost models and roofline placement.

"Large Scale Distributed Linear Algebra With TPUs" attributes its
results with per-collective byte accounting and roofline placement,
and "Memory-efficient array redistribution" (arXiv 2112.01075) shows
redistribution cost is predictable enough to assert against. This
module makes both first-class instead of bench-script folklore:

- :class:`OpCost` — FLOPs, HBM bytes and ICI (inter-chip) bytes for
  ONE apply of an operator, per device;
- a registry (:func:`register_cost` / :func:`estimate`) with models
  for the production operator families (MatrixMult block/SUMMA,
  BlockDiag, V/HStack, the distributed FFTs' pencil transposes, the
  halo-exchange stencils) that recurses through the lazy composition
  wrappers (product/sum/scaled/adjoint);
- :func:`summa_comm_volume` — the per-device communication-volume
  model that ``ops/matrixmult.py``'s ``schedule="auto"`` previously
  kept private (it now calls this function), exposed so tests can
  hand-check it and bench rows can cite it;
- the per-chip peak tables (dense-matmul TFLOP/s, HBM GB/s —
  the figures ``bench.py`` has carried since rounds 2/7 — plus an
  APPROXIMATE aggregate ICI GB/s per chip) and :func:`roofline`,
  which converts an :class:`OpCost` + peaks into a predicted time and
  a bound ("compute" / "hbm" / "ici") so ``bench.py`` stamps
  predicted-vs-measured on every row.

Counting conventions (what the hand-count tests pin):

- FLOPs: a real GEMM ``(m, k) @ (k, n)`` costs ``2·m·k·n``; complex
  costs 4× that (4 real multiplies + accumulation, counted as
  ``8·m·k·n`` total). FFTs count the standard ``5·n·log2(n)`` per
  length-``n`` transform.
- HBM bytes: operand + result traffic assuming each buffer streams
  once per apply (matrices at their STORAGE dtype — the
  ``compute_dtype`` lever halves this — vectors at theirs). On-chip
  (VMEM) residency makes the true figure smaller; the model is an
  upper bound, exactly like the bench's ``hbm_pct`` qualifier.
- ICI bytes: bytes RECEIVED per device per apply. An all-gather over
  ``P`` devices of a result of ``B`` bytes receives ``B·(P-1)/P``;
  a tiled all-to-all moves ``B·(P-1)/P`` of the local block; a psum
  (ring all-reduce) ``2·B·(P-1)/P``; a ppermute exactly its slab.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["OpCost", "estimate", "register_cost", "roofline",
           "summa_comm_volume", "summa_comm_volume_split",
           "pencil_transpose_cost",
           "peak_flops", "peak_hbm_gbps", "peak_ici_gbps",
           "peak_dcn_gbps", "allreduce_latency_s",
           "device_peaks", "PEAK_TFLOPS", "PEAK_HBM_GBPS",
           "PEAK_ICI_GBPS", "PEAK_DCN_GBPS", "ALLREDUCE_LATENCY_S"]


# ------------------------------------------------------------- peak tables
# Dense matmul peak per chip, TFLOP/s (bf16 inputs, f32 accumulation on
# the MXU) — public spec-sheet numbers; most-specific key first. The
# f32 peak under the package's `highest` matmul-precision pin is bf16/6
# (3 products x 2 operand splits — bench.py round-4 correction).
PEAK_TFLOPS = [
    ("v6e", 918.0), ("v6 lite", 918.0), ("v6", 918.0),
    ("v5p", 459.0), ("v5e", 197.0), ("v5 lite", 197.0), ("v5", 459.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]

# HBM bandwidth peak per chip, GB/s — public spec-sheet numbers (the
# denominator every hbm_gbps claim is divided by; docs/design.md
# round-7 correction).
PEAK_HBM_GBPS = [
    ("v6e", 1640.0), ("v6 lite", 1640.0), ("v6", 1640.0),
    ("v5p", 2765.0), ("v5e", 819.0), ("v5 lite", 819.0), ("v5", 2765.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]

# APPROXIMATE aggregate ICI bandwidth per chip, GB/s (sum over links,
# derived from published per-pod interconnect figures: v5p 4800 Gb/s,
# v5e 1600 Gb/s, v6e 3584 Gb/s, v4 2400 Gb/s; older chips rougher).
# Good for roofline PLACEMENT (is this apply compute-, HBM- or
# ICI-bound, within ~2x), not for bandwidth claims — unknown chips get
# NO ICI roofline rather than a wrong one.
PEAK_ICI_GBPS = [
    ("v6e", 448.0), ("v6 lite", 448.0), ("v6", 448.0),
    ("v5p", 600.0), ("v5e", 200.0), ("v5 lite", 200.0), ("v5", 600.0),
    ("v4", 300.0), ("v3", 280.0), ("v2", 160.0),
]

# APPROXIMATE per-chip DCN bandwidth, GB/s (round 11): the inter-slice
# fabric is the hosts' datacenter NICs shared by each host's local
# chips — roughly a 100-200 Gb/s NIC over 4 chips. Like the ICI table
# this is for roofline PLACEMENT and for the ~10-30x ICI:DCN ratio the
# hierarchical schedules exploit, not for bandwidth claims; unknown
# chips get NO DCN roofline. Single-slice deployments never produce
# dcn_bytes, so these entries are inert off multislice.
PEAK_DCN_GBPS = [
    ("v6e", 12.5), ("v6 lite", 12.5), ("v6", 12.5),
    ("v5p", 25.0), ("v5e", 6.25), ("v5 lite", 6.25), ("v5", 25.0),
    ("v4", 6.25), ("v3", 6.25), ("v2", 6.25),
]

# APPROXIMATE per-fabric all-reduce LATENCY, seconds (round 17): the
# α term of the α–β model, i.e. the floor one small (few-scalar)
# all-reduce pays regardless of payload. A Krylov iteration's dot
# products are exactly such reductions, so on DCN-connected pods the
# iteration time is `max(apply, n_reductions * α)` — this is the term
# the communication-avoiding tier (solvers/ca.py) exists to shrink,
# and the selection signal its `auto` mode reads. Like the bandwidth
# tables these are placement numbers (order-of-magnitude per fabric
# class), not measurements: ICI ~ microseconds, DCN ~ tens of
# microseconds per software-pipelined hop tree, `host` ~ the CPU-sim /
# single-host dispatch floor.
ALLREDUCE_LATENCY_S = {
    "ici": 2e-6,
    "dcn": 50e-6,
    "host": 20e-6,
}


def allreduce_latency_s(fabric: str) -> Optional[float]:
    """Per-fabric small-all-reduce latency floor (seconds); ``None``
    for unknown fabric names rather than a wrong constant."""
    return ALLREDUCE_LATENCY_S.get((fabric or "").strip().lower())


def _lookup(table, device_kind: str) -> Optional[float]:
    kind = (device_kind or "").lower()
    for key, val in table:
        if key in kind:
            return val
    return None


def peak_flops(device_kind: str, mode: str = "bf16") -> Optional[float]:
    """Per-chip dense-matmul peak (FLOP/s) for ``mode`` (``bf16`` or
    ``f32_highest`` — the latter is bf16/6 under the package's
    precision pin). ``None`` for unknown chips."""
    tf = _lookup(PEAK_TFLOPS, device_kind)
    if tf is None:
        return None
    peak = tf * 1e12
    return peak / 6.0 if mode.startswith("f32") else peak


def peak_hbm_gbps(device_kind: str) -> Optional[float]:
    """Per-chip HBM bandwidth peak, GB/s (None for unknown chips — an
    unknown chip gets NO roofline rather than a wrong one)."""
    return _lookup(PEAK_HBM_GBPS, device_kind)


def peak_ici_gbps(device_kind: str) -> Optional[float]:
    """APPROXIMATE aggregate per-chip ICI bandwidth, GB/s (see table
    note); None for unknown chips."""
    return _lookup(PEAK_ICI_GBPS, device_kind)


def peak_dcn_gbps(device_kind: str) -> Optional[float]:
    """APPROXIMATE per-chip DCN (inter-slice) bandwidth, GB/s (see
    table note); None for unknown chips."""
    return _lookup(PEAK_DCN_GBPS, device_kind)


def device_peaks(device=None, mode: str = "bf16") -> Dict:
    """Peak dict for :func:`roofline` from a live ``jax.Device``
    (default: ``jax.devices()[0]``): ``{"flops", "hbm_gbps",
    "ici_gbps", "device_kind", "platform"}`` with ``None`` entries off
    TPU / on unknown chips."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    platform = getattr(device, "platform", "")
    if platform != "tpu":
        return {"flops": None, "hbm_gbps": None, "ici_gbps": None,
                "dcn_gbps": None,
                "allreduce_latency_s": allreduce_latency_s("host"),
                "device_kind": kind, "platform": platform}
    return {"flops": peak_flops(kind, mode),
            "hbm_gbps": peak_hbm_gbps(kind),
            "ici_gbps": peak_ici_gbps(kind),
            "dcn_gbps": peak_dcn_gbps(kind),
            "allreduce_latency_s": allreduce_latency_s("ici"),
            "device_kind": kind, "platform": platform}


# ----------------------------------------------------------------- OpCost
@dataclass
class OpCost:
    """Cost of ONE operator apply, PER DEVICE: floating-point
    operations, HBM bytes streamed, ICI bytes received — and, on
    hybrid meshes (round 11), DCN bytes received, split out because
    the two fabrics differ by ~10-30x in bandwidth and a single
    "inter-chip bytes" number hides exactly what the hierarchical
    schedules optimize. ``ici_bytes`` stays the intra-slice share (NOT
    the total), so ``ici + dcn`` is total off-chip traffic; flat
    meshes keep ``dcn_bytes == 0`` and every pre-round-11 model reads
    unchanged. ``dcn_bytes`` sits after ``notes`` so existing
    positional constructors keep their meaning. ``notes`` carries
    model provenance (which registry entry, which schedule).

    ``reductions_per_iter`` (round 17, appended last for the same
    positional-compat reason): how many latency-bound small
    all-reduces the cost's unit of work issues — the count the
    roofline's α-term ``latency`` component multiplies by the
    per-fabric :data:`ALLREDUCE_LATENCY_S` constant. 0 (the default)
    keeps every pre-round-17 model and roofline unchanged."""

    flops: float = 0.0
    hbm_bytes: float = 0.0
    ici_bytes: float = 0.0
    notes: Tuple[str, ...] = field(default_factory=tuple)
    dcn_bytes: float = 0.0
    reductions_per_iter: float = 0.0

    def __add__(self, other: "OpCost") -> "OpCost":
        return OpCost(self.flops + other.flops,
                      self.hbm_bytes + other.hbm_bytes,
                      self.ici_bytes + other.ici_bytes,
                      self.notes + other.notes,
                      self.dcn_bytes + other.dcn_bytes,
                      self.reductions_per_iter
                      + other.reductions_per_iter)

    def scaled(self, k: float) -> "OpCost":
        return OpCost(self.flops * k, self.hbm_bytes * k,
                      self.ici_bytes * k, self.notes,
                      self.dcn_bytes * k, self.reductions_per_iter * k)

    def as_dict(self) -> Dict:
        return {"flops": self.flops, "hbm_bytes": self.hbm_bytes,
                "ici_bytes": self.ici_bytes,
                "dcn_bytes": self.dcn_bytes,
                "reductions_per_iter": self.reductions_per_iter,
                "notes": list(self.notes)}


def _itemsize(dt) -> int:
    if dt is None:
        return 4
    try:
        return np.dtype(dt).itemsize
    except TypeError:
        # jnp dtypes like bfloat16 that numpy doesn't know natively
        import jax.numpy as jnp
        return jnp.dtype(dt).itemsize


def _flop_factor(dt) -> float:
    """Complex GEMMs cost 4 real multiply-accumulate pairs per term."""
    try:
        return 4.0 if np.issubdtype(np.dtype(dt), np.complexfloating) \
            else 1.0
    except TypeError:
        return 1.0


# ------------------------------------------------------------- comm models
def summa_comm_volume(N: int, K: int, M: int,
                      grid: Tuple[int, int]) -> Dict[str, float]:
    """Per-device ELEMENT volume received per forward apply of the two
    SUMMA schedules, on padded tiles over a ``(pr, pc)`` grid — the
    model ``ops/matrixmult.py``'s ``schedule="auto"`` selects with
    (previously inlined there; ring/bulk variants move the same bytes,
    only the interleaving differs):

    - ``gather``: all-gather the A row-block along ``c`` + all-gather
      the X column along ``r``;
    - ``stat_a``: A never moves — all-gather X fully (both axes), then
      reduce-scatter the partial products along ``c``.

    Returns ``{"gather": ..., "stat_a": ..., "adjoint": ...}``
    (adjoint = the stationary-A Y-gather + r-psum schedule).
    """
    split = summa_comm_volume_split(N, K, M, grid)
    return {k: v["r"] + v["c"] for k, v in split.items()}


def summa_comm_volume_split(N: int, K: int, M: int,
                            grid: Tuple[int, int]
                            ) -> Dict[str, Dict[str, float]]:
    """:func:`summa_comm_volume` split BY GRID AXIS — per schedule,
    the per-device element volume received over the ``r`` (row) and
    ``c`` (column) axis collectives separately. This is the per-fabric
    attribution seam (round 11): on a hybrid mesh whose grid is
    fabric-aligned (rows = slices, so ``r`` collectives ride DCN and
    ``c`` collectives ride ICI — the layout ``ops/matrixmult.py`` pins
    when the hierarchical seam is on), each axis's volume IS that
    fabric's bytes. A topology-blind schedule gets the conservative
    charge instead: with no pinned axis→fabric assignment, every
    collective may ride the slow fabric, so the whole total is
    DCN-attributed (how the flat baseline of the ``hierarchical_vs_flat``
    bench row and the ≥3x acceptance ratio are counted)."""
    pr, pc = int(grid[0]), int(grid[1])
    Np = pr * math.ceil(N / pr)
    Kp_r = pr * math.ceil(K / pr)
    Kp_c = pc * math.ceil(K / pc)
    Mp = pc * math.ceil(M / pc)
    gather = {"c": (Np // pr) * Kp_c * (pc - 1) / pc,
              "r": Kp_r * (Mp // pc) * (pr - 1) / pr}
    stat_a = {"r": Kp_r * (Mp // pc) * (pr - 1) / pr,
              "c": (Kp_r * Mp * (pc - 1) / pc
                    + (Np // pr) * Mp * (pc - 1) / pc)}
    # adjoint: gather Y row along 'c' ((Np/pr, Mp) result), then psum
    # the (Kp_c/pc, Mp) partial over 'r' (ring all-reduce ~ 2(pr-1)/pr)
    adjoint = {"c": (Np // pr) * Mp * (pc - 1) / pc,
               "r": (Kp_c // pc) * Mp * 2 * (pr - 1) / pr}
    return {"gather": gather, "stat_a": stat_a, "adjoint": adjoint}


def pencil_transpose_cost(shape: Tuple[int, ...], n_dev: int,
                          itemsize: int = 8,
                          n_transposes: int = 2,
                          fabric_shape: Optional[Tuple[int, int]] = None,
                          hierarchical: bool = False) -> OpCost:
    """Off-chip cost of the distributed FFT's pencil transpose(s):
    each tiled all-to-all of the full array moves ``(P-1)/P`` of the
    local block off-chip, regardless of chunking
    (``chunked_pencil_transpose`` streams the SAME bytes in K pieces).
    ``itemsize`` is the element size on the wire — 8 for c64, 2×4 for
    the planar (re, im) f32 plane pair (identical bytes for the full
    spectrum; ~half for a real transform's half-spectrum, which the
    caller accounts by passing the half-spectrum shape). HBM term: one
    read + one write of the local block per transpose.

    ``fabric_shape=(D, I)`` (round 11) splits the off-chip bytes per
    fabric on a D-slice hybrid mesh of I devices each:

    - ``hierarchical=True`` — the two-level schedule
      (:func:`~pylops_mpi_tpu.parallel.collectives.hier_pencil_transpose`):
      the intra-slice all-to-all moves ``(I-1)/I`` of the local block
      on ICI, the staged inter-slice exchange ``(D-1)/D`` on DCN.
    - ``hierarchical=False`` — the topology-blind baseline. A flat
      tuple-axis all-to-all on a hybrid mesh does NOT lower to a
      pointwise exchange: GSPMD's portable cross-slice decomposition
      gathers the array (the generic-reshard lowering ``ops/fft.py``
      documents for multi-axis meshes), so each device receives
      ``(I-1)`` local blocks over ICI and ``(P-I)`` over DCN — the
      D-fold DCN inflation the hierarchical schedule removes.

    ``fabric_shape=None`` (flat mesh) keeps the pre-round-11 model
    verbatim: all off-chip bytes in ``ici_bytes``, ``dcn_bytes == 0``.
    """
    n_total = float(np.prod(shape))
    local_bytes = n_total * itemsize / max(n_dev, 1)
    frac = (n_dev - 1) / n_dev if n_dev > 1 else 0.0
    ici = local_bytes * frac * n_transposes
    dcn = 0.0
    notes = (f"pencil_transpose x{n_transposes}",)
    if fabric_shape is not None:
        d, i = int(fabric_shape[0]), int(fabric_shape[1])
        if d > 1 and i >= 1 and d * i == n_dev:
            if hierarchical:
                ici = local_bytes * (i - 1) / i * n_transposes
                dcn = local_bytes * (d - 1) / d * n_transposes
                notes = (f"pencil_transpose x{n_transposes} "
                         f"hier[dcn{d}xici{i}]",)
            else:
                ici = local_bytes * (i - 1) * n_transposes
                dcn = local_bytes * (n_dev - i) * n_transposes
                notes = (f"pencil_transpose x{n_transposes} "
                         f"flat-on-hybrid[dcn{d}xici{i}:gather]",)
    return OpCost(flops=0.0,
                  hbm_bytes=2.0 * local_bytes * n_transposes,
                  ici_bytes=ici, notes=notes, dcn_bytes=dcn)


# ------------------------------------------------------------ the registry
_REGISTRY: Dict[type, Callable] = {}


def register_cost(cls, fn: Callable) -> None:
    """Register ``fn(op, direction) -> OpCost`` for operator class
    ``cls`` (``direction`` in {"forward", "adjoint"}). Subclasses
    resolve through the MRO, most-derived first."""
    _REGISTRY[cls] = fn


def estimate(op, direction: str = "forward") -> Optional[OpCost]:
    """Per-device cost of one ``direction`` apply of ``op``, or
    ``None`` when no model (or no composable sub-model) exists —
    callers must treat a missing model as "unknown", never as zero."""
    if direction not in ("forward", "adjoint"):
        raise ValueError(f"direction={direction!r}")
    _bind_builtin()
    for cls in type(op).__mro__:
        fn = _REGISTRY.get(cls)
        if fn is not None:
            return fn(op, direction)
    return None


def _n_dev(op) -> int:
    mesh = getattr(op, "mesh", None)
    if mesh is None:
        return 1
    return int(mesh.devices.size)


# --- models for the production families (registered at the bottom of
# the modules that define the classes would create import cycles; the
# registry binds lazily by class object at first `estimate` call
# instead, via the _builtin table of dotted names).

def _cost_sparse_matmul(op, direction: str) -> OpCost:
    """Sparse matmul tier: flops and matrix bytes scale with ``nnz``
    (value + two int32 indices per triplet), not ``N·M`` — the whole
    point of the tier. Adjoint charges the scatter's cross-shard
    combine (psum-shaped, same bytes as the ring schedule's P-1 hops
    of the x-block ring)."""
    P = _n_dev(op)
    it_v = _itemsize(op.dtype)
    it_w = _itemsize(getattr(op, "compute_dtype", None) or op.dtype)
    ff = _flop_factor(op.dtype)
    flops = 2.0 * ff * op.nnz / P
    trip = op.nnz * (it_w + 8.0) / P
    if direction == "forward":
        vec = (op.Ncol + op.N / P) * it_v
        return OpCost(flops, trip + vec, 0.0, ("sparse.forward",))
    vec = (op.N + op.Ncol / P) * it_v
    ici = op.Ncol * it_v * 2.0 * (P - 1) / P
    return OpCost(flops, trip + vec, ici,
                  (f"sparse.adjoint+{op.adjoint_mode}",))


def _cost_block_matmul(op, direction: str) -> OpCost:
    P = _n_dev(op)
    it_a = _itemsize(getattr(op, "compute_dtype", None) or op.dtype)
    it_v = _itemsize(op.dtype)
    ff = _flop_factor(op.dtype)
    flops = 2.0 * ff * op.N * op.K * op.M / P
    a_bytes = op.N * op.K * it_a / P
    if direction == "forward":
        vec = (op.K * op.M + op.N * op.M / P) * it_v
        return OpCost(flops, a_bytes + vec, 0.0, ("block.forward",))
    # adjoint: sharded-N contraction -> one psum of the (K, M) result
    vec = (op.N * op.M / P + op.K * op.M) * it_v
    ici = op.K * op.M * it_v * 2.0 * (P - 1) / P
    return OpCost(flops, a_bytes + vec, ici, ("block.adjoint+psum",))


def _summa_fabric_split(op, bytes_r: float,
                        bytes_c: float) -> Tuple[float, float, str]:
    """``(ici_bytes, dcn_bytes, note)`` attribution of SUMMA's
    per-grid-axis comm bytes (round 11). Flat mesh: everything is ICI
    (the pre-round-11 model). Hybrid mesh + fabric-aligned
    hierarchical schedule (``op._hier``): each grid axis is charged to
    the fabric it actually spans (rows = slices, so ``r`` rides DCN
    and ``c`` rides ICI for the aligned layout). Hybrid mesh +
    topology-blind schedule: conservative slow-fabric charge — with no
    pinned axis→fabric assignment every collective may cross DCN."""
    mesh2 = getattr(op, "mesh2", None)
    if mesh2 is None:
        return bytes_r + bytes_c, 0.0, ""
    from ..parallel import topology as _topo
    if not _topo.is_hybrid(mesh2):
        return bytes_r + bytes_c, 0.0, ""
    if not getattr(op, "_hier", False):
        return 0.0, bytes_r + bytes_c, "+fabric[blind:dcn]"
    fr = _topo.axis_fabric(mesh2, "r")
    fc = _topo.axis_fabric(mesh2, "c")
    ici = ((bytes_r if fr == "ici" else 0.0)
           + (bytes_c if fc == "ici" else 0.0))
    dcn = ((bytes_r if fr == "dcn" else 0.0)
           + (bytes_c if fc == "dcn" else 0.0))
    return ici, dcn, f"+fabric[r={fr},c={fc}]"


def _cost_summa_matmul(op, direction: str) -> OpCost:
    pr, pc = op.grid
    P = pr * pc
    it_a = _itemsize(getattr(op, "compute_dtype", None) or op.dtype)
    it_v = _itemsize(op.dtype)
    ff = _flop_factor(op.dtype)
    flops = 2.0 * ff * op.Np * op.Kp_c * op.Mp / P
    a_bytes = op.Np * op.Kp_c * it_a / P
    split = summa_comm_volume_split(op.N, op.K, op.M, op.grid)
    if direction == "forward":
        sched = getattr(op, "schedule", "gather")
        sp = split.get(sched, split["gather"])
        # A moves narrow (gather schedule's c-axis term), X moves wide;
        # approximate with the A-row term at it_a and the rest at it_v
        if sched == "gather":
            a_term = (op.Np // pr) * op.Kp_c * (pc - 1) / pc
            bytes_c = a_term * it_a + (sp["c"] - a_term) * it_v
        else:
            bytes_c = sp["c"] * it_v
        bytes_r = sp["r"] * it_v
        ici, dcn, fnote = _summa_fabric_split(op, bytes_r, bytes_c)
        vec = (op.Kp_r * op.Mp / P + op.Np * op.Mp / P) * it_v
        return OpCost(flops, a_bytes + vec, ici,
                      (f"summa.forward[{sched}]{fnote}",), dcn)
    sp = split["adjoint"]
    ici, dcn, fnote = _summa_fabric_split(op, sp["r"] * it_v,
                                          sp["c"] * it_v)
    vec = (op.Np * op.Mp / P + op.Kp_c * op.Mp / pc) * it_v
    return OpCost(flops, a_bytes + vec, ici,
                  (f"summa.adjoint{fnote}",), dcn)


def _cost_blockdiag(op, direction: str) -> OpCost:
    P = _n_dev(op)
    batched = getattr(op, "_batched", None)
    it_a = _itemsize(getattr(op, "compute_dtype", None) or op.dtype)
    it_v = _itemsize(op.dtype)
    ff = _flop_factor(op.dtype)
    if batched is not None:
        nblk, m, n = batched.shape
        k = getattr(op, "_batched_k", 1)
        flops = 2.0 * ff * nblk * m * n * k / P
        hbm = (nblk * m * n * it_a
               + (op.shape[0] + op.shape[1]) * it_v) / P
        return OpCost(flops, hbm, 0.0, ("blockdiag.batched",))
    flops = 2.0 * ff * float(np.sum(op.nops * op.mops)) / P
    hbm = (float(np.sum(op.nops * op.mops)) * it_a
           + (op.shape[0] + op.shape[1]) * it_v) / P
    return OpCost(flops, hbm, 0.0, ("blockdiag.per-block",))


def _cost_stack(op, direction: str) -> OpCost:
    # sum the children (each applied once per stack apply); the
    # homogeneous-row batched path adds the adjoint reduce-scatter,
    # which the children's own models do not know about — approximate
    # with the children total (a lower bound, noted).
    total = OpCost(notes=("stack.children-sum",))
    for child in getattr(op, "ops", ()):
        c = estimate(child, direction)
        if c is None:
            return None
        total = total + c
    return total


def _cost_wrapper(op, direction: str) -> OpCost:
    """Lazy composition wrappers: recurse into args. Adjoint/transpose
    swap direction; product sums its factors; scaled/conj forward."""
    from ..linearoperator import (
        _AdjointLinearOperator, _TransposedLinearOperator,
        _ProductLinearOperator, _SumLinearOperator,
        _ScaledLinearOperator, _ConjLinearOperator,
        _PowerLinearOperator, _CheckpointedLinearOperator)
    flip = {"forward": "adjoint", "adjoint": "forward"}
    if isinstance(op, (_AdjointLinearOperator, _TransposedLinearOperator)):
        return estimate(op.args[0], flip[direction])
    if isinstance(op, _ProductLinearOperator):
        a = estimate(op.args[0], direction)
        b = estimate(op.args[1], direction)
        return None if (a is None or b is None) else a + b
    if isinstance(op, _SumLinearOperator):
        a = estimate(op.args[0], direction)
        b = estimate(op.args[1], direction)
        return None if (a is None or b is None) else a + b
    if isinstance(op, (_ScaledLinearOperator, _ConjLinearOperator,
                       _CheckpointedLinearOperator)):
        return estimate(op.args[0], direction)
    if isinstance(op, _PowerLinearOperator):
        c = estimate(op.args[0], direction)
        return None if c is None else c.scaled(op._p)
    return None


def _cost_fft(op, direction: str) -> OpCost:
    """Distributed pencil FFT: per-axis ``5 n log2 n`` transform FLOPs
    over the local share + the pencil-transpose collectives. Uses the
    operator's logical dims and engine mode (planar plane pairs move
    2xf32 = the same 8 bytes/element as c64 for the full spectrum)."""
    dims = getattr(op, "dims", None)
    if not dims or any(d is None for d in dims):
        return None
    P = _n_dev(op)
    n_total = float(np.prod(dims))
    axes = getattr(op, "axes", tuple(range(len(dims))))
    flops = sum(5.0 * n_total * math.log2(max(2, dims[ax]))
                for ax in axes) / P
    n_t = max(0, len(axes) - 1)  # one transpose per non-local axis pair
    fab = None
    mesh = getattr(op, "mesh", None)
    if mesh is not None:
        from ..parallel import topology as _topo
        h = _topo.hybrid_axes(mesh)
        if h is not None:
            fab = (h[2], h[3])
    cost = pencil_transpose_cost(dims, P, itemsize=8, n_transposes=n_t,
                                 fabric_shape=fab,
                                 hierarchical=bool(
                                     getattr(op, "_hier", False)))
    return OpCost(flops, cost.hbm_bytes + 2 * n_total * 8 / P,
                  cost.ici_bytes, ("fft.pencil",) + cost.notes,
                  cost.dcn_bytes)


def _cost_derivative(op, direction: str) -> OpCost:
    """Stencil: taps x N flops, one read+write sweep, and the
    ring-halo ghost slabs (2 x w rows) on the ICI."""
    dims = getattr(op, "dims", None) or (op.shape[1],)
    P = _n_dev(op)
    n_total = float(np.prod(dims))
    it = _itemsize(op.dtype)
    taps = 3.0  # centered first/second difference
    row = n_total / max(1, dims[0])
    w = 1  # one ghost row per side (3-point stencils)
    ici = 2.0 * w * row * it if P > 1 else 0.0
    return OpCost(2.0 * taps * n_total / P, 2.0 * n_total * it / P, ici,
                  ("stencil.halo",))


# dotted-name -> model; resolved lazily so this module imports clean
# from scripts (bench.py children) without pulling the operator stack
_BUILTIN = [
    ("pylops_mpi_tpu.ops.matrixmult:_MPIBlockMatrixMult",
     _cost_block_matmul),
    ("pylops_mpi_tpu.ops.matrixmult:_MPIAutoMatrixMult",
     _cost_block_matmul),
    ("pylops_mpi_tpu.ops.matrixmult:_MPISummaMatrixMult",
     _cost_summa_matmul),
    ("pylops_mpi_tpu.ops.sparse:MPISparseMatrixMult",
     _cost_sparse_matmul),
    ("pylops_mpi_tpu.ops.blockdiag:MPIBlockDiag", _cost_blockdiag),
    ("pylops_mpi_tpu.ops.stack:MPIVStack", _cost_stack),
    ("pylops_mpi_tpu.ops.stack:MPIHStack", _cost_stack),
    ("pylops_mpi_tpu.ops.fft:MPIFFTND", _cost_fft),
    ("pylops_mpi_tpu.ops.fft:MPIFFT2D", _cost_fft),
    ("pylops_mpi_tpu.ops.derivatives:MPIFirstDerivative",
     _cost_derivative),
    ("pylops_mpi_tpu.ops.derivatives:MPISecondDerivative",
     _cost_derivative),
    ("pylops_mpi_tpu.linearoperator:_AdjointLinearOperator",
     _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_TransposedLinearOperator",
     _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_ProductLinearOperator",
     _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_SumLinearOperator", _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_ScaledLinearOperator",
     _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_ConjLinearOperator", _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_PowerLinearOperator",
     _cost_wrapper),
    ("pylops_mpi_tpu.linearoperator:_CheckpointedLinearOperator",
     _cost_wrapper),
]
_builtin_bound = False


def _bind_builtin() -> None:
    global _builtin_bound
    if _builtin_bound:
        return
    import importlib
    for dotted, fn in _BUILTIN:
        modname, clsname = dotted.split(":")
        try:
            cls = getattr(importlib.import_module(modname), clsname)
        except Exception:
            continue
        _REGISTRY.setdefault(cls, fn)
    _builtin_bound = True


# ---------------------------------------------------------------- roofline
def roofline(cost: OpCost, peaks: Dict, n_dev: int = 1,
             measured_s: Optional[float] = None) -> Dict:
    """Place an :class:`OpCost` on the roofline: per-component times
    (``flops / peak_flops``, ``hbm_bytes / hbm_bw``, ``ici_bytes /
    ici_bw``, when the cost carries a hybrid-mesh split ``dcn_bytes /
    dcn_bw``, and when it declares ``reductions_per_iter`` an α-term
    ``latency`` component = reductions x the fabric's
    ``allreduce_latency_s``; the cost is PER DEVICE, the peaks PER
    CHIP, so ``n_dev``
    only scales aggregate reporting), predicted seconds = max of the
    available components (a perfectly-overlapped execution's lower
    bound), and ``bound`` = the component that dominates. Components
    whose peak is ``None``/0 are skipped — an unknown chip yields
    ``predicted_s=None`` rather than a wrong roofline.

    ``measured_s`` (optional): the measured per-apply seconds. When
    the implied HBM bandwidth EXCEEDS the chip's HBM peak, the
    working set cannot have streamed from HBM — it was VMEM-resident
    — so the result re-buckets: ``regime="vmem"``, the HBM component
    is dropped from the bound, and ``hbm_pct`` is never reported
    above 100 (the VERDICT round-5 misattribution: 1261 GB/s
    "measured" against an 819 GB/s v5e peak is a cache number, not an
    HBM number). Otherwise ``regime="hbm"`` with the honest
    ``hbm_pct``."""
    comps = {}
    if peaks.get("flops"):
        comps["compute"] = cost.flops / peaks["flops"]
    if peaks.get("hbm_gbps"):
        comps["hbm"] = cost.hbm_bytes / (peaks["hbm_gbps"] * 1e9)
    if peaks.get("ici_gbps") and cost.ici_bytes:
        comps["ici"] = cost.ici_bytes / (peaks["ici_gbps"] * 1e9)
    if peaks.get("dcn_gbps") and cost.dcn_bytes:
        comps["dcn"] = cost.dcn_bytes / (peaks["dcn_gbps"] * 1e9)
    # α-term (round 17): reductions pay a per-collective latency floor
    # that no bandwidth component captures — a Krylov iteration's few
    # scalar dots cost microseconds of wire time each, not bytes. Only
    # costs that declare reductions_per_iter opt in, so every earlier
    # roofline is unchanged.
    if peaks.get("allreduce_latency_s") and cost.reductions_per_iter:
        comps["latency"] = (cost.reductions_per_iter
                            * peaks["allreduce_latency_s"])
    if not comps:
        return {"predicted_s": None, "bound": None, "components_s": {},
                "cost": cost.as_dict(), "n_dev": n_dev}
    bound = max(comps, key=comps.get)
    out = {"predicted_s": comps[bound], "bound": bound,
           "components_s": {k: float(f"{v:.4g}")
                            for k, v in comps.items()},
           "cost": cost.as_dict(), "n_dev": n_dev}
    if measured_s and measured_s > 0 and peaks.get("hbm_gbps") \
            and cost.hbm_bytes:
        implied_gbps = cost.hbm_bytes / measured_s / 1e9
        if implied_gbps > peaks["hbm_gbps"]:
            out["regime"] = "vmem"
            out["implied_hbm_gbps"] = round(implied_gbps, 1)
            out["note"] = ("implied bandwidth exceeds the HBM peak: "
                           "working set is VMEM-resident; not an HBM "
                           "measurement")
            nonhbm = {k: v for k, v in comps.items() if k != "hbm"}
            if nonhbm:
                out["bound"] = max(nonhbm, key=nonhbm.get)
        else:
            out["regime"] = "hbm"
            out["hbm_pct"] = round(
                100.0 * implied_gbps / peaks["hbm_gbps"], 1)
    return out

"""Namespace parity with ``pylops_mpi.waveeqprocessing``."""
from ..ops.mdc import MPIMDC

"""Solver-state checkpoint / resume.

The reference has **no** checkpointing (SURVEY §5: solvers expose
``setup/step/run`` so callers *could* snapshot externally, ref
``cls_basic.py:57-141``, but no serialization exists). This module adds
it as a genuine improvement with two backends:

- **native** (default): crash-safe atomic pickle + sidecar blobs
  streamed by the C++ threaded writer — single-file, single-process,
  restores sharded arrays to their original Partition/axis layout.
- **orbax** (``backend="orbax"`` or
  ``PYLOPS_MPI_TPU_CKPT_BACKEND=orbax``): the SHARDED device arrays go
  straight into an orbax directory checkpoint — no host gather, which
  is the multi-host requirement (``asarray()`` cannot fetch
  non-addressable shards on a pod; see docs/multihost.md) — with the
  partition metadata in a JSON sidecar inside the directory.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np
import jax

from ..distributedarray import DistributedArray, Partition
from ..stacked import StackedDistributedArray

__all__ = ["save_solver", "load_solver", "save_pytree", "load_pytree",
           "save_fused_carry", "load_fused_carry", "FUSED_SCHEMA_VERSION"]

_SOLVER_FIELDS = ("y", "s", "r", "c", "q", "kold", "iiter", "cost", "cost1",
                  "damp", "tol", "niter", "t", "z", "alpha", "thresh",
                  "normresold", "eps")


def _check_addressable(v: DistributedArray) -> None:
    """The native backend gathers every shard to host (``asarray``) —
    impossible on a multi-host pod, where each process can only address
    its own slice's shards. Fail here with the fix in the message
    instead of deep inside jax's cross-host gather."""
    arr = getattr(v, "_arr", None)
    if arr is not None and not getattr(arr, "is_fully_addressable", True):
        raise RuntimeError(
            "native checkpoint backend cannot gather a multi-host "
            "DistributedArray: some shards are on non-addressable "
            "devices (other hosts). Use the orbax backend — "
            "save_*(..., backend='orbax') or "
            "PYLOPS_MPI_TPU_CKPT_BACKEND=orbax — which writes each "
            "host's shards locally with no gather (docs/multihost.md).")


def _encode(v):
    if isinstance(v, DistributedArray):
        _check_addressable(v)
        return {"__dist__": True, "value": v.asarray(),
                "partition": v.partition.name, "axis": v.axis,
                "local_shapes": v.local_shapes, "mask": v.mask}
    if isinstance(v, StackedDistributedArray):
        return {"__stacked__": True,
                "arrays": [_encode(d) for d in v.distarrays]}
    if isinstance(v, jax.Array):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        return type(v)(_encode(e) for e in v)
    return v


def _decode(v, mesh=None):
    if isinstance(v, dict) and v.get("__dist__"):
        out = DistributedArray.to_dist(
            v["value"], mesh=mesh, partition=Partition[v["partition"]],
            axis=v["axis"], local_shapes=v["local_shapes"], mask=v["mask"])
        return out
    if isinstance(v, dict) and v.get("__stacked__"):
        return StackedDistributedArray([_decode(d, mesh) for d in v["arrays"]])
    if isinstance(v, (list, tuple)):
        return type(v)(_decode(e, mesh) for e in v)
    return v


# Arrays at or above this size are stored in a sidecar blob file written
# by the native (C++) threaded writer instead of being pickled inline.
_BLOB_THRESHOLD = 1 << 20


def _extract_blobs(v, blobs):
    if isinstance(v, np.ndarray) and v.nbytes >= _BLOB_THRESHOLD:
        a = np.ascontiguousarray(v)
        off = sum(b.nbytes for b in blobs)
        blobs.append(a)
        return {"__blob__": True, "offset": off, "dtype": a.dtype.str,
                "shape": a.shape}
    if isinstance(v, dict):
        return {k: _extract_blobs(e, blobs) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_extract_blobs(e, blobs) for e in v)
    return v


def _restore_blobs(v, blob_buf):
    if isinstance(v, dict) and v.get("__blob__"):
        dt = np.dtype(v["dtype"])
        n = int(np.prod(v["shape"], dtype=np.int64))
        off = v["offset"]
        return np.frombuffer(blob_buf, dtype=dt, count=n,
                             offset=off).reshape(v["shape"]).copy()
    if isinstance(v, dict):
        return {k: _restore_blobs(e, blob_buf) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_restore_blobs(e, blob_buf) for e in v)
    return v


# -------------------------------------------------------- orbax backend
def _flatten_for_orbax(tree):
    """Split a checkpoint tree into (device_arrays, json_meta): sharded
    buffers stay jax.Arrays (orbax writes per-shard, no gather);
    everything else — partition layout, scalars, strings — rides the
    JSON sidecar."""
    arrays: Dict[str, Any] = {}
    meta: Dict[str, Any] = {}
    for k, v in tree.items():
        if isinstance(v, StackedDistributedArray):
            meta[k] = {"kind": "stacked", "n": len(v.distarrays)}
            for i, d in enumerate(v.distarrays):
                sub_a, sub_m = _flatten_for_orbax({f"{k}.{i}": d})
                arrays.update(sub_a)
                meta.update(sub_m)
        elif isinstance(v, DistributedArray):
            arrays[k] = v._arr  # physical (padded) sharded buffer
            meta[k] = {"kind": "dist", "partition": v.partition.name,
                       "axis": int(v.axis),
                       "global_shape": list(v.global_shape),
                       "local_shapes": [list(s) for s in v.local_shapes],
                       "mask": list(v.mask) if v.mask is not None else None}
        elif isinstance(v, (jax.Array, np.ndarray)):
            arrays[k] = v
            meta[k] = {"kind": "array"}
        elif isinstance(v, (int, float, complex, str, bool, type(None))):
            meta[k] = {"kind": "py",
                       "value": [v.real, v.imag] if isinstance(v, complex)
                       else v,
                       "complex": isinstance(v, complex)}
        elif isinstance(v, np.generic):
            meta[k] = {"kind": "py", "value": v.item(), "complex": False}
        elif isinstance(v, (list, tuple)):
            # e.g. the in-flight cost history: a python list of device
            # scalars — recurse with indexed keys
            meta[k] = {"kind": "seq", "n": len(v),
                       "tuple": isinstance(v, tuple)}
            for i, e in enumerate(v):
                sub_a, sub_m = _flatten_for_orbax({f"{k}.{i}": e})
                arrays.update(sub_a)
                meta.update(sub_m)
        else:
            raise TypeError(
                f"orbax backend cannot store {k!r} of type {type(v)}; "
                "use the native backend")
    return arrays, meta


def _save_orbax(path: str, tree: Dict[str, Any]) -> None:
    import json
    import secrets
    import shutil
    if any("." in k for k in tree):
        raise ValueError("orbax backend reserves '.' in keys for "
                         "container components")
    arrays, meta = _flatten_for_orbax(tree)
    path = os.path.abspath(path)
    # crash safety mirrors the native backend: build the complete new
    # checkpoint beside the old one, then swap directories — a crash at
    # any point leaves either the old or the new checkpoint whole
    tmp = path + ".tmp" + secrets.token_hex(4)
    if arrays:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, arrays, force=True)
    else:  # scalar/string-only tree: meta-only checkpoint directory
        os.makedirs(tmp, exist_ok=True)
    with open(os.path.join(tmp, "pylops_meta.json"), "w") as f:
        json.dump(meta, f)
    old = None
    if os.path.exists(path):
        old = path + ".old" + secrets.token_hex(4)
        os.rename(path, old)
    os.rename(tmp, path)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def _load_orbax(path: str, mesh=None) -> Dict[str, Any]:
    import json
    from ..parallel.mesh import default_mesh
    path = os.path.abspath(path)
    with open(os.path.join(path, "pylops_meta.json")) as f:
        meta = json.load(f)
    arrays = {}
    if any(m.get("kind") in ("dist", "array") for m in meta.values()):
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            arrays = ckptr.restore(path)
    mesh = mesh if mesh is not None else default_mesh()
    out: Dict[str, Any] = {}

    def _dist(k, m):
        d = DistributedArray(
            global_shape=tuple(m["global_shape"]), mesh=mesh,
            partition=Partition[m["partition"]], axis=m["axis"],
            local_shapes=[tuple(s) for s in m["local_shapes"]],
            mask=tuple(m["mask"]) if m["mask"] is not None else None,
            dtype=arrays[k].dtype)
        d._arr = d._place(jax.numpy.asarray(arrays[k]))
        return d

    def _build(k, m):
        if m["kind"] == "stacked":
            return StackedDistributedArray(
                [_build(f"{k}.{i}", meta[f"{k}.{i}"])
                 for i in range(m["n"])])
        if m["kind"] == "seq":
            seq = [_build(f"{k}.{i}", meta[f"{k}.{i}"])
                   for i in range(m["n"])]
            return tuple(seq) if m["tuple"] else seq
        if m["kind"] == "dist":
            return _dist(k, m)
        if m["kind"] == "array":
            return np.asarray(arrays[k])
        v = m["value"]
        return complex(v[0], v[1]) if m.get("complex") else v

    roots = {k for k in meta
             if "." not in k or meta.get(k.rsplit(".", 1)[0]) is None}
    for k in sorted(roots):
        out[k] = _build(k, meta[k])
    return out


def save_pytree(path: str, tree: Dict[str, Any],
                backend: Optional[str] = None) -> None:
    """Serialize a dict of arrays/DistributedArrays/scalars.

    ``backend="native"`` (default): large array payloads stream
    one-by-one (flat peak memory) into a uniquely-named sidecar via the
    native threaded writer; the pickle references the sidecar by name
    and is replaced atomically, so a crash mid-save leaves the previous
    checkpoint pair intact. ``backend="orbax"``: directory checkpoint
    with per-shard writes and no host gather (multi-host safe)."""
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    if backend == "orbax":
        return _save_orbax(path, tree)
    if backend != "native":
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    import glob
    import secrets
    from .. import native
    enc = {k: _encode(v) for k, v in tree.items()}
    blobs: list = []
    enc = _extract_blobs(enc, blobs)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    old_sidecars = glob.glob(os.path.abspath(path) + ".blobs.*")
    blob_name = None
    if blobs:
        blob_name = os.path.basename(path) + ".blobs." + secrets.token_hex(4)
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        off = 0
        for b in blobs:
            native.write_binary_at(blob_path, off, b.view(np.uint8).reshape(-1))
            off += b.nbytes
    enc["__blobfile__"] = blob_name
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(enc, f)
    os.replace(tmp, path)
    for old in old_sidecars:
        if os.path.basename(old) != blob_name and os.path.exists(old):
            os.remove(old)


def load_pytree(path: str, mesh=None,
                backend: Optional[str] = None) -> Dict[str, Any]:
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    if backend not in ("native", "orbax"):
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    if backend == "orbax" or os.path.isdir(path):
        # a directory path is unambiguously an orbax checkpoint
        return _load_orbax(path, mesh=mesh)
    from .. import native
    with open(path, "rb") as f:
        enc = pickle.load(f)
    blob_name = enc.pop("__blobfile__", None)
    if blob_name is not None:
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        if not os.path.exists(blob_path):
            raise FileNotFoundError(
                f"checkpoint sidecar {blob_path!r} is missing — the "
                f"checkpoint directory must be moved/copied as a whole")
        nbytes = os.path.getsize(blob_path)
        blob_buf = native.read_binary(blob_path, np.uint8, (nbytes,))
        enc = _restore_blobs(enc, blob_buf)
    return {k: _decode(v, mesh) for k, v in enc.items()}


def save_solver(path: str, solver, x=None,
                backend: Optional[str] = None) -> None:
    """Snapshot a CG/CGLS/ISTA/FISTA solver mid-run (between ``step``
    calls) so a later process can resume. ``backend="orbax"`` writes
    the sharded buffers without a host gather (multi-host safe)."""
    # resolve arg-or-env ONCE: the env-var route must pick the same
    # encoding as the explicit argument
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    orbax = backend == "orbax"
    state: Dict[str, Any] = {"__class__": type(solver).__name__}
    for field in _SOLVER_FIELDS:
        if hasattr(solver, field):
            v = getattr(solver, field)
            state[field] = v if orbax else _encode(v)
    if x is not None:
        state["x"] = x if orbax else _encode(x)
    save_pytree(path, state, backend=backend)


def load_solver(path: str, solver, mesh=None,
                backend: Optional[str] = None):
    """Restore a snapshot into a freshly-constructed solver (same
    operator). Returns the model vector ``x`` if it was saved."""
    state = load_pytree(path, mesh=mesh, backend=backend)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(solver).__name__:
        raise ValueError(f"checkpoint is for {cls}, not {type(solver).__name__}")
    x = state.pop("x", None)
    for k, v in state.items():
        setattr(solver, k, v)
    return x


# ------------------------------------------------ fused-carry schema
# Mid-solve snapshots of the SEGMENTED fused solvers
# (solvers/segmented.py, ISSUE 6): the whole while_loop carry — the
# distributed recurrence vectors plus the recurrence scalars, the
# iteration counter, the cost buffers, the machine-precision floor and
# the guard words — under a versioned header, so a killed process can
# resume mid-solve and replay the remaining epochs bit-identically.
FUSED_SCHEMA_VERSION = 1


def save_fused_carry(path: str, solver: str, carry: Dict[str, Any],
                     backend: Optional[str] = None) -> None:
    """Snapshot a segmented fused solve's carry between epochs.
    ``solver`` names the loop family (``"cg"``/``"cgls"``); ``carry``
    is the field dict the segmented driver threads (plus its plan
    metadata — ``niter``/``damp``/``tol``/``epoch``/``guards``), all of
    which round-trips bit-exactly through either backend."""
    state = dict(carry)
    state["__fused__"] = solver
    state["__fused_schema__"] = FUSED_SCHEMA_VERSION
    save_pytree(path, state, backend=backend)


def load_fused_carry(path: str, solver: str, mesh=None,
                     backend: Optional[str] = None) -> Dict[str, Any]:
    """Load a segmented fused carry saved by :func:`save_fused_carry`,
    validating the solver family and schema version (a mismatch names
    the problem instead of resuming a wrong trajectory)."""
    state = load_pytree(path, mesh=mesh, backend=backend)
    kind = state.pop("__fused__", None)
    if kind is None:
        raise ValueError(
            f"{path!r} is not a fused-carry checkpoint (it may be a "
            "class-API save_solver snapshot — load it with load_solver)")
    if kind != solver:
        raise ValueError(f"fused-carry checkpoint is for {kind!r}, "
                         f"not {solver!r}")
    schema = state.pop("__fused_schema__", None)
    if schema != FUSED_SCHEMA_VERSION:
        raise ValueError(
            f"fused-carry schema {schema!r} != {FUSED_SCHEMA_VERSION} "
            f"(checkpoint written by an incompatible version)")
    return state

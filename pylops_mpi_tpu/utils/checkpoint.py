"""Solver-state checkpoint / resume.

The reference has **no** checkpointing (SURVEY §5: solvers expose
``setup/step/run`` so callers *could* snapshot externally, ref
``cls_basic.py:57-141``, but no serialization exists). This module adds
it as a genuine improvement with two backends:

- **native** (default): crash-safe atomic pickle + sidecar blobs
  streamed by the C++ threaded writer — single-file, single-process,
  restores sharded arrays to their original Partition/axis layout.
- **orbax** (``backend="orbax"`` or
  ``PYLOPS_MPI_TPU_CKPT_BACKEND=orbax``): the SHARDED device arrays go
  straight into an orbax directory checkpoint — no host gather, which
  is the multi-host requirement (``asarray()`` cannot fetch
  non-addressable shards on a pod; see docs/multihost.md) — with the
  partition metadata in a JSON sidecar inside the directory.

**Mesh-elastic restore** (ISSUE 8): loading with a ``mesh`` whose
device count differs from the save-time shard count RESHARDS instead
of failing — the balanced :func:`~pylops_mpi_tpu.parallel.partition.\
local_split` recomputes the per-shard layout for the new device count
(the same host-side regrid family as
:func:`~pylops_mpi_tpu.parallel.collectives.all_to_all_resharding`
performs on device), so a checkpoint written by an 8-device
``dcn(2)×ici(4)`` job restores onto the 4-device mesh that survives a
host loss. Exact-count loads keep the saved ``local_shapes``
bit-for-bit, so same-mesh resume is unchanged. Only the genuinely
impossible regrids refuse, with the reason named:
a ``mask`` (sub-communicator colors are a statement about the OLD
topology — no canonical meaning on a different device count), or a
SCATTER axis shorter than the new device count (some devices would own
zero rows — re-pick the mesh or the axis). See
``docs/robustness.md#mesh-elastic-restore``.

Both backends write crash-atomically (build-beside + rename); a worker
killed mid-save can leave at most a stale temp, which the next save in
the same path garbage-collects.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax

from ..diagnostics import trace as _trace
from ..distributedarray import DistributedArray, Partition
from ..parallel.partition import unpad_index_map
from ..stacked import StackedDistributedArray

__all__ = ["save_solver", "load_solver", "save_pytree", "load_pytree",
           "save_fused_carry", "load_fused_carry", "FUSED_SCHEMA_VERSION"]

_SOLVER_FIELDS = ("y", "s", "r", "c", "q", "kold", "iiter", "cost", "cost1",
                  "damp", "tol", "niter", "t", "z", "alpha", "thresh",
                  "normresold", "eps")


def _check_addressable(v: DistributedArray) -> None:
    """The native backend gathers every shard to host (``asarray``) —
    impossible on a multi-host pod, where each process can only address
    its own slice's shards. Fail here with the fix in the message
    instead of deep inside jax's cross-host gather."""
    arr = getattr(v, "_arr", None)
    if arr is not None and not getattr(arr, "is_fully_addressable", True):
        raise RuntimeError(
            "native checkpoint backend cannot gather a multi-host "
            "DistributedArray: some shards are on non-addressable "
            "devices (other hosts). Use the orbax backend — "
            "save_*(..., backend='orbax') or "
            "PYLOPS_MPI_TPU_CKPT_BACKEND=orbax — which writes each "
            "host's shards locally with no gather (docs/multihost.md).")


def _encode(v):
    if isinstance(v, DistributedArray):
        _check_addressable(v)
        return {"__dist__": True, "value": v.asarray(),
                "partition": v.partition.name, "axis": v.axis,
                "local_shapes": v.local_shapes, "mask": v.mask}
    if isinstance(v, StackedDistributedArray):
        return {"__stacked__": True,
                "arrays": [_encode(d) for d in v.distarrays]}
    if isinstance(v, jax.Array):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        return type(v)(_encode(e) for e in v)
    return v


def _target_n_shards(mesh) -> int:
    if mesh is None:
        from ..parallel.mesh import default_mesh
        mesh = default_mesh()
    return int(mesh.devices.size)


def _budgeted_restore() -> bool:
    """True when ``PYLOPS_MPI_TPU_RESHARD_BUDGET`` is set: the
    mesh-elastic restore then streams its placement through the
    bounded planner (``place_replica`` — host-staged under the
    round-14 spill tier when the budget demands it) instead of the
    legacy one-shot ``to_dist``. Unset keeps the legacy path
    bit-identical."""
    from ..parallel.reshard import reshard_budget
    try:
        return reshard_budget() is not None
    except ValueError:
        return False


def _resolve_mesh(mesh):
    if mesh is None:
        from ..parallel.mesh import default_mesh
        return default_mesh()
    return mesh


def _check_elastic(partition: Partition, axis: int,
                   global_shape: Tuple[int, ...], mask, n_old: int,
                   n_new: int) -> None:
    """Refuse the genuinely impossible regrids, naming the reason.
    Everything else reshards via the balanced split."""
    if mask is not None:
        raise ValueError(
            f"cannot restore a masked DistributedArray onto a "
            f"{n_new}-device mesh: its mask (sub-communicator colors "
            f"{tuple(mask)!r}) describes the original {n_old}-device "
            "topology and has no canonical regrid — rebuild the array "
            "and its mask for the new mesh, or restore onto a mesh "
            "with the original device count")
    if partition == Partition.SCATTER and global_shape[axis] < n_new:
        raise ValueError(
            f"cannot reshard a SCATTER axis of length "
            f"{global_shape[axis]} onto {n_new} devices: some devices "
            "would own zero rows. Restore onto a mesh with at most "
            f"{global_shape[axis]} devices, or shard a longer axis")


def _decode(v, mesh=None):
    if isinstance(v, dict) and v.get("__dist__"):
        partition = Partition[v["partition"]]
        axis = v["axis"]
        local_shapes, mask = v["local_shapes"], v["mask"]
        n_old, n_new = len(local_shapes), _target_n_shards(mesh)
        if n_old != n_new:
            # mesh-elastic restore: the saved "value" is the LOGICAL
            # global array, so resharding is just a fresh balanced
            # split over the new device count
            _check_elastic(partition, axis, np.shape(v["value"]), mask,
                           n_old, n_new)
            _trace.event("checkpoint.elastic_reshard", cat="checkpoint",
                         backend="native", partition=partition.name,
                         axis=axis, n_old=n_old, n_new=n_new,
                         global_shape=list(np.shape(v["value"])))
            if _budgeted_restore():
                # round 14: a scratch budget is set, so stream the
                # placement through the bounded planner (host-staged
                # when the budget demands it) instead of the one-shot
                # to_dist device_put
                from ..parallel import reshard as _reshard
                return _reshard.place_replica(
                    np.asarray(v["value"]), _resolve_mesh(mesh),
                    partition, axis, mask=mask)
            local_shapes = None  # balanced local_split on the new mesh
        out = DistributedArray.to_dist(
            v["value"], mesh=mesh, partition=partition,
            axis=axis, local_shapes=local_shapes, mask=mask)
        return out
    if isinstance(v, dict) and v.get("__stacked__"):
        return StackedDistributedArray([_decode(d, mesh) for d in v["arrays"]])
    if isinstance(v, (list, tuple)):
        return type(v)(_decode(e, mesh) for e in v)
    return v


# Arrays at or above this size are stored in a sidecar blob file written
# by the native (C++) threaded writer instead of being pickled inline.
_BLOB_THRESHOLD = 1 << 20


def _extract_blobs(v, blobs):
    if isinstance(v, np.ndarray) and v.nbytes >= _BLOB_THRESHOLD:
        a = np.ascontiguousarray(v)
        off = sum(b.nbytes for b in blobs)
        blobs.append(a)
        return {"__blob__": True, "offset": off, "dtype": a.dtype.str,
                "shape": a.shape}
    if isinstance(v, dict):
        return {k: _extract_blobs(e, blobs) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_extract_blobs(e, blobs) for e in v)
    return v


def _restore_blobs(v, blob_buf):
    if isinstance(v, dict) and v.get("__blob__"):
        dt = np.dtype(v["dtype"])
        n = int(np.prod(v["shape"], dtype=np.int64))
        off = v["offset"]
        return np.frombuffer(blob_buf, dtype=dt, count=n,
                             offset=off).reshape(v["shape"]).copy()
    if isinstance(v, dict):
        return {k: _restore_blobs(e, blob_buf) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_restore_blobs(e, blob_buf) for e in v)
    return v


# -------------------------------------------------------- orbax backend
def _flatten_for_orbax(tree):
    """Split a checkpoint tree into (device_arrays, json_meta): sharded
    buffers stay jax.Arrays (orbax writes per-shard, no gather);
    everything else — partition layout, scalars, strings — rides the
    JSON sidecar."""
    arrays: Dict[str, Any] = {}
    meta: Dict[str, Any] = {}
    for k, v in tree.items():
        if isinstance(v, StackedDistributedArray):
            meta[k] = {"kind": "stacked", "n": len(v.distarrays)}
            for i, d in enumerate(v.distarrays):
                sub_a, sub_m = _flatten_for_orbax({f"{k}.{i}": d})
                arrays.update(sub_a)
                meta.update(sub_m)
        elif isinstance(v, DistributedArray):
            arrays[k] = v._arr  # physical (padded) sharded buffer
            meta[k] = {"kind": "dist", "partition": v.partition.name,
                       "axis": int(v.axis),
                       "global_shape": list(v.global_shape),
                       "local_shapes": [list(s) for s in v.local_shapes],
                       "mask": list(v.mask) if v.mask is not None else None}
        elif isinstance(v, (jax.Array, np.ndarray)):
            arrays[k] = v
            meta[k] = {"kind": "array"}
        elif isinstance(v, (int, float, complex, str, bool, type(None))):
            meta[k] = {"kind": "py",
                       "value": [v.real, v.imag] if isinstance(v, complex)
                       else v,
                       "complex": isinstance(v, complex)}
        elif isinstance(v, np.generic):
            meta[k] = {"kind": "py", "value": v.item(), "complex": False}
        elif isinstance(v, (list, tuple)):
            # e.g. the in-flight cost history: a python list of device
            # scalars — recurse with indexed keys
            meta[k] = {"kind": "seq", "n": len(v),
                       "tuple": isinstance(v, tuple)}
            for i, e in enumerate(v):
                sub_a, sub_m = _flatten_for_orbax({f"{k}.{i}": e})
                arrays.update(sub_a)
                meta.update(sub_m)
        else:
            raise TypeError(
                f"orbax backend cannot store {k!r} of type {type(v)}; "
                "use the native backend")
    return arrays, meta


def _save_orbax(path: str, tree: Dict[str, Any]) -> None:
    import json
    import secrets
    import shutil
    if any("." in k for k in tree):
        raise ValueError("orbax backend reserves '.' in keys for "
                         "container components")
    arrays, meta = _flatten_for_orbax(tree)
    path = os.path.abspath(path)
    # crash safety mirrors the native backend: build the complete new
    # checkpoint beside the old one, then swap directories — a crash at
    # any point leaves either the old or the new checkpoint whole.
    # Multi-process: a save is a RENDEZVOUS — every process streams its
    # addressable shards into ONE deterministic temp dir (orbax
    # coordinates the per-shard writes), and only process 0 writes the
    # sidecar and performs the swap, fenced by barriers so no process
    # returns before the new checkpoint is visible.
    nproc = jax.process_count()
    if nproc > 1:
        tmp = path + ".tmp-multiproc"
        if jax.process_index() == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        _barrier("pylops_ckpt_pre")
    else:
        tmp = path + ".tmp" + secrets.token_hex(4)
    if arrays:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(tmp, arrays, force=True)
    elif nproc <= 1 or jax.process_index() == 0:
        os.makedirs(tmp, exist_ok=True)  # scalar-only: meta-only dir
    if nproc <= 1 or jax.process_index() == 0:
        with open(os.path.join(tmp, "pylops_meta.json"), "w") as f:
            json.dump(meta, f)
        old = None
        if os.path.exists(path):
            old = path + ".old" + secrets.token_hex(4)
            os.rename(path, old)
        os.rename(tmp, path)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    if nproc > 1:
        _barrier("pylops_ckpt_post")


def _barrier(tag: str) -> None:
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def _load_orbax(path: str, mesh=None) -> Dict[str, Any]:
    import json
    from ..parallel.mesh import default_mesh
    path = os.path.abspath(path)
    with open(os.path.join(path, "pylops_meta.json")) as f:
        meta = json.load(f)
    arrays = {}
    array_keys = [k for k, m in meta.items()
                  if m.get("kind") in ("dist", "array")]
    if array_keys:
        import orbax.checkpoint as ocp
        # restore every leaf as a host numpy array: a checkpoint
        # written by a MULTI-process job carries jax.Array shard
        # metadata orbax cannot re-materialize without a concrete
        # sharding — and the elastic-restore path re-places the data
        # on the (possibly different) target mesh itself anyway
        rargs = {k: ocp.RestoreArgs(restore_type=np.ndarray)
                 for k in array_keys}
        with ocp.PyTreeCheckpointer() as ckptr:
            arrays = ckptr.restore(path, restore_args=rargs)
    mesh = mesh if mesh is not None else default_mesh()
    out: Dict[str, Any] = {}

    def _dist(k, m):
        partition = Partition[m["partition"]]
        axis = int(m["axis"])
        global_shape = tuple(m["global_shape"])
        saved_shapes = [tuple(s) for s in m["local_shapes"]]
        mask = tuple(m["mask"]) if m["mask"] is not None else None
        n_old, n_new = len(saved_shapes), int(mesh.devices.size)
        if n_old != n_new:
            # mesh-elastic restore. Orbax stores the PHYSICAL
            # pad-to-max buffer, so first gather it back to the
            # logical global array (unpad via the old shard sizes),
            # then re-split balanced over the new device count.
            _check_elastic(partition, axis, global_shape, mask,
                           n_old, n_new)
            _trace.event("checkpoint.elastic_reshard", cat="checkpoint",
                         backend="orbax", partition=partition.name,
                         axis=axis, n_old=n_old, n_new=n_new,
                         global_shape=list(global_shape))
            phys = np.asarray(arrays[k])
            if partition == Partition.SCATTER:
                sizes = [s[axis] for s in saved_shapes]
                logical = np.take(phys, unpad_index_map(sizes),
                                  axis=axis)
            else:  # broadcast: the physical buffer IS the global array
                logical = phys
            if _budgeted_restore():
                # round 14: stream the elastic placement through the
                # bounded planner instead of the one-shot to_dist
                from ..parallel import reshard as _reshard
                return _reshard.place_replica(logical, mesh,
                                              partition, axis)
            return DistributedArray.to_dist(
                logical, mesh=mesh, partition=partition, axis=axis,
                local_shapes=None, mask=None)
        d = DistributedArray(
            global_shape=global_shape, mesh=mesh,
            partition=partition, axis=axis,
            local_shapes=saved_shapes, mask=mask,
            dtype=arrays[k].dtype)
        d._arr = d._place(jax.numpy.asarray(arrays[k]))
        return d

    def _build(k, m):
        if m["kind"] == "stacked":
            return StackedDistributedArray(
                [_build(f"{k}.{i}", meta[f"{k}.{i}"])
                 for i in range(m["n"])])
        if m["kind"] == "seq":
            seq = [_build(f"{k}.{i}", meta[f"{k}.{i}"])
                   for i in range(m["n"])]
            return tuple(seq) if m["tuple"] else seq
        if m["kind"] == "dist":
            return _dist(k, m)
        if m["kind"] == "array":
            return np.asarray(arrays[k])
        v = m["value"]
        return complex(v[0], v[1]) if m.get("complex") else v

    roots = {k for k in meta
             if "." not in k or meta.get(k.rsplit(".", 1)[0]) is None}
    for k in sorted(roots):
        out[k] = _build(k, meta[k])
    return out


def _gc_stale_tmps(path: str) -> None:
    """Drop temp files left by a worker KILLED mid-save (pid-suffixed,
    and the pid no longer runs). The kill-mid-save tests prove the
    previous checkpoint loads regardless; this just stops dead temps
    accumulating across supervisor relaunches in the same directory."""
    import glob
    import re
    for tmp in glob.glob(path + ".tmp*"):
        m = re.match(re.escape(path) + r"\.tmp(\d+)$", tmp)
        if not m or int(m.group(1)) == os.getpid():
            continue
        try:
            os.kill(int(m.group(1)), 0)  # raises when the pid is gone
        except ProcessLookupError:
            try:
                os.remove(tmp)
            except OSError:
                pass
        except OSError:
            pass  # pid exists but isn't ours to probe: leave its temp


def _save_pytree_impl(path: str, tree: Dict[str, Any],
                      backend: Optional[str] = None) -> None:
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    if backend == "orbax":
        return _save_orbax(path, tree)
    if backend != "native":
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    import glob
    import secrets
    from .. import native
    enc = {k: _encode(v) for k, v in tree.items()}
    blobs: list = []
    enc = _extract_blobs(enc, blobs)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    _gc_stale_tmps(path)
    old_sidecars = glob.glob(os.path.abspath(path) + ".blobs.*")
    blob_name = None
    if blobs:
        blob_name = os.path.basename(path) + ".blobs." + secrets.token_hex(4)
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        off = 0
        for b in blobs:
            native.write_binary_at(blob_path, off, b.view(np.uint8).reshape(-1))
            off += b.nbytes
    enc["__blobfile__"] = blob_name
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(enc, f)
        # durability before visibility: the rename must never land a
        # file whose bytes are still in the page cache when the host
        # dies — fsync the temp, THEN swap it in
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    for old in old_sidecars:
        if os.path.basename(old) != blob_name and os.path.exists(old):
            os.remove(old)


def save_pytree(path: str, tree: Dict[str, Any],
                backend: Optional[str] = None) -> None:
    """Serialize a dict of arrays/DistributedArrays/scalars.

    ``backend="native"`` (default): large array payloads stream
    one-by-one (flat peak memory) into a uniquely-named sidecar via the
    native threaded writer; the pickle references the sidecar by name,
    is fsynced, and is replaced atomically, so a crash at ANY point
    mid-save leaves the previous checkpoint pair intact (stale temps
    from killed writers are garbage-collected on the next save).
    ``backend="orbax"``: directory checkpoint with per-shard writes and
    no host gather (multi-host safe).

    On a multi-host job a save is also a RENDEZVOUS (every process must
    write its shards), so under supervision it runs under the
    collective watchdog (stage ``checkpoint_io``) — a save blocked on a
    dead peer becomes a classified
    :class:`~pylops_mpi_tpu.resilience.elastic.WatchdogTimeout` instead
    of an infinite hang. Unsupervised: a plain direct call."""
    from ..resilience.elastic import watched_call
    return watched_call(_save_pytree_impl, path, tree, backend=backend,
                        stage="checkpoint_io")


def _load_pytree_impl(path: str, mesh=None,
                      backend: Optional[str] = None) -> Dict[str, Any]:
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    # every checkpoint read funnels through here; the in-place elastic
    # acceptance test pins ZERO of these events on its recovery path
    _trace.event("checkpoint.load", cat="checkpoint", path=path,
                 backend=backend)
    if backend not in ("native", "orbax"):
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    if backend == "orbax" or os.path.isdir(path):
        # a directory path is unambiguously an orbax checkpoint
        return _load_orbax(path, mesh=mesh)
    from .. import native
    with open(path, "rb") as f:
        enc = pickle.load(f)
    blob_name = enc.pop("__blobfile__", None)
    if blob_name is not None:
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        if not os.path.exists(blob_path):
            raise FileNotFoundError(
                f"checkpoint sidecar {blob_path!r} is missing — the "
                f"checkpoint directory must be moved/copied as a whole")
        nbytes = os.path.getsize(blob_path)
        blob_buf = native.read_binary(blob_path, np.uint8, (nbytes,))
        enc = _restore_blobs(enc, blob_buf)
    return {k: _decode(v, mesh) for k, v in enc.items()}


def load_pytree(path: str, mesh=None,
                backend: Optional[str] = None) -> Dict[str, Any]:
    """Load a :func:`save_pytree` checkpoint. Pass ``mesh`` to restore
    onto a specific mesh — including one with a DIFFERENT device count
    (mesh-elastic restore, module docstring). Watchdogged like
    :func:`save_pytree` (a multi-host load is a rendezvous too)."""
    from ..resilience.elastic import watched_call
    return watched_call(_load_pytree_impl, path, mesh=mesh,
                        backend=backend, stage="checkpoint_io")


def save_solver(path: str, solver, x=None,
                backend: Optional[str] = None) -> None:
    """Snapshot a CG/CGLS/ISTA/FISTA solver mid-run (between ``step``
    calls) so a later process can resume. ``backend="orbax"`` writes
    the sharded buffers without a host gather (multi-host safe)."""
    # resolve arg-or-env ONCE: the env-var route must pick the same
    # encoding as the explicit argument
    backend = backend or os.environ.get("PYLOPS_MPI_TPU_CKPT_BACKEND",
                                        "native")
    orbax = backend == "orbax"
    state: Dict[str, Any] = {"__class__": type(solver).__name__}
    for field in _SOLVER_FIELDS:
        if hasattr(solver, field):
            v = getattr(solver, field)
            state[field] = v if orbax else _encode(v)
    if x is not None:
        state["x"] = x if orbax else _encode(x)
    save_pytree(path, state, backend=backend)


def load_solver(path: str, solver, mesh=None,
                backend: Optional[str] = None):
    """Restore a snapshot into a freshly-constructed solver (same
    operator). Returns the model vector ``x`` if it was saved."""
    state = load_pytree(path, mesh=mesh, backend=backend)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(solver).__name__:
        raise ValueError(f"checkpoint is for {cls}, not {type(solver).__name__}")
    x = state.pop("x", None)
    for k, v in state.items():
        setattr(solver, k, v)
    return x


# ------------------------------------------------ fused-carry schema
# Mid-solve snapshots of the SEGMENTED fused solvers
# (solvers/segmented.py, ISSUE 6): the whole while_loop carry — the
# distributed recurrence vectors plus the recurrence scalars, the
# iteration counter, the cost buffers, the machine-precision floor and
# the guard words — under a versioned header, so a killed process can
# resume mid-solve and replay the remaining epochs bit-identically.
FUSED_SCHEMA_VERSION = 1


def save_fused_carry(path: str, solver: str, carry: Dict[str, Any],
                     backend: Optional[str] = None) -> None:
    """Snapshot a segmented fused solve's carry between epochs.
    ``solver`` names the loop family (``"cg"``/``"cgls"``); ``carry``
    is the field dict the segmented driver threads (plus its plan
    metadata — ``niter``/``damp``/``tol``/``epoch``/``guards``), all of
    which round-trips bit-exactly through either backend."""
    state = dict(carry)
    state["__fused__"] = solver
    state["__fused_schema__"] = FUSED_SCHEMA_VERSION
    save_pytree(path, state, backend=backend)


def load_fused_carry(path: str, solver: str, mesh=None,
                     backend: Optional[str] = None) -> Dict[str, Any]:
    """Load a segmented fused carry saved by :func:`save_fused_carry`,
    validating the solver family and schema version (a mismatch names
    the problem instead of resuming a wrong trajectory).

    ``mesh`` may differ from the save-time mesh in device count and
    axis split (mesh-elastic restore, module docstring): the carry's
    distributed vectors reshard onto the new balanced split, so a
    shrunk post-failure job resumes the solve where the full job
    left off. Recurrence scalars are layout-independent and pass
    through untouched."""
    state = load_pytree(path, mesh=mesh, backend=backend)
    kind = state.pop("__fused__", None)
    if kind is None:
        raise ValueError(
            f"{path!r} is not a fused-carry checkpoint (it may be a "
            "class-API save_solver snapshot — load it with load_solver)")
    if kind != solver:
        raise ValueError(f"fused-carry checkpoint is for {kind!r}, "
                         f"not {solver!r}")
    schema = state.pop("__fused_schema__", None)
    if schema != FUSED_SCHEMA_VERSION:
        raise ValueError(
            f"fused-carry schema {schema!r} != {FUSED_SCHEMA_VERSION} "
            f"(checkpoint written by an incompatible version)")
    return state

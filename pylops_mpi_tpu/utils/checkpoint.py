"""Solver-state checkpoint / resume.

The reference has **no** checkpointing (SURVEY §5: solvers expose
``setup/step/run`` so callers *could* snapshot externally, ref
``cls_basic.py:57-141``, but no serialization exists). This module adds
it as a genuine improvement: any solver's state (DistributedArrays,
scalars, cost history) is a pytree, saved with orbax when available and
a NumPy fallback otherwise. Sharded arrays are restored to their
original Partition/axis layout.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np
import jax

from ..distributedarray import DistributedArray, Partition
from ..stacked import StackedDistributedArray

__all__ = ["save_solver", "load_solver", "save_pytree", "load_pytree"]

_SOLVER_FIELDS = ("y", "s", "r", "c", "q", "kold", "iiter", "cost", "cost1",
                  "damp", "tol", "niter", "t", "z", "alpha", "thresh",
                  "normresold", "eps")


def _encode(v):
    if isinstance(v, DistributedArray):
        return {"__dist__": True, "value": v.asarray(),
                "partition": v.partition.name, "axis": v.axis,
                "local_shapes": v.local_shapes, "mask": v.mask}
    if isinstance(v, StackedDistributedArray):
        return {"__stacked__": True,
                "arrays": [_encode(d) for d in v.distarrays]}
    if isinstance(v, jax.Array):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        return type(v)(_encode(e) for e in v)
    return v


def _decode(v, mesh=None):
    if isinstance(v, dict) and v.get("__dist__"):
        out = DistributedArray.to_dist(
            v["value"], mesh=mesh, partition=Partition[v["partition"]],
            axis=v["axis"], local_shapes=v["local_shapes"], mask=v["mask"])
        return out
    if isinstance(v, dict) and v.get("__stacked__"):
        return StackedDistributedArray([_decode(d, mesh) for d in v["arrays"]])
    if isinstance(v, (list, tuple)):
        return type(v)(_decode(e, mesh) for e in v)
    return v


def save_pytree(path: str, tree: Dict[str, Any]) -> None:
    """Serialize a dict of arrays/DistributedArrays/scalars."""
    enc = {k: _encode(v) for k, v in tree.items()}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(enc, f)


def load_pytree(path: str, mesh=None) -> Dict[str, Any]:
    with open(path, "rb") as f:
        enc = pickle.load(f)
    return {k: _decode(v, mesh) for k, v in enc.items()}


def save_solver(path: str, solver, x=None) -> None:
    """Snapshot a CG/CGLS/ISTA/FISTA solver mid-run (between ``step``
    calls) so a later process can resume."""
    state: Dict[str, Any] = {"__class__": type(solver).__name__}
    for field in _SOLVER_FIELDS:
        if hasattr(solver, field):
            state[field] = _encode(getattr(solver, field))
    if x is not None:
        state["x"] = _encode(x)
    save_pytree(path, state)


def load_solver(path: str, solver, mesh=None):
    """Restore a snapshot into a freshly-constructed solver (same
    operator). Returns the model vector ``x`` if it was saved."""
    state = load_pytree(path, mesh=mesh)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(solver).__name__:
        raise ValueError(f"checkpoint is for {cls}, not {type(solver).__name__}")
    x = state.pop("x", None)
    for k, v in state.items():
        setattr(solver, k, v)
    return x

"""Solver-state checkpoint / resume.

The reference has **no** checkpointing (SURVEY §5: solvers expose
``setup/step/run`` so callers *could* snapshot externally, ref
``cls_basic.py:57-141``, but no serialization exists). This module adds
it as a genuine improvement: any solver's state (DistributedArrays,
scalars, cost history) is a pytree, saved with orbax when available and
a NumPy fallback otherwise. Sharded arrays are restored to their
original Partition/axis layout.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np
import jax

from ..distributedarray import DistributedArray, Partition
from ..stacked import StackedDistributedArray

__all__ = ["save_solver", "load_solver", "save_pytree", "load_pytree"]

_SOLVER_FIELDS = ("y", "s", "r", "c", "q", "kold", "iiter", "cost", "cost1",
                  "damp", "tol", "niter", "t", "z", "alpha", "thresh",
                  "normresold", "eps")


def _encode(v):
    if isinstance(v, DistributedArray):
        return {"__dist__": True, "value": v.asarray(),
                "partition": v.partition.name, "axis": v.axis,
                "local_shapes": v.local_shapes, "mask": v.mask}
    if isinstance(v, StackedDistributedArray):
        return {"__stacked__": True,
                "arrays": [_encode(d) for d in v.distarrays]}
    if isinstance(v, jax.Array):
        return np.asarray(v)
    if isinstance(v, (list, tuple)):
        return type(v)(_encode(e) for e in v)
    return v


def _decode(v, mesh=None):
    if isinstance(v, dict) and v.get("__dist__"):
        out = DistributedArray.to_dist(
            v["value"], mesh=mesh, partition=Partition[v["partition"]],
            axis=v["axis"], local_shapes=v["local_shapes"], mask=v["mask"])
        return out
    if isinstance(v, dict) and v.get("__stacked__"):
        return StackedDistributedArray([_decode(d, mesh) for d in v["arrays"]])
    if isinstance(v, (list, tuple)):
        return type(v)(_decode(e, mesh) for e in v)
    return v


# Arrays at or above this size are stored in a sidecar blob file written
# by the native (C++) threaded writer instead of being pickled inline.
_BLOB_THRESHOLD = 1 << 20


def _extract_blobs(v, blobs):
    if isinstance(v, np.ndarray) and v.nbytes >= _BLOB_THRESHOLD:
        a = np.ascontiguousarray(v)
        off = sum(b.nbytes for b in blobs)
        blobs.append(a)
        return {"__blob__": True, "offset": off, "dtype": a.dtype.str,
                "shape": a.shape}
    if isinstance(v, dict):
        return {k: _extract_blobs(e, blobs) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_extract_blobs(e, blobs) for e in v)
    return v


def _restore_blobs(v, blob_buf):
    if isinstance(v, dict) and v.get("__blob__"):
        dt = np.dtype(v["dtype"])
        n = int(np.prod(v["shape"], dtype=np.int64))
        off = v["offset"]
        return np.frombuffer(blob_buf, dtype=dt, count=n,
                             offset=off).reshape(v["shape"]).copy()
    if isinstance(v, dict):
        return {k: _restore_blobs(e, blob_buf) for k, e in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_restore_blobs(e, blob_buf) for e in v)
    return v


def save_pytree(path: str, tree: Dict[str, Any]) -> None:
    """Serialize a dict of arrays/DistributedArrays/scalars. Large array
    payloads stream one-by-one (flat peak memory) into a uniquely-named
    sidecar via the native threaded writer; the pickle references the
    sidecar by name and is replaced atomically, so a crash mid-save
    leaves the previous checkpoint pair intact."""
    import glob
    import secrets
    from .. import native
    enc = {k: _encode(v) for k, v in tree.items()}
    blobs: list = []
    enc = _extract_blobs(enc, blobs)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    old_sidecars = glob.glob(os.path.abspath(path) + ".blobs.*")
    blob_name = None
    if blobs:
        blob_name = os.path.basename(path) + ".blobs." + secrets.token_hex(4)
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        off = 0
        for b in blobs:
            native.write_binary_at(blob_path, off, b.view(np.uint8).reshape(-1))
            off += b.nbytes
    enc["__blobfile__"] = blob_name
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        pickle.dump(enc, f)
    os.replace(tmp, path)
    for old in old_sidecars:
        if os.path.basename(old) != blob_name and os.path.exists(old):
            os.remove(old)


def load_pytree(path: str, mesh=None) -> Dict[str, Any]:
    from .. import native
    with open(path, "rb") as f:
        enc = pickle.load(f)
    blob_name = enc.pop("__blobfile__", None)
    if blob_name is not None:
        blob_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                 blob_name)
        if not os.path.exists(blob_path):
            raise FileNotFoundError(
                f"checkpoint sidecar {blob_path!r} is missing — the "
                f"checkpoint directory must be moved/copied as a whole")
        nbytes = os.path.getsize(blob_path)
        blob_buf = native.read_binary(blob_path, np.uint8, (nbytes,))
        enc = _restore_blobs(enc, blob_buf)
    return {k: _decode(v, mesh) for k, v in enc.items()}


def save_solver(path: str, solver, x=None) -> None:
    """Snapshot a CG/CGLS/ISTA/FISTA solver mid-run (between ``step``
    calls) so a later process can resume."""
    state: Dict[str, Any] = {"__class__": type(solver).__name__}
    for field in _SOLVER_FIELDS:
        if hasattr(solver, field):
            state[field] = _encode(getattr(solver, field))
    if x is not None:
        state["x"] = _encode(x)
    save_pytree(path, state)


def load_solver(path: str, solver, mesh=None):
    """Restore a snapshot into a freshly-constructed solver (same
    operator). Returns the model vector ``x`` if it was saved."""
    state = load_pytree(path, mesh=mesh)
    cls = state.pop("__class__", None)
    if cls is not None and cls != type(solver).__name__:
        raise ValueError(f"checkpoint is for {cls}, not {type(solver).__name__}")
    x = state.pop("x", None)
    for k, v in state.items():
        setattr(solver, k, v)
    return x

"""Feature flags / environment configuration.

Rebuild of ``pylops_mpi/utils/deps.py:1-66``. The reference's flags pick
between MPI, CUDA-aware MPI and NCCL backends at import time
(``NCCL_PYLOPS_MPI``, ``PYLOPS_MPI_CUDA_AWARE``). The TPU build has one
backend — XLA collectives — so the seam carries different switches:

- ``PYLOPS_MPI_TPU_PLATFORM``: force ``jax_platforms`` (e.g. ``cpu``
  for the 8-virtual-device simulation) before first backend use.
- ``PYLOPS_MPI_TPU_X64``: enable float64 (defaults to JAX's setting;
  TPUs prefer f32/bf16).
- ``BENCH_PYLOPS_MPI`` / ``BENCH_PYLOPS_MPI_TPU``: benchmark kill-switch
  (ref ``utils/benchmark.py:25``; both names honoured).
- ``TEST_CUPY_PYLOPS`` has no analog (no CuPy engine); kept as a no-op
  recognised name so reference test-harness scripts don't break.
- ``PYLOPS_MPI_TPU_MATMUL_PRECISION``: default ``highest`` — on TPU the
  stock matmul precision decomposes f32 operands into bf16 MXU passes
  (~1e-3 relative error, measured on hardware by the round-3
  selfcheck's SUMMA check), which breaks numerics parity with the
  reference's true-f32 GEMMs. Pinning ``jax_default_matmul_precision``
  makes ``float32`` operators mean float32; the fast path stays
  available explicitly through ``compute_dtype=bfloat16`` (bf16 inputs
  are unaffected by the precision flag). Set to ``default`` to restore
  JAX's backend default.
- ``PYLOPS_MPI_TPU_OVERLAP``: ``auto`` (default) | ``on`` | ``off`` —
  the pipelined-collectives seam (round 8). ``on`` switches the
  comm-heavy operator families to overlapped schedules: ring SUMMA
  (double-buffered ``ppermute`` + per-step GEMM instead of bulk
  gather/psum), chunked pencil transposes (K tiled ``all_to_all``\\ s
  interleaved with the per-chunk local transforms), and
  interior/boundary-split halo stencils (ghost ``ppermute``\\ s in
  flight while the interior computes). ``off`` keeps the bulk
  schedules bit-identical to pre-round-8 results; ``auto`` enables the
  overlap only on real TPU backends, where it hides ICI transfer
  behind MXU compute — on the CPU simulation the chunked schedules
  only add dispatches. Per-operator ``overlap=`` kwargs override the
  env.
- ``PYLOPS_MPI_TPU_COMM_CHUNKS``: default chunk count (4) for the
  streamed pencil transposes when the overlap is enabled; per-operator
  ``comm_chunks=`` wins. Chunk counts that don't fit the axis fall
  back (logged) instead of erroring.
- ``PYLOPS_MPI_TPU_HIERARCHICAL``: ``auto`` (default) | ``on`` |
  ``off`` — the topology-aware collectives seam (round 11). ``on``
  switches the comm-heavy operators to hierarchical schedules on
  hybrid (dcn × ici) meshes: two-level pencil transposes that keep the
  dense shuffle on ICI and stage one smaller exchange over DCN,
  slice-staged rings and two-level reduce-scatter/all-gather. ``off``
  keeps the flat schedules bit-identical; ``auto`` engages on real TPU
  backends or when ``PYLOPS_MPI_TPU_FABRIC`` declares a simulated
  fabric. Per-operator ``hierarchical=`` kwargs override the env.
- ``PYLOPS_MPI_TPU_FABRIC``: ``DxI`` (e.g. ``2x4``) — CPU-sim fabric
  override for :mod:`pylops_mpi_tpu.parallel.topology`: classify the
  device list as D slices of I devices each when deciding which mesh
  axes are ICI vs DCN.
- ``PYLOPS_MPI_TPU_TRACE`` / ``PYLOPS_MPI_TPU_TELEMETRY`` /
  ``PYLOPS_MPI_TPU_TRACE_FILE`` / ``PYLOPS_MPI_TPU_PROFILE_DIR`` /
  ``PYLOPS_MPI_TPU_METRICS`` (``_FILE``, ``_INTERVAL``): the
  observability seams (rounds 9/10) — structured span tracing, in-loop
  solver telemetry, ``jax.profiler`` capture and the fleet metrics
  registry. Resolved by :mod:`pylops_mpi_tpu.diagnostics` (see
  ``docs/observability.md``), not here, so the jax-free scripts can
  read them standalone.
"""

from __future__ import annotations

import os

__all__ = ["jax_enabled", "platform_override", "x64_enabled",
           "explicit_stencil_enabled", "apply_environment",
           "overlap_mode", "overlap_enabled", "comm_chunks_default",
           "batch_default",
           "overlap_env_pinned", "comm_chunks_env_pinned",
           "hierarchical_mode", "hierarchical_enabled",
           "hierarchical_env_pinned",
           "KNOBS", "knob_names", "knob_table_markdown"]

jax_enabled = True  # the only engine; mirrors deps.nccl_enabled's role


# --------------------------------------------------------- knob registry
# The ONE table of every PYLOPS_MPI_TPU_* environment knob (round 10):
# (name, values, default, consumer module(s), one-line purpose).
# tests/test_tuning.py greps the package for knob reads and fails on
# any knob missing here; docs/tpu.md renders this table
# (knob_table_markdown) instead of per-PR ad-hoc lists. Add a row when
# you add a knob — or better, register a tuning space
# (pylops_mpi_tpu/tuning/space.py) instead of adding one.
KNOBS = [
    ("PYLOPS_MPI_TPU_PLATFORM", "cpu|tpu|…", "unset (auto)",
     "utils/deps.py",
     "force the JAX platform before first backend use"),
    ("PYLOPS_MPI_TPU_X64", "0|1", "0", "utils/deps.py",
     "enable float64 (TPUs prefer f32/bf16)"),
    ("PYLOPS_MPI_TPU_MATMUL_PRECISION", "highest|default|…", "highest",
     "utils/deps.py",
     "jax_default_matmul_precision pin (f32 means f32 on the MXU)"),
    ("PYLOPS_MPI_TPU_EXPLICIT_STENCIL", "0|1", "1",
     "utils/deps.py, ops/derivatives.py",
     "hand-scheduled shard_map stencil path vs implicit GSPMD"),
    ("PYLOPS_MPI_TPU_OVERLAP", "auto|on|off", "auto",
     "utils/deps.py (ops/matrixmult|fft|stack|derivatives|halo)",
     "pipelined-collectives seam: ring SUMMA, chunked transposes, "
     "split halo stencils"),
    ("PYLOPS_MPI_TPU_COMM_CHUNKS", "int>=1", "4",
     "utils/deps.py, ops/fft.py",
     "default chunk count for streamed pencil transposes"),
    ("PYLOPS_MPI_TPU_RESHARD_BUDGET", "bytes (k/m/g suffixes)",
     "unset (unbounded)", "parallel/reshard.py",
     "peak per-device scratch ceiling of the resharding planner; a "
     "move that cannot fit refuses with the minimum budget that "
     "would succeed"),
    ("PYLOPS_MPI_TPU_SPILL", "auto|on|off", "auto",
     "utils/deps.py (parallel/reshard.py, parallel/spill.py)",
     "host-RAM spill tier for the resharding planner: auto converts "
     "only would-refuse moves into double-buffered host-staged "
     "schedules, on forces host staging for every concrete move, off "
     "keeps the round-13 refusal behavior bit-identical"),
    ("PYLOPS_MPI_TPU_HIERARCHICAL", "auto|on|off", "auto",
     "utils/deps.py (parallel/topology.py, "
     "ops/matrixmult|fft|stack|halo|derivatives)",
     "topology-aware hierarchical collectives on hybrid (dcn x ici) "
     "meshes: two-level pencil transposes, slice-staged rings, "
     "per-fabric byte accounting; off keeps the flat schedules "
     "bit-identical"),
    ("PYLOPS_MPI_TPU_FABRIC", "DxI (e.g. 2x4)", "unset (detect)",
     "parallel/topology.py",
     "fabric override for CPU-sim testing: treat the device list as D "
     "slices of I devices each (id-major) when classifying mesh axes "
     "as ICI/DCN"),
    ("PYLOPS_MPI_TPU_PRECISION", "f32|bf16|c64", "f32",
     "ops/_precision.py",
     "storage/compute precision policy for operators built with "
     "compute_dtype=None"),
    ("PYLOPS_MPI_TPU_DONATE", "0|1", "1",
     "ops/_precision.py, solvers/basic.py, utils/hlo.py",
     "buffer donation of the fused solvers' model-vector argument"),
    ("PYLOPS_MPI_TPU_FUSED_CACHE", "int>=1", "32", "solvers/basic.py",
     "fused-solver executable cache capacity"),
    ("PYLOPS_MPI_TPU_FFT_MODE", "auto|xla|matmul|planar", "auto",
     "ops/dft.py",
     "local-FFT engine seam (planar = complex-free plane pairs)"),
    ("PYLOPS_MPI_TPU_FFTLESS_RUNTIMES", "csv of runtime substrings",
     "built-in list", "ops/dft.py",
     "runtimes known to lack the fft custom-call (auto avoids XLA "
     "FFT there)"),
    ("PYLOPS_MPI_TPU_DFT_BASE", "int", "128 on TPU / 16 on CPU",
     "ops/dft.py", "mixed-radix GEMM base of the matmul DFT engine"),
    ("PYLOPS_MPI_TPU_FFI_COMPLEX", "0|1", "1", "ops/blockdiag.py",
     "complex blocks may use the native XLA-FFI fused-normal kernel"),
    ("PYLOPS_MPI_TPU_FFI_THREADS", "int", "cores/devices",
     "native/ffi.py", "threads per FFI fused-normal kernel call"),
    ("PYLOPS_MPI_TPU_NATIVE", "0|1", "1", "native/__init__.py",
     "build/load the native host-pack helper library"),
    ("PYLOPS_MPI_TPU_NATIVE_THREADS", "int", "min(16, cores)",
     "native/__init__.py", "threads for native pack/IO helpers"),
    ("PYLOPS_MPI_TPU_CKPT_BACKEND", "native|orbax", "native",
     "utils/checkpoint.py", "checkpoint encode/decode backend"),
    ("PYLOPS_MPI_TPU_GUARDS", "off|on", "off",
     "resilience/status.py (solvers/basic.py, solvers/sparsity.py)",
     "in-loop breakdown/stagnation guards in the fused solvers; off "
     "traces bit-identical programs"),
    ("PYLOPS_MPI_TPU_GUARD_STALL", "int>=2", "50",
     "resilience/status.py",
     "stagnation window: iterations without a new best residual "
     "before status=stagnation"),
    ("PYLOPS_MPI_TPU_RESTARTS", "int>=0", "2",
     "resilience/driver.py",
     "max precision-escalation restarts of resilient_solve"),
    ("PYLOPS_MPI_TPU_SEGMENT", "int>=0", "0 (one segment)",
     "solvers/segmented.py",
     "default epoch length of the segmented fused solvers "
     "(checkpoint cadence)"),
    ("PYLOPS_MPI_TPU_RETRIES", "int>=0", "3",
     "resilience/retry.py (parallel/mesh.py, benchmarks)",
     "bounded retries for transient host-side faults (multihost "
     "init, harvest stage spawn)"),
    ("PYLOPS_MPI_TPU_RETRY_BACKOFF", "seconds", "0.5",
     "resilience/retry.py",
     "initial retry backoff (doubling, capped at 30 s)"),
    ("PYLOPS_MPI_TPU_TRACE", "off|spans|full", "off",
     "diagnostics/trace.py (linearoperator, collectives, solvers)",
     "structured span tracing; full adds in-loop solver telemetry"),
    ("PYLOPS_MPI_TPU_TRACE_FILE", "path", "unset",
     "diagnostics/trace.py", "auto-dump the trace JSONL at exit"),
    ("PYLOPS_MPI_TPU_TRACE_BUFFER", "int", "65536",
     "diagnostics/trace.py", "trace ring-buffer capacity (events)"),
    ("PYLOPS_MPI_TPU_TELEMETRY", "auto|on|off", "auto",
     "diagnostics/telemetry.py",
     "in-loop solver telemetry gate under TRACE=full"),
    ("PYLOPS_MPI_TPU_PROFILE_DIR", "path", "unset",
     "diagnostics/profiler.py",
     "jax.profiler capture dir for profile_capture regions"),
    ("PYLOPS_MPI_TPU_TUNE", "off|on|auto", "off",
     "tuning/plan.py (ops/*, parallel/collectives.py)",
     "autotuner seam: on replays cached/cost-model plans, auto also "
     "measures on cache miss"),
    ("PYLOPS_MPI_TPU_TUNE_CACHE", "path", "unset (memory-only)",
     "tuning/cache.py", "persistent JSON plan cache"),
    ("PYLOPS_MPI_TPU_TUNE_BUDGET", "seconds", "STAGE_BUDGETS['tune']",
     "tuning/search.py", "wall budget for one measurement search"),
    ("PYLOPS_MPI_TPU_TUNE_TOPK", "int>=1", "4", "tuning/search.py",
     "how many seed-ranked candidates get timed"),
    ("PYLOPS_MPI_TPU_TUNE_MARGIN", "float", "0.02", "tuning/search.py",
     "fractional win required to move off the default plan"),
    ("PYLOPS_MPI_TPU_BATCH", "int>=1", "1",
     "utils/deps.py (benchmarks, tuning contexts)",
     "default RHS-column count K of the batched solve paths (block "
     "solvers' bench race width; carried into plan-cache keys)"),
    ("PYLOPS_MPI_TPU_TEST_DEVICES", "int", "8",
     "tests/conftest.py, .github/workflows/build.yml",
     "virtual-device count of the CPU-sim test mesh"),
    ("PYLOPS_MPI_TPU_RETRY_JITTER", "float in [0,1]", "0",
     "resilience/retry.py",
     "decorrelating backoff jitter fraction (supervisor sets 0.25 for "
     "workers so reconnects don't stampede)"),
    ("PYLOPS_MPI_TPU_HEARTBEAT", "seconds", "1.0",
     "resilience/elastic.py",
     "heartbeat-write interval of supervised workers"),
    ("PYLOPS_MPI_TPU_HEARTBEAT_FILE", "path", "unset (unsupervised)",
     "resilience/elastic.py (set by resilience/supervisor.py)",
     "per-worker beat file; also the auto trigger for the collective "
     "watchdog"),
    ("PYLOPS_MPI_TPU_WATCHDOG", "auto|on|off", "auto",
     "resilience/elastic.py (parallel/mesh.py, utils/checkpoint.py)",
     "collective watchdog over blocking host-side phases; auto arms "
     "only under supervision, off is bit-identical"),
    ("PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT", "seconds",
     "STAGE_BUDGETS per stage", "resilience/elastic.py",
     "global override of every watched stage's deadline"),
    ("PYLOPS_MPI_TPU_COORDINATOR", "host:port", "set by supervisor",
     "resilience/elastic.py, resilience/supervisor.py",
     "jax.distributed coordinator address of the current attempt"),
    ("PYLOPS_MPI_TPU_NUM_PROCESSES", "int>=1", "set by supervisor",
     "resilience/elastic.py, resilience/supervisor.py",
     "world size of the current attempt (shrinks after failures)"),
    ("PYLOPS_MPI_TPU_PROCESS_ID", "int>=0", "set by supervisor",
     "resilience/elastic.py, resilience/supervisor.py",
     "this worker's rank within the current attempt"),
    ("PYLOPS_MPI_TPU_ATTEMPT", "int>=0", "set by supervisor",
     "resilience/elastic.py, resilience/supervisor.py",
     "0-based relaunch counter of the supervised job"),
    ("PYLOPS_MPI_TPU_INPLACE", "auto|on|off", "auto",
     "resilience/elastic.py (solvers/segmented.py)",
     "in-place (no-checkpoint) elastic recovery: survivors bank the "
     "solver carry each epoch and replan it onto the shrunk mesh on a "
     "reconfig; auto arms only when the supervisor assigned a "
     "reconfig file"),
    ("PYLOPS_MPI_TPU_QUORUM", "float in (0,1]", "0.5",
     "resilience/elastic.py, resilience/supervisor.py",
     "surviving fraction of the attempt's world required before the "
     "in-place path engages; below it the checkpoint-relaunch ladder "
     "runs"),
    ("PYLOPS_MPI_TPU_RECONFIG_FILE", "path",
     "unset (set by supervisor under inplace=True)",
     "resilience/elastic.py (resilience/supervisor.py)",
     "per-worker in-place reassignment file; its presence is the auto "
     "trigger for carry banking and reconfig polling"),
    ("PYLOPS_MPI_TPU_FAULT_KILL_RESHARD", "int>=1", "unset (off)",
     "resilience/faults.py (parallel/reshard.py)",
     "chaos seam: SIGKILL this process when the reshard-step counter "
     "reaches N — rehearses a worker dying mid-reshard so the "
     "checkpoint fallback path stays proven"),
    ("PYLOPS_MPI_TPU_FAULT_KILL_SPILL", "int>=1", "unset (off)",
     "resilience/faults.py (parallel/spill.py)",
     "chaos seam: SIGKILL this process when the host-stage step "
     "counter reaches N — rehearses a worker dying mid-spill so the "
     "checkpoint fallback path stays proven"),
    ("PYLOPS_MPI_TPU_METRICS", "off|on", "off",
     "diagnostics/metrics.py (solvers, collectives, resilience, "
     "tuning)",
     "fleet metrics registry; off is zero-cost no-op handles and the "
     "fused-solver HLO stays bit-identical"),
    ("PYLOPS_MPI_TPU_METRICS_FILE", "path",
     "unset (set by supervisor per worker)",
     "diagnostics/metrics.py (resilience/supervisor.py)",
     "periodic atomic JSON snapshot target of the metrics registry"),
    ("PYLOPS_MPI_TPU_METRICS_INTERVAL", "seconds", "5.0",
     "diagnostics/metrics.py",
     "snapshot-write cadence of the background metrics writer"),
    ("PYLOPS_MPI_TPU_BATCHED_CACHE", "int>=1", "8",
     "solvers/block.py",
     "batched_solve per-family compiled-executable LRU capacity "
     "(hit/miss counters: solver.batched.cache.*)"),
    ("PYLOPS_MPI_TPU_SERVE_QUEUE", "int>=1", "1024",
     "serving/queue.py",
     "admission-queue depth bound; a submit past it is rejected "
     "(QueueFull) — the serving backpressure knob"),
    ("PYLOPS_MPI_TPU_SERVE_WINDOW_MS", "milliseconds", "10.0",
     "serving/queue.py",
     "batch-formation window: how long the dispatcher holds an "
     "undersized batch open for late arrivals"),
    ("PYLOPS_MPI_TPU_SERVE_K_BUCKETS", "csv of ints", "1,2,4,8,16",
     "serving/engine.py",
     "block-width buckets the warm pool compiles and the packer "
     "rounds ragged fills up to"),
    ("PYLOPS_MPI_TPU_SERVE_DRAIN_TIMEOUT", "seconds", "30.0",
     "serving/service.py",
     "graceful-drain bound: how long SIGTERM/drain waits for "
     "in-flight batches before giving up"),
    ("PYLOPS_MPI_TPU_PRECOND", "none|jacobi|block_jacobi|mg", "none",
     "ops/precond.py",
     "default preconditioner kind make_precond builds when no "
     "explicit kind is passed (solvers stay unpreconditioned — and "
     "bit-identical — unless a call site opts in with M=)"),
    ("PYLOPS_MPI_TPU_MG_LEVELS", "int>=1", "3",
     "ops/precond.py",
     "V-cycle depth VCyclePrecond builds when levels= is not given "
     "(auto-reduced when grid divisibility runs out first)"),
    ("PYLOPS_MPI_TPU_REFINE", "0|1", "0",
     "resilience/driver.py",
     "iterative-refinement gate: resilient_solve turns "
     "precision-escalation restarts into narrow-inner-solve + "
     "wide-correction refinement passes instead of full wide "
     "re-solves"),
    ("PYLOPS_MPI_TPU_CA", "off|pipelined|sstep|auto", "off",
     "solvers/ca.py (solvers/basic.py, solvers/block.py, "
     "solvers/segmented.py)",
     "communication-avoiding Krylov tier: pipelined single-reduction "
     "PCG/PCGLS, s-step Gram mode, or latency-aware auto selection "
     "via the costmodel; off traces today's fused engines "
     "bit-identically"),
    ("PYLOPS_MPI_TPU_CA_S", "int>=2", "4",
     "solvers/ca.py (tuning/space.py)",
     "s-step depth of the CA solvers' Gram mode: one stacked "
     "reduction per s iterations at the price of 2s-1 operator "
     "applies; the monomial-basis conditioning guard falls back to "
     "the pipelined engine on breakdown"),
    ("PYLOPS_MPI_TPU_REDUCE_STALL", "int>=0", "unset (off)",
     "parallel/collectives.py (solvers, bench.py)",
     "bench/chaos seam: chain an N-step serial scalar dependency "
     "onto every solver reduction result so the CPU sim becomes "
     "latency-dominated like a pod fabric; unset/0 traces "
     "bit-identical programs"),
    ("PYLOPS_MPI_TPU_AOT", "auto|on|off", "off",
     "aot/store.py (solvers/basic.py, serving/engine.py)",
     "ahead-of-time executable tier for the fused solver programs: "
     "on lowers+compiles explicitly, serializes the executable "
     "(PJRT) into the bank, and replays it through the flat-call "
     "path on the next process start; auto arms only when AOT_CACHE "
     "is set; off (default) traces today's jit path bit-identically"),
    ("PYLOPS_MPI_TPU_AOT_CACHE", "directory", "unset (memory-only)",
     "aot/store.py",
     "on-disk bank for serialized executables (index.json + one blob "
     "per entry, schema-versioned, atomic, flock'd read-merge-write; "
     "rank 0 writes, other ranks read); unset under AOT=on keeps the "
     "bank process-local in memory"),
    ("PYLOPS_MPI_TPU_COMPILE_CACHE", "directory", "unset (off)",
     "aot/compile_cache.py (package import)",
     "JAX persistent compilation cache dir — the fallback compile "
     "tier for programs the AOT bank does not serialize (closure "
     "operators, preconditioned solves, ISTA/FISTA); shared per CI "
     "job, rank-0-writes/others-read on multi-host"),
    ("PYLOPS_MPI_TPU_AUTODIFF", "off|on", "off",
     "utils/deps.py (solvers/basic.py, solvers/block.py, autodiff/*)",
     "differentiable-solver tier: on lets traced (jax.grad/jvp) "
     "inputs through cg/cgls/block_cg/block_cgls route to the "
     "implicit-diff custom_vjp rules (autodiff/implicit.py) instead "
     "of failing on the reverse-undifferentiable while_loop; off "
     "(default) leaves every solver entry and lowered program "
     "bit-identical — the explicit pylops_mpi_tpu.autodiff API "
     "works regardless of the knob"),
]


def knob_names():
    """Registered knob names (the set the registry test checks package
    reads against)."""
    return [row[0] for row in KNOBS]


def knob_table_markdown() -> str:
    """Render the registry as the markdown table embedded in
    docs/tpu.md ("Environment knobs") — regenerate the docs section
    with ``python -c "from pylops_mpi_tpu.utils.deps import
    knob_table_markdown; print(knob_table_markdown())"`` after adding
    a row."""
    lines = ["| knob | values | default | consumer | purpose |",
             "| --- | --- | --- | --- | --- |"]
    for name, values, default, consumer, purpose in KNOBS:
        lines.append(f"| `{name}` | `{values}` | {default} | "
                     f"{consumer} | {purpose} |")
    return "\n".join(lines)


def platform_override():
    return os.environ.get("PYLOPS_MPI_TPU_PLATFORM")


def explicit_stencil_enabled() -> bool:
    """Hand-scheduled shard_map (ring-halo ppermute + Pallas) stencil
    path for the axis-0 derivatives; set
    ``PYLOPS_MPI_TPU_EXPLICIT_STENCIL=0`` to force the implicit
    (GSPMD-partitioned) formulation."""
    return os.environ.get("PYLOPS_MPI_TPU_EXPLICIT_STENCIL", "1") != "0"


def x64_enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_X64", "0") == "1"


def precond_default() -> str:
    """``PYLOPS_MPI_TPU_PRECOND`` — the preconditioner kind
    :func:`~pylops_mpi_tpu.ops.precond.make_precond` builds when the
    caller passes no explicit ``kind``."""
    return os.environ.get("PYLOPS_MPI_TPU_PRECOND", "none").strip() \
        .lower() or "none"


def mg_levels_default() -> int:
    """``PYLOPS_MPI_TPU_MG_LEVELS`` — V-cycle depth (floored at 1; a
    malformed value falls back to the default rather than breaking
    construction)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_MG_LEVELS", "3"))
    except ValueError:
        v = 3
    return max(1, v)


def refine_enabled() -> bool:
    """``PYLOPS_MPI_TPU_REFINE`` — when on, resilient_solve's
    precision-escalation restarts run as iterative-refinement passes
    (narrow inner solve + wide correction, resilience/driver.py)."""
    return os.environ.get("PYLOPS_MPI_TPU_REFINE", "0") == "1"


_warned_ca = False


def ca_mode() -> str:
    """``PYLOPS_MPI_TPU_CA`` resolved to ``off``/``pipelined``/
    ``sstep``/``auto`` (unknown values fall back to ``off`` with a
    one-time warning — a typo in a CI matrix must not silently swap
    solver engines)."""
    global _warned_ca
    m = os.environ.get("PYLOPS_MPI_TPU_CA", "off").strip().lower()
    if m in ("", "none", "default", "0", "classic"):
        m = "off"
    if m not in ("off", "pipelined", "sstep", "auto"):
        if not _warned_ca:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_CA={m!r} is not one of "
                "['off', 'pipelined', 'sstep', 'auto']; using 'off'",
                stacklevel=2)
            _warned_ca = True
        m = "off"
    return m


_warned_autodiff = False


def autodiff_mode() -> str:
    """``PYLOPS_MPI_TPU_AUTODIFF`` resolved to ``off``/``on`` (unknown
    values fall back to ``off`` with a one-time warning — a typo must
    not silently change which solver entries accept tracers)."""
    global _warned_autodiff
    m = os.environ.get("PYLOPS_MPI_TPU_AUTODIFF", "off").strip().lower()
    if m in ("", "none", "default", "0"):
        m = "off"
    if m in ("1", "true"):
        m = "on"
    if m not in ("off", "on"):
        if not _warned_autodiff:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_AUTODIFF={m!r} is not one of "
                "['off', 'on']; using 'off'", stacklevel=2)
            _warned_autodiff = True
        m = "off"
    return m


def autodiff_enabled() -> bool:
    """True when the differentiable-solver tier may reroute traced
    solver inputs (see :func:`autodiff_mode`)."""
    return autodiff_mode() == "on"


def ca_s_default() -> int:
    """``PYLOPS_MPI_TPU_CA_S`` — s-step depth of the CA solvers' Gram
    mode (floored at 2; a malformed value falls back to the default
    rather than breaking the solve)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_CA_S", "4"))
    except ValueError:
        v = 4
    return max(2, v)


def reduce_stall_steps() -> int:
    """``PYLOPS_MPI_TPU_REDUCE_STALL`` — serial-chain length appended
    to every solver reduction result (0/unset = off, bit-identical
    trace; malformed values are off rather than breaking the solve)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_REDUCE_STALL", "0"))
    except ValueError:
        v = 0
    return max(0, v)


_warned_overlap = False


def overlap_mode() -> str:
    """``PYLOPS_MPI_TPU_OVERLAP`` resolved to ``auto``/``on``/``off``
    (unknown values fall back to ``auto`` with a one-time warning — a
    typo in a CI matrix must not silently flip schedules)."""
    global _warned_overlap
    m = os.environ.get("PYLOPS_MPI_TPU_OVERLAP", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in ("auto", "on", "off"):
        if not _warned_overlap:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_OVERLAP={m!r} is not one of "
                "['auto', 'on', 'off']; using 'auto'", stacklevel=2)
            _warned_overlap = True
        m = "auto"
    return m


def overlap_enabled(user=None) -> bool:
    """Resolve the pipelined-collectives tri-state to a bool. ``user``
    is a per-operator ``overlap=`` kwarg (``True``/``False``/
    ``"on"``/``"off"``/``"auto"``; ``None`` defers to the env).
    ``auto`` enables overlap only on real TPU backends: the ring /
    chunked schedules exist to hide ICI transfer behind compute, and on
    the CPU simulation they only add dispatch overhead while ``off``
    stays bit-identical to the bulk results."""
    if isinstance(user, bool):
        return user
    if user is None:
        mode = overlap_mode()
    else:
        mode = str(user).strip().lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"overlap={user!r}: expected 'auto', 'on', 'off', "
                "True or False")
    if mode == "on":
        return True
    if mode == "off":
        return False
    import jax
    return jax.default_backend() == "tpu"


def overlap_env_pinned() -> bool:
    """True when ``PYLOPS_MPI_TPU_OVERLAP`` is explicitly ``on`` or
    ``off`` — explicit env settings are user intent and beat the
    autotuner's plans, exactly like an explicit ``overlap=`` kwarg
    (``auto``/unset leaves the plan seam free to decide)."""
    return overlap_mode() in ("on", "off")


_warned_spill = False


def spill_mode() -> str:
    """``PYLOPS_MPI_TPU_SPILL`` resolved to ``auto``/``on``/``off``
    (unknown values fall back to ``auto`` with a one-time warning,
    same contract as :func:`overlap_mode`). ``off`` keeps the round-13
    planner refusal behavior bit-identical; ``auto`` (the default)
    converts ONLY moves the device planner would refuse into
    host-staged schedules — every currently-succeeding path keeps its
    device plan untouched; ``on`` forces host staging for every
    concrete cross-layout move (the CI rehearsal mode — traced moves
    never spill, a ``device_get`` needs a concrete array)."""
    global _warned_spill
    m = os.environ.get("PYLOPS_MPI_TPU_SPILL", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in ("auto", "on", "off"):
        if not _warned_spill:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_SPILL={m!r} is not one of "
                "['auto', 'on', 'off']; using 'auto'", stacklevel=2)
            _warned_spill = True
        m = "auto"
    return m


_warned_hier = False


def hierarchical_mode() -> str:
    """``PYLOPS_MPI_TPU_HIERARCHICAL`` resolved to
    ``auto``/``on``/``off`` (unknown values fall back to ``auto`` with
    a one-time warning, same contract as :func:`overlap_mode`)."""
    global _warned_hier
    m = os.environ.get("PYLOPS_MPI_TPU_HIERARCHICAL",
                       "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in ("auto", "on", "off"):
        if not _warned_hier:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_HIERARCHICAL={m!r} is not one of "
                "['auto', 'on', 'off']; using 'auto'", stacklevel=2)
            _warned_hier = True
        m = "auto"
    return m


def hierarchical_enabled(user=None) -> bool:
    """Resolve the hierarchical-collectives tri-state to a bool.
    ``user`` is a per-operator ``hierarchical=`` kwarg (``True``/
    ``False``/``"on"``/``"off"``/``"auto"``; ``None`` defers to the
    env). ``auto`` enables the hierarchical schedules on real TPU
    backends and on CPU simulations that declare a fabric via
    ``PYLOPS_MPI_TPU_FABRIC`` — everywhere else ``off`` keeps the flat
    schedules bit-identical. A True result is still only *intent*: the
    schedules engage per operator only when the mesh is actually
    hybrid (``parallel.topology.is_hybrid``)."""
    if isinstance(user, bool):
        return user
    if user is None:
        mode = hierarchical_mode()
    else:
        mode = str(user).strip().lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"hierarchical={user!r}: expected 'auto', 'on', 'off', "
                "True or False")
    if mode == "on":
        return True
    if mode == "off":
        return False
    if os.environ.get("PYLOPS_MPI_TPU_FABRIC", "").strip():
        return True
    import jax
    return jax.default_backend() == "tpu"


def hierarchical_env_pinned() -> bool:
    """True when ``PYLOPS_MPI_TPU_HIERARCHICAL`` is explicitly ``on``
    or ``off`` — explicit env settings beat the autotuner's plans,
    same precedence rule as :func:`overlap_env_pinned`."""
    return hierarchical_mode() in ("on", "off")


def comm_chunks_env_pinned() -> bool:
    """True when ``PYLOPS_MPI_TPU_COMM_CHUNKS`` is explicitly set
    (even to the default value) — same tuner-precedence rule as
    :func:`overlap_env_pinned`."""
    return "PYLOPS_MPI_TPU_COMM_CHUNKS" in os.environ


def batch_default() -> int:
    """Default RHS-column count ``K`` of the batched solve paths
    (``PYLOPS_MPI_TPU_BATCH``, default 1 = single-RHS; floored at 1).
    Consumed by the benchmark's batched-throughput race and forwarded
    into plan-cache contexts (``extra["batch"]``) so a plan measured
    at one block width is never replayed at another."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_BATCH", "1"))
    except ValueError:
        v = 1
    return max(1, v)


def comm_chunks_default() -> int:
    """Default chunk count for the streamed pencil transposes
    (``PYLOPS_MPI_TPU_COMM_CHUNKS``, default 4; floored at 1)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_COMM_CHUNKS", "4"))
    except ValueError:
        v = 4
    return max(1, v)


def matmul_precision():
    """``jax_default_matmul_precision`` to pin at import (see module
    docstring); ``default``/empty leaves JAX's backend default."""
    p = os.environ.get("PYLOPS_MPI_TPU_MATMUL_PRECISION", "highest")
    return None if p in ("", "default") else p


_applied = False


def apply_environment() -> None:
    """Apply env-flag configuration to JAX (idempotent; call before any
    jnp op if overriding the platform)."""
    global _applied
    if _applied:
        return
    import jax
    plat = platform_override()
    if plat:
        jax.config.update("jax_platforms", plat)
    if x64_enabled():
        jax.config.update("jax_enable_x64", True)
    prec = matmul_precision()
    if prec is not None:
        jax.config.update("jax_default_matmul_precision", prec)
    _applied = True

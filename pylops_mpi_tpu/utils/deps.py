"""Feature flags / environment configuration.

Rebuild of ``pylops_mpi/utils/deps.py:1-66``. The reference's flags pick
between MPI, CUDA-aware MPI and NCCL backends at import time
(``NCCL_PYLOPS_MPI``, ``PYLOPS_MPI_CUDA_AWARE``). The TPU build has one
backend — XLA collectives — so the seam carries different switches:

- ``PYLOPS_MPI_TPU_PLATFORM``: force ``jax_platforms`` (e.g. ``cpu``
  for the 8-virtual-device simulation) before first backend use.
- ``PYLOPS_MPI_TPU_X64``: enable float64 (defaults to JAX's setting;
  TPUs prefer f32/bf16).
- ``BENCH_PYLOPS_MPI`` / ``BENCH_PYLOPS_MPI_TPU``: benchmark kill-switch
  (ref ``utils/benchmark.py:25``; both names honoured).
- ``TEST_CUPY_PYLOPS`` has no analog (no CuPy engine); kept as a no-op
  recognised name so reference test-harness scripts don't break.
- ``PYLOPS_MPI_TPU_MATMUL_PRECISION``: default ``highest`` — on TPU the
  stock matmul precision decomposes f32 operands into bf16 MXU passes
  (~1e-3 relative error, measured on hardware by the round-3
  selfcheck's SUMMA check), which breaks numerics parity with the
  reference's true-f32 GEMMs. Pinning ``jax_default_matmul_precision``
  makes ``float32`` operators mean float32; the fast path stays
  available explicitly through ``compute_dtype=bfloat16`` (bf16 inputs
  are unaffected by the precision flag). Set to ``default`` to restore
  JAX's backend default.
"""

from __future__ import annotations

import os

__all__ = ["jax_enabled", "platform_override", "x64_enabled",
           "explicit_stencil_enabled", "apply_environment"]

jax_enabled = True  # the only engine; mirrors deps.nccl_enabled's role


def platform_override():
    return os.environ.get("PYLOPS_MPI_TPU_PLATFORM")


def explicit_stencil_enabled() -> bool:
    """Hand-scheduled shard_map (ring-halo ppermute + Pallas) stencil
    path for the axis-0 derivatives; set
    ``PYLOPS_MPI_TPU_EXPLICIT_STENCIL=0`` to force the implicit
    (GSPMD-partitioned) formulation."""
    return os.environ.get("PYLOPS_MPI_TPU_EXPLICIT_STENCIL", "1") != "0"


def x64_enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_X64", "0") == "1"


def matmul_precision():
    """``jax_default_matmul_precision`` to pin at import (see module
    docstring); ``default``/empty leaves JAX's backend default."""
    p = os.environ.get("PYLOPS_MPI_TPU_MATMUL_PRECISION", "highest")
    return None if p in ("", "default") else p


_applied = False


def apply_environment() -> None:
    """Apply env-flag configuration to JAX (idempotent; call before any
    jnp op if overriding the platform)."""
    global _applied
    if _applied:
        return
    import jax
    plat = platform_override()
    if plat:
        jax.config.update("jax_platforms", plat)
    if x64_enabled():
        jax.config.update("jax_enable_x64", True)
    prec = matmul_precision()
    if prec is not None:
        jax.config.update("jax_default_matmul_precision", prec)
    _applied = True

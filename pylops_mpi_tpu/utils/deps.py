"""Feature flags / environment configuration.

Rebuild of ``pylops_mpi/utils/deps.py:1-66``. The reference's flags pick
between MPI, CUDA-aware MPI and NCCL backends at import time
(``NCCL_PYLOPS_MPI``, ``PYLOPS_MPI_CUDA_AWARE``). The TPU build has one
backend — XLA collectives — so the seam carries different switches:

- ``PYLOPS_MPI_TPU_PLATFORM``: force ``jax_platforms`` (e.g. ``cpu``
  for the 8-virtual-device simulation) before first backend use.
- ``PYLOPS_MPI_TPU_X64``: enable float64 (defaults to JAX's setting;
  TPUs prefer f32/bf16).
- ``BENCH_PYLOPS_MPI`` / ``BENCH_PYLOPS_MPI_TPU``: benchmark kill-switch
  (ref ``utils/benchmark.py:25``; both names honoured).
- ``TEST_CUPY_PYLOPS`` has no analog (no CuPy engine); kept as a no-op
  recognised name so reference test-harness scripts don't break.
- ``PYLOPS_MPI_TPU_MATMUL_PRECISION``: default ``highest`` — on TPU the
  stock matmul precision decomposes f32 operands into bf16 MXU passes
  (~1e-3 relative error, measured on hardware by the round-3
  selfcheck's SUMMA check), which breaks numerics parity with the
  reference's true-f32 GEMMs. Pinning ``jax_default_matmul_precision``
  makes ``float32`` operators mean float32; the fast path stays
  available explicitly through ``compute_dtype=bfloat16`` (bf16 inputs
  are unaffected by the precision flag). Set to ``default`` to restore
  JAX's backend default.
- ``PYLOPS_MPI_TPU_OVERLAP``: ``auto`` (default) | ``on`` | ``off`` —
  the pipelined-collectives seam (round 8). ``on`` switches the
  comm-heavy operator families to overlapped schedules: ring SUMMA
  (double-buffered ``ppermute`` + per-step GEMM instead of bulk
  gather/psum), chunked pencil transposes (K tiled ``all_to_all``\\ s
  interleaved with the per-chunk local transforms), and
  interior/boundary-split halo stencils (ghost ``ppermute``\\ s in
  flight while the interior computes). ``off`` keeps the bulk
  schedules bit-identical to pre-round-8 results; ``auto`` enables the
  overlap only on real TPU backends, where it hides ICI transfer
  behind MXU compute — on the CPU simulation the chunked schedules
  only add dispatches. Per-operator ``overlap=`` kwargs override the
  env.
- ``PYLOPS_MPI_TPU_COMM_CHUNKS``: default chunk count (4) for the
  streamed pencil transposes when the overlap is enabled; per-operator
  ``comm_chunks=`` wins. Chunk counts that don't fit the axis fall
  back (logged) instead of erroring.
- ``PYLOPS_MPI_TPU_TRACE`` / ``PYLOPS_MPI_TPU_TELEMETRY`` /
  ``PYLOPS_MPI_TPU_TRACE_FILE`` / ``PYLOPS_MPI_TPU_PROFILE_DIR``: the
  observability seams (round 9) — structured span tracing, in-loop
  solver telemetry and ``jax.profiler`` capture. Resolved by
  :mod:`pylops_mpi_tpu.diagnostics` (see ``docs/observability.md``),
  not here, so the jax-free scripts can read them standalone.
"""

from __future__ import annotations

import os

__all__ = ["jax_enabled", "platform_override", "x64_enabled",
           "explicit_stencil_enabled", "apply_environment",
           "overlap_mode", "overlap_enabled", "comm_chunks_default"]

jax_enabled = True  # the only engine; mirrors deps.nccl_enabled's role


def platform_override():
    return os.environ.get("PYLOPS_MPI_TPU_PLATFORM")


def explicit_stencil_enabled() -> bool:
    """Hand-scheduled shard_map (ring-halo ppermute + Pallas) stencil
    path for the axis-0 derivatives; set
    ``PYLOPS_MPI_TPU_EXPLICIT_STENCIL=0`` to force the implicit
    (GSPMD-partitioned) formulation."""
    return os.environ.get("PYLOPS_MPI_TPU_EXPLICIT_STENCIL", "1") != "0"


def x64_enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_X64", "0") == "1"


_warned_overlap = False


def overlap_mode() -> str:
    """``PYLOPS_MPI_TPU_OVERLAP`` resolved to ``auto``/``on``/``off``
    (unknown values fall back to ``auto`` with a one-time warning — a
    typo in a CI matrix must not silently flip schedules)."""
    global _warned_overlap
    m = os.environ.get("PYLOPS_MPI_TPU_OVERLAP", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in ("auto", "on", "off"):
        if not _warned_overlap:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_OVERLAP={m!r} is not one of "
                "['auto', 'on', 'off']; using 'auto'", stacklevel=2)
            _warned_overlap = True
        m = "auto"
    return m


def overlap_enabled(user=None) -> bool:
    """Resolve the pipelined-collectives tri-state to a bool. ``user``
    is a per-operator ``overlap=`` kwarg (``True``/``False``/
    ``"on"``/``"off"``/``"auto"``; ``None`` defers to the env).
    ``auto`` enables overlap only on real TPU backends: the ring /
    chunked schedules exist to hide ICI transfer behind compute, and on
    the CPU simulation they only add dispatch overhead while ``off``
    stays bit-identical to the bulk results."""
    if isinstance(user, bool):
        return user
    if user is None:
        mode = overlap_mode()
    else:
        mode = str(user).strip().lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"overlap={user!r}: expected 'auto', 'on', 'off', "
                "True or False")
    if mode == "on":
        return True
    if mode == "off":
        return False
    import jax
    return jax.default_backend() == "tpu"


def comm_chunks_default() -> int:
    """Default chunk count for the streamed pencil transposes
    (``PYLOPS_MPI_TPU_COMM_CHUNKS``, default 4; floored at 1)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_COMM_CHUNKS", "4"))
    except ValueError:
        v = 4
    return max(1, v)


def matmul_precision():
    """``jax_default_matmul_precision`` to pin at import (see module
    docstring); ``default``/empty leaves JAX's backend default."""
    p = os.environ.get("PYLOPS_MPI_TPU_MATMUL_PRECISION", "highest")
    return None if p in ("", "default") else p


_applied = False


def apply_environment() -> None:
    """Apply env-flag configuration to JAX (idempotent; call before any
    jnp op if overriding the platform)."""
    global _applied
    if _applied:
        return
    import jax
    plat = platform_override()
    if plat:
        jax.config.update("jax_platforms", plat)
    if x64_enabled():
        jax.config.update("jax_enable_x64", True)
    prec = matmul_precision()
    if prec is not None:
        jax.config.update("jax_default_matmul_precision", prec)
    _applied = True

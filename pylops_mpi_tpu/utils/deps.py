"""Feature flags / environment configuration.

Rebuild of ``pylops_mpi/utils/deps.py:1-66``. The reference's flags pick
between MPI, CUDA-aware MPI and NCCL backends at import time
(``NCCL_PYLOPS_MPI``, ``PYLOPS_MPI_CUDA_AWARE``). The TPU build has one
backend — XLA collectives — so the seam carries different switches:

- ``PYLOPS_MPI_TPU_PLATFORM``: force ``jax_platforms`` (e.g. ``cpu``
  for the 8-virtual-device simulation) before first backend use.
- ``PYLOPS_MPI_TPU_X64``: enable float64 (defaults to JAX's setting;
  TPUs prefer f32/bf16).
- ``BENCH_PYLOPS_MPI`` / ``BENCH_PYLOPS_MPI_TPU``: benchmark kill-switch
  (ref ``utils/benchmark.py:25``; both names honoured).
- ``TEST_CUPY_PYLOPS`` has no analog (no CuPy engine); kept as a no-op
  recognised name so reference test-harness scripts don't break.
"""

from __future__ import annotations

import os

__all__ = ["jax_enabled", "platform_override", "x64_enabled",
           "explicit_stencil_enabled", "apply_environment"]

jax_enabled = True  # the only engine; mirrors deps.nccl_enabled's role


def platform_override():
    return os.environ.get("PYLOPS_MPI_TPU_PLATFORM")


def explicit_stencil_enabled() -> bool:
    """Hand-scheduled shard_map (ring-halo ppermute + Pallas) stencil
    path for the axis-0 derivatives; set
    ``PYLOPS_MPI_TPU_EXPLICIT_STENCIL=0`` to force the implicit
    (GSPMD-partitioned) formulation."""
    return os.environ.get("PYLOPS_MPI_TPU_EXPLICIT_STENCIL", "1") != "0"


def x64_enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_X64", "0") == "1"


_applied = False


def apply_environment() -> None:
    """Apply env-flag configuration to JAX (idempotent; call before any
    jnp op if overriding the platform)."""
    global _applied
    if _applied:
        return
    import jax
    plat = platform_override()
    if plat:
        jax.config.update("jax_platforms", plat)
    if x64_enabled():
        jax.config.update("jax_enable_x64", True)
    _applied = True

"""Benchmark / tracing utility.

Rebuild of ``pylops_mpi/utils/benchmark.py:25-173``: a ``@benchmark``
decorator plus in-function ``mark(label)`` region markers with a
nested-call stack and tree-formatted output. The reference barrier-syncs
all MPI ranks and device-syncs CUDA before each ``perf_counter``
(ref ``_sync``, ``benchmark.py:70-73``); here synchronisation is
``jax.block_until_ready`` on the values observed so far (one controller
— no barrier needed), and a ``jax.profiler`` trace can be attached for
XLA-level inspection. Disabled globally by ``BENCH_PYLOPS_MPI=0``
(ref ``benchmark.py:25``; the same kill-switch name is honoured, plus
``BENCH_PYLOPS_MPI_TPU``).

This is the reference-parity MANUAL timing decorator. For the
always-on structured tracing layer (env-gated spans wired through
every operator/collective/solver, Chrome-trace JSONL artifacts,
in-loop solver telemetry), see :mod:`pylops_mpi_tpu.diagnostics` and
``docs/observability.md``.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from contextlib import contextmanager
from typing import Callable, List, Optional

import jax

__all__ = ["benchmark", "mark", "profile_trace", "time_callable"]


def _enabled() -> bool:
    flag = os.getenv("BENCH_PYLOPS_MPI_TPU",
                     os.getenv("BENCH_PYLOPS_MPI", "1"))
    return int(flag) == 1


# Active span stack for nested @benchmark calls. Unlike the reference's
# flat (label, time, level) marker list decoded by a post-hoc stack walk
# (ref benchmark.py:27-67), regions here are first-class span objects
# built live: a decorated call opens a _Span, mark() timestamps segment
# boundaries inside the innermost open span, and nested decorated calls
# attach themselves as children. Rendering is then a trivial tree walk.
_span_stack: List["_Span"] = []


class _Span:
    """One timed region: wall-clock extent + ordered segment marks +
    nested child spans (kept in chronological order)."""

    __slots__ = ("label", "t0", "t1", "marks", "children")

    def __init__(self, label: str):
        self.label = label
        self.t0 = 0.0
        self.t1 = 0.0
        self.marks: List = []      # (label, timestamp)
        self.children: List["_Span"] = []

    @property
    def total(self) -> float:
        return self.t1 - self.t0

    def segments(self):
        """Durations between consecutive marks; the first segment runs
        from span start to the first mark, the last from the final mark
        to span end."""
        edges = [("start", self.t0)] + self.marks + [("end", self.t1)]
        for (a, ta), (b, tb) in zip(edges, edges[1:]):
            yield a, b, tb - ta

    def render(self, lines: List[str], depth: int = 0) -> List[str]:
        pad = "  " * depth
        lines.append(f"{pad}[{self.label}] total {self.total:.6f} s\n")
        if self.marks:
            for a, b, dt in self.segments():
                pct = 100.0 * dt / self.total if self.total > 0 else 0.0
                lines.append(f"{pad}  {a} => {b}: {dt:.6f} s ({pct:.1f}%)\n")
        for child in self.children:
            child.render(lines, depth + 1)
        return lines


def _sync(values=()) -> None:
    """Block until outstanding device work is done (the analog of the
    reference's Barrier + CUDA device sync)."""
    for v in values:
        try:
            jax.block_until_ready(v)
        except Exception:
            pass
    jax.effects_barrier()


def mark(label: str, *values) -> None:
    """Segment boundary inside a ``@benchmark``-ed function (ref
    ``benchmark.py:76-90``): closes the running segment and opens the
    next. Optional ``values`` are block-waited first so asynchronous
    device work is attributed to the segment that launched it."""
    if not _enabled():
        return
    if not _span_stack:
        raise RuntimeError("mark() called outside of a benchmarked region")
    _sync(values)
    _span_stack[-1].marks.append((label, time.perf_counter()))


def benchmark(func: Optional[Callable] = None, description: str = "",
              logger: Optional[logging.Logger] = None):
    """Decorator measuring start-to-end runtime with nested ``mark``
    support (ref ``benchmark.py:92-173``; output format redesigned —
    span tree with per-segment percentages instead of the reference's
    arrow chains)."""

    def noop_decorator(f):
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            return f(*args, **kwargs)
        return wrapped

    def actual_decorator(f):
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            span = _Span(description or f.__name__)
            _sync()
            if _span_stack:
                _span_stack[-1].children.append(span)
            _span_stack.append(span)
            span.t0 = time.perf_counter()
            try:
                out = f(*args, **kwargs)
                _sync((out,))
            finally:
                span.t1 = time.perf_counter()
                _span_stack.pop()
            if not _span_stack:
                text = "".join(span.render([]))
                if logger is not None:
                    logger.info("\n" + text)
                else:
                    print(text, end="")
            return out
        return wrapped

    if not _enabled():
        return noop_decorator if func is None else noop_decorator(func)
    if func is not None:
        return actual_decorator(func)
    return actual_decorator


def time_callable(fn: Callable, repeats: int = 3, warmup: int = 1):
    """Time a zero-arg callable with the module's sync discipline
    (``_sync`` on the returned value — the same barrier the
    ``@benchmark`` decorator applies): ``warmup`` unrecorded calls
    (compile/first-dispatch), then ``repeats`` timed calls. Returns
    ``{"best_s", "mean_s", "times_s", "compile_s"}`` — the timing
    primitive behind the autotuner's measurement trials
    (:mod:`pylops_mpi_tpu.tuning.search`). ``compile_s`` is the wall
    of the FIRST warmup call (compile + first dispatch; ``None`` with
    ``warmup=0``) — the split that lets the tuner report measurement
    budget spent compiling vs measuring, and that collapses toward
    the run floor when the AOT bank or the persistent compilation
    cache already holds the program."""
    compile_s = None
    for i in range(max(0, int(warmup))):
        t0 = time.perf_counter()
        _sync((fn(),))
        if i == 0:
            compile_s = time.perf_counter() - t0
    times = []
    for _ in range(max(1, int(repeats))):
        _sync()
        t0 = time.perf_counter()
        out = fn()
        _sync((out,))
        times.append(time.perf_counter() - t0)
    return {"best_s": min(times),
            "mean_s": sum(times) / len(times),
            "times_s": times,
            "compile_s": compile_s}


@contextmanager
def profile_trace(logdir: str):
    """Attach a ``jax.profiler`` trace around a region — the XLA-level
    view the reference cannot offer (TensorBoard-compatible)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

"""Benchmark / tracing utility.

Rebuild of ``pylops_mpi/utils/benchmark.py:25-173``: a ``@benchmark``
decorator plus in-function ``mark(label)`` region markers with a
nested-call stack and tree-formatted output. The reference barrier-syncs
all MPI ranks and device-syncs CUDA before each ``perf_counter``
(ref ``_sync``, ``benchmark.py:70-73``); here synchronisation is
``jax.block_until_ready`` on the values observed so far (one controller
— no barrier needed), and a ``jax.profiler`` trace can be attached for
XLA-level inspection. Disabled globally by ``BENCH_PYLOPS_MPI=0``
(ref ``benchmark.py:25``; the same kill-switch name is honoured, plus
``BENCH_PYLOPS_MPI_TPU``).
"""

from __future__ import annotations

import functools
import logging
import os
import time
from contextlib import contextmanager
from typing import Callable, List, Optional

import jax

__all__ = ["benchmark", "mark", "profile_trace"]


def _enabled() -> bool:
    flag = os.getenv("BENCH_PYLOPS_MPI_TPU",
                     os.getenv("BENCH_PYLOPS_MPI", "1"))
    return int(flag) == 1


# Stack of active mark functions (nested benchmark support,
# ref benchmark.py:27-29)
_mark_func_stack: List[Callable] = []
_markers: List = []


def _sync(values=()) -> None:
    """Block until outstanding device work is done (the analog of the
    reference's Barrier + CUDA device sync)."""
    for v in values:
        try:
            jax.block_until_ready(v)
        except Exception:
            pass
    jax.effects_barrier()


def mark(label: str, *values) -> None:
    """Region marker (ref ``benchmark.py:76-90``): ends the previous
    region and starts a new one. Optional ``values`` are block-waited to
    attribute asynchronous device work to the right region."""
    if not _enabled():
        return
    if not _mark_func_stack:
        raise RuntimeError("mark() called outside of a benchmarked region")
    _sync(values)
    _mark_func_stack[-1](label)


def _parse_output_tree(markers) -> List[str]:
    """ref ``benchmark.py:33-67``"""
    output = []
    stack: List = []
    i = 0
    while i < len(markers):
        label, t, level = markers[i]
        if label.startswith("[decorator]"):
            indent = "\t" * (level - 1)
            output.append(f"{indent}{label}: total runtime: {t:6f} s\n")
        else:
            if stack:
                prev_label, prev_time, prev_level = stack[-1]
                if prev_level == level:
                    indent = "\t" * level
                    output.append(
                        f"{indent}{prev_label}-->{label}: {t - prev_time:6f} s\n")
                    stack.pop()
            if i + 1 <= len(markers) - 1:
                _, _, next_level = markers[i + 1]
                if next_level >= level:
                    stack.append(markers[i])
        i += 1
    return output


def benchmark(func: Optional[Callable] = None, description: str = "",
              logger: Optional[logging.Logger] = None):
    """Decorator measuring start-to-end runtime with nested ``mark``
    support (ref ``benchmark.py:92-173``)."""

    def noop_decorator(f):
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            return f(*args, **kwargs)
        return wrapped

    def actual_decorator(f):
        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            global _markers
            level = len(_mark_func_stack) + 1

            def local_mark(label):
                _markers.append((label, time.perf_counter(), level))

            _mark_func_stack.append(local_mark)
            desc = description or f.__name__
            _sync()
            t0 = time.perf_counter()
            out = f(*args, **kwargs)
            _sync((out,))
            t1 = time.perf_counter()
            _mark_func_stack.pop()
            _markers.append((f"[decorator] {desc}", t1 - t0, level))
            if not _mark_func_stack:
                text = "".join(_parse_output_tree(_markers))
                _markers = []
                if logger is not None:
                    logger.info("\n" + text)
                else:
                    print(text, end="")
            return out
        return wrapped

    if not _enabled():
        return noop_decorator if func is None else noop_decorator(func)
    if func is not None:
        return actual_decorator(func)
    return actual_decorator


@contextmanager
def profile_trace(logdir: str):
    """Attach a ``jax.profiler`` trace around a region — the XLA-level
    view the reference cannot offer (TensorBoard-compatible)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

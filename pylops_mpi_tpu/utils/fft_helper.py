"""Distributed fftshift helpers.

Rebuild of ``pylops_mpi/utils/fft_helper.py:11-105``: the reference
rolls local axes locally and redistributes to roll the sharded axis;
here a shift is one ``jnp.roll`` on the logical global array — the
partitioner emits whatever permute is needed for the sharded axis.
"""

from __future__ import annotations

from typing import Sequence, Union

import numpy as np
import jax.numpy as jnp

from ..distributedarray import DistributedArray

__all__ = ["fftshift_nd", "ifftshift_nd"]


def _shift(x: DistributedArray, axes, inverse: bool) -> DistributedArray:
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    g = x.array
    g = jnp.fft.ifftshift(g, axes=axes) if inverse else \
        jnp.fft.fftshift(g, axes=axes)
    out = DistributedArray(global_shape=x.global_shape, mesh=x.mesh,
                           partition=x.partition, axis=x.axis,
                           local_shapes=x.local_shapes, mask=x.mask,
                           dtype=x.dtype)
    out[:] = g
    return out


def fftshift_nd(x: DistributedArray, axes=None) -> DistributedArray:
    axes = tuple(range(x.ndim)) if axes is None else axes
    return _shift(x, axes, inverse=False)


def ifftshift_nd(x: DistributedArray, axes=None) -> DistributedArray:
    axes = tuple(range(x.ndim)) if axes is None else axes
    return _shift(x, axes, inverse=True)

"""Operator decorators.

Rebuild of ``pylops_mpi/utils/decorators.py:9-86``. The reference's
``reshaped`` rebalances an arbitrarily-sharded flat input to the
operator's expected per-rank N-D shapes with ghost-cell transfers
computed from cumulative shard-size differences, reshapes, applies, and
re-ravels (redistributing to axis 0 first). On a mesh the rebalancing is
a logical-view repack (XLA schedules any movement), so the decorator
reduces to: flat → N-D DistributedArray sharded on axis 0 → wrapped
``_matvec`` → shard-major ravel.

Provided for users writing custom operators whose inner logic wants the
N-D layout; the built-in operators inline this.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from ..distributedarray import DistributedArray, Partition

__all__ = ["reshaped"]


def _flatten_out(y):
    """Normalize a wrapped function's return to the flat axis-0 vector
    solvers expect (ref ``decorators.py:79-81`` does this
    unconditionally)."""
    if isinstance(y, DistributedArray) and y.ndim > 1:
        return y.redistribute(0).ravel() if y.axis != 0 else y.ravel()
    return y


def reshaped(func=None, forward: Optional[bool] = None,
             stacking: bool = False):
    """Decorate an ``_matvec``/``_rmatvec`` so it receives an N-D
    DistributedArray shaped per ``self.dims``/``self.dimsd`` and its
    return value is flattened back (ref ``decorators.py:9-86``)."""

    def decorator(f):
        fwd = forward if forward is not None else \
            f.__name__.endswith("matvec") and "r" not in f.__name__[:2]

        @functools.wraps(f)
        def wrapper(self, x: DistributedArray):
            if stacking:
                # stacking operators keep the vector FLAT but rebalanced
                # to the operator's per-shard layout (local_shapes_m on
                # the forward side, local_shapes_n on the adjoint side —
                # ref decorators.py:39-52's ghost-cell rebalancing,
                # here a logical repack scheduled by XLA)
                shapes = self.local_shapes_m if fwd else \
                    self.local_shapes_n
                nd = DistributedArray(global_shape=x.global_shape,
                                      mesh=x.mesh,
                                      partition=Partition.SCATTER,
                                      axis=0, local_shapes=shapes,
                                      mask=x.mask, dtype=x.dtype)
                nd[:] = x.array
                return _flatten_out(f(self, nd))
            dims = self.dims if fwd else self.dimsd
            dims = tuple(int(d) for d in np.atleast_1d(dims))
            nd = DistributedArray(global_shape=dims, mesh=x.mesh,
                                  partition=Partition.SCATTER, axis=0,
                                  mask=x.mask, dtype=x.dtype)
            nd[:] = x.array.reshape(dims)
            return _flatten_out(f(self, nd))
        return wrapper

    if func is not None:
        return decorator(func)
    return decorator

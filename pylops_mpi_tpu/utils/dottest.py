"""Distributed adjoint (dot) test — rebuild of
``pylops_mpi/utils/dottest.py:11-107``: checks
``(Op u)ᴴ v == uᴴ (Opᴴ v)`` on gathered global arrays.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["dottest"]


def dottest(Op, u, v, nr: Optional[int] = None, nc: Optional[int] = None,
            rtol: float = 1e-6, atol: float = 1e-21,
            raiseerror: bool = True, verb: bool = False) -> bool:
    if nr is None:
        nr = Op.shape[0]
    if nc is None:
        nc = Op.shape[1]
    if (nr, nc) != Op.shape:
        raise AssertionError("Provided nr and nc do not match operator shape")

    y = Op.matvec(u)
    x = Op.rmatvec(v)

    yy = np.vdot(y.asarray(), v.asarray())
    xx = np.vdot(u.asarray(), x.asarray())

    passed = bool(np.isclose(xx, yy, rtol, atol))
    if (not passed and raiseerror) or verb:
        status = "passed" if passed else "failed"
        msg = f"Dot test {status}, v^H(Opu)={yy} - u^H(Op^Hv)={xx}"
        if not passed and raiseerror:
            raise AssertionError(msg)
        print(msg)
    return passed

"""Distributed adjoint (dot) test — rebuild of
``pylops_mpi/utils/dottest.py:11-107``: checks
``(Op u)ᴴ v == uᴴ (Opᴴ v)`` on gathered global arrays.

The MPI reference requires caller-provided ``u``/``v``; serial pylops'
``dottest`` generates them. This build follows the serial convention as
an extension: ``u``/``v`` may be omitted and random test vectors are
generated to match the operator's shape, with ``complexflag`` selecting
which side is complex (0: both real, 1: model complex, 2: data complex,
3: both complex) and ``seed`` (default 42) keeping failures
reproducible. The data-side vector is generated from a probe ``matvec``
so its layout (ragged shards, halo extents, stacked structure) always
matches; operators whose MODEL space is stacked
(e.g. ``MPIStackedBlockDiag``) still need explicit ``u``/``v``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["dottest"]


def _dtype_for(Op, cmplx):
    return np.promote_types(np.dtype(Op.dtype),
                            np.complex64 if cmplx else np.float32)


def _rand_model(Op, n, cmplx, rng):
    """Random model-side vector honouring the operator's model layout
    when it exposes one (``local_shapes_m``; MPIHalo's model side is
    ``local_dim_sizes``)."""
    from ..distributedarray import DistributedArray
    x = rng.standard_normal(n)
    if cmplx:
        x = x + 1j * rng.standard_normal(n)
    shapes = getattr(Op, "local_shapes_m",
                     getattr(Op, "local_dim_sizes", None))
    return DistributedArray.to_dist(x.astype(_dtype_for(Op, cmplx)),
                                    mesh=getattr(Op, "mesh", None),
                                    local_shapes=shapes)


def _rand_like(d, cmplx, rng, dtype):
    """Random vector with the exact structure/layout of ``d`` (plain or
    stacked) — used for the data side, whose layout is taken from a
    probe ``matvec`` so layout-sensitive operators (halo, ragged
    blockdiag, stacked outputs) get valid cotangents."""
    from ..distributedarray import DistributedArray
    from ..stacked import StackedDistributedArray
    if isinstance(d, StackedDistributedArray):
        return StackedDistributedArray(
            [_rand_like(a, cmplx, rng, dtype) for a in d.distarrays])
    from ..parallel.partition import Partition
    x = rng.standard_normal(d.global_shape)
    if cmplx:
        x = x + 1j * rng.standard_normal(d.global_shape)
    scatter = d.partition == Partition.SCATTER
    return DistributedArray.to_dist(
        x.astype(dtype), mesh=d.mesh, axis=d.axis,
        partition=d.partition, mask=d.mask,
        local_shapes=d.local_shapes if scatter else None)


def dottest(Op, u=None, v=None, nr: Optional[int] = None,
            nc: Optional[int] = None, complexflag: int = 0,
            rtol: float = 1e-6, atol: float = 1e-21,
            raiseerror: bool = True, verb: bool = False,
            seed: Optional[int] = 42) -> bool:
    if nr is None:
        nr = Op.shape[0]
    if nc is None:
        nc = Op.shape[1]
    if (nr, nc) != Op.shape:
        raise AssertionError("Provided nr and nc do not match operator shape")
    if complexflag not in (0, 1, 2, 3):
        raise ValueError(f"complexflag must be 0, 1, 2 or 3, "
                         f"got {complexflag}")

    rng = np.random.default_rng(seed)
    u_auto = u is None
    if u_auto:
        u = _rand_model(Op, nc, complexflag in (1, 3), rng)

    try:
        y = Op.matvec(u)
    except (ValueError, TypeError) as e:
        if u_auto:
            # layout/type rejection of the generated vector (stacked or
            # bespoke model space); genuine operator errors re-raise
            # below with this chained for diagnosis
            raise TypeError(
                "dottest could not auto-generate a model vector for this "
                "operator (stacked or bespoke model space) — pass u (and "
                "v) explicitly") from e
        raise
    if v is None:
        v = _rand_like(y, complexflag in (2, 3), rng,
                       _dtype_for(Op, complexflag in (2, 3)))
    x = Op.rmatvec(v)

    yy = np.vdot(y.asarray(), v.asarray())
    xx = np.vdot(u.asarray(), x.asarray())

    passed = bool(np.isclose(xx, yy, rtol, atol))
    if (not passed and raiseerror) or verb:
        status = "passed" if passed else "failed"
        msg = f"Dot test {status}, v^H(Opu)={yy} - u^H(Op^Hv)={xx}"
        if not passed and raiseerror:
            raise AssertionError(msg)
        print(msg)
    return passed

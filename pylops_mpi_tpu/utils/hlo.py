"""Collective-schedule inspection.

Observability the reference cannot offer (its comm schedule is implicit
in per-rank Python control flow; SURVEY §5 records "race detection:
none"): here every operator application lowers to ONE XLA program, so
the full collective schedule — which collectives, how many, and how many
bytes each moves — can be read off the compiled HLO before anything
runs. Use it to catch layout regressions (e.g. a stencil accidentally
lowering to a full all-gather instead of boundary ``collective-permute``
— the exact failure mode VERDICT round 1 flagged in the halo operator).

``collective_report(fn, *args)`` → dict mapping collective kind to
``{"count": n, "bytes": total}``; ``assert_no_full_gather(fn, *args,
max_fraction=...)`` raises if any single all-gather result exceeds the
given fraction of the largest argument's bytes;
``assert_complex_free(fn, *args)`` raises on any complex-dtype
instruction — the pin for the planar plane-pair FFT programs on
runtimes without complex lowering.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

import numpy as np
import jax

__all__ = ["collective_report", "assert_no_full_gather",
           "parse_hlo_collectives", "complex_dtype_lines",
           "assert_complex_free", "compiled_hlo", "count_ops",
           "assert_max_converts", "donation_report", "assert_donation",
           "count_collectives", "assert_ring_schedule",
           "host_callback_lines", "count_host_callbacks",
           "assert_no_host_callbacks", "while_body_computations",
           "count_reductions", "assert_single_reduction"]

# HLO opcode -> canonical name; bytes counted from the result shape
_COLLECTIVE_OPS = ("all-gather", "all-reduce", "all-to-all",
                   "collective-permute", "reduce-scatter",
                   "collective-broadcast")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8, "s64": 8, "u64": 8, "f64": 8,
    "c128": 16,
}

# The op may be sync ("all-gather(") or async ("all-gather-start(");
# "-done(" lines are skipped so async pairs count once. The result
# type(s) precede "=" — async starts carry a tuple whose largest member
# is the gathered buffer.
_OP_RE = re.compile(
    r"\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?\(")
_TYPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def _shape_bytes(dt: str, dims: str) -> int:
    nelem = int(np.prod([int(d) for d in dims.split(",") if d])) \
        if dims else 1
    return nelem * _DTYPE_BYTES.get(dt, 4)


def _leaf_bytes(tree) -> int:
    return max((np.dtype(l.dtype).itemsize * int(np.prod(l.shape))
                for l in jax.tree.leaves(tree) if hasattr(l, "shape")),
               default=0)


def collective_report(fn, *args, **kwargs) -> Dict[str, Dict[str, int]]:
    """Compile ``fn(*args, **kwargs)`` (jit if it is not already) and
    tally every collective in the optimized HLO: count and total result
    bytes per collective kind. Handles both sync opcodes (CPU backend)
    and the async ``-start``/``-done`` pairs TPU lowering emits."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    return parse_hlo_collectives(
        jfn.lower(*args, **kwargs).compile().as_text())


def parse_hlo_collectives(hlo: str) -> Dict[str, Dict[str, int]]:
    """Tally collectives in HLO text (exposed for direct testing against
    TPU-style async lowerings without TPU hardware). Per kind:
    ``count``, total ``bytes`` moved, and ``max_bytes`` of any single
    instruction (variadic/combined ops sum their result buffers)."""
    report: Dict[str, Dict[str, int]] = {}
    for line in hlo.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        # result type(s) sit between "=" and the opcode:
        #   %y = f32[512]{0} all-gather(...)                     (sync)
        #   %s = (f32[64], f32[512]) all-gather-start(...)       (async)
        # An async start's tuple also carries the OPERAND shapes, which
        # reappear as the call arguments — subtract those so only the
        # produced buffers are counted. Sync (possibly variadic
        # combined) ops list only results on the left.
        seg = line[:m.start()]
        if "=" in seg:
            seg = seg.split("=", 1)[1]
        lhs = [_shape_bytes(dt, dims)
               for dt, dims in _TYPE_RE.findall(seg)]
        nbytes = sum(lhs)
        if m.group(2):  # "-start"
            # all-gather/permute starts carry (operands..., results...)
            # in their tuple — subtract the operand echoes. all-reduce
            # starts carry results only (result shape == operand shape),
            # recognizable by the lhs having no extra entries.
            rhs = [_shape_bytes(dt, dims)
                   for dt, dims in _TYPE_RE.findall(line[m.end():])]
            if len(lhs) > len(rhs):
                nbytes -= sum(rhs)
        nbytes = max(nbytes, 0)
        ent = report.setdefault(m.group(1),
                                {"count": 0, "bytes": 0, "max_bytes": 0})
        ent["count"] += 1
        ent["bytes"] += nbytes
        ent["max_bytes"] = max(ent["max_bytes"], nbytes)
    return report


_COMPLEX_TYPE_RE = re.compile(r"\bc(?:64|128)\[")


def complex_dtype_lines(hlo: str) -> list:
    """Every HLO line whose instruction touches a complex dtype (a
    ``c64[...]``/``c128[...]`` shape anywhere — result or operand)."""
    return [ln for ln in hlo.splitlines() if _COMPLEX_TYPE_RE.search(ln)]


def assert_complex_free(fn, *args, **kwargs):
    """Compile ``fn(*args, **kwargs)`` and raise ``AssertionError`` if
    the optimized HLO contains ANY complex-dtype instruction —
    collectives included. This is the pin for the planar (plane-pair)
    distributed FFT programs: on TPU runtimes with no complex lowering
    at all (round-5 hardware finding) a single c64 op anywhere in the
    program, even a pure representation op, is a runtime
    ``UNIMPLEMENTED`` that wedges the client. Returns the collective
    report of the same program for further schedule checks."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    hlo = jfn.lower(*args, **kwargs).compile().as_text()
    lines = complex_dtype_lines(hlo)
    if lines:
        head = "\n".join(ln.strip()[:160] for ln in lines[:8])
        raise AssertionError(
            f"program contains {len(lines)} complex-dtype instruction "
            f"line(s); first few:\n{head}")
    return parse_hlo_collectives(hlo)


def compiled_hlo(fn, *args, **kwargs) -> str:
    """Optimized HLO text of ``fn(*args, **kwargs)`` (jit-wrapping if
    needed) — the shared entry for every pin below."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    return jfn.lower(*args, **kwargs).compile().as_text()


def count_ops(hlo: str, opcode: str, shape_re: Optional[str] = None,
              computation_re: Optional[str] = None) -> int:
    """Count instructions of ``opcode`` in HLO text.

    ``shape_re`` restricts to instructions whose RESULT shape string
    (e.g. ``f32[8,512,512]``) matches the regex — the handle for
    per-A-tile pins ("how many converts touch a block-stack-shaped
    buffer?"). ``computation_re`` restricts to instructions inside
    computations whose name matches (e.g. ``r"body"`` for the
    ``while``-loop body region, so per-iteration counts don't include
    setup converts). Counting is text-level on the optimized HLO, the
    same layer the collective pins use."""
    op_re = re.compile(r"\b" + re.escape(opcode) + r"(?:\.\d+)?\(")
    shape_pat = re.compile(shape_re) if shape_re else None
    comp_pat = re.compile(computation_re) if computation_re else None
    # computation headers: "%region_1.42 (p: f32[...]) -> ... {",
    # "ENTRY %main.33 (...) -> ... {"
    header_re = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")
    n = 0
    in_scope = comp_pat is None
    for line in hlo.splitlines():
        ls = line.strip()
        hm = header_re.match(ls)
        if hm is not None:
            in_scope = comp_pat is None or bool(comp_pat.search(hm.group(1)))
            continue
        if not in_scope or "=" not in ls:
            continue
        # "%convert.51 = bf16[512]{0} convert(f32[512]{0} %p), ..." —
        # the opcode is the first call-form token after the result type
        rhs = ls.split("=", 1)[1]
        m = op_re.search(rhs)
        if m is None or (m.start() > 0 and rhs[m.start() - 1] == "%"):
            continue
        if shape_pat is not None and not shape_pat.search(rhs[:m.start()]):
            continue
        n += 1
    return n


def assert_max_converts(fn, *args, max_converts: int = 0,
                        shape_re: Optional[str] = None,
                        computation_re: Optional[str] = None, **kwargs):
    """Compile and raise ``AssertionError`` if the program holds more
    than ``max_converts`` dtype-convert instructions (optionally
    restricted by result shape / computation, see :func:`count_ops`).
    This is the mixed-precision pin: a bf16-storage fused solver may
    widen each A tile at the GEMM operand (≤2 per iteration — matvec +
    rmatvec) but must not convert per-element wide copies of anything
    else. Returns the count."""
    hlo = compiled_hlo(fn, *args, **kwargs)
    n = count_ops(hlo, "convert", shape_re=shape_re,
                  computation_re=computation_re)
    if n > max_converts:
        lines = [ln.strip()[:160] for ln in hlo.splitlines()
                 if " convert(" in ln or re.search(r"convert\.\d+\(", ln)]
        head = "\n".join(lines[:8])
        raise AssertionError(
            f"program contains {n} convert op(s) (> {max_converts})"
            + (f" matching shape {shape_re!r}" if shape_re else "")
            + f"; first few:\n{head}")
    return n


_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9, ]*)\}:\s*\((\d+)\s*,\s*\{([0-9, ]*)\}")


def _alias_blob(hlo: str) -> str:
    """The brace-balanced ``input_output_alias={...}`` attribute value
    from the module header (empty string when absent)."""
    start = hlo.find("input_output_alias={")
    if start < 0:
        return ""
    i = hlo.index("{", start)
    depth = 0
    for j in range(i, min(len(hlo), i + 20000)):
        if hlo[j] == "{":
            depth += 1
        elif hlo[j] == "}":
            depth -= 1
            if depth == 0:
                return hlo[i + 1:j]
    return ""


def donation_report(fn, *args, **kwargs) -> Dict:
    """Compile and report buffer donation: which entry parameters are
    aliased to outputs (``input_output_alias`` on the HLO module —
    donation's footprint in the compiled program), and how many
    ``copy`` instructions read a donated parameter (the copies the
    donation was supposed to eliminate). Keys: ``aliased_params``
    (sorted param numbers), ``donated_param_copies``."""
    hlo = compiled_hlo(fn, *args, **kwargs)
    return parse_donation(hlo)


def parse_donation(hlo: str) -> Dict:
    """Text-level donation report (exposed for direct testing)."""
    params = set()
    for mm in _ALIAS_ENTRY_RE.finditer(_alias_blob(hlo)):
        params.add(int(mm.group(2)))
    # copies consuming a donated parameter: the donated Arg should be
    # written in place, not defensively copied
    n_copies = 0
    if params:
        arg_names = "|".join(rf"Arg_{p}\." for p in sorted(params))
        pat = re.compile(r"\bcopy(?:\.\d+)?\([^)]*%(?:" + arg_names + r")")
        for line in hlo.splitlines():
            if pat.search(line):
                n_copies += 1
    return {"aliased_params": sorted(params),
            "donated_param_copies": n_copies}


def assert_donation(fn, *args, min_aliased: int = 1, **kwargs) -> Dict:
    """Compile and raise ``AssertionError`` unless at least
    ``min_aliased`` entry parameters are donation-aliased to outputs
    AND no ``copy`` instruction reads a donated parameter — the
    zero-copy while_loop-state pin for the fused solvers (a donated
    ``x0`` must become the loop carry in place). Returns the report."""
    rep = donation_report(fn, *args, **kwargs)
    if len(rep["aliased_params"]) < min_aliased:
        raise AssertionError(
            f"expected >= {min_aliased} donation-aliased parameters, "
            f"found {rep['aliased_params']} — was the entry compiled "
            "without donate_argnums (PYLOPS_MPI_TPU_DONATE=0?)")
    if rep["donated_param_copies"]:
        raise AssertionError(
            f"{rep['donated_param_copies']} copy op(s) read a donated "
            "parameter: the donated buffer is being defensively copied "
            "instead of aliased in place")
    return rep


def count_collectives(fn, *args, kind: Optional[str] = None, **kwargs):
    """Compile ``fn(*args, **kwargs)`` and return the per-kind
    collective instruction counts (``{"all-to-all": 2, ...}``), or a
    single int when ``kind`` is given (0 when absent). The counting
    handle for the pipelined-schedule pins: chunked pencil transpose =
    K all-to-alls per transpose, bulk paths' op counts unchanged."""
    rep = collective_report(fn, *args, **kwargs)
    counts = {k: v["count"] for k, v in rep.items()}
    if kind is not None:
        return counts.get(kind, 0)
    return counts


_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=")
_USE_RE = re.compile(r"%([\w.\-]+)")


def _defuse_graph(hlo: str):
    """``result name -> operand names`` over the whole module (text
    level; computation calls appear as ``calls=%name`` operands, which
    conservatively widens reachability — fine for chain checks)."""
    graph = {}
    for line in hlo.splitlines():
        m = _DEF_RE.match(line)
        if m is None:
            continue
        rhs = line.split("=", 1)[1]
        graph[m.group(1)] = [u for u in _USE_RE.findall(rhs)]
    return graph


def _op_results(hlo: str, opcode: str) -> list:
    """Result names of every ``opcode`` (or async ``opcode-start``)
    instruction, in text order."""
    pat = re.compile(r"\b" + re.escape(opcode) + r"(-start)?(?:\.\d+)?\(")
    out = []
    for line in hlo.splitlines():
        m = _DEF_RE.match(line)
        if m is None or "=" not in line:
            continue
        rhs = line.split("=", 1)[1]
        pm = pat.search(rhs)
        if pm is not None and not (pm.start() > 0
                                   and rhs[pm.start() - 1] == "%"):
            out.append(m.group(1))
    return out


def assert_ring_schedule(fn, *args, steps: int, dots: Optional[int] = None,
                         check_chain: bool = True, **kwargs):
    """Compile and assert the program lowered as a double-buffered ring
    (``parallel.collectives.ring_pass``):

    - exactly ``steps`` collective-permutes (sync or async ``-start``),
      i.e. P-1 hops — a bulk all-gather-then-GEMM shows 0 permutes and
      is the regression this pin exists to catch;
    - when ``dots`` is given, at least that many ``dot`` instructions
      (one local GEMM per ring step);
    - when ``check_chain``, the permutes form a DEPENDENCY CHAIN (hop
      ``s+1`` transitively consumes hop ``s``'s result) — the
      pipelined-ring signature, as opposed to ``steps`` independent
      one-shot permutes all issued against the same buffer. Checked on
      the def-use graph, not instruction print order, which the CPU
      backend shuffles.

    Returns ``(n_permutes, n_dots)``."""
    hlo = compiled_hlo(fn, *args, **kwargs)
    perms = _op_results(hlo, "collective-permute")
    n_dots = len(_op_results(hlo, "dot"))
    if len(perms) != steps:
        raise AssertionError(
            f"expected a ring of exactly {steps} collective-permute "
            f"step(s), found {len(perms)} — the schedule did not lower "
            "as a ring (bulk gather, or a fused/eliminated chain)")
    if dots is not None and n_dots < dots:
        raise AssertionError(
            f"expected >= {dots} dot op(s) (one local GEMM per ring "
            f"step), found {n_dots}")
    if check_chain and steps >= 2:
        graph = _defuse_graph(hlo)
        pset = set(perms)

        def upstream_perms(name, seen=None):
            seen = set() if seen is None else seen
            hits = set()
            stack = list(graph.get(name, ()))
            while stack:
                u = stack.pop()
                if u in seen:
                    continue
                seen.add(u)
                if u in pset:
                    hits.add(u)
                stack.extend(graph.get(u, ()))
            return hits

        depths = sorted(len(upstream_perms(p)) for p in perms)
        if depths != list(range(steps)):
            raise AssertionError(
                f"collective-permutes do not form a dependency chain "
                f"(upstream-permute counts {depths}, expected "
                f"{list(range(steps))}): the hops were issued in "
                "parallel, not pipelined as a ring")
    return len(perms), n_dots


_CALLBACK_RE = re.compile(
    r'custom[-_]call[^\n]*custom_call_target="[^"]*callback[^"]*"',
    re.IGNORECASE)


def host_callback_lines(hlo: str) -> list:
    """Every HLO line whose instruction is a host-callback custom-call
    (``xla_python_cpu_callback`` / ``xla_ffi_python_cpu_callback`` /
    GPU variants — anything whose ``custom_call_target`` mentions
    ``callback``): the compiled footprint of ``jax.debug.callback`` /
    ``io_callback`` / ``pure_callback``."""
    return [ln for ln in hlo.splitlines() if _CALLBACK_RE.search(ln)]


def count_host_callbacks(fn, *args, **kwargs) -> int:
    """Compile ``fn(*args, **kwargs)`` and count host-callback
    custom-calls in the optimized HLO."""
    return len(host_callback_lines(compiled_hlo(fn, *args, **kwargs)))


def assert_no_host_callbacks(fn, *args, **kwargs) -> str:
    """Compile and raise ``AssertionError`` if the program contains ANY
    host-callback custom-call — the telemetry-off pin for the fused
    solver loops (``diagnostics/telemetry.py``): with
    ``PYLOPS_MPI_TPU_TRACE≠full`` the donated/fused hot path must
    compile to a program with zero host round-trips, bit-identical to
    the pre-diagnostics build. Returns the HLO text for further
    checks."""
    hlo = compiled_hlo(fn, *args, **kwargs)
    lines = host_callback_lines(hlo)
    if lines:
        head = "\n".join(ln.strip()[:160] for ln in lines[:8])
        raise AssertionError(
            f"program contains {len(lines)} host-callback custom-call "
            f"line(s) — telemetry/debug callbacks leaked into a build "
            f"that should be callback-free; first few:\n{head}")
    return hlo


def assert_no_full_gather(fn, *args, max_fraction: float = 0.5, **kwargs):
    """Raise ``AssertionError`` if the compiled program contains an
    all-gather whose result is larger than ``max_fraction`` of the
    largest input's bytes — the signature of a sharded operand being
    silently replicated. Returns the report for further checks."""
    report = collective_report(fn, *args, **kwargs)
    in_bytes = _leaf_bytes((args, kwargs))
    if in_bytes == 0:
        raise ValueError(
            "assert_no_full_gather could not size the inputs — pass the "
            "sharded arrays as arguments (positional or keyword), not "
            "closed-over values")
    limit = max_fraction * in_bytes
    ag = report.get("all-gather")
    if ag and ag["max_bytes"] > limit:
        raise AssertionError(
            f"program contains an all-gather producing {ag['max_bytes']} "
            f"bytes (> {max_fraction:.0%} of the {in_bytes}-byte "
            f"largest input): a sharded operand is being replicated")
    return report


# ---------------------------------------------------------------------------
# reduction counting — the communication-avoiding solver pins
# ---------------------------------------------------------------------------
#
# The CA tier's whole contract is "exactly one all-reduce per solver
# iteration" (solvers/ca.py). count_ops() cannot express that pin: the
# reductions live inside the while-loop BODY computation, whose
# XLA-assigned name carries no reliable substring, so the counter below
# finds the body computations structurally — parse ``body=%name`` off
# every ``while(`` instruction, then close transitively over every
# computation those bodies call (fusions, to_apply reducers, nested
# whiles, conditional branches).

_HEADER_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")
_CALLEE_RE = re.compile(
    r"\b(?:calls|to_apply|body|condition|branch_computations|"
    r"called_computations)=\{?%?([\w.\-]+(?:\}?,\s*%?[\w.\-]+)*)")
_WHILE_BODY_RE = re.compile(r"\bwhile\((?:[^)]|\n)*?\)[^\n]*?body=%?([\w.\-]+)")


def _computations(hlo: str) -> Dict[str, list]:
    """``computation name -> its instruction lines`` (text level)."""
    comps: Dict[str, list] = {}
    cur = None
    for line in hlo.splitlines():
        hm = _HEADER_RE.match(line.strip())
        if hm is not None:
            cur = hm.group(1)
            comps[cur] = []
            continue
        if cur is not None:
            comps[cur].append(line)
    return comps


def _callees(lines: list) -> set:
    """Names of every computation referenced by the given instruction
    lines (``body=``/``condition=`` of nested whiles, ``to_apply=`` of
    reduces, ``calls=`` of fusions, conditional branch lists)."""
    out = set()
    for line in lines:
        for m in _CALLEE_RE.finditer(line):
            for name in m.group(1).split(","):
                out.add(name.strip().lstrip("%").rstrip("}"))
    return out


def while_body_computations(hlo: str) -> set:
    """Names of every while-loop body computation in the module plus
    everything those bodies transitively call. This is the scope the
    per-iteration reduction pins count over — setup reductions (the
    ``kold0`` dot outside the loop) must not leak into a
    per-iteration count."""
    comps = _computations(hlo)
    roots = set()
    for lines in comps.values():
        for line in lines:
            m = _WHILE_BODY_RE.search(line)
            if m is not None:
                roots.add(m.group(1))
    # transitive closure over called computations
    seen = set()
    stack = list(roots)
    while stack:
        name = stack.pop()
        if name in seen or name not in comps:
            continue
        seen.add(name)
        stack.extend(_callees(comps[name]))
    return seen


_REDUCE_RE = re.compile(r"\ball-reduce(-start)?(?:\.\d+)?\(")


def _count_reduce_lines(lines) -> int:
    n = 0
    for line in lines:
        if "=" not in line:
            continue
        rhs = line.split("=", 1)[1]
        m = _REDUCE_RE.search(rhs)
        if m is not None and not (m.start() > 0
                                  and rhs[m.start() - 1] == "%"):
            n += 1
    return n


def count_reductions(hlo: str, scope: str = "body") -> int:
    """Count ``all-reduce`` instructions in HLO text.

    Counts sync ``all-reduce(`` and async ``all-reduce-start(`` once
    each (``-done`` halves are skipped by construction). ``scope``:

    - ``"body"`` (default): only instructions inside while-loop body
      computations (transitively, via :func:`while_body_computations`)
      — the per-iteration count the CA pins assert on;
    - ``"all"``: the whole module, setup reductions included.
    """
    if scope == "all":
        return _count_reduce_lines(hlo.splitlines())
    if scope != "body":
        raise ValueError(f"scope must be 'body' or 'all', got {scope!r}")
    comps = _computations(hlo)
    bodies = while_body_computations(hlo)
    return sum(_count_reduce_lines(comps[name])
               for name in bodies if name in comps)


def assert_single_reduction(fn, *args, scope: str = "body",
                            **kwargs) -> str:
    """Compile ``fn(*args, **kwargs)`` and raise ``AssertionError``
    unless the optimized HLO carries EXACTLY ONE all-reduce in
    ``scope`` — the pipelined-solver pin: every per-iteration dot
    product must have been merged into the single stacked reduction
    (solvers/ca.py), because each extra all-reduce is one more
    latency floor on the critical path. Returns the HLO text for
    further checks."""
    hlo = compiled_hlo(fn, *args, **kwargs)
    n = count_reductions(hlo, scope=scope)
    if n != 1:
        lines = [ln.strip()[:160] for ln in hlo.splitlines()
                 if _REDUCE_RE.search(ln)]
        head = "\n".join(lines[:8])
        raise AssertionError(
            f"expected exactly 1 all-reduce in scope {scope!r}, found "
            f"{n} — the stacked-reduction merge did not hold; "
            f"all-reduce lines:\n{head}")
    return hlo

"""Collective-schedule inspection.

Observability the reference cannot offer (its comm schedule is implicit
in per-rank Python control flow; SURVEY §5 records "race detection:
none"): here every operator application lowers to ONE XLA program, so
the full collective schedule — which collectives, how many, and how many
bytes each moves — can be read off the compiled HLO before anything
runs. Use it to catch layout regressions (e.g. a stencil accidentally
lowering to a full all-gather instead of boundary ``collective-permute``
— the exact failure mode VERDICT round 1 flagged in the halo operator).

``collective_report(fn, *args)`` → dict mapping collective kind to
``{"count": n, "bytes": total}``; ``assert_no_full_gather(fn, *args,
max_fraction=...)`` raises if any single all-gather result exceeds the
given fraction of the largest argument's bytes;
``assert_complex_free(fn, *args)`` raises on any complex-dtype
instruction — the pin for the planar plane-pair FFT programs on
runtimes without complex lowering.
"""

from __future__ import annotations

import re
from typing import Dict

import numpy as np
import jax

__all__ = ["collective_report", "assert_no_full_gather",
           "parse_hlo_collectives", "complex_dtype_lines",
           "assert_complex_free"]

# HLO opcode -> canonical name; bytes counted from the result shape
_COLLECTIVE_OPS = ("all-gather", "all-reduce", "all-to-all",
                   "collective-permute", "reduce-scatter",
                   "collective-broadcast")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8, "s64": 8, "u64": 8, "f64": 8,
    "c128": 16,
}

# The op may be sync ("all-gather(") or async ("all-gather-start(");
# "-done(" lines are skipped so async pairs count once. The result
# type(s) precede "=" — async starts carry a tuple whose largest member
# is the gathered buffer.
_OP_RE = re.compile(
    r"\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start)?\(")
_TYPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def _shape_bytes(dt: str, dims: str) -> int:
    nelem = int(np.prod([int(d) for d in dims.split(",") if d])) \
        if dims else 1
    return nelem * _DTYPE_BYTES.get(dt, 4)


def _leaf_bytes(tree) -> int:
    return max((np.dtype(l.dtype).itemsize * int(np.prod(l.shape))
                for l in jax.tree.leaves(tree) if hasattr(l, "shape")),
               default=0)


def collective_report(fn, *args, **kwargs) -> Dict[str, Dict[str, int]]:
    """Compile ``fn(*args, **kwargs)`` (jit if it is not already) and
    tally every collective in the optimized HLO: count and total result
    bytes per collective kind. Handles both sync opcodes (CPU backend)
    and the async ``-start``/``-done`` pairs TPU lowering emits."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    return parse_hlo_collectives(
        jfn.lower(*args, **kwargs).compile().as_text())


def parse_hlo_collectives(hlo: str) -> Dict[str, Dict[str, int]]:
    """Tally collectives in HLO text (exposed for direct testing against
    TPU-style async lowerings without TPU hardware). Per kind:
    ``count``, total ``bytes`` moved, and ``max_bytes`` of any single
    instruction (variadic/combined ops sum their result buffers)."""
    report: Dict[str, Dict[str, int]] = {}
    for line in hlo.splitlines():
        m = _OP_RE.search(line)
        if m is None:
            continue
        # result type(s) sit between "=" and the opcode:
        #   %y = f32[512]{0} all-gather(...)                     (sync)
        #   %s = (f32[64], f32[512]) all-gather-start(...)       (async)
        # An async start's tuple also carries the OPERAND shapes, which
        # reappear as the call arguments — subtract those so only the
        # produced buffers are counted. Sync (possibly variadic
        # combined) ops list only results on the left.
        seg = line[:m.start()]
        if "=" in seg:
            seg = seg.split("=", 1)[1]
        lhs = [_shape_bytes(dt, dims)
               for dt, dims in _TYPE_RE.findall(seg)]
        nbytes = sum(lhs)
        if m.group(2):  # "-start"
            # all-gather/permute starts carry (operands..., results...)
            # in their tuple — subtract the operand echoes. all-reduce
            # starts carry results only (result shape == operand shape),
            # recognizable by the lhs having no extra entries.
            rhs = [_shape_bytes(dt, dims)
                   for dt, dims in _TYPE_RE.findall(line[m.end():])]
            if len(lhs) > len(rhs):
                nbytes -= sum(rhs)
        nbytes = max(nbytes, 0)
        ent = report.setdefault(m.group(1),
                                {"count": 0, "bytes": 0, "max_bytes": 0})
        ent["count"] += 1
        ent["bytes"] += nbytes
        ent["max_bytes"] = max(ent["max_bytes"], nbytes)
    return report


_COMPLEX_TYPE_RE = re.compile(r"\bc(?:64|128)\[")


def complex_dtype_lines(hlo: str) -> list:
    """Every HLO line whose instruction touches a complex dtype (a
    ``c64[...]``/``c128[...]`` shape anywhere — result or operand)."""
    return [ln for ln in hlo.splitlines() if _COMPLEX_TYPE_RE.search(ln)]


def assert_complex_free(fn, *args, **kwargs):
    """Compile ``fn(*args, **kwargs)`` and raise ``AssertionError`` if
    the optimized HLO contains ANY complex-dtype instruction —
    collectives included. This is the pin for the planar (plane-pair)
    distributed FFT programs: on TPU runtimes with no complex lowering
    at all (round-5 hardware finding) a single c64 op anywhere in the
    program, even a pure representation op, is a runtime
    ``UNIMPLEMENTED`` that wedges the client. Returns the collective
    report of the same program for further schedule checks."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    hlo = jfn.lower(*args, **kwargs).compile().as_text()
    lines = complex_dtype_lines(hlo)
    if lines:
        head = "\n".join(ln.strip()[:160] for ln in lines[:8])
        raise AssertionError(
            f"program contains {len(lines)} complex-dtype instruction "
            f"line(s); first few:\n{head}")
    return parse_hlo_collectives(hlo)


def assert_no_full_gather(fn, *args, max_fraction: float = 0.5, **kwargs):
    """Raise ``AssertionError`` if the compiled program contains an
    all-gather whose result is larger than ``max_fraction`` of the
    largest input's bytes — the signature of a sharded operand being
    silently replicated. Returns the report for further checks."""
    report = collective_report(fn, *args, **kwargs)
    in_bytes = _leaf_bytes((args, kwargs))
    if in_bytes == 0:
        raise ValueError(
            "assert_no_full_gather could not size the inputs — pass the "
            "sharded arrays as arguments (positional or keyword), not "
            "closed-over values")
    limit = max_fraction * in_bytes
    ag = report.get("all-gather")
    if ag and ag["max_bytes"] > limit:
        raise AssertionError(
            f"program contains an all-gather producing {ag['max_bytes']} "
            f"bytes (> {max_fraction:.0%} of the {in_bytes}-byte "
            f"largest input): a sharded operand is being replicated")
    return report

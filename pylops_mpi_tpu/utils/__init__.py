from .dottest import dottest
from .fft_helper import fftshift_nd, ifftshift_nd
from .benchmark import benchmark, mark, profile_trace
from .checkpoint import (save_solver, load_solver, save_pytree,
                         load_pytree, save_fused_carry, load_fused_carry)
from .hlo import collective_report, assert_no_full_gather

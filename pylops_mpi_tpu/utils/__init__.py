from .dottest import dottest

"""Adjoint-based VJP/JVP rules for ``MPILinearOperator`` applies.

JAX can already trace straight through every operator's ``matvec``
(DistributedArray is a pytree; shard_map collectives are transposable),
but doing so makes reverse mode re-derive the adjoint by transposing
the forward collective schedule — a program nobody tuned. A linear
operator does not need any of that: the cotangent of ``y = A x``
w.r.t. ``x`` is (in JAX's transpose convention) ``Aᵀ v``, which the
operator already implements as ``rmatvec`` (modulo conjugation for
complex dtypes). These rules substitute the hand-written adjoint —
the SAME code path the solvers run, with its overlap/tuning/
hierarchical schedules — for the machine-derived transpose.

Parameter cotangents (the ``∂⟨v, A(θ)x⟩/∂θ`` term for MatrixMult
weights, sparse COO vals, precond diagonals, the ``eps`` of a scaled
regularizer, …) flow through the existing
``register_operator_arrays`` pytree registration: the operator travels
through the rule as a differentiable pytree argument and its leaf
cotangents are produced by one ``jax.vjp`` of the apply with the
VECTOR held fixed — linear in the parameters, so this traces the
apply once, never the solver.

``mode="vjp"`` (default) installs ``jax.custom_vjp`` — reverse mode
only (forward-mode through a custom_vjp function is a JAX error).
``mode="jvp"`` installs ``jax.custom_jvp`` for forward-mode work
(tangent of ``A x`` is ``A dx`` — one more apply).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..linearoperator import (MPILinearOperator, operator_is_jit_arg,
                              register_operator_arrays)

__all__ = ["DifferentiableOperator", "make_differentiable",
           "transpose_apply", "param_cotangent"]


# ------------------------------------------------------------ helpers
def _is_complex(v) -> bool:
    return np.issubdtype(np.dtype(v.dtype), np.complexfloating)


def transpose_apply(Op, v, direction: str = "matvec"):
    """JAX-transpose of one operator apply: the cotangent of
    ``y = Op.matvec(x)`` w.r.t. ``x`` is ``Opᵀ v`` (NOT ``Opᴴ v`` —
    JAX cotangents are unconjugated; ``grad`` conjugates at the end),
    i.e. ``conj(rmatvec(conj(v)))``, which for real dtypes is exactly
    ``rmatvec(v)`` — zero extra ops. ``direction="rmatvec"``
    transposes the adjoint apply: ``(Opᴴ)ᵀ v = conj(matvec(conj(v)))``.
    """
    if direction == "matvec":
        if _is_complex(v):
            return Op.rmatvec(v.conj()).conj()
        return Op.rmatvec(v)
    if _is_complex(v):
        return Op.matvec(v.conj()).conj()
    return Op.matvec(v)


def param_cotangent(Op, x, v, direction: str = "matvec"):
    """Operator-parameter cotangent of one apply: the pullback of
    ``θ ↦ A(θ) x`` (``x`` fixed) evaluated at ``v``, as a pytree
    shaped like ``Op`` (integer leaves — sparse rows/cols — get the
    conventional ``float0`` zeros). This is the only place the rules
    trace through an apply, and only the parameter direction."""
    if direction == "matvec":
        _, pull = jax.vjp(lambda o: o.matvec(x), Op)
    else:
        _, pull = jax.vjp(lambda o: o.rmatvec(x), Op)
    return pull(v)[0]


def zero_op_cotangent(Op):
    """An all-zeros cotangent pytree for ``Op`` (``params=False``
    rules): ``float0`` for integer leaves, ``zeros_like`` otherwise."""
    leaves, treedef = jax.tree_util.tree_flatten(Op)
    zeros = []
    for leaf in leaves:
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.inexact):
            zeros.append(jnp.zeros_like(arr))
        else:
            zeros.append(np.zeros(np.shape(arr), dtype=jax.dtypes.float0))
    return jax.tree_util.tree_unflatten(treedef, zeros)


# --------------------------------------- leaves-as-argument rules
# The differentiable argument is the operator's LEAF LIST, not the
# operator object: ``register_operator_arrays`` keeps the instance as
# pytree aux with identity equality, so an operator-shaped cotangent
# (whose aux is the unflattened copy) could never match the primal
# treedef at custom_vjp's structure check. A plain list of arrays has
# no aux — its cotangent (the same-order leaf list) always validates —
# and unflattening with the closed-over treedef inside the rule is
# exactly the shallow-copy-and-swap that jit argument passing does.
def _zero_leaf(leaf):
    arr = np.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf
    if np.issubdtype(np.dtype(arr.dtype), np.inexact):
        return jnp.zeros_like(arr)
    return np.zeros(np.shape(arr), dtype=jax.dtypes.float0)


def _make_vjp_rule(direction: str, params: bool, treedef):
    unflatten = jax.tree_util.tree_unflatten

    def _apply(leaves, x):
        op = unflatten(treedef, leaves)
        return (op.matvec(x) if direction == "matvec"
                else op.rmatvec(x))

    rule = jax.custom_vjp(_apply)

    def fwd(leaves, x):
        return _apply(leaves, x), (leaves, x)

    def bwd(res, v):
        leaves, x = res
        op = unflatten(treedef, leaves)
        gx = transpose_apply(op, v, direction)
        if params:
            gop = param_cotangent(op, x, v, direction)
            gl = list(jax.tree_util.tree_leaves(gop))
        else:
            gl = [_zero_leaf(l) for l in leaves]
        return gl, gx

    rule.defvjp(fwd, bwd)
    return rule


def _make_jvp_rule(direction: str, params: bool, treedef):
    unflatten = jax.tree_util.tree_unflatten

    def _apply(leaves, x):
        op = unflatten(treedef, leaves)
        return (op.matvec(x) if direction == "matvec"
                else op.rmatvec(x))

    rule = jax.custom_jvp(_apply)

    @rule.defjvp
    def _jvp(primals, tangents):
        leaves, x = primals
        dleaves, dx = tangents
        y = _apply(leaves, x)
        dy = _apply(leaves, dx)      # linearity in x: one more apply
        if params:
            dy = dy + jax.jvp(lambda lv: _apply(lv, x),
                              (list(leaves),), (list(dleaves),))[1]
        return y, dy

    return rule


# ------------------------------------------------- closure-form rules
# For operators whose pytree leaves are NOT all jax types (compositions
# over unregistered user classes): the operator cannot travel through
# the rule as a differentiable argument, so it closes over — only the
# vector gets a cotangent. Rules are built per call at trace time
# (cheap: a custom_vjp object, no compile).
def _closure_vjp(Op, direction: str):
    def _apply(x):
        return (Op.matvec(x) if direction == "matvec"
                else Op.rmatvec(x))

    rule = jax.custom_vjp(_apply)
    rule.defvjp(lambda x: (_apply(x), None),
                lambda _, v: (transpose_apply(Op, v, direction),))
    return rule


def _closure_jvp(Op, direction: str):
    def _apply(x):
        return (Op.matvec(x) if direction == "matvec"
                else Op.rmatvec(x))

    rule = jax.custom_jvp(_apply)
    rule.defjvp(lambda p, t: (_apply(p[0]), _apply(t[0])))
    return rule


class DifferentiableOperator(MPILinearOperator):
    """Wrapper installing the adjoint AD rules on an operator's
    applies. Linear-operator semantics are unchanged — same shape,
    dtype, block routing — but under ``jax.grad``/``jax.vjp``
    (``mode="vjp"``) or ``jax.jvp`` (``mode="jvp"``) the apply
    differentiates by the hand-written adjoint instead of a traced
    transpose.

    ``params=True`` (default where possible) also produces cotangents/
    tangents for the operator's OWN pytree leaves — requires the
    wrapped operator to be jit-argument clean
    (:func:`~pylops_mpi_tpu.linearoperator.operator_is_jit_arg`);
    ``params=None`` auto-resolves to that predicate. Compositions over
    unregistered classes fall back to vector-only rules (closure form).
    """

    accepts_block = True

    def __init__(self, A: MPILinearOperator, mode: str = "vjp",
                 params=None):
        if isinstance(A, DifferentiableOperator):   # idempotent
            A = A.args[0]
        if mode not in ("vjp", "jvp"):
            raise ValueError(f"mode={mode!r}: expected 'vjp' or 'jvp'")
        as_arg = operator_is_jit_arg(A)
        if params is None:
            params = as_arg
        elif params and not as_arg:
            raise ValueError(
                "params=True needs a pytree-registered operator whose "
                "leaves are all arrays/scalars (register_operator_arrays"
                "); got " + type(A).__name__)
        self._mode = mode
        self._params = bool(params)
        self._as_arg = as_arg
        self.dims, self.dimsd = A.dims, A.dimsd
        super().__init__(shape=A.shape, dtype=A.dtype)
        mesh = getattr(A, "mesh", None)
        if mesh is not None:
            self.mesh = mesh
        self.args = (A,)

    @property
    def A(self):
        # via args so pytree unflattening (which swaps args) keeps the
        # rules reading the traced sub-operator, not a stale copy
        return self.args[0]

    def _rule(self, direction: str):
        A = self.args[0]
        if self._as_arg:
            leaves, treedef = jax.tree_util.tree_flatten(A)
            fn = (_make_vjp_rule if self._mode == "vjp"
                  else _make_jvp_rule)(direction, self._params, treedef)
            return lambda x: fn(leaves, x)
        if self._mode == "vjp":
            return _closure_vjp(A, direction)
        return _closure_jvp(A, direction)

    def _matvec(self, x):
        return self._rule("matvec")(x)

    def _rmatvec(self, x):
        return self._rule("rmatvec")(x)

    def _adjoint(self):
        return DifferentiableOperator(self.args[0].H, mode=self._mode,
                                      params=self._params)

    def aot_signature(self):
        from ..aot.signature import op_signature
        return ("diff", self._mode, self._params,
                op_signature(self.args[0]))


def make_differentiable(Op: MPILinearOperator, mode: str = "vjp",
                        params=None) -> DifferentiableOperator:
    """Wrap ``Op`` with adjoint AD rules — see
    :class:`DifferentiableOperator`."""
    return DifferentiableOperator(Op, mode=mode, params=params)


register_operator_arrays(DifferentiableOperator, "args")

"""Differentiable operator layer (ROADMAP item 5).

The reference library is solve-only; this tier makes the whole stack
end-to-end differentiable without ever asking JAX to transpose a
shard_map collective or unroll a ``lax.while_loop`` tape:

- :mod:`rules` — adjoint-based ``jax.custom_vjp``/``custom_jvp`` rules
  for operator applies: the VJP of ``A @ x`` w.r.t. ``x`` is ``Aᴴ @ v``,
  which every ``MPILinearOperator`` already carries as ``rmatvec``.
  Parameter cotangents (MatrixMult weights, sparse COO vals, precond
  diagonals) flow through the existing pytree registration.
- :mod:`implicit` — implicit differentiation through the fused
  CG/CGLS fixed points (and their block ``(N, K)`` carries): the
  backward pass is ONE more solve with the same operator family,
  reusing the ``_get_fused`` executables, tuned plans, CA mode, the
  ``M=`` preconditioner seam and the AOT bank.
- :mod:`unrolled` — reverse-differentiable fixed-iteration (scan-tape)
  CG/CGLS oracles, used by the tests and the bench gradient race as
  the "what everyone else does" baseline.
- :mod:`fit` — a minimal ``value_and_grad`` training driver
  (grad-of-``batched_solve`` over an operator family = minibatch
  training of a learned regularizer).

``PYLOPS_MPI_TPU_AUTODIFF=on`` additionally lets the CLASSIC entries
(``cg``/``cgls``/``block_cg``/``block_cgls``) accept traced inputs and
route here; the explicit API below works with the knob off too, and
off-mode lowers bit-identical solver programs (tests/test_autodiff.py).
See docs/autodiff.md for rule semantics and the guard exclusion.
"""

from .rules import (DifferentiableOperator, make_differentiable)
from .implicit import (cg_solve, cgls_solve, block_cg_solve,
                       block_cgls_solve)
from .unrolled import unrolled_cg, unrolled_cgls
from .fit import fit, trainable_leaves, param_count
from . import rules, implicit, unrolled  # noqa: F401  (submodule access)
from . import fit as _fit_mod  # noqa: F401

__all__ = [
    "DifferentiableOperator", "make_differentiable",
    "cg_solve", "cgls_solve", "block_cg_solve", "block_cgls_solve",
    "unrolled_cg", "unrolled_cgls",
    "fit", "trainable_leaves", "param_count",
]

"""Implicit differentiation through the fused solves.

``lax.while_loop`` is not reverse-differentiable, and even if it were,
an unrolled tape would hold every iterate (O(niter · n) memory). A
converged Krylov solve does not need either: differentiate the FIXED
POINT instead of the iteration.

CG (SPD ``A``), fixed point ``A x* = y``::

    dA x* + A dx* = dy
    ⟨v, dx*⟩ = ⟨λ, dy⟩ − ⟨λ, dA x*⟩          with  Aᵀ λ = v

so the backward pass is ONE more solve with the same operator
(``∂y = λ``; parameter cotangents are the pullback of ``θ ↦ A(θ) x*``
at ``λ``, negated — see :func:`rules.param_cotangent`).

CGLS (damped least squares), fixed point
``N x* = Aᴴ y`` with ``N = AᴴA + damp²``::

    ⟨v, dx*⟩ = ⟨μ, dy⟩ + ⟨λ, dAᴴ r*⟩ − ⟨μ, dA x*⟩
    with  Nᵀ λ = v,  μ = (Aᴴ)ᵀ λ,  r* = y − A x*

— one CG solve on the normal operator (the same system CGLS itself
iterates on, so the ``M=`` preconditioner seam transfers unchanged).

The backward solve dispatches exactly like the forward one: concrete
inputs run the cached host path (``_run_*_fused`` — same ``_get_fused``
executables, tuned plans, CA engines, AOT bank as plain solves; a
gradient costs one forward-shaped solve), traced inputs (under
``jax.jit``/nested transforms) inline the fused builders into the
surrounding trace. Guards are EXCLUDED from the rule: the fixed-point
algebra differentiates the converged iterate, not the in-loop
breakdown ``select`` machinery, so the traced path always uses the
unguarded builders (docs/autodiff.md). The preconditioner ``M`` and
the cost/iteration diagnostics are gradient-transparent: ``M`` changes
the iteration, not the fixed point, and the diagnostic outputs carry
``stop_gradient`` semantics (their cotangents are discarded).
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["cg_solve", "cgls_solve", "block_cg_solve",
           "block_cgls_solve", "should_intercept"]


# ------------------------------------------------------------ helpers
def _leaves(*pytrees):
    for t in pytrees:
        if t is None:
            continue
        yield from jax.tree_util.tree_leaves(t)


def _has_tracer(*pytrees) -> bool:
    return any(isinstance(l, jax.core.Tracer) for l in _leaves(*pytrees))


def should_intercept(Op, y, x0=None) -> bool:
    """True when a classic solver entry holds traced inputs that the
    host path cannot run (``int(iiter)`` on a tracer) — the
    ``PYLOPS_MPI_TPU_AUTODIFF=on`` reroute predicate. Concrete solves
    never intercept: off-mode and on-mode lower identical programs."""
    return _has_tracer(Op, y, x0)


def _zeros_like_vec(v):
    return jax.tree_util.tree_map(jnp.zeros_like, v)


def _conj_if_complex(v):
    if np.issubdtype(np.dtype(v.dtype), np.complexfloating):
        return v.conj()
    return v


class _NormalOperator:
    """``v ↦ AᴴA v + damp² v`` — the model-space normal system the
    CGLS backward pass solves. Closure-only (never a pytree leaf);
    block inputs route through the sub-operator's public applies."""

    def __init__(self, Op, damp: float):
        n = int(Op.shape[1])
        self.shape = (n, n)
        self.dtype = Op.dtype
        self.mesh = getattr(Op, "mesh", None)
        self._Op = Op
        self._damp2 = float(damp) * float(damp)

    def matvec(self, x):
        v = self._Op.rmatvec(self._Op.matvec(x))
        return v + x * self._damp2 if self._damp2 else v

    rmatvec = matvec


# Concrete backward solves build the normal operator once per
# (operator, damp) so repeated gradient steps reuse ONE fused-cache
# entry instead of recompiling per call (id(Nop) keys the cache).
_NORMAL_MEMO: OrderedDict = OrderedDict()
_NORMAL_MEMO_MAX = 16


def _normal_operator(Op, damp: float):
    if _has_tracer(Op):
        return _NormalOperator(Op, damp)
    key = (id(Op), float(damp))
    hit = _NORMAL_MEMO.get(key)
    if hit is not None and hit[0] is Op:
        _NORMAL_MEMO.move_to_end(key)
        return hit[1]
    Nop = _NormalOperator(Op, damp)
    _NORMAL_MEMO[key] = (Op, Nop)
    while len(_NORMAL_MEMO) > _NORMAL_MEMO_MAX:
        _NORMAL_MEMO.popitem(last=False)
    return Nop


# ------------------------------------------------------ forward passes
def _forward_cg(Op, y, x0, niter, tol, M, block):
    """One fused CG solve → ``(x, iiter, cost)``. Concrete inputs run
    the cached host path (same executables as plain ``cg``); traced
    inputs inline the unguarded fused builder."""
    from ..solvers import basic as _b
    if not _has_tracer(Op, y, x0):
        if block:
            from ..solvers import block as _blk
            x, iiter, cost = _blk.block_cg(Op, y, x0, niter=niter,
                                           tol=tol, guards=False, M=M)
            return x, iiter, cost
        x, iiter, cost, _ = _b._run_cg_fused(Op, y, x0, False, niter,
                                             tol, False, M=M)
        return x, iiter, cost
    from ..solvers import ca as _ca
    mode = _ca.resolve_mode(Op, "cg")
    if mode != "off":
        # s-step's host-side breakdown fallback cannot run under trace;
        # the pipelined twin covers both CA modes here
        return _ca._pipe_cg_fused(Op, y, x0, tol, niter=niter, M=M,
                                  block=block)
    if block:
        from ..solvers import block as _blk
        return _blk._block_cg_fused(Op, y, x0, tol, niter=niter, M=M)
    return _b._cg_fused(Op, y, x0, tol, niter=niter, M=M)


def _forward_cgls(Op, y, x0, niter, damp, tol, M, block):
    """One fused CGLS solve → ``(x, iiter, cost, cost1, kold)``."""
    from ..solvers import basic as _b
    if not _has_tracer(Op, y, x0):
        if block:
            from ..solvers import block as _blk
            return _blk._run_block_cgls_fused(Op, y, x0, niter, damp,
                                              tol, M)
        x, iiter, cost, cost1, kold, _ = _b._run_cgls_fused(
            Op, y, x0, False, niter, damp, tol, False, False, M=M)
        return x, iiter, cost, cost1, kold
    from ..solvers import ca as _ca
    mode = _ca.resolve_mode(Op, "cgls")
    if mode != "off":
        return _ca._pipe_cgls_fused(Op, y, x0, damp, tol, niter=niter,
                                    M=M, block=block)
    if block:
        from ..solvers import block as _blk
        return _blk._block_cgls_fused(Op, y, x0, damp, tol,
                                      niter=niter, M=M)
    return _b._cgls_fused(Op, y, x0, damp, tol, niter=niter, M=M)


# ----------------------------------------------------- backward passes
def _cg_backward(Op, xstar, v, niter, tol, M, block, want_params):
    """``Aᵀ λ = v`` by one more CG solve (SPD: same operator, so the
    tuned plans / CA engine / AOT entry of the forward family are the
    ones that run); cotangents ``(gy, gleaves)`` — the operator
    cotangent as a flat LEAF LIST in ``tree_flatten(Op)`` order (see
    rules.py on why operator-shaped cotangent pytrees cannot pass
    custom_vjp's structure check)."""
    from ..diagnostics import metrics as _metrics
    _metrics.inc("autodiff.backward_solves")
    vc = _conj_if_complex(v)
    lam = _forward_cg(Op, vc, _zeros_like_vec(vc), niter, tol, M,
                      block)[0]
    lam = _conj_if_complex(lam)
    gy = lam
    gleaves = None
    if want_params:
        from .rules import param_cotangent
        gop = param_cotangent(Op, xstar, lam)
        gleaves = [_neg_leaf(l) for l in
                   jax.tree_util.tree_leaves(gop)]
    return gy, gleaves


def _cgls_backward(Op, y, xstar, v, niter, damp, tol, M, block,
                   want_params):
    """``Nᵀ λ = v`` (N the damped normal operator) by one CG solve,
    then ``μ = (Aᴴ)ᵀ λ``; cotangents ``(gy, gleaves)`` (leaf-list
    operator cotangent, see :func:`_cg_backward`)."""
    from ..diagnostics import metrics as _metrics
    from .rules import transpose_apply, param_cotangent
    _metrics.inc("autodiff.backward_solves")
    Nop = _normal_operator(Op, damp)
    vc = _conj_if_complex(v)
    lam = _forward_cg(Nop, vc, _zeros_like_vec(vc), niter, tol, M,
                      block)[0]
    lam = _conj_if_complex(lam)
    mu = transpose_apply(Op, lam, "rmatvec")
    gy = mu
    gleaves = None
    if want_params:
        rstar = y - Op.matvec(xstar)
        t1 = param_cotangent(Op, rstar, lam, "rmatvec")
        t2 = param_cotangent(Op, xstar, mu, "matvec")
        gleaves = [_sub_leaf(a, b) for a, b in
                   zip(jax.tree_util.tree_leaves(t1),
                       jax.tree_util.tree_leaves(t2))]
    return gy, gleaves


def _neg_leaf(a):
    return a if _is_float0(a) else -a


def _sub_leaf(a, b):
    return a if _is_float0(a) else a - b


def _is_float0(a) -> bool:
    return getattr(a, "dtype", None) == jax.dtypes.float0


# ----------------------------------------------------- custom_vjp glue
def _op_from_leaves(Op_orig, leaves, treedef):
    """Rebuild the operator from the rule's leaf-list argument —
    UNLESS both the leaves and the original operator are concrete, in
    which case the leaves are the ones just flattened off ``Op_orig``
    and returning the original instance preserves the ``id(Op)``-keyed
    fused-cache/AOT entries (an unflattened copy would recompile every
    gradient step). When ``Op_orig`` was built inside a transform (its
    leaves are tracers of the OUTER trace — e.g. ``grad`` w.r.t.
    operator parameters) it must NOT be reused with the concrete
    primal leaves custom_vjp hands the fwd/bwd passes: that would leak
    the outer tracers into the rule's pure-primal computation."""
    if not _has_tracer(leaves) and not _has_tracer(Op_orig):
        return Op_orig
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _make_cg_rule(niter, tol, M, block, treedef=None, Op_orig=None,
                  Op_static=None):
    if treedef is not None:
        @jax.custom_vjp
        def solve(leaves, y, x0):
            op = _op_from_leaves(Op_orig, leaves, treedef)
            return _forward_cg(op, y, x0, niter, tol, M, block)

        def fwd(leaves, y, x0):
            op = _op_from_leaves(Op_orig, leaves, treedef)
            outs = _forward_cg(op, y, x0, niter, tol, M, block)
            return outs, (leaves, outs[0])

        def bwd(res, cts):
            leaves, xstar = res
            op = _op_from_leaves(Op_orig, leaves, treedef)
            gy, gleaves = _cg_backward(op, xstar, cts[0], niter, tol,
                                       M, block, want_params=True)
            return gleaves, gy, _zeros_like_vec(xstar)

        solve.defvjp(fwd, bwd)
        return solve

    @jax.custom_vjp
    def solve(y, x0):
        return _forward_cg(Op_static, y, x0, niter, tol, M, block)

    def fwd(y, x0):
        outs = _forward_cg(Op_static, y, x0, niter, tol, M, block)
        return outs, outs[0]

    def bwd(xstar, cts):
        gy, _ = _cg_backward(Op_static, xstar, cts[0], niter, tol, M,
                             block, want_params=False)
        return gy, _zeros_like_vec(xstar)

    solve.defvjp(fwd, bwd)
    return solve


def _make_cgls_rule(niter, damp, tol, M, block, treedef=None,
                    Op_orig=None, Op_static=None):
    if treedef is not None:
        @jax.custom_vjp
        def solve(leaves, y, x0):
            op = _op_from_leaves(Op_orig, leaves, treedef)
            return _forward_cgls(op, y, x0, niter, damp, tol, M, block)

        def fwd(leaves, y, x0):
            op = _op_from_leaves(Op_orig, leaves, treedef)
            outs = _forward_cgls(op, y, x0, niter, damp, tol, M, block)
            return outs, (leaves, y, outs[0])

        def bwd(res, cts):
            leaves, y, xstar = res
            op = _op_from_leaves(Op_orig, leaves, treedef)
            gy, gleaves = _cgls_backward(op, y, xstar, cts[0], niter,
                                         damp, tol, M, block,
                                         want_params=True)
            return gleaves, gy, _zeros_like_vec(xstar)

        solve.defvjp(fwd, bwd)
        return solve

    @jax.custom_vjp
    def solve(y, x0):
        return _forward_cgls(Op_static, y, x0, niter, damp, tol, M,
                             block)

    def fwd(y, x0):
        outs = _forward_cgls(Op_static, y, x0, niter, damp, tol, M,
                             block)
        return outs, (y, outs[0])

    def bwd(res, cts):
        y, xstar = res
        gy, _ = _cgls_backward(Op_static, y, xstar, cts[0], niter,
                               damp, tol, M, block, want_params=False)
        return gy, _zeros_like_vec(xstar)

    solve.defvjp(fwd, bwd)
    return solve


def _solve_cg(Op, y, x0, niter, tol, M, block):
    from ..linearoperator import operator_is_jit_arg
    if x0 is None:
        x0 = _default_x0(Op, y, block)
    if operator_is_jit_arg(Op):
        leaves, treedef = jax.tree_util.tree_flatten(Op)
        rule = _make_cg_rule(niter, tol, M, block, treedef=treedef,
                             Op_orig=Op)
        return rule(leaves, y, x0)
    rule = _make_cg_rule(niter, tol, M, block, Op_static=Op)
    return rule(y, x0)


def _solve_cgls(Op, y, x0, niter, damp, tol, M, block):
    from ..linearoperator import operator_is_jit_arg
    if x0 is None:
        x0 = _default_x0(Op, y, block)
    if operator_is_jit_arg(Op):
        leaves, treedef = jax.tree_util.tree_flatten(Op)
        rule = _make_cgls_rule(niter, damp, tol, M, block,
                               treedef=treedef, Op_orig=Op)
        return rule(leaves, y, x0)
    rule = _make_cgls_rule(niter, damp, tol, M, block, Op_static=Op)
    return rule(y, x0)


def _default_x0(Op, y, block):
    # global shape / mesh / partition are static even when y is traced,
    # so the zero model is a concrete constant of the trace
    if block:
        from ..solvers.block import _zero_block_model
        return _zero_block_model(Op, y)
    from ..solvers.basic import _zero_like_model
    return _zero_like_model(Op, y)


# ------------------------------------------------------------ user API
def cg_solve(Op, y, x0=None, *, niter: int = 10, tol: float = 1e-4,
             M=None):
    """Differentiable fused CG: returns ``x`` only, with the implicit
    fixed-point VJP installed (backward pass = one more CG solve with
    the same operator/preconditioner family). Works with
    ``PYLOPS_MPI_TPU_AUTODIFF`` off — the knob only gates the CLASSIC
    entries' tracer reroute. Gradients flow to ``y``, and to ``Op``'s
    pytree leaves when the operator is jit-argument clean; ``x0``
    receives zero cotangent (the converged iterate does not depend on
    the start), ``M`` and the diagnostics are gradient-transparent."""
    return _solve_cg(Op, y, x0, niter, tol, M, block=False)[0]


def cgls_solve(Op, y, x0=None, *, niter: int = 10, damp: float = 0.0,
               tol: float = 1e-4, M=None):
    """Differentiable fused CGLS: returns ``x`` only; backward pass is
    one CG solve on the damped normal operator ``AᴴA + damp²`` (the
    system CGLS itself iterates on, so ``M=`` transfers). See
    :func:`cg_solve` for the cotangent contract."""
    return _solve_cgls(Op, y, x0, niter, damp, tol, M, block=False)[0]


def block_cg_solve(Op, y, x0=None, *, niter: int = 10,
                   tol: float = 1e-4, M=None):
    """Differentiable fused block CG over an ``(n, K)`` carry — the
    fixed-point rule applies column-wise; one block backward solve
    covers all K cotangent columns."""
    return _solve_cg(Op, y, x0, niter, tol, M, block=True)[0]


def block_cgls_solve(Op, y, x0=None, *, niter: int = 10,
                     damp: float = 0.0, tol: float = 1e-4, M=None):
    """Differentiable fused block CGLS over an ``(n, K)`` carry; see
    :func:`block_cg_solve` / :func:`cgls_solve`."""
    return _solve_cgls(Op, y, x0, niter, damp, tol, M, block=True)[0]


# ------------------------------------------------- classic-entry shims
# The PYLOPS_MPI_TPU_AUTODIFF=on reroute targets: same return contracts
# as the host entries, but every host-only conversion (int(iiter),
# np.asarray slicing, istop comparison) becomes its traced equivalent.
def entry_cg(Op, y, x0, niter, tol, M):
    x, iiter, cost = _solve_cg(Op, y, x0, niter, tol, M, block=False)
    return x, iiter, cost


def entry_cgls(Op, y, x0, niter, damp, tol, M):
    x, iiter, cost, cost1, kold = _solve_cgls(Op, y, x0, niter, damp,
                                              tol, M, block=False)
    istop = jnp.where(jnp.max(kold) < tol, 1, 2)
    return x, istop, iiter, kold, jnp.take(cost1, iiter), cost


def entry_block_cg(Op, y, x0, niter, tol, M):
    return _solve_cg(Op, y, x0, niter, tol, M, block=True)


def entry_block_cgls(Op, y, x0, niter, damp, tol, M):
    x, iiter, cost, cost1, kold = _solve_cgls(Op, y, x0, niter, damp,
                                              tol, M, block=True)
    istop = jnp.where(jnp.max(kold) < tol, 1, 2)
    return x, istop, iiter, kold, jnp.take(cost1, iiter, axis=0), cost

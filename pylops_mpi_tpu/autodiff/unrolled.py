"""Reverse-differentiable fixed-iteration CG/CGLS (scan tape).

The oracle the implicit rules are checked against, and the baseline
the bench gradient race times: a ``lax.scan`` over exactly ``niter``
iterations is what a user without implicit diff would write —
reverse-differentiable because scan saves the per-iteration carry as
a tape, which is precisely its cost: O(niter · n) activation memory
and a backward pass that replays every iteration, versus the implicit
rule's ONE extra solve. Single-RHS only (the tests reduce block
gradients column-wise against this).

Math mirrors ``basic._make_cg_body`` / ``_make_cgls_body`` (same
``_rdot`` reduction dtype, same ``_mp_floor`` freeze — a tape through
``0/0`` past convergence would poison the gradient with NaNs), minus
the early-exit ``tol`` check: the tape runs the full ``niter``
schedule, which is also what makes it a fair memory/wall baseline.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["unrolled_cg", "unrolled_cgls"]


def unrolled_cg(Op, y, x0=None, *, niter: int = 10, M=None):
    """Fixed-``niter`` (P)CG as a differentiable scan; returns ``x``."""
    from ..solvers.basic import (_rdot, _step_scalar, _precond_apply,
                                 _mp_floor, _vdtype, _zero_like_model)
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    xdt = _vdtype(x0)
    x = x0
    r = y - Op.matvec(x)
    z = _precond_apply(M, r, xdt)
    c = z
    kold = _rdot(r, z)
    floors = _mp_floor(kold)

    def step(carry, _):
        x, r, c, kold = carry
        done = kold <= floors
        q = Op.matvec(c)
        a = kold / _rdot(c, q)
        a = jnp.where(done, jnp.zeros_like(a), a)
        x = x + c * _step_scalar(a, xdt)
        r = r - q * _step_scalar(a, xdt)
        z = _precond_apply(M, r, xdt)
        k = _rdot(r, z)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        c = z + c * _step_scalar(b, xdt)
        return (x, r, c, k), None

    (x, _, _, _), _ = lax.scan(step, (x, r, c, kold), None,
                               length=niter)
    return x


def unrolled_cgls(Op, y, x0=None, *, niter: int = 10,
                  damp: float = 0.0, M=None):
    """Fixed-``niter`` (P)CGLS (classic two-sweep) as a differentiable
    scan; returns ``x``. ``damp`` quirk matches the fused setup
    (initial gradient uses un-squared ``damp``, steps use ``damp²`` —
    solvers/basic.py module doc)."""
    from ..solvers.basic import (_rdot, _step_scalar, _precond_apply,
                                 _mp_floor, _vdtype, _zero_like_model)
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    damp2 = damp ** 2
    xdt = _vdtype(x0)
    x = x0
    s = y - Op.matvec(x)
    rq = Op.rmatvec(s) - x * damp
    z = _precond_apply(M, rq, xdt)
    c = z
    kold = _rdot(rq, z)
    floors = _mp_floor(kold)

    def step(carry, _):
        x, s, c, kold = carry
        done = kold <= floors
        q = Op.matvec(c)
        den = _rdot(q, q) + damp2 * _rdot(c, c)
        a = kold / den
        a = jnp.where(done, jnp.zeros_like(a), a)
        x = x + c * _step_scalar(a, xdt)
        s = s - q * _step_scalar(a, xdt)
        rq = Op.rmatvec(s) - x * damp2
        z = _precond_apply(M, rq, xdt)
        k = _rdot(rq, z)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        c = z + c * _step_scalar(b, xdt)
        return (x, s, c, k), None

    (x, _, _, _), _ = lax.scan(step, (x, s, c, kold), None,
                               length=niter)
    return x

"""Minimal training driver for differentiable solves.

``grad``-of-``solve`` turns every inversion in this package into a
trainable layer: the loss closes over a solver call (via
:mod:`.implicit`'s custom_vjp rules), its parameters are an operator
pytree (MatrixMult weights, sparse COO vals, a learned regularization
weight, …), and each optimizer step costs ONE forward solve plus ONE
backward solve — not a ``niter``-deep tape. :func:`fit` is a
self-contained pytree Adam/SGD (no optax in the image, and none
needed for two update rules); examples/learned_regularizer.py is the
end-to-end proof.

Integer leaves (sparse ``rows``/``cols``) are structural, not
trainable: their cotangents are ``float0`` and :func:`fit` leaves
them untouched, so an operator pytree can ride through the optimizer
whole.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["fit", "trainable_leaves", "param_count"]


def _is_trainable(leaf) -> bool:
    try:
        return jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)
    except (TypeError, ValueError):
        return False


def trainable_leaves(params) -> list:
    """The inexact (float/complex) leaves of a parameter pytree — what
    :func:`fit` will actually update. Integer/bool leaves (sparse
    index arrays, flags) are structural and skipped."""
    return [leaf for leaf in jax.tree_util.tree_leaves(params)
            if _is_trainable(leaf)]


def param_count(params) -> int:
    """Total trainable scalar count of a parameter pytree."""
    return int(sum(np.prod(np.shape(leaf)) or 1
                   for leaf in trainable_leaves(params)))


def _zeros_slot(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros_like(p) if _is_trainable(p) else None,
        params)


def _sgd_update(p, g, lr):
    if not _is_trainable(p) or g is None or \
            getattr(getattr(g, "dtype", None), "name", "") == "float0":
        return p
    return p - lr * g.astype(p.dtype) if hasattr(g, "astype") \
        else p - lr * g


def fit(loss_fn: Callable, params: Any, *, steps: int = 100,
        lr: float = 1e-2, optimizer: str = "adam",
        beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8,
        callback: Optional[Callable] = None):
    """Minimize ``loss_fn(params)`` by Adam (default) or plain SGD.

    ``loss_fn`` must be a scalar-valued function of the parameter
    pytree — typically closing over data and calling one of the
    :mod:`.implicit` solves (``cgls_solve`` etc.), so each step's
    gradient is computed by one extra fused solve rather than an
    unrolled tape. Returns ``(params, losses)`` with ``losses`` a
    ``(steps,)`` numpy array of the per-step loss values (evaluated at
    the PRE-update parameters). ``callback(step, loss, params)`` (if
    given) runs on host every step.

    The loop is deliberately host-driven (no ``lax.scan`` over steps):
    each ``value_and_grad`` call hits the solver rules' concrete host
    path, so the fused forward/backward executables compile ONCE and
    every subsequent step reuses them — the same warm-cache story as
    plain repeated solves, now for training.
    """
    if optimizer not in ("adam", "sgd"):
        raise ValueError(
            f"optimizer={optimizer!r}: expected 'adam' or 'sgd'")
    vg = jax.value_and_grad(loss_fn, allow_int=True)
    losses = np.zeros(steps, dtype=np.float64)

    if optimizer == "sgd":
        for step in range(steps):
            loss, grads = vg(params)
            losses[step] = float(loss)
            params = jax.tree_util.tree_map(
                lambda p, g: _sgd_update(p, g, lr), params, grads)
            if callback is not None:
                callback(step, losses[step], params)
        return params, losses

    m = _zeros_slot(params)
    v = _zeros_slot(params)
    for step in range(steps):
        loss, grads = vg(params)
        losses[step] = float(loss)
        t = step + 1
        bc1 = 1.0 - beta1 ** t
        bc2 = 1.0 - beta2 ** t

        def upd(p, g, mi, vi):
            if not _is_trainable(p) or g is None or \
                    getattr(getattr(g, "dtype", None), "name",
                            "") == "float0":
                return p, mi, vi
            g = jnp.asarray(g).astype(p.dtype) if hasattr(p, "dtype") \
                else jnp.asarray(g)
            mi = beta1 * mi + (1.0 - beta1) * g
            vi = beta2 * vi + (1.0 - beta2) * jnp.abs(g) ** 2
            step_dir = (mi / bc1) / (jnp.sqrt(vi / bc2) + eps)
            return p - lr * step_dir, mi, vi

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(
            m, is_leaf=lambda x: x is None)
        flat_v = jax.tree_util.tree_leaves(
            v, is_leaf=lambda x: x is None)
        out = [upd(p, g, mi, vi) for p, g, mi, vi
               in zip(flat_p, flat_g, flat_m, flat_v)]
        params = jax.tree_util.tree_unflatten(
            treedef, [o[0] for o in out])
        m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        if callback is not None:
            callback(step, losses[step], params)
    return params, losses

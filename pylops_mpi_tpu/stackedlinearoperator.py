"""Stacked linear-operator ABC.

Rebuild of ``pylops_mpi/StackedLinearOperator.py:15-568``: the abstract
base for operators whose model and/or data are
:class:`StackedDistributedArray`s, with the same lazy algebra as
:class:`MPILinearOperator`. Here the two hierarchies share one base —
the algebra wrappers compose either vector type — so this class only
adds the reference's composition guards (product forbids stacking
incompatibilities, ref ``StackedLinearOperator.py:430-443``).
"""

from __future__ import annotations

from .linearoperator import (MPILinearOperator, _ProductLinearOperator,
                             _ScaledLinearOperator)

__all__ = ["MPIStackedLinearOperator"]


class MPIStackedLinearOperator(MPILinearOperator):
    """Abstract operator over stacked model/data spaces
    (ref ``StackedLinearOperator.py:15-387``)."""

    def dot(self, x):
        from .ops.stack import MPIStackedVStack
        from .ops.blockdiag import MPIStackedBlockDiag
        if isinstance(x, MPIStackedLinearOperator) or \
                isinstance(x, MPILinearOperator):
            # the reference forbids VStack @ VStack and length-mismatched
            # BlockDiag products (StackedLinearOperator.py:430-443) —
            # without the guard the zip over components would silently
            # truncate and return a wrong-shaped answer much later
            if isinstance(self, MPIStackedVStack) and \
                    isinstance(x, MPIStackedVStack):
                raise ValueError(
                    "both operands cannot be MPIStackedVStack")
            if (isinstance(self, MPIStackedBlockDiag)
                    and isinstance(x, MPIStackedBlockDiag)
                    and len(self.ops) != len(x.ops)):
                raise ValueError(
                    "both MPIStackedBlockDiag cannot have different "
                    f"number of ops, {len(self.ops)} != {len(x.ops)}")
        return super().dot(x)

"""Namespace parity with ``pylops_mpi.basicoperators``."""
from ..ops.blockdiag import MPIBlockDiag, MPIStackedBlockDiag
from ..ops.stack import MPIVStack, MPIStackedVStack, MPIHStack
from ..ops.derivatives import (MPIFirstDerivative, MPISecondDerivative,
                               MPILaplacian, MPIGradient)
from ..ops.matrixmult import (MPIMatrixMult, active_grid_comm,
                              local_block_split, block_gather)
from ..ops.halo import MPIHalo, halo_block_split

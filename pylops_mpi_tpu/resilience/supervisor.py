"""Elastic job supervisor: launch, watch, classify, shrink, relaunch.

The multi-host story so far launches workers fire-and-forget
(``tests/test_multihost.py`` did its own ``subprocess.Popen`` pair) and
a single hung or preempted worker turns the whole job into a silent
wall-clock burn. :func:`launch_job` generalizes that launcher into the
missing control loop:

1. **Launch** N workers with the elastic env contract
   (:mod:`.elastic` module docstring): coordinator address on a fresh
   free port, world size, rank, attempt counter, and a per-worker
   heartbeat file assignment.
2. **Watch** — poll worker processes and their heartbeat files.
3. **Classify** every failure into one of three kinds (the table in
   ``docs/robustness.md#failure-classification``):

   ===================  =============================================
   ``exit``             process ended with a nonzero return code
   ``signal``           process was killed by a signal (rc < 0)
   ``stale_heartbeat``  process alive but its beat file has not been
                        touched for ``stale_factor`` × the beat
                        interval — wedged (SIGSTOP'd, deadlocked in a
                        collective, runaway swap), not dead
   ===================  =============================================

4. **Shrink + relaunch** — kill every straggler of the failed attempt
   (a job that lost one peer deadlocks the rest inside their next
   collective), then relaunch on the SURVIVING worker slots with a
   shrunk world size and a fresh coordinator port, up to
   ``max_relaunches`` times. Workers see the new world via the env
   contract and rebuild their (smaller) mesh; mesh-elastic checkpoint
   restore (``utils/checkpoint.py``) makes the saved state land on it.

The supervisor deliberately imports neither jax nor the worker's code:
it supervises OS processes and files only, so it stays responsive while
workers compile, collect, or die. Worker stdout/stderr go to per-worker
log files (PIPEs would deadlock a chatty worker on a full pipe buffer);
the tail of each log is collected into the result for post-mortems.
"""

from __future__ import annotations

import json
import math
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from .elastic import read_heartbeat

__all__ = ["WorkerHandle", "Failure", "JobResult", "JOB_REPORT_SCHEMA",
           "free_port", "launch_job", "write_job_report"]

JOB_REPORT_SCHEMA = 1


def free_port() -> int:
    """An OS-assigned free TCP port for the attempt's coordinator.
    (Small race window between close and the coordinator's bind — the
    bounded retry inside ``initialize_multihost`` absorbs a loss.)"""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class WorkerHandle:
    """One launched worker process of one attempt."""
    rank: int                 # rank within the CURRENT attempt's world
    slot: int                 # stable identity across attempts
    proc: subprocess.Popen
    heartbeat_path: str
    log_path: str
    launched_at: float        # monotonic; bring-up grace reference
    metrics_path: str = ""    # worker's assigned snapshot file
    reconfig_path: str = ""   # in-place reassignment file (inplace=True)

    def alive(self) -> bool:
        return self.proc.poll() is None


@dataclass
class Failure:
    """One classified worker failure (see module docstring table)."""
    attempt: int
    rank: int
    slot: int
    kind: str                 # "exit" | "signal" | "stale_heartbeat"
    returncode: Optional[int]
    detail: str
    detected_after_s: float   # since this attempt's launch

    def as_dict(self) -> Dict:
        return {"attempt": self.attempt, "rank": self.rank,
                "slot": self.slot, "kind": self.kind,
                "returncode": self.returncode, "detail": self.detail,
                "detected_after_s": round(self.detected_after_s, 3)}


@dataclass
class JobResult:
    """What :func:`launch_job` hands back: whether the final attempt
    finished clean, how many processes that attempt ran with, every
    classified failure along the way, the tail of each final worker's
    log (keyed by rank), and — when the workers ran with
    ``PYLOPS_MPI_TPU_METRICS=on`` — each final worker's last metrics
    snapshot (``metrics``, keyed by rank; harvested from the worker's
    snapshot file with its last heartbeat as fallback)."""
    ok: bool
    world_size: int
    attempts: int
    failures: List[Failure] = field(default_factory=list)
    outputs: Dict[int, str] = field(default_factory=dict)
    returncodes: Dict[int, int] = field(default_factory=dict)
    logdir: Optional[str] = None
    metrics: Dict[int, Dict] = field(default_factory=dict)


def _harvest_metrics(workers: Sequence[WorkerHandle]) -> Dict[int, Dict]:
    """Final per-worker metrics snapshots: the worker's snapshot file
    first (the atexit write is the freshest), its last heartbeat's
    embedded ``metrics`` payload as fallback (a SIGKILLed worker never
    ran atexit, but its beats carried the registry). Workers without
    either (metrics off) are simply absent."""
    out: Dict[int, Dict] = {}
    for w in workers:
        snap = _metrics.read_snapshot(w.metrics_path) \
            if w.metrics_path else None
        if snap is None:
            beat = read_heartbeat(w.heartbeat_path)
            if beat and isinstance(beat.get("metrics"), dict):
                snap = beat["metrics"]
        if snap is not None:
            out[w.rank] = snap
    return out


def write_job_report(result: JobResult) -> Optional[str]:
    """Persist the job post-mortem as ``job_report.json`` next to the
    worker logs (ISSUE 10 log hygiene): schema-versioned, with every
    failure classification and the final per-worker metrics snapshots.
    Atomic (temp + ``os.replace``); a failed write is swallowed — the
    in-memory :class:`JobResult` is already in the caller's hands."""
    if not result.logdir:
        return None
    path = os.path.join(result.logdir, "job_report.json")
    doc = {"schema": JOB_REPORT_SCHEMA, "ok": result.ok,
           "world_size": result.world_size, "attempts": result.attempts,
           "failures": [f.as_dict() for f in result.failures],
           "returncodes": {str(r): rc
                           for r, rc in result.returncodes.items()},
           "metrics": {str(r): m for r, m in result.metrics.items()},
           "logdir": result.logdir}
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None


def _format_argv(argv: Sequence[str], *, port: int, rank: int,
                 world: int, attempt: int) -> List[str]:
    """Expand the ``{port}``/``{rank}``/``{world}``/``{attempt}``
    placeholders. Non-placeholder args pass through untouched (a
    literal ``{`` elsewhere is the caller's problem to escape, but no
    existing worker argv carries one)."""
    subst = {"port": port, "rank": rank, "world": world,
             "attempt": attempt}
    out = []
    for a in argv:
        try:
            out.append(str(a).format(**subst))
        except (KeyError, IndexError, ValueError):
            out.append(str(a))
    return out


def _tail(path: str, max_bytes: int = 8192) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def _kill_all(workers: Sequence[WorkerHandle]) -> None:
    """SIGKILL the whole attempt. A worker that lost a peer is (or soon
    will be) blocked inside a collective; there is nothing graceful to
    wait for, and SIGCONT-before-KILL would only matter for SIGSTOP'd
    workers, which SIGKILL reaps regardless."""
    for w in workers:
        if w.alive():
            try:
                w.proc.kill()
            except OSError:
                pass
    for w in workers:
        try:
            w.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            pass


def _classify(w: WorkerHandle, *, stale_s: float,
              now_mono: float) -> Optional[Dict]:
    """Return ``{"kind", "returncode", "detail"}`` when worker ``w`` has
    failed, else None. Heartbeat staleness is judged against the beat
    file's mtime (wall clock — mtimes are epoch-stamped), with the
    LAUNCH time (monotonic) standing in as beat zero so a worker that
    dies before its first beat is caught by the same rule."""
    rc = w.proc.poll()
    if rc is not None:
        if rc == 0:
            return None  # clean exit is success, handled by the caller
        if rc < 0:
            try:
                signame = signal.Signals(-rc).name
            except ValueError:
                signame = f"signal {-rc}"
            return {"kind": "signal", "returncode": rc,
                    "detail": f"killed by {signame}"}
        return {"kind": "exit", "returncode": rc,
                "detail": f"exited with code {rc}"}
    try:
        beat_age = time.time() - os.path.getmtime(w.heartbeat_path)
    except OSError:
        beat_age = now_mono - w.launched_at  # no beat: age since launch
    if beat_age > stale_s:
        beat = read_heartbeat(w.heartbeat_path)
        return {"kind": "stale_heartbeat", "returncode": None,
                "detail": (f"no heartbeat for {beat_age:.2f}s "
                           f"(threshold {stale_s:.2f}s; last beat "
                           f"{beat})")}
    return None


def launch_job(argv: Sequence[str], num_workers: int, *,
               max_relaunches: int = 1,
               shrink: bool = True,
               heartbeat_interval: float = 1.0,
               stale_factor: float = 2.0,
               grace_s: Optional[float] = None,
               poll_s: float = 0.05,
               job_timeout_s: Optional[float] = None,
               env: Optional[Dict[str, str]] = None,
               logdir: Optional[str] = None,
               on_poll: Optional[Callable[[int, List[WorkerHandle]],
                                          None]] = None,
               on_relaunch: Optional[Callable[[int, Failure],
                                              None]] = None,
               python: Optional[str] = None,
               inplace: bool = False,
               quorum: float = 0.5,
               aot_cache: Optional[str] = None) -> JobResult:
    """Launch ``num_workers`` supervised worker processes and babysit
    them to completion, relaunching on a shrunk world after failures.

    ``argv`` is the worker command line; ``{port}``, ``{rank}``,
    ``{world}`` and ``{attempt}`` placeholders are expanded per worker
    per attempt (so ``tests/multihost_worker.py``'s positional
    ``<port> <rank>`` convention slots straight in), and the same
    values always travel in the env contract for workers that prefer
    :func:`~pylops_mpi_tpu.resilience.elastic.worker_config`. When
    ``argv[0]`` ends in ``.py`` it is run under ``python`` (default:
    ``sys.executable``).

    Failure handling: the FIRST classified failure of an attempt kills
    the whole attempt (peers are wedging in collectives already) and —
    while relaunch budget remains — relaunches on the surviving slots:
    ``shrink=True`` (default) drops the failed worker's slot so the new
    attempt runs with a smaller world; ``shrink=False`` keeps the world
    size (a supervisor for jobs whose hosts come back, e.g. spot
    reclaims with replacement). A relaunch budget of ``max_relaunches``
    bounds the loop; a shrink below one worker, or a timeout
    (``job_timeout_s``, whole job), ends it with ``ok=False``.

    Staleness: a worker counts as wedged when its beat file mtime is
    older than ``stale_factor × heartbeat_interval`` (plus ``grace_s``
    of bring-up slack, default ``10 × interval``, applied only until
    the first beat lands — interpreter start + jax import dwarf the
    beat interval).

    ``on_poll(attempt, workers)`` runs every poll tick — the chaos
    tests use it to SIGSTOP a worker mid-epoch; production callers can
    use it for progress reporting.

    ``on_relaunch(next_attempt, failure)`` runs after a failed attempt
    has been killed and before its relaunch starts — the serving layer
    uses it to move the dead attempt's claimed-but-unfinished requests
    back into the pending spool so no in-flight work is lost. A raising
    hook is swallowed (recovery must not kill the supervisor); it is
    NOT called for terminal failures (budget exhausted, job timeout) —
    the caller still holds the final :class:`JobResult` for those.

    Worker env: inherits ``os.environ``, overlaid with ``env``, overlaid
    with the elastic contract (contract wins — a stale
    ``PYLOPS_MPI_TPU_PROCESS_ID`` from an outer supervised run must not
    leak into workers).

    ``aot_cache`` (a directory) arms the AOT executable bank for every
    worker (``PYLOPS_MPI_TPU_AOT=on`` + ``PYLOPS_MPI_TPU_AOT_CACHE``,
    plus the persistent compilation cache under the same root): attempt
    0 compiles and banks the fused solver programs; every RELAUNCHED
    attempt prewarms from the bank, so recovery wall-clock stops
    including a recompile (the cold-start tax the relaunch ladder used
    to pay per attempt — docs/aot.md#recovery). Explicit ``env``
    entries for the same knobs win.

    In-place recovery (``inplace=True``): each worker additionally gets
    a ``PYLOPS_MPI_TPU_RECONFIG_FILE`` assignment, and when a failure
    leaves EXACTLY ONE live survivor meeting the ``quorum`` fraction of
    the attempt's world (and relaunch budget remains), the supervisor
    kills only the failed worker and writes the survivor a reconfig
    naming the shrunk world — the survivor re-forms its mesh and
    replans the live solver carry over collectives, with no checkpoint
    write/read on the recovery path. Any other shape (multiple
    survivors — a multi-process mesh cannot be re-formed without the
    hanging ``jax.distributed`` teardown barrier — below-quorum, spent
    budget, or a job timeout) takes the classic kill-all +
    checkpoint-relaunch ladder. Decision table:
    ``docs/robustness.md#in-place-recovery``."""
    if num_workers < 1:
        raise ValueError(f"num_workers must be >= 1, got {num_workers}")
    argv = [str(a) for a in argv]
    python = python or sys.executable
    logdir = logdir or tempfile.mkdtemp(prefix="pylops-supervisor-")
    os.makedirs(logdir, exist_ok=True)
    if grace_s is None:
        grace_s = 10.0 * heartbeat_interval
    stale_s = stale_factor * heartbeat_interval

    result = JobResult(ok=False, world_size=num_workers, attempts=0,
                       logdir=logdir)
    slots = list(range(num_workers))  # surviving stable identities
    t_job = time.monotonic()

    for attempt in range(max_relaunches + 1):
        world = len(slots)
        port = free_port()
        # monotonic: in-place reconfigs also count an attempt, so the
        # loop index alone cannot seed the total
        result.attempts += 1
        result.world_size = world
        _trace.event("supervisor.launch", cat="resilience",
                     attempt=attempt, world=world, port=port,
                     slots=list(slots))
        workers: List[WorkerHandle] = []
        for rank, slot in enumerate(slots):
            hb = os.path.join(logdir,
                              f"worker{slot}.attempt{attempt}.hb")
            log = os.path.join(logdir,
                               f"worker{slot}.attempt{attempt}.log")
            met = os.path.join(
                logdir, f"worker{slot}.attempt{attempt}.metrics.json")
            rcf = os.path.join(
                logdir, f"worker{slot}.attempt{attempt}.reconfig.json") \
                if inplace else ""
            wenv = dict(os.environ)
            if aot_cache:
                # relaunch prewarms from the bank attempt 0 seeded —
                # recovery wall stops paying the recompile (the
                # compilation cache shares the root as the fallback
                # layer for programs the bank does not serialize)
                wenv["PYLOPS_MPI_TPU_AOT"] = "on"
                wenv["PYLOPS_MPI_TPU_AOT_CACHE"] = aot_cache
                wenv.setdefault(
                    "PYLOPS_MPI_TPU_COMPILE_CACHE",
                    os.path.join(aot_cache, "xla"))
            if env:
                wenv.update(env)
            wenv.update({
                "PYLOPS_MPI_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "PYLOPS_MPI_TPU_NUM_PROCESSES": str(world),
                "PYLOPS_MPI_TPU_PROCESS_ID": str(rank),
                "PYLOPS_MPI_TPU_ATTEMPT": str(attempt),
                "PYLOPS_MPI_TPU_HEARTBEAT_FILE": hb,
                "PYLOPS_MPI_TPU_HEARTBEAT": repr(heartbeat_interval),
                # snapshot assignment is unconditional: the worker's
                # registry only starts its writer under METRICS=on
                "PYLOPS_MPI_TPU_METRICS_FILE": met,
            })
            if inplace:
                wenv["PYLOPS_MPI_TPU_RECONFIG_FILE"] = rcf
            else:
                # a stale assignment from an outer supervised run must
                # not arm in-place polling in this job's workers
                wenv.pop("PYLOPS_MPI_TPU_RECONFIG_FILE", None)
            # relaunched peers must not re-dial the coordinator in
            # lockstep; setdefault so an explicit caller value wins
            wenv.setdefault("PYLOPS_MPI_TPU_RETRY_JITTER", "0.25")
            cmd = _format_argv(argv, port=port, rank=rank, world=world,
                               attempt=attempt)
            if cmd and cmd[0].endswith(".py"):
                cmd = [python] + cmd
            logf = open(log, "wb")
            try:
                proc = subprocess.Popen(cmd, stdout=logf,
                                        stderr=subprocess.STDOUT,
                                        env=wenv)
            finally:
                logf.close()  # the child holds its own fd now
            workers.append(WorkerHandle(rank=rank, slot=slot, proc=proc,
                                        heartbeat_path=hb, log_path=log,
                                        launched_at=time.monotonic(),
                                        metrics_path=met,
                                        reconfig_path=rcf))

        failure: Optional[Failure] = None
        while True:
            now = time.monotonic()
            if job_timeout_s is not None and now - t_job > job_timeout_s:
                _kill_all(workers)
                failure = Failure(
                    attempt=attempt, rank=-1, slot=-1, kind="timeout",
                    returncode=None,
                    detail=f"job exceeded {job_timeout_s}s",
                    detected_after_s=now - workers[0].launched_at)
                result.failures.append(failure)
                result.outputs = {w.rank: _tail(w.log_path)
                                  for w in workers}
                result.metrics = _harvest_metrics(workers)
                _trace.event("supervisor.timeout", cat="resilience",
                             attempt=attempt)
                write_job_report(result)
                return result  # a job timeout is terminal, no relaunch
            if on_poll is not None:
                on_poll(attempt, workers)
            for w in workers:
                # bring-up grace: until the first beat file appears,
                # only the longer grace window applies
                eff_stale = stale_s if os.path.exists(w.heartbeat_path) \
                    else max(stale_s, grace_s)
                cls = _classify(w, stale_s=eff_stale, now_mono=now)
                if cls is not None:
                    failure = Failure(attempt=attempt, rank=w.rank,
                                      slot=w.slot,
                                      detected_after_s=now - w.launched_at,
                                      **cls)
                    break
            if failure is not None:
                # ---- in-place path: patch the live survivor instead
                # of killing the attempt. Gates (the robustness.md
                # decision table): armed, not a job timeout, relaunch
                # budget left, quorum met, and EXACTLY one survivor —
                # a multi-process mesh cannot be re-formed in place
                # (the jax.distributed teardown barrier hangs while a
                # peer is dead), so 2+ survivors fall through to the
                # checkpoint-relaunch ladder.
                survivors = [w for w in workers
                             if w.slot != failure.slot and w.alive()]
                need = max(1, math.ceil(quorum * world))
                if (inplace and attempt < max_relaunches
                        and len(survivors) == 1
                        and len(survivors) >= need):
                    result.failures.append(failure)
                    _trace.event("supervisor.failure", cat="resilience",
                                 **failure.as_dict())
                    _kill_all([w for w in workers
                               if w.slot == failure.slot])
                    slots = [s for s in slots if s != failure.slot]
                    for new_rank, w in enumerate(survivors):
                        doc = {"attempt": attempt + 1,
                               "num_processes": len(survivors),
                               "process_id": new_rank,
                               "coordinator": None,
                               "lost_slot": failure.slot}
                        tmp = w.reconfig_path + f".tmp{os.getpid()}"
                        with open(tmp, "w") as f:
                            json.dump(doc, f)
                        os.replace(tmp, w.reconfig_path)
                    result.attempts += 1
                    result.world_size = len(survivors)
                    world = len(survivors)
                    _metrics.inc("supervisor.inplace_reconfigs")
                    _trace.event("supervisor.inplace_reconfig",
                                 cat="resilience", attempt=attempt + 1,
                                 world=world, lost_slot=failure.slot,
                                 slots=list(slots))
                    workers = survivors
                    failure = None
                    continue
                break
            if all(w.proc.poll() == 0 for w in workers):
                result.ok = True
                result.outputs = {w.rank: _tail(w.log_path)
                                  for w in workers}
                result.returncodes = {w.rank: 0 for w in workers}
                result.metrics = _harvest_metrics(workers)
                _trace.event("supervisor.success", cat="resilience",
                             attempt=attempt, world=world)
                write_job_report(result)
                return result
            time.sleep(poll_s)

        # ---- attempt failed: kill stragglers, record, shrink, retry
        result.failures.append(failure)
        _trace.event("supervisor.failure", cat="resilience",
                     **failure.as_dict())
        _kill_all(workers)
        result.outputs = {w.rank: _tail(w.log_path) for w in workers}
        result.metrics = _harvest_metrics(workers)
        result.returncodes = {w.rank: (w.proc.poll()
                                       if w.proc.poll() is not None
                                       else -9)
                              for w in workers}
        if shrink and failure.slot in slots:
            slots = [s for s in slots if s != failure.slot]
        if not slots or attempt >= max_relaunches:
            write_job_report(result)
            return result
        if on_relaunch is not None:
            try:
                on_relaunch(attempt + 1, failure)
            except Exception:
                pass
        _metrics.inc("supervisor.relaunches")
        _trace.event("supervisor.relaunch", cat="resilience",
                     attempt=attempt + 1, world=len(slots),
                     slots=list(slots))
    write_job_report(result)
    return result

"""Worker-side elastic runtime: heartbeats and the collective watchdog.

The supervisor (:mod:`.supervisor`) can only act on what it can
observe from outside the worker process. This module is the worker's
half of that contract:

- **Heartbeats** — a daemon thread writes a small JSON beat file every
  ``PYLOPS_MPI_TPU_HEARTBEAT`` seconds (atomically: temp + replace, so
  the supervisor never reads a torn beat). The thread is independent
  of the main thread, so a worker stuck inside a fused epoch or a long
  compile still beats; the beat STOPS only when the process is truly
  wedged (SIGSTOP, runaway GC, kernel-level stall) or dead — exactly
  the states the supervisor classifies as ``stale_heartbeat``.
- **The collective watchdog** — blocking host-side phases that wait on
  *peers* (``jax.distributed`` bring-up, multi-host checkpoint
  save/load) hang forever when one peer is gone; a heartbeat cannot
  catch this, because the *stuck* worker's beat thread keeps running.
  :func:`watched_call` runs such a phase in a worker thread with a
  deadline from the central :data:`~pylops_mpi_tpu.diagnostics.\
profiler.STAGE_BUDGETS` table (the same machinery the harvest ladder's
  :class:`~pylops_mpi_tpu.diagnostics.profiler.DeadlineRunner` uses)
  and raises a classified :class:`WatchdogTimeout` instead of blocking
  — the worker exits nonzero, the supervisor reaps it and relaunches
  the job on the surviving host set.

Gating: the watchdog defaults to ``auto`` — armed only when the
process is SUPERVISED (``PYLOPS_MPI_TPU_HEARTBEAT_FILE`` is set by the
supervisor), so plain library use is bit-for-bit unchanged (no extra
threads, no trace events; the off-mode pins in
``tests/test_supervisor.py`` hold this). ``PYLOPS_MPI_TPU_WATCHDOG=on``
arms it unconditionally; ``off`` disarms even under supervision.

The env contract (set by :func:`.supervisor.launch_job`, read by
:func:`worker_config` / :func:`elastic_initialize`):

==================================  ====================================
``PYLOPS_MPI_TPU_COORDINATOR``      ``host:port`` of the jax.distributed
                                    coordinator for THIS attempt
``PYLOPS_MPI_TPU_NUM_PROCESSES``    world size of this attempt (shrinks
                                    after a failure)
``PYLOPS_MPI_TPU_PROCESS_ID``       this worker's rank in the attempt
``PYLOPS_MPI_TPU_ATTEMPT``          0-based relaunch counter
``PYLOPS_MPI_TPU_HEARTBEAT_FILE``   where to write beats
``PYLOPS_MPI_TPU_HEARTBEAT``        beat interval, seconds
==================================  ====================================
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import namedtuple
from typing import Any, Callable, Dict, Optional

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from ..diagnostics.profiler import STAGE_BUDGETS

__all__ = ["heartbeat_interval", "heartbeat_file", "HeartbeatWriter",
           "start_heartbeat", "stop_heartbeat", "maybe_start_heartbeat",
           "read_heartbeat", "WatchdogTimeout", "watchdog_mode",
           "watchdog_enabled", "watchdog_timeout", "watched_call",
           "WorkerConfig", "worker_config", "elastic_initialize",
           "request_drain", "drain_requested", "reset_drain",
           "install_sigterm_drain",
           "ElasticReconfig", "inplace_mode", "inplace_armed",
           "quorum_fraction", "reconfig_file", "pending_reconfig",
           "apply_reconfig", "reform_mesh", "bank_carry", "banked_carry",
           "clear_carry", "restore_carry"]


# ------------------------------------------------------------ heartbeats
def heartbeat_interval() -> float:
    """``PYLOPS_MPI_TPU_HEARTBEAT`` beat interval in seconds (default
    1.0; floored at 0.05 so a typo cannot busy-spin the writer)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_HEARTBEAT", "1.0"))
    except ValueError:
        v = 1.0
    return max(0.05, v)


def heartbeat_file() -> Optional[str]:
    """``PYLOPS_MPI_TPU_HEARTBEAT_FILE`` — the beat path the supervisor
    assigned this worker, or ``None`` when unsupervised."""
    return os.environ.get("PYLOPS_MPI_TPU_HEARTBEAT_FILE") or None


class HeartbeatWriter(threading.Thread):
    """Daemon thread writing ``{"pid", "seq", "wall", "mono"}`` —
    plus ``"metrics"`` (the live registry snapshot,
    ``diagnostics/metrics.py``) when ``PYLOPS_MPI_TPU_METRICS=on`` —
    to ``path`` every ``interval`` seconds, atomically (pid-suffixed
    temp + ``os.replace``), so the supervisor's reader can never
    observe a torn beat. ``stop()`` is idempotent and joins the
    thread."""

    def __init__(self, path: str, interval: float):
        super().__init__(name="pylops-heartbeat", daemon=True)
        self.path = os.path.abspath(path)
        self.interval = float(interval)
        self.seq = 0
        # NOT named _stop: Thread.join() calls a private self._stop()
        self._halt = threading.Event()

    def beat(self) -> None:
        self.seq += 1
        doc = {"pid": os.getpid(), "seq": self.seq,
               "wall": time.time(), "mono": time.monotonic()}
        # live per-worker PROGRESS, not just liveness (ISSUE 10): the
        # supervisor's read_heartbeat sees the current metrics registry
        # in every beat. One env lookup when metrics are off.
        if _metrics.metrics_enabled():
            try:
                doc["metrics"] = _metrics.snapshot()
            except Exception:
                pass  # a metrics bug must not kill the beat
        payload = json.dumps(doc)
        tmp = self.path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a full disk must not kill the worker via its beat

    def run(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beat()  # first beat immediately: bring-up counts as alive
        while not self._halt.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=5.0)


_HB_LOCK = threading.Lock()
_WRITER: Optional[HeartbeatWriter] = None


def start_heartbeat(path: Optional[str] = None,
                    interval: Optional[float] = None
                    ) -> Optional[HeartbeatWriter]:
    """Start (or return the already-running) heartbeat writer. With no
    ``path`` argument the env contract decides; returns ``None`` when
    no path is configured — the unsupervised no-op."""
    global _WRITER
    path = path or heartbeat_file()
    if path is None:
        return None
    with _HB_LOCK:
        if _WRITER is not None and _WRITER.is_alive():
            return _WRITER
        _WRITER = HeartbeatWriter(
            path, heartbeat_interval() if interval is None else interval)
        _WRITER.start()
        return _WRITER


def maybe_start_heartbeat() -> Optional[HeartbeatWriter]:
    """Env-driven auto-start used by long-running entry points (the
    segmented solvers): one dict lookup when unsupervised, the running
    writer when supervised. Safe to call from anywhere, any number of
    times."""
    if heartbeat_file() is None:
        return None
    return start_heartbeat()


def stop_heartbeat() -> None:
    global _WRITER
    with _HB_LOCK:
        if _WRITER is not None:
            _WRITER.stop()
            _WRITER = None


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """Supervisor-side beat reader: the parsed beat dict, or ``None``
    when the file is missing or (transiently) unparseable."""
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


# ------------------------------------------------------------- watchdog
class WatchdogTimeout(RuntimeError):
    """A watched host-side phase blew its deadline — a hung peer, not
    a slow computation. Carries ``stage`` and ``timeout_s`` so the
    supervisor's failure record (and the trace event) name the phase
    that wedged."""

    def __init__(self, stage: str, timeout_s: float):
        self.stage = stage
        self.timeout_s = float(timeout_s)
        super().__init__(
            f"watchdog: stage {stage!r} still blocked after "
            f"{timeout_s:.0f}s — a peer is likely hung or gone; "
            "exiting so the supervisor can relaunch on the surviving "
            "hosts (docs/robustness.md#collective-watchdog)")


_WD_MODES = ("auto", "on", "off")
_warned_wd = False


def watchdog_mode() -> str:
    """``PYLOPS_MPI_TPU_WATCHDOG`` resolved to ``auto``/``on``/``off``
    (default ``auto``; unknown values warn once and fall back to
    ``auto`` — same rule as the overlap/trace knobs)."""
    global _warned_wd
    m = os.environ.get("PYLOPS_MPI_TPU_WATCHDOG", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in _WD_MODES:
        if not _warned_wd:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_WATCHDOG={m!r} is not one of "
                f"{_WD_MODES}; using 'auto'", stacklevel=2)
            _warned_wd = True
        m = "auto"
    return m


def watchdog_enabled() -> bool:
    """``on`` → armed; ``off`` → disarmed; ``auto`` (default) → armed
    only when this process is supervised (a heartbeat file is
    configured) — plain library use never grows watchdog threads."""
    m = watchdog_mode()
    if m == "on":
        return True
    if m == "off":
        return False
    return heartbeat_file() is not None


def watchdog_timeout(stage: str, default: Optional[float] = None) -> float:
    """Deadline for one watched stage: the global override
    ``PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT`` when set, else the stage's row
    in the central ``STAGE_BUDGETS`` table (``tpu`` column), else
    ``default`` (300 s)."""
    raw = os.environ.get("PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    row = STAGE_BUDGETS.get(stage)
    if row and row.get("tpu"):
        return float(row["tpu"])
    return 300.0 if default is None else float(default)


_wd_tls = threading.local()  # reentrancy: nested watched phases run direct


def watched_call(fn: Callable, *args, stage: str,
                 timeout_s: Optional[float] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under the collective watchdog.

    Disarmed (the default, unsupervised case) this is a direct call —
    zero threads, zero trace events, bit-identical behavior. Armed, the
    call runs in a daemon worker thread with deadline
    ``timeout_s`` (default: :func:`watchdog_timeout` for ``stage``);
    if the deadline passes, a ``resilience.watchdog`` trace event is
    emitted and :class:`WatchdogTimeout` is raised in the CALLER —
    the blocked thread is left behind (Python cannot kill it), which
    is exactly right for a supervised worker: the raise unwinds to a
    nonzero exit and the supervisor reaps the whole process. Nested
    watched phases (checkpoint-inside-harvest) run direct under the
    outer deadline instead of stacking threads."""
    if not watchdog_enabled() or getattr(_wd_tls, "active", False):
        return fn(*args, **kwargs)
    deadline = watchdog_timeout(stage) if timeout_s is None \
        else float(timeout_s)
    out: "queue.Queue" = queue.Queue(maxsize=1)

    def runner():
        _wd_tls.active = True
        try:
            out.put((True, fn(*args, **kwargs)))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            out.put((False, e))

    t = threading.Thread(target=runner, daemon=True,
                         name=f"pylops-watchdog-{stage}")
    with _trace.span("resilience.watchdog", cat="resilience",
                     stage=stage, timeout_s=deadline):
        t.start()
        try:
            ok, payload = out.get(timeout=deadline)
        except queue.Empty:
            _trace.event("resilience.watchdog_timeout", cat="resilience",
                         stage=stage, timeout_s=deadline)
            raise WatchdogTimeout(stage, deadline) from None
    if ok:
        return payload
    raise payload


# -------------------------------------------------------- drain signal
# SIGTERM semantics for serve-forever workers (serving/service.py):
# the deployment's stop is a DRAIN, not a kill — finish in-flight
# batches, refuse new claims, then exit 0. A signal handler can only
# run on the main thread; the serving loops poll this event instead.
_DRAIN = threading.Event()
_prev_sigterm: Any = None


def request_drain() -> None:
    """Ask this process's serving loops to drain and exit (idempotent;
    also callable directly, e.g. from tests or an admin endpoint)."""
    if not _DRAIN.is_set():
        _DRAIN.set()
        _trace.event("resilience.drain_requested", cat="resilience",
                     pid=os.getpid())
        _metrics.inc("serve.drain_requests")


def drain_requested() -> bool:
    """Whether a drain has been requested for this process."""
    return _DRAIN.is_set()


def reset_drain() -> None:
    """Clear the drain flag (test isolation; a served process never
    un-drains)."""
    _DRAIN.clear()


def install_sigterm_drain() -> bool:
    """Route SIGTERM to :func:`request_drain` (chaining any previous
    handler). Returns False — leaving signal disposition untouched —
    when not on the main thread, where Python forbids ``signal.signal``.
    Idempotent: a second install keeps the first chain."""
    import signal as _signal
    global _prev_sigterm
    if threading.current_thread() is not threading.main_thread():
        return False
    current = _signal.getsignal(_signal.SIGTERM)
    if getattr(current, "_pylops_drain", False):
        return True  # already installed

    def _handler(signum, frame):
        request_drain()
        if callable(current) and current not in (
                _signal.SIG_IGN, _signal.SIG_DFL):
            current(signum, frame)

    _handler._pylops_drain = True
    _prev_sigterm = current
    _signal.signal(_signal.SIGTERM, _handler)
    return True


# ----------------------------------------------------- worker bring-up
WorkerConfig = namedtuple(
    "WorkerConfig", ["coordinator", "num_processes", "process_id",
                     "attempt", "heartbeat_path", "heartbeat_s"])
WorkerConfig.__doc__ = (
    "The supervisor-assigned identity of this worker process for the "
    "CURRENT attempt: coordinator address, (possibly shrunk) world "
    "size, rank, 0-based relaunch counter, and the heartbeat "
    "assignment. Unsupervised processes get "
    "(None, None, None, 0, None, interval).")


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def worker_config() -> WorkerConfig:
    """Read the supervisor env contract (module docstring)."""
    return WorkerConfig(
        coordinator=os.environ.get("PYLOPS_MPI_TPU_COORDINATOR") or None,
        num_processes=_env_int("PYLOPS_MPI_TPU_NUM_PROCESSES"),
        process_id=_env_int("PYLOPS_MPI_TPU_PROCESS_ID"),
        attempt=_env_int("PYLOPS_MPI_TPU_ATTEMPT") or 0,
        heartbeat_path=heartbeat_file(),
        heartbeat_s=heartbeat_interval())


# ----------------------------------------- in-place reconfiguration
# Round 13. The classic recovery ladder (supervisor kills the whole
# attempt, relaunches shrunk, workers resume FROM CHECKPOINT) pays a
# full checkpoint write+read on every failure. The in-place path keeps
# the survivors alive: the supervisor classifies the dead worker,
# writes each survivor a reconfig file naming the shrunk world, and the
# survivor — which has been banking the fused-solver carry at every
# epoch boundary (host-replicated via collectives, bounded-scratch) —
# re-forms its mesh and replans the carry onto it with
# ``parallel/reshard.place_replica``. No checkpoint I/O on the
# recovery path; the checkpoint ladder stays as the fallback whenever
# the quorum fails, the planner refuses, or the survivor itself dies
# mid-reshard (the ``faults.maybe_kill_reshard`` chaos seam).
INPLACE_ENV = "PYLOPS_MPI_TPU_INPLACE"
QUORUM_ENV = "PYLOPS_MPI_TPU_QUORUM"
RECONFIG_ENV = "PYLOPS_MPI_TPU_RECONFIG_FILE"

_IP_MODES = ("auto", "on", "off")
_warned_ip = False


class ElasticReconfig(RuntimeError):
    """The supervisor reassigned this worker to a shrunk world while a
    solve was running. Raised at the next epoch boundary; carries the
    parsed reconfig ``config`` dict so the catcher can
    :func:`apply_reconfig`, :func:`reform_mesh`, and resume from the
    banked carry (:func:`restore_carry`) — or fall back to the
    checkpoint when any of those refuse."""

    def __init__(self, config: Dict[str, Any]):
        self.config = dict(config)
        super().__init__(
            f"elastic reconfig: attempt {config.get('attempt')} world "
            f"{config.get('num_processes')} rank "
            f"{config.get('process_id')} (in-place shrink; resume from "
            "the banked carry or fall back to the checkpoint)")


def inplace_mode() -> str:
    """``PYLOPS_MPI_TPU_INPLACE`` resolved to ``auto``/``on``/``off``
    (default ``auto``; unknown values warn once and fall back —
    the watchdog knob's rule)."""
    global _warned_ip
    m = os.environ.get(INPLACE_ENV, "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in _IP_MODES:
        if not _warned_ip:
            import warnings
            warnings.warn(f"{INPLACE_ENV}={m!r} is not one of "
                          f"{_IP_MODES}; using 'auto'", stacklevel=2)
            _warned_ip = True
        m = "auto"
    return m


def reconfig_file() -> Optional[str]:
    """The reconfig path the supervisor assigned this worker (set only
    when the job was launched with ``inplace=True``), or ``None``."""
    return os.environ.get(RECONFIG_ENV) or None


def inplace_armed() -> bool:
    """``on`` → armed; ``off`` → disarmed; ``auto`` (default) → armed
    only when the supervisor assigned a reconfig file — plain library
    use never banks carries or polls for reconfigs."""
    m = inplace_mode()
    if m == "on":
        return True
    if m == "off":
        return False
    return reconfig_file() is not None


def quorum_fraction() -> float:
    """``PYLOPS_MPI_TPU_QUORUM``: the fraction of the launch world
    that must survive a failure for the in-place path to engage
    (default 0.5; clamped to (0, 1]). Below quorum the supervisor
    takes the checkpoint-relaunch ladder — too much state died to
    trust a live patch-up."""
    try:
        v = float(os.environ.get(QUORUM_ENV, "0.5"))
    except ValueError:
        v = 0.5
    return min(1.0, max(1e-9, v))


def pending_reconfig() -> Optional[Dict[str, Any]]:
    """The supervisor's reconfig assignment for this worker, parsed,
    when it names an attempt NEWER than the one this process is
    running — else ``None``. (Applying a reconfig bumps
    ``PYLOPS_MPI_TPU_ATTEMPT``, which is what marks it consumed.)"""
    path = reconfig_file()
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.loads(f.read())
    except (OSError, ValueError):
        return None  # torn write: the next poll sees the full file
    if not isinstance(doc, dict) or "attempt" not in doc:
        return None
    cur = _env_int("PYLOPS_MPI_TPU_ATTEMPT") or 0
    if int(doc["attempt"]) <= cur:
        return None
    return doc


def apply_reconfig(config: Dict[str, Any]) -> WorkerConfig:
    """Adopt a reconfig assignment: rewrite the worker env contract
    (world size, rank, attempt, coordinator) so
    :func:`worker_config` — and :func:`pending_reconfig`'s consumed
    check — reflect the shrunk world. Returns the new config."""
    os.environ["PYLOPS_MPI_TPU_NUM_PROCESSES"] = \
        str(int(config["num_processes"]))
    os.environ["PYLOPS_MPI_TPU_PROCESS_ID"] = \
        str(int(config["process_id"]))
    os.environ["PYLOPS_MPI_TPU_ATTEMPT"] = str(int(config["attempt"]))
    if config.get("coordinator"):
        os.environ["PYLOPS_MPI_TPU_COORDINATOR"] = \
            str(config["coordinator"])
    _trace.event("resilience.reconfig_applied", cat="resilience",
                 attempt=int(config["attempt"]),
                 world=int(config["num_processes"]),
                 rank=int(config["process_id"]))
    return worker_config()


def reform_mesh(cfg: WorkerConfig):
    """Re-form this survivor's mesh for the shrunk world WITHOUT a
    process restart. A one-process world gets a mesh over
    ``jax.local_devices()`` — NOT ``jax.devices()``, which still lists
    the dead peer's remote devices while the old ``jax.distributed``
    client lingers. A multi-process reform would need that client torn
    down and re-initialized, and its shutdown is a collective barrier
    that hangs when a peer is dead — so multi-survivor worlds refuse
    here and take the checkpoint-relaunch fallback (the quorum/fallback
    table, docs/robustness.md#in-place-recovery)."""
    world = cfg.num_processes or 1
    if world > 1:
        raise RuntimeError(
            "reform_mesh: re-forming a multi-process world in place "
            "needs a jax.distributed restart, whose shutdown barrier "
            "hangs while a peer is dead; fall back to the checkpoint "
            "relaunch path")
    import jax
    from jax.sharding import Mesh
    import numpy as np
    devs = jax.local_devices()
    from ..parallel.mesh import SP_AXIS
    mesh = Mesh(np.asarray(devs), (SP_AXIS,))
    _trace.event("resilience.mesh_reformed", cat="resilience",
                 world=world, n_devices=len(devs))
    return mesh


# ------------------------------------------------- survivor carry bank
# The bank holds one host-replicated snapshot of the fused-solver
# carry per tag ("cg"/"cgls"), refreshed at every epoch boundary while
# in-place recovery is armed. Vector fields are gathered to host
# through collectives (``process_allgather`` of the physical pad-to-max
# buffer, then the static unpad map) — every process holds the full
# logical value, so any survivor can replant it alone.
_BANK_LOCK = threading.Lock()
_BANK: Dict[str, Dict[str, Any]] = {}


def _host_value(arr) -> Any:
    """Host numpy copy of a (possibly multi-process-replicated) jax
    array: a non-fully-addressable input goes through the allgather
    (which returns it fully replicated), local data copies directly."""
    import numpy as np
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr))


def _host_global(darr) -> Any:
    """Host copy of a DistributedArray's logical global value, via an
    allgather when shards live on other processes."""
    import numpy as np
    phys = _host_value(darr._arr)
    if darr._even:
        return phys
    from ..parallel.partition import unpad_index_map
    idx = unpad_index_map(darr._axis_sizes, darr._s_phys)
    return np.take(phys, idx, axis=darr._axis)


def bank_carry(tag: str, carry: Dict[str, Any]) -> None:
    """Bank one epoch-boundary carry snapshot under ``tag``. Vector
    fields (DistributedArrays) are recorded as host-replicated values
    plus their layout (partition/axis/shard-count/mask); everything
    else as plain host scalars/arrays. Stacked vectors are not
    bankable — banking refuses (and in-place recovery falls back to
    the checkpoint) rather than guessing a layout."""
    import numpy as np
    from ..distributedarray import DistributedArray
    rec: Dict[str, Any] = {}
    for name, val in carry.items():
        if isinstance(val, DistributedArray):
            rec[name] = {"kind": "dist",
                         "partition": val.partition.name,
                         "axis": int(val.axis),
                         "n_shards": int(val.n_shards),
                         "mask": (tuple(val.mask)
                                  if val.mask is not None else None),
                         "value": _host_global(val)}
        elif hasattr(val, "distarrays"):  # StackedDistributedArray
            raise TypeError(
                f"bank_carry: field {name!r} is a stacked vector; "
                "in-place banking supports flat DistributedArray "
                "carries only — run with the checkpoint fallback")
        elif isinstance(val, (int, float, str, bool, type(None))):
            rec[name] = {"kind": "raw", "value": val}
        else:
            rec[name] = {"kind": "array", "value": _host_value(val)}
    with _BANK_LOCK:
        _BANK[tag] = {"wall": time.time(), "fields": rec}
    _trace.event("resilience.carry_banked", cat="resilience", tag=tag,
                 n_fields=len(rec))


def banked_carry(tag: str) -> Optional[Dict[str, Any]]:
    """The raw banked record for ``tag`` (or ``None``) — test/debug
    introspection; consumers use :func:`restore_carry`."""
    with _BANK_LOCK:
        return _BANK.get(tag)


def clear_carry(tag: Optional[str] = None) -> None:
    with _BANK_LOCK:
        if tag is None:
            _BANK.clear()
        else:
            _BANK.pop(tag, None)


def restore_carry(tag: str, mesh, budget=None, chunks=None
                  ) -> Dict[str, Any]:
    """Replant the banked carry onto ``mesh`` (the re-formed, shrunk
    mesh) through the bounded-memory resharding planner — each vector
    field via :func:`~pylops_mpi_tpu.parallel.reshard.place_replica`
    with a fresh balanced split for the new world. Raises ``KeyError``
    when nothing is banked and lets planner refusals
    (:class:`~pylops_mpi_tpu.parallel.reshard.ReshardError` — budget,
    mask, short axis) propagate: the caller's fallback is the
    checkpoint. NO checkpoint I/O happens here — that absence is
    trace-pinned by the chaos acceptance test."""
    from ..parallel import reshard as _reshard
    from ..parallel.partition import Partition
    import jax.numpy as jnp
    with _BANK_LOCK:
        bank = _BANK.get(tag)
    if bank is None:
        raise KeyError(f"restore_carry: no banked carry for tag {tag!r}")
    n_new = int(mesh.devices.size)
    state: Dict[str, Any] = {}
    for name, rec in bank["fields"].items():
        kind = rec["kind"]
        if kind == "dist":
            if rec["mask"] is not None and rec["n_shards"] != n_new:
                raise _reshard.ReshardError(
                    f"restore_carry: field {name!r} carries a mask and "
                    f"the world changed {rec['n_shards']} -> {n_new}; "
                    "masks are per-shard group colors — fall back to "
                    "the checkpoint path", 0)
            state[name] = _reshard.place_replica(
                rec["value"], mesh, Partition[rec["partition"]],
                rec["axis"],
                mask=(rec["mask"] if rec["n_shards"] == n_new else None),
                budget=(budget if budget is not None
                        else _reshard._UNSET),
                chunks=chunks)
        elif kind == "raw":
            state[name] = rec["value"]
        else:
            state[name] = jnp.asarray(rec["value"])
    _trace.event("resilience.inplace_recovery", cat="resilience",
                 tag=tag, n_fields=len(state), world_devices=n_new)
    _metrics.inc("resilience.inplace_recoveries")
    return state


def elastic_initialize() -> WorkerConfig:
    """One-call worker bring-up for supervised jobs: start the
    heartbeat, then — when this attempt's world has more than one
    process — join the ``jax.distributed`` job named by the env
    contract (under the bounded retry AND the collective watchdog via
    :func:`~pylops_mpi_tpu.parallel.mesh.initialize_multihost`).
    Single-process attempts (the shrunk mesh after every peer failed)
    skip the distributed runtime entirely and run on local devices.
    Returns the :class:`WorkerConfig` so the worker can build its
    (possibly shrunk) mesh from ``num_processes``."""
    cfg = worker_config()
    maybe_start_heartbeat()
    if cfg.num_processes is not None and cfg.num_processes > 1:
        from ..parallel.mesh import initialize_multihost
        initialize_multihost(coordinator_address=cfg.coordinator,
                             num_processes=cfg.num_processes,
                             process_id=cfg.process_id)
    _trace.event("resilience.elastic_init", cat="resilience",
                 attempt=cfg.attempt, world=cfg.num_processes or 1,
                 rank=cfg.process_id or 0)
    return cfg

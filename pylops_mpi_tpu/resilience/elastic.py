"""Worker-side elastic runtime: heartbeats and the collective watchdog.

The supervisor (:mod:`.supervisor`) can only act on what it can
observe from outside the worker process. This module is the worker's
half of that contract:

- **Heartbeats** — a daemon thread writes a small JSON beat file every
  ``PYLOPS_MPI_TPU_HEARTBEAT`` seconds (atomically: temp + replace, so
  the supervisor never reads a torn beat). The thread is independent
  of the main thread, so a worker stuck inside a fused epoch or a long
  compile still beats; the beat STOPS only when the process is truly
  wedged (SIGSTOP, runaway GC, kernel-level stall) or dead — exactly
  the states the supervisor classifies as ``stale_heartbeat``.
- **The collective watchdog** — blocking host-side phases that wait on
  *peers* (``jax.distributed`` bring-up, multi-host checkpoint
  save/load) hang forever when one peer is gone; a heartbeat cannot
  catch this, because the *stuck* worker's beat thread keeps running.
  :func:`watched_call` runs such a phase in a worker thread with a
  deadline from the central :data:`~pylops_mpi_tpu.diagnostics.\
profiler.STAGE_BUDGETS` table (the same machinery the harvest ladder's
  :class:`~pylops_mpi_tpu.diagnostics.profiler.DeadlineRunner` uses)
  and raises a classified :class:`WatchdogTimeout` instead of blocking
  — the worker exits nonzero, the supervisor reaps it and relaunches
  the job on the surviving host set.

Gating: the watchdog defaults to ``auto`` — armed only when the
process is SUPERVISED (``PYLOPS_MPI_TPU_HEARTBEAT_FILE`` is set by the
supervisor), so plain library use is bit-for-bit unchanged (no extra
threads, no trace events; the off-mode pins in
``tests/test_supervisor.py`` hold this). ``PYLOPS_MPI_TPU_WATCHDOG=on``
arms it unconditionally; ``off`` disarms even under supervision.

The env contract (set by :func:`.supervisor.launch_job`, read by
:func:`worker_config` / :func:`elastic_initialize`):

==================================  ====================================
``PYLOPS_MPI_TPU_COORDINATOR``      ``host:port`` of the jax.distributed
                                    coordinator for THIS attempt
``PYLOPS_MPI_TPU_NUM_PROCESSES``    world size of this attempt (shrinks
                                    after a failure)
``PYLOPS_MPI_TPU_PROCESS_ID``       this worker's rank in the attempt
``PYLOPS_MPI_TPU_ATTEMPT``          0-based relaunch counter
``PYLOPS_MPI_TPU_HEARTBEAT_FILE``   where to write beats
``PYLOPS_MPI_TPU_HEARTBEAT``        beat interval, seconds
==================================  ====================================
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import namedtuple
from typing import Any, Callable, Dict, Optional

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from ..diagnostics.profiler import STAGE_BUDGETS

__all__ = ["heartbeat_interval", "heartbeat_file", "HeartbeatWriter",
           "start_heartbeat", "stop_heartbeat", "maybe_start_heartbeat",
           "read_heartbeat", "WatchdogTimeout", "watchdog_mode",
           "watchdog_enabled", "watchdog_timeout", "watched_call",
           "WorkerConfig", "worker_config", "elastic_initialize",
           "request_drain", "drain_requested", "reset_drain",
           "install_sigterm_drain"]


# ------------------------------------------------------------ heartbeats
def heartbeat_interval() -> float:
    """``PYLOPS_MPI_TPU_HEARTBEAT`` beat interval in seconds (default
    1.0; floored at 0.05 so a typo cannot busy-spin the writer)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_HEARTBEAT", "1.0"))
    except ValueError:
        v = 1.0
    return max(0.05, v)


def heartbeat_file() -> Optional[str]:
    """``PYLOPS_MPI_TPU_HEARTBEAT_FILE`` — the beat path the supervisor
    assigned this worker, or ``None`` when unsupervised."""
    return os.environ.get("PYLOPS_MPI_TPU_HEARTBEAT_FILE") or None


class HeartbeatWriter(threading.Thread):
    """Daemon thread writing ``{"pid", "seq", "wall", "mono"}`` —
    plus ``"metrics"`` (the live registry snapshot,
    ``diagnostics/metrics.py``) when ``PYLOPS_MPI_TPU_METRICS=on`` —
    to ``path`` every ``interval`` seconds, atomically (pid-suffixed
    temp + ``os.replace``), so the supervisor's reader can never
    observe a torn beat. ``stop()`` is idempotent and joins the
    thread."""

    def __init__(self, path: str, interval: float):
        super().__init__(name="pylops-heartbeat", daemon=True)
        self.path = os.path.abspath(path)
        self.interval = float(interval)
        self.seq = 0
        # NOT named _stop: Thread.join() calls a private self._stop()
        self._halt = threading.Event()

    def beat(self) -> None:
        self.seq += 1
        doc = {"pid": os.getpid(), "seq": self.seq,
               "wall": time.time(), "mono": time.monotonic()}
        # live per-worker PROGRESS, not just liveness (ISSUE 10): the
        # supervisor's read_heartbeat sees the current metrics registry
        # in every beat. One env lookup when metrics are off.
        if _metrics.metrics_enabled():
            try:
                doc["metrics"] = _metrics.snapshot()
            except Exception:
                pass  # a metrics bug must not kill the beat
        payload = json.dumps(doc)
        tmp = self.path + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a full disk must not kill the worker via its beat

    def run(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.beat()  # first beat immediately: bring-up counts as alive
        while not self._halt.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._halt.set()
        if self.is_alive():
            self.join(timeout=5.0)


_HB_LOCK = threading.Lock()
_WRITER: Optional[HeartbeatWriter] = None


def start_heartbeat(path: Optional[str] = None,
                    interval: Optional[float] = None
                    ) -> Optional[HeartbeatWriter]:
    """Start (or return the already-running) heartbeat writer. With no
    ``path`` argument the env contract decides; returns ``None`` when
    no path is configured — the unsupervised no-op."""
    global _WRITER
    path = path or heartbeat_file()
    if path is None:
        return None
    with _HB_LOCK:
        if _WRITER is not None and _WRITER.is_alive():
            return _WRITER
        _WRITER = HeartbeatWriter(
            path, heartbeat_interval() if interval is None else interval)
        _WRITER.start()
        return _WRITER


def maybe_start_heartbeat() -> Optional[HeartbeatWriter]:
    """Env-driven auto-start used by long-running entry points (the
    segmented solvers): one dict lookup when unsupervised, the running
    writer when supervised. Safe to call from anywhere, any number of
    times."""
    if heartbeat_file() is None:
        return None
    return start_heartbeat()


def stop_heartbeat() -> None:
    global _WRITER
    with _HB_LOCK:
        if _WRITER is not None:
            _WRITER.stop()
            _WRITER = None


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """Supervisor-side beat reader: the parsed beat dict, or ``None``
    when the file is missing or (transiently) unparseable."""
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


# ------------------------------------------------------------- watchdog
class WatchdogTimeout(RuntimeError):
    """A watched host-side phase blew its deadline — a hung peer, not
    a slow computation. Carries ``stage`` and ``timeout_s`` so the
    supervisor's failure record (and the trace event) name the phase
    that wedged."""

    def __init__(self, stage: str, timeout_s: float):
        self.stage = stage
        self.timeout_s = float(timeout_s)
        super().__init__(
            f"watchdog: stage {stage!r} still blocked after "
            f"{timeout_s:.0f}s — a peer is likely hung or gone; "
            "exiting so the supervisor can relaunch on the surviving "
            "hosts (docs/robustness.md#collective-watchdog)")


_WD_MODES = ("auto", "on", "off")
_warned_wd = False


def watchdog_mode() -> str:
    """``PYLOPS_MPI_TPU_WATCHDOG`` resolved to ``auto``/``on``/``off``
    (default ``auto``; unknown values warn once and fall back to
    ``auto`` — same rule as the overlap/trace knobs)."""
    global _warned_wd
    m = os.environ.get("PYLOPS_MPI_TPU_WATCHDOG", "auto").strip().lower()
    if m in ("", "none", "default"):
        m = "auto"
    if m not in _WD_MODES:
        if not _warned_wd:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_WATCHDOG={m!r} is not one of "
                f"{_WD_MODES}; using 'auto'", stacklevel=2)
            _warned_wd = True
        m = "auto"
    return m


def watchdog_enabled() -> bool:
    """``on`` → armed; ``off`` → disarmed; ``auto`` (default) → armed
    only when this process is supervised (a heartbeat file is
    configured) — plain library use never grows watchdog threads."""
    m = watchdog_mode()
    if m == "on":
        return True
    if m == "off":
        return False
    return heartbeat_file() is not None


def watchdog_timeout(stage: str, default: Optional[float] = None) -> float:
    """Deadline for one watched stage: the global override
    ``PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT`` when set, else the stage's row
    in the central ``STAGE_BUDGETS`` table (``tpu`` column), else
    ``default`` (300 s)."""
    raw = os.environ.get("PYLOPS_MPI_TPU_WATCHDOG_TIMEOUT")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    row = STAGE_BUDGETS.get(stage)
    if row and row.get("tpu"):
        return float(row["tpu"])
    return 300.0 if default is None else float(default)


_wd_tls = threading.local()  # reentrancy: nested watched phases run direct


def watched_call(fn: Callable, *args, stage: str,
                 timeout_s: Optional[float] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under the collective watchdog.

    Disarmed (the default, unsupervised case) this is a direct call —
    zero threads, zero trace events, bit-identical behavior. Armed, the
    call runs in a daemon worker thread with deadline
    ``timeout_s`` (default: :func:`watchdog_timeout` for ``stage``);
    if the deadline passes, a ``resilience.watchdog`` trace event is
    emitted and :class:`WatchdogTimeout` is raised in the CALLER —
    the blocked thread is left behind (Python cannot kill it), which
    is exactly right for a supervised worker: the raise unwinds to a
    nonzero exit and the supervisor reaps the whole process. Nested
    watched phases (checkpoint-inside-harvest) run direct under the
    outer deadline instead of stacking threads."""
    if not watchdog_enabled() or getattr(_wd_tls, "active", False):
        return fn(*args, **kwargs)
    deadline = watchdog_timeout(stage) if timeout_s is None \
        else float(timeout_s)
    out: "queue.Queue" = queue.Queue(maxsize=1)

    def runner():
        _wd_tls.active = True
        try:
            out.put((True, fn(*args, **kwargs)))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            out.put((False, e))

    t = threading.Thread(target=runner, daemon=True,
                         name=f"pylops-watchdog-{stage}")
    with _trace.span("resilience.watchdog", cat="resilience",
                     stage=stage, timeout_s=deadline):
        t.start()
        try:
            ok, payload = out.get(timeout=deadline)
        except queue.Empty:
            _trace.event("resilience.watchdog_timeout", cat="resilience",
                         stage=stage, timeout_s=deadline)
            raise WatchdogTimeout(stage, deadline) from None
    if ok:
        return payload
    raise payload


# -------------------------------------------------------- drain signal
# SIGTERM semantics for serve-forever workers (serving/service.py):
# the deployment's stop is a DRAIN, not a kill — finish in-flight
# batches, refuse new claims, then exit 0. A signal handler can only
# run on the main thread; the serving loops poll this event instead.
_DRAIN = threading.Event()
_prev_sigterm: Any = None


def request_drain() -> None:
    """Ask this process's serving loops to drain and exit (idempotent;
    also callable directly, e.g. from tests or an admin endpoint)."""
    if not _DRAIN.is_set():
        _DRAIN.set()
        _trace.event("resilience.drain_requested", cat="resilience",
                     pid=os.getpid())
        _metrics.inc("serve.drain_requests")


def drain_requested() -> bool:
    """Whether a drain has been requested for this process."""
    return _DRAIN.is_set()


def reset_drain() -> None:
    """Clear the drain flag (test isolation; a served process never
    un-drains)."""
    _DRAIN.clear()


def install_sigterm_drain() -> bool:
    """Route SIGTERM to :func:`request_drain` (chaining any previous
    handler). Returns False — leaving signal disposition untouched —
    when not on the main thread, where Python forbids ``signal.signal``.
    Idempotent: a second install keeps the first chain."""
    import signal as _signal
    global _prev_sigterm
    if threading.current_thread() is not threading.main_thread():
        return False
    current = _signal.getsignal(_signal.SIGTERM)
    if getattr(current, "_pylops_drain", False):
        return True  # already installed

    def _handler(signum, frame):
        request_drain()
        if callable(current) and current not in (
                _signal.SIG_IGN, _signal.SIG_DFL):
            current(signum, frame)

    _handler._pylops_drain = True
    _prev_sigterm = current
    _signal.signal(_signal.SIGTERM, _handler)
    return True


# ----------------------------------------------------- worker bring-up
WorkerConfig = namedtuple(
    "WorkerConfig", ["coordinator", "num_processes", "process_id",
                     "attempt", "heartbeat_path", "heartbeat_s"])
WorkerConfig.__doc__ = (
    "The supervisor-assigned identity of this worker process for the "
    "CURRENT attempt: coordinator address, (possibly shrunk) world "
    "size, rank, 0-based relaunch counter, and the heartbeat "
    "assignment. Unsupervised processes get "
    "(None, None, None, 0, None, interval).")


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def worker_config() -> WorkerConfig:
    """Read the supervisor env contract (module docstring)."""
    return WorkerConfig(
        coordinator=os.environ.get("PYLOPS_MPI_TPU_COORDINATOR") or None,
        num_processes=_env_int("PYLOPS_MPI_TPU_NUM_PROCESSES"),
        process_id=_env_int("PYLOPS_MPI_TPU_PROCESS_ID"),
        attempt=_env_int("PYLOPS_MPI_TPU_ATTEMPT") or 0,
        heartbeat_path=heartbeat_file(),
        heartbeat_s=heartbeat_interval())


def elastic_initialize() -> WorkerConfig:
    """One-call worker bring-up for supervised jobs: start the
    heartbeat, then — when this attempt's world has more than one
    process — join the ``jax.distributed`` job named by the env
    contract (under the bounded retry AND the collective watchdog via
    :func:`~pylops_mpi_tpu.parallel.mesh.initialize_multihost`).
    Single-process attempts (the shrunk mesh after every peer failed)
    skip the distributed runtime entirely and run on local devices.
    Returns the :class:`WorkerConfig` so the worker can build its
    (possibly shrunk) mesh from ``num_processes``."""
    cfg = worker_config()
    maybe_start_heartbeat()
    if cfg.num_processes is not None and cfg.num_processes > 1:
        from ..parallel.mesh import initialize_multihost
        initialize_multihost(coordinator_address=cfg.coordinator,
                             num_processes=cfg.num_processes,
                             process_id=cfg.process_id)
    _trace.event("resilience.elastic_init", cat="resilience",
                 attempt=cfg.attempt, world=cfg.num_processes or 1,
                 rank=cfg.process_id or 0)
    return cfg

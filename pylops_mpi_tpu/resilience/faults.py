"""Fault-injection (chaos) seams.

Every recovery path in the resilience layer is only as real as the
fault that exercises it. This module is the controlled way to break
things, used by the chaos suite (``tests/test_resilience.py``) to prove
each detector and recovery end to end:

- **In-loop NaN injection** (:func:`arm`, kind ``"nan"``): the guarded
  fused builders consult :func:`armed` at trace time and, when a fault
  is armed, multiply the first operator application of the loop body by
  ``where(iiter == k, NaN, 1)`` — a NaN lands in the matvec result at
  exactly the chosen iteration, the way a flaky interconnect or a DMA
  bit-flip would deliver one. Nothing is traced when nothing is armed
  (the bit-identity pins stay valid), and the fused-solver cache keys
  on :func:`fault_signature` so a poisoned executable can never be
  replayed for a clean solve.
- **In-loop stall injection** (kind ``"stall"``): zeroes the step
  scalar from the chosen iteration on — the recurrence freezes at a
  non-converged residual, which is exactly the signature the
  stagnation detector must catch.
- **Plan-cache corruption** (:func:`corrupt_plan_cache`): truncates /
  garbles a tuning-cache JSON mid-file, the artifact a killed writer
  would have left before the atomic-rename hardening. ``tuning/cache``
  must degrade to cost-model plans, never raise.
- **Flaky callables** (:func:`flaky`): wraps a function to raise for
  its first N calls (default ``TimeoutError`` — the simulated
  collective/coordinator timeout), the probe for
  :mod:`pylops_mpi_tpu.resilience.retry` and the multihost
  ``jax.distributed`` init path.

Faults are armed per-process and (by default) **one-shot**: the first
guarded solve that traces consumes the fault, so a restart ladder sees
the fault exactly once — the injected-breakdown-then-clean-restart
scenario of the ISSUE 6 acceptance test.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

__all__ = ["arm", "disarm", "armed", "consume", "fault_signature",
           "inject_nan", "inject_stall", "host_stall",
           "corrupt_plan_cache", "flaky",
           "maybe_kill_reshard", "reset_reshard_steps", "reshard_steps",
           "maybe_kill_spill", "reset_spill_steps", "spill_steps"]

_LOCK = threading.Lock()
_ARMED: Optional[Dict] = None
_KINDS = ("nan", "stall")

# ------------------------------------------- kill-mid-reshard seam
# Round 13: the resharding planner calls :func:`maybe_kill_reshard`
# between every plan step. With PYLOPS_MPI_TPU_FAULT_KILL_RESHARD=<N>
# set, the process SIGKILLs itself when the process-global step counter
# reaches N (1-based) — a worker dying mid-reshard, the scenario the
# in-place recovery path must survive by falling back to the
# checkpoint. Unset (the default) the seam is a counter bump only.
_RESHARD_STEPS = {"count": 0}
KILL_RESHARD_ENV = "PYLOPS_MPI_TPU_FAULT_KILL_RESHARD"


def reset_reshard_steps() -> None:
    with _LOCK:
        _RESHARD_STEPS["count"] = 0


def reshard_steps() -> int:
    """Planner steps executed in this process since the last reset."""
    with _LOCK:
        return _RESHARD_STEPS["count"]


def maybe_kill_reshard() -> None:
    """Advance the reshard step counter; SIGKILL this process when it
    reaches ``PYLOPS_MPI_TPU_FAULT_KILL_RESHARD`` (1-based). SIGKILL —
    not an exception — because the fault being rehearsed is a dead
    worker, and nothing (atexit, finally blocks, checkpoint flushes)
    must get a chance to tidy up."""
    with _LOCK:
        _RESHARD_STEPS["count"] += 1
        count = _RESHARD_STEPS["count"]
    import os
    raw = os.environ.get(KILL_RESHARD_ENV, "").strip()
    if not raw:
        return
    if count >= int(raw):
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


# --------------------------------------------- kill-mid-spill seam
# Round 14: the host-staging executor (parallel/spill.py) calls
# :func:`maybe_kill_spill` once per ``host_stage`` step. With
# PYLOPS_MPI_TPU_FAULT_KILL_SPILL=<N> set, the process SIGKILLs itself
# when the process-global step counter reaches N (1-based) — a worker
# dying mid-spill, which the checkpoint-relaunch ladder must survive
# exactly as it survives a kill mid-reshard. Unset (the default) the
# seam is a counter bump only.
_SPILL_STEPS = {"count": 0}
KILL_SPILL_ENV = "PYLOPS_MPI_TPU_FAULT_KILL_SPILL"


def reset_spill_steps() -> None:
    with _LOCK:
        _SPILL_STEPS["count"] = 0


def spill_steps() -> int:
    """Host-stage steps executed in this process since the last reset."""
    with _LOCK:
        return _SPILL_STEPS["count"]


def maybe_kill_spill() -> None:
    """Advance the host-stage step counter; SIGKILL this process when
    it reaches ``PYLOPS_MPI_TPU_FAULT_KILL_SPILL`` (1-based). SIGKILL —
    not an exception — for the same reason as
    :func:`maybe_kill_reshard`: the rehearsed fault is a dead worker,
    and nothing must get a chance to tidy up."""
    with _LOCK:
        _SPILL_STEPS["count"] += 1
        count = _SPILL_STEPS["count"]
    import os
    raw = os.environ.get(KILL_SPILL_ENV, "").strip()
    if not raw:
        return
    if count >= int(raw):
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


def arm(kind: str, iteration: int, once: bool = True) -> None:
    """Arm an in-loop fault: ``kind="nan"`` poisons the first operator
    application of the loop body at ``iteration`` (0-based body-entry
    count); ``kind="stall"`` zeroes the step scalar from ``iteration``
    on. ``once=True`` (default) disarms after the next guarded solve
    consumes it."""
    if kind not in _KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; "
                         f"expected one of {_KINDS}")
    if iteration < 0:
        raise ValueError(f"iteration must be >= 0, got {iteration}")
    global _ARMED
    with _LOCK:
        _ARMED = {"kind": kind, "iteration": int(iteration),
                  "once": bool(once)}


def disarm() -> None:
    global _ARMED
    with _LOCK:
        _ARMED = None


def armed() -> Optional[Dict]:
    """The armed fault spec (a copy), or ``None``."""
    with _LOCK:
        return dict(_ARMED) if _ARMED else None


def consume() -> Optional[Dict]:
    """Read-and-maybe-disarm: the guarded solver entry points call this
    ONCE per solve, before building the fused program — the returned
    spec parameterizes that program, and a one-shot fault is disarmed
    so the next solve (e.g. the restart after the injected breakdown)
    traces clean."""
    global _ARMED
    with _LOCK:
        spec = dict(_ARMED) if _ARMED else None
        if spec and spec.get("once"):
            _ARMED = None
    return spec


def fault_signature(spec: Optional[Dict] = None):
    """Hashable compile-relevant fault state for the fused-solver
    cache key (same pattern as the telemetry/donation gates)."""
    if spec is None:
        spec = armed()
    if not spec:
        return ("faults", None)
    return ("faults", spec["kind"], spec["iteration"])


# ------------------------------------------------ traced injection ops
def inject_nan(v, iiter, at: int):
    """Multiply a (possibly stacked) distributed vector by
    ``where(iiter == at, NaN, 1)`` — traced into the guarded loop body
    at the operator-apply seam. The scalar is real, so complex carries
    keep their dtype (solvers/basic.py ``_step_scalar`` promotion
    rule)."""
    import jax.numpy as jnp
    import numpy as np
    dt = np.dtype(v.dtype)
    sdt = np.finfo(dt).dtype if jnp.issubdtype(dt, jnp.complexfloating) \
        else dt
    scale = jnp.where(jnp.asarray(iiter) == at,
                      jnp.asarray(jnp.nan, dtype=sdt),
                      jnp.asarray(1.0, dtype=sdt))
    return v * scale


def inject_stall(a, iiter, at: int):
    """Zero the step scalar from iteration ``at`` on: the iterate and
    residual stop moving while the loop keeps spinning — the
    stagnation detector's target signature."""
    import jax.numpy as jnp
    return jnp.where(jnp.asarray(iiter) >= at, jnp.zeros_like(a), a)


# -------------------------------------------------- host-side chaos
def host_stall(seconds: float) -> None:
    """Block THIS process for ``seconds`` — the straggler injection of
    the fleet-observability acceptance (ISSUE 10): one rank sleeps
    between collective dispatches so the cross-worker trace aggregation
    (``diagnostics/aggregate.py``) must attribute the resulting
    per-collective skew to it. Distinct from ``kind="stall"``
    (in-loop step zeroing, which burns iterations, not wall clock):
    collective-entry skew measures wall clock."""
    import time
    time.sleep(max(0.0, float(seconds)))


def corrupt_plan_cache(path: str, mode: str = "truncate") -> None:
    """Damage a tuning-cache JSON the way a killed writer or a bad
    disk would: ``truncate`` cuts the file mid-object, ``garbage``
    replaces it with non-JSON bytes, ``schema`` rewrites it with a
    wrong schema version. ``tuning/cache.load_plans`` must treat every
    variant as a logged miss."""
    import json
    import os
    if mode == "truncate":
        with open(path, "r+") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.truncate(max(1, size // 2))
    elif mode == "garbage":
        with open(path, "w") as f:
            f.write("\x00\xff not json at all {{{")
    elif mode == "schema":
        with open(path, "w") as f:
            json.dump({"schema": -1, "plans": {}}, f)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


def flaky(fn: Callable, failures: int,
          exc: Callable[[], BaseException] = None) -> Callable:
    """Wrap ``fn`` to raise for its first ``failures`` calls, then
    delegate — the simulated collective/coordinator timeout. ``exc``
    builds the exception (default ``TimeoutError``). The wrapper
    exposes ``.calls`` for assertions."""
    if exc is None:
        exc = lambda: TimeoutError("injected timeout")  # noqa: E731
    state = {"calls": 0}

    def wrapper(*args, **kwargs):
        state["calls"] += 1
        wrapper.calls = state["calls"]
        if state["calls"] <= failures:
            raise exc()
        return fn(*args, **kwargs)

    wrapper.calls = 0
    return wrapper

"""Solver status word + in-loop guard gating.

The fused CG/CGLS/ISTA/FISTA solvers run their whole iteration as one
``lax.while_loop`` — which also means a numerical breakdown (NaN from a
flaky interconnect, a bf16 denominator underflow, a stalled recurrence)
is invisible until the loop burns through every remaining iteration and
returns garbage. The resilience layer (ISSUE 6) adds a **status word**
to the fused carries, computed entirely from the recurrence scalars the
loops already hold — zero host callbacks, pinned by
``utils/hlo.assert_no_host_callbacks`` in guards-on mode:

- ``CONVERGED`` / ``MAXITER`` — the two normal exits, resolved on
  device after the loop.
- ``BREAKDOWN`` — NaN/Inf in a recurrence scalar (``k``, step ``a``,
  momentum ``b``, sparse cost) or a denominator underflow (``kold`` or
  ``qᵀq`` collapsing to 0 turns the next ratio into Inf). The loop
  exits on the NEXT ``cond`` evaluation and the carry keeps the **last
  finite iterate**: the poisoned update is rejected with a
  ``jnp.where`` select, so ``resilient_solve`` can restart from it.
  The s-step CA engine's monomial-basis conditioning guard
  (solvers/ca.py) speaks the same word: a Gram-pivot breakdown sets
  ``BREAKDOWN`` and the driver continues under the pipelined engine
  from that last finite iterate.
- ``STAGNATION`` — the best residual norm has not improved for
  ``PYLOPS_MPI_TPU_GUARD_STALL`` consecutive iterations (the
  machine-precision freeze documented in ``solvers/basic._mp_floor``
  is excluded — a solve parked at the floor is done, not sick).

Gating — ``PYLOPS_MPI_TPU_GUARDS``:

- ``off`` (default): the fused builders trace EXACTLY the pre-guard
  program — bit-identical lowered HLO, pinned by the resilience suite.
- ``on``: the guard carries and selects are traced in; the solve can
  exit early with a diagnosable status.

The public ``cg``/``cgls``/``ista``/``fista`` wrappers keep their
return signatures in both modes; the status of the most recent guarded
solve is published here (:func:`record` / :func:`last_status`) and as a
``solver.status`` trace event, and the guarded entry points
(``solvers.basic.cg_guarded`` etc.) return the code explicitly for the
:func:`pylops_mpi_tpu.resilience.resilient_solve` driver.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace

__all__ = ["RUNNING", "CONVERGED", "MAXITER", "BREAKDOWN", "STAGNATION",
           "STATUS_NAMES", "status_name", "guards_mode", "guards_enabled",
           "stall_window", "guards_signature", "record", "record_columns",
           "last_status", "clear_statuses"]

# in-carry status word values (int32 scalars inside the while_loop)
RUNNING = 0
CONVERGED = 1
MAXITER = 2
BREAKDOWN = 3
STAGNATION = 4

STATUS_NAMES = {RUNNING: "running", CONVERGED: "converged",
                MAXITER: "maxiter", BREAKDOWN: "breakdown",
                STAGNATION: "stagnation"}

_warned_mode = False


def status_name(code) -> str:
    """Human name for a status code (unknown codes print as
    ``status<code>`` rather than raising — a diagnostic must never
    crash the thing it is diagnosing)."""
    return STATUS_NAMES.get(int(code), f"status{int(code)}")


def guards_mode() -> str:
    """``PYLOPS_MPI_TPU_GUARDS`` resolved to ``off``/``on`` (unknown
    values fall back to ``off`` with a one-time warning — a typo in a
    CI matrix must not silently change traced programs)."""
    global _warned_mode
    m = os.environ.get("PYLOPS_MPI_TPU_GUARDS", "off").strip().lower()
    if m in ("", "0", "none", "default"):
        m = "off"
    if m in ("1", "true"):
        m = "on"
    if m not in ("off", "on"):
        if not _warned_mode:
            import warnings
            warnings.warn(
                f"PYLOPS_MPI_TPU_GUARDS={m!r} is not one of "
                "['off', 'on']; guards stay off", stacklevel=2)
            _warned_mode = True
        m = "off"
    return m


def guards_enabled(user=None) -> bool:
    """Resolve the guard gate: a per-call ``guards=`` kwarg
    (``True``/``False``; ``None`` defers to the env) beats
    ``PYLOPS_MPI_TPU_GUARDS`` — same precedence rule as the overlap
    and precision seams."""
    if isinstance(user, bool):
        return user
    if user is not None:
        raise ValueError(f"guards={user!r}: expected True, False or None")
    return guards_mode() == "on"


def stall_window() -> int:
    """Stagnation window ``PYLOPS_MPI_TPU_GUARD_STALL`` (default 50,
    floored at 2 — a window of 1 would flag every non-monotone CG
    step, and CG residual norms are legitimately non-monotone)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_GUARD_STALL", "50"))
    except ValueError:
        v = 50
    return max(2, v)


def guards_signature(user=None):
    """Compile-relevant guard state for the fused-solver cache key: a
    program traced with the guard carries embedded must never be
    reused when the gate is off (and vice versa), and a different
    stall window is a different traced constant."""
    on = guards_enabled(user)
    return ("guards", on, stall_window() if on else None)


# ------------------------------------------------- last-status channel
# The public solver wrappers keep their return signatures when guards
# are on; the status word of the most recent guarded solve per solver
# name lands here (and as a solver.status trace event).
_LOCK = threading.Lock()
_LAST: Dict[str, Dict] = {}


def record(solver: str, code: int, iiter: int) -> None:
    info = {"status": int(code), "status_name": status_name(code),
            "iiter": int(iiter)}
    with _LOCK:
        _LAST[solver] = info
    # fleet metrics: guard verdicts per kind (ISSUE 10)
    _metrics.inc(f"guards.{solver}.{status_name(code)}")
    _trace.event("solver.status", cat="resilience", solver=solver, **info)


def record_columns(solver: str, codes, iiter: int) -> None:
    """Per-column status words of a guarded BLOCK solve (one code per
    RHS column; solvers/block.py). ``status`` keeps the WORST column —
    the scalar consumers (resilient_solve triage, the trace viewer)
    see a block solve degrade exactly like a single-RHS one — and the
    full vector lands under ``"columns"``/``"column_names"``."""
    codes = [int(c) for c in codes]
    worst = max(codes) if codes else CONVERGED
    info = {"status": worst, "status_name": status_name(worst),
            "iiter": int(iiter), "columns": codes,
            "column_names": [status_name(c) for c in codes]}
    with _LOCK:
        _LAST[solver] = info
    for c in codes:  # per-COLUMN verdicts: K columns, K counts
        _metrics.inc(f"guards.{solver}.{status_name(c)}")
    _trace.event("solver.status", cat="resilience", solver=solver, **info)


def last_status(solver: str) -> Optional[Dict]:
    """Status record of the most recent guarded solve for ``solver``
    (``"cg"``/``"cgls"``/``"ista"``/``"fista"``), or ``None`` if no
    guarded solve has run."""
    with _LOCK:
        info = _LAST.get(solver)
        return dict(info) if info else None


def clear_statuses() -> None:
    """Test-isolation helper."""
    with _LOCK:
        _LAST.clear()

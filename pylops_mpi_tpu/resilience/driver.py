"""``resilient_solve`` — graceful degradation by precision escalation.

The narrow-storage fast path (ops/_precision.py, ISSUE 2) buys its
HBM-roofline wins with headroom: a bf16-stored operator can underflow a
denominator or overflow a recurrence scalar that the same system at f32
absorbs. The guarded fused solvers (ISSUE 6, solvers/basic.py) turn
that event into a ``BREAKDOWN`` status and a **last finite iterate**;
this driver turns it into a finished solve:

1. run the guarded fused solver at the current precision rung;
2. on ``breakdown``/``stagnation``, rebuild the operator ONE rung wider
   (``ops/_precision.escalate_dtype``: bf16 → f32 → f64, c64 → c128)
   and restart **from the last finite iterate** with the remaining
   iteration budget;
3. bounded by ``max_restarts`` (``PYLOPS_MPI_TPU_RESTARTS``, default
   2); every restart emits a structured ``solver.restart`` trace event.

The caller supplies an **operator factory** ``make_op(compute_dtype)``
(``compute_dtype=None`` on the first rung — the operator resolves the
env precision policy itself, exactly as a direct construction would),
because operators capture their storage dtype at construction; passing
a plain operator instead disables escalation (restarts are then only
possible for ``stagnation``, at the same precision, which is usually
futile — the driver stops instead).

Tuned plans survive restarts for free: the plan cache key
(tuning/plan.py) carries the dtype, so each rung replays its own plan
and invalidates nothing.
"""

from __future__ import annotations

import os
from collections import namedtuple
from typing import Callable, Optional, Sequence, Union

import numpy as np

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from . import status as _rstatus

__all__ = ["resilient_solve", "ResilientResult", "max_restarts_default"]

ResilientResult = namedtuple(
    "ResilientResult",
    ["x", "status", "iiter", "restarts", "compute_dtype", "cost",
     "attempts"])
ResilientResult.__doc__ = (
    "Outcome of a resilient solve: the final iterate, the final status "
    "NAME (``converged``/``maxiter``/``breakdown``/``stagnation``), "
    "total iterations across every attempt, the restart count, the "
    "compute dtype of the last attempt, its cost history, and a "
    "per-attempt record list (precision, iterations, status).")

_SOLVERS = ("cg", "cgls", "ista", "fista")


def max_restarts_default() -> int:
    """``PYLOPS_MPI_TPU_RESTARTS`` (default 2, floored at 0)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_RESTARTS", "2"))
    except ValueError:
        v = 2
    return max(0, v)


def _run_guarded(solver: str, Op, y, x, niter: int, tol: float,
                 damp: float, solver_kwargs: dict):
    from ..solvers.basic import cg_guarded, cgls_guarded
    from ..solvers.sparsity import ista_guarded, fista_guarded
    if solver == "cg":
        xn, it, cost, code = cg_guarded(Op, y, x, niter=niter, tol=tol)
    elif solver == "cgls":
        xn, it, cost, _, _, code = cgls_guarded(
            Op, y, x, niter=niter, damp=damp, tol=tol,
            normal=bool(solver_kwargs.get("normal", False)))
    else:
        if x is None:
            from ..solvers.basic import _zero_like_model
            x = _zero_like_model(Op, y)
        fn = ista_guarded if solver == "ista" else fista_guarded
        kw = {k: v for k, v in solver_kwargs.items() if k != "normal"}
        xn, it, cost, code = fn(Op, y, x, niter=niter, tol=tol, **kw)
    return xn, it, cost, code


def resilient_solve(make_op: Union[Callable, object], y, x0=None, *,
                    solver: str = "cgls", niter: int = 100,
                    tol: float = 1e-4, damp: float = 0.0,
                    max_restarts: Optional[int] = None,
                    precisions: Optional[Sequence] = None,
                    **solver_kwargs) -> ResilientResult:
    """Solve with in-loop breakdown detection and bounded
    precision-escalation restarts (module docstring).

    ``make_op`` — operator factory ``make_op(compute_dtype)`` (or a
    plain operator, escalation disabled). ``precisions`` — explicit
    rung sequence of compute dtypes for attempts after the first
    (default: one :func:`~pylops_mpi_tpu.ops._precision.escalate_dtype`
    rung per restart). Extra ``solver_kwargs`` reach the guarded sparse
    solvers (``eps``, ``alpha``, ``threshkind``, ...) or CGLS
    (``normal``)."""
    from ..ops._precision import effective_compute_dtype, escalate_dtype
    if solver not in _SOLVERS:
        raise ValueError(f"solver={solver!r}: expected one of {_SOLVERS}")
    if max_restarts is None:
        max_restarts = max_restarts_default()
    factory = make_op if callable(make_op) else None
    ladder = list(precisions) if precisions is not None else None

    x = x0
    cdt = None  # first rung: the operator's own (policy-resolved) dtype
    restarts = 0
    total_iiter = 0
    attempts = []
    cost = None
    while True:
        Op = factory(cdt) if factory is not None else make_op
        eff = effective_compute_dtype(Op)
        remaining = max(1, niter - total_iiter)
        x, it, cost, code = _run_guarded(solver, Op, y, x, remaining,
                                         tol, damp, solver_kwargs)
        total_iiter += it
        attempts.append({"compute_dtype": eff.name, "iiter": it,
                         "status": _rstatus.status_name(code)})
        if code in (_rstatus.CONVERGED, _rstatus.MAXITER):
            break
        # breakdown / stagnation: escalate one rung and restart from
        # the last finite iterate
        if ladder is not None:
            nxt = np.dtype(ladder.pop(0)) if ladder else None
        else:
            nxt = escalate_dtype(eff)
        if factory is None or nxt is None or restarts >= max_restarts:
            break
        restarts += 1
        _metrics.inc(f"solver.{solver}.restarts")
        _trace.event("solver.restart", cat="resilience", solver=solver,
                     status=_rstatus.status_name(code),
                     at_iter=total_iiter, restart=restarts,
                     from_dtype=eff.name, to_dtype=nxt.name)
        cdt = nxt
    return ResilientResult(x=x, status=_rstatus.status_name(code),
                           iiter=total_iiter, restarts=restarts,
                           compute_dtype=eff.name, cost=cost,
                           attempts=attempts)

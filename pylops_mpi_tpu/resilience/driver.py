"""``resilient_solve`` — graceful degradation by precision escalation.

The narrow-storage fast path (ops/_precision.py, ISSUE 2) buys its
HBM-roofline wins with headroom: a bf16-stored operator can underflow a
denominator or overflow a recurrence scalar that the same system at f32
absorbs. The guarded fused solvers (ISSUE 6, solvers/basic.py) turn
that event into a ``BREAKDOWN`` status and a **last finite iterate**;
this driver turns it into a finished solve:

1. run the guarded fused solver at the current precision rung;
2. on ``breakdown``/``stagnation``, rebuild the operator ONE rung wider
   (``ops/_precision.escalate_dtype``: bf16 → f32 → f64, c64 → c128)
   and restart **from the last finite iterate** with the remaining
   iteration budget;
3. bounded by ``max_restarts`` (``PYLOPS_MPI_TPU_RESTARTS``, default
   2); every restart emits a structured ``solver.restart`` trace event.

The caller supplies an **operator factory** ``make_op(compute_dtype)``
(``compute_dtype=None`` on the first rung — the operator resolves the
env precision policy itself, exactly as a direct construction would),
because operators capture their storage dtype at construction; passing
a plain operator instead disables escalation (restarts are then only
possible for ``stagnation``, at the same precision, which is usually
futile — the driver stops instead).

Tuned plans survive restarts for free: the plan cache key
(tuning/plan.py) carries the dtype, so each rung replays its own plan
and invalidates nothing.
"""

from __future__ import annotations

import os
from collections import namedtuple
from typing import Callable, Optional, Sequence, Union

import numpy as np

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace
from . import status as _rstatus

__all__ = ["resilient_solve", "refined_solve", "ResilientResult",
           "RefinedResult", "max_restarts_default"]

ResilientResult = namedtuple(
    "ResilientResult",
    ["x", "status", "iiter", "restarts", "compute_dtype", "cost",
     "attempts"])
ResilientResult.__doc__ = (
    "Outcome of a resilient solve: the final iterate, the final status "
    "NAME (``converged``/``maxiter``/``breakdown``/``stagnation``), "
    "total iterations across every attempt, the restart count, the "
    "compute dtype of the last attempt, its cost history, and a "
    "per-attempt record list (precision, iterations, status).")

_SOLVERS = ("cg", "cgls", "ista", "fista")


def max_restarts_default() -> int:
    """``PYLOPS_MPI_TPU_RESTARTS`` (default 2, floored at 0)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_RESTARTS", "2"))
    except ValueError:
        v = 2
    return max(0, v)


def _run_guarded(solver: str, Op, y, x, niter: int, tol: float,
                 damp: float, solver_kwargs: dict, M=None):
    from ..solvers.basic import cg_guarded, cgls_guarded
    from ..solvers.sparsity import ista_guarded, fista_guarded
    if solver == "cg":
        xn, it, cost, code = cg_guarded(Op, y, x, niter=niter, tol=tol,
                                        M=M)
    elif solver == "cgls":
        xn, it, cost, _, _, code = cgls_guarded(
            Op, y, x, niter=niter, damp=damp, tol=tol,
            normal=bool(solver_kwargs.get("normal", False)), M=M)
    else:
        if M is not None:
            raise ValueError(
                f"M= (preconditioning) is not supported for {solver}")
        if x is None:
            from ..solvers.basic import _zero_like_model
            x = _zero_like_model(Op, y)
        fn = ista_guarded if solver == "ista" else fista_guarded
        kw = {k: v for k, v in solver_kwargs.items() if k != "normal"}
        xn, it, cost, code = fn(Op, y, x, niter=niter, tol=tol, **kw)
    return xn, it, cost, code


def resilient_solve(make_op: Union[Callable, object], y, x0=None, *,
                    solver: str = "cgls", niter: int = 100,
                    tol: float = 1e-4, damp: float = 0.0,
                    max_restarts: Optional[int] = None,
                    precisions: Optional[Sequence] = None,
                    M=None, refine: Optional[bool] = None,
                    **solver_kwargs) -> ResilientResult:
    """Solve with in-loop breakdown detection and bounded
    precision-escalation restarts (module docstring).

    ``make_op`` — operator factory ``make_op(compute_dtype)`` (or a
    plain operator, escalation disabled). ``precisions`` — explicit
    rung sequence of compute dtypes for attempts after the first
    (default: one :func:`~pylops_mpi_tpu.ops._precision.escalate_dtype`
    rung per restart). ``M`` — preconditioner threaded to the guarded
    CG/CGLS entries (ops/precond.py). ``refine`` — route the solve
    through :func:`refined_solve` (narrow inner solves + wide
    correction steps); default is the ``PYLOPS_MPI_TPU_REFINE`` knob.
    Extra ``solver_kwargs`` reach the guarded sparse solvers (``eps``,
    ``alpha``, ``threshkind``, ...) or CGLS (``normal``)."""
    from ..ops._precision import effective_compute_dtype, escalate_dtype
    from ..utils.deps import refine_enabled
    if solver not in _SOLVERS:
        raise ValueError(f"solver={solver!r}: expected one of {_SOLVERS}")
    if refine is None:
        refine = refine_enabled()
    if refine and callable(make_op) and solver in ("cg", "cgls"):
        rr = refined_solve(make_op, y, x0, solver=solver, niter=niter,
                           tol=tol, damp=damp, M=M, **solver_kwargs)
        status = {"converged": "converged", "maxpasses": "maxiter",
                  "stalled": "stagnation"}[rr.status]
        return ResilientResult(
            x=rr.x, status=status, iiter=rr.iiter,
            restarts=max(0, rr.passes - 1),
            compute_dtype=rr.attempts[-1]["compute_dtype"]
            if rr.attempts else "none",
            cost=rr.residuals, attempts=rr.attempts)
    if max_restarts is None:
        max_restarts = max_restarts_default()
    factory = make_op if callable(make_op) else None
    ladder = list(precisions) if precisions is not None else None

    x = x0
    cdt = None  # first rung: the operator's own (policy-resolved) dtype
    restarts = 0
    total_iiter = 0
    attempts = []
    cost = None
    while True:
        Op = factory(cdt) if factory is not None else make_op
        eff = effective_compute_dtype(Op)
        remaining = max(1, niter - total_iiter)
        x, it, cost, code = _run_guarded(solver, Op, y, x, remaining,
                                         tol, damp, solver_kwargs, M=M)
        total_iiter += it
        attempts.append({"compute_dtype": eff.name, "iiter": it,
                         "status": _rstatus.status_name(code)})
        if code in (_rstatus.CONVERGED, _rstatus.MAXITER):
            break
        # breakdown / stagnation: escalate one rung and restart from
        # the last finite iterate
        if ladder is not None:
            nxt = np.dtype(ladder.pop(0)) if ladder else None
        else:
            nxt = escalate_dtype(eff)
        if factory is None or nxt is None or restarts >= max_restarts:
            break
        restarts += 1
        _metrics.inc(f"solver.{solver}.restarts")
        _trace.event("solver.restart", cat="resilience", solver=solver,
                     status=_rstatus.status_name(code),
                     at_iter=total_iiter, restart=restarts,
                     from_dtype=eff.name, to_dtype=nxt.name)
        cdt = nxt
    return ResilientResult(x=x, status=_rstatus.status_name(code),
                           iiter=total_iiter, restarts=restarts,
                           compute_dtype=eff.name, cost=cost,
                           attempts=attempts)


# ------------------------------------------------------------ refinement
RefinedResult = namedtuple(
    "RefinedResult",
    ["x", "status", "iiter", "passes", "residuals", "narrow_frac",
     "attempts"])
RefinedResult.__doc__ = (
    "Outcome of an iteratively refined solve: the wide-precision "
    "iterate, status (``converged``/``maxpasses``/``stalled``), total "
    "inner iterations, correction-pass count, the per-pass wide "
    "residual norms, the fraction of operator applies executed at "
    "narrow precision, and a per-pass record list.")


class _NormalOperator:
    """``v ↦ OpᴴOp v + damp² v`` — the model-space normal system the
    damped-CGLS refinement pass solves for its correction. Lives
    outside the pytree registry on purpose: the refinement driver only
    runs it through the closure-capture solver path."""

    def __init__(self, Op, damp: float):
        n = int(Op.shape[1])
        self.shape = (n, n)
        self.dtype = Op.dtype
        self.mesh = getattr(Op, "mesh", None)
        self._Op = Op
        self._damp2 = float(damp) * float(damp)

    def matvec(self, x):
        v = self._Op.rmatvec(self._Op.matvec(x))
        return v + x * self._damp2 if self._damp2 else v

    rmatvec = matvec


def _wrap_wide(g, like):
    from ..distributedarray import DistributedArray
    return DistributedArray._wrap(like._from_global(g), like)


def refined_solve(make_op: Callable, y, x0=None, *, solver: str = "cg",
                  niter: int = 100, tol: float = 1e-10,
                  damp: float = 0.0, inner_dtype=None,
                  inner_niter: Optional[int] = None,
                  inner_tol: float = 1e-4, max_passes: int = 8,
                  M=None, wide_dtype=None,
                  **solver_kwargs) -> RefinedResult:
    """Mixed-precision iterative refinement: narrow inner (P)CG/CGLS
    solves, wide (f64) residuals and correction updates.

    Each pass recomputes the TRUE residual of the wide system —
    ``s = y − Ax`` (cg) or the gradient ``g = Aᴴ(y−Ax) − damp²x``
    (cgls) — at ``wide_dtype`` through ``make_op(wide_dtype)``, solves
    the correction system at the narrow rung through
    ``make_op(inner_dtype)`` (optionally preconditioned by ``M``), and
    applies ``x += d`` in wide precision. The narrow solver only ever
    sees the residual, whose solution is O(residual) small, so its
    limited range/precision bounds the CORRECTION error, not the
    solution error — bf16/f32 inner solves reach f64 accuracy while
    ≥80% of the matvec FLOPs run at the narrow dtype
    (``solver.refine.*`` telemetry counts them).

    Composition with escalation: an inner breakdown/stagnation, or a
    pass that fails to shrink the wide residual, escalates the inner
    rung one step (``escalate_dtype``) and re-runs the pass from the
    reverted iterate — the refinement analog of ``resilient_solve``'s
    restart. ``PYLOPS_MPI_TPU_REFINE=1`` routes ``resilient_solve``
    here for cg/cgls factories.

    ``inner_dtype=None`` lets the first narrow build resolve the env
    precision policy, exactly like ``resilient_solve``'s first rung.
    ``inner_tol`` is the per-pass relative tolerance of the correction
    solve (coarse on purpose — outer passes, not inner iterations, buy
    the final accuracy)."""
    import jax
    from ..ops._precision import effective_compute_dtype, escalate_dtype
    if solver not in ("cg", "cgls"):
        raise ValueError(f"solver={solver!r}: refinement supports "
                         "'cg' and 'cgls'")
    if not callable(make_op):
        raise TypeError(
            "refined_solve needs an operator FACTORY make_op("
            "compute_dtype) — it must build both the wide and the "
            "narrow operator; a plain operator cannot escalate")
    if wide_dtype is None:
        base = np.float64 if jax.config.jax_enable_x64 else np.float32
        wide_dtype = np.promote_types(base, np.dtype(y.dtype))
    wide_dtype = np.dtype(wide_dtype)
    if inner_niter is None:
        inner_niter = niter

    Opw = make_op(wide_dtype)
    cdt = inner_dtype
    Opn = make_op(np.dtype(cdt) if cdt is not None else None)
    per_apply = 2 if solver == "cgls" else 1

    yg = y._global().astype(wide_dtype)
    ynorm = float(np.linalg.norm(np.asarray(yg)))
    if solver == "cgls":
        gref = Opw.rmatvec(_wrap_wide(yg, y))._global()
        refnorm = float(np.linalg.norm(np.asarray(gref)))
    else:
        refnorm = ynorm
    refnorm = refnorm if refnorm > 0 else 1.0

    if x0 is not None:
        x = _wrap_wide(x0._global().astype(wide_dtype), x0)
    else:
        from ..solvers.basic import _zero_like_model
        x = _zero_like_model(Opw, _wrap_wide(yg, y))

    residuals = []
    attempts = []
    total_iiter = 0
    n_narrow = 0.0
    n_wide = 0.0
    status = "maxpasses"
    prev_norm = np.inf
    passes = 0
    while passes < max_passes:
        # ---- wide TRUE residual -----------------------------------
        ax = Opw.matvec(x)._global().astype(wide_dtype)
        s_g = yg - ax
        n_wide += 1
        if solver == "cgls":
            g = Opw.rmatvec(_wrap_wide(s_g, y))._global() \
                .astype(wide_dtype)
            n_wide += 1
            if self_damp := float(damp):
                g = g - x._global() * (self_damp * self_damp)
            rnorm = float(np.linalg.norm(np.asarray(g)))
        else:
            rnorm = float(np.linalg.norm(np.asarray(s_g)))
        residuals.append(rnorm)
        if rnorm <= tol * refnorm:
            status = "converged"
            break
        if passes > 0 and rnorm >= prev_norm:
            # the last correction did not help: revert, escalate the
            # inner rung, retry — the refinement analog of a restart
            nxt = escalate_dtype(effective_compute_dtype(Opn))
            if nxt is None:
                status = "stalled"
                break
            x = x_prev  # noqa: F821 — rnorm >= prev_norm implies set
            _trace.event("solver.refine_escalate", cat="resilience",
                         solver=solver, at_pass=passes,
                         to_dtype=nxt.name)
            _metrics.inc("solver.refine.escalations")
            Opn = make_op(nxt)
            prev_norm = np.inf
            continue

        # ---- narrow correction solve ------------------------------
        # the fused solvers' stop test is ABSOLUTE (max(kold) > tol,
        # kold = r·z ≈ ||r||²); refinement needs the inner tolerance
        # RELATIVE to the pass's own rhs — each pass then contracts
        # the wide residual by ≈ inner_tol instead of stalling at it
        passes += 1
        itol = float((inner_tol * rnorm) ** 2)
        eff = effective_compute_dtype(Opn)
        ndt = np.dtype(Opn.dtype)
        if solver == "cgls" and float(damp):
            Nop = _NormalOperator(Opn, damp)
            rhs = _wrap_wide(g.astype(ndt), x)
            d, it, _, code = _run_guarded(
                "cg", Nop, rhs, None, inner_niter, itol, 0.0,
                {}, M=M)
            napp = 2.0 * (it + 1)      # each normal apply = 2 of A
        else:
            rhs = _wrap_wide(s_g.astype(ndt), y)
            d, it, _, code = _run_guarded(
                solver, Opn, rhs, None, inner_niter, itol, 0.0,
                solver_kwargs, M=M)
            napp = float(per_apply) * (it + 1)
        total_iiter += it
        n_narrow += napp
        attempts.append({"compute_dtype": eff.name, "iiter": it,
                         "status": _rstatus.status_name(code),
                         "residual": rnorm})
        _metrics.inc("solver.refine.passes")

        # ---- wide correction update -------------------------------
        x_prev = x
        prev_norm = rnorm
        x = _wrap_wide(
            x._global() + d._global().astype(wide_dtype), x)
        if code not in (_rstatus.CONVERGED, _rstatus.MAXITER):
            nxt = escalate_dtype(eff)
            if nxt is not None:
                _trace.event("solver.refine_escalate",
                             cat="resilience", solver=solver,
                             at_pass=passes, to_dtype=nxt.name)
                _metrics.inc("solver.refine.escalations")
                Opn = make_op(nxt)

    _metrics.inc("solver.refine.narrow_matvecs", n_narrow)
    _metrics.inc("solver.refine.wide_matvecs", n_wide)
    frac = n_narrow / max(1.0, n_narrow + n_wide)
    return RefinedResult(x=x, status=status, iiter=total_iiter,
                         passes=passes, residuals=residuals,
                         narrow_frac=frac, attempts=attempts)

"""Bounded retry with exponential backoff.

Multi-host bring-up is the flakiest moment of a pod job: the
``jax.distributed`` coordinator may not be listening yet, a DNS entry
may lag the pod scheduler, a preempted peer may rejoin seconds late.
The reference stack leans on ``mpiexec`` to re-run the world; here one
controller process must absorb transient faults itself. This module is
the ONE retry/backoff implementation, used by
:func:`pylops_mpi_tpu.parallel.mesh.initialize_multihost` and by the
harvest ladder's stage spawn (``benchmarks/tpu_probe_loop.py``) — both
places where the failure is transient-by-construction and a bounded
retry is the difference between a lost window and a banked result.

Retries are **bounded** (``PYLOPS_MPI_TPU_RETRIES``, default 3 extra
attempts) with doubling backoff from
``PYLOPS_MPI_TPU_RETRY_BACKOFF`` seconds (default 0.5, capped at 30 s
per sleep); every retry emits a structured ``resilience.retry`` trace
event so a flaky-but-recovering init is visible in the JSONL artifact
instead of silently eating minutes. The final failure re-raises the
last exception unchanged — retry must never LAUNDER an error.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple, Type

from ..diagnostics import trace as _trace

__all__ = ["retry_call", "default_retries", "default_backoff_s"]

_MAX_SLEEP_S = 30.0


def default_retries() -> int:
    """``PYLOPS_MPI_TPU_RETRIES`` (default 3, floored at 0 — 0 means
    one attempt, no retries)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_RETRIES", "3"))
    except ValueError:
        v = 3
    return max(0, v)


def default_backoff_s() -> float:
    """``PYLOPS_MPI_TPU_RETRY_BACKOFF`` initial sleep in seconds
    (default 0.5, floored at 0 for tests that must not sleep)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_RETRY_BACKOFF", "0.5"))
    except ValueError:
        v = 0.5
    return max(0.0, v)


def retry_call(fn: Callable, *args,
               retries: Optional[int] = None,
               backoff_s: Optional[float] = None,
               exceptions: Tuple[Type[BaseException], ...] = (Exception,),
               describe: str = "call",
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on an exception from
    ``exceptions``, sleep (doubling backoff, capped) and retry up to
    ``retries`` more times. Emits one ``resilience.retry`` trace event
    per retry; the last failure propagates unchanged.

    ``sleep`` is injectable so the chaos tests don't wait out real
    backoffs."""
    retries = default_retries() if retries is None else max(0, retries)
    backoff = default_backoff_s() if backoff_s is None else max(0.0,
                                                                backoff_s)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except exceptions as e:
            attempt += 1
            if attempt > retries:
                raise
            wait = min(backoff * (2 ** (attempt - 1)), _MAX_SLEEP_S)
            _trace.event("resilience.retry", cat="resilience",
                         what=describe, attempt=attempt,
                         retries=retries, backoff_s=round(wait, 3),
                         error=repr(e)[:200])
            if wait > 0:
                sleep(wait)

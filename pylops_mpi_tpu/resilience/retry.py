"""Bounded retry with exponential backoff.

Multi-host bring-up is the flakiest moment of a pod job: the
``jax.distributed`` coordinator may not be listening yet, a DNS entry
may lag the pod scheduler, a preempted peer may rejoin seconds late.
The reference stack leans on ``mpiexec`` to re-run the world; here one
controller process must absorb transient faults itself. This module is
the ONE retry/backoff implementation, used by
:func:`pylops_mpi_tpu.parallel.mesh.initialize_multihost` and by the
harvest ladder's stage spawn (``benchmarks/tpu_probe_loop.py``) — both
places where the failure is transient-by-construction and a bounded
retry is the difference between a lost window and a banked result.

Retries are **bounded** (``PYLOPS_MPI_TPU_RETRIES``, default 3 extra
attempts) with doubling backoff from
``PYLOPS_MPI_TPU_RETRY_BACKOFF`` seconds (default 0.5, capped at 30 s
per sleep); every retry emits a structured ``resilience.retry`` trace
event so a flaky-but-recovering init is visible in the JSONL artifact
instead of silently eating minutes. The final failure re-raises the
last exception unchanged — retry must never LAUNDER an error.

**Jitter** (``PYLOPS_MPI_TPU_RETRY_JITTER``, default 0 — exact
doubling stays the pinned behavior): after a supervisor relaunch, P
workers all lose the coordinator at the same instant and would
otherwise reconnect in lockstep, hammering the restarted coordinator
at exactly t+0.5, t+1.5, t+3.5, … The decorrelating jitter shrinks
each sleep by a uniform random fraction up to the knob (AWS
"full/decorrelated jitter" family: ``wait × (1 − U[0,1)·j)``), so the
stampede spreads while the CAP and the bounded attempt count are
unchanged. The supervisor sets ``j=0.25`` in its worker env.

**Retryability** (``retry_if``): a coarse exception tuple cannot say
"retry 'connection refused' but not 'address already in use'"; the
optional predicate sees the caught exception and vetoes the retry
(re-raising unchanged) when it returns False.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from ..diagnostics import metrics as _metrics
from ..diagnostics import trace as _trace

__all__ = ["retry_call", "default_retries", "default_backoff_s",
           "default_jitter"]

_MAX_SLEEP_S = 30.0


def default_retries() -> int:
    """``PYLOPS_MPI_TPU_RETRIES`` (default 3, floored at 0 — 0 means
    one attempt, no retries)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_RETRIES", "3"))
    except ValueError:
        v = 3
    return max(0, v)


def default_backoff_s() -> float:
    """``PYLOPS_MPI_TPU_RETRY_BACKOFF`` initial sleep in seconds
    (default 0.5, floored at 0 for tests that must not sleep)."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_RETRY_BACKOFF", "0.5"))
    except ValueError:
        v = 0.5
    return max(0.0, v)


def default_jitter() -> float:
    """``PYLOPS_MPI_TPU_RETRY_JITTER`` decorrelation fraction in
    [0, 1] (default 0.0 — deterministic doubling; the supervisor sets
    0.25 for its workers). Clamped: 1.0 means a sleep may shrink to
    ~0, never grow past the doubling schedule's cap."""
    try:
        v = float(os.environ.get("PYLOPS_MPI_TPU_RETRY_JITTER", "0"))
    except ValueError:
        v = 0.0
    return min(1.0, max(0.0, v))


def retry_call(fn: Callable, *args,
               retries: Optional[int] = None,
               backoff_s: Optional[float] = None,
               exceptions: Tuple[Type[BaseException], ...] = (Exception,),
               retry_if: Optional[Callable[[BaseException], bool]] = None,
               jitter: Optional[float] = None,
               describe: str = "call",
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on an exception from
    ``exceptions`` that ``retry_if`` (when given) deems retryable,
    sleep (doubling backoff, capped, optionally jittered — module
    docstring) and retry up to ``retries`` more times. Emits one
    ``resilience.retry`` trace event per retry; the last failure — and
    any non-retryable one — propagates unchanged.

    ``sleep`` and ``rng`` are injectable so the chaos tests neither
    wait out real backoffs nor depend on global random state."""
    retries = default_retries() if retries is None else max(0, retries)
    backoff = default_backoff_s() if backoff_s is None else max(0.0,
                                                                backoff_s)
    jitter = default_jitter() if jitter is None \
        else min(1.0, max(0.0, jitter))
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except exceptions as e:
            if retry_if is not None and not retry_if(e):
                raise
            attempt += 1
            if attempt > retries:
                raise
            wait = min(backoff * (2 ** (attempt - 1)), _MAX_SLEEP_S)
            if jitter > 0.0 and wait > 0.0:
                u = (rng or random).random()
                wait *= 1.0 - jitter * u
            _metrics.inc("resilience.retries")
            _trace.event("resilience.retry", cat="resilience",
                         what=describe, attempt=attempt,
                         retries=retries, backoff_s=round(wait, 3),
                         error=repr(e)[:200])
            if wait > 0:
                sleep(wait)

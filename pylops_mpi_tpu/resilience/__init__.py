"""Resilient solver runtime (ISSUE 6).

Four pieces, layered over the fused solvers:

- :mod:`.status` — the in-loop status word
  (``converged``/``maxiter``/``breakdown``/``stagnation``) and the
  ``PYLOPS_MPI_TPU_GUARDS`` gate (off-mode traces bit-identical
  programs).
- :mod:`.driver` — :func:`resilient_solve`: precision-escalation
  restarts from the last finite iterate (bf16 → f32 → f64).
- :mod:`.retry` — bounded retry/backoff for transient host-side
  faults (multihost init, harvest stage spawn).
- :mod:`.faults` — the chaos seams that prove all of the above end to
  end (in-loop NaN/stall injection, plan-cache corruption, flaky
  callables).

Segmented checkpoint/resume lives with the solvers
(:mod:`pylops_mpi_tpu.solvers.segmented`) and the carry schema in
:mod:`pylops_mpi_tpu.utils.checkpoint`. See ``docs/robustness.md``.
"""

from . import faults, retry, status
from .status import (RUNNING, CONVERGED, MAXITER, BREAKDOWN, STAGNATION,
                     status_name, guards_mode, guards_enabled,
                     last_status)
from .retry import retry_call
from .driver import resilient_solve, ResilientResult

__all__ = ["faults", "retry", "status", "RUNNING", "CONVERGED",
           "MAXITER", "BREAKDOWN", "STAGNATION", "status_name",
           "guards_mode", "guards_enabled", "last_status", "retry_call",
           "resilient_solve", "ResilientResult"]

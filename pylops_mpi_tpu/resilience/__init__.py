"""Resilient solver + elastic job runtime (ISSUEs 6 and 8).

In-process (layered over the fused solvers):

- :mod:`.status` — the in-loop status word
  (``converged``/``maxiter``/``breakdown``/``stagnation``) and the
  ``PYLOPS_MPI_TPU_GUARDS`` gate (off-mode traces bit-identical
  programs).
- :mod:`.driver` — :func:`resilient_solve`: precision-escalation
  restarts from the last finite iterate (bf16 → f32 → f64).
- :mod:`.retry` — bounded retry/backoff (with decorrelating jitter)
  for transient host-side faults (multihost init, harvest stage
  spawn).
- :mod:`.faults` — the chaos seams that prove all of the above end to
  end (in-loop NaN/stall injection, plan-cache corruption, flaky
  callables).

Across processes (the elastic multi-host runtime):

- :mod:`.elastic` — the worker side: heartbeat writer thread, the
  supervisor↔worker env contract, and the collective watchdog
  (:func:`watched_call`) that turns a hung peer into a classified
  :class:`WatchdogTimeout`.
- :mod:`.supervisor` — :func:`launch_job`: launch N workers, watch
  heartbeats, classify failures (exit / signal / stale heartbeat),
  kill stragglers and relaunch on the surviving slots with a shrunk
  world; mesh-elastic checkpoint restore
  (:func:`pylops_mpi_tpu.utils.checkpoint.load_fused_carry` with a
  new ``mesh``) carries the state across.

Segmented checkpoint/resume lives with the solvers
(:mod:`pylops_mpi_tpu.solvers.segmented`) and the carry schema in
:mod:`pylops_mpi_tpu.utils.checkpoint`. See ``docs/robustness.md``
and ``docs/multihost.md#surviving-failures``.
"""

from . import elastic, faults, retry, status, supervisor
from .status import (RUNNING, CONVERGED, MAXITER, BREAKDOWN, STAGNATION,
                     status_name, guards_mode, guards_enabled,
                     last_status)
from .retry import retry_call
from .driver import (resilient_solve, refined_solve, ResilientResult, RefinedResult)
from .elastic import (WatchdogTimeout, watched_call, watchdog_mode,
                      watchdog_enabled, start_heartbeat, stop_heartbeat,
                      maybe_start_heartbeat, worker_config,
                      elastic_initialize, WorkerConfig,
                      request_drain, drain_requested, reset_drain,
                      install_sigterm_drain)
from .supervisor import launch_job, JobResult, Failure, WorkerHandle

__all__ = ["elastic", "faults", "retry", "status", "supervisor",
           "RUNNING", "CONVERGED", "MAXITER", "BREAKDOWN", "STAGNATION",
           "status_name", "guards_mode", "guards_enabled", "last_status",
           "retry_call", "resilient_solve", "refined_solve", "ResilientResult", "RefinedResult",
           "WatchdogTimeout", "watched_call", "watchdog_mode",
           "watchdog_enabled", "start_heartbeat", "stop_heartbeat",
           "maybe_start_heartbeat", "worker_config",
           "elastic_initialize", "WorkerConfig",
           "request_drain", "drain_requested", "reset_drain",
           "install_sigterm_drain",
           "launch_job", "JobResult", "Failure", "WorkerHandle"]

"""Power iteration — dominant-eigenpair estimate.

Rebuild of ``pylops_mpi/optimization/eigs.py:10-98``: random init per
shard, normalize by the distributed norm, Rayleigh quotient via ``vdot``
(one ``psum`` per iteration), early stop on relative eigenvalue change.

Default execution is the fused path: the whole iteration runs as one
``lax.while_loop`` so the Rayleigh quotient and norms never sync to the
host (the reference — and the round-1 rebuild — pulled the eigenvalue
estimate back every iteration). ``fused=False`` restores the eager loop.
"""

from __future__ import annotations

from typing import Tuple, Union

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..stacked import StackedDistributedArray

__all__ = ["power_iteration"]

Vector = Union[DistributedArray, StackedDistributedArray]


def power_iteration(Op, b_k: Vector, niter: int = 10, tol: float = 1e-5,
                    dtype="float64", seed: int = 42, fused: bool = True,
                    ) -> Tuple[complex, Vector, int]:
    """ref ``eigs.py:10-98``. ``b_k`` provides the vector-space template;
    its values are replaced with random ones as in the reference."""
    rng = np.random.default_rng(seed)
    cmpx = 1j if np.issubdtype(np.dtype(dtype), np.complexfloating) else 0

    def rand_like(d: DistributedArray) -> DistributedArray:
        vals = rng.random(d.global_shape) + cmpx * rng.random(d.global_shape)
        out = d.zeros_like()
        out[:] = jnp.asarray(vals, dtype=dtype)
        return out

    if isinstance(b_k, StackedDistributedArray):
        b_k = StackedDistributedArray([rand_like(d) for d in b_k.distarrays])
    else:
        b_k = rand_like(b_k)
    b_k = b_k * (1.0 / b_k.norm())

    if fused:
        return _power_iteration_fused(Op, b_k, niter, tol)

    maxeig_old = 0.0
    iiter = 0
    for iiter in range(niter):
        b1_k = Op.matvec(b_k)
        maxeig = complex(np.asarray(b_k.dot(b1_k, vdot=True)))
        if abs(maxeig.imag) < 1e-12:
            maxeig = maxeig.real
        b1_k_norm = b1_k.norm()
        b_k = b1_k * (1.0 / b1_k_norm)
        if np.abs(maxeig - maxeig_old) < tol * np.abs(maxeig):
            break
        maxeig_old = maxeig
    return maxeig, b_k, iiter + 1


def _power_run(op, b_in, niter, tol):
    """The whole power iteration as one ``lax.while_loop``; the first
    step runs outside the loop to seed the eigenvalue carry (the eager
    loop's ``maxeig_old = 0`` first-pass comparison is preserved)."""
    def one_step(b):
        b1 = op.matvec(b)
        maxeig = jnp.asarray(b.dot(b1, vdot=True))
        # the norm accumulates at the policy reduction floor (f32 for
        # narrow spaces); the scale re-enters the update at the carry
        # dtype so the while_loop pytree stays dtype-stable
        from .basic import _step_scalar
        scale = _step_scalar(1.0 / jnp.asarray(b1.norm()), b1.dtype)
        return b1 * scale, maxeig

    def body(state):
        b, maxeig_old, iiter, _ = state
        b, maxeig = one_step(b)
        converged = jnp.abs(maxeig - maxeig_old) < tol * jnp.abs(maxeig)
        return (b, maxeig, iiter + 1, converged)

    def cond(state):
        return (state[2] < niter) & (~state[3])

    b0, maxeig0 = one_step(b_in)
    conv0 = jnp.abs(maxeig0 - 0.0) < tol * jnp.abs(maxeig0)
    state = (b0, maxeig0, jnp.asarray(1), conv0)
    b_out, maxeig, iiter, _ = lax.while_loop(cond, body, state)
    return b_out, maxeig, iiter


def _power_iteration_fused(Op, b_k: Vector, niter: int, tol):
    """Registered operator compositions enter the compiled program as a
    pytree argument — their sharded buffers must not be closed over on
    multi-process meshes (``linearoperator.operator_is_jit_arg``);
    anything else (e.g. unregistered user subclasses) runs the eager
    form, whose ``lax.while_loop`` still compiles with closure capture.
    The compiled program lives in the solvers' bounded LRU
    (``basic._FUSED_CACHE``): repeated estimates on the SAME operator
    instance hit the cache; a fresh composition per call retraces
    either way (pytree aux compares by identity), but the LRU bounds
    how many churned entries stay pinned and ``clear_fused_cache()``
    releases them — ista/fista additionally cache the resulting
    eigenvalue per parent operator so the churn happens at most
    once."""
    from ..linearoperator import operator_is_jit_arg
    from .basic import _get_fused, _vkey
    if operator_is_jit_arg(Op):
        from functools import partial
        # b_k is built fresh above (rand_like) — donate it outright:
        # the normalized-iterate carry starts in its buffer
        fn = _get_fused(Op, (id(Op), "power", _vkey(b_k)),
                        lambda op: partial(_power_run, op),
                        donate_argnums=(0,), aot_eligible=True)
        b_k, maxeig, iiter = fn(b_k, niter, tol)
    else:
        b_k, maxeig, iiter = _power_run(Op, b_k, niter, tol)
    maxeig = complex(np.asarray(maxeig))
    if abs(maxeig.imag) < 1e-12:
        maxeig = maxeig.real
    return maxeig, b_k, int(iiter)

"""Power iteration — dominant-eigenpair estimate.

Rebuild of ``pylops_mpi/optimization/eigs.py:10-98``: random init per
shard, normalize by the distributed norm, Rayleigh quotient via ``vdot``
(one ``psum`` per iteration), early stop on relative eigenvalue change.
"""

from __future__ import annotations

from typing import Tuple, Union

import numpy as np
import jax.numpy as jnp

from ..distributedarray import DistributedArray
from ..stacked import StackedDistributedArray

__all__ = ["power_iteration"]

Vector = Union[DistributedArray, StackedDistributedArray]


def power_iteration(Op, b_k: Vector, niter: int = 10, tol: float = 1e-5,
                    dtype="float64", seed: int = 42,
                    ) -> Tuple[complex, Vector, int]:
    """ref ``eigs.py:10-98``. ``b_k`` provides the vector-space template;
    its values are replaced with random ones as in the reference."""
    rng = np.random.default_rng(seed)
    cmpx = 1j if np.issubdtype(np.dtype(dtype), np.complexfloating) else 0

    def rand_like(d: DistributedArray) -> DistributedArray:
        vals = rng.random(d.global_shape) + cmpx * rng.random(d.global_shape)
        out = d.zeros_like()
        out[:] = jnp.asarray(vals, dtype=dtype)
        return out

    if isinstance(b_k, StackedDistributedArray):
        b_k = StackedDistributedArray([rand_like(d) for d in b_k.distarrays])
    else:
        b_k = rand_like(b_k)
    b_k = b_k * (1.0 / b_k.norm())

    maxeig_old = 0.0
    iiter = 0
    for iiter in range(niter):
        b1_k = Op.matvec(b_k)
        maxeig = complex(np.asarray(b_k.dot(b1_k, vdot=True)))
        if abs(maxeig.imag) < 1e-12:
            maxeig = maxeig.real
        b1_k_norm = b1_k.norm()
        b_k = b1_k * (1.0 / b1_k_norm)
        if np.abs(maxeig - maxeig_old) < tol * np.abs(maxeig):
            break
        maxeig_old = maxeig
    return maxeig, b_k, iiter + 1

"""Communication-avoiding Krylov tier (``PYLOPS_MPI_TPU_CA``).

Every classic fused CG/CGLS iteration pays 2-5 separate ``_rdot``
all-reduces (solvers/basic.py), each a latency-bound collective whose
scalar result sits on the recurrence critical path — on a DCN-connected
pod the per-collective wire latency, not bandwidth, becomes the
iteration floor ("Large Scale Distributed Linear Algebra With TPUs",
2112.09017, hits exactly this wall at pod scale). This module trades
a little algebra and a little roundoff head-room for fewer, earlier
collectives:

- **pipelined PCG / PCGLS** (:func:`run_cg_fused` / :func:`run_cgls_fused`
  with ``mode="pipelined"``): Ghysels–Vanroose-style recurrences carry
  the auxiliary vectors ``u = M r``, ``w = A u``, ``z = A M w`` companions
  so BOTH per-iteration dot products — ``γ = (r, u)`` and ``δ = (w, u)``
  — stack into ONE small vector reduced by a single all-reduce
  (:func:`_stacked_rdot`), issued at the TOP of the body so XLA can
  overlap the collective with the operator apply that follows. Lowered
  HLO carries exactly one ``all-reduce`` in the while body
  (``utils.hlo.assert_single_reduction``) vs 2 (CG) / up to 5 (CGLS)
  classic. CGLS runs pipelined CG on the damped normal system
  ``(AᴴA + damp²I) x = Aᴴ y`` — its ``cost``/``cost1`` lanes therefore
  record the preconditioned NORMAL-residual norm ``sqrt(γ)``, not the
  data-residual norm the classic engine logs.
- **s-step CA-CG** (``mode="sstep"``): each outer step grows monomial
  Krylov chains ``{(MA)^j p}`` and ``{(MA)^j z}`` locally (2s-1 operator
  applies), then pays ONE Gram-matrix all-reduce for everything s
  iterations of CG need — the coordinate recurrences run on replicated
  (2s+1)-vectors with zero further communication. The monomial basis
  conditions like κ(A)^s, so a breakdown guard (non-finite or
  non-positive pivot) rejects the outer update, raises
  ``status=BREAKDOWN`` (the PR 6 status word), and the host wrapper
  falls back to the pipelined engine from the last completed outer
  iterate (:func:`last_fallback` reports it). s-step is restricted to
  plain even unmasked real ``DistributedArray`` spaces; anything else
  silently uses the pipelined engine.

Mode selection is ``PYLOPS_MPI_TPU_CA=off|pipelined|sstep|auto``
(utils/deps.py). ``auto`` consults the α-β latency term the PR 11/17
cost model carries (``diagnostics.costmodel.roofline`` ``latency``
component vs the bandwidth bound) and NEVER chooses s-step on its own.
``off`` never reaches this module — the classic engines trace
bit-identical programs under unchanged cache keys.

Composition contracts (pinned by tests/test_ca.py):

- the ``M=`` seam: every engine takes the PR 15 preconditioner, and
  ``M=None`` drops the ``u``/``q`` carries entirely (they alias ``r``/
  ``s``), so unpreconditioned solves trace the lean program;
- PR 6 guards: the same reject-poisoned-update / breakdown / stagnation
  carry as the classic bodies, via the shared ``_guard_update``;
- PR 8 blocks: :func:`run_block_cg` / :func:`run_block_cgls` carry
  ``(K,)`` recurrence lanes with the same per-column freeze and
  per-column status words (``_bguard_update``);
- PR 6/8 segmented checkpoints: the ``*_seg_*`` builders expose the CA
  carries to ``solvers/segmented.py``; carries are stamped with the CA
  mode and :data:`CA_SCHEMA`, and a resume under a different mode
  refuses (``resume must replay the same plan``).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..stacked import StackedDistributedArray
from ..diagnostics import metrics as _metrics
from ..diagnostics import telemetry, trace as _trace
from .basic import (_DONATE_X0, _donate_copy, _get_fused, _guard_update,
                    _i32, _mkey, _mp_floor, _precond_apply, _rdot,
                    _reject, _resolve_status, _step_scalar, _vdtype,
                    _vkey)
from .block import _bdot, _bguard_update, _bresolve, _status0

__all__ = ["resolve_mode", "ca_key", "classic_reductions_per_iter",
           "ca_reductions_per_iter", "last_fallback", "clear_fallback",
           "CA_SCHEMA"]

# CA while-loop carries are a different pytree than the classic
# engines' — segmented checkpoints written by this tier stamp this
# schema (classic carries keep _FUSED_SCHEMA=1) so a resume can never
# feed one engine's carry to the other.
CA_SCHEMA = 2

# stagnation window used when a status word is carried WITHOUT guards
# (the s-step engine always carries one for its breakdown verdict):
# effectively infinite, so only BREAKDOWN can fire.
_NO_STALL = 1 << 30

# classic fused engines' all-reduces per iteration — the α-term seed
# for the auto selector and the bench's reduction-count baseline.
_CLASSIC_REDUCTIONS = {"cg": 2, "cgls": 5, "block_cg": 2,
                       "block_cgls": 5}


def classic_reductions_per_iter(solver: str) -> int:
    """All-reduces per iteration of the CLASSIC fused engine."""
    return _CLASSIC_REDUCTIONS.get(solver, 2)


def ca_reductions_per_iter(mode: str, s: int = 1) -> float:
    """All-reduces per iteration under a CA mode: pipelined = 1,
    s-step = 1/s (one Gram reduction per s iterations)."""
    if mode == "sstep":
        return 1.0 / max(1, int(s))
    if mode == "pipelined":
        return 1.0
    return float(_CLASSIC_REDUCTIONS["cg"])


# ------------------------------------------------------ mode selection
def _auto_mode(Op, solver: str) -> str:
    """Latency-aware α-β selection: pipeline when the per-iteration
    reduction latency is a material fraction of the bandwidth-bound
    iteration time. Never chooses s-step (its basis conditioning is an
    opt-in risk). Unknown chips (no roofline) fall back to the explicit
    latency seam: an armed ``PYLOPS_MPI_TPU_REDUCE_STALL`` says the
    operator lives on a latency-dominated fabric (the CPU-sim bench
    shape), anything else stays classic."""
    try:
        from ..diagnostics import costmodel as _cm
        peaks = _cm.device_peaks()
        lat = peaks.get("allreduce_latency_s")
        if not lat:
            return "off"
        alpha_s = classic_reductions_per_iter(solver) * lat
        cost = _cm.estimate(Op)
        if cost is not None:
            rf = _cm.roofline(cost, peaks)
            pred = rf.get("predicted_s")
            if pred:
                return "pipelined" if alpha_s >= 0.25 * pred else "off"
    except Exception:
        return "off"
    from ..utils import deps as _deps
    return "pipelined" if _deps.reduce_stall_steps() else "off"


def resolve_mode(Op=None, solver: str = "cg") -> str:
    """Resolve ``PYLOPS_MPI_TPU_CA`` to a concrete engine for this
    solve: ``off`` | ``pipelined`` | ``sstep``."""
    from ..utils import deps as _deps
    mode = _deps.ca_mode()
    if mode == "auto":
        mode = _auto_mode(Op, solver)
    return mode


def ca_key(mode: str, s: Optional[int] = None):
    """Cache-key fragment for a CA engine. ``off`` contributes NOTHING
    so classic entries keep their pre-CA keys byte-identical."""
    if mode == "off":
        return ()
    if mode == "sstep":
        return (("ca", "sstep", int(s)),)
    return (("ca", mode),)


# ------------------------------------------------------ fallback events
_FB_LOCK = threading.Lock()
_LAST_FALLBACK: Optional[dict] = None


def _record_fallback(solver: str, s: int, iiter: int) -> None:
    global _LAST_FALLBACK
    with _FB_LOCK:
        _LAST_FALLBACK = {"solver": solver, "s": int(s),
                          "iteration": int(iiter)}
    _metrics.inc("solver.ca.sstep_fallbacks")
    _trace.event("solver.ca.sstep_fallback", cat="solver",
                 solver=solver, s=int(s), iteration=int(iiter))


def last_fallback() -> Optional[dict]:
    """The most recent s-step→pipelined breakdown fallback (``{solver,
    s, iteration}``), or ``None`` — the PR 6 escalation ladder's view
    into the basis-conditioning guard."""
    with _FB_LOCK:
        return dict(_LAST_FALLBACK) if _LAST_FALLBACK else None


def clear_fallback() -> None:
    global _LAST_FALLBACK
    with _FB_LOCK:
        _LAST_FALLBACK = None


# ------------------------------------------------------ stacked reductions
def _fusable(vs) -> bool:
    """True when the recurrence dots over these vectors can share one
    physical all-reduce: plain (non-stacked) DistributedArrays, no
    sub-communicator mask, uniform physical split, matching shapes."""
    shapes = set()
    for v in vs:
        if not isinstance(v, DistributedArray):
            return False
        if v.mask is not None or not v._even:
            return False
        shapes.add(v._arr.shape)
    return len(shapes) == 1


def _stacked_rdot(pairs):
    """The tentpole reduction: m recurrence dot products stacked into
    one small vector BEFORE the collective, so the lowered HLO carries
    a single ``all-reduce`` of m scalars instead of m latency-bound
    round trips. Falls back to per-pair :func:`basic._rdot` (one
    collective each — and one ``reduce_stall`` each, so the latency
    seam stays per-collective-honest) for stacked/ragged/masked
    spaces."""
    from ..ops._precision import accum_dtype, reduction_dtype
    from ..parallel.collectives import reduce_stall
    flat = [v for p in pairs for v in p]
    if not _fusable(flat):
        return jnp.stack([_rdot(u, v) for (u, v) in pairs])
    rdt = reduction_dtype(_vdtype(pairs[0][0]))
    acc = accum_dtype(pairs[0][0]._arr.dtype)
    zs = [(u._arr * jnp.conj(v._arr)).astype(acc).reshape(-1)
          for (u, v) in pairs]
    k = jnp.abs(jnp.sum(jnp.stack(zs, axis=0), axis=-1)).astype(rdt)
    return reduce_stall(k)


def _stacked_bdot(pairs):
    """Block twin of :func:`_stacked_rdot`: m per-column dots over
    ``(n, K)`` block vectors → one all-reduce of an ``(m, K)`` tile.
    Ragged row splits mask their padding rows exactly as
    ``DistributedArray.col_dot`` does."""
    from ..ops._precision import accum_dtype, reduction_dtype
    from ..parallel.collectives import reduce_stall
    ref = pairs[0][0]
    rdt = reduction_dtype(_vdtype(ref))
    acc = accum_dtype(ref._arr.dtype)
    # every operand repacks into the FIRST pair element's physical
    # layout (operator outputs of a ragged split can pad differently
    # than RHS-derived vectors), so the m tiles stack into one buffer
    # and lower to a single fused reduction
    mask = None if ref._even else ref._valid_phys_mask()
    zs = []
    for (u, v) in pairs:
        z = (jnp.conj(ref._operand_phys(u))
             * ref._operand_phys(v)).astype(acc)
        if mask is not None:
            z = jnp.where(mask, z, 0)
        zs.append(z)
    k = jnp.abs(jnp.sum(jnp.stack(zs, axis=0), axis=1)).astype(rdt)
    return reduce_stall(k)


# ------------------------------------------------------ pipelined engine
def _make_pipe_body(applyA, xdt, floors, tol, *, M=None, guards=False,
                    carry_status=False, stall_n=0, block=False,
                    fault=None, name="cg"):
    """Pipelined (P)CG loop body over the carry ``(x, r[, u], w, z[,
    q], s, p, kold, aold, iiter, cost[, status][, bestk, stall])``.

    Invariants carried: ``u = M r`` (dropped when ``M is None`` —
    ``u`` IS ``r``), ``w = A u``; auxiliary directions ``z = A M w``-,
    ``q = M w``-, ``s = w``-, ``p = u``-companions of the classic
    search direction. Both dots — ``γ = (r, u)`` and the pipelined
    pivot ``δ = (w, u)`` — are issued as ONE stacked reduction at the
    top of the body, BEFORE the operator apply ``n = A M w``, so the
    collective and the matvec overlap. ``kold`` carries γ, which makes
    the loop's stopping test lag one iteration behind the classic
    engine (cost lane j holds the residual of iterate j-1; iteration
    counts agree within +1).

    ``block=True`` swaps per-column ``(K,)`` recurrence lanes, the
    ``max(floors, tol)`` per-column freeze and per-column guard
    verdicts in — the same unified body serves all four pipelined
    engines."""
    from ..resilience import faults as _faults, status as _rstatus
    from .basic import _fault_sites
    precond = M is not None
    nan_at, stall_at = _fault_sites(guards, fault)
    dot2 = _stacked_bdot if block else _stacked_rdot

    def body(state):
        if precond:
            x, r, u, w, z, q, s, p = state[:8]
            rest = state[8:]
        else:
            x, r, w, z, s, p = state[:6]
            u, q = r, s
            rest = state[6:]
        if guards:
            kold, aold, iiter, cost, status, bestk, stall = rest
        elif carry_status:
            kold, aold, iiter, cost, status = rest
            bestk = stall = None
        else:
            kold, aold, iiter, cost = rest
            status = bestk = stall = None
        # the single reduction, first — everything below overlaps it
        g = dot2(((r, u), (w, u)))
        gamma, delta = g[0], g[1]
        m = _precond_apply(M, w, xdt)
        n = applyA(m)
        if nan_at is not None:
            n = _faults.inject_nan(n, iiter, nan_at)
        # block freeze tests the CARRIED γ (kold), not the one just
        # reduced: the single-RHS while-cond exits after the body has
        # applied the update its own γ drove, so a column must apply
        # that same last update before freezing — per-column iterates
        # stay bit-identical to their single-RHS solves
        done = (kold <= jnp.maximum(floors, tol)) if block \
            else (gamma <= floors)
        if block and (guards or carry_status):
            done = done | (status != _rstatus.RUNNING)
        zero = jnp.zeros_like(gamma)
        b = jnp.where((iiter == 0) | done, zero, gamma / kold)
        a = jnp.where(done, zero, gamma / (delta - b * gamma / aold))
        if stall_at is not None:
            a = _faults.inject_stall(a, iiter, stall_at)
        bs = _step_scalar(b, xdt)
        as_ = _step_scalar(a, xdt)
        zn = n + z * bs
        sn = w + s * bs
        pn = u + p * bs
        if precond:
            qn = m + q * bs
            un = u - qn * as_
        xn = x + pn * as_
        rn = r - sn * as_
        wn = w - zn * as_
        k = gamma
        if guards:
            if block:
                bad = ((~jnp.isfinite(a)) | (~jnp.isfinite(b))
                       | (~jnp.isfinite(gamma)) | (~jnp.isfinite(delta)))
            else:
                bad = (jnp.any(~jnp.isfinite(a))
                       | jnp.any(~jnp.isfinite(b))
                       | jnp.any(~jnp.isfinite(gamma))
                       | jnp.any(~jnp.isfinite(delta)))
            x = _reject(bad, x, xn)
            r = _reject(bad, r, rn)
            w = _reject(bad, w, wn)
            z = _reject(bad, z, zn)
            s = _reject(bad, s, sn)
            p = _reject(bad, p, pn)
            if precond:
                u = _reject(bad, u, un)
                q = _reject(bad, q, qn)
            k = jnp.where(bad, kold, gamma)
            upd = _bguard_update if block else _guard_update
            status, bestk, stall = upd(status, bestk, stall, bad, k,
                                       done, stall_n)
            aold = jnp.where(bad | done, aold, a)
        else:
            x, r, w, z, s, p = xn, rn, wn, zn, sn, pn
            if precond:
                u, q = un, qn
            aold = jnp.where(done, aold, a)
        iiter = iiter + 1
        cost = lax.dynamic_update_index_in_dim(cost, jnp.sqrt(k), iiter, 0)
        telemetry.iteration(name, iiter, resid=jnp.sqrt(k), k=k, alpha=a)
        head = (x, r, u, w, z, q, s, p) if precond else (x, r, w, z, s, p)
        if guards:
            return head + (k, aold, iiter, cost, status, bestk, stall)
        if carry_status:
            return head + (k, aold, iiter, cost, status)
        return head + (k, aold, iiter, cost)

    return body


def _pipe_seed(applyA, dot1, r, u, niter, precond, x):
    """Shared tail of the pipelined setups: seed ``w``, the recurrence
    scalars and the alias head (the first body overwrites every
    auxiliary direction because ``b = 0`` at ``iiter == 0``, so they
    start as aliases — no extra buffers, no extra flops)."""
    w = applyA(u)
    kold = dot1(r, u)
    floors = _mp_floor(kold)
    aold = jnp.ones_like(kold)
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                      dtype=jnp.asarray(kold).dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold), 0, 0)
    if precond:
        head = (x, r, u, w, w, u, w, u)
    else:
        head = (x, r, w, w, w, r)
    return head, kold, floors, aold, cost0


def _pipe_cg_seed(Op, y, x0, *, niter, M, block):
    xdt = _vdtype(x0)
    x = x0  # donated: the carry aliases the caller's buffer in place
    r = y - Op.matvec(x)
    u = _precond_apply(M, r, xdt)
    dot1 = _bdot if block else _rdot
    return _pipe_seed(Op.matvec, dot1, r, u, niter, M is not None, x)


def _normal_apply(Op, damp2, xdt, normal):
    """``v → (AᴴA + damp²I) v`` — the operator the pipelined CGLS body
    iterates on. ``normal=True`` uses the one-sweep fused
    ``Op.normal_matvec`` (same opt-in as classic ``cgls(normal=True)``)."""
    d2 = _step_scalar(damp2, xdt)
    if normal:
        def applyA(v):
            u2, _ = Op.normal_matvec(v)
            return u2 + v * d2
    else:
        def applyA(v):
            return Op.rmatvec(Op.matvec(v)) + v * d2
    return applyA


def _pipe_cgls_seed(Op, y, x0, damp, damp2, *, niter, normal, M, block):
    """Pipelined CGLS setup. Matches the classic ``_cgls_setup``
    recurrence seed exactly — including the reference quirk of damping
    the initial residual by ``damp`` (not ``damp²``) — so ``kold``,
    ``floors`` and ``cost[0]`` agree with the classic engine; the
    carried residual is the TRUE damped normal residual."""
    xdt = _vdtype(x0)
    applyA = _normal_apply(Op, damp2, xdt, normal)
    dot1 = _bdot if block else _rdot
    x = x0
    s0 = y - Op.matvec(x)
    rq = Op.rmatvec(s0) - x * _step_scalar(damp, xdt)
    zq = _precond_apply(M, rq, xdt)
    kold = dot1(rq, zq)
    floors = _mp_floor(kold)
    r = rq + x * _step_scalar(damp - damp2, xdt)
    u = _precond_apply(M, r, xdt)
    w = applyA(u)
    aold = jnp.ones_like(kold)
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                      dtype=jnp.asarray(kold).dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold), 0, 0)
    if M is not None:
        head = (x, r, u, w, w, u, w, u)
    else:
        head = (x, r, w, w, w, r)
    return head, kold, floors, aold, cost0, applyA


def _pipe_loop(body, head, kold, aold, cost0, niter, tol, *, guards,
               block, precond):
    """Assemble carry + cond and run the pipelined while_loop; returns
    ``(x, kold, iiter, cost[, resolved_status])``."""
    from ..resilience import status as _rstatus
    nh = 8 if precond else 6
    base = head + (kold, aold, jnp.asarray(0), cost0)
    if guards:
        if block:
            K = kold.shape[0]
            st0 = (_status0(K), kold, jnp.zeros((K,), jnp.int32))
        else:
            st0 = (_i32(_rstatus.RUNNING), jnp.max(kold), _i32(0))
        state = base + st0

        if block:
            def cond(st):
                return ((st[nh + 2] < niter)
                        & jnp.any((st[nh] > tol)
                                  & (st[nh + 4] == _rstatus.RUNNING)))
        else:
            def cond(st):
                return ((st[nh + 2] < niter)
                        & (jnp.max(st[nh]) > tol)
                        & (st[nh + 4] == _rstatus.RUNNING))

        out = lax.while_loop(cond, body, state)
        resolve = _bresolve if block else _resolve_status
        return (out[0], out[nh], out[nh + 2], out[nh + 3],
                resolve(out[nh + 4], out[nh], tol))

    def cond(st):
        return (st[nh + 2] < niter) & (jnp.max(st[nh]) > tol)

    out = lax.while_loop(cond, body, state := base)
    return out[0], out[nh], out[nh + 2], out[nh + 3]


def _pipe_cg_fused(Op, y, x0, tol, *, niter, M=None, guards=False,
                   stall_n=0, fault=None, block=False):
    """Whole pipelined (P)CG solve as one ``lax.while_loop`` — the CA
    twin of ``basic._cg_fused`` (same return contract).

    Also the autodiff tier's traced CA seam (autodiff/implicit.py):
    under a non-``off`` CA mode, traced forward/backward solves inline
    THIS builder for both ``pipelined`` and ``sstep`` — the s-step
    engine's host-side breakdown fallback (``run_sstep_*``) cannot run
    inside a trace, and the pipelined twin is its communication
    equivalent (one fused reduction per iteration)."""
    head, kold, floors, aold, cost0 = _pipe_cg_seed(
        Op, y, x0, niter=niter, M=M, block=block)
    body = _make_pipe_body(Op.matvec, _vdtype(x0), floors, tol, M=M,
                           guards=guards, stall_n=stall_n, block=block,
                           fault=fault,
                           name="block_cg" if block else "cg")
    out = _pipe_loop(body, head, kold, aold, cost0, niter, tol,
                     guards=guards, block=block, precond=M is not None)
    if guards:
        x, kold, iiter, cost, status = out
        return x, iiter, cost, status
    x, kold, iiter, cost = out
    return x, iiter, cost


def _pipe_cgls_fused(Op, y, x0, damp, tol, *, niter, normal=False,
                     M=None, guards=False, stall_n=0, fault=None,
                     block=False):
    """Whole pipelined (P)CGLS solve — pipelined CG on the damped
    normal system; return contract of ``basic._cgls_fused_any``
    (``cost1`` aliases ``cost``: both lanes are the normal-residual
    norm here)."""
    damp2 = damp ** 2
    head, kold, floors, aold, cost0, applyA = _pipe_cgls_seed(
        Op, y, x0, damp, damp2, niter=niter, normal=normal, M=M,
        block=block)
    body = _make_pipe_body(applyA, _vdtype(x0), floors, tol, M=M,
                           guards=guards, stall_n=stall_n, block=block,
                           fault=fault,
                           name="block_cgls" if block else "cgls")
    out = _pipe_loop(body, head, kold, aold, cost0, niter, tol,
                     guards=guards, block=block, precond=M is not None)
    if guards:
        x, kold, iiter, cost, status = out
        return x, iiter, cost, cost, kold, status
    x, kold, iiter, cost = out
    return x, iiter, cost, cost, kold


# ------------------------------------------------------ s-step engine
def _sstep_eligible(*vs) -> bool:
    """s-step needs the fused Gram matmul: plain even unmasked real
    DistributedArray spaces only (signed inner products — ``abs`` would
    corrupt the coordinate recurrences, so complex is out)."""
    for v in vs:
        if not isinstance(v, DistributedArray):
            return False
        if v.mask is not None or not v._even:
            return False
        if np.issubdtype(np.dtype(v.dtype), np.complexfloating):
            return False
    return True


def _sstep_maps(s: int):
    """Static coordinate operators for the 2s+1-column combined basis
    ``V = [V_0..V_s | Z_0..Z_{s-1}]`` with products ``W = [W_0..W_{s-1}
    | Y_0..Y_{s-2}]`` (``W_j = A V_j``, ``Y_j = A Z_j``):
    ``Amap`` maps V-coordinates to W-coordinates of ``A·``, ``Smap``
    shifts V-coordinates by one application of ``M A``. Degrees stay in
    range by construction: at inner step j the direction has V-degree j
    (≤ s-1) and the residual-companion Z-degree j-1 (≤ s-2)."""
    nv, nw = 2 * s + 1, 2 * s - 1
    Amap = np.zeros((nw, nv))
    Smap = np.zeros((nv, nv))
    for j in range(s):
        Amap[j, j] = 1.0            # A V_j = W_j
        Smap[j + 1, j] = 1.0        # (MA) V_j = V_{j+1}
    for j in range(s - 1):
        Amap[s + j, s + 1 + j] = 1.0        # A Z_j = Y_j
        Smap[s + 2 + j, s + 1 + j] = 1.0    # (MA) Z_j = Z_{j+1}
    return Amap, Smap


def _make_sstep_body(Op, xdt, floors, tol, *, s, niter, M=None,
                     guards=False, stall_n=0):
    """s-step CA-CG outer body: build the monomial block (2s-1 operator
    applies, local), pay ONE Gram all-reduce, run s coordinate-space CG
    steps (replicated small vectors, zero communication), recombine.
    A non-finite or non-positive pivot is the monomial-basis
    conditioning guard: the whole outer update is rejected (the carry
    keeps the last completed outer iterate) and ``status=BREAKDOWN``."""
    from ..ops._precision import accum_dtype
    from ..parallel.collectives import reduce_stall
    from ..resilience import status as _rstatus
    precond = M is not None
    Amap_np, Smap_np = _sstep_maps(s)
    nv, nw = 2 * s + 1, 2 * s - 1

    def body(state):
        if precond:
            x, r, p, z = state[:4]
            rest = state[4:]
        else:
            x, r, p = state[:3]
            z = r
            rest = state[3:]
        kold, iiter, cost, status, bestk, stall = rest
        acc = accum_dtype(x._arr.dtype)
        Amap = jnp.asarray(Amap_np, acc)
        Smap = jnp.asarray(Smap_np, acc)
        # monomial chains: V from the direction p, Z from the
        # (preconditioned) residual z — all operator applies, no dots
        V_cols, W_cols = [p], []
        v = p
        for _ in range(s):
            Av = Op.matvec(v)
            W_cols.append(Av)
            v = _precond_apply(M, Av, xdt)
            V_cols.append(v)
        Z_cols, Y_cols = [z], []
        zc = z
        for _ in range(s - 1):
            Az = Op.matvec(zc)
            Y_cols.append(Az)
            zc = _precond_apply(M, Az, xdt)
            Z_cols.append(zc)
        Vm = jnp.stack([c._arr for c in V_cols + Z_cols],
                       axis=0).astype(acc)              # (2s+1, n)
        Wm = jnp.stack([c._arr for c in W_cols + Y_cols] + [r._arr],
                       axis=0).astype(acc)              # (2s, n)
        # THE one collective of the outer step: every inner product s
        # iterations of CG will touch, in a single (2s+1, 2s) tile
        Gall = reduce_stall(Vm @ Wm.T)
        G = Gall[:, :nw]        # (2s+1, 2s-1): (V_i, W_j)
        g0 = Gall[:, nw]        # (2s+1,):      (V_i, r0)
        cp = jnp.zeros((nv,), acc).at[0].set(1.0)       # p = V_0
        cz = jnp.zeros((nv,), acc).at[s + 1].set(1.0)   # z = Z_0
        d = jnp.zeros((nw,), acc)
        e = jnp.zeros((nv,), acc)
        k_run = kold.astype(acc)
        bad = jnp.asarray(False)
        iit = iiter
        tol_floor = jnp.maximum(floors.astype(acc), jnp.asarray(tol, acc))
        for _j in range(s):
            gamma = g0 @ cz - d @ (G.T @ cz)
            done = (k_run <= tol_floor) | (iit >= niter)
            acp = Amap @ cp
            delta = acp @ (G.T @ cp)
            alpha = gamma / delta
            sick = (~jnp.isfinite(alpha)) | (~jnp.isfinite(gamma)) \
                | (~jnp.isfinite(delta)) | (delta <= 0)
            bad = bad | (sick & ~done)
            live = ~done & ~bad
            alpha = jnp.where(live, alpha, 0.0)
            e = e + alpha * cp
            d = d + alpha * acp
            cz = cz - alpha * (Smap @ cp)
            gamma_n = g0 @ cz - d @ (G.T @ cz)
            beta = jnp.where(live, gamma_n / gamma, 0.0)
            cp = jnp.where(live, cz + beta * cp, cp)
            k_run = jnp.where(live, jnp.abs(gamma_n), k_run)
            iit = iit + jnp.where(live, 1, 0)
            cost = lax.dynamic_update_index_in_dim(
                cost, jnp.sqrt(k_run).astype(cost.dtype), iit, 0)
        # recombination — one local matvec against the stored basis
        def comb(base, coeff, mat):
            upd = (coeff @ mat).astype(base.dtype)
            return DistributedArray._wrap(base._arr + upd, base)

        xn = comb(x, e, Vm)
        rn = DistributedArray._wrap(
            r._arr - (d @ Wm[:nw]).astype(r.dtype), r)
        pn = DistributedArray._wrap((cp @ Vm).astype(r.dtype), r)
        zn = DistributedArray._wrap((cz @ Vm).astype(r.dtype), r)
        x = _reject(bad, x, xn)
        r = _reject(bad, r, rn)
        p = _reject(bad, p, pn)
        if precond:
            z = _reject(bad, z, zn)
        k = jnp.where(bad, kold, k_run.astype(kold.dtype))
        done_f = k <= jnp.maximum(floors, jnp.asarray(tol, kold.dtype))
        status, bestk, stall = _guard_update(
            status, bestk, stall, bad, k, done_f,
            stall_n if guards else _NO_STALL)
        telemetry.iteration("cg", iit, resid=jnp.sqrt(k), k=k,
                            alpha=jnp.asarray(0.0))
        head = (x, r, p, z) if precond else (x, r, p)
        return head + (k, iit, cost, status, bestk, stall)

    return body


def _sstep_cg_seed(Op, y, x0, *, niter, M):
    xdt = _vdtype(x0)
    x = x0  # donated
    r = y - Op.matvec(x)
    z = _precond_apply(M, r, xdt)
    kold = _rdot(r, z)
    floors = _mp_floor(kold)
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                      dtype=jnp.asarray(kold).dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold), 0, 0)
    head = (x, r, z, z) if M is not None else (x, r, r)
    return head, kold, floors, cost0


def _sstep_cg_fused(Op, y, x0, tol, *, niter, s, M=None, guards=False,
                    stall_n=0):
    """Whole s-step CA-CG solve as one ``lax.while_loop``; ALWAYS
    returns ``(x, iiter, cost, status)`` — the status word carries the
    basis-conditioning verdict the host fallback wrapper needs even on
    the unguarded path."""
    from ..resilience import status as _rstatus
    head, kold, floors, cost0 = _sstep_cg_seed(Op, y, x0, niter=niter,
                                               M=M)
    body = _make_sstep_body(Op, _vdtype(x0), floors, tol, s=s,
                            niter=niter, M=M, guards=guards,
                            stall_n=stall_n)
    nh = 4 if M is not None else 3
    state = head + (kold, jnp.asarray(0), cost0,
                    _i32(_rstatus.RUNNING), jnp.max(kold), _i32(0))

    def cond(st):
        return ((st[nh + 1] < niter) & (jnp.max(st[nh]) > tol)
                & (st[nh + 3] == _rstatus.RUNNING))

    out = lax.while_loop(cond, body, state)
    x, kold, iiter, cost, status = (out[0], out[nh], out[nh + 1],
                                    out[nh + 2], out[nh + 3])
    return x, iiter, cost, _resolve_status(status, kold, tol)


# ------------------------------------------------------ runners
def _guard_ctx(Op, guards):
    """(fault spec, stall window, extra key parts) for a guarded build
    — the same consume-once contract as the classic runners."""
    if not guards:
        return None, 0, ()
    from ..resilience import faults as _faults, status as _rstatus
    spec = _faults.consume()
    return spec, _rstatus.stall_window(), (
        _rstatus.guards_signature(True), _faults.fault_signature(spec))


def _call_pipe_cg(Op, y, x0, x0_owned, niter, tol, guards, M, *,
                  block=False, spec=None, stall_n=0, extra=()):
    name = "block_cg" if block else "cg"
    fn = _get_fused(Op, (id(Op), "ca-" + name, niter, _vkey(y),
                         _vkey(x0)) + extra + ca_key("pipelined")
                    + _mkey(M),
                    lambda op: partial(_pipe_cg_fused, op, niter=niter,
                                       guards=guards, M=M,
                                       stall_n=stall_n, fault=spec,
                                       block=block),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None and spec is None))
    out = fn(y, x0 if x0_owned else _donate_copy(x0), tol)
    if guards:
        x, iiter, cost, status = out
        return x, int(iiter), cost, status
    x, iiter, cost = out
    return x, int(iiter), cost, None


def run_cg_fused(Op, y, x0, x0_owned, niter, tol, guards, M=None,
                 mode="pipelined"):
    """CA twin of ``basic._run_cg_fused`` — same return contract
    ``(x, iiter, cost, status_code_or_None)``. ``mode="sstep"``
    downgrades to pipelined when the space is ineligible or a chaos
    fault is armed (faults inject at the classic per-iteration seams),
    and falls back to pipelined from the last completed outer iterate
    on a basis-conditioning breakdown."""
    from ..resilience import status as _rstatus
    from ..utils import deps as _deps
    spec, stall_n, extra = _guard_ctx(Op, guards)
    if mode == "sstep" and (spec is not None
                            or not _sstep_eligible(y, x0)):
        mode = "pipelined"
    if mode == "sstep":
        s = _deps.ca_s_default()
        fn = _get_fused(Op, (id(Op), "ca-cg", niter, _vkey(y),
                             _vkey(x0)) + extra + ca_key("sstep", s)
                        + _mkey(M),
                        lambda op: partial(_sstep_cg_fused, op,
                                           niter=niter, s=s, guards=guards,
                                           M=M, stall_n=stall_n),
                        donate_argnums=_DONATE_X0, keepalive=M,
                        aot_eligible=(M is None))
        x, iiter, cost, status = fn(
            y, x0 if x0_owned else _donate_copy(x0), tol)
        iiter, code = int(iiter), int(status)
        cost = np.asarray(cost)[:iiter + 1]
        if code == _rstatus.BREAKDOWN and iiter < niter:
            # monomial-basis conditioning guard fired: restart the
            # remaining budget on the s=1 (pipelined) engine from the
            # last completed outer iterate
            _record_fallback("cg", s, iiter)
            x, it2, cost2, status2 = _call_pipe_cg(
                Op, y, x, True, niter - iiter, tol, guards, M,
                stall_n=stall_n, extra=extra)
            cost = np.concatenate([cost, np.asarray(cost2)[1:it2 + 1]])
            iiter = iiter + it2
            code = int(status2) if status2 is not None else None
        elif not guards:
            code = None
    else:
        x, iiter, cost, status = _call_pipe_cg(
            Op, y, x0, x0_owned, niter, tol, guards, M, spec=spec,
            stall_n=stall_n, extra=extra)
        cost = np.asarray(cost)[:iiter + 1]
        code = int(status) if status is not None else None
    _metrics.inc("solver.cg.solves")
    _metrics.inc("solver.cg.iterations", iiter)
    if guards:
        _rstatus.record("cg", code, iiter)
        return x, iiter, cost, code
    return x, iiter, cost, None


def run_cgls_fused(Op, y, x0, x0_owned, niter, damp, tol, use_normal,
                   guards, M=None, mode="pipelined"):
    """CA twin of ``basic._run_cgls_fused`` — returns ``(x, iiter,
    cost, cost1, kold, status_code_or_None)``. Both CA modes solve the
    damped normal system, so ``cost``/``cost1`` carry the
    normal-residual norm ``sqrt(γ)``; ``sstep`` on the normal operator
    keeps the same breakdown→pipelined fallback as CG."""
    from ..resilience import status as _rstatus
    spec, stall_n, extra = _guard_ctx(Op, guards)
    # s-step CGLS would need the normal-operator chains; the pipelined
    # engine already collapses every CGLS dot into one reduction, so
    # sstep requests route there (docs/ca.md)
    fn = _get_fused(Op, (id(Op), "ca-cgls", use_normal, niter,
                         _vkey(y), _vkey(x0)) + extra
                    + ca_key("pipelined") + _mkey(M),
                    lambda op: partial(_pipe_cgls_fused, op, niter=niter,
                                       normal=use_normal, guards=guards,
                                       M=M, stall_n=stall_n, fault=spec),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None and spec is None))
    out = fn(y, x0 if x0_owned else _donate_copy(x0), damp, tol)
    if guards:
        x, iiter, cost, cost1, kold, status = out
        iiter, code = int(iiter), int(status)
    else:
        x, iiter, cost, cost1, kold = out
        iiter, code = int(iiter), None
    _metrics.inc("solver.cgls.solves")
    _metrics.inc("solver.cgls.iterations", iiter)
    if guards:
        _rstatus.record("cgls", code, iiter)
    return (x, iiter, np.asarray(cost)[:iiter + 1],
            np.asarray(cost1)[:iiter + 1], kold, code)


def run_block_cg(Op, y, x0, x0_owned, niter, tol, guards, M=None,
                 mode="pipelined"):
    """Pipelined block CG (K > 1): same public contract as the fused
    section of ``block.block_cg`` — ``(x, iiter, cost_np)`` with
    per-column status words recorded. s-step has no block variant
    (the Gram tile would grow with K); it pipelines."""
    from ..resilience import status as _rstatus
    spec, stall_n, extra = _guard_ctx(Op, guards)
    x, iiter, cost, status = _call_pipe_cg(
        Op, y, x0, x0_owned, niter, tol, guards, M, block=True,
        spec=spec, stall_n=stall_n, extra=extra)
    _metrics.inc("solver.block_cg.solves")
    _metrics.inc("solver.block_cg.iterations", iiter)
    if guards:
        _rstatus.record_columns(
            "block_cg", [int(cd) for cd in np.asarray(status)], iiter)
    return x, iiter, np.asarray(cost)[:iiter + 1]


def run_block_cgls(Op, y, x0, x0_owned, niter, damp, tol, guards,
                   M=None, mode="pipelined"):
    """Pipelined block CGLS (K > 1): public contract of
    ``block.block_cgls``'s fused section — ``(x, istop, iiter, kold,
    r2norm, cost)`` with the CA cost-lane caveat (normal-residual
    norms)."""
    from ..resilience import status as _rstatus
    spec, stall_n, extra = _guard_ctx(Op, guards)
    fn = _get_fused(Op, (id(Op), "ca-block_cgls", niter, _vkey(y),
                         _vkey(x0)) + extra + ca_key("pipelined")
                    + _mkey(M),
                    lambda op: partial(_pipe_cgls_fused, op, niter=niter,
                                       normal=False, guards=guards, M=M,
                                       stall_n=stall_n, fault=spec,
                                       block=True),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None and spec is None))
    out = fn(y, x0 if x0_owned else _donate_copy(x0), damp, tol)
    if guards:
        x, iiter, cost, cost1, kold, status = out
        iiter = int(iiter)
        _rstatus.record_columns(
            "block_cgls", [int(cd) for cd in np.asarray(status)], iiter)
    else:
        x, iiter, cost, cost1, kold = out
        iiter = int(iiter)
    _metrics.inc("solver.block_cgls.solves")
    _metrics.inc("solver.block_cgls.iterations", iiter)
    kold = np.asarray(kold)
    istop = np.where(kold < tol, 1, 2)
    return (x, istop, iiter, kold, np.asarray(cost1)[iiter],
            np.asarray(cost)[:iiter + 1])


# ------------------------------------------------------ segmented seams
def seg_fields(solver: str, mode: str, M) -> tuple:
    """Checkpoint field names of a CA segmented carry (the classic
    drivers' ``_CG_FIELDS`` analogue) — the pytree the epoch program
    threads and the checkpoint stores, keyed by engine and by the
    ``M=None`` carry elision."""
    if mode == "sstep":
        head = ("x", "r", "p", "z") if M is not None else ("x", "r", "p")
        return head + ("kold", "iiter", "cost", "status", "bestk",
                       "stall")
    if M is not None:
        head = ("x", "r", "u", "w", "z", "q", "s", "p")
    else:
        head = ("x", "r", "w", "z", "s", "p")
    return head + ("kold", "aold", "iiter", "cost", "status", "bestk",
                   "stall")


def check_resume_ca(state: dict, mode: str, s: Optional[int] = None):
    """Refuse a resume whose checkpoint was written under a different
    CA engine — the carries are different pytrees with different
    semantics. Pre-CA checkpoints carry no ``ca`` key and count as
    ``off``."""
    got = str(state.get("ca", "off"))
    want = mode
    if got != want:
        raise ValueError(
            f"fused-carry checkpoint was written with ca={got!r} but "
            f"this run requests ca={want!r}: resume must replay the "
            "same plan (set PYLOPS_MPI_TPU_CA to match or restart "
            "without resume=True)")
    if mode == "sstep":
        got_s = int(state.get("ca_s", 0))
        if s is not None and got_s != int(s):
            raise ValueError(
                f"fused-carry checkpoint was written with s={got_s} "
                f"but this run requests s={int(s)}: resume must replay "
                "the same plan")


def pipe_cg_setup_builder(Op, *, niter, M=None):
    """Segmented setup: returns the head vectors + ``(kold, aold,
    cost0, floors)`` — the driver seeds ``iiter``/status triple."""
    def setup(y, x0):
        head, kold, floors, aold, cost0 = _pipe_cg_seed(
            Op, y, x0, niter=niter, M=M, block=False)
        return head + (kold, aold, cost0, floors)

    return setup


def pipe_cgls_setup_builder(Op, *, niter, normal=False, M=None):
    def setup(y, x0, damp, damp2):
        head, kold, floors, aold, cost0, _ = _pipe_cgls_seed(
            Op, y, x0, damp, damp2, niter=niter, normal=normal, M=M,
            block=False)
        return head + (kold, aold, cost0, floors)

    return setup


def _pipe_epoch(applyA_of, fields_n, *, guards, stall_n, M, name):
    """Shared segmented epoch runner for the pipelined engines.
    ``applyA_of(damp2)`` binds the iterated operator (CG ignores the
    operand). Signature matches the classic epoch builders: ``run(y,
    *fields, floors[, damp2], tol, epoch_end)`` and returns the full
    field tuple (status triple always included — unguarded bodies
    thread the status word and pass ``bestk``/``stall`` through)."""
    from ..resilience import status as _rstatus
    precond = M is not None
    nh = 8 if precond else 6

    def run(y, *rest):
        vals = rest[:fields_n]
        tail = rest[fields_n:]
        if len(tail) == 4:
            floors, damp2, tol, epoch_end = tail
        else:
            floors, tol, epoch_end = tail
            damp2 = None
        xdt = _vdtype(vals[0])
        body = _make_pipe_body(applyA_of(damp2, xdt), xdt, floors, tol,
                               M=M, guards=guards,
                               carry_status=not guards,
                               stall_n=stall_n, name=name)
        if guards:
            def cond(st):
                return ((st[nh + 2] < epoch_end)
                        & (jnp.max(st[nh]) > tol)
                        & (st[nh + 4] == _rstatus.RUNNING))

            return lax.while_loop(cond, body, vals)

        def cond(st):
            return ((st[nh + 2] < epoch_end)
                    & (jnp.max(st[nh]) > tol)
                    & (st[nh + 4] == _rstatus.RUNNING))

        out = lax.while_loop(cond, body, vals[:-2])
        return out + tuple(vals[-2:])

    return run


def pipe_cg_epoch_builder(Op, *, guards, stall_n, M=None):
    n = len(seg_fields("cg", "pipelined", M))
    return _pipe_epoch(lambda damp2, xdt: Op.matvec, n, guards=guards,
                       stall_n=stall_n, M=M, name="cg")


def pipe_cgls_epoch_builder(Op, *, guards, stall_n, normal=False,
                            M=None):
    n = len(seg_fields("cgls", "pipelined", M))
    return _pipe_epoch(
        lambda damp2, xdt: _normal_apply(Op, damp2, xdt, normal), n,
        guards=guards, stall_n=stall_n, M=M, name="cgls")


def sstep_cg_setup_builder(Op, *, niter, M=None):
    def setup(y, x0):
        head, kold, floors, cost0 = _sstep_cg_seed(Op, y, x0,
                                                   niter=niter, M=M)
        return head + (kold, cost0, floors)

    return setup


def sstep_cg_epoch_builder(Op, *, s, niter, guards, stall_n, M=None):
    """Segmented s-step epochs: each outer body advances up to ``s``
    iterations, so an epoch may overshoot its boundary by at most
    ``s-1`` iterations (checkpoints land AT OR AFTER the requested
    boundary — the identity contract is per-carry, not per-boundary).
    A breakdown surfaces as ``status=BREAKDOWN`` and stops the driver;
    segmented runs do NOT auto-fall back (the caller restarts under
    ``PYLOPS_MPI_TPU_CA=pipelined``, which the mode-stamped carry then
    enforces)."""
    from ..resilience import status as _rstatus
    fields_n = len(seg_fields("cg", "sstep", M))
    nh = 4 if M is not None else 3

    def run(y, *rest):
        vals = rest[:fields_n]
        floors, tol, epoch_end = rest[fields_n:]
        body = _make_sstep_body(Op, _vdtype(vals[0]), floors, tol, s=s,
                                niter=niter, M=M, guards=guards,
                                stall_n=stall_n)

        def cond(st):
            return ((st[nh + 1] < epoch_end)
                    & (jnp.max(st[nh]) > tol)
                    & (st[nh + 3] == _rstatus.RUNNING))

        return lax.while_loop(cond, body, vals)

    return run

"""ISTA / FISTA sparse solvers.

Rebuild of ``pylops_mpi/optimization/cls_sparsity.py`` (ISTA ``49-485``,
FISTA ``486-715``) and the functional wrappers ``sparsity.py:11-257``.
Thresholding applies elementwise to the distributed model — the
reference thresholds each rank's local shard (``_apply_thresh``,
ref ``cls_sparsity.py:21-46``); here one jnp expression covers the
sharded array. Step size defaults to ``1/λmax(OpᴴOp)`` via
:func:`power_iteration` (ref ``239-255``); the residual-increase guard
(``monitorres``, ref ``298-307``) and per-iteration cost
``½‖r‖² + ε‖x‖₁`` are preserved.

Two execution paths, mirroring ``solvers/basic.py``:

- **class API** (`ISTA`, `FISTA`): reference-parity ``setup/step/run``
  with ``callback``/``monitorres`` hooks (host-synced scalars, as the
  reference's mechanics demand, ref ``cls_sparsity.py:309-343``).
- **fused path** (functional ``ista``/``fista`` default when no
  callback/show/monitorres): the whole solve is one ``lax.while_loop``
  under ``jit`` — matvec, rmatvec, threshold, momentum and the norm
  ``psum``s compile into a single XLA program; cost history lives in a
  fixed-length on-device buffer, and no scalar crosses the host
  boundary per iteration (SURVEY §7: THE idiomatic-redesign win).

Threshold formulas match pylops' ``_softthreshold`` / ``_hardthreshold``
(cut at ``√(2·thresh)``) / ``_halfthreshold`` (cut at
``(54^⅓/4)·thresh^⅔``).
"""

from __future__ import annotations

import time
from functools import partial
from math import sqrt
from typing import Any, Callable, Dict, Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..stacked import StackedDistributedArray
from ..diagnostics import telemetry, trace as _trace
from .eigs import power_iteration

__all__ = ["ISTA", "FISTA", "ista", "fista", "ista_guarded",
           "fista_guarded"]

Vector = Union[DistributedArray, StackedDistributedArray]


def _softthreshold(x: jax.Array, thresh) -> jax.Array:
    if jnp.iscomplexobj(x):
        r = jnp.maximum(jnp.abs(x) - thresh, 0.0)
        return r * jnp.exp(1j * jnp.angle(x))
    return jnp.maximum(jnp.abs(x) - thresh, 0.0) * jnp.sign(x)


def _hardthreshold(x: jax.Array, thresh) -> jax.Array:
    return jnp.where(jnp.abs(x) <= jnp.sqrt(2 * thresh), 0, x)


def _halfthreshold(x: jax.Array, thresh) -> jax.Array:
    arg = jnp.clip((thresh / 8.0) * (jnp.abs(x) / 3.0) ** (-1.5), -1.0, 1.0)
    # Xu et al. half-thresholding: h(x) = 2/3 x (1 + cos(2π/3 − 2/3 φ)),
    # φ = arccos((λ/8)(|x|/3)^(−3/2)); a 2·φ here diverges the iteration
    phi = 2.0 / 3.0 * jnp.arccos(arg)
    x1 = 2.0 / 3.0 * x * (1 + jnp.cos(2.0 * jnp.pi / 3.0 - phi))
    cut = (54 ** (1.0 / 3.0) / 4.0) * thresh ** (2.0 / 3.0)
    return jnp.where(jnp.abs(x) <= cut, 0.0, x1)


_THRESHF = {"soft": _softthreshold, "hard": _hardthreshold,
            "half": _halfthreshold}


def _apply_thresh(x: Vector, threshf: Callable, thresh) -> Vector:
    """ref ``cls_sparsity.py:21-46``"""
    if isinstance(x, DistributedArray):
        return DistributedArray._wrap(threshf(x._arr, thresh), x)
    return StackedDistributedArray(
        [DistributedArray._wrap(threshf(d._arr, thresh), d)
         for d in x.distarrays])


class ISTA:
    """Iterative Shrinkage-Thresholding Algorithm
    (ref ``cls_sparsity.py:49-485``).

    The class ``setup``/``step``/``run`` API syncs 3-4 scalars to host
    per iteration (monitorres/callback parity with the reference) — it
    is the slow path; the functional :func:`ista`/:func:`fista` default
    to the fused on-device loop."""

    def __init__(self, Op):
        self.Op = Op
        self.callback = lambda x: None
        self.tstart = time.time()

    def setup(self, y: Vector, x0: Vector, niter: Optional[int] = None,
              SOp=None, eps: float = 0.1, alpha: Optional[float] = None,
              eigsdict: Optional[Dict[str, Any]] = None, tol: float = 1e-10,
              threshkind: str = "soft", perc: Optional[float] = None,
              decay: Optional[np.ndarray] = None, monitorres: bool = False,
              show: bool = False) -> Vector:
        if threshkind not in _THRESHF:
            raise NotImplementedError(
                "threshkind should be hard, soft or half")
        if perc is not None:
            raise NotImplementedError(
                "percentile thresholding is not implemented")
        self.y = y
        self.SOp = SOp
        self.niter = niter
        self.eps = eps
        self.tol = tol
        self.monitorres = monitorres
        self.threshf = _THRESHF[threshkind]
        self.eigsdict = {} if eigsdict is None else eigsdict
        self.decay = decay if decay is not None else np.ones(niter or 1)
        if alpha is not None:
            self.alpha = alpha
        else:
            # 1/λmax(OpᴴOp) via power iteration (ref 239-255)
            Op1 = self.Op.H @ self.Op
            maxeig = np.abs(power_iteration(
                Op1, b_k=x0.zeros_like() if isinstance(x0, DistributedArray)
                else x0.copy(), dtype=Op1.dtype, **self.eigsdict)[0])
            self.alpha = float(1.0 / maxeig)
        self.thresh = eps * self.alpha * 0.5
        x = x0.copy()
        if monitorres:
            self.normresold = np.inf
        self.t = 1.0
        self.cost = []
        self.iiter = 0
        if show:
            self._print_setup()
        return x

    def step(self, x: Vector, show: bool = False) -> Tuple[Vector, float]:
        """ref ``cls_sparsity.py:309-343``"""
        xold = x.copy()
        res = self.y - self.Op.matvec(x)
        if self.monitorres:
            normres = float(jnp.max(jnp.asarray(res.norm())))
            if normres > self.normresold:
                raise ValueError(
                    f"ISTA stopped at iteration {self.iiter} due to "
                    "residual increasing, consider modifying "
                    "eps and/or alpha...")
            self.normresold = normres
        grad = self.Op.rmatvec(res) * self.alpha
        x_unthresh = x + grad
        if self.SOp is not None:
            x_unthresh = self.SOp.rmatvec(x_unthresh)
        x = _apply_thresh(x_unthresh, self.threshf,
                          self.decay[min(self.iiter, len(self.decay) - 1)]
                          * self.thresh)
        if self.SOp is not None:
            x = self.SOp.matvec(x)
        xupdate = float(jnp.max(jnp.asarray((x - xold).norm())))
        costdata = 0.5 * float(jnp.max(jnp.asarray(res.norm()))) ** 2
        costreg = self.eps * float(jnp.max(jnp.asarray(x.norm(1))))
        self.cost.append(costdata + costreg)
        self.iiter += 1
        if show:
            self._print_step(x, costdata, costreg, xupdate)
        return x, xupdate

    def run(self, x: Vector, niter: Optional[int] = None, show: bool = False,
            itershow=(10, 10, 10)) -> Vector:
        xupdate = np.inf
        niter = self.niter if niter is None else niter
        if niter is None:
            raise ValueError("niter must not be None")
        while self.iiter < niter and xupdate > self.tol:
            showstep = show and (self.iiter < itershow[0]
                                 or niter - self.iiter < itershow[1]
                                 or self.iiter % itershow[2] == 0)
            x, xupdate = self.step(x, showstep)
            self.callback(x)
        return x

    def finalize(self, show: bool = False) -> None:
        self.tend = time.time()
        self.telapsed = self.tend - self.tstart
        self.cost = np.asarray(self.cost)

    def solve(self, y: Vector, x0: Vector, niter: Optional[int] = None,
              SOp=None, eps: float = 0.1, alpha: Optional[float] = None,
              eigsdict=None, tol: float = 1e-10, threshkind: str = "soft",
              perc=None, decay=None, monitorres: bool = False,
              show: bool = False, itershow=(10, 10, 10)
              ) -> Tuple[Vector, int, np.ndarray]:
        x = self.setup(y=y, x0=x0, niter=niter, SOp=SOp, eps=eps, alpha=alpha,
                       eigsdict=eigsdict, tol=tol, threshkind=threshkind,
                       perc=perc, decay=decay, monitorres=monitorres,
                       show=show)
        x = self.run(x, niter, show=show, itershow=itershow)
        self.finalize(show)
        return x, self.iiter, self.cost

    def _print_setup(self):
        print(f"{type(self).__name__}\neps = {self.eps:.2e}\t"
              f"alpha = {self.alpha:.2e}\tniter = {self.niter}")

    def _print_step(self, x, costdata, costreg, xupdate):
        print(f"{self.iiter:6g}  {costdata + costreg:11.4e}  "
              f"{xupdate:11.4e}")


class FISTA(ISTA):
    """Fast ISTA with Nesterov momentum
    (ref ``cls_sparsity.py:486-715``; momentum update ``645-649``)."""

    def setup(self, *args, **kwargs) -> Vector:
        x = super().setup(*args, **kwargs)
        self.z = x.copy()
        return x

    def step(self, x: Vector, show: bool = False) -> Tuple[Vector, float]:
        xold = x.copy()
        res = self.y - self.Op.matvec(self.z)
        if self.monitorres:
            normres = float(jnp.max(jnp.asarray(res.norm())))
            if normres > self.normresold:
                raise ValueError(
                    f"FISTA stopped at iteration {self.iiter} due to "
                    "residual increasing, consider modifying "
                    "eps and/or alpha...")
            self.normresold = normres
        grad = self.Op.rmatvec(res) * self.alpha
        x_unthresh = self.z + grad
        if self.SOp is not None:
            x_unthresh = self.SOp.rmatvec(x_unthresh)
        x = _apply_thresh(x_unthresh, self.threshf,
                          self.decay[min(self.iiter, len(self.decay) - 1)]
                          * self.thresh)
        if self.SOp is not None:
            x = self.SOp.matvec(x)
        told = self.t
        self.t = (1.0 + sqrt(1.0 + 4.0 * self.t ** 2)) / 2.0
        self.z = x + (x - xold) * ((told - 1.0) / self.t)
        xupdate = float(jnp.max(jnp.asarray((x - xold).norm())))
        costdata = 0.5 * float(jnp.max(jnp.asarray(
            (self.y - self.Op.matvec(x)).norm()))) ** 2
        costreg = self.eps * float(jnp.max(jnp.asarray(x.norm(1))))
        self.cost.append(costdata + costreg)
        self.iiter += 1
        if show:
            self._print_step(x, costdata, costreg, xupdate)
        return x, xupdate


# --------------------------------------------------------- fused (on-device)
def _ista_fused(Op, y: Vector, x0: Vector, alpha, eps, tol, decay,
                *, niter: int, threshf: Callable, SOp=None,
                momentum: bool = False, guards: bool = False,
                stall_n: int = 0, fault=None):
    """Whole ISTA/FISTA solve as one ``lax.while_loop``. The eager class
    API pulls 3-4 host floats per iteration (xupdate, costdata, costreg,
    optionally normres); here every scalar stays on device and the
    threshold/momentum arithmetic fuses into the matvec program.

    ``x0`` is DONATED (solvers/basic.py builder convention): the ``x``
    carry starts in the caller's buffer; the momentum carry ``z``
    shares the same initial value, so its init is the one unavoidable
    copy of the donated buffer.

    Dtype discipline (the while_loop carry must hold its dtypes at
    every iteration — solvers/basic.py ``_step_scalar``): the decay /
    step / momentum scalars are pinned to the model space's REAL dtype
    so a float64 python scalar can never promote an f32 carry, and the
    xupdate/cost scalars live at the policy reduction dtype.

    ``guards=True`` (ISSUE 6) appends a ``(status, bestc, stall)``
    guard carry — NaN/Inf in the cost or xupdate scalars reject the
    poisoned update (the carry keeps the last finite iterate) and exit
    with ``status=BREAKDOWN``; ``stall_n`` iterations without a new
    best cost exit with ``status=STAGNATION``. ``guards=False`` traces
    exactly the pre-guard program (bit-identity pin)."""
    from .basic import (_step_scalar, _vdtype, _reject, _guard_update,
                        _resolve_status, _i32, _fault_sites)
    from ..resilience import faults as _faults
    from ..ops._precision import reduction_dtype
    nan_at, stall_at = _fault_sites(guards, fault)
    xdt = _vdtype(x0)
    rdt = reduction_dtype(xdt)
    thresh = eps * alpha * 0.5
    decay_arr = jnp.asarray(decay, dtype=rdt)
    nd = decay_arr.shape[0]

    def threshold(v, iiter):
        tv = decay_arr[jnp.minimum(iiter, nd - 1)] * thresh
        return _apply_thresh(v, threshf, tv)

    def _relayout_like(template, v):
        """``v`` in ``template``'s shard layout (no-op when they already
        match). The while_loop carry must keep a STABLE pytree: with a
        sparsifying transform whose data layout differs from the
        model's (ragged shard counts, e.g. 8 blocks over 5 devices),
        ``SOp.matvec`` hands back a different layout than the carry
        entered with and tracing fails on pytree mismatch. Stacked
        vectors relayout component-wise."""
        if (isinstance(v, StackedDistributedArray)
                and isinstance(template, StackedDistributedArray)):
            return StackedDistributedArray(
                [_relayout_like(t, c) for t, c
                 in zip(template.distarrays, v.distarrays)])
        if (isinstance(v, DistributedArray)
                and isinstance(template, DistributedArray)
                and (v._axis != template._axis
                     or tuple(v._axis_sizes)
                     != tuple(template._axis_sizes))):
            return DistributedArray._wrap(template._operand_phys(v),
                                          template)
        return v

    def body(state):
        if guards:
            x, z, t, iiter, cost, _, status, bestc, stall = state
        else:
            x, z, t, iiter, cost, _ = state
        xin = z if momentum else x
        mv = Op.matvec(xin)
        if nan_at is not None:
            mv = _faults.inject_nan(mv, iiter, nan_at)
        res = y - mv
        step = _step_scalar(jnp.asarray(alpha, dtype=rdt), xdt)
        if stall_at is not None:
            step = _faults.inject_stall(step, iiter, stall_at)
        x_unthresh = xin + Op.rmatvec(res) * step
        if SOp is not None:
            x_unthresh = SOp.rmatvec(x_unthresh)
        xnew = threshold(x_unthresh, iiter)
        if SOp is not None:
            xnew = SOp.matvec(xnew)
        if momentum:
            # Nesterov sequence (ref cls_sparsity.py:645-649)
            tnew = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
            znew = xnew + (xnew - x) * _step_scalar((t - 1.0) / tnew,
                                                    xdt)
            costdata = 0.5 * jnp.max(jnp.asarray(
                (y - Op.matvec(xnew)).norm())) ** 2
        else:
            tnew, znew = t, xnew
            costdata = 0.5 * jnp.max(jnp.asarray(res.norm())) ** 2
        costreg = eps * jnp.max(jnp.asarray(xnew.norm(1)))
        xupdate = jnp.max(jnp.asarray((xnew - x).norm())).astype(rdt)
        costval = (costdata + costreg).astype(cost.dtype)
        xnew = _relayout_like(x, xnew)
        znew = _relayout_like(z, znew)
        if guards:
            bad = (jnp.any(~jnp.isfinite(costval))
                   | jnp.any(~jnp.isfinite(xupdate)))
            xnew = _reject(bad, x, xnew)
            znew = _reject(bad, z, znew)
            tnew = jnp.where(bad, t, tnew)
            # a rejected step must not look converged: keep the loop
            # exit decision on the status word, not a NaN-turned-zero
            xupdate = jnp.where(bad, jnp.asarray(jnp.inf, dtype=rdt),
                                xupdate)
            status, bestc, stall = _guard_update(
                status, bestc, stall, bad, costval,
                jnp.zeros_like(bad), stall_n)
        cost = lax.dynamic_update_index_in_dim(cost, costval, iiter, 0)
        # no-op unless telemetry is enabled (PYLOPS_MPI_TPU_TRACE=full)
        # — the disabled build traces NOTHING here (zero-callback pin)
        telemetry.iteration("fista" if momentum else "ista", iiter + 1,
                            cost=costdata + costreg, xupdate=xupdate)
        if guards:
            return (xnew, znew, tnew, iiter + 1, cost, xupdate, status,
                    bestc, stall)
        return (xnew, znew, tnew, iiter + 1, cost, xupdate)

    def cond(state):
        if guards:
            from ..resilience import status as _rstatus
            return ((state[3] < niter) & (state[5] > tol)
                    & (state[6] == _rstatus.RUNNING))
        return (state[3] < niter) & (state[5] > tol)

    x = x0          # donated: carry aliases the caller's buffer
    z = x0.copy()   # second carry from the same buffer: one real copy
    t0 = jnp.asarray(1.0, dtype=rdt)
    cost0 = jnp.zeros((niter,), dtype=t0.dtype)
    state = (x, z, t0, jnp.asarray(0), cost0,
             jnp.asarray(jnp.inf, dtype=rdt))
    if guards:
        from ..resilience import status as _rstatus
        state = state + (_i32(_rstatus.RUNNING),
                         jnp.asarray(jnp.inf, dtype=cost0.dtype),
                         _i32(0))
        out = lax.while_loop(cond, body, state)
        x, iiter, cost, xupdate, status = (out[0], out[3], out[4],
                                           out[5], out[6])
        return x, iiter, cost, _resolve_status(status, xupdate, tol)
    x, z, t, iiter, cost, xupdate = lax.while_loop(cond, body, state)
    return x, iiter, cost


def _sparse_fused_solve(Op, y, x0, niter, SOp, eps, alpha, eigsdict, tol,
                        threshkind, decay, momentum, guards=False):
    from .basic import _get_fused, _vkey, _donate_copy, _DONATE_X0

    if threshkind not in _THRESHF:
        raise NotImplementedError("threshkind should be hard, soft or half")
    if x0 is None:
        raise ValueError("x0 required")
    if alpha is None:
        # the dominant eigenvalue depends only on Op: cache it so
        # repeated ista/fista solves on one operator don't re-estimate
        # (each estimate builds a fresh Op.H @ Op whose power loop
        # cannot hit any compilation cache — pytree aux compares by
        # instance identity)
        ekey = (id(Op), "maxeig",
                tuple(sorted((eigsdict or {}).items())))
        from .basic import _FUSED_CACHE, _FUSED_CACHE_MAX
        hit = _FUSED_CACHE.get(ekey)
        if hit is not None:
            alpha = hit[0]
            _FUSED_CACHE.move_to_end(ekey)
        else:
            Op1 = Op.H @ Op
            b0 = x0.zeros_like() if isinstance(x0, DistributedArray) \
                else x0.copy()
            maxeig = np.abs(power_iteration(Op1, b_k=b0, dtype=Op1.dtype,
                                            **(eigsdict or {}))[0])
            alpha = float(1.0 / maxeig)
            _FUSED_CACHE[ekey] = (alpha, Op)
            if len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
                _FUSED_CACHE.popitem(last=False)
    decay = np.ones(niter) if decay is None else np.asarray(decay)
    name = "fista" if momentum else "ista"
    key = (id(Op), name, niter, threshkind,
           id(SOp) if SOp is not None else None, len(decay),
           _vkey(y), _vkey(x0))
    if guards:
        from ..resilience import faults as _faults, status as _rstatus
        spec = _faults.consume()
        stall_n = _rstatus.stall_window()
        key = key + (_rstatus.guards_signature(True),
                     _faults.fault_signature(spec))
        fn = _get_fused(Op, key,
                        lambda op: partial(_ista_fused, op, niter=niter,
                                           threshf=_THRESHF[threshkind],
                                           SOp=SOp, momentum=momentum,
                                           guards=True, stall_n=stall_n,
                                           fault=spec),
                        donate_argnums=_DONATE_X0)
        x, iiter, cost, status = fn(y, _donate_copy(x0), alpha, eps, tol,
                                    jnp.asarray(decay))
        iiter, code = int(iiter), int(status)
        _rstatus.record(name, code, iiter)
        return x, iiter, np.asarray(cost)[:iiter], code
    fn = _get_fused(Op, key,
                    lambda op: partial(_ista_fused, op, niter=niter,
                                       threshf=_THRESHF[threshkind],
                                       SOp=SOp, momentum=momentum),
                    donate_argnums=_DONATE_X0)
    x, iiter, cost = fn(y, _donate_copy(x0), alpha, eps, tol,
                        jnp.asarray(decay))
    iiter = int(iiter)
    return x, iiter, np.asarray(cost)[:iiter]


def ista(Op, y: Vector, x0: Optional[Vector] = None,
         niter: int = 10, SOp=None, eps: float = 0.1,
         alpha: Optional[float] = None, eigsdict=None, tol: float = 1e-10,
         threshkind: str = "soft", perc=None, decay=None,
         monitorres: bool = False, show: bool = False, itershow=(10, 10, 10),
         callback: Optional[Callable] = None, fused: Optional[bool] = None,
         guards: Optional[bool] = None):
    """Functional ISTA (ref ``optimization/sparsity.py:11-133``). With no
    callback/show/monitorres, runs the fused on-device loop. ``guards``
    resolves against ``PYLOPS_MPI_TPU_GUARDS`` (see
    :func:`pylops_mpi_tpu.solvers.basic.cg`); the status word lands in
    ``resilience.status.last_status("ista")``."""
    use_fused = fused if fused is not None else \
        (callback is None and not show and not monitorres and perc is None)
    from ..resilience.status import guards_enabled
    use_guards = use_fused and guards_enabled(guards)
    with _trace.span("solver.ista", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, niter=niter, eps=eps,
                     threshkind=threshkind, fused=use_fused,
                     guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()):
        if use_fused:
            if callback is not None or show or monitorres:
                raise ValueError("fused=True cannot honor callback/show/"
                                 "monitorres; use fused=False for hooks")
            if perc is not None:
                raise NotImplementedError(
                    "percentile thresholding is not implemented")
            out = _sparse_fused_solve(Op, y, x0, niter, SOp, eps, alpha,
                                      eigsdict, tol, threshkind, decay,
                                      momentum=False, guards=use_guards)
            return out[:3]
        solver = ISTA(Op)
        if callback is not None:
            solver.callback = callback
        return solver.solve(y, x0, niter=niter, SOp=SOp, eps=eps,
                            alpha=alpha, eigsdict=eigsdict, tol=tol,
                            threshkind=threshkind, perc=perc, decay=decay,
                            monitorres=monitorres, show=show,
                            itershow=itershow)


def fista(Op, y: Vector, x0: Optional[Vector] = None,
          niter: int = 10, SOp=None, eps: float = 0.1,
          alpha: Optional[float] = None, eigsdict=None, tol: float = 1e-10,
          threshkind: str = "soft", perc=None, decay=None,
          monitorres: bool = False, show: bool = False, itershow=(10, 10, 10),
          callback: Optional[Callable] = None, fused: Optional[bool] = None,
          guards: Optional[bool] = None):
    """Functional FISTA (ref ``optimization/sparsity.py:136-257``). With
    no callback/show/monitorres, runs the fused on-device loop.
    ``guards`` resolves against ``PYLOPS_MPI_TPU_GUARDS`` (see
    :func:`ista`)."""
    use_fused = fused if fused is not None else \
        (callback is None and not show and not monitorres and perc is None)
    from ..resilience.status import guards_enabled
    use_guards = use_fused and guards_enabled(guards)
    with _trace.span("solver.fista", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, niter=niter, eps=eps,
                     threshkind=threshkind, fused=use_fused,
                     guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()):
        if use_fused:
            if callback is not None or show or monitorres:
                raise ValueError("fused=True cannot honor callback/show/"
                                 "monitorres; use fused=False for hooks")
            if perc is not None:
                raise NotImplementedError(
                    "percentile thresholding is not implemented")
            out = _sparse_fused_solve(Op, y, x0, niter, SOp, eps, alpha,
                                      eigsdict, tol, threshkind, decay,
                                      momentum=True, guards=use_guards)
            return out[:3]
        solver = FISTA(Op)
        if callback is not None:
            solver.callback = callback
        return solver.solve(y, x0, niter=niter, SOp=SOp, eps=eps,
                            alpha=alpha, eigsdict=eigsdict, tol=tol,
                            threshkind=threshkind, perc=perc, decay=decay,
                            monitorres=monitorres, show=show,
                            itershow=itershow)


def ista_guarded(Op, y: Vector, x0: Vector, niter: int = 10, SOp=None,
                 eps: float = 0.1, alpha: Optional[float] = None,
                 eigsdict=None, tol: float = 1e-10,
                 threshkind: str = "soft", decay=None):
    """Guarded fused ISTA with an explicit status word: returns
    ``(x, iiter, cost, status_code)`` — the sparse-solver counterpart
    of :func:`pylops_mpi_tpu.solvers.basic.cg_guarded`, consumed by
    :func:`pylops_mpi_tpu.resilience.resilient_solve`."""
    with _trace.span("solver.ista", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, niter=niter, eps=eps,
                     threshkind=threshkind, fused=True, guards=True,
                     telemetry=telemetry.telemetry_enabled()):
        return _sparse_fused_solve(Op, y, x0, niter, SOp, eps, alpha,
                                   eigsdict, tol, threshkind, decay,
                                   momentum=False, guards=True)


def fista_guarded(Op, y: Vector, x0: Vector, niter: int = 10, SOp=None,
                  eps: float = 0.1, alpha: Optional[float] = None,
                  eigsdict=None, tol: float = 1e-10,
                  threshkind: str = "soft", decay=None):
    """Guarded fused FISTA with an explicit status word: returns
    ``(x, iiter, cost, status_code)``; see :func:`ista_guarded`."""
    with _trace.span("solver.fista", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, niter=niter, eps=eps,
                     threshkind=threshkind, fused=True, guards=True,
                     telemetry=telemetry.telemetry_enabled()):
        return _sparse_fused_solve(Op, y, x0, niter, SOp, eps, alpha,
                                   eigsdict, tol, threshkind, decay,
                                   momentum=True, guards=True)

"""Block-Krylov solvers and the vmap-over-parameters batched engine.

Serving-scale workloads arrive as MANY same-shape inverse problems —
shot gathers, deconvolution panels, tomography slices — and solving
them one RHS at a time leaves the amortization on the table twice:
every solve re-walks the operator's memory (the matvec is bandwidth
bound, so K columns through one GEMM cost barely more than one) and
every distinct problem recompiles or re-tunes. Two batching axes fix
the two wastes:

- **block solvers** (:func:`block_cg`, :func:`block_cgls`): ONE
  operator, K RHS columns carried through one fused ``lax.while_loop``.
  The data/model vectors are 2-D ``DistributedArray``\\ s ``(n, K)``
  (rows sharded, trailing column axis local); every operator apply
  moves all K columns per step (the widened-GEMM paths in
  MatrixMult/BlockDiag/stacks/Fredholm1), and the recurrence scalars
  become ``(K,)`` vectors via :meth:`DistributedArray.col_dot`.
  Columns converge independently: a per-column ``done`` mask freezes
  finished columns in-loop (zero step + zero momentum — the same
  select trick as the machine-precision freeze in ``solvers/basic``),
  and with guards on each column carries its own status word, so a
  poisoned column breaks down alone while its siblings keep iterating.
- **vmap over operator parameters** (:func:`batched_solve`): B
  operators from one factory, differing only in tensor data (e.g. MDC
  kernels), stacked leaf-wise and pushed through ``jax.vmap`` of the
  single-RHS fused loop — one compile for the whole family.

``K=1`` block solves route to the EXACT single-RHS fused program
(same ``_get_fused`` cache entry → bit-identical HLO, pinned by
tests/test_block_solver.py). Buffer donation covers the block carries
(``x0`` is ``(n, K)`` and donated like the 1-D case), and telemetry
records per-column residual vectors (``diagnostics/telemetry`` stores
size>1 samples as lists) with the same zero-host-callback-off
guarantee. See docs/batching.md for when each axis wins.
"""

from __future__ import annotations

import os
from collections import OrderedDict, namedtuple
from functools import partial
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..diagnostics import metrics as _metrics
from ..diagnostics import telemetry, trace as _trace
from .basic import (_DONATE_X0, _donate_copy, _get_fused, _i32, _mkey,
                    _mp_floor, _precond_apply, _precond_signature,
                    _reject, _step_scalar, _vdtype, _vkey)

__all__ = ["block_cg", "block_cgls", "block_cg_segmented",
           "batched_solve", "BatchedResult", "batched_cache_info"]


def _bdot(u: DistributedArray, v: DistributedArray):
    """Per-column recurrence dot at the policy reduction dtype — the
    ``(K,)`` twin of ``solvers.basic._rdot`` (including its
    ``reduce_stall`` latency seam: no-op unless armed)."""
    from ..ops._precision import reduction_dtype
    from ..parallel.collectives import reduce_stall
    return reduce_stall(jnp.abs(u.col_dot(v, vdot=True)).astype(
        reduction_dtype(_vdtype(u))))


def _check_block(Op, y):
    if not (isinstance(y, DistributedArray) and y.ndim == 2):
        raise ValueError(
            "block solvers need a 2-D (rows, columns) DistributedArray "
            f"data vector; got {type(y).__name__} with shape "
            f"{getattr(y, 'global_shape', None)}")
    if y.global_shape[0] != Op.shape[0]:
        raise ValueError(
            f"data rows {y.global_shape[0]} do not match operator rows "
            f"{Op.shape[0]}")


def _squeeze_col(v: DistributedArray) -> DistributedArray:
    """(n, 1) block vector → the 1-D vector the single-RHS programs
    take (K=1 routing)."""
    return DistributedArray._wrap(
        v._arr[..., 0], v, global_shape=(v.global_shape[0],),
        local_shapes=tuple((s[0],) for s in v.local_shapes))


def _expand_col(v: DistributedArray) -> DistributedArray:
    """1-D vector → (n, 1) block vector."""
    return DistributedArray._wrap(
        v._arr[..., None], v, global_shape=v.global_shape + (1,),
        local_shapes=tuple(tuple(s) + (1,) for s in v.local_shapes))


def _zero_block_model(Op, y: DistributedArray) -> DistributedArray:
    K = int(y.global_shape[1])
    return DistributedArray(global_shape=(Op.shape[1], K), mesh=y.mesh,
                            partition=y.partition, axis=0, dtype=y.dtype)


def _status0(K: int):
    from ..resilience import status as _rstatus
    return jnp.full((K,), _rstatus.RUNNING, dtype=jnp.int32)


def _bguard_update(status, bestk, stall, bad, k, done, stall_n: int):
    """Per-column guard-carry step: each column's breakdown/stagnation
    verdict is independent — the column-wise ``where`` of
    ``basic._guard_update``. A verdict is sticky (first one wins) and
    frozen/poisoned columns do not run their stall counter."""
    from ..resilience import status as _rstatus
    improved = (k < bestk) & ~bad
    stall = jnp.where(bad | done, stall,
                      jnp.where(improved, jnp.zeros_like(stall),
                                stall + 1))
    bestk = jnp.where(improved, k, bestk)
    verdict = jnp.where(bad, _i32(_rstatus.BREAKDOWN),
                        jnp.where(stall >= stall_n,
                                  _i32(_rstatus.STAGNATION),
                                  _i32(_rstatus.RUNNING)))
    status = jnp.where(status == _rstatus.RUNNING, verdict, status)
    return status, bestk, stall


def _bresolve(status, kold, tol):
    """Post-loop per-column status resolution (on device)."""
    from ..resilience import status as _rstatus
    return jnp.where(status != _rstatus.RUNNING, status,
                     jnp.where(kold <= tol, _i32(_rstatus.CONVERGED),
                               _i32(_rstatus.MAXITER)))


# ------------------------------------------------------ fused block loops
def _make_block_cg_body(Op, xdt, floors, tol, *, M=None, guards=False,
                        carry_status=False, stall_n=0):
    """Block-CG loop body over ``(x, r, c, kold, iiter, cost
    [, status][, bestk, stall])`` with every recurrence scalar a
    ``(K,)`` vector. Columns freeze individually — at the
    machine-precision floor, at ``tol``, or once their status word
    closes — by zeroing their step/momentum lanes.

    ``M`` preconditions ALL K columns in one apply: ``z = M r`` is one
    block matvec on the ``(n, K)`` residual (operators route 2-D
    inputs through their widened paths or the ``_apply_columns`` vmap
    fallback), and the recurrence becomes ``kold = r·z`` per column.
    The carry layout is unchanged — ``z`` is recomputed each
    iteration, never carried — and ``M=None`` traces the identical
    pre-seam program (``z`` IS ``r``)."""
    from ..resilience import status as _rstatus

    def body(state):
        if guards:
            x, r, c, kold, iiter, cost, status, bestk, stall = state
        elif carry_status:
            x, r, c, kold, iiter, cost, status = state
        else:
            x, r, c, kold, iiter, cost = state
        done = kold <= jnp.maximum(floors, tol)
        if guards or carry_status:
            done = done | (status != _rstatus.RUNNING)
        Opc = Op.matvec(c)
        a = kold / _bdot(c, Opc)
        a = jnp.where(done, jnp.zeros_like(a), a)
        xn = x + c * _step_scalar(a, xdt)
        rn = r - Opc * _step_scalar(a, xdt)
        zn = _precond_apply(M, rn, xdt)
        k = _bdot(rn, zn)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        cn = zn + c * _step_scalar(b, xdt)
        if guards:
            # per-column verdicts: only the poisoned column's update is
            # rejected (its lane of the (K,) mask), siblings proceed
            bad = (~jnp.isfinite(a)) | (~jnp.isfinite(k)) \
                | (~jnp.isfinite(b))
            x = _reject(bad, x, xn)
            r = _reject(bad, r, rn)
            c = _reject(bad, c, cn)
            k = jnp.where(bad, kold, k)
            status, bestk, stall = _bguard_update(status, bestk, stall,
                                                  bad, k, done, stall_n)
        else:
            x, r, c = xn, rn, cn
        iiter = iiter + 1
        cost = lax.dynamic_update_index_in_dim(cost, jnp.sqrt(k), iiter, 0)
        # per-column residual history; no-op (nothing traced) when
        # telemetry is off — the zero-host-callback pin
        telemetry.iteration("block_cg", iiter, resid=jnp.sqrt(k), k=k,
                            alpha=a)
        if guards:
            return (x, r, c, k, iiter, cost, status, bestk, stall)
        if carry_status:
            return (x, r, c, k, iiter, cost, status)
        return (x, r, c, k, iiter, cost)

    return body


def _block_cg_fused(Op, y, x0, tol, *, niter: int, M=None,
                    guards: bool = False, stall_n: int = 0):
    from ..resilience import status as _rstatus
    xdt = _vdtype(x0)
    x = x0  # donated: the block carry aliases the caller's buffer
    r = y - Op.matvec(x)
    z = _precond_apply(M, r, xdt)
    c = z
    kold = _bdot(r, z)
    floors = _mp_floor(kold)
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                      dtype=jnp.asarray(kold).dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold), 0, 0)
    body = _make_block_cg_body(Op, xdt, floors, tol, M=M, guards=guards,
                               stall_n=stall_n)
    if guards:
        K = kold.shape[0]
        state = (x, r, c, kold, jnp.asarray(0), cost0, _status0(K),
                 kold, jnp.zeros((K,), jnp.int32))

        def cond(st):
            return ((st[4] < niter)
                    & jnp.any((st[3] > tol)
                              & (st[6] == _rstatus.RUNNING)))

        x, r, c, kold, iiter, cost, status, _, _ = \
            lax.while_loop(cond, body, state)
        return x, iiter, cost, _bresolve(status, kold, tol)

    def cond(st):
        return (st[4] < niter) & (jnp.max(st[3]) > tol)

    state = (x, r, c, kold, jnp.asarray(0), cost0)
    x, r, c, kold, iiter, cost = lax.while_loop(cond, body, state)
    return x, iiter, cost


def _make_block_cgls_body(Op, xdt, damp2, floors, tol, *, M=None,
                          guards=False, carry_status=False, stall_n=0):
    """Block-CGLS (classic two-sweep) loop body over ``(x, s, c, q,
    kold, iiter, cost, cost1[, status][, bestk, stall])`` — per-column
    scalars throughout; see :func:`_make_block_cg_body`. ``M``
    approximates ``(OpᴴOp + damp²I)⁻¹`` and is applied to the normal
    residual, all K columns at once."""
    from ..resilience import status as _rstatus

    def body(state):
        if guards:
            x, s, c, q, kold, iiter, cost, cost1, status, bestk, stall \
                = state
        elif carry_status:
            x, s, c, q, kold, iiter, cost, cost1, status = state
        else:
            x, s, c, q, kold, iiter, cost, cost1 = state
        done = kold <= jnp.maximum(floors, tol)
        if guards or carry_status:
            done = done | (status != _rstatus.RUNNING)
        a = jnp.abs(kold / (_bdot(q, q) + damp2 * _bdot(c, c)))
        a = jnp.where(done, jnp.zeros_like(a), a)
        xn = x + c * _step_scalar(a, xdt)
        sn_ = s - q * _step_scalar(a, xdt)
        r = Op.rmatvec(sn_) - xn * damp2
        z = _precond_apply(M, r, xdt)
        k = _bdot(r, z)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        cn = z + c * _step_scalar(b, xdt)
        qn = Op.matvec(cn)
        if guards:
            bad = (~jnp.isfinite(a)) | (~jnp.isfinite(k)) \
                | (~jnp.isfinite(b))
            x = _reject(bad, x, xn)
            s = _reject(bad, s, sn_)
            c = _reject(bad, c, cn)
            q = _reject(bad, q, qn)
            k = jnp.where(bad, kold, k)
            status, bestk, stall = _bguard_update(status, bestk, stall,
                                                  bad, k, done, stall_n)
        else:
            x, s, c, q = xn, sn_, cn, qn
        iiter = iiter + 1
        sn = jnp.sqrt(_bdot(s, s))
        cost = lax.dynamic_update_index_in_dim(cost, sn, iiter, 0)
        r2 = jnp.sqrt(sn ** 2 + damp2 * _bdot(x, x))
        cost1 = lax.dynamic_update_index_in_dim(cost1, r2, iiter, 0)
        telemetry.iteration("block_cgls", iiter, resid=sn, k=k, alpha=a)
        if guards:
            return (x, s, c, q, k, iiter, cost, cost1, status, bestk,
                    stall)
        if carry_status:
            return (x, s, c, q, k, iiter, cost, cost1, status)
        return (x, s, c, q, k, iiter, cost, cost1)

    return body


def _block_cgls_fused(Op, y, x0, damp, tol, *, niter: int, M=None,
                      guards: bool = False, stall_n: int = 0):
    from ..resilience import status as _rstatus
    damp2 = damp ** 2
    xdt = _vdtype(x0)
    x = x0  # donated (see _DONATE_X0)
    s = y - Op.matvec(x)
    rq = Op.rmatvec(s) - x * damp  # the reference's un-squared setup
    z = _precond_apply(M, rq, xdt)  # damp quirk (solvers/basic module
    c = z                           # doc); M seeds the first direction
    q = Op.matvec(c)
    kold = _bdot(rq, z)
    floors = _mp_floor(kold)
    sn0 = jnp.sqrt(_bdot(s, s))
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(sn0), dtype=sn0.dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, sn0, 0, 0)
    cost1_0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(cost0),
        jnp.sqrt(sn0 ** 2 + damp2 * _bdot(x, x)), 0, 0)
    body = _make_block_cgls_body(Op, xdt, damp2, floors, tol, M=M,
                                 guards=guards, stall_n=stall_n)
    if guards:
        K = kold.shape[0]
        state = (x, s, c, q, kold, jnp.asarray(0), cost0, cost1_0,
                 _status0(K), kold, jnp.zeros((K,), jnp.int32))

        def cond(st):
            return ((st[5] < niter)
                    & jnp.any((st[4] > tol)
                              & (st[8] == _rstatus.RUNNING)))

        out = lax.while_loop(cond, body, state)
        x, kold, iiter, cost, cost1, status = (out[0], out[4], out[5],
                                               out[6], out[7], out[8])
        return (x, iiter, cost, cost1, kold,
                _bresolve(status, kold, tol))

    def cond(st):
        return (st[5] < niter) & (jnp.max(st[4]) > tol)

    state = (x, s, c, q, kold, jnp.asarray(0), cost0, cost1_0)
    out = lax.while_loop(cond, body, state)
    return out[0], out[5], out[6], out[7], out[4]


# ------------------------------------------------------ public wrappers
def block_cg(Op, y: DistributedArray,
             x0: Optional[DistributedArray] = None, niter: int = 10,
             tol: float = 1e-4, guards: Optional[bool] = None,
             M=None):
    """Fused block CG: K RHS columns through one ``lax.while_loop``.

    ``y`` (and the optional ``x0``) are 2-D ``(n, K)``
    ``DistributedArray``\\ s — rows sharded, columns local. Returns
    ``(x, iiter, cost)`` with ``cost`` of shape ``(iiter+1, K)`` (one
    residual trajectory per column). Finished columns freeze in-loop;
    with guards on, per-column status words land in
    ``resilience.status.last_status("block_cg")["columns"]``.
    ``K=1`` routes through the single-RHS fused program — same cache
    entry, bit-identical HLO.

    ``PYLOPS_MPI_TPU_AUTODIFF=on`` reroutes traced inputs to the
    implicit-diff rule (one block backward solve covers all K
    cotangent columns) — see :func:`~pylops_mpi_tpu.solvers.basic.cg`;
    guards are excluded on the traced path."""
    _check_block(Op, y)
    from ..utils import deps as _deps
    if _deps.autodiff_enabled():
        from ..autodiff import implicit as _autodiff
        if _autodiff.should_intercept(Op, y, x0):
            return _autodiff.entry_block_cg(Op, y, x0, niter, tol, M)
    K = int(y.global_shape[1])
    x0_owned = x0 is None
    if x0 is None:
        x0 = _zero_block_model(Op, y)
    from ..resilience.status import guards_enabled
    use_guards = guards_enabled(guards)
    with _trace.span("solver.block_cg", cat="solver",
                     op=type(Op).__name__, shape=Op.shape, batch=K,
                     dtype=_vdtype(x0), niter=niter, tol=tol,
                     guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()):
        if K == 1:
            from ..resilience import status as _rstatus
            from .basic import _run_cg_fused
            x1, iiter, cost, code = _run_cg_fused(
                Op, _squeeze_col(y), _squeeze_col(x0), True, niter,
                tol, use_guards, M=M)
            if use_guards:
                _rstatus.record_columns("block_cg", [code], iiter)
            return _expand_col(x1), iiter, np.asarray(cost)[:, None]
        from . import ca as _ca
        _ca_mode = _ca.resolve_mode(Op, "block_cg")
        if _ca_mode != "off":
            # K>1 communication-avoiding route (s-step pipelines: no
            # block Gram variant); K=1 already inherited CA above via
            # the single-RHS runner's own dispatch
            return _ca.run_block_cg(Op, y, x0, x0_owned, niter, tol,
                                    use_guards, M=M, mode=_ca_mode)
        if use_guards:
            from ..resilience import status as _rstatus
            stall_n = _rstatus.stall_window()
            fn = _get_fused(
                Op, (id(Op), "block_cg", niter, _vkey(y), _vkey(x0),
                     _rstatus.guards_signature(True)) + _mkey(M),
                lambda op: partial(_block_cg_fused, op, niter=niter,
                                   M=M, guards=True, stall_n=stall_n),
                donate_argnums=_DONATE_X0, keepalive=M,
                aot_eligible=(M is None))
            x, iiter, cost, status = fn(
                y, x0 if x0_owned else _donate_copy(x0), tol)
            iiter = int(iiter)
            _metrics.inc("solver.block_cg.solves")
            _metrics.inc("solver.block_cg.iterations", iiter)
            _rstatus.record_columns(
                "block_cg", [int(cd) for cd in np.asarray(status)],
                iiter)
            return x, iiter, np.asarray(cost)[:iiter + 1]
        fn = _get_fused(Op, (id(Op), "block_cg", niter, _vkey(y),
                             _vkey(x0)) + _mkey(M),
                        lambda op: partial(_block_cg_fused, op,
                                           niter=niter, M=M),
                        donate_argnums=_DONATE_X0, keepalive=M,
                        aot_eligible=(M is None))
        x, iiter, cost = fn(y, x0 if x0_owned else _donate_copy(x0),
                            tol)
        iiter = int(iiter)
        _metrics.inc("solver.block_cg.solves")
        _metrics.inc("solver.block_cg.iterations", iiter)
        return x, iiter, np.asarray(cost)[:iiter + 1]


def _run_block_cgls_fused(Op, y, x0, niter, damp, tol, M=None,
                          x0_owned: bool = False):
    """Compile-cache-and-run the unguarded fused block-CGLS loop;
    raw ``(x, iiter, cost, cost1, kold)`` with ``(iiter+1, K)`` sliced
    histories — the :func:`~pylops_mpi_tpu.solvers.basic._run_cgls_fused`
    contract minus the status word. Factored out of :func:`block_cgls`
    (identical ``_get_fused`` key) so the autodiff tier's concrete
    forward (autodiff/implicit.py) reuses the SAME cached executables
    and AOT bank entries as plain solves instead of growing a parallel
    executable set."""
    fn = _get_fused(Op, (id(Op), "block_cgls", niter, _vkey(y),
                         _vkey(x0)) + _mkey(M),
                    lambda op: partial(_block_cgls_fused, op,
                                       niter=niter, M=M),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None))
    x, iiter, cost, cost1, kold = fn(
        y, x0 if x0_owned else _donate_copy(x0), damp, tol)
    iiter = int(iiter)
    _metrics.inc("solver.block_cgls.solves")
    _metrics.inc("solver.block_cgls.iterations", iiter)
    return (x, iiter, np.asarray(cost)[:iiter + 1],
            np.asarray(cost1)[:iiter + 1], np.asarray(kold))


def block_cgls(Op, y: DistributedArray,
               x0: Optional[DistributedArray] = None, niter: int = 10,
               damp: float = 0.0, tol: float = 1e-4,
               guards: Optional[bool] = None, M=None):
    """Fused block CGLS (classic two-sweep schedule); see
    :func:`block_cg`. Returns ``(x, istop, iiter, kold, r2norm,
    cost)`` — the :func:`~pylops_mpi_tpu.solvers.basic.cgls` shape with
    per-column ``istop``/``kold``/``r2norm`` vectors and a
    ``(iiter+1, K)`` cost history.

    ``PYLOPS_MPI_TPU_AUTODIFF=on`` reroutes traced inputs to the
    implicit-diff rule — see :func:`block_cg`."""
    _check_block(Op, y)
    from ..utils import deps as _deps
    if _deps.autodiff_enabled():
        from ..autodiff import implicit as _autodiff
        if _autodiff.should_intercept(Op, y, x0):
            return _autodiff.entry_block_cgls(Op, y, x0, niter, damp,
                                              tol, M)
    K = int(y.global_shape[1])
    x0_owned = x0 is None
    if x0 is None:
        x0 = _zero_block_model(Op, y)
    from ..resilience.status import guards_enabled
    use_guards = guards_enabled(guards)
    with _trace.span("solver.block_cgls", cat="solver",
                     op=type(Op).__name__, shape=Op.shape, batch=K,
                     dtype=_vdtype(x0), niter=niter, damp=damp, tol=tol,
                     guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()):
        if K == 1:
            from ..resilience import status as _rstatus
            from .basic import _run_cgls_fused
            x1, iiter, cost, cost1, kold, code = _run_cgls_fused(
                Op, _squeeze_col(y), _squeeze_col(x0), True, niter,
                damp, tol, False, use_guards, M=M)
            if use_guards:
                _rstatus.record_columns("block_cgls", [code], iiter)
            kold = np.atleast_1d(np.asarray(kold))
            istop = np.where(kold < tol, 1, 2)
            return (_expand_col(x1), istop, iiter, kold,
                    np.atleast_1d(np.asarray(cost1)[-1]),
                    np.asarray(cost)[:, None])
        from . import ca as _ca
        _ca_mode = _ca.resolve_mode(Op, "block_cgls")
        if _ca_mode != "off":
            return _ca.run_block_cgls(Op, y, x0, x0_owned, niter, damp,
                                      tol, use_guards, M=M,
                                      mode=_ca_mode)
        if use_guards:
            from ..resilience import status as _rstatus
            stall_n = _rstatus.stall_window()
            fn = _get_fused(
                Op, (id(Op), "block_cgls", niter, _vkey(y), _vkey(x0),
                     _rstatus.guards_signature(True)) + _mkey(M),
                lambda op: partial(_block_cgls_fused, op, niter=niter,
                                   M=M, guards=True, stall_n=stall_n),
                donate_argnums=_DONATE_X0, keepalive=M,
                aot_eligible=(M is None))
            x, iiter, cost, cost1, kold, status = fn(
                y, x0 if x0_owned else _donate_copy(x0), damp, tol)
            iiter = int(iiter)
            _metrics.inc("solver.block_cgls.solves")
            _metrics.inc("solver.block_cgls.iterations", iiter)
            _rstatus.record_columns(
                "block_cgls", [int(cd) for cd in np.asarray(status)],
                iiter)
        else:
            x, iiter, cost, cost1, kold = _run_block_cgls_fused(
                Op, y, x0, niter, damp, tol, M=M, x0_owned=x0_owned)
            return (x, np.where(kold < tol, 1, 2), iiter, kold,
                    cost1[-1], cost)
        kold = np.asarray(kold)
        istop = np.where(kold < tol, 1, 2)
        return (x, istop, iiter, kold,
                np.asarray(cost1)[iiter],
                np.asarray(cost)[:iiter + 1])


# ------------------------------------------------------ segmented blocks
def _block_cg_setup_builder(Op, *, niter, M=None):
    def setup(y, x0):
        x = x0
        r = y - Op.matvec(x)
        z = _precond_apply(M, r, _vdtype(x0))
        c = z
        kold = _bdot(r, z)
        floors = _mp_floor(kold)
        cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                          dtype=jnp.asarray(kold).dtype)
        cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold),
                                                0, 0)
        return x, r, c, kold, cost0, floors

    return setup


def _block_cg_epoch_builder(Op, *, guards, stall_n, M=None):
    def run(y, x, r, c, kold, iiter, cost, status, bestk, stall,
            floors, tol, epoch_end):
        from ..resilience import status as _rstatus
        body = _make_block_cg_body(Op, _vdtype(x), floors, tol, M=M,
                                   guards=guards,
                                   carry_status=not guards,
                                   stall_n=stall_n)
        if guards:
            state = (x, r, c, kold, iiter, cost, status, bestk, stall)

            def cond(st):
                return ((st[4] < epoch_end)
                        & jnp.any((st[3] > tol)
                                  & (st[6] == _rstatus.RUNNING)))

            return lax.while_loop(cond, body, state)
        state = (x, r, c, kold, iiter, cost, status)

        def cond(st):
            return (st[4] < epoch_end) & (jnp.max(st[3]) > tol)

        out = lax.while_loop(cond, body, state)
        return out + (bestk, stall)

    return run


_BLOCK_CG_FIELDS = ("x", "r", "c", "kold", "iiter", "cost", "status",
                    "bestk", "stall")


def block_cg_segmented(Op, y: DistributedArray,
                       x0: Optional[DistributedArray] = None,
                       niter: int = 100, tol: float = 1e-4,
                       epoch: Optional[int] = None,
                       checkpoint_path: Optional[str] = None,
                       resume: bool = True,
                       backend: Optional[str] = None,
                       guards: Optional[bool] = None,
                       on_epoch=None, M=None):
    """Segmented block CG: epochs of fused block iterations with the
    whole ``(n, K)`` carry checkpointed between epochs
    (``utils/checkpoint.save_fused_carry`` round-trips any-ndim
    ``DistributedArray`` carries unchanged). A killed process
    re-invoking with the same ``checkpoint_path`` resumes from the
    last banked epoch; see :func:`~.segmented.cg_segmented` for the
    epoch/cadence contract. Returns ``(x, iiter, cost, status)`` with
    per-column status codes."""
    from .segmented import _FUSED_SCHEMA, _load_carry, resolve_epoch
    from ..resilience import status as _rstatus
    from ..resilience.status import guards_enabled, stall_window
    from ..utils import checkpoint as _ckpt
    from ..resilience.elastic import maybe_start_heartbeat
    _check_block(Op, y)
    maybe_start_heartbeat()
    K = int(y.global_shape[1])
    guards_on = guards_enabled(guards)
    stall_n = stall_window() if guards_on else 0
    E = resolve_epoch(epoch, niter)
    if x0 is None:
        x0 = _zero_block_model(Op, y)
    meta = {"niter": niter, "tol": float(tol), "guards": guards_on,
            "batch": K, "precond": _precond_signature(M)}
    state = (_load_carry(checkpoint_path, "block_cg", y.mesh, meta)
             if resume else None)
    resumed = state is not None
    fields = _BLOCK_CG_FIELDS

    with _trace.span("solver.block_cg_segmented", cat="solver",
                     op=type(Op).__name__, shape=Op.shape, batch=K,
                     niter=niter, epoch=E, guards=guards_on,
                     resumed=resumed,
                     checkpoint=bool(checkpoint_path)):
        if state is None:
            setup = _get_fused(
                Op, (id(Op), "block_cg-seg-setup", niter, _vkey(y),
                     _vkey(x0)) + _mkey(M),
                lambda op: _block_cg_setup_builder(op, niter=niter,
                                                   M=M),
                keepalive=M, aot_eligible=(M is None))
            x, r, c, kold, cost, floors = setup(y, x0)
            state = dict(zip(fields, [
                x, r, c, kold, jnp.asarray(0), cost, _status0(K),
                kold, jnp.zeros((K,), jnp.int32)]))
            state["floors"] = floors
        run = _get_fused(
            Op, (id(Op), "block_cg-seg", niter, _vkey(y), _vkey(x0),
                 ("guards", guards_on,
                  stall_n if guards_on else None)) + _mkey(M),
            lambda op: _block_cg_epoch_builder(op, guards=guards_on,
                                               stall_n=stall_n, M=M),
            keepalive=M, aot_eligible=(M is None))
        epochs = 0
        while True:
            iiter = int(state["iiter"])
            kold_np = np.asarray(state["kold"])
            codes = np.asarray(state["status"])
            live = ((kold_np > tol) & (codes == _rstatus.RUNNING)
                    & np.isfinite(kold_np))
            if iiter >= niter or not live.any():
                break
            epoch_end = min(iiter + E, niter)
            floors = state["floors"]
            out = run(y, *[state[f] for f in fields], floors, tol,
                      epoch_end)
            state = dict(zip(fields, out))
            state["floors"] = floors
            epochs += 1
            if checkpoint_path:
                carry = {**meta, "epoch": E, "schema": _FUSED_SCHEMA}
                carry.update({f: state[f] for f in fields})
                carry["floors"] = state["floors"]
                _ckpt.save_fused_carry(checkpoint_path, "block_cg",
                                       carry, backend=backend)
                _trace.event("solver.checkpoint", cat="resilience",
                             solver="block_cg",
                             iiter=int(state["iiter"]), epoch=epochs,
                             path=checkpoint_path)
            if on_epoch is not None:
                on_epoch({"epoch": epochs, "iiter": int(state["iiter"]),
                          "resid": float(jnp.max(jnp.asarray(
                              state["cost"])[int(state["iiter"])])),
                          "columns": [_rstatus.status_name(int(cd))
                                      for cd in
                                      np.asarray(state["status"])]})
        iiter = int(state["iiter"])
        kold_np = np.asarray(state["kold"])
        codes = np.asarray(state["status"])
        final = np.where(
            codes != _rstatus.RUNNING, codes,
            np.where(~np.isfinite(kold_np), _rstatus.BREAKDOWN,
                     np.where(kold_np <= tol, _rstatus.CONVERGED,
                              _rstatus.MAXITER))).astype(np.int32)
        if guards_on:
            _rstatus.record_columns("block_cg",
                                    [int(cd) for cd in final], iiter)
        cost = np.asarray(state["cost"])[:iiter + 1]
        return state["x"], iiter, cost, final


# ------------------------------------------- vmap over operator params
BatchedResult = namedtuple("BatchedResult",
                           ["xs", "iiter", "cost", "cost1", "kold"])
BatchedResult.__doc__ = (
    "Result of a vmap-over-parameters batched solve: ``xs`` is the "
    "list of per-problem model vectors; ``iiter``/``cost`` (and for "
    "CGLS ``cost1``/``kold``) carry a leading problem axis. ``cost`` "
    "rows past a problem's own ``iiter`` are zeros — the batch runs "
    "until every problem's loop exits.")

_BATCHED_CACHE: "OrderedDict" = OrderedDict()


def _batched_cache_max() -> int:
    """``PYLOPS_MPI_TPU_BATCHED_CACHE`` — capacity of the per-family
    compiled-executable LRU (default 8, floored at 1 so a typo cannot
    disable caching entirely)."""
    try:
        v = int(os.environ.get("PYLOPS_MPI_TPU_BATCHED_CACHE", "8"))
    except ValueError:
        v = 8
    return max(1, v)


def batched_cache_info() -> dict:
    """Introspection for the warm pool / tests: the batched-solve LRU's
    ``{"size", "max", "families"}`` where ``families`` lists the cached
    ``(solver, niter, B, op)`` heads newest-last. Hit/miss traffic is
    on the metrics counters ``solver.batched.cache.hit`` / ``.miss``
    (the ``tuning.cache.*`` idiom)."""
    return {"size": len(_BATCHED_CACHE),
            "max": _batched_cache_max(),
            "families": [k[:4] for k in _BATCHED_CACHE]}


def _aval_key(t):
    return tuple((tuple(l.shape), str(l.dtype))
                 for l in jax.tree_util.tree_leaves(t))


def batched_solve(factory, params: Sequence, ys: Sequence,
                  *, solver: str = "cgls",
                  x0s: Optional[Sequence] = None, niter: int = 10,
                  damp: float = 0.0, tol: float = 1e-4) -> BatchedResult:
    """Solve a FAMILY of same-shape problems — one compile.

    ``factory(p)`` builds the operator for parameter pytree ``p``;
    the B operators must be the same registered-pytree class
    (``linearoperator.register_operator_arrays``) with identical
    shapes, differing only in tensor data (e.g. many MDC chains with
    different kernels). Their array leaves are stacked and the
    single-RHS fused loop (``solver`` in ``{"cg", "cgls"}``) is
    ``jax.vmap``-ed over the stacked operator, data and model — the
    whole family shares ONE compiled program, cached across calls.
    Each problem's ``while_loop`` lane freezes when its own
    convergence test passes (the vmap batching rule masks finished
    lanes). Guards are not traced into the vmapped program — use the
    block solvers for per-problem status words.

    The stacked ``x0`` buffer is donated (when the donation gate is
    on), like the single-solve path."""
    from ..linearoperator import operator_is_jit_arg
    from ..ops._precision import donation_enabled
    from .basic import _cg_fused, _cgls_fused, _zero_like_model
    if solver not in ("cg", "cgls"):
        raise ValueError(f"solver={solver!r}: expected 'cg' or 'cgls'")
    params = list(params)
    ys = list(ys)
    if not params or len(params) != len(ys):
        raise ValueError(
            f"need one y per parameter set, got {len(params)} params "
            f"and {len(ys)} ys")
    ops = [factory(p) for p in params]
    Op0 = ops[0]
    if not operator_is_jit_arg(Op0):
        raise TypeError(
            f"batched_solve needs a registered pytree operator class "
            f"(linearoperator.register_operator_arrays); "
            f"{type(Op0).__name__} is not registered")
    for op in ops[1:]:
        if type(op) is not type(Op0) or op.shape != Op0.shape:
            raise ValueError(
                "batched_solve needs a same-shape operator family; got "
                f"{type(Op0).__name__}{Op0.shape} and "
                f"{type(op).__name__}{op.shape}")
    B = len(ops)
    stack = lambda *ls: jnp.stack(ls)
    # the operator pytree's aux is the instance itself (treedefs of two
    # family members never compare equal), so stack leaf-wise by hand
    # and unflatten with the first member's treedef
    leaves0, treedef0 = jax.tree_util.tree_flatten(Op0)
    if not leaves0:
        # zero array leaves would make every lane silently replay
        # member 0's arrays out of the treedef aux (e.g. an
        # MPIBlockDiag whose block count is not a multiple of the
        # device count never builds its stacked `_batched` leaf)
        raise ValueError(
            f"{type(Op0).__name__} flattens to no array leaves in this "
            "configuration, so nothing varies across the family; "
            "batched_solve cannot vmap it — solve the members "
            "individually (for MPIBlockDiag, the stacked-GEMM leaf "
            "needs the block count to be a multiple of the device "
            "count)")
    fam_leaves = [leaves0] + [jax.tree_util.tree_leaves(op)
                              for op in ops[1:]]
    for i, ls in enumerate(fam_leaves[1:], start=1):
        if len(ls) != len(leaves0) or any(
                jnp.shape(a) != jnp.shape(b) or
                jnp.asarray(a).dtype != jnp.asarray(b).dtype
                for a, b in zip(ls, leaves0)):
            raise ValueError(
                f"operator {i} flattens to different leaf avals than "
                "operator 0; batched_solve needs a same-shape family")
    OpB = jax.tree_util.tree_unflatten(
        treedef0, [stack(*ls) for ls in zip(*fam_leaves)])
    YB = jax.tree_util.tree_map(stack, *ys)
    if x0s is None:
        x0s = [_zero_like_model(op, yv) for op, yv in zip(ops, ys)]
    else:
        x0s = [x.copy() for x in x0s]  # donated below; keep callers' own
    X0B = jax.tree_util.tree_map(stack, *x0s)
    donate = (2,) if donation_enabled() else ()
    key = (solver, niter, B, type(Op0).__name__, _aval_key(OpB),
           _vkey(ys[0]), _vkey(x0s[0]), donate,
           telemetry.telemetry_signature())
    jfn = _BATCHED_CACHE.get(key)
    _metrics.inc("solver.batched.cache.hit" if jfn is not None
                 else "solver.batched.cache.miss")
    with _trace.span(f"solver.batched_{solver}", cat="solver",
                     op=type(Op0).__name__, shape=Op0.shape, family=B,
                     niter=niter, tol=tol, compiled=jfn is not None,
                     telemetry=telemetry.telemetry_enabled()):
        if jfn is None:
            if solver == "cg":
                one = lambda op, yv, xv, d, t: _cg_fused(op, yv, xv, t,
                                                         niter=niter)
            else:
                one = lambda op, yv, xv, d, t: _cgls_fused(
                    op, yv, xv, d, t, niter=niter)
            jfn = jax.jit(jax.vmap(one, in_axes=(0, 0, 0, None, None)),
                          donate_argnums=donate)
            _BATCHED_CACHE[key] = jfn
            if len(_BATCHED_CACHE) > _batched_cache_max():
                _BATCHED_CACHE.popitem(last=False)
        else:
            _BATCHED_CACHE.move_to_end(key)
        out = jfn(OpB, YB, X0B, damp, tol)
        X = out[0]
        xs = [jax.tree_util.tree_map(lambda l: l[i], X)
              for i in range(B)]
        if solver == "cg":
            return BatchedResult(xs=xs, iiter=np.asarray(out[1]),
                                 cost=np.asarray(out[2]), cost1=None,
                                 kold=None)
        return BatchedResult(xs=xs, iiter=np.asarray(out[1]),
                             cost=np.asarray(out[2]),
                             cost1=np.asarray(out[3]),
                             kold=np.asarray(out[4]))

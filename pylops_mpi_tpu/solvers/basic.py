"""CG / CGLS distributed solvers.

Rebuild of ``pylops_mpi/optimization/cls_basic.py`` (CG ``12-249``, CGLS
``252-531``) and the functional wrappers ``optimization/basic.py``.

Two execution paths:

- **class API** (`CG`, `CGLS`): reference-parity ``setup/step/run/
  finalize/solve`` with per-iteration ``callback`` hooks. Each step is a
  handful of fused XLA ops; scalars stay on device (no per-iteration
  ``.item()`` host syncs — the reference pulls 4 scalars/iter,
  ref ``cls_basic.py:389-401``).
- **fused path** (functional ``cg``/``cgls`` with ``fused=True``,
  default): the whole iteration runs as one ``lax.while_loop`` under
  ``jit`` — matvec, rmatvec and the dot-product ``psum``s compile into a
  single XLA program per solve; the cost history is carried in a
  fixed-length on-device trace buffer (SURVEY §7 hard-part: host-synced
  solver scalars).

Reference quirk preserved: CGLS ``setup`` damps the initial residual by
``damp`` while ``step`` uses ``damp**2`` (ref ``cls_basic.py:345-350`` vs
``392-393``); immaterial for the usual ``x0 = 0``.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable, Optional, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..stacked import StackedDistributedArray
from ..diagnostics import metrics as _metrics
from ..diagnostics import telemetry, trace as _trace

__all__ = ["CG", "CGLS", "cg", "cgls", "cg_guarded", "cgls_guarded",
           "clear_fused_cache"]

Vector = Union[DistributedArray, StackedDistributedArray]


def _abs(v):
    return jnp.abs(jnp.asarray(v))


def _vdtype(v):
    """Element dtype of a (possibly nested-stacked) distributed
    vector."""
    if isinstance(v, StackedDistributedArray):
        return np.result_type(*[_vdtype(d) for d in v.distarrays])
    return v.dtype


def _rdot(u, v):
    """Recurrence dot product at the policy reduction dtype: the
    squared-norm scalars (``k``, ``cOpc``, ``q·q``) must accumulate at
    f32 or better even when the carry vectors are narrower — a bf16
    ``k/kold`` ratio is the recurrence contamination behind the round-5
    bf16 cliff (ops/_precision.py module doc). For ≥f32 carries this is
    exactly the old ``_abs(u.dot(v.conj()))``.

    The result passes through ``collectives.reduce_stall`` — a no-op
    (nothing traced) unless the ``PYLOPS_MPI_TPU_REDUCE_STALL`` latency
    seam is armed, in which case every reduction result drags an N-step
    serial dependency chain: the bench's stand-in for per-collective
    wire latency on a real fabric (docs/ca.md)."""
    from ..ops._precision import reduction_dtype
    from ..parallel.collectives import reduce_stall
    return reduce_stall(
        _abs(u.dot(v.conj())).astype(reduction_dtype(_vdtype(u))))


def _step_scalar(s, carry_dtype):
    """Cast a recurrence scalar for a vector update so the CARRY dtype
    survives the multiply: a wide (f32) step scalar times a narrow
    (bf16) carry would promote the carry and break the while_loop's
    fixed pytree dtypes. Real scalars against complex carries pass
    through (no promotion)."""
    dt = np.dtype(carry_dtype)
    if np.issubdtype(dt, np.complexfloating):
        return s
    return s.astype(dt)


def _cast_vec(v, dt):
    """Cast a (possibly stacked) distributed vector to ``dt`` without
    leaving the jit trace — used to pin a preconditioner's output back
    to the carry dtype so the while_loop pytree dtypes stay fixed."""
    if isinstance(v, StackedDistributedArray):
        return StackedDistributedArray(
            [_cast_vec(d, dt) for d in v.distarrays])
    return DistributedArray._wrap(v._arr.astype(dt), v)


def _precond_apply(M, r, xdt):
    """Apply the preconditioner seam: ``z = M⁻¹ r`` (``M.matvec`` — the
    preconditioner operator IS the approximate inverse), cast back to
    the carry dtype. ``M=None`` returns ``r`` ITSELF — not a copy, not
    a new op — so the unpreconditioned trace is the literally unchanged
    pre-seam program (the ``M=None`` HLO bit-identity pin,
    tests/test_precond.py)."""
    if M is None:
        return r
    z = M.matvec(r)
    if np.dtype(_vdtype(z)) != np.dtype(xdt):
        z = _cast_vec(z, np.dtype(xdt))
    return z


def _precond_signature(M) -> str:
    """Stable identity of a preconditioner CONFIGURATION (not instance)
    — what segmented checkpoints bank so a resume with a different M
    refuses instead of silently mixing trajectories."""
    if M is None:
        return "none"
    sig = getattr(M, "precond_signature", None)
    if callable(sig):
        return str(sig())
    return f"{type(M).__name__}{tuple(M.shape)}"


def _mkey(M):
    """Fused-cache key component for the preconditioner: EMPTY when
    ``M=None`` so every pre-seam cache key is byte-identical to before
    the seam existed (zero new cache entries for unpreconditioned
    solves)."""
    return () if M is None else (("M", id(M)),)


def _mp_floor(k0):
    """Machine-precision floor for the solver's squared recurrence
    norm — ``k = |r|²`` for CG, ``k = |Aᴴr|²`` for CGLS: once ``k``
    falls below ``(100·eps)²·k0`` further updates are numerical noise. The fused loops FREEZE the recurrence there (zero
    step + zero momentum) instead of exiting: iterating past this point
    is not just useless, it is unstable — the ``k/kold`` ratio of
    noise-level quantities can drift above 1 and pump the recurrence
    exponentially (observed: a 5-shard ragged CGLS at tol=0 reached
    1e13 error by iteration 400 while NumPy's trajectory happened to
    hit an exact fixed point). Freezing (rather than early exit) keeps
    the iteration count — and the per-iteration work the benchmarks
    time — exactly as requested."""
    k0 = jnp.asarray(k0)
    eps = jnp.finfo(k0.dtype).eps
    return k0 * (100 * eps) ** 2


class _BaseSolver:
    def __init__(self, Op):
        self.Op = Op
        self.callback = lambda x: None
        self.tstart = time.time()

    def _callback_wrap(self, callback):
        if callback is not None:
            self.callback = callback

    def memory_usage(self) -> None:
        """No-op hook, reference Solver-ABC parity
        (ref ``cls_basic.py:54-55``)."""


class CG(_BaseSolver):
    """Conjugate gradient for square distributed operators
    (ref ``cls_basic.py:12-249``).

    The ``setup``/``step``/``run`` class API exists for callback /
    per-iteration-inspection parity with the reference and syncs 2-3
    scalars to host EVERY iteration — it is the slow path. The
    functional :func:`cg` (fused ``lax.while_loop``, default when no
    callbacks) is the fast path."""

    def setup(self, y: Vector, x0: Vector, niter: Optional[int] = None,
              tol: float = 1e-4, show: bool = False) -> Vector:
        self.y = y
        self.tol = tol
        self.niter = niter
        x = x0.copy()
        self.r = self.y - self.Op.matvec(x)
        self.c = self.r.copy()
        self.kold = _abs(self.r.dot(self.r.conj()))
        self.cost = [jnp.sqrt(self.kold)]
        self.iiter = 0
        if show:
            self._print_setup()
        return x

    def step(self, x: Vector, show: bool = False) -> Vector:
        """One CG step (ref ``cls_basic.py:112-141``); α/β stay on
        device."""
        Opc = self.Op.matvec(self.c)
        cOpc = _abs(self.c.dot(Opc.conj()))
        a = self.kold / cOpc
        x = x + self.c * a
        self.r = self.r - Opc * a
        k = _abs(self.r.dot(self.r.conj()))
        b = k / self.kold
        self.c = self.r + self.c * b
        self.kold = k
        self.iiter += 1
        self.cost.append(jnp.sqrt(self.kold))
        telemetry.iteration("cg", self.iiter, resid=jnp.sqrt(k), k=k)
        if show:
            self._print_step(x)
        return x

    def run(self, x: Vector, niter: Optional[int] = None,
            show: bool = False, itershow=(10, 10, 10)) -> Vector:
        niter = self.niter if niter is None else niter
        if niter is None:
            raise ValueError("niter must not be None")
        while self.iiter < niter and float(jnp.max(self.kold)) > self.tol:
            showstep = show and (self.iiter < itershow[0]
                                 or niter - self.iiter < itershow[1]
                                 or self.iiter % itershow[2] == 0)
            x = self.step(x, showstep)
            self.callback(x)
        return x

    def finalize(self, show: bool = False) -> None:
        self.tend = time.time()
        self.telapsed = self.tend - self.tstart
        self.cost = np.asarray(jnp.stack(self.cost))

    def solve(self, y: Vector, x0: Vector, niter: int = 10, tol: float = 1e-4,
              show: bool = False, itershow=(10, 10, 10)
              ) -> Tuple[Vector, int, np.ndarray]:
        x = self.setup(y=y, x0=x0, niter=niter, tol=tol, show=show)
        x = self.run(x, niter, show=show, itershow=itershow)
        self.finalize(show)
        return x, self.iiter, self.cost

    def _print_setup(self):
        print(f"CG\ntol = {self.tol:10e}\tniter = {self.niter}")

    def _print_step(self, x):
        print(f"{self.iiter:6g}        {float(jnp.max(self.cost[self.iiter])):11.4e}")


class CGLS(_BaseSolver):
    """Damped least-squares CGLS (ref ``cls_basic.py:252-531``).

    Like :class:`CG`, the ``setup``/``step``/``run`` API is the
    host-synced slow path, provided for callback parity; the functional
    :func:`cgls` (fused ``lax.while_loop``) is the fast path."""

    def setup(self, y: Vector, x0: Vector, niter: Optional[int] = None,
              damp: float = 0.0, tol: float = 1e-4,
              show: bool = False) -> Vector:
        self.y = y
        self.damp = damp ** 2
        self.tol = tol
        self.niter = niter
        x = x0.copy()
        self.s = self.y - self.Op.matvec(x)
        # ref cls_basic.py:347-349 uses un-squared damp here (see module doc)
        r = self.Op.rmatvec(self.s) - x * damp
        self.c = r.copy()
        self.q = self.Op.matvec(self.c)
        self.kold = _abs(r.dot(r.conj()))
        self.cost = [jnp.asarray(self.s.norm())]
        self.cost1 = [jnp.sqrt(self.cost[0] ** 2
                               + self.damp * _abs(x.dot(x.conj())))]
        self.iiter = 0
        if show:
            self._print_setup()
        return x

    def step(self, x: Vector, show: bool = False) -> Vector:
        """One CGLS step (ref ``cls_basic.py:373-404``)."""
        a = _abs(self.kold / (self.q.dot(self.q.conj())
                              + self.damp * self.c.dot(self.c.conj())))
        x = x + self.c * a
        self.s = self.s - self.q * a
        r = self.Op.rmatvec(self.s) - x * self.damp
        k = _abs(r.dot(r.conj()))
        b = k / self.kold
        self.c = r + self.c * b
        self.q = self.Op.matvec(self.c)
        self.kold = k
        self.iiter += 1
        self.cost.append(jnp.asarray(self.s.norm()))
        self.cost1.append(jnp.sqrt(self.cost[self.iiter] ** 2
                                   + self.damp * _abs(x.dot(x.conj()))))
        telemetry.iteration("cgls", self.iiter,
                            resid=self.cost[self.iiter], k=k)
        if show:
            self._print_step(x)
        return x

    def run(self, x: Vector, niter: Optional[int] = None,
            show: bool = False, itershow=(10, 10, 10)) -> Vector:
        niter = self.niter if niter is None else niter
        if niter is None:
            raise ValueError("niter must not be None")
        while self.iiter < niter and float(jnp.max(self.kold)) > self.tol:
            showstep = show and (self.iiter < itershow[0]
                                 or niter - self.iiter < itershow[1]
                                 or self.iiter % itershow[2] == 0)
            x = self.step(x, showstep)
            self.callback(x)
        return x

    def finalize(self, show: bool = False) -> None:
        self.tend = time.time()
        self.telapsed = self.tend - self.tstart
        self.istop = 1 if float(jnp.max(self.kold)) < self.tol else 2
        self.r1norm = self.kold
        self.r2norm = self.cost1[self.iiter]
        self.cost = np.asarray(jnp.stack(self.cost))
        self.cost1 = np.asarray(jnp.stack(self.cost1))

    def solve(self, y: Vector, x0: Vector, niter: int = 10, damp: float = 0.0,
              tol: float = 1e-4, show: bool = False, itershow=(10, 10, 10)
              ) -> Tuple[Vector, int, int, jax.Array, jax.Array, np.ndarray]:
        x = self.setup(y=y, x0=x0, niter=niter, damp=damp, tol=tol, show=show)
        x = self.run(x, niter, show=show, itershow=itershow)
        self.finalize(show)
        return x, self.istop, self.iiter, self.r1norm, self.r2norm, self.cost

    def _print_setup(self):
        print(f"CGLS\ntol = {self.tol:10e}\tniter = {self.niter}")

    def _print_step(self, x):
        print(f"{self.iiter:6g}        {float(jnp.max(self.cost[self.iiter])):11.4e}")


# --------------------------------------------------------- fused (on-device)
# Builder calling convention (shared by _get_fused and every fused
# loop below): all runtime operands are POSITIONAL with the model
# vector second — ``fn(y, x0, ...)`` — so donation can address it by
# argnum. ``x0`` is donated (``_DONATE_X0``): the loop carry starts in
# the caller's buffer instead of a program-entry copy, which is why
# the builders bind the carry as ``x = x0`` (a traced ``x0.copy()``
# would be exactly the copy-of-donated-state the HLO pin forbids —
# tests/test_precision.py::test_fused_cgls_donation).
#
# In-loop guards (ISSUE 6): every builder takes a static ``guards``
# flag. ``guards=False`` (the default, and the only mode when
# ``PYLOPS_MPI_TPU_GUARDS`` is off) traces EXACTLY the pre-guard
# program — bit-identical lowered HLO, pinned by the resilience
# suite. ``guards=True`` appends a ``(status, bestk, stall)`` guard
# carry computed purely from the recurrence scalars the loop already
# holds (zero host callbacks): NaN/Inf in the step/momentum/norm
# scalars or a denominator underflow reject the poisoned update (the
# carry keeps the LAST FINITE iterate) and exit with
# ``status=BREAKDOWN``; ``stall_n`` iterations without a new best
# residual exit with ``status=STAGNATION`` (the machine-precision
# freeze below is excluded — parked at the floor is done, not sick).
_DONATE_X0 = (1,)


def _i32(v):
    return jnp.asarray(v, dtype=jnp.int32)


def _reject(bad, old, new):
    """``old`` where ``bad`` else ``new``, elementwise over a
    (possibly stacked) distributed vector — the guard carries keep the
    last finite iterate by rejecting a poisoned update wholesale
    (scaling the step to zero would not do: ``NaN * 0`` is ``NaN``)."""
    if isinstance(new, StackedDistributedArray):
        return StackedDistributedArray(
            [_reject(bad, o, n)
             for o, n in zip(old.distarrays, new.distarrays)])
    return DistributedArray._wrap(jnp.where(bad, old._arr, new._arr), new)


def _guard_update(status, bestk, stall, bad, k, done, stall_n: int):
    """One guard-carry step, shared by every guarded body: breakdown
    beats stagnation; the stall counter only runs while the recurrence
    is live (not poisoned, not frozen at the machine-precision
    floor)."""
    from ..resilience import status as _rstatus
    kmax = jnp.max(k)
    improved = (kmax < bestk) & ~bad
    frozen = jnp.all(done)
    stall = jnp.where(bad | frozen, stall,
                      jnp.where(improved, jnp.zeros_like(stall),
                                stall + 1))
    bestk = jnp.where(improved, kmax, bestk)
    status = jnp.where(bad, _i32(_rstatus.BREAKDOWN),
                       jnp.where(stall >= stall_n,
                                 _i32(_rstatus.STAGNATION), status))
    return status, bestk, stall


def _resolve_status(status, kold, tol):
    """Post-loop status resolution (still on device): a loop that
    exited without a guard verdict either converged or ran out of
    iterations."""
    from ..resilience import status as _rstatus
    return jnp.where(
        status != _rstatus.RUNNING, status,
        jnp.where(jnp.max(kold) <= tol, _i32(_rstatus.CONVERGED),
                  _i32(_rstatus.MAXITER)))


def _fault_sites(guards: bool, fault):
    """Static (nan_at, stall_at) injection iterations for a guarded
    body — both ``None`` (nothing traced) unless a chaos fault is
    armed (resilience/faults.py)."""
    if not guards or not fault:
        return None, None
    if fault.get("kind") == "nan":
        return fault["iteration"], None
    if fault.get("kind") == "stall":
        return None, fault["iteration"]
    return None, None


def _make_cg_body(Op, xdt, floors, *, M=None, guards=False,
                  carry_status=False, stall_n=0, fault=None):
    """CG loop body over the carry ``(x, r, c, kold, iiter, cost
    [, status][, bestk, stall])`` — the one implementation behind the
    single-shot fused loop, the guarded variant and the segmented
    epoch program. ``carry_status`` threads the status word without
    the detectors (the segmented path always carries it so resumed
    epochs keep one pytree).

    ``M`` is the preconditioner seam (PCG): ``z = M r`` replaces ``r``
    in the recurrence norm (``kold = r·z``) and the direction update
    (``c = z + b c``) — the TRUE residual ``r`` stays in the carry, so
    the carry pytree (shapes, dtypes, donation aliasing) is identical
    with and without M, and ``M=None`` traces the exact
    unpreconditioned program (``z`` IS ``r``)."""
    from ..resilience import faults as _faults
    nan_at, stall_at = _fault_sites(guards, fault)

    def body(state):
        if guards:
            x, r, c, kold, iiter, cost, status, bestk, stall = state
        elif carry_status:
            x, r, c, kold, iiter, cost, status = state
        else:
            x, r, c, kold, iiter, cost = state
        done = kold <= floors
        Opc = Op.matvec(c)
        if nan_at is not None:
            Opc = _faults.inject_nan(Opc, iiter, nan_at)
        a = kold / _rdot(c, Opc)
        a = jnp.where(done, jnp.zeros_like(a), a)
        if stall_at is not None:
            a = _faults.inject_stall(a, iiter, stall_at)
        xn = x + c * _step_scalar(a, xdt)
        rn = r - Opc * _step_scalar(a, xdt)
        zn = _precond_apply(M, rn, xdt)
        k = _rdot(rn, zn)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        cn = zn + c * _step_scalar(b, xdt)
        if guards:
            bad = (jnp.any(~jnp.isfinite(a)) | jnp.any(~jnp.isfinite(k))
                   | jnp.any(~jnp.isfinite(b)))
            x = _reject(bad, x, xn)
            r = _reject(bad, r, rn)
            c = _reject(bad, c, cn)
            k = jnp.where(bad, kold, k)
            status, bestk, stall = _guard_update(status, bestk, stall,
                                                 bad, k, done, stall_n)
        else:
            x, r, c = xn, rn, cn
        iiter = iiter + 1
        cost = lax.dynamic_update_index_in_dim(cost, jnp.sqrt(k), iiter, 0)
        # no-op unless telemetry is enabled (PYLOPS_MPI_TPU_TRACE=full):
        # disabled builds trace NOTHING here — the zero-host-callback pin
        telemetry.iteration("cg", iiter, resid=jnp.sqrt(k), k=k, alpha=a)
        if guards:
            return (x, r, c, k, iiter, cost, status, bestk, stall)
        if carry_status:
            return (x, r, c, k, iiter, cost, status)
        return (x, r, c, k, iiter, cost)

    return body


def _cg_fused(Op, y: Vector, x0: Vector, tol, *, niter: int, M=None,
              guards: bool = False, stall_n: int = 0, fault=None):
    """Whole CG solve as one ``lax.while_loop`` (SURVEY §3.2: the
    reference's hot loop does 4 host-synced allreduces per iteration —
    here everything fuses into a single XLA program). Recurrence
    scalars accumulate at the policy reduction dtype (``_rdot``) and
    re-enter vector updates at the carry dtype (``_step_scalar``) so
    the carry pytree dtypes are identical at iteration 1 and k.
    ``M`` preconditions (PCG — see :func:`_make_cg_body`);
    ``guards=True`` returns an extra status word (see the section
    comment above)."""
    xdt = _vdtype(x0)
    x = x0  # donated: the carry aliases the caller's buffer in place
    r = y - Op.matvec(x)
    z = _precond_apply(M, r, xdt)
    c = z
    kold = _rdot(r, z)
    floors = _mp_floor(kold)
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold), dtype=jnp.asarray(kold).dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold), 0, 0)
    body = _make_cg_body(Op, xdt, floors, M=M, guards=guards,
                         stall_n=stall_n, fault=fault)
    if guards:
        from ..resilience import status as _rstatus
        state = (x, r, c, kold, jnp.asarray(0), cost0,
                 _i32(_rstatus.RUNNING), jnp.max(kold), _i32(0))

        def cond(state):
            return ((state[4] < niter) & (jnp.max(state[3]) > tol)
                    & (state[6] == _rstatus.RUNNING))

        x, r, c, kold, iiter, cost, status, _, _ = \
            lax.while_loop(cond, body, state)
        return x, iiter, cost, _resolve_status(status, kold, tol)

    def cond(state):
        _, _, _, kold, iiter, _ = state
        return (iiter < niter) & (jnp.max(kold) > tol)

    state = (x, r, c, kold, jnp.asarray(0), cost0)
    x, r, c, kold, iiter, cost = lax.while_loop(cond, body, state)
    return x, iiter, cost


def _make_cgls_body(Op, xdt, damp2, floors, *, M=None, normal=False,
                    guards=False, carry_status=False, stall_n=0,
                    fault=None):
    """CGLS loop body (classic two-sweep or fused-normal) over the
    carry ``(x, s, c, q, ...)`` / ``(x, s, r, c, ...)`` — shared by the
    single-shot loops, the guarded variants and the segmented epoch
    program (solvers/segmented.py). ``M`` preconditions the NORMAL
    equations (PCGLS): it should approximate ``(OpᴴOp + damp²)⁻¹``;
    applied to the normal residual in both sweep schedules, carries
    unchanged, ``M=None`` bit-identical (see :func:`_make_cg_body`)."""
    from ..resilience import faults as _faults
    nan_at, stall_at = _fault_sites(guards, fault)

    def body_classic(state):
        if guards:
            x, s, c, q, kold, iiter, cost, cost1, status, bestk, stall \
                = state
        elif carry_status:
            x, s, c, q, kold, iiter, cost, cost1, status = state
        else:
            x, s, c, q, kold, iiter, cost, cost1 = state
        done = kold <= floors
        a = _abs(kold / (_rdot(q, q) + damp2 * _rdot(c, c)))
        a = jnp.where(done, jnp.zeros_like(a), a)
        if stall_at is not None:
            a = _faults.inject_stall(a, iiter, stall_at)
        xn = x + c * _step_scalar(a, xdt)
        sn_ = s - q * _step_scalar(a, xdt)
        r = Op.rmatvec(sn_) - xn * damp2
        z = _precond_apply(M, r, xdt)
        k = _rdot(r, z)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        cn = z + c * _step_scalar(b, xdt)
        qn = Op.matvec(cn)
        if nan_at is not None:
            qn = _faults.inject_nan(qn, iiter, nan_at)
        if guards:
            bad = (jnp.any(~jnp.isfinite(a)) | jnp.any(~jnp.isfinite(k))
                   | jnp.any(~jnp.isfinite(b)))
            x = _reject(bad, x, xn)
            s = _reject(bad, s, sn_)
            c = _reject(bad, c, cn)
            q = _reject(bad, q, qn)
            k = jnp.where(bad, kold, k)
            status, bestk, stall = _guard_update(status, bestk, stall,
                                                 bad, k, done, stall_n)
        else:
            x, s, c, q = xn, sn_, cn, qn
        iiter = iiter + 1
        sn = jnp.asarray(s.norm())
        cost = lax.dynamic_update_index_in_dim(cost, sn, iiter, 0)
        r2 = jnp.sqrt(sn ** 2 + damp2 * _rdot(x, x))
        cost1 = lax.dynamic_update_index_in_dim(cost1, r2, iiter, 0)
        # no-op unless telemetry is enabled (see _make_cg_body note)
        telemetry.iteration("cgls", iiter, resid=sn, k=k, alpha=a)
        if guards:
            return (x, s, c, q, k, iiter, cost, cost1, status, bestk,
                    stall)
        if carry_status:
            return (x, s, c, q, k, iiter, cost, cost1, status)
        return (x, s, c, q, k, iiter, cost, cost1)

    def body_normal(state):
        if guards:
            x, s, r, c, kold, iiter, cost, cost1, status, bestk, stall \
                = state
        elif carry_status:
            x, s, r, c, kold, iiter, cost, cost1, status = state
        else:
            x, s, r, c, kold, iiter, cost, cost1 = state
        done = kold <= floors
        u, q = Op.normal_matvec(c)
        if nan_at is not None:
            u = _faults.inject_nan(u, iiter, nan_at)
            q = _faults.inject_nan(q, iiter, nan_at)
        a = _abs(kold / (_rdot(q, q) + damp2 * _rdot(c, c)))
        a = jnp.where(done, jnp.zeros_like(a), a)
        if stall_at is not None:
            a = _faults.inject_stall(a, iiter, stall_at)
        xn = x + c * _step_scalar(a, xdt)
        sn_ = s - q * _step_scalar(a, xdt)
        rn = r - (u + c * damp2) * _step_scalar(a, xdt)
        zn = _precond_apply(M, rn, xdt)
        k = _rdot(rn, zn)
        k = jnp.where(done, kold, k)
        b = jnp.where(done, jnp.zeros_like(k), k / kold)
        cn = zn + c * _step_scalar(b, xdt)
        if guards:
            bad = (jnp.any(~jnp.isfinite(a)) | jnp.any(~jnp.isfinite(k))
                   | jnp.any(~jnp.isfinite(b)))
            x = _reject(bad, x, xn)
            s = _reject(bad, s, sn_)
            r = _reject(bad, r, rn)
            c = _reject(bad, c, cn)
            k = jnp.where(bad, kold, k)
            status, bestk, stall = _guard_update(status, bestk, stall,
                                                 bad, k, done, stall_n)
        else:
            x, s, r, c = xn, sn_, rn, cn
        iiter = iiter + 1
        sn = jnp.asarray(s.norm())
        cost = lax.dynamic_update_index_in_dim(cost, sn, iiter, 0)
        r2 = jnp.sqrt(sn ** 2 + damp2 * _rdot(x, x))
        cost1 = lax.dynamic_update_index_in_dim(cost1, r2, iiter, 0)
        # no-op unless telemetry is enabled (see _make_cg_body note)
        telemetry.iteration("cgls", iiter, resid=sn, k=k, alpha=a)
        if guards:
            return (x, s, r, c, k, iiter, cost, cost1, status, bestk,
                    stall)
        if carry_status:
            return (x, s, r, c, k, iiter, cost, cost1, status)
        return (x, s, r, c, k, iiter, cost, cost1)

    return body_normal if normal else body_classic


def _cgls_setup(Op, y: Vector, x0: Vector, damp, damp2, *, niter: int,
                normal: bool, M=None):
    """Shared CGLS prologue: residuals, first direction, recurrence
    norm, machine-precision floor and the cost buffers — used by the
    single-shot fused loops here and the segmented driver
    (solvers/segmented.py), which must seed the exact same carry."""
    x = x0  # donated: carry aliases the caller's buffer (see _DONATE_X0)
    s = y - Op.matvec(x)
    rq = Op.rmatvec(s) - x * damp  # ref's un-squared setup damp (see
    z = _precond_apply(M, rq, _vdtype(x0))  # module doc) seeds only
    c = z                          # the first direction, as in the
    if not normal:                 # classic path
        q = Op.matvec(c)
    kold = _rdot(rq, z)
    floors = _mp_floor(kold)
    if normal:
        # the recurrence tracks the true gradient r = Opᴴs − damp²x, so
        # it must start from the damp²-form, not the quirked one
        r = rq + x * (damp - damp2)
    sn0 = jnp.asarray(s.norm())
    cost0 = jnp.zeros((niter + 1,) + jnp.shape(sn0), dtype=sn0.dtype)
    cost0 = lax.dynamic_update_index_in_dim(cost0, sn0, 0, 0)
    cost1_0 = lax.dynamic_update_index_in_dim(
        jnp.zeros_like(cost0),
        jnp.sqrt(sn0 ** 2 + damp2 * _rdot(x, x)), 0, 0)
    if normal:
        return (x, s, r, c, kold), floors, cost0, cost1_0
    return (x, s, c, q, kold), floors, cost0, cost1_0


def _cgls_fused_any(Op, y: Vector, x0: Vector, damp, tol, *, niter: int,
                    normal: bool, guards: bool, M=None, stall_n: int = 0,
                    fault=None):
    damp2 = damp ** 2
    xdt = _vdtype(x0)
    head, floors, cost0, cost1_0 = _cgls_setup(Op, y, x0, damp, damp2,
                                               niter=niter, normal=normal,
                                               M=M)
    body = _make_cgls_body(Op, xdt, damp2, floors, M=M, normal=normal,
                           guards=guards, stall_n=stall_n, fault=fault)
    if guards:
        from ..resilience import status as _rstatus
        kold0 = head[4]
        state = head + (jnp.asarray(0), cost0, cost1_0,
                        _i32(_rstatus.RUNNING), jnp.max(kold0), _i32(0))

        def cond(state):
            return ((state[5] < niter) & (jnp.max(state[4]) > tol)
                    & (state[8] == _rstatus.RUNNING))

        out = lax.while_loop(cond, body, state)
        x, kold, iiter, cost, cost1, status = (out[0], out[4], out[5],
                                               out[6], out[7], out[8])
        return (x, iiter, cost, cost1, kold,
                _resolve_status(status, kold, tol))

    def cond(state):
        return (state[5] < niter) & (jnp.max(state[4]) > tol)

    state = head + (jnp.asarray(0), cost0, cost1_0)
    out = lax.while_loop(cond, body, state)
    return out[0], out[5], out[6], out[7], out[4]


def _cgls_fused(Op, y: Vector, x0: Vector, damp, tol, *, niter: int,
                guards: bool = False, M=None, stall_n: int = 0,
                fault=None):
    return _cgls_fused_any(Op, y, x0, damp, tol, niter=niter,
                           normal=False, guards=guards, M=M,
                           stall_n=stall_n, fault=fault)


def _cgls_fused_normal(Op, y: Vector, x0: Vector, damp, tol, *,
                       niter: int, guards: bool = False, M=None,
                       stall_n: int = 0, fault=None):
    """CGLS with one operator memory sweep per iteration: the step uses
    ``(u, q) = Op.normal_matvec(c)`` (``u = OpᴴOp c`` computed in the
    same pass that yields ``q = Op c``) and the gradient recurrence
    ``r ← r − a (u + damp² c)``, which is algebraically identical to the
    textbook ``r = Opᴴ s − damp² x`` (s-update substituted). Halves HBM
    traffic on memory-bound matvecs; enabled when
    ``Op.has_fused_normal``."""
    return _cgls_fused_any(Op, y, x0, damp, tol, niter=niter,
                           normal=True, guards=guards, M=M,
                           stall_n=stall_n, fault=fault)


# Bounded LRU of compiled fused solvers. The operator itself is stored
# alongside the jitted fn: keeping it alive pins its id(), making the
# id-based key collision-free, and eviction drops both the executable
# and the operator's device buffers.
#
# Two documented consequences (round-1 VERDICT weak #9):
# - up to PYLOPS_MPI_TPU_FUSED_CACHE (default 32) operators stay alive
#   through the cache, holding their device buffers — call
#   clear_fused_cache() in long-lived sessions that churn operators;
# - an operator evicted and then reused recompiles silently (first
#   solve pays compile time again). Raise the env cap when iterating
#   over more than 32 distinct (operator, niter, shape) combinations.
import os
from collections import OrderedDict

_FUSED_CACHE: "OrderedDict" = OrderedDict()
try:
    _FUSED_CACHE_MAX = max(
        1, int(os.environ.get("PYLOPS_MPI_TPU_FUSED_CACHE", "32")))
except ValueError:  # malformed env var must not break import
    _FUSED_CACHE_MAX = 32


def clear_fused_cache() -> None:
    """Drop every cached fused-solver executable and the operator
    references (and device buffers) they pin."""
    _FUSED_CACHE.clear()


def _get_fused(Op, key, make_builder, donate_argnums=(), keepalive=None,
               aot_eligible=False):
    """Compile (and cache) the fused loop for ``Op``.
    ``make_builder(op)`` must return the loop with that operator bound;
    the returned fn is called with POSITIONAL runtime operands (the
    builder calling convention above). ``donate_argnums`` are indices
    into those operands whose buffers the program may consume in place
    (the while_loop carry starts in the donated buffer instead of a
    program-entry copy) — applied only when the precision layer's
    donation gate is on (``PYLOPS_MPI_TPU_DONATE``), and folded into
    the cache key so flipping the gate retraces rather than reusing an
    executable with the wrong aliasing contract.

    Registered operator classes (``linearoperator.OP_ARRAY_PYTREES``)
    enter the jitted program as a pytree ARGUMENT — their device
    buffers are traced, not closed over, which multi-process JAX
    requires for arrays spanning non-addressable devices (exercised by
    tests/multihost_worker.py). Unregistered operators keep the
    closure form.

    ``keepalive`` pins any extra object whose ``id()`` participates in
    ``key`` (the preconditioner ``M``) for the life of the cache entry,
    so a freed-then-reallocated object can never alias a stale key.

    ``aot_eligible=True`` (set only by call sites whose key carries no
    process-local ids past element 0 — unpreconditioned, no armed
    fault spec) routes the jit-argument branch through the AOT
    executable bank (``pylops_mpi_tpu/aot/``) when
    ``PYLOPS_MPI_TPU_AOT`` arms it: the program is lowered+compiled
    explicitly, serialized to the bank, and on the next process start
    loaded in milliseconds instead of recompiled. With the tier off
    (the default) this parameter contributes NOTHING — same jit, same
    keys, bit-identical HLO (tests/test_aot.py pins it)."""
    from ..linearoperator import operator_is_jit_arg
    from ..ops._precision import donation_enabled
    donate = tuple(donate_argnums) if donation_enabled() else ()
    # telemetry state is compile-relevant: a program traced with the
    # in-loop debug callbacks embedded must never be reused when the
    # gate is off (and vice versa) — same pattern as the donation gate.
    # So is the reduce_stall latency seam (it traces a scalar chain
    # into every reduction); disarmed it contributes NOTHING, keeping
    # pre-seam keys byte-identical.
    from ..parallel.collectives import stall_signature
    key = key + (donate, telemetry.telemetry_signature()) \
        + stall_signature()
    entry = _FUSED_CACHE.get(key)
    if entry is None:
        if operator_is_jit_arg(Op):
            jfn = jax.jit(lambda op, *a: make_builder(op)(*a),
                          donate_argnums=tuple(i + 1 for i in donate))
            fn = None
            if aot_eligible:
                from .. import aot as _aot
                fn = _aot.maybe_aot_fused(jfn, Op, key)
            if fn is None:
                def fn(*a, _jfn=jfn, _op=Op):
                    return _jfn(_op, *a)
        else:
            fn = jax.jit(make_builder(Op), donate_argnums=donate)
        entry = (fn, Op, keepalive)
        _FUSED_CACHE[key] = entry
        if len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
            _FUSED_CACHE.popitem(last=False)
    else:
        _FUSED_CACHE.move_to_end(key)
    return entry[0]


def _donate_copy(v: Vector) -> Vector:
    """Fresh-buffer copy of a caller-owned vector so the fused entry
    can donate it: donation consumes the argument's buffer, and the
    public wrappers must not invalidate a vector the caller may reuse.
    One eager vector copy per solve — negligible against the solve,
    and the program-entry copy it replaces was the same bytes."""
    from ..ops._precision import donation_enabled
    return v.copy() if donation_enabled() else v


def _run_cg_fused(Op, y: Vector, x0: Vector, x0_owned: bool, niter: int,
                  tol, guards: bool, M=None):
    """Compile-cache-and-run the fused CG loop. Returns ``(x, iiter,
    cost, status_code)`` — ``status_code`` is ``None`` on the unguarded
    path (whose traced program is bit-identical to the pre-guard
    build; the guard carries only exist under ``guards=True``).
    ``M=None`` leaves the cache key byte-identical to the pre-seam
    layout (``_mkey`` contributes nothing), so unpreconditioned solves
    reuse existing entries.

    ``PYLOPS_MPI_TPU_CA`` routes here: any mode but ``off`` dispatches
    to the communication-avoiding tier (solvers/ca.py) under its own
    cache keys; ``off`` takes the classic path below untouched — same
    keys, same trace, bit-identical HLO (tests/test_ca.py)."""
    from . import ca as _ca
    _ca_mode = _ca.resolve_mode(Op, "cg")
    if _ca_mode != "off":
        return _ca.run_cg_fused(Op, y, x0, x0_owned, niter, tol,
                                guards, M=M, mode=_ca_mode)
    if guards:
        from ..resilience import faults as _faults, status as _rstatus
        spec = _faults.consume()
        stall_n = _rstatus.stall_window()
        fn = _get_fused(Op, (id(Op), "cg", niter, _vkey(y), _vkey(x0),
                             _rstatus.guards_signature(True),
                             _faults.fault_signature(spec)) + _mkey(M),
                        lambda op: partial(_cg_fused, op, niter=niter,
                                           guards=True, M=M,
                                           stall_n=stall_n, fault=spec),
                        donate_argnums=_DONATE_X0, keepalive=M,
                        aot_eligible=(M is None and spec is None))
        x, iiter, cost, status = fn(
            y, x0 if x0_owned else _donate_copy(x0), tol)
        iiter, code = int(iiter), int(status)
        _rstatus.record("cg", code, iiter)
        _metrics.inc("solver.cg.solves")
        _metrics.inc("solver.cg.iterations", iiter)
        return x, iiter, np.asarray(cost)[:iiter + 1], code
    fn = _get_fused(Op, (id(Op), "cg", niter, _vkey(y),
                         _vkey(x0)) + _mkey(M),
                    lambda op: partial(_cg_fused, op, niter=niter, M=M),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None))
    x, iiter, cost = fn(y, x0 if x0_owned else _donate_copy(x0), tol)
    iiter = int(iiter)
    # host-side, AFTER the fused loop returned: metrics never add an
    # in-loop callback (the fleet-obs HLO pin)
    _metrics.inc("solver.cg.solves")
    _metrics.inc("solver.cg.iterations", iiter)
    return x, iiter, np.asarray(cost)[:iiter + 1], None


def cg(Op, y: Vector, x0: Optional[Vector] = None, niter: int = 10,
       tol: float = 1e-4, show: bool = False, itershow=(10, 10, 10),
       callback: Optional[Callable] = None, fused: Optional[bool] = None,
       guards: Optional[bool] = None,
       M=None) -> Tuple[Vector, int, np.ndarray]:
    """Functional CG (ref ``optimization/basic.py:13-70``). With no
    callback/show, runs the fused on-device loop. ``guards`` resolves
    against ``PYLOPS_MPI_TPU_GUARDS`` (resilience/status.py): guarded
    fused solves can exit early on breakdown/stagnation — the return
    signature is unchanged, the status word lands in
    ``resilience.status.last_status("cg")``.

    ``M`` is an optional preconditioner (an ``MPILinearOperator``
    approximating ``Op⁻¹``, SPD) applied to the residual inside the
    fused while_loop — see docs/preconditioning.md. Fused path only.

    Under ``PYLOPS_MPI_TPU_AUTODIFF=on``, traced inputs (calls inside
    ``jax.jit``/``jax.grad``) reroute to the implicit-diff rule
    (autodiff/implicit.py) instead of failing on host conversions —
    fused path only, guards excluded; with the knob off (default) this
    check is one host-side env read and the traced/lowered programs
    are bit-identical (tests/test_autodiff.py pins it)."""
    from ..utils import deps as _deps
    if _deps.autodiff_enabled():
        from ..autodiff import implicit as _autodiff
        if _autodiff.should_intercept(Op, y, x0):
            if callback is not None or show or fused is False:
                raise ValueError(
                    "traced cg() (PYLOPS_MPI_TPU_AUTODIFF=on) supports "
                    "only the fused path: callback/show/fused=False "
                    "need host synchronization inside the trace")
            return _autodiff.entry_cg(Op, y, x0, niter, tol, M)
    x0_owned = x0 is None  # freshly built → donate without a copy
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    use_fused = fused if fused is not None else (callback is None and not show)
    if use_fused and (callback is not None or show):
        raise ValueError("fused=True cannot honor callback/show; use "
                         "fused=False for per-iteration hooks")
    if M is not None and not use_fused:
        raise ValueError("M= (preconditioning) requires the fused path; "
                         "drop callback/show or pass fused=True")
    from ..resilience.status import guards_enabled
    use_guards = use_fused and guards_enabled(guards)
    with _trace.span("solver.cg", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, dtype=_vdtype(x0), niter=niter,
                     tol=tol, fused=use_fused, guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()), \
            _metrics.timer("solver.cg"):
        if use_fused:
            x, iiter, cost, _ = _run_cg_fused(Op, y, x0, x0_owned,
                                              niter, tol, use_guards,
                                              M=M)
            return x, iiter, cost
        solver = CG(Op)
        solver._callback_wrap(callback)
        x, iiter, cost = solver.solve(y, x0, niter=niter, tol=tol,
                                      show=show, itershow=itershow)
        return x, iiter, cost


def cg_guarded(Op, y: Vector, x0: Optional[Vector] = None,
               niter: int = 10, tol: float = 1e-4, M=None):
    """Guarded fused CG with an explicit status word: returns
    ``(x, iiter, cost, status_code)`` where the code is one of
    ``resilience.status.{CONVERGED, MAXITER, BREAKDOWN, STAGNATION}``.
    On breakdown ``x`` is the last finite iterate — the restart seed
    for :func:`pylops_mpi_tpu.resilience.resilient_solve`."""
    x0_owned = x0 is None
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    with _trace.span("solver.cg", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, dtype=_vdtype(x0), niter=niter,
                     tol=tol, fused=True, guards=True,
                     telemetry=telemetry.telemetry_enabled()), \
            _metrics.timer("solver.cg"):
        return _run_cg_fused(Op, y, x0, x0_owned, niter, tol, True, M=M)


def _run_cgls_fused(Op, y: Vector, x0: Vector, x0_owned: bool,
                    niter: int, damp, tol, use_normal: bool,
                    guards: bool, M=None):
    """Compile-cache-and-run the fused CGLS loop; see
    :func:`_run_cg_fused` for the guard/status contract (including the
    ``M=None`` cache-key neutrality). Returns
    ``(x, iiter, cost, cost1, kold, status_code_or_None)``. Non-``off``
    ``PYLOPS_MPI_TPU_CA`` modes dispatch to solvers/ca.py (whose CGLS
    cost lanes carry normal-residual norms — docs/ca.md)."""
    from . import ca as _ca
    _ca_mode = _ca.resolve_mode(Op, "cgls")
    if _ca_mode != "off":
        return _ca.run_cgls_fused(Op, y, x0, x0_owned, niter, damp,
                                  tol, use_normal, guards, M=M,
                                  mode=_ca_mode)
    builder = _cgls_fused_normal if use_normal else _cgls_fused
    if guards:
        from ..resilience import faults as _faults, status as _rstatus
        spec = _faults.consume()
        stall_n = _rstatus.stall_window()
        fn = _get_fused(Op, (id(Op), "cgls", use_normal, niter,
                             _vkey(y), _vkey(x0),
                             _rstatus.guards_signature(True),
                             _faults.fault_signature(spec)) + _mkey(M),
                        lambda op: partial(builder, op, niter=niter,
                                           guards=True, M=M,
                                           stall_n=stall_n, fault=spec),
                        donate_argnums=_DONATE_X0, keepalive=M,
                        aot_eligible=(M is None and spec is None))
        x, iiter, cost, cost1, kold, status = fn(
            y, x0 if x0_owned else _donate_copy(x0), damp, tol)
        iiter, code = int(iiter), int(status)
        _rstatus.record("cgls", code, iiter)
        _metrics.inc("solver.cgls.solves")
        _metrics.inc("solver.cgls.iterations", iiter)
        return (x, iiter, np.asarray(cost)[:iiter + 1],
                np.asarray(cost1)[:iiter + 1], kold, code)
    fn = _get_fused(Op, (id(Op), "cgls", use_normal, niter,
                         _vkey(y), _vkey(x0)) + _mkey(M),
                    lambda op: partial(builder, op, niter=niter, M=M),
                    donate_argnums=_DONATE_X0, keepalive=M,
                    aot_eligible=(M is None))
    x, iiter, cost, cost1, kold = fn(
        y, x0 if x0_owned else _donate_copy(x0), damp, tol)
    iiter = int(iiter)
    _metrics.inc("solver.cgls.solves")
    _metrics.inc("solver.cgls.iterations", iiter)
    return (x, iiter, np.asarray(cost)[:iiter + 1],
            np.asarray(cost1)[:iiter + 1], kold, None)


def cgls(Op, y: Vector, x0: Optional[Vector] = None, niter: int = 10,
         damp: float = 0.0, tol: float = 1e-4, show: bool = False,
         itershow=(10, 10, 10), callback: Optional[Callable] = None,
         fused: Optional[bool] = None, normal: Optional[bool] = None,
         guards: Optional[bool] = None, M=None):
    """Functional CGLS (ref ``optimization/basic.py:73-148``).

    ``normal=True`` selects the one-sweep normal-equations iteration
    (``_cgls_fused_normal``) — fastest on memory-bound operators that
    provide a fused ``normal_matvec`` (e.g. batched MPIBlockDiag), but
    its gradient recurrence drifts slightly in f32, so it is opt-in.
    ``guards`` resolves against ``PYLOPS_MPI_TPU_GUARDS`` (see
    :func:`cg`); the status word lands in
    ``resilience.status.last_status("cgls")``.

    ``M`` is an optional preconditioner for the NORMAL system — an SPD
    ``MPILinearOperator`` approximating ``(OpᴴOp + damp²I)⁻¹``, applied
    to the normal residual ``Opᴴ s − damp² x`` inside the fused loop
    (docs/preconditioning.md). Fused path only.

    ``PYLOPS_MPI_TPU_AUTODIFF=on`` reroutes traced inputs to the
    implicit-diff rule — see :func:`cg` (same fused-only restriction;
    ``normal=True`` is a forward-schedule choice the fixed-point rule
    does not need, so the traced path always runs the classic
    two-sweep schedule)."""
    from ..utils import deps as _deps
    if _deps.autodiff_enabled():
        from ..autodiff import implicit as _autodiff
        if _autodiff.should_intercept(Op, y, x0):
            if callback is not None or show or fused is False:
                raise ValueError(
                    "traced cgls() (PYLOPS_MPI_TPU_AUTODIFF=on) "
                    "supports only the fused path: callback/show/"
                    "fused=False need host synchronization inside the "
                    "trace")
            return _autodiff.entry_cgls(Op, y, x0, niter, damp, tol, M)
    x0_owned = x0 is None  # freshly built → donate without a copy
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    use_fused = fused if fused is not None else (callback is None and not show)
    if use_fused and (callback is not None or show):
        raise ValueError("fused=True cannot honor callback/show; use "
                         "fused=False for per-iteration hooks")
    if M is not None and not use_fused:
        raise ValueError("M= (preconditioning) requires the fused path; "
                         "drop callback/show or pass fused=True")
    use_normal = bool(normal)
    if use_normal and not use_fused:
        raise ValueError("normal=True requires the fused path; drop "
                         "callback/show or pass fused=True")
    from ..resilience.status import guards_enabled
    use_guards = use_fused and guards_enabled(guards)
    with _trace.span("solver.cgls", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, dtype=_vdtype(x0), niter=niter,
                     damp=damp, tol=tol, fused=use_fused,
                     normal=use_normal, guards=use_guards,
                     telemetry=telemetry.telemetry_enabled()), \
            _metrics.timer("solver.cgls"):
        if use_fused:
            x, iiter, cost, cost1, kold, _ = _run_cgls_fused(
                Op, y, x0, x0_owned, niter, damp, tol, use_normal,
                use_guards, M=M)
            istop = 1 if float(jnp.max(kold)) < tol else 2
            return x, istop, iiter, kold, cost1[-1], cost
        solver = CGLS(Op)
        solver._callback_wrap(callback)
        return solver.solve(y, x0, niter=niter, damp=damp, tol=tol,
                            show=show, itershow=itershow)


def cgls_guarded(Op, y: Vector, x0: Optional[Vector] = None,
                 niter: int = 10, damp: float = 0.0, tol: float = 1e-4,
                 normal: bool = False, M=None):
    """Guarded fused CGLS with an explicit status word: returns
    ``(x, iiter, cost, cost1, kold, status_code)``; see
    :func:`cg_guarded` for the status contract."""
    x0_owned = x0 is None
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    with _trace.span("solver.cgls", cat="solver", op=type(Op).__name__,
                     shape=Op.shape, dtype=_vdtype(x0), niter=niter,
                     damp=damp, tol=tol, fused=True,
                     normal=bool(normal), guards=True,
                     telemetry=telemetry.telemetry_enabled()), \
            _metrics.timer("solver.cgls"):
        return _run_cgls_fused(Op, y, x0, x0_owned, niter, damp, tol,
                               bool(normal), True, M=M)


def _vkey(v: Vector):
    if isinstance(v, StackedDistributedArray):
        return tuple(_vkey(d) for d in v.distarrays)
    return (v.global_shape, v.partition, v.axis, v.mask, str(v.dtype))


def _zero_like_model(Op, y: Vector) -> Vector:
    """Build a zero initial model matching ``Op``'s input space."""
    if hasattr(Op, "model_template"):
        return Op.model_template()
    if isinstance(y, DistributedArray):
        return DistributedArray(global_shape=Op.shape[1], mesh=y.mesh,
                                partition=y.partition, dtype=y.dtype)
    raise ValueError("x0 required for stacked model spaces")

from .basic import CG, CGLS, cg, cgls, clear_fused_cache
from .sparsity import ISTA, FISTA, ista, fista
from .eigs import power_iteration

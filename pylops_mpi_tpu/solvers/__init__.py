from .basic import CG, CGLS, cg, cgls

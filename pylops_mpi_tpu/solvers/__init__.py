from .basic import (CG, CGLS, cg, cgls, cg_guarded, cgls_guarded,
                    clear_fused_cache)
from .sparsity import ISTA, FISTA, ista, fista, ista_guarded, fista_guarded
from .segmented import cg_segmented, cgls_segmented, SegmentedResult
from .block import (block_cg, block_cgls, block_cg_segmented,
                    batched_solve, BatchedResult, batched_cache_info)
from .eigs import power_iteration
from . import ca

from .basic import CG, CGLS, cg, cgls
from .sparsity import ISTA, FISTA, ista, fista
from .eigs import power_iteration

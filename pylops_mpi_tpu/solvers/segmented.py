"""Segmented fused solves: epoch-sized ``lax.while_loop``\\ s with
checkpoint/resume.

The single-shot fused solvers (``solvers/basic.py``) run all ``niter``
iterations inside one ``lax.while_loop`` — maximum throughput, zero
host syncs, and zero survivability: a preempted worker loses the whole
solve. This module splits ``niter`` into **epochs** of ``E`` fused
iterations; between epochs the carry surfaces to host, where it can be
checkpointed (``utils/checkpoint.save_fused_carry``) and inspected.
Killing the process between epochs and resuming from disk replays the
remaining epochs through the SAME compiled program on a bit-exact
carry, so the resumed trajectory is identical to the uninterrupted one
(exact equality on the CPU sim — the ISSUE 6 acceptance bar) whenever
the epoch length divides the schedule the same way.

Cost model: one host round-trip + (optionally) one checkpoint write
per ``E`` iterations. ``E`` defaults to ``PYLOPS_MPI_TPU_SEGMENT``
(unset/0 → one segment, i.e. the plain fused behavior); production
pod runs pick ``E`` so the checkpoint cadence matches the preemption
budget (docs/robustness.md).

Guards (``PYLOPS_MPI_TPU_GUARDS`` / ``guards=``) compose: a guarded
segmented solve exits its epoch early on breakdown/stagnation and the
driver stops with the status word, leaving the last finite iterate in
the final checkpoint.
"""

from __future__ import annotations

import os
from collections import namedtuple
from typing import Callable, Optional

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray
from ..diagnostics import trace as _trace
from .basic import (Vector, _get_fused, _vkey, _vdtype,
                    _zero_like_model, _rdot, _mp_floor, _i32, _mkey,
                    _make_cg_body, _make_cgls_body, _cgls_setup,
                    _precond_apply, _precond_signature)

__all__ = ["cg_segmented", "cgls_segmented", "SegmentedResult",
           "resolve_epoch"]

SegmentedResult = namedtuple(
    "SegmentedResult",
    ["x", "istop", "iiter", "r1norm", "r2norm", "cost", "status",
     "epochs"])
SegmentedResult.__doc__ = (
    "Result of a segmented fused solve: reference-parity fields "
    "(``x``, ``istop``, ``iiter``, ``r1norm``, ``r2norm``, ``cost``) "
    "plus the resilience status name and the number of epochs "
    "executed in THIS process (a resumed solve counts only its own).")

_FUSED_SCHEMA = 1  # checkpoint carry schema (utils/checkpoint.py)


def resolve_epoch(epoch: Optional[int], niter: int) -> int:
    """Epoch length: explicit ``epoch=`` kwarg beats
    ``PYLOPS_MPI_TPU_SEGMENT`` (unset/0 → one segment of ``niter``);
    clamped to ``[1, niter]``."""
    if epoch is None:
        try:
            epoch = int(os.environ.get("PYLOPS_MPI_TPU_SEGMENT", "0"))
        except ValueError:
            epoch = 0
        if epoch < 1:
            epoch = niter
    return max(1, min(int(epoch), niter))


def _guard_params(guards):
    from ..resilience.status import guards_enabled, stall_window
    on = guards_enabled(guards)
    return on, (stall_window() if on else 0)


# ------------------------------------------------------ epoch programs
def _cg_epoch_builder(Op, *, niter, guards, stall_n, M=None):
    def run(y, x, r, c, kold, iiter, cost, status, bestk, stall,
            floors, tol, epoch_end):
        body = _make_cg_body(Op, _vdtype(x), floors, M=M, guards=guards,
                             carry_status=not guards, stall_n=stall_n)
        if guards:
            from ..resilience import status as _rstatus
            state = (x, r, c, kold, iiter, cost, status, bestk, stall)

            def cond(st):
                return ((st[4] < epoch_end) & (jnp.max(st[3]) > tol)
                        & (st[6] == _rstatus.RUNNING))

            return lax.while_loop(cond, body, state)
        state = (x, r, c, kold, iiter, cost, status)

        def cond(st):
            return (st[4] < epoch_end) & (jnp.max(st[3]) > tol)

        out = lax.while_loop(cond, body, state)
        return out + (bestk, stall)  # keep one output schema

    return run


def _cgls_epoch_builder(Op, *, niter, guards, stall_n, M=None):
    def run(y, x, s, c, q, kold, iiter, cost, cost1, status, bestk,
            stall, floors, damp2, tol, epoch_end):
        body = _make_cgls_body(Op, _vdtype(x), damp2, floors, M=M,
                               normal=False, guards=guards,
                               carry_status=not guards, stall_n=stall_n)
        if guards:
            from ..resilience import status as _rstatus
            state = (x, s, c, q, kold, iiter, cost, cost1, status,
                     bestk, stall)

            def cond(st):
                return ((st[5] < epoch_end) & (jnp.max(st[4]) > tol)
                        & (st[8] == _rstatus.RUNNING))

            return lax.while_loop(cond, body, state)
        state = (x, s, c, q, kold, iiter, cost, cost1, status)

        def cond(st):
            return (st[5] < epoch_end) & (jnp.max(st[4]) > tol)

        out = lax.while_loop(cond, body, state)
        return out + (bestk, stall)

    return run


def _cg_setup_builder(Op, *, niter, M=None):
    def setup(y, x0):
        x = x0
        r = y - Op.matvec(x)
        z = _precond_apply(M, r, _vdtype(x0))
        c = z
        kold = _rdot(r, z)
        floors = _mp_floor(kold)
        cost0 = jnp.zeros((niter + 1,) + jnp.shape(kold),
                          dtype=jnp.asarray(kold).dtype)
        cost0 = lax.dynamic_update_index_in_dim(cost0, jnp.sqrt(kold),
                                                0, 0)
        return x, r, c, kold, cost0, floors

    return setup


def _cgls_setup_builder(Op, *, niter, M=None):
    def setup(y, x0, damp, damp2):
        head, floors, cost0, cost1_0 = _cgls_setup(
            Op, y, x0, damp, damp2, niter=niter, normal=False, M=M)
        return head + (cost0, cost1_0, floors)

    return setup


# ------------------------------------------------------ shared driver
def _final_status(guard_code: int, kold, tol) -> int:
    from ..resilience import status as _rstatus
    if guard_code != _rstatus.RUNNING:
        return guard_code
    kmax = float(jnp.max(jnp.asarray(kold)))
    if not np.isfinite(kmax):
        # host-side backstop: even an unguarded segmented solve can
        # name a poisoned recurrence when the carry surfaces
        return _rstatus.BREAKDOWN
    if kmax <= tol:
        return _rstatus.CONVERGED
    return _rstatus.MAXITER


def _load_carry(checkpoint_path, solver, mesh, expect):
    """Load + validate a fused-carry checkpoint; returns the state
    dict or ``None`` when the file/dir does not exist."""
    from ..utils import checkpoint as _ckpt
    if not checkpoint_path or not os.path.exists(checkpoint_path):
        return None
    state = _ckpt.load_fused_carry(checkpoint_path, solver, mesh=mesh)
    for key, want in expect.items():
        got = state.get(key)
        if isinstance(want, float):
            ok = got is not None and float(got) == float(want)
        else:
            ok = got == want
        if not ok:
            raise ValueError(
                f"fused-carry checkpoint {checkpoint_path!r} was saved "
                f"with {key}={got!r}, resume requested {key}={want!r}; "
                "resume must replay the same plan")
    return state


def cg_segmented(Op, y: Vector, x0: Optional[Vector] = None,
                 niter: int = 100, tol: float = 1e-4,
                 epoch: Optional[int] = None,
                 checkpoint_path: Optional[str] = None,
                 resume: bool = True, backend: Optional[str] = None,
                 guards: Optional[bool] = None,
                 on_epoch: Optional[Callable] = None,
                 resume_state: Optional[dict] = None,
                 M=None) -> SegmentedResult:
    """Segmented fused CG: epochs of ``epoch`` fused iterations,
    checkpointed to ``checkpoint_path`` after every epoch (when given)
    and auto-resumed from it (``resume=True``) after a kill.
    ``resume_state`` resumes from an in-memory carry instead — the
    in-place elastic path hands the replanted bank here so recovery
    never touches checkpoint I/O. ``M`` preconditions the fused
    epochs; its signature is banked in the checkpoint meta, so a
    resume under a DIFFERENT preconditioner refuses (the trajectory
    would silently diverge from the banked one)."""
    return _segmented(Op, y, x0, "cg", niter, 0.0, tol, epoch,
                      checkpoint_path, resume, backend, guards, on_epoch,
                      resume_state, M=M)


def cgls_segmented(Op, y: Vector, x0: Optional[Vector] = None,
                   niter: int = 100, damp: float = 0.0,
                   tol: float = 1e-4, epoch: Optional[int] = None,
                   checkpoint_path: Optional[str] = None,
                   resume: bool = True, backend: Optional[str] = None,
                   guards: Optional[bool] = None,
                   on_epoch: Optional[Callable] = None,
                   resume_state: Optional[dict] = None,
                   M=None) -> SegmentedResult:
    """Segmented fused CGLS (classic two-sweep schedule); see
    :func:`cg_segmented`. A killed process re-invoking with the same
    ``checkpoint_path`` (and the same ``niter``/``damp``/``tol``)
    resumes from the last banked epoch and reproduces the
    uninterrupted trajectory bit-identically when ``epoch`` divides
    the schedule the same way. ``resume_state`` (an in-memory carry,
    e.g. :func:`~pylops_mpi_tpu.resilience.elastic.restore_carry`'s
    output) takes precedence over the checkpoint and keeps the
    recovery path free of checkpoint reads."""
    return _segmented(Op, y, x0, "cgls", niter, damp, tol, epoch,
                      checkpoint_path, resume, backend, guards, on_epoch,
                      resume_state, M=M)


_CG_FIELDS = ("x", "r", "c", "kold", "iiter", "cost", "status",
              "bestk", "stall")
_CGLS_FIELDS = ("x", "s", "c", "q", "kold", "iiter", "cost", "cost1",
                "status", "bestk", "stall")


def _check_resume_state(state, expect):
    """Validate an in-memory resume carry against the requested plan —
    the same contract :func:`_load_carry` enforces for checkpoints."""
    for key, want in expect.items():
        got = state.get(key)
        if isinstance(want, float):
            ok = got is not None and float(got) == float(want)
        else:
            ok = got == want
        if not ok:
            raise ValueError(
                f"resume_state was banked with {key}={got!r}, resume "
                f"requested {key}={want!r}; resume must replay the "
                "same plan")
    return dict(state)


def _segmented(Op, y, x0, solver, niter, damp, tol, epoch,
               checkpoint_path, resume, backend, guards, on_epoch,
               resume_state=None, M=None):
    from ..resilience import status as _rstatus
    from ..resilience import elastic as _elastic
    from ..resilience.elastic import maybe_start_heartbeat
    from ..utils import checkpoint as _ckpt
    # under a supervisor (heartbeat file assigned in the env) the long
    # epoch loop is exactly what must prove liveness; no-op otherwise
    maybe_start_heartbeat()
    is_cgls = solver == "cgls"
    guards_on, stall_n = _guard_params(guards)
    E = resolve_epoch(epoch, niter)
    if x0 is None:
        x0 = _zero_like_model(Op, y)
    mesh = y.mesh if isinstance(y, DistributedArray) else None
    damp2 = damp ** 2

    # communication-avoiding tier (PYLOPS_MPI_TPU_CA, solvers/ca.py):
    # the CA carries are different pytrees, stamped into the checkpoint
    # meta so a resume under a different engine refuses. s-step is
    # CG-only and needs the fused-Gram-eligible spaces; everything else
    # downgrades to the pipelined engine.
    from . import ca as _ca
    from ..utils import deps as _deps
    ca = _ca.resolve_mode(Op, solver)
    if ca == "sstep" and (is_cgls or not _ca._sstep_eligible(y, x0)):
        ca = "pipelined"
    ca_s = _deps.ca_s_default() if ca == "sstep" else None
    if ca == "off":
        fields = _CGLS_FIELDS if is_cgls else _CG_FIELDS
    else:
        fields = _ca.seg_fields(solver, ca, M)

    meta = {"niter": niter, "tol": float(tol), "guards": guards_on,
            "precond": _precond_signature(M)}
    if is_cgls:
        meta["damp"] = float(damp)
    if resume_state is not None:
        state = _check_resume_state(resume_state, meta)
    else:
        state = (_load_carry(checkpoint_path, solver, mesh, meta)
                 if resume else None)
    if state is not None:
        _ca.check_resume_ca(state, ca, ca_s)
    resumed = state is not None
    # in-place elastic recovery: armed only under a supervisor that
    # assigned a reconfig file (or forced on); plain use stays inert
    ip_armed = _elastic.inplace_armed()

    with _trace.span(f"solver.{solver}_segmented", cat="solver",
                     op=type(Op).__name__, shape=Op.shape, niter=niter,
                     epoch=E, guards=guards_on, resumed=resumed,
                     checkpoint=bool(checkpoint_path)):
        if state is None:
            if ca == "sstep":
                def setup_builder(op, *, niter, M):
                    return _ca.sstep_cg_setup_builder(op, niter=niter,
                                                      M=M)
            elif ca == "pipelined":
                if is_cgls:
                    def setup_builder(op, *, niter, M):
                        return _ca.pipe_cgls_setup_builder(op,
                                                           niter=niter,
                                                           M=M)
                else:
                    def setup_builder(op, *, niter, M):
                        return _ca.pipe_cg_setup_builder(op,
                                                         niter=niter,
                                                         M=M)
            else:
                setup_builder = (_cgls_setup_builder if is_cgls
                                 else _cg_setup_builder)
            setup = _get_fused(Op, (id(Op), f"{solver}-seg-setup", niter,
                                    _vkey(y), _vkey(x0))
                               + _ca.ca_key(ca, ca_s) + _mkey(M),
                               lambda op: setup_builder(op, niter=niter,
                                                        M=M),
                               keepalive=M,
                               aot_eligible=(M is None))
            out = setup(y, x0, damp, damp2) if is_cgls else setup(y, x0)
            if ca == "sstep":
                nh = len(fields) - 6
                kold, cost, floors = out[nh:]
                vals = (list(out[:nh])
                        + [kold, jnp.asarray(0), cost])
            elif ca == "pipelined":
                nh = len(fields) - 7
                kold, aold, cost, floors = out[nh:]
                vals = (list(out[:nh])
                        + [kold, aold, jnp.asarray(0), cost])
            elif is_cgls:
                x, s, c, q, kold, cost, cost1, floors = out
                vals = [x, s, c, q, kold, jnp.asarray(0), cost, cost1]
            else:
                x, r, c, kold, cost, floors = out
                vals = [x, r, c, kold, jnp.asarray(0), cost]
            vals += [_i32(_rstatus.RUNNING), jnp.max(kold), _i32(0)]
            state = dict(zip(fields, vals))
            state["floors"] = floors
        if ca == "sstep":
            def run_builder(op, *, niter, guards, stall_n, M):
                return _ca.sstep_cg_epoch_builder(op, s=ca_s,
                                                  niter=niter,
                                                  guards=guards,
                                                  stall_n=stall_n, M=M)
        elif ca == "pipelined":
            if is_cgls:
                def run_builder(op, *, niter, guards, stall_n, M):
                    return _ca.pipe_cgls_epoch_builder(op, guards=guards,
                                                       stall_n=stall_n,
                                                       M=M)
            else:
                def run_builder(op, *, niter, guards, stall_n, M):
                    return _ca.pipe_cg_epoch_builder(op, guards=guards,
                                                     stall_n=stall_n,
                                                     M=M)
        else:
            run_builder = (_cgls_epoch_builder if is_cgls
                           else _cg_epoch_builder)
        run = _get_fused(Op, (id(Op), f"{solver}-seg", niter,
                              _vkey(y), _vkey(x0),
                              ("guards", guards_on,
                               stall_n if guards_on else None))
                         + _ca.ca_key(ca, ca_s) + _mkey(M),
                         lambda op: run_builder(op, niter=niter,
                                                guards=guards_on,
                                                stall_n=stall_n, M=M),
                         keepalive=M, aot_eligible=(M is None))

        epochs = 0
        while True:
            if ip_armed:
                rc = _elastic.pending_reconfig()
                if rc is not None:
                    # the supervisor shrank the world under us; unwind
                    # to the caller, who re-forms the mesh and resumes
                    # from the banked carry (elastic_worker.py)
                    raise _elastic.ElasticReconfig(rc)
            iiter = int(state["iiter"])
            code = int(state["status"])
            kmax = float(jnp.max(jnp.asarray(state["kold"])))
            if (iiter >= niter or kmax <= tol
                    or code != _rstatus.RUNNING
                    or not np.isfinite(kmax)):
                break
            epoch_end = min(iiter + E, niter)
            args = [state[f] for f in fields] + [state["floors"]]
            if is_cgls:
                args += [damp2]
            out = run(y, *args, tol, epoch_end)
            state = dict(zip(fields, out))
            state["floors"] = args[len(fields)]
            epochs += 1
            if ip_armed or checkpoint_path:
                carry = {**meta, "epoch": E,
                         "schema": (_FUSED_SCHEMA if ca == "off"
                                    else _ca.CA_SCHEMA)}
                if ca != "off":
                    # engine stamp: a resume under a different CA mode
                    # (or s) refuses — the carries are different pytrees
                    carry["ca"] = ca
                    if ca == "sstep":
                        carry["ca_s"] = int(ca_s)
                carry.update({f: state[f] for f in fields})
                carry["floors"] = state["floors"]
            if ip_armed:
                # bank BEFORE the checkpoint write: any epoch the
                # supervisor can observe as saved is also banked, so
                # an in-place recovery never resumes behind the disk
                _elastic.bank_carry(solver, carry)
            if checkpoint_path:
                _ckpt.save_fused_carry(checkpoint_path, solver, carry,
                                       backend=backend)
                _trace.event("solver.checkpoint", cat="resilience",
                             solver=solver, iiter=int(state["iiter"]),
                             epoch=epochs, path=checkpoint_path)
            if on_epoch is not None:
                on_epoch({"epoch": epochs, "iiter": int(state["iiter"]),
                          "resid": float(jnp.max(jnp.asarray(
                              state["cost"])[int(state["iiter"])])),
                          "status": _rstatus.status_name(
                              int(state["status"]))})

        iiter = int(state["iiter"])
        code = _final_status(int(state["status"]), state["kold"], tol)
        if guards_on:
            _rstatus.record(solver, code, iiter)
        cost = np.asarray(state["cost"])[:iiter + 1]
        istop = 1 if code == _rstatus.CONVERGED else 2
        if is_cgls and "cost1" in state:
            r2 = np.asarray(state["cost1"])[iiter]
        else:
            # CA engines carry a single cost lane (sqrt of the
            # preconditioned normal-residual norm for cgls)
            r2 = cost[-1] if len(cost) else None
        return SegmentedResult(
            x=state["x"], istop=istop, iiter=iiter,
            r1norm=state["kold"], r2norm=r2, cost=cost,
            status=_rstatus.status_name(code), epochs=epochs)

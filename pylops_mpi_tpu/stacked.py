"""StackedDistributedArray: a heterogeneous vector of DistributedArrays.

Rebuild of ref ``pylops_mpi/DistributedArray.py:963-1242``. In JAX a list
of arrays is already a pytree, so most of the reference class dissolves;
what remains is the solver-facing arithmetic/dot/norm API so stacked
operators (e.g. Gradient output) plug into CG/CGLS unchanged.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .distributedarray import DistributedArray

__all__ = ["StackedDistributedArray"]


class StackedDistributedArray:
    """Stack of :class:`DistributedArray`s with vector-space semantics
    (ref ``DistributedArray.py:963-1242``)."""

    def __init__(self, distarrays: Sequence[DistributedArray]):
        self.distarrays = list(distarrays)
        self.narrays = len(self.distarrays)

    def __getitem__(self, index):
        return self.distarrays[index]

    def __setitem__(self, index, value):
        self.distarrays[index] = value

    @property
    def global_shape(self):
        """Elementwise sum of component global shapes — the reference's
        (ref ``DistributedArray.py:1000-1035``) convention for nested
        stacking. Defined only when every component has the same rank;
        mixed-rank stacks raise (use ``size`` for the flat element
        count)."""
        if not self.distarrays:
            raise ValueError("global_shape of an empty stack is undefined")
        gs = self.distarrays[0].global_shape
        for d in self.distarrays[1:]:
            ds = d.global_shape
            if len(ds) != len(gs):
                raise ValueError(
                    "global_shape requires equal-rank components, got "
                    f"{len(gs)}-d and {len(ds)}-d; use .size instead")
            gs = tuple(a + b for a, b in zip(gs, ds))
        return gs

    @property
    def size(self) -> int:
        """Total number of elements across components (incl. nested)."""
        return int(sum(d.size for d in self.distarrays))

    def asarray(self) -> np.ndarray:
        """Global gather: concatenation of flattened components
        (ref ``DistributedArray.py:1196-1214``)."""
        return np.concatenate([d.asarray().ravel() for d in self.distarrays])

    def _apply(self, fn, other=None) -> "StackedDistributedArray":
        if other is None:
            return StackedDistributedArray([fn(d) for d in self.distarrays])
        self._check_stacked_size(other)
        return StackedDistributedArray(
            [fn(a, b) for a, b in zip(self.distarrays, other.distarrays)])

    def _check_stacked_size(self, other: "StackedDistributedArray"):
        if self.narrays != getattr(other, "narrays", None):
            raise ValueError("Stacked size mismatch")

    def copy(self):
        return self._apply(lambda d: d.copy())

    def conj(self):
        return self._apply(lambda d: d.conj())

    def zeros_like(self):
        return self._apply(lambda d: d.zeros_like())

    def empty_like(self):
        """Same layouts, uninitialized-semantics (zeros here: XLA has no
        cheaper alloc) — ref 0.6.0 ``StackedDistributedArray``
        addition."""
        return self._apply(lambda d: d.empty_like())

    def __neg__(self):
        return self._apply(lambda d: -d)

    def add(self, x):
        return self._apply(lambda a, b: a + b, x)

    def __add__(self, x):
        return self.add(x)

    def __iadd__(self, x):
        self._check_stacked_size(x)
        for i, d in enumerate(x.distarrays):
            self.distarrays[i] = self.distarrays[i] + d
        return self

    def __sub__(self, x):
        return self._apply(lambda a, b: a - b, x)

    def __isub__(self, x):
        self._check_stacked_size(x)
        for i, d in enumerate(x.distarrays):
            self.distarrays[i] = self.distarrays[i] - d
        return self

    def multiply(self, x):
        if isinstance(x, StackedDistributedArray):
            return self._apply(lambda a, b: a * b, x)
        return self._apply(lambda d: d * x)

    def __mul__(self, x):
        return self.multiply(x)

    def __rmul__(self, x):
        return self.multiply(x)

    def dot(self, y: "StackedDistributedArray", vdot: bool = False) -> jax.Array:
        """Sum of component dots (ref ``DistributedArray.py:1144-1159``)."""
        self._check_stacked_size(y)
        parts = [a.dot(b, vdot=vdot) for a, b in zip(self.distarrays, y.distarrays)]
        return sum(parts[1:], parts[0])

    def norm(self, ord=None) -> jax.Array:
        """Stacked vector norm combining component norms with the correct
        cross-component reduction per order
        (ref ``DistributedArray.py:1161-1194``)."""
        ord = 2 if ord is None else ord
        norms = jnp.stack([jnp.asarray(d.norm(ord)) for d in self.distarrays])
        if ord == 0:
            return jnp.sum(norms, axis=0)
        if ord == np.inf:
            return jnp.max(norms, axis=0)
        if ord == -np.inf:
            return jnp.min(norms, axis=0)
        return jnp.sum(norms ** ord, axis=0) ** (1.0 / ord)

    def __repr__(self):
        return f"<StackedDistributedArray with {self.narrays} arrays>"


def _stacked_flatten(x: StackedDistributedArray):
    return (x.distarrays,), None


def _stacked_unflatten(aux, children):
    out = StackedDistributedArray.__new__(StackedDistributedArray)
    out.distarrays = list(children[0])
    out.narrays = len(out.distarrays)
    return out


jax.tree_util.register_pytree_node(
    StackedDistributedArray, _stacked_flatten, _stacked_unflatten)

"""Distributed linear-operator abstraction with lazy composition algebra.

Rebuild of ``pylops_mpi/LinearOperator.py`` (ref lines 16-602). Operators
map :class:`DistributedArray` → :class:`DistributedArray`; every
``_matvec``/``_rmatvec`` is pure and jit-traceable, so whole solver loops
(including all operator algebra below) compile to a single XLA program —
the reference instead interprets the expression tree per call in Python
with host-synced collectives in between.

Lazy wrappers mirror ref ``LinearOperator.py:408-580``:
``_AdjointLinearOperator`` (swap mat/rmat), ``_TransposedLinearOperator``
(conj∘rmat∘conj), ``_ProductLinearOperator``, ``_ScaledLinearOperator``,
``_SumLinearOperator``, ``_PowerLinearOperator``, ``_ConjLinearOperator``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import numpy as np
import jax.numpy as jnp

from .distributedarray import DistributedArray, Partition
from .stacked import StackedDistributedArray

__all__ = ["MPILinearOperator", "LinearOperator", "aslinearoperator",
           "asmpilinearoperator"]

VectorLike = Union[DistributedArray, StackedDistributedArray]


def _scalar_like(x) -> bool:
    """Python/numpy scalars plus 0-d arrays (jax or numpy) — the
    latter possibly TRACED, which is how a learnable scalar weight
    (``eps * Reg`` under ``jax.grad``) enters the operator algebra."""
    if np.isscalar(x):
        return True
    import jax
    return (isinstance(x, (jax.Array, np.ndarray, np.generic))
            and np.ndim(x) == 0)


class MPILinearOperator:
    """Abstract distributed linear operator
    (ref ``pylops_mpi/LinearOperator.py:16-168``).

    Subclasses implement ``_matvec``/``_rmatvec`` on
    :class:`DistributedArray`. ``Op`` wraps a *local* operator (our
    jnp-based :mod:`ops.local` analog of a pylops op) applied to the
    array's global value — the one-controller equivalent of the
    reference's per-rank apply (ref ``LinearOperator.py:194-242``),
    which in practice targets replicated arrays.
    """

    def __init__(self, Op=None, shape: Optional[Tuple[int, int]] = None,
                 dtype=None):
        self.Op = Op
        if Op is not None:
            self.shape = Op.shape if shape is None else shape
            self.dtype = Op.dtype if dtype is None else dtype
        else:
            self.shape = shape
            self.dtype = np.dtype(dtype) if dtype is not None else None
        if not hasattr(self, "dims") or self.dims is None:
            self.dims = (self.shape[1],) if self.shape else None
        if not hasattr(self, "dimsd") or self.dimsd is None:
            self.dimsd = (self.shape[0],) if self.shape else None

    # subclasses may pre-set dims/dimsd before calling super().__init__
    dims: Optional[Tuple[int, ...]] = None
    dimsd: Optional[Tuple[int, ...]] = None

    # Block (column-batched) applies: a ``(N, K)`` DistributedArray is K
    # independent model vectors sharing one operator apply. Operators
    # whose ``_matvec``/``_rmatvec`` natively widen their contraction
    # over the trailing column axis set ``accepts_block = True``;
    # everything else falls back to a single compiled ``jax.vmap`` over
    # columns (no per-column Python loop either way).
    accepts_block = False

    # ------------------------------------------------------------- apply
    def matvec(self, x: VectorLike) -> VectorLike:
        """Forward apply with global-shape check
        (ref ``LinearOperator.py:170-192``). Accepts ``(N,)`` or the
        block form ``(N, K)`` — K model columns through one apply.
        Opens a diagnostics span (``PYLOPS_MPI_TPU_TRACE``) tagged with
        the operator class, shape, dtype and mesh axes; compositions
        nest naturally."""
        M, N = self.shape
        block = (isinstance(x, DistributedArray) and x.ndim == 2
                 and x.global_shape[0] == N)
        if isinstance(x, DistributedArray) and not block \
                and x.global_shape != (N,):
            raise ValueError(
                f"dimension mismatch: operator {self.shape}, x {x.global_shape}")
        from .diagnostics import trace
        with trace.op_span(self, "matvec"):
            if block and not self.accepts_block:
                return self._apply_columns(x, forward=True)
            return self._matvec(x)

    def rmatvec(self, x: VectorLike) -> VectorLike:
        """Adjoint apply with global-shape check
        (ref ``LinearOperator.py:206-230``). Accepts ``(M,)`` or the
        block form ``(M, K)``; traced like :meth:`matvec`."""
        M, N = self.shape
        block = (isinstance(x, DistributedArray) and x.ndim == 2
                 and x.global_shape[0] == M)
        if isinstance(x, DistributedArray) and not block \
                and x.global_shape != (M,):
            raise ValueError(
                f"dimension mismatch: operator {self.shape}, x {x.global_shape}")
        from .diagnostics import trace
        with trace.op_span(self, "rmatvec"):
            if block and not self.accepts_block:
                return self._apply_columns(x, forward=False)
            return self._rmatvec(x)

    def _apply_columns(self, x: "DistributedArray", forward: bool):
        """Generic block fallback: ``jax.vmap`` the single-column apply
        over the trailing axis — one traced program for all K columns.
        Operators with a native widened contraction (``accepts_block``)
        never reach this."""
        import jax
        fn = self._matvec if forward else self._rmatvec
        row_locals = tuple((s[0],) for s in x.local_shapes)
        tmpl = {}

        def one(col):
            xi = DistributedArray._wrap(
                col, x, global_shape=(x.global_shape[0],),
                local_shapes=row_locals)
            yi = fn(xi)
            if not isinstance(yi, DistributedArray):
                raise TypeError(
                    f"{type(self).__name__}: block apply supports "
                    f"DistributedArray results only, got "
                    f"{type(yi).__name__}")
            tmpl["like"] = yi
            return yi._arr

        out = jax.vmap(one, in_axes=1, out_axes=1)(x._arr)
        like = tmpl["like"]
        K = x.global_shape[1]
        return DistributedArray._wrap(
            out, like, global_shape=like.global_shape + (K,),
            local_shapes=tuple(tuple(s) + (K,) for s in like.local_shapes))

    def _wrap_local(self, y, x: "DistributedArray", n: int):
        out = DistributedArray(global_shape=n, mesh=x.mesh,
                               partition=x.partition, axis=0,
                               mask=x.mask, dtype=y.dtype)
        out[:] = y
        return out

    def _matvec(self, x: VectorLike) -> VectorLike:
        if self.Op is not None:
            return self._wrap_local(self.Op.matvec(x.array.ravel()), x,
                                    self.shape[0])
        raise NotImplementedError

    def _rmatvec(self, x: VectorLike) -> VectorLike:
        if self.Op is not None:
            return self._wrap_local(self.Op.rmatvec(x.array.ravel()), x,
                                    self.shape[1])
        raise NotImplementedError

    # ------------------------------------------------- normal-equations
    # ``(u, q) = (Opᴴ Op x, Op x)`` — the CGLS hot pair. The generic
    # path is two sweeps; operators that can produce both in one memory
    # pass (e.g. MPIBlockDiag's Pallas kernel) override this and set
    # ``has_fused_normal``.
    has_fused_normal = False

    def normal_matvec(self, x: VectorLike):
        q = self.matvec(x)
        return self.rmatvec(q), q

    # ----------------------------------------------------------- algebra
    def dot(self, x):
        """Operator-operator, operator-scalar or operator-vector product
        (ref ``LinearOperator.py:244-280``). Scalars include 0-d
        jax/numpy arrays — possibly TRACED (a learnable ``eps * Reg``
        weight under ``jax.grad``): the scale rides in ``args`` as a
        differentiable pytree leaf."""
        if isinstance(x, MPILinearOperator):
            return _ProductLinearOperator(self, x)
        if _scalar_like(x):
            return _ScaledLinearOperator(self, x)
        if isinstance(x, StackedDistributedArray) or x.ndim == 1:
            return self.matvec(x)
        if x.ndim == 2 and x.global_shape[0] == self.shape[1]:
            return self.matvec(x)  # block (column-batched) apply
        raise ValueError(f"expected 1-d DistributedArray, got {x.global_shape!r}")

    def adjoint(self):
        return self._adjoint()

    H = property(adjoint)

    def transpose(self):
        return self._transpose()

    T = property(transpose)

    def conj(self):
        return _ConjLinearOperator(self)

    def _adjoint(self):
        return _AdjointLinearOperator(self)

    def _transpose(self):
        return _TransposedLinearOperator(self)

    def __mul__(self, x):
        return self.dot(x)

    def __rmul__(self, x):
        if _scalar_like(x):
            return _ScaledLinearOperator(self, x)
        return NotImplemented

    def __matmul__(self, x):
        if _scalar_like(x):
            raise ValueError("Scalar not allowed, use * instead")
        return self.__mul__(x)

    def __rmatmul__(self, x):
        if _scalar_like(x):
            raise ValueError("Scalar not allowed, use * instead")
        return self.__rmul__(x)

    def __pow__(self, p):
        return _PowerLinearOperator(self, p)

    def __add__(self, x):
        return _SumLinearOperator(self, x)

    def __neg__(self):
        return _ScaledLinearOperator(self, -1)

    def __sub__(self, x):
        return self.__add__(-x)

    def checkpointed(self) -> "MPILinearOperator":
        """Wrap matvec/rmatvec in :func:`jax.checkpoint` (remat): under
        reverse-mode AD the operator's intermediates are recomputed in
        the backward pass instead of stored — the standard
        FLOPs-for-HBM trade for long composed chains whose activation
        memory would not fit. No effect outside AD."""
        return _CheckpointedLinearOperator(self)

    def todifferentiable(self, mode: str = "vjp", params=None) \
            -> "MPILinearOperator":
        """Wrap the operator with the adjoint autodiff rules: under
        ``jax.grad``/``jax.vjp`` (``mode="vjp"``) or ``jax.jvp``
        (``mode="jvp"``) its applies differentiate by the hand-written
        ``rmatvec``/``matvec`` instead of a machine-derived transpose
        of the forward collective schedule. See
        :class:`pylops_mpi_tpu.autodiff.DifferentiableOperator` for the
        ``params`` (operator-leaf cotangents) contract."""
        from .autodiff.rules import make_differentiable
        return make_differentiable(self, mode=mode, params=params)

    def todense(self) -> np.ndarray:
        """Dense matrix of the operator, by applying it to each identity
        column and gathering (serial-pylops convenience; the MPI
        reference has no equivalent because no rank holds the global
        matrix). O(n) matvecs — intended for tests and small operators
        (warned above n=8192)."""
        from .distributedarray import DistributedArray
        m, n = self.shape
        if n > 8192:
            import warnings
            warnings.warn(
                f"todense() runs {n} distributed matvecs and builds an "
                f"{m}x{n} dense matrix on host — tests/small operators "
                "only", stacklevel=2)
        dt = np.dtype(self.dtype)
        mesh = getattr(self, "mesh", None)
        shapes = getattr(self, "local_shapes_m",
                         getattr(self, "local_dim_sizes", None))
        out = np.zeros((m, n), dtype=dt)
        for j in range(n):
            e = np.zeros(n, dtype=dt)
            e[j] = 1
            col = self.matvec(DistributedArray.to_dist(
                e, mesh=mesh, local_shapes=shapes))
            out[:, j] = np.asarray(col.asarray())
        return out

    def __repr__(self):
        M, N = self.shape
        dt = "unspecified dtype" if self.dtype is None else f"dtype={self.dtype}"
        return f"<{M}x{N} {self.__class__.__name__} with {dt}>"


# Friendly alias — the TPU build has no MPI, but the reference-facing name
# is kept so user scripts port by changing only the import.
LinearOperator = MPILinearOperator


class _AdjointLinearOperator(MPILinearOperator):
    """ref ``LinearOperator.py:408-421``"""

    # all lazy wrappers delegate through the sub-operators' PUBLIC
    # matvec/rmatvec (which route block inputs to the child's native
    # widened contraction or its vmap fallback), so the wrappers
    # themselves accept the column axis
    accepts_block = True

    def __init__(self, A: MPILinearOperator):
        self.dims, self.dimsd = A.dimsd, A.dims
        super().__init__(shape=(A.shape[1], A.shape[0]), dtype=A.dtype)
        self.args = (A,)

    @property
    def A(self):
        # via args so pytree unflattening (which swaps args) keeps the
        # methods reading the traced sub-operator, not a stale copy
        return self.args[0]

    def _matvec(self, x):
        return self.A.rmatvec(x)

    def _rmatvec(self, x):
        return self.A.matvec(x)


class _TransposedLinearOperator(MPILinearOperator):
    """transpose = conj ∘ rmatvec ∘ conj (ref ``LinearOperator.py:424-443``)"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator):
        self.dims, self.dimsd = A.dimsd, A.dims
        super().__init__(shape=(A.shape[1], A.shape[0]), dtype=A.dtype)
        self.args = (A,)

    @property
    def A(self):
        return self.args[0]  # see _AdjointLinearOperator.A

    def _matvec(self, x):
        return self.A.rmatvec(x.conj()).conj()

    def _rmatvec(self, x):
        return self.A.matvec(x.conj()).conj()


class _ProductLinearOperator(MPILinearOperator):
    """ref ``LinearOperator.py:446-466``"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator, B: MPILinearOperator):
        if A.shape[1] != B.shape[0]:
            raise ValueError(f"cannot multiply {A} and {B}: shape mismatch")
        self.args = (A, B)
        self.dims, self.dimsd = B.dims, A.dimsd
        super().__init__(shape=(A.shape[0], B.shape[1]),
                         dtype=_get_dtype([A, B]))

    def _matvec(self, x):
        return self.args[0].matvec(self.args[1].matvec(x))

    def _rmatvec(self, x):
        return self.args[1].rmatvec(self.args[0].rmatvec(x))

    def _adjoint(self):
        A, B = self.args
        return B.H * A.H


class _ScaledLinearOperator(MPILinearOperator):
    """ref ``LinearOperator.py:469-496``"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator, alpha):
        if not _scalar_like(alpha):
            raise ValueError("scalar expected as alpha")
        self.args = (A, alpha)
        self.dims, self.dimsd = A.dims, A.dimsd
        # 0-d arrays (possibly traced) carry their own dtype; python
        # scalars keep the type-promotion rule of the reference
        adt = getattr(alpha, "dtype", None)
        super().__init__(shape=A.shape,
                         dtype=_get_dtype([A], [adt if adt is not None
                                                else type(alpha)]))

    @staticmethod
    def _conj(alpha):
        # host conj for concrete scalars (keeps scalar dispatch in
        # ``dot`` working); jnp.conj for the traced leaf the pytree
        # registration turns alpha into under jit
        return np.conj(alpha) if np.isscalar(alpha) else jnp.conj(alpha)

    def _matvec(self, x):
        return self.args[0].matvec(x) * self.args[1]

    def _rmatvec(self, x):
        return self.args[0].rmatvec(x) * self._conj(self.args[1])

    def _adjoint(self):
        A, alpha = self.args
        return A.H * self._conj(alpha)


class _SumLinearOperator(MPILinearOperator):
    """ref ``LinearOperator.py:499-524``"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator, B: MPILinearOperator):
        if A.shape != B.shape:
            raise ValueError(f"cannot add {A} and {B}: shape mismatch")
        self.args = (A, B)
        self.dims, self.dimsd = A.dims, A.dimsd
        super().__init__(shape=A.shape, dtype=_get_dtype([A, B]))

    def _matvec(self, x):
        return self.args[0].matvec(x) + self.args[1].matvec(x)

    def _rmatvec(self, x):
        return self.args[0].rmatvec(x) + self.args[1].rmatvec(x)

    def _adjoint(self):
        A, B = self.args
        return A.H + B.H


class _PowerLinearOperator(MPILinearOperator):
    """repeat-apply (ref ``LinearOperator.py:527-552``)"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator, p: int):
        if A.shape[0] != A.shape[1]:
            raise ValueError("square operator expected")
        if not isinstance(p, (int, np.integer)) or p < 0:
            raise ValueError("non-negative integer expected as p")
        self.args = (A, p)
        # p also kept OUTSIDE args: when the operator travels into jit
        # as a pytree argument, args' leaves are traced — the loop
        # bound must stay a static python int
        self._p = int(p)
        self.dims, self.dimsd = A.dims, A.dimsd
        super().__init__(shape=A.shape, dtype=A.dtype)

    def _power(self, fun, x):
        res = x.copy()
        for _ in range(self._p):
            res = fun(res)
        return res

    def _matvec(self, x):
        return self._power(self.args[0].matvec, x)

    def _rmatvec(self, x):
        return self._power(self.args[0].rmatvec, x)


class _ConjLinearOperator(MPILinearOperator):
    """ref ``LinearOperator.py:555-580``"""

    accepts_block = True

    def __init__(self, A: MPILinearOperator):
        self.dims, self.dimsd = A.dims, A.dimsd
        super().__init__(shape=A.shape, dtype=A.dtype)
        self.args = (A,)

    @property
    def A(self):
        return self.args[0]  # see _AdjointLinearOperator.A

    def _matvec(self, x):
        return self.A.matvec(x.conj()).conj()

    def _rmatvec(self, x):
        return self.A.rmatvec(x.conj()).conj()

    def _adjoint(self):
        return _ConjLinearOperator(self.A.H)


class _CheckpointedLinearOperator(MPILinearOperator):
    """Remat wrapper: matvec/rmatvec run under :func:`jax.checkpoint` so
    reverse-mode AD recomputes their intermediates instead of storing
    them (TPU HBM lever for long composed chains)."""

    accepts_block = True

    # layout metadata forwarded so dottest/todense/solvers see the same
    # shard layout on the wrapper as on the wrapped operator
    _FORWARDED = ("dims", "dimsd", "mesh", "local_shapes_m",
                  "local_shapes_n", "local_dim_sizes",
                  "local_extent_sizes")

    def __init__(self, A: MPILinearOperator):
        for attr in self._FORWARDED:
            if hasattr(A, attr):
                setattr(self, attr, getattr(A, attr))
        super().__init__(shape=A.shape, dtype=A.dtype)
        self.args = (A,)

    @property
    def A(self):
        return self.args[0]  # see _AdjointLinearOperator.A

    # checkpoint wrapping happens per call (cheap at trace time): a
    # bound-at-init closure would pin the ORIGINAL operator's buffers
    # even after pytree unflattening swapped in traced ones
    def _matvec(self, x):
        import jax
        return jax.checkpoint(self.args[0].matvec)(x)

    def _rmatvec(self, x):
        import jax
        return jax.checkpoint(self.args[0].rmatvec)(x)

    def _adjoint(self):
        return _CheckpointedLinearOperator(self.A.H)


def _get_dtype(operators, dtypes=None):
    if dtypes is None:
        dtypes = []
    for op in operators:
        if op is not None and hasattr(op, "dtype") and op.dtype is not None:
            dtypes.append(op.dtype)
    return np.result_type(*dtypes) if dtypes else None


def aslinearoperator(Op) -> MPILinearOperator:
    """Wrap a local (jnp-level) operator as a distributed one
    (ref ``asmpilinearoperator``, ``LinearOperator.py:583-602``)."""
    if isinstance(Op, MPILinearOperator):
        return Op
    return MPILinearOperator(Op=Op)


asmpilinearoperator = aslinearoperator


# --------------------------------------------------- operators as pytrees
# Multi-process JAX forbids closing over arrays that span non-addressable
# devices: "Please pass such arrays as arguments to the function". The
# fused solvers therefore pass the OPERATOR itself as a jit argument
# whenever its class is registered here — its device buffers flatten to
# pytree children while everything else (shapes, meshes, sub-operator
# lists) rides along as aux, compared by object identity for the
# compilation cache. This is what makes ``cgls(...)`` work unchanged on
# a 2-process ``jax.distributed`` CPU job (tests/multihost_worker.py)
# and on multi-host pods, replacing the reference's per-rank operator
# state (each rank owning only its local block).

OP_ARRAY_PYTREES = set()


def register_operator_arrays(cls, *attrs: str) -> None:
    """Register ``cls`` as a jax pytree whose children are the device
    buffers (or registered sub-operators) stored in ``attrs``; the
    instance itself is the aux. Unflatten shallow-copies the instance
    and swaps in the (possibly traced) children, so operator methods
    run unmodified under trace."""
    import copy
    import jax

    def _flatten(op):
        return tuple(getattr(op, a) for a in attrs), op

    def _unflatten(aux, children):
        new = copy.copy(aux)
        for a, c in zip(attrs, children):
            setattr(new, a, c)
        return new

    jax.tree_util.register_pytree_node(cls, _flatten, _unflatten)
    OP_ARRAY_PYTREES.add(cls)


def operator_is_jit_arg(Op) -> bool:
    """True when ``Op`` can safely travel into ``jax.jit`` as a pytree
    argument: its class is registered AND every flattened leaf is an
    array/scalar. A registered wrapper composed over an UNREGISTERED
    user operator flattens that child to an opaque leaf, which jit
    would reject — such compositions fall back to closure capture
    (works single-process; multi-process users must register their
    classes, see docs/multihost.md)."""
    if type(Op) not in OP_ARRAY_PYTREES:
        return False
    import jax
    import numpy as _np
    return all(
        l is None or isinstance(l, (jax.Array, _np.ndarray, _np.number,
                                    int, float, complex, bool))
        for l in jax.tree_util.tree_leaves(Op))


# The base class (aslinearoperator instances) and every lazy wrapper:
# wrappers expose their sub-operators through ``args`` so compositions
# like (Op.H @ Op) or eps*Reg recurse into the registered leaves.
# Array-less classes register with NO attrs — they still need to be
# pytree nodes to be valid CHILDREN of a registered wrapper. The
# _Power wrapper's exponent and _Scaled's alpha ride in args as traced
# leaves; the static copies (_p / dtype math) stay in aux.
register_operator_arrays(MPILinearOperator)
for _w in (_AdjointLinearOperator, _TransposedLinearOperator,
           _ProductLinearOperator, _ScaledLinearOperator,
           _SumLinearOperator, _PowerLinearOperator,
           _ConjLinearOperator, _CheckpointedLinearOperator):
    register_operator_arrays(_w, "args")

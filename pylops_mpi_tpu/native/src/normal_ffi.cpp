// One-pass fused normal matvec for CPU hosts, as an XLA FFI custom call.
//
// (u, q) = (A^T A x, A x) per block of a batched block-diagonal
// operator, reading each A block from DRAM ONCE: thread t owns a
// contiguous row slab of every block; for each of its rows it computes
// q[r] = <A[r], x> and immediately accumulates u_t += q[r] * A[r]
// while the row is still in registers/L1. The classic two-sweep
// schedule (BLAS gemv + gemv^T, what the reference's per-rank NumPy
// engine does) reads A twice; on bandwidth-bound sizes this kernel
// approaches 2x.
//
// This is the CPU analog of the Pallas `_normal_kernel`
// (ops/pallas_kernels.py), which does the same single-sweep trick in
// VMEM on TPU. Registered through jax.ffi so the fused CGLS
// while_loop can call it from inside jit (native/ffi.py).
//
// Reference context: the reference has no first-party native compute
// (SURVEY.md §2.6); its normal-equation products are two separate
// rank-local BLAS calls inside the Python solver loop
// (pylops_mpi/optimization/cls_basic.py:370-404).

#include <algorithm>
#include <complex>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// adjoint-side conjugation: identity for real T, conj for complex —
// q = A x uses the plain product, u = Aᴴ q conjugates the row
template <typename T>
inline T Cj(T v) { return v; }
template <typename U>
inline std::complex<U> Cj(std::complex<U> v) { return std::conj(v); }

int NumThreads(int64_t rows_total) {
  long hw = static_cast<long>(std::thread::hardware_concurrency());
  // kernel-specific knob — deliberately NOT the shared
  // PYLOPS_MPI_TPU_NATIVE_THREADS that tunes the host pack/IO
  // helpers: this kernel runs once per shard_map shard and its budget
  // is per-shard, while the helpers' budget is per-process
  if (const char* env = std::getenv("PYLOPS_MPI_TPU_FFI_THREADS")) {
    long v = std::strtol(env, nullptr, 10);
    if (v > 0) hw = v;
  }
  if (hw < 1) hw = 1;
  // never more threads than row slabs of ~64 rows: tiny problems
  // must not pay thread spawn for nothing
  int64_t cap = std::max<int64_t>(1, rows_total / 64);
  return static_cast<int>(std::min<int64_t>(hw, cap));
}

template <typename T>
void SlabWorker(const T* A, const T* X, T* Q, T* acc, int64_t nblk,
                int64_t m, int64_t n, int64_t r0, int64_t r1) {
  // acc: private (nblk, n) accumulator, zero-initialised by caller
  for (int64_t b = 0; b < nblk; ++b) {
    const T* Ab = A + b * m * n;
    const T* xb = X + b * n;
    T* qb = Q + b * m;
    T* ub = acc + b * n;
    for (int64_t r = r0; r < r1; ++r) {
      const T* row = Ab + r * n;
      // 16 partial sums: enough independent chains for AVX-512 FMA
      // without -ffast-math, deterministic summation order
      T p[16] = {0};
      int64_t j = 0;
      for (; j + 16 <= n; j += 16) {
        for (int k = 0; k < 16; ++k) p[k] += row[j + k] * xb[j + k];
      }
      T s = 0;
      for (int k = 0; k < 16; ++k) s += p[k];
      for (; j < n; ++j) s += row[j] * xb[j];
      qb[r] = s;
      for (int64_t k = 0; k < n; ++k) ub[k] += s * Cj(row[k]);
    }
  }
}

template <typename T>
ffi::Error FusedNormal(const T* A, const T* X, T* U, T* Q, int64_t nblk,
                       int64_t m, int64_t n) {
  const int nt = NumThreads(m);
  if (nt <= 1) {
    std::memset(U, 0, sizeof(T) * nblk * n);
    SlabWorker<T>(A, X, Q, U, nblk, m, n, 0, m);
    return ffi::Error::Success();
  }
  std::vector<std::vector<T>> accs(nt);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  const int64_t slab = (m + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    accs[t].assign(static_cast<size_t>(nblk * n), T(0));
    const int64_t r0 = t * slab;
    const int64_t r1 = std::min<int64_t>(m, r0 + slab);
    if (r0 >= r1) continue;
    threads.emplace_back(SlabWorker<T>, A, X, Q, accs[t].data(), nblk, m,
                         n, r0, r1);
  }
  for (auto& th : threads) th.join();
  // deterministic tree-free reduction in fixed thread order
  std::memset(U, 0, sizeof(T) * nblk * n);
  for (int t = 0; t < nt; ++t) {
    if (accs[t].empty()) continue;
    const T* a = accs[t].data();
    for (int64_t k = 0; k < nblk * n; ++k) U[k] += a[k];
  }
  return ffi::Error::Success();
}

template <ffi::DataType DT>
ffi::Error FusedNormalDispatch(ffi::Buffer<DT> a, ffi::Buffer<DT> x,
                               ffi::ResultBuffer<DT> u,
                               ffi::ResultBuffer<DT> q) {
  auto d = a.dimensions();
  if (d.size() != 3) {
    return ffi::Error::InvalidArgument("A must be (nblk, m, n)");
  }
  const int64_t nblk = d[0], m = d[1], n = d[2];
  auto dx = x.dimensions();
  if (dx.size() != 2 || dx[0] != nblk || dx[1] != n) {
    return ffi::Error::InvalidArgument("X must be (nblk, n)");
  }
  return FusedNormal(a.typed_data(), x.typed_data(), u->typed_data(),
                     q->typed_data(), nblk, m, n);
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalF32, FusedNormalDispatch<ffi::F32>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalF64, FusedNormalDispatch<ffi::F64>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F64>>()
        .Arg<ffi::Buffer<ffi::F64>>()
        .Ret<ffi::Buffer<ffi::F64>>()
        .Ret<ffi::Buffer<ffi::F64>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalC64, FusedNormalDispatch<ffi::C64>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::C64>>()
        .Arg<ffi::Buffer<ffi::C64>>()
        .Ret<ffi::Buffer<ffi::C64>>()
        .Ret<ffi::Buffer<ffi::C64>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalC128, FusedNormalDispatch<ffi::C128>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::C128>>()
        .Arg<ffi::Buffer<ffi::C128>>()
        .Ret<ffi::Buffer<ffi::C128>>()
        .Ret<ffi::Buffer<ffi::C128>>());

// One-pass fused normal matvec for CPU hosts, as an XLA FFI custom call.
//
// (u, q) = (A^T A x, A x) per block of a batched block-diagonal
// operator, reading each A block from DRAM ONCE: thread t owns a
// contiguous row slab of every block; for each of its rows it computes
// q[r] = <A[r], x> and immediately accumulates u_t += q[r] * A[r]
// while the row is still in registers/L1. The classic two-sweep
// schedule (BLAS gemv + gemv^T, what the reference's per-rank NumPy
// engine does) reads A twice; on bandwidth-bound sizes this kernel
// approaches 2x.
//
// This is the CPU analog of the Pallas `_normal_kernel`
// (ops/pallas_kernels.py), which does the same single-sweep trick in
// VMEM on TPU. Registered through jax.ffi so the fused CGLS
// while_loop can call it from inside jit (native/ffi.py).
//
// Reference context: the reference has no first-party native compute
// (SURVEY.md §2.6); its normal-equation products are two separate
// rank-local BLAS calls inside the Python solver loop
// (pylops_mpi/optimization/cls_basic.py:370-404).

#include <algorithm>
#include <complex>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

int NumThreads(int64_t rows_total);

// Shared thread orchestration for both element kinds: slab-partition
// rows [0, m) across threads, give each a private zeroed accumulator
// of acc_len scalars, join, then merge in fixed thread order (the
// deterministic reduction both kernels rely on). work(acc, r0, r1)
// must write only its own rows of Q and only its private acc.
template <typename U, typename W>
ffi::Error RunSlabs(W&& work, U* Uo, int64_t acc_len, int64_t m) {
  const int nt = NumThreads(m);
  if (nt <= 1) {
    std::memset(Uo, 0, sizeof(U) * acc_len);
    work(Uo, int64_t{0}, m);
    return ffi::Error::Success();
  }
  std::vector<std::vector<U>> accs(nt);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  const int64_t slab = (m + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    accs[t].assign(static_cast<size_t>(acc_len), U(0));
    const int64_t r0 = t * slab;
    const int64_t r1 = std::min<int64_t>(m, r0 + slab);
    if (r0 >= r1) continue;
    threads.emplace_back(
        [&work, &accs, t, r0, r1] { work(accs[t].data(), r0, r1); });
  }
  for (auto& th : threads) th.join();
  std::memset(Uo, 0, sizeof(U) * acc_len);
  for (int t = 0; t < nt; ++t) {
    if (accs[t].empty()) continue;
    const U* a = accs[t].data();
    for (int64_t k = 0; k < acc_len; ++k) Uo[k] += a[k];
  }
  return ffi::Error::Success();
}

int NumThreads(int64_t rows_total) {
  long hw = static_cast<long>(std::thread::hardware_concurrency());
  // kernel-specific knob — deliberately NOT the shared
  // PYLOPS_MPI_TPU_NATIVE_THREADS that tunes the host pack/IO
  // helpers: this kernel runs once per shard_map shard and its budget
  // is per-shard, while the helpers' budget is per-process
  if (const char* env = std::getenv("PYLOPS_MPI_TPU_FFI_THREADS")) {
    long v = std::strtol(env, nullptr, 10);
    if (v > 0) hw = v;
  }
  if (hw < 1) hw = 1;
  // never more threads than row slabs of ~64 rows: tiny problems
  // must not pay thread spawn for nothing
  int64_t cap = std::max<int64_t>(1, rows_total / 64);
  return static_cast<int>(std::min<int64_t>(hw, cap));
}

template <typename T>
void SlabWorker(const T* A, const T* X, T* Q, T* acc, int64_t nblk,
                int64_t m, int64_t n, int64_t r0, int64_t r1) {
  // acc: private (nblk, n) accumulator, zero-initialised by caller
  for (int64_t b = 0; b < nblk; ++b) {
    const T* Ab = A + b * m * n;
    const T* xb = X + b * n;
    T* qb = Q + b * m;
    T* ub = acc + b * n;
    for (int64_t r = r0; r < r1; ++r) {
      const T* row = Ab + r * n;
      // 16 partial sums: enough independent chains for AVX-512 FMA
      // without -ffast-math, deterministic summation order
      T p[16] = {0};
      int64_t j = 0;
      for (; j + 16 <= n; j += 16) {
        for (int k = 0; k < 16; ++k) p[k] += row[j + k] * xb[j + k];
      }
      T s = 0;
      for (int k = 0; k < 16; ++k) s += p[k];
      for (; j < n; ++j) s += row[j] * xb[j];
      qb[r] = s;
      // real-only kernel: Aᵀ needs no conjugation (complex blocks
      // route to SlabWorkerCplx, never here)
      for (int64_t k = 0; k < n; ++k) ub[k] += s * row[k];
    }
  }
}

// Complex slab worker on reinterpreted real buffers (std::complex<U>
// guarantees interleaved re,im). Scalar std::complex math measured
// 0.42x the XLA two-sweep (compute-bound); instead the complex dot is
// TWO plain real dots of the interleaved row against precomputed
// auxiliary vectors —
//   s_re = <row_f, xa>,  xa = [br0, -bi0, br1, -bi1, …]
//   s_im = <row_f, xb>,  xb = [bi0,  br0, bi1,  br1, …]
// — which the compiler vectorises like the real kernel, and the
// conjugated axpy u += s·conj(row) is the pairwise form below.
template <typename U>
void SlabWorkerCplx(const U* A, const U* XA, const U* XB, U* Q, U* acc,
                    int64_t nblk, int64_t m, int64_t n, int64_t r0,
                    int64_t r1) {
  const int64_t n2 = 2 * n;
  for (int64_t b = 0; b < nblk; ++b) {
    const U* Ab = A + b * m * n2;
    const U* xa = XA + b * n2;
    const U* xb = XB + b * n2;
    U* qb = Q + b * m * 2;
    U* ub = acc + b * n2;
    for (int64_t r = r0; r < r1; ++r) {
      const U* row = Ab + r * n2;
      U pa[16] = {0}, pb[16] = {0};
      int64_t j = 0;
      for (; j + 16 <= n2; j += 16) {
        for (int k = 0; k < 16; ++k) {
          pa[k] += row[j + k] * xa[j + k];
          pb[k] += row[j + k] * xb[j + k];
        }
      }
      U sre = 0, sim = 0;
      for (int k = 0; k < 16; ++k) { sre += pa[k]; sim += pb[k]; }
      for (; j < n2; ++j) { sre += row[j] * xa[j]; sim += row[j] * xb[j]; }
      qb[2 * r] = sre;
      qb[2 * r + 1] = sim;
      // u += s * conj(row):  re += sre*ar + sim*ai, im += sim*ar - sre*ai
      for (int64_t k = 0; k < n; ++k) {
        const U ar = row[2 * k], ai = row[2 * k + 1];
        ub[2 * k] += sre * ar + sim * ai;
        ub[2 * k + 1] += sim * ar - sre * ai;
      }
    }
  }
}

template <typename U>
ffi::Error FusedNormalCplx(const std::complex<U>* Ac,
                           const std::complex<U>* Xc, std::complex<U>* Uc,
                           std::complex<U>* Qc, int64_t nblk, int64_t m,
                           int64_t n) {
  const U* A = reinterpret_cast<const U*>(Ac);
  U* Uo = reinterpret_cast<U*>(Uc);
  U* Q = reinterpret_cast<U*>(Qc);
  // auxiliary re/im mixing vectors, once per call (2·nblk·n U each)
  std::vector<U> XA(static_cast<size_t>(nblk * 2 * n));
  std::vector<U> XB(static_cast<size_t>(nblk * 2 * n));
  for (int64_t b = 0; b < nblk; ++b) {
    const std::complex<U>* xb_ = Xc + b * n;
    U* xa = XA.data() + b * 2 * n;
    U* xb = XB.data() + b * 2 * n;
    for (int64_t jj = 0; jj < n; ++jj) {
      xa[2 * jj] = xb_[jj].real();
      xa[2 * jj + 1] = -xb_[jj].imag();
      xb[2 * jj] = xb_[jj].imag();
      xb[2 * jj + 1] = xb_[jj].real();
    }
  }
  return RunSlabs<U>(
      [&](U* acc, int64_t r0, int64_t r1) {
        SlabWorkerCplx<U>(A, XA.data(), XB.data(), Q, acc, nblk, m, n,
                          r0, r1);
      },
      Uo, nblk * 2 * n, m);
}

template <typename T>
ffi::Error FusedNormal(const T* A, const T* X, T* U, T* Q, int64_t nblk,
                       int64_t m, int64_t n) {
  return RunSlabs<T>(
      [&](T* acc, int64_t r0, int64_t r1) {
        SlabWorker<T>(A, X, Q, acc, nblk, m, n, r0, r1);
      },
      U, nblk * n, m);
}

// route by element type: complex goes to the planar-trick worker
template <typename U>
ffi::Error FusedNormalRoute(const std::complex<U>* A,
                            const std::complex<U>* X, std::complex<U>* Uo,
                            std::complex<U>* Q, int64_t nblk, int64_t m,
                            int64_t n) {
  return FusedNormalCplx<U>(A, X, Uo, Q, nblk, m, n);
}

template <typename T>
ffi::Error FusedNormalRoute(const T* A, const T* X, T* Uo, T* Q,
                            int64_t nblk, int64_t m, int64_t n) {
  return FusedNormal<T>(A, X, Uo, Q, nblk, m, n);
}

template <ffi::DataType DT>
ffi::Error FusedNormalDispatch(ffi::Buffer<DT> a, ffi::Buffer<DT> x,
                               ffi::ResultBuffer<DT> u,
                               ffi::ResultBuffer<DT> q) {
  auto d = a.dimensions();
  if (d.size() != 3) {
    return ffi::Error::InvalidArgument("A must be (nblk, m, n)");
  }
  const int64_t nblk = d[0], m = d[1], n = d[2];
  auto dx = x.dimensions();
  if (dx.size() != 2 || dx[0] != nblk || dx[1] != n) {
    return ffi::Error::InvalidArgument("X must be (nblk, n)");
  }
  return FusedNormalRoute(a.typed_data(), x.typed_data(), u->typed_data(),
                          q->typed_data(), nblk, m, n);
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalF32, FusedNormalDispatch<ffi::F32>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::F32>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalF64, FusedNormalDispatch<ffi::F64>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F64>>()
        .Arg<ffi::Buffer<ffi::F64>>()
        .Ret<ffi::Buffer<ffi::F64>>()
        .Ret<ffi::Buffer<ffi::F64>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalC64, FusedNormalDispatch<ffi::C64>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::C64>>()
        .Arg<ffi::Buffer<ffi::C64>>()
        .Ret<ffi::Buffer<ffi::C64>>()
        .Ret<ffi::Buffer<ffi::C64>>());

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    FusedNormalC128, FusedNormalDispatch<ffi::C128>,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::C128>>()
        .Arg<ffi::Buffer<ffi::C128>>()
        .Ret<ffi::Buffer<ffi::C128>>()
        .Ret<ffi::Buffer<ffi::C128>>());

// Native host runtime for pylops-mpi-tpu.
//
// The reference (pylops-mpi) leans on third-party native code for its
// performance-critical host paths (MPI datatype packing inside
// Allgatherv, mpi4py pickling buffers, FFTW transposes — see
// SURVEY.md §2.6).  This library is the first-party TPU-build analog:
// the host-side staging work that happens *around* the XLA compute
// path — scattering a global host array into the padded per-shard
// physical layout (``DistributedArray.to_dist``,
// ref pylops_mpi/DistributedArray.py:408-461), gathering it back
// (``asarray``, ref DistributedArray.py:371-406), and feeding shards
// from disk — implemented as multithreaded C++ instead of Python
// slicing.
//
// Layout contract (all arrays C-contiguous, described as
// (outer, axis, inner_bytes)):
//   logical  global:  (outer, G,          inner)   G = sum(sizes[p])
//   physical padded:  (outer, P * s_phys, inner)   shard p occupies rows
//                     [p*s_phys, p*s_phys + sizes[p]); the remainder is
//                     zero padding (pad-to-max — the same trick the
//                     reference's NCCL path uses for ragged allgathers,
//                     pylops_mpi/utils/_nccl.py:363-403).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

// Run fn(task) for task in [0, ntasks) over nthreads workers.
void parallel_for(int64_t ntasks, int32_t nthreads,
                  const std::function<void(int64_t)> &fn) {
  if (nthreads <= 1 || ntasks <= 1) {
    for (int64_t t = 0; t < ntasks; ++t) fn(t);
    return;
  }
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int64_t t = next.fetch_add(1, std::memory_order_relaxed);
      if (t >= ntasks) return;
      fn(t);
    }
  };
  std::vector<std::thread> pool;
  int32_t n = static_cast<int32_t>(std::min<int64_t>(nthreads, ntasks));
  pool.reserve(n);
  for (int32_t i = 0; i < n; ++i) pool.emplace_back(worker);
  for (auto &th : pool) th.join();
}

}  // namespace

extern "C" {

// Balanced split of n elements over P shards: first n % P shards get
// one extra element (ref pylops_mpi/DistributedArray.py:62-71).
void lp_local_split(int64_t n, int32_t P, int64_t *out_sizes) {
  int64_t q = n / P, r = n % P;
  for (int32_t p = 0; p < P; ++p) out_sizes[p] = q + (p < r ? 1 : 0);
}

// Logical global -> physical padded (pack). Zero-fills padding.
void lp_pack_padded(const char *src, char *dst, int64_t outer, int64_t inner,
                    int32_t P, const int64_t *sizes, int64_t s_phys,
                    int32_t nthreads) {
  std::vector<int64_t> offs(P + 1, 0);
  for (int32_t p = 0; p < P; ++p) offs[p + 1] = offs[p] + sizes[p];
  const int64_t G = offs[P];
  const int64_t phys_rows = static_cast<int64_t>(P) * s_phys;
  parallel_for(outer * P, nthreads, [&](int64_t task) {
    const int64_t o = task / P;
    const int32_t p = static_cast<int32_t>(task % P);
    const char *s = src + (o * G + offs[p]) * inner;
    char *d = dst + (o * phys_rows + p * s_phys) * inner;
    std::memcpy(d, s, static_cast<size_t>(sizes[p] * inner));
    const int64_t pad = s_phys - sizes[p];
    if (pad > 0)
      std::memset(d + sizes[p] * inner, 0, static_cast<size_t>(pad * inner));
  });
}

// Physical padded -> logical global (unpack / strip padding).
void lp_unpack_padded(const char *src, char *dst, int64_t outer, int64_t inner,
                      int32_t P, const int64_t *sizes, int64_t s_phys,
                      int32_t nthreads) {
  std::vector<int64_t> offs(P + 1, 0);
  for (int32_t p = 0; p < P; ++p) offs[p + 1] = offs[p] + sizes[p];
  const int64_t G = offs[P];
  const int64_t phys_rows = static_cast<int64_t>(P) * s_phys;
  parallel_for(outer * P, nthreads, [&](int64_t task) {
    const int64_t o = task / P;
    const int32_t p = static_cast<int32_t>(task % P);
    const char *s = src + (o * phys_rows + p * s_phys) * inner;
    char *d = dst + (o * G + offs[p]) * inner;
    std::memcpy(d, s, static_cast<size_t>(sizes[p] * inner));
  });
}

// Parallel chunked pread of [offset, offset+nbytes) from path into dst.
// Returns 0 on success, -1 on open failure, -2 on short/failed read.
// This is the data-loader primitive: tutorials stream multi-GB seismic
// volumes from disk (ref tutorials/poststack.py) — chunked pread keeps
// the page-cache + NVMe queue busy from multiple threads.
int32_t lp_read_file(const char *path, int64_t offset, int64_t nbytes,
                     char *dst, int32_t nthreads) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  const int64_t chunk = 8 << 20;  // 8 MiB
  const int64_t ntasks = (nbytes + chunk - 1) / chunk;
  std::atomic<int32_t> err(0);
  parallel_for(ntasks, nthreads, [&](int64_t t) {
    int64_t start = t * chunk;
    int64_t len = std::min(chunk, nbytes - start);
    int64_t done = 0;
    while (done < len) {
      ssize_t got = pread(fd, dst + start + done, static_cast<size_t>(len - done),
                          offset + start + done);
      if (got <= 0) { err.store(-2); return; }
      done += got;
    }
  });
  close(fd);
  return err.load();
}

// Parallel chunked pwrite at an arbitrary offset without truncation —
// lets a caller stream several arrays into one file with flat peak
// memory (checkpoint writer, see utils/checkpoint.py).
int32_t lp_write_file_at(const char *path, int64_t offset, int64_t nbytes,
                         const char *src, int32_t nthreads) {
  int fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  const int64_t chunk = 8 << 20;
  const int64_t ntasks = (nbytes + chunk - 1) / chunk;
  std::atomic<int32_t> err(0);
  parallel_for(ntasks, nthreads, [&](int64_t t) {
    int64_t start = t * chunk;
    int64_t len = std::min(chunk, nbytes - start);
    int64_t done = 0;
    while (done < len) {
      ssize_t put = pwrite(fd, src + start + done, static_cast<size_t>(len - done),
                           offset + start + done);
      if (put <= 0) { err.store(-2); return; }
      done += put;
    }
  });
  close(fd);
  return err.load();
}

// Parallel chunked pwrite (checkpoint writer counterpart).
int32_t lp_write_file(const char *path, int64_t nbytes, const char *src,
                      int32_t nthreads) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  if (ftruncate(fd, nbytes) != 0) { close(fd); return -1; }
  const int64_t chunk = 8 << 20;
  const int64_t ntasks = (nbytes + chunk - 1) / chunk;
  std::atomic<int32_t> err(0);
  parallel_for(ntasks, nthreads, [&](int64_t t) {
    int64_t start = t * chunk;
    int64_t len = std::min(chunk, nbytes - start);
    int64_t done = 0;
    while (done < len) {
      ssize_t put = pwrite(fd, src + start + done, static_cast<size_t>(len - done),
                           start + done);
      if (put <= 0) { err.store(-2); return; }
      done += put;
    }
  });
  close(fd);
  return err.load();
}

}  // extern "C"

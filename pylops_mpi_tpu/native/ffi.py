"""XLA-FFI bindings for the native one-pass normal matvec (CPU).

``fused_normal(A, X) -> (U, Q)`` computes ``(AᴴA x, A x)`` per block
with ONE DRAM sweep of ``A`` (``src/normal_ffi.cpp``) — the CPU analog
of the Pallas ``_normal_kernel`` that does the same trick in VMEM on
TPU (``ops/pallas_kernels.py``). It is an XLA custom call, so the
fused CGLS ``while_loop`` dispatches it from inside jit with zero
Python per iteration; the reference's per-rank engine instead issues
two separate BLAS gemv calls from the Python solver loop
(ref ``pylops_mpi/optimization/cls_basic.py:370-404``).

Build-on-first-use with ``g++`` against the FFI headers jaxlib ships
(``jax.ffi.include_dir()``), cached under ``_build/`` keyed by source
hash, ctypes-loaded, registered per dtype. Everything degrades
gracefully: no compiler / no headers / non-CPU backend →
``available() == False`` and callers fall back to the two-sweep path.
Disable explicitly with ``PYLOPS_MPI_TPU_NATIVE=0`` (the same seam as
the rest of the native runtime).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import warnings
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "normal_ffi.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")

__all__ = ["available", "fused_normal"]

_lock = threading.Lock()
_state: Optional[bool] = None  # None = not tried; True/False = usable

_TARGETS = {
    np.dtype(np.float32): "pylops_mpi_tpu_fused_normal_f32",
    np.dtype(np.float64): "pylops_mpi_tpu_fused_normal_f64",
    np.dtype(np.complex64): "pylops_mpi_tpu_fused_normal_c64",
    np.dtype(np.complex128): "pylops_mpi_tpu_fused_normal_c128",
}
_SYMBOLS = {
    np.dtype(np.float32): "FusedNormalF32",
    np.dtype(np.float64): "FusedNormalF64",
    np.dtype(np.complex64): "FusedNormalC64",
    np.dtype(np.complex128): "FusedNormalC128",
}


def _enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_NATIVE", "1") != "0"


def _build_and_register() -> bool:
    import jax
    import jax.ffi

    inc = jax.ffi.include_dir()
    if not os.path.isdir(os.path.join(inc, "xla", "ffi", "api")):
        return False
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD_DIR, f"normal_ffi_{tag}.so")
    if not os.path.exists(so):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so + f".tmp{os.getpid()}"
        # -march=native is safe and load-bearing here: the library is
        # built on first use ON the host that runs it, and the kernel
        # must reach FMA/AVX width to hit the DRAM roof instead of
        # being compute-bound
        cmd = ["g++", "-O3", "-march=native", "-funroll-loops", "-shared",
               "-fPIC", "-std=c++17", "-pthread", f"-I{inc}", _SRC,
               "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except subprocess.CalledProcessError:
            # exotic hosts where -march=native fails: portable build
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                   "-pthread", f"-I{inc}", _SRC, "-o", tmp]
            subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, so)
    lib = ctypes.CDLL(so)
    for dt, target in _TARGETS.items():
        handler = jax.ffi.pycapsule(getattr(lib, _SYMBOLS[dt]))
        jax.ffi.register_ffi_target(target, handler, platform="cpu")
    return True


def available() -> bool:
    """True when the custom-call library is built and registered (CPU
    backends only — the TPU path is the Pallas kernel)."""
    global _state
    if _state is not None:
        return _state
    with _lock:
        if _state is not None:
            return _state
        ok = False
        try:
            import jax
            if _enabled() and jax.default_backend() == "cpu":
                ok = _build_and_register()
                # default thread budget: the kernel runs once PER SHARD
                # inside shard_map, and on a virtual multi-device CPU
                # mesh those calls are concurrent — splitting the
                # socket's cores between them avoids oversubscription.
                # Uses the kernel-specific PYLOPS_MPI_TPU_FFI_THREADS
                # (explicit setting always wins); the shared
                # PYLOPS_MPI_TPU_NATIVE_THREADS knob of the pack/IO
                # helpers is deliberately left alone
                if ok and "PYLOPS_MPI_TPU_FFI_THREADS" not in os.environ:
                    ndev = max(1, len(jax.local_devices()))
                    os.environ["PYLOPS_MPI_TPU_FFI_THREADS"] = str(
                        max(1, (os.cpu_count() or 1) // ndev))
        except Exception as e:  # no g++, missing headers, …
            warnings.warn(f"pylops_mpi_tpu: native fused-normal FFI "
                          f"unavailable ({e!r}); using the two-sweep "
                          f"fallback", stacklevel=2)
            ok = False
        _state = ok
        return ok


def supports(dtype) -> bool:
    """True when the kernel has a handler for ``dtype`` (f32/f64 plus
    c64/c128 with adjoint-side conjugation). The single owner of the
    dtype contract — callers must not reach into ``_TARGETS``."""
    return np.dtype(dtype) in _TARGETS


def fused_normal(A, X):
    """``(U, Q) = (AᴴA x, A x)`` for ``A (nblk, m, n)``,
    ``X (nblk, n)`` via the one-pass native kernel — any dtype
    :func:`supports` accepts (real f32/f64, complex c64/c128; the
    adjoint side conjugates). Caller must check :func:`available`
    first and pass A and X at the SAME dtype."""
    import jax
    import jax.ffi

    dt = np.dtype(A.dtype)
    target = _TARGETS.get(dt)
    if target is None:
        raise TypeError(f"fused_normal: unsupported dtype {A.dtype}")
    nblk, m, n = A.shape
    call = jax.ffi.ffi_call(
        target,
        (jax.ShapeDtypeStruct((nblk, n), A.dtype),
         jax.ShapeDtypeStruct((nblk, m), A.dtype)))
    return call(A, X)

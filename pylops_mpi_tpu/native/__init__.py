"""First-party native host runtime (C++), with NumPy fallback.

The reference gets its host-path muscle from third-party native code
(MPI datatype packing, FFTW, CuPy — SURVEY.md §2.6 / ref
``pyproject.toml:1-8`` shows zero first-party native).  Here the staging
work around the XLA compute path — padded shard pack/unpack for uneven
``Partition.SCATTER`` splits (ref ``pylops_mpi/DistributedArray.py:408-461``,
``371-406``; pad-to-max idiom from ``utils/_nccl.py:363-403``) and
threaded binary IO for data loading / checkpoints — is first-party C++
(``src/hostpack.cpp``), compiled on first use with ``g++`` and bound via
``ctypes``.

Disable with ``PYLOPS_MPI_TPU_NATIVE=0`` (same env-flag seam as the
reference's ``NCCL_PYLOPS_MPI``, ref ``utils/deps.py:62-64``); every
entry point transparently falls back to NumPy when the library is
unavailable (no compiler, unsupported OS).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import warnings
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["available", "local_split_native", "pack_padded", "unpack_padded",
           "read_binary", "write_binary", "write_binary_at",
           "default_threads"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "hostpack.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _enabled() -> bool:
    return os.environ.get("PYLOPS_MPI_TPU_NATIVE", "1") != "0"


def default_threads() -> int:
    n = os.environ.get("PYLOPS_MPI_TPU_NATIVE_THREADS")
    if n:
        return max(1, int(n))
    return max(1, min(16, os.cpu_count() or 1))


def _build_and_load() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD_DIR, f"hostpack_{tag}.so")
    if not os.path.exists(so):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True)
        except subprocess.CalledProcessError as e:
            stderr = (e.stderr or b"").decode("utf-8", "replace")[-800:]
            raise RuntimeError(
                f"g++ build failed (rc={e.returncode}): {stderr}") from e
        os.replace(tmp, so)
    lib = ctypes.CDLL(so)
    i64, i32, cp = ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p
    vp = ctypes.c_void_p
    lib.lp_local_split.argtypes = [i64, i32, vp]
    lib.lp_pack_padded.argtypes = [vp, vp, i64, i64, i32, vp, i64, i32]
    lib.lp_unpack_padded.argtypes = [vp, vp, i64, i64, i32, vp, i64, i32]
    lib.lp_read_file.argtypes = [cp, i64, i64, vp, i32]
    lib.lp_read_file.restype = i32
    lib.lp_write_file.argtypes = [cp, i64, vp, i32]
    lib.lp_write_file.restype = i32
    lib.lp_write_file_at.argtypes = [cp, i64, i64, vp, i32]
    lib.lp_write_file_at.restype = i32
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if not _enabled():
        return None
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is None and not _tried:
            _tried = True
            try:
                _lib = _build_and_load()
            except Exception as e:  # no g++, read-only fs, ...
                warnings.warn(f"native host runtime unavailable, using NumPy "
                              f"fallback: {e}", stacklevel=2)
                _lib = None
    return _lib


def available() -> bool:
    """True when the compiled C++ runtime is loadable."""
    return _get_lib() is not None


# --------------------------------------------------------------- helpers
def _outer_inner(shape: Sequence[int], axis: int, itemsize: int):
    outer = int(np.prod(shape[:axis], dtype=np.int64)) if axis else 1
    inner = int(np.prod(shape[axis + 1:], dtype=np.int64)) * itemsize
    return outer, inner


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def _check_sizes(sizes: np.ndarray, s_phys: int) -> None:
    """Shared validation so a bad public-API call raises here instead of
    corrupting memory in the C++ memcpy loops (advisor round-1 note)."""
    if sizes.ndim != 1:
        raise ValueError("sizes must be 1-D")
    if np.any(sizes < 0):
        raise ValueError("sizes must be non-negative")
    if len(sizes) and int(sizes.max()) > int(s_phys):
        raise ValueError(f"max(sizes)={int(sizes.max())} > s_phys={s_phys}")


# ------------------------------------------------------------ public API
def local_split_native(n: int, nshards: int) -> np.ndarray:
    """Balanced axis split (ref ``DistributedArray.py:62-71``)."""
    lib = _get_lib()
    if lib is None:
        from ..parallel.partition import Partition, local_split
        shapes = local_split((int(n),), int(nshards), Partition.SCATTER, 0)
        return np.asarray([s[0] for s in shapes], dtype=np.int64)
    out = np.empty(nshards, dtype=np.int64)
    lib.lp_local_split(int(n), int(nshards), _ptr(out))
    return out


def pack_padded(x: np.ndarray, axis: int, sizes: Sequence[int],
                s_phys: int, nthreads: Optional[int] = None) -> np.ndarray:
    """Logical global host array -> padded physical layout: shard ``p``'s
    rows land at ``[p*s_phys, p*s_phys+sizes[p])`` along ``axis``, the
    rest zero-filled."""
    x = np.ascontiguousarray(x)
    axis = axis % x.ndim
    sizes = np.ascontiguousarray(sizes, dtype=np.int64)
    P = len(sizes)
    _check_sizes(sizes, s_phys)
    if int(sizes.sum()) != x.shape[axis]:
        raise ValueError(f"sum(sizes)={int(sizes.sum())} != "
                         f"x.shape[{axis}]={x.shape[axis]}")
    shp = list(x.shape)
    shp[axis] = P * int(s_phys)
    lib = _get_lib()
    if lib is None:
        out = np.zeros(shp, dtype=x.dtype)
        offs = np.concatenate([[0], np.cumsum(sizes)])
        for p in range(P):
            src = [slice(None)] * x.ndim
            dst = [slice(None)] * x.ndim
            src[axis] = slice(int(offs[p]), int(offs[p + 1]))
            dst[axis] = slice(p * s_phys, p * s_phys + int(sizes[p]))
            out[tuple(dst)] = x[tuple(src)]
        return out
    out = np.empty(shp, dtype=x.dtype)
    outer, inner = _outer_inner(x.shape, axis, x.itemsize)
    lib.lp_pack_padded(_ptr(x), _ptr(out), outer, inner, P, _ptr(sizes),
                       int(s_phys), nthreads or default_threads())
    return out


def unpack_padded(x: np.ndarray, axis: int, sizes: Sequence[int],
                  s_phys: int, nthreads: Optional[int] = None) -> np.ndarray:
    """Padded physical host array -> logical global (strip padding)."""
    x = np.ascontiguousarray(x)
    axis = axis % x.ndim
    sizes = np.ascontiguousarray(sizes, dtype=np.int64)
    P = len(sizes)
    _check_sizes(sizes, s_phys)
    if x.shape[axis] != P * int(s_phys):
        raise ValueError(f"x.shape[{axis}]={x.shape[axis]} != "
                         f"len(sizes)*s_phys={P * int(s_phys)}")
    shp = list(x.shape)
    shp[axis] = int(sizes.sum())
    lib = _get_lib()
    if lib is None:
        parts = []
        for p in range(P):
            idx = [slice(None)] * x.ndim
            idx[axis] = slice(p * s_phys, p * s_phys + int(sizes[p]))
            parts.append(x[tuple(idx)])
        return np.concatenate(parts, axis=axis)
    out = np.empty(shp, dtype=x.dtype)
    outer, inner = _outer_inner(out.shape, axis, x.itemsize)
    lib.lp_unpack_padded(_ptr(x), _ptr(out), outer, inner, P, _ptr(sizes),
                         int(s_phys), nthreads or default_threads())
    return out


def read_binary(path: str, dtype, shape: Sequence[int], *, offset: int = 0,
                nthreads: Optional[int] = None) -> np.ndarray:
    """Threaded chunked read of a raw binary volume (data-loader
    primitive for e.g. seismic cubes, ref ``tutorials/poststack.py``)."""
    dtype = np.dtype(dtype)
    out = np.empty(shape, dtype=dtype)
    nbytes = out.nbytes
    lib = _get_lib()
    if lib is None:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(nbytes)
        if len(data) != nbytes:
            raise IOError(f"short read from {path}")
        out[...] = np.frombuffer(data, dtype=dtype).reshape(shape)
        return out
    rc = lib.lp_read_file(path.encode(), int(offset), nbytes, _ptr(out),
                          nthreads or default_threads())
    if rc != 0:
        raise IOError(f"native read of {path} failed (rc={rc})")
    return out


def write_binary(path: str, x: np.ndarray,
                 nthreads: Optional[int] = None) -> None:
    """Threaded chunked write (checkpoint-writer primitive)."""
    x = np.ascontiguousarray(x)
    lib = _get_lib()
    if lib is None:
        with open(path, "wb") as f:
            f.write(x.tobytes())
        return
    rc = lib.lp_write_file(path.encode(), x.nbytes, _ptr(x),
                           nthreads or default_threads())
    if rc != 0:
        raise IOError(f"native write of {path} failed (rc={rc})")


def write_binary_at(path: str, offset: int, x: np.ndarray,
                    nthreads: Optional[int] = None) -> None:
    """Threaded chunked write of ``x`` at byte ``offset`` (no
    truncation) — streams several arrays into one file with flat peak
    host memory."""
    x = np.ascontiguousarray(x)
    lib = _get_lib()
    if lib is None:
        with open(path, "r+b" if os.path.exists(path) else "wb") as f:
            f.seek(offset)
            f.write(x.tobytes())
        return
    rc = lib.lp_write_file_at(path.encode(), int(offset), x.nbytes, _ptr(x),
                              nthreads or default_threads())
    if rc != 0:
        raise IOError(f"native write of {path} failed (rc={rc})")

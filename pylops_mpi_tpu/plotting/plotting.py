"""Shard-layout visualization.

Rebuild of ``pylops_mpi/plotting/plotting.py:13-73``: rank-layout
visualization and per-shard panels. Matplotlib is optional (gated
import) — the reference requires it as a hard dependency.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..distributedarray import DistributedArray

__all__ = ["plot_distributed_array", "plot_local_arrays"]


def _plt():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:
        raise ImportError(
            "matplotlib is required for plotting; install it or use "
            "DistributedArray.local_arrays() directly") from e


def plot_distributed_array(arr: DistributedArray, figsize=(8, 3)):
    """Color-coded shard ownership of a 1-D/2-D DistributedArray
    (ref ``plotting.py:13-44``)."""
    plt = _plt()
    sizes = [s[arr.axis] for s in arr.local_shapes]
    owner = np.repeat(np.arange(arr.n_shards), sizes)
    fig, ax = plt.subplots(figsize=figsize)
    if arr.ndim == 1:
        ax.imshow(owner[None, :], aspect="auto", cmap="tab10",
                  vmin=0, vmax=max(9, arr.n_shards - 1))
        ax.set_yticks([])
    else:
        shape = [1, 1]
        shape[arr.axis] = arr.global_shape[arr.axis]
        grid = np.broadcast_to(owner.reshape(shape),
                               arr.global_shape[:2])
        ax.imshow(grid, aspect="auto", cmap="tab10")
    ax.set_title(f"shard layout: {arr.n_shards} devices, axis={arr.axis}")
    return fig, ax


def plot_local_arrays(arr: DistributedArray, cmap: str = "viridis",
                      figsize=(12, 3)):
    """One panel per shard (ref ``plotting.py:46-73``, which gathers to
    rank 0 — here the controller already sees everything)."""
    plt = _plt()
    locs = arr.local_arrays()
    fig, axs = plt.subplots(1, len(locs), figsize=figsize)
    axs = np.atleast_1d(axs)
    for i, (ax, loc) in enumerate(zip(axs, locs)):
        view = loc if loc.ndim > 1 else loc[None, :]
        ax.imshow(view, aspect="auto", cmap=cmap)
        ax.set_title(f"shard {i}")
    fig.tight_layout()
    return fig, axs

from .plotting import plot_distributed_array, plot_local_arrays

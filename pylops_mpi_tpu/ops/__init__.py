from . import local
from .blockdiag import MPIBlockDiag, MPIStackedBlockDiag
from .stack import MPIVStack, MPIStackedVStack, MPIHStack
from .derivatives import (MPIFirstDerivative, MPISecondDerivative,
                          MPILaplacian, MPIGradient)
from .matrixmult import MPIMatrixMult, local_block_split, block_gather
from .halo import MPIHalo, halo_block_split
from .nonstatconv import MPINonStationaryConvolve1D
from .fft import MPIFFTND, MPIFFT2D
from .fredholm import MPIFredholm1
from .mdc import MPIMDC
from .precond import (JacobiPrecond, BlockJacobiPrecond, VCyclePrecond,
                      make_precond, probe_diagonal)
from .sparse import MPISparseMatrixMult, auto_sparse_matmult

from . import local
from .blockdiag import MPIBlockDiag, MPIStackedBlockDiag
from .stack import MPIVStack, MPIStackedVStack, MPIHStack

"""Distributed sparse matrix–vector products (the sparse matmul tier).

The dense tier (:mod:`.matrixmult`) pays ``2·N·M`` flops and streams
``N·M`` matrix elements per apply regardless of structure.  Many of the
operators PyLops users feed through ``MatrixMult`` are sparse —
regularization stencils, picking/masking matrices, banded systems — and
at ≥90% sparsity the dense GEMM is pure waste: the MXU multiplies
zeros and HBM streams them.  :class:`MPISparseMatrixMult` stores only
the ``nnz`` nonzeros as flattened COO-of-CSR triplets and applies them
with gather + ``segment_sum`` (forward) / scatter-add (adjoint), so
both flops and bytes scale with ``nnz`` instead of ``N·M``.

Layout.  The triplets are kept **row-sorted** (CSR order): ``rows`` is
the nondecreasing row index of each nonzero, ``cols`` its column,
``data`` its value.  Row-sorted segments make ``segment_sum`` emit its
``indices_are_sorted`` fast path and keep each device's slice of the
flattened arrays contiguous in rows — the "row-sharded" layout of the
reference's distributed CSR, realized here as a sharding of the nnz
axis rather than per-rank Python state.

Adjoint.  Two schedules:

- ``"scatter"`` (default): one logical ``zeros(Ncol).at[cols].add``
  — XLA's SPMD partitioner lowers the scatter plus the implicit
  cross-shard reduction (one psum-shaped combine).  Fully fused, jit-
  and vmap-safe, the schedule the solver tier traces into its loops.
- ``"ring"``: an explicit ``shard_map`` kernel reusing
  :func:`~pylops_mpi_tpu.parallel.collectives.ring_pass` — each device
  owns an equal slice of the nnz triplets, the (values, cols) bundle
  rotates around the ring, and every device folds the resident slice's
  contributions into its own block of ``x``.  P−1 ppermutes interleave
  with P masked scatters, so the hop of slice ``s+1`` flies while
  slice ``s`` accumulates — the overlap path for adjoint-heavy solves
  (CGLS) on real ICI.  Ragged ``Ncol`` is ceil-padded per block and
  sliced off after the gather.

Both paths produce bit-identical results up to floating-point
reassociation of the cross-shard sum; tests pin scatter-vs-ring parity
to engine precision.

Tier selection.  ``auto_sparse_matmult`` consults the tuner
(``tuning.get_plan("sparse_matmult", ...)`` with ``nnz`` in the key)
and builds the sparse operator only when the cost seed — flops and
bytes ∝ nnz vs the dense ``N·M`` — says it wins; tuning off (the
default) always returns the dense operator, so the sparse-tier-off HLO
stays bit-identical to today (pinned).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..distributedarray import DistributedArray, Partition
from ..linearoperator import MPILinearOperator, register_operator_arrays

__all__ = ["MPISparseMatrixMult", "auto_sparse_matmult"]


class MPISparseMatrixMult(MPILinearOperator):
    """Row-sharded sparse (CSR/banded) matrix multiplication.

    Parameters
    ----------
    rows, cols : array-like (nnz,) int
        Row/column index of each nonzero. ``rows`` must be
        nondecreasing (CSR order); :meth:`from_dense` and
        :meth:`from_banded` produce it sorted.
    data : array-like (nnz,)
        Nonzero values.
    shape : (N, Ncol)
        Dense shape of the matrix.
    mesh : jax.sharding.Mesh, optional
        1-D device mesh (default: the process-wide default mesh).
    dtype, compute_dtype : optional
        Operator dtype and the dtype the gathered products are formed
        in (e.g. ``bfloat16`` values with ``float32`` accumulation).
    adjoint_mode : {"scatter", "ring"}
        Adjoint schedule (see module docstring).
    """

    accepts_block = True

    def __init__(self, rows, cols, data, shape: Tuple[int, int], *,
                 mesh=None, dtype=None, compute_dtype=None,
                 adjoint_mode: str = "scatter"):
        if adjoint_mode not in ("scatter", "ring"):
            raise ValueError(f"adjoint_mode={adjoint_mode!r} "
                             "(expected 'scatter' or 'ring')")
        rows = np.asarray(rows)
        if rows.size and np.any(np.diff(rows) < 0):
            order = np.argsort(rows, kind="stable")
            rows = rows[order]
            cols = np.asarray(cols)[order]
            data = np.asarray(data)[order]
        self._rows = jnp.asarray(rows, dtype=jnp.int32)
        self._cols = jnp.asarray(cols, dtype=jnp.int32)
        self._data = jnp.asarray(data)
        if dtype is not None:
            self._data = self._data.astype(dtype)
        self.N, self.Ncol = int(shape[0]), int(shape[1])
        self.nnz = int(self._rows.shape[0])
        if self.nnz:
            rmax = int(np.max(rows))
            cmax = int(np.max(np.asarray(cols)))
            if rmax >= self.N or cmax >= self.Ncol:
                raise ValueError(
                    f"triplet index ({rmax}, {cmax}) outside shape "
                    f"({self.N}, {self.Ncol})")
        self.compute_dtype = compute_dtype
        self.adjoint_mode = adjoint_mode
        from ..parallel.mesh import default_mesh
        self.mesh = mesh if mesh is not None else default_mesh()
        super().__init__(shape=(self.N, self.Ncol),
                         dtype=np.dtype(self._data.dtype))

    # ------------------------------------------------------- constructors
    @classmethod
    def from_dense(cls, A, *, tol: float = 0.0, **kw):
        """Build from a dense matrix, keeping entries with
        ``|a| > tol`` (row-major scan → CSR order for free)."""
        A = np.asarray(A)
        if A.ndim != 2:
            raise ValueError(f"from_dense expects 2-D, got {A.shape}")
        rows, cols = np.nonzero(np.abs(A) > tol)
        return cls(rows, cols, A[rows, cols], A.shape, **kw)

    @classmethod
    def from_banded(cls, offsets, bands, shape: Tuple[int, int], **kw):
        """Build from a banded description: for each diagonal
        ``offsets[k]``, ``bands[k]`` holds its entries (length of the
        diagonal within ``shape``; scipy ``dia``-style)."""
        N, Ncol = int(shape[0]), int(shape[1])
        rows_l, cols_l, data_l = [], [], []
        for off, band in zip(offsets, bands):
            off = int(off)
            r0, c0 = (max(0, -off), max(0, off))
            ln = min(N - r0, Ncol - c0)
            if ln <= 0:
                continue
            band = np.asarray(band)
            if band.shape[0] != ln:
                raise ValueError(
                    f"band at offset {off} has {band.shape[0]} entries; "
                    f"diagonal length is {ln}")
            rows_l.append(np.arange(r0, r0 + ln))
            cols_l.append(np.arange(c0, c0 + ln))
            data_l.append(band)
        if not rows_l:
            return cls(np.zeros(0, int), np.zeros(0, int),
                       np.zeros(0), shape, **kw)
        return cls(np.concatenate(rows_l), np.concatenate(cols_l),
                   np.concatenate(data_l), shape, **kw)

    # ------------------------------------------------------------ queries
    @property
    def density(self) -> float:
        return self.nnz / float(max(1, self.N * self.Ncol))

    def diagonal(self) -> jax.Array:
        """Main diagonal (length ``min(N, Ncol)``) — the Jacobi
        preconditioner's fast path (:mod:`.precond`)."""
        n = min(self.N, self.Ncol)
        d = jnp.zeros(n, dtype=self._data.dtype)
        on = self._rows == self._cols
        idx = jnp.where(on, self._rows, n)  # off-diagonal -> dropped
        return d.at[idx].add(jnp.where(on, self._data, 0),
                             mode="drop")

    def todense(self):
        A = jnp.zeros((self.N, self.Ncol), dtype=self._data.dtype)
        return A.at[self._rows, self._cols].add(self._data)

    # ------------------------------------------------------------- apply
    def _wdt(self, g):
        if self.compute_dtype is not None:
            return np.dtype(self.compute_dtype)
        return np.promote_types(g.dtype, self._data.dtype)

    def _wrap_out(self, arr: jax.Array, x: DistributedArray,
                  length: int) -> DistributedArray:
        gshape = (length,) if arr.ndim == 1 else (length, arr.shape[1])
        y = DistributedArray(global_shape=gshape, mesh=x.mesh,
                            partition=Partition.SCATTER, axis=0,
                            mask=x.mask, dtype=arr.dtype)
        y[:] = arr
        return y

    def _matvec(self, x: DistributedArray) -> DistributedArray:
        g = x._global()
        wdt = self._wdt(g)
        vals = self._data.astype(wdt)
        xg = jnp.take(g, self._cols, axis=0).astype(wdt)
        prod = vals[:, None] * xg if g.ndim == 2 else vals * xg
        y = jax.ops.segment_sum(prod, self._rows,
                                num_segments=self.N,
                                indices_are_sorted=True)
        return self._wrap_out(y.astype(self.dtype), x, self.N)

    def _rmatvec(self, x: DistributedArray) -> DistributedArray:
        g = x._global()
        wdt = self._wdt(g)
        vals = jnp.conj(self._data).astype(wdt)
        yg = jnp.take(g, self._rows, axis=0).astype(wdt)
        prod = vals[:, None] * yg if g.ndim == 2 else vals * yg
        if (self.adjoint_mode == "ring" and g.ndim == 1
                and len(self.mesh.axis_names) == 1):
            out = self._rmatvec_ring(prod)
        else:
            shp = (self.Ncol,) if g.ndim == 1 else (self.Ncol,
                                                    g.shape[1])
            out = jnp.zeros(shp, dtype=wdt).at[self._cols].add(prod)
        return self._wrap_out(out.astype(self.dtype), x, self.Ncol)

    def _rmatvec_ring(self, prod: jax.Array) -> jax.Array:
        """Explicit ring adjoint: rotate the (values, cols) bundle,
        fold the resident slice into this device's x-block."""
        from ..jaxcompat import shard_map
        from ..parallel.collectives import ring_pass
        from jax.sharding import PartitionSpec as PSpec

        P_ = int(self.mesh.devices.size)
        name = self.mesh.axis_names[0]
        if P_ == 1:
            return jnp.zeros(self.Ncol, dtype=prod.dtype) \
                      .at[self._cols].add(prod)
        npad = P_ * (-(-self.nnz // P_))       # nnz ceil-padded
        cw = -(-self.Ncol // P_)               # x-block width
        # padding scatters value 0 to column 0 of block 0 — harmless
        vp = jnp.pad(prod, (0, npad - self.nnz))
        cp = jnp.pad(self._cols, (0, npad - self.nnz))

        def kernel(vl, cl):
            i = lax.axis_index(name)
            lo = i * cw

            def body(acc, resident, owner, s):
                v, c = resident
                loc = c - lo
                sel = (loc >= 0) & (loc < cw)
                return acc.at[jnp.where(sel, loc, cw)].add(
                    jnp.where(sel, v, 0), mode="drop")

            acc = ring_pass((vl, cl), name, P_, body,
                            init=jnp.zeros(cw, dtype=vl.dtype))
            return lax.all_gather(acc, name, axis=0, tiled=True)

        full = shard_map(kernel, mesh=self.mesh,
                         in_specs=(PSpec(name), PSpec(name)),
                         out_specs=PSpec(None), check_vma=False)(vp, cp)
        return full[:self.Ncol]


# Autodiff tier: ``_data`` (COO values) is the differentiable leaf —
# adjoint rules and implicit solver VJPs deliver value cotangents there.
# ``_rows``/``_cols`` are integer structure: their cotangents are float0
# (symbolic zeros), i.e. the sparsity PATTERN is not trainable.
register_operator_arrays(MPISparseMatrixMult, "_data", "_rows", "_cols")


def auto_sparse_matmult(A, *, mesh=None, dtype=None,
                        compute_dtype=None, tol: float = 0.0,
                        nnz: Optional[int] = None) -> MPILinearOperator:
    """Dense-or-sparse matmul tier selection through the tuner.

    Counts ``A``'s nonzeros and asks ``tuning.get_plan`` (space
    ``"sparse_matmult"``, cost ∝ nnz vs ``N·M``) which tier to build.
    With tuning off — the default — the plan is ``None`` and the dense
    operator is returned unconditionally, so existing programs lower
    to bit-identical HLO (pinned by tests/test_sparse.py).
    """
    A = np.asarray(A)
    if A.ndim != 2:
        raise ValueError(f"auto_sparse_matmult expects 2-D, got {A.shape}")
    N, Ncol = A.shape
    if nnz is None:
        nnz = int(np.count_nonzero(np.abs(A) > tol))

    tier = "dense"
    from ..tuning import plan as _tuneplan
    pl = _tuneplan.get_plan(
        "sparse_matmult", shape=(int(N), int(Ncol)),
        dtype=dtype if dtype is not None else A.dtype, mesh=mesh,
        extra={"nnz": int(nnz),
               "itemsize": int(np.dtype(dtype or A.dtype).itemsize)})
    if pl is not None:
        tier = pl.params.get("tier", "dense")
    if tier == "sparse":
        return MPISparseMatrixMult.from_dense(
            A, tol=tol, mesh=mesh, dtype=dtype,
            compute_dtype=compute_dtype)
    from .matrixmult import MPIMatrixMult
    return MPIMatrixMult(A, 1, mesh=mesh, dtype=dtype,
                         compute_dtype=compute_dtype)

"""Fredholm integral of the first kind, distributed over slices.

Rebuild of ``pylops_mpi/signalprocessing/Fredholm1.py:14-169``: batched
per-slice matmul ``d[k] = G[k] @ m[k]`` with the kernel ``G`` sharded
along its first (slice/frequency) dimension and BROADCAST model/data —
the reference computes each rank's slice batch then allgather+vstacks
the full data (ref ``129-131``).

TPU-native: one batched einsum with ``G`` slice-sharded. XLA shards the
batch dimension (each device contracts its own frequency batch on the
MXU) and replicates the result for the BROADCAST output — the same
gather, scheduled by the partitioner over ICI.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..distributedarray import DistributedArray, Partition
from ..linearoperator import MPILinearOperator
from ..parallel.mesh import axis_sharding

__all__ = ["MPIFredholm1"]


class MPIFredholm1(MPILinearOperator):
    """Distributed Fredholm1 (ref ``Fredholm1.py:14-169``).

    Parameters mirror the reference except ``G`` is the full global
    kernel ``(nsl, nx, ny)`` (one controller), not this rank's chunk.
    """

    def __init__(self, G, nz: int = 1, saveGt: bool = False,
                 usematmul: bool = True, mesh=None, dtype="float64"):
        G = jnp.asarray(G)
        self.nz = int(nz)
        self.nsl, self.nx, self.ny = G.shape
        from ..parallel.mesh import default_mesh
        self.mesh = mesh if mesh is not None else default_mesh()
        # the reference forbids shards with < 2 slices
        # (ref Fredholm1.py:79-83) — an artifact of its per-rank batched
        # matmul; the batched einsum here has no such limit, so any
        # nsl >= 1 is accepted
        if self.nsl < 1:
            raise ValueError("G must have at least one slice")
        self.dims = (self.nsl, self.ny, self.nz)
        self.dimsd = (self.nsl, self.nx, self.nz)
        super().__init__(shape=(int(np.prod(self.dimsd)),
                                int(np.prod(self.dims))),
                         dtype=np.dtype(dtype))
        try:
            self.G = jax.device_put(G, axis_sharding(self.mesh, 3, 0))
        except ValueError:
            self.G = G
        self.GT = jnp.conj(G.transpose(0, 2, 1)) if saveGt else None

    def _check_bcast(self, x):
        if x.partition not in (Partition.BROADCAST, Partition.UNSAFE_BROADCAST):
            raise ValueError(
                f"x should have partition={Partition.BROADCAST},"
                f"{Partition.UNSAFE_BROADCAST} Got {x.partition} instead...")

    def _matvec(self, x: DistributedArray) -> DistributedArray:
        self._check_bcast(x)
        m = x.array.reshape(self.dims).astype(self.dtype)
        d = jnp.einsum("kxy,kyz->kxz", self.G, m)
        y = DistributedArray(global_shape=self.shape[0], mesh=x.mesh,
                             partition=x.partition, dtype=self.dtype)
        y[:] = d.ravel()
        return y

    def _rmatvec(self, x: DistributedArray) -> DistributedArray:
        self._check_bcast(x)
        d = x.array.reshape(self.dimsd).astype(self.dtype)
        GT = self.GT if self.GT is not None else jnp.conj(self.G).transpose(0, 2, 1)
        m = jnp.einsum("kyx,kxz->kyz", GT, d)
        y = DistributedArray(global_shape=self.shape[1], mesh=x.mesh,
                             partition=x.partition, dtype=self.dtype)
        y[:] = m.ravel()
        return y

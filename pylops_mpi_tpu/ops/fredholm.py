"""Fredholm integral of the first kind, distributed over slices.

Rebuild of ``pylops_mpi/signalprocessing/Fredholm1.py:14-169``: batched
per-slice matmul ``d[k] = G[k] @ m[k]`` with the kernel ``G`` sharded
along its first (slice/frequency) dimension and BROADCAST model/data —
the reference computes each rank's slice batch then allgather+vstacks
the full data (ref ``129-131``).

TPU-native: one batched einsum with ``G`` slice-sharded. XLA shards the
batch dimension (each device contracts its own frequency batch on the
MXU) and replicates the result for the BROADCAST output — the same
gather, scheduled by the partitioner over ICI.

Beyond the reference (SURVEY §7.10): SCATTER model/data are also
accepted when the slice count divides the mesh. Each device then holds
only its frequency batch of the model AND the data, the einsum is
slice-aligned with ``G``'s sharding, and the whole apply contains ZERO
collectives — 1/P the memory of the reference's replicated-model
design. Construct the vectors with ``model_local_shapes`` /
``data_local_shapes``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..distributedarray import DistributedArray, Partition
from ..linearoperator import MPILinearOperator
from ..parallel.mesh import axis_sharding

__all__ = ["MPIFredholm1"]


class MPIFredholm1(MPILinearOperator):
    """Distributed Fredholm1 (ref ``Fredholm1.py:14-169``).

    Parameters mirror the reference except ``G`` is the full global
    kernel ``(nsl, nx, ny)`` (one controller), not this rank's chunk.
    ``usematmul`` is accepted for signature parity but has no effect:
    it selects between per-slice matmul and einsum execution in the
    reference (identical results, ref ``Fredholm1.py:120-131``); here
    the batched einsum on the MXU is always the right schedule.

    ``compute_dtype`` (e.g. ``jnp.complex64`` for a c128 operator,
    ``jnp.bfloat16`` for a real one) narrows the STORAGE of the
    kernel — by far the memory hog at ``nsl·nx·ny`` — while vectors
    and accumulation stay in the operator dtype (the
    ``MPIBlockDiag(compute_dtype=...)`` HBM-bandwidth lever; the
    reference's engine has no narrow-storage path).

    ``planar=True``: the complex-free execution mode for TPU runtimes
    with no complex lowering (round-5 hardware finding, ops/dft.py).
    The complex kernel ``G`` is stored as a STACKED REAL plane pair
    ``(2, nsl, nx, ny)`` (``[0]`` real, ``[1]`` imag, slice axis still
    sharded), model/data vectors carry the matching ``(2, nsl, ·, nz)``
    plane layout, the operator dtype is the real plane dtype, and each
    complex batched GEMM runs as 4 real einsums — no complex dtype ever
    reaches the device. This is the Fredholm core of the planar MDC
    chain (``ops/mdc.py``); only BROADCAST vectors are supported (the
    zero-collective slice-aligned SCATTER layout is a flat-vector
    contract that the leading plane axis breaks).
    """

    def __init__(self, G, nz: int = 1, saveGt: bool = False,
                 usematmul: bool = True, mesh=None, dtype="float64",
                 compute_dtype=None, planar: bool = False):
        G = jnp.asarray(G)
        self.planar = bool(planar)
        if self.planar:
            # planes store the REAL representation: a complex
            # compute_dtype narrows to its real counterpart
            if compute_dtype is not None and \
                    np.issubdtype(np.dtype(compute_dtype),
                                  np.complexfloating):
                compute_dtype = np.real(
                    np.ones(1, dtype=compute_dtype)).dtype
            if np.issubdtype(np.dtype(dtype), np.complexfloating):
                dtype = np.real(np.ones(1, dtype=np.dtype(dtype))).dtype
        if compute_dtype is None:
            # env-policy default: bf16 storage for f32 kernels under
            # the bf16 policy, c64 for c128 under the c64 policy
            from ._precision import default_compute_dtype
            compute_dtype = default_compute_dtype(dtype)
        self.compute_dtype = compute_dtype
        self.nz = int(nz)
        if self.planar:
            pdt = np.real(np.ones(1, dtype=G.dtype)).dtype
            G = jnp.stack([jnp.real(G).astype(pdt),
                           jnp.imag(G).astype(pdt)])
            self.nsl, self.nx, self.ny = G.shape[1:]
        else:
            self.nsl, self.nx, self.ny = G.shape
        if compute_dtype is not None:
            G = G.astype(compute_dtype)
        from ..parallel.mesh import default_mesh
        self.mesh = mesh if mesh is not None else default_mesh()
        # the reference forbids shards with < 2 slices
        # (ref Fredholm1.py:79-83) — an artifact of its per-rank batched
        # matmul; the batched einsum here has no such limit, so any
        # nsl >= 1 is accepted
        if self.nsl < 1:
            raise ValueError("G must have at least one slice")
        plead = (2,) if self.planar else ()
        self.dims = plead + (self.nsl, self.ny, self.nz)
        self.dimsd = plead + (self.nsl, self.nx, self.nz)
        super().__init__(shape=(int(np.prod(self.dimsd)),
                                int(np.prod(self.dims))),
                         dtype=np.dtype(dtype))
        try:
            self.G = jax.device_put(
                G, axis_sharding(self.mesh, G.ndim, len(plead)))
        except ValueError:
            self.G = G
        if not saveGt:
            self.GT = None
        elif self.planar:
            # conj-transpose planes: (Grᵀ, -Giᵀ) per slice
            self.GT = jnp.stack([G[0].transpose(0, 2, 1),
                                 -G[1].transpose(0, 2, 1)])
        else:
            self.GT = jnp.conj(G.transpose(0, 2, 1))
        self._ndev = int(self.mesh.devices.size)

    @property
    def model_local_shapes(self):
        """Slice-aligned SCATTER split of the flat model vector (the
        zero-communication layout); None when slices do not divide the
        mesh."""
        return self._slice_shapes(self.ny)

    @property
    def data_local_shapes(self):
        """Slice-aligned SCATTER split of the flat data vector."""
        return self._slice_shapes(self.nx)

    def _slice_shapes(self, inner):
        if self.planar or self.nsl % self._ndev != 0:
            # must match G's even NamedSharding for the zero-comm path
            # (planar: the leading plane axis breaks the flat
            # slice-aligned layout — BROADCAST only)
            return None
        from ..parallel.partition import flat_outer_shapes
        return flat_outer_shapes(self.nsl, inner * self.nz, self._ndev)

    def _check_partition(self, x, inner):
        if x.partition in (Partition.BROADCAST,
                           Partition.UNSAFE_BROADCAST):
            return
        shapes = self._slice_shapes(inner)
        if x.partition == Partition.SCATTER and shapes is not None \
                and tuple(x._axis_sizes) == tuple(s[0] for s in shapes):
            return
        raise ValueError(
            "x must be BROADCAST, or SCATTER with slice-aligned local "
            "shapes (model_local_shapes/data_local_shapes; requires "
            "nsl % n_devices == 0 and planar=False); got "
            f"{x.partition} with local sizes {tuple(x._axis_sizes)}")

    # block (column-batched) inputs fold their K columns into the
    # trailing z dimension of the SAME batched contraction (z -> z*K)
    accepts_block = True

    def _wrap(self, arr, x: DistributedArray, n: int,
              inner: int, ncol=None) -> DistributedArray:
        shapes = None
        if x.partition == Partition.SCATTER:
            shapes = self._slice_shapes(inner)
            if shapes is not None and ncol is not None:
                shapes = tuple(tuple(s) + (ncol,) for s in shapes)
        gshape = n if ncol is None else (n, ncol)
        y = DistributedArray(global_shape=gshape, mesh=x.mesh,
                             partition=x.partition, local_shapes=shapes,
                             dtype=self.dtype)
        y[:] = arr.ravel() if ncol is None else arr.reshape(-1, ncol)
        return y

    def _contract(self, spec, K, v):
        """Batched contraction honoring ``compute_dtype``: BOTH operands
        narrow, accumulation in the operator dtype (the shared
        narrow-storage rule, :mod:`ops._precision`)."""
        from ._precision import einsum_narrow
        if self.compute_dtype is None:
            v = v.astype(self.dtype)
        return einsum_narrow(spec, K, v, self.compute_dtype, self.dtype)

    def _matvec(self, x: DistributedArray) -> DistributedArray:
        self._check_partition(x, self.ny)
        ncol = int(x.global_shape[1]) if x.ndim == 2 else None
        m = x.array.reshape(self.dims if ncol is None
                            else self.dims[:-1] + (self.nz * ncol,))
        if self.planar:
            # complex batched GEMM on plane pairs, 4 real einsums (the
            # Karatsuba 3-einsum form needs a kernel-sized Gr+Gi temp —
            # an extra full sweep of the memory hog — so the plain
            # 4-sweep lowering wins here, unlike the host-folded
            # constants of ops/dft.py)
            c = lambda K, v: self._contract("kxy,kyz->kxz", K, v)
            dr = c(self.G[0], m[0]) - c(self.G[1], m[1])
            di = c(self.G[0], m[1]) + c(self.G[1], m[0])
            d = jnp.stack([dr, di])
        else:
            d = self._contract("kxy,kyz->kxz", self.G, m)
        return self._wrap(d, x, self.shape[0], self.nx, ncol)

    def _rmatvec(self, x: DistributedArray) -> DistributedArray:
        self._check_partition(x, self.nx)
        ncol = int(x.global_shape[1]) if x.ndim == 2 else None
        d = x.array.reshape(self.dimsd if ncol is None
                            else self.dimsd[:-1] + (self.nz * ncol,))
        if self.planar:
            if self.GT is not None:
                Hr, Hi = self.GT[0], self.GT[1]
            else:  # Gᴴ planes: (Grᵀ, -Giᵀ) per slice
                Hr = self.G[0].transpose(0, 2, 1)
                Hi = -self.G[1].transpose(0, 2, 1)
            c = lambda K, v: self._contract("kyx,kxz->kyz", K, v)
            mr = c(Hr, d[0]) - c(Hi, d[1])
            mi = c(Hr, d[1]) + c(Hi, d[0])
            m = jnp.stack([mr, mi])
        else:
            GT = self.GT if self.GT is not None \
                else jnp.conj(self.G).transpose(0, 2, 1)
            m = self._contract("kyx,kxz->kyz", GT, d)
        return self._wrap(m, x, self.shape[1], self.ny, ncol)


# the frequency-sharded kernel travels into jit as a pytree child
# (multi-process arrays must not be closed over — linearoperator.py)
from ..linearoperator import register_operator_arrays  # noqa: E402
register_operator_arrays(MPIFredholm1, "G", "GT")
